"""Winner-recipe neighborhood probe with aliasing-aware substitutions.

HALO_INCONTEXT.json (one-op schedules) and MENU_INCUMBENT.json (menu-argmin
compositions) together falsify additive per-op cost models for this
workload: isolated and composed costs differ 10-100x in both directions.
The physically dominant effect at nq=3, 512^3 f32 (U = 2.07 GB) is whether
the ghost-shell write lowers IN PLACE — a full-U copy is ~5 ms of HBM
traffic, and the r4 winners' one consistent menu deviation (z-unpacks via
the ALIASED batched Pallas kernel) is exactly an in-place guarantee, not a
kernel-speed win.

This probe measures, as ONE decorrelated paired batch against naive, the
exact r4z winner recipe plus single aimed substitutions that extend the
aliasing guarantee (and the flat-staging kernels) to the other faces:

  w0  r4z recipe: all-XLA packs, all-rdma, z-unpacks pallasb, 3 lanes
  w1  w0 + y-unpacks -> .pallasf   (aliased + consumes staging directly;
                                    0.44 ms one-op vs 67 ms XLA DUS)
  w2  w0 + x-unpacks -> .pallas    (aliased per-row window kernel)
  w3  all unpacks aliased: x .pallas, y .pallasf, z .pallasb
  w4  w3 + x/y packs -> .pallasf   (emit staging in-kernel)
  w5  w0 + z-unpacks -> .pallas    (aliased per-row instead of batched)

Output: experiments/MENU_INCUMBENT2.json.  Run alone on the real chip
(memory: tpu-bench-hygiene).
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def mk_prefer(unpack_map, pack_map):
    def prefer(op_name, choices):
        if op_name.startswith("xfer_"):
            return next((c for c in choices if c.endswith(".rdma")), None)
        axis = op_name.rsplit("_", 1)[1][-1]  # x / y / z
        table = unpack_map if op_name.startswith("unpack_") else pack_map
        want = table.get(axis, ".xla")
        hit = next((c for c in choices if c.endswith(want)), None)
        return hit if hit is not None else next(
            (c for c in choices if c.endswith(".xla")), None)

    return prefer


VARIANTS = [
    ("w0-r4z", {"z": ".pallasb"}, {}),
    ("w1-yflat", {"z": ".pallasb", "y": ".pallasf"}, {}),
    ("w2-xrow", {"z": ".pallasb", "x": ".pallas"}, {}),
    ("w3-allalias", {"z": ".pallasb", "y": ".pallasf", "x": ".pallas"}, {}),
    ("w4-packsflat", {"z": ".pallasb", "y": ".pallasf", "x": ".pallas"},
     {"x": ".pallasf", "y": ".pallasf"}),
    ("w5-zrow", {"z": ".pallas"}, {}),
]


def main() -> int:
    import jax

    from tenzing_tpu.bench.benchmarker import (
        BenchOpts,
        BenchResult,
        EmpiricalBenchmarker,
    )
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        HALO_PHASES,
        build_graph,
        host_buffer_names,
        make_pipeline_buffers,
        naive_order,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.local import drive, phase_policy
    from tenzing_tpu.utils.numeric import paired_speedup

    hargs = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)
    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    g = build_graph(hargs, impl_choice=True, xfer_choice=True)
    naive_seq = naive_order(hargs, Platform.make_n_lanes(1))
    plat3 = Platform.make_n_lanes(3)

    seqs = []
    for label, umap, pmap in VARIANTS:
        seq, _ = drive(g, plat3, phase_policy(
            plat3, HALO_PHASES, mk_prefer(umap, pmap)))
        seqs.append((label, seq))

    ex = TraceExecutor(Platform.make_n_lanes(8), jbufs)
    emp = EmpiricalBenchmarker(ex)
    screen_opts = BenchOpts(n_iters=8, target_secs=0.1, max_retries=2)
    t0 = time.time()
    times = emp.benchmark_batch_times(
        [naive_seq] + [s for _, s in seqs], screen_opts, seed=21)
    rows = {}
    for (label, _), ts in zip(seqs, times[1:]):
        res = BenchResult.from_times(ts)
        m, lo, hi = paired_speedup(times[0], ts, seed=22)
        rows[label] = {"pct50_ms": res.pct50 * 1e3,
                       "paired_vs_naive": [m, lo, hi]}
        sys.stderr.write(
            f"{label}: pct50={res.pct50*1e3:.3f}ms paired={m:.4f} "
            f"[{lo:.4f},{hi:.4f}]\n")
    naive_res = BenchResult.from_times(times[0])
    out = {
        "device": str(jax.devices()[0]),
        "protocol": "one decorrelated paired batch, n_iters=8, floor 0.1s",
        "naive_pct50_ms": naive_res.pct50 * 1e3,
        "variants": rows,
        "wall_s": round(time.time() - t0, 1),
    }
    path = Path(__file__).parent / "MENU_INCUMBENT2.json"
    path.write_text(json.dumps(out, indent=1))
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
