"""In-context per-op costs for the halo flagship — the corrected menu bound.

MENU_INCUMBENT.json falsified the r4 menu bound: composing the per-face
kernel minima from KERNEL_MICROBENCH.json (fetch-fenced jit-chain slopes)
produces schedules 1.3-1.6x SLOWER than naive, while the real winners
(halo_search_tpu_r4{k,y,z}.csv) choose almost the opposite menu — all-XLA
packs, all-rdma transfers, Pallas-batched z-unpacks only.  The chain-slope
numbers do not survive executor context (different fusion, token-lane
ordering, VMEM pressure, core serialization of Pallas kernels).

This experiment measures every menu variant of every face op as a ONE-OP
schedule under the same TraceExecutor + EmpiricalBenchmarker the search
uses (adaptive >=10x floor, fetch-fenced), plus the winner-recipe phase
cumulative (packs -> +xfers -> full) — the decomposition VERDICT r4 item 1
option (b) asks for.  Output: experiments/HALO_INCONTEXT.json with
 * per_op_ms: in-context cost of each variant,
 * menu_min_ms: the corrected serial compute floor (sum of per-op minima),
 * cumulative_ms: where the winner recipe's time actually goes.

Run alone on the real chip (memory: tpu-bench-hygiene).
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    import jax

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import DIRECTIONS, HaloArgs, dir_name
    from tenzing_tpu.models.halo_pipeline import (
        HALO_PHASES,
        direction_ops,
        host_buffer_names,
        make_pipeline_buffers,
    )
    from tenzing_tpu.ops.halo_pallas import (
        PackChoice,
        UnpackChoice,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.greedy import greedy_phase_order

    hargs = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)
    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    plat2 = Platform.make_n_lanes(2)
    ex = TraceExecutor(Platform.make_n_lanes(8), jbufs)
    emp = EmpiricalBenchmarker(ex)
    opts = BenchOpts(n_iters=6, target_secs=0.05, max_retries=2)

    def timed(label, build, plat=None):
        g = Graph()
        build(g)
        seq = greedy_phase_order(g, plat if plat is not None else plat2,
                                 HALO_PHASES)
        t0 = time.time()
        try:
            res = emp.benchmark(seq, opts)
        except Exception as e:
            sys.stderr.write(f"{label}: FAILED {type(e).__name__}: "
                             f"{str(e)[:120]}\n")
            return None
        sys.stderr.write(
            f"{label}: pct50={res.pct50*1e3:.4f}ms "
            f"(wall {time.time()-t0:.0f}s)\n")
        return res.pct50 * 1e3

    per_op = {}
    # one representative direction per axis (+/- are symmetric shapes)
    for d in [(1, 0, 0), (0, 1, 0), (0, 0, 1)]:
        dn = dir_name(d)
        pc, uc = PackChoice(hargs, d), UnpackChoice(hargs, d)
        for op in pc.choices():

            def one(g, op=op):
                g.start_then(op)
                g.then_finish(op)

            per_op[op.name()] = timed(op.name(), one)
        for op in uc.choices():

            def one(g, op=op):
                g.start_then(op)
                g.then_finish(op)

            per_op[op.name()] = timed(op.name(), one)
        # transfer engines for this axis (buf_<d> staged already in bufs)
        for engine in ("host", "rdma"):
            ops = direction_ops(hargs, d, engine=engine)
            xfer_chain = ops[1:-1]  # spill/fetch or rdma, plus await

            def chain(g, xfer_chain=xfer_chain):
                g.start_then(xfer_chain[0])
                for a, b in zip(xfer_chain, xfer_chain[1:]):
                    g.then(a, b)
                g.then_finish(xfer_chain[-1])

            per_op[f"xfer_{dn}.{engine}"] = timed(f"xfer_{dn}.{engine}",
                                                  chain)

    # corrected serial compute floor: per-axis minima x2 directions
    menu_min = 0.0
    per_axis = {}
    for ax in ("px", "py", "pz"):
        pmin = min(v for k, v in per_op.items()
                   if k.startswith(f"pack_{ax}.") and v is not None)
        umin = min(v for k, v in per_op.items()
                   if k.startswith(f"unpack_{ax}.") and v is not None)
        xmin = min(v for k, v in per_op.items()
                   if k.startswith(f"xfer_{ax}.") and v is not None)
        per_axis[ax] = {"pack_min_ms": pmin, "unpack_min_ms": umin,
                        "xfer_min_ms": xmin,
                        "pack_argmin": min(
                            ((v, k) for k, v in per_op.items()
                             if k.startswith(f"pack_{ax}.") and v is not None)
                        )[1],
                        "unpack_argmin": min(
                            ((v, k) for k, v in per_op.items()
                             if k.startswith(f"unpack_{ax}.")
                             and v is not None)
                        )[1]}
        menu_min += 2 * (pmin + umin)

    # winner-recipe cumulative, as explicit per-direction chain prefixes:
    # all-XLA packs, rdma transfers, z-unpacks pallasb / rest xla (the
    # revealed choice of the r4{k,y,z} winners)
    from tenzing_tpu.ops.comm_ops import AwaitTransfer
    from tenzing_tpu.ops.halo_pallas import PackXla, UnpackPallasB, UnpackXla
    from tenzing_tpu.ops.rdma import RdmaCopyStart

    def winner_chain(d):
        dn = dir_name(d)
        pack = PackXla(hargs, d)
        xfer = RdmaCopyStart(f"xfer_{dn}.rdma", f"buf_{dn}", f"recv_{dn}")
        await_ = AwaitTransfer(f"await_{dn}", f"recv_{dn}")
        unpack = (UnpackPallasB if d[2] != 0 else UnpackXla)(hargs, d)
        return [pack, xfer, await_, unpack]

    cumulative = {}

    def chains_prefix(label, n_ops):
        """All six directions' winner chains truncated to ``n_ops`` ops."""

        def build(g):
            for d in DIRECTIONS:
                ops = winner_chain(d)[:n_ops]
                g.start_then(ops[0])
                for a, b in zip(ops, ops[1:]):
                    g.then(a, b)
                g.then_finish(ops[-1])

        return timed(f"cumulative {label}", build,
                     plat=Platform.make_n_lanes(3))

    cumulative["packs"] = chains_prefix("packs", 1)
    cumulative["packs+xfers"] = chains_prefix("packs+xfers", 2)
    cumulative["packs+xfers+awaits"] = chains_prefix("packs+xfers+awaits", 3)
    cumulative["full"] = chains_prefix("full", 4)

    out = {
        "device": str(jax.devices()[0]),
        "protocol": "one-op schedules, EmpiricalBenchmarker n_iters=6 "
                    "floor 0.05s, 2-lane greedy",
        "per_op_ms": per_op,
        "per_axis": per_axis,
        "menu_min_serial_ms": menu_min,
        "cumulative_ms": cumulative,
    }
    path = Path(__file__).parent / "HALO_INCONTEXT.json"
    path.write_text(json.dumps(out, indent=1))
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
