"""Profile the flagship winner: where do 12.5 ms go when the menu-additive
floor is 3.4 ms?

Finding + measured follow-up (round 4): the trace attributes ~10 ms/iter of
busy time to XLA chunked layout-conversion copies (slice-start/copy-start
families through S(1)) implementing the (rows,128) <-> 4D-face reshapes of
the flat staging layout.  A 4D-staging-end-to-end rework (commit "4D staging
end-to-end", reverted) removed the reshapes — and made the searched winner
SLOWER (driver r4p: winner 15.3 ms vs the 12.2-12.6 ms flat-staging basin;
verdict 2.19 vs 2.45-2.59): 4D staging buffers are tile-padded, so y/z faces
(3-wide in a sublane/lane dim padded to 8/128) carry 2.7-42x more DMA bytes
per transfer than the dense flat layout.  The relayout tax is OVERLAPPABLE
(the searched schedules hide it behind transfers); the padded-DMA tax is
not.  Dense-but-reshape-free staging would need pack kernels that emit the
(rows,128) layout directly from the grid window (an in-kernel cross-lane
relayout Mosaic does not currently express cheaply) — recorded here as the
next kernel-level headroom, with the flat layout kept as the measured
winner.

Loads the best recorded schedule from the round-4 databases
(bench/recorded.py ranking), traces it with jax.profiler through the real
executor, and reports the device-timeline breakdown: per-op-name busy time,
transfer/compute concurrency, and the top time sinks.  Companion to
halo_roofline.py's bounds — this attributes the gap instead of just
measuring it.

Run on the TPU: python experiments/profile_winner.py
Writes experiments/PROFILE_WINNER.json (+ raw trace under experiments/traces/).
"""

import glob
import json
import sys
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def per_op_breakdown(trace_dir, top_n: int = 24):
    """Total busy ns per event name on device planes, longest first."""
    from jax.profiler import ProfileData

    paths = sorted(glob.glob(str(Path(trace_dir) / "**" / "*.xplane.pb"),
                             recursive=True))
    data = ProfileData.from_file(paths[-1])
    busy = defaultdict(float)
    spans = defaultdict(int)
    for plane in data.planes:
        pname = plane.name.lower()
        if not ("tpu" in pname or "device" in pname or "xla" in pname):
            continue
        for line in plane.lines:
            for ev in line.events:
                if ev.end_ns > ev.start_ns:
                    busy[ev.name] += (ev.end_ns - ev.start_ns) / 1e6
                    spans[ev.name] += 1
    rows = sorted(busy.items(), key=lambda kv: -kv[1])[:top_n]
    return [{"name": n, "total_ms": round(t, 3), "events": spans[n]}
            for n, t in rows]


def main() -> int:
    from tenzing_tpu.bench.compile_cache import enable_compile_cache

    enable_compile_cache()

    from tenzing_tpu.bench.recorded import rank_recorded
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        build_graph,
        host_buffer_names,
        make_pipeline_buffers,
        naive_order,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.utils.profiling import analyze_trace, capture_trace

    hargs = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)
    g = build_graph(hargs, impl_choice=True, xfer_choice=True)
    repo = Path(__file__).resolve().parent.parent
    paths = sorted(glob.glob(str(repo / "experiments" /
                                 "halo_search_tpu_r4*.csv")))
    ranked = rank_recorded(paths, g, topk=1,
                           log=lambda m: sys.stderr.write(m + "\n"))
    assert ranked, "no recorded winner to profile"
    winner, ratio = ranked[0]

    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    plat = Platform.make_n_lanes(8)
    ex = TraceExecutor(plat, jbufs)

    out = {"recorded_ratio": round(ratio, 4), "schedules": {}}
    tdir = repo / "experiments" / "traces"
    for label, seq in (
        ("winner", winner),
        ("naive", naive_order(hargs, Platform.make_n_lanes(1))),
    ):
        d = tdir / f"profile_{label}"
        _, wall = capture_trace(ex, seq, d, iters=3)
        conc = analyze_trace(d)
        ops = per_op_breakdown(d)
        out["schedules"][label] = {
            "wall_s_3iters": round(wall, 4),
            "concurrency": conc,
            "top_ops": ops,
        }
        sys.stderr.write(f"{label}: wall {wall:.3f}s\n")
        for r in ops[:12]:
            sys.stderr.write(
                f"  {r['total_ms']:9.3f} ms x{r['events']:<4} {r['name'][:90]}\n"
            )

    (repo / "experiments" / "PROFILE_WINNER.json").write_text(
        json.dumps(out, indent=1)
    )
    print("wrote experiments/PROFILE_WINNER.json")
    return 0


if __name__ == "__main__":
    sys.exit(main())
