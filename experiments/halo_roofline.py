"""Absolute (roofline) accounting for the halo flagship (VERDICT r3 item 3).

The searched-vs-naive headline is self-relative; this script pins it to the
hardware.  On the real chip it measures, at the flagship config (nq=3, 512^3,
r=3), the achievable bandwidth of each physical engine the schedule uses:

* ``host`` — the pinned-host round trip (spill + fetch + await), both
  serialized one-face-at-a-time (the naive discipline) and all-six-posted
  (the aggregate the overlap schedules can draw);
* ``rdma`` — the on-chip DMA loopback copy (post + await);
* ``compute`` — the pack+unpack slices alone (no transfers): the HBM-bound
  floor no schedule can beat.

From tenzing_tpu.bench.roofline.halo_cost it derives bytes/iteration, then
reports the measured naive and searched-winner times as a fraction of their
*achievable* bound:

  naive bound    = t_compute + xfer_bytes / host_bw_serial      (all serialized)
  searched bound = max(t_compute, host_share / host_bw_agg)     (ideal overlap;
                   the mixed winner moves half the faces on the on-chip DMA,
                   whose time is negligible next to the host path)

Appends/updates the ``halo_pipeline`` entry of
experiments/EXTERNAL_BASELINES.json — the row next to attention's 52%-MFU row.

Run AFTER any driver bench finishes (host CPU is in the measured path:
memory/tpu-bench-hygiene).
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    import jax
    import numpy as np

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.bench.roofline import halo_cost
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import DIRECTIONS, HaloArgs, dir_name
    from tenzing_tpu.models.halo_pipeline import (
        HALO_PHASES,
        direction_ops,
        host_buffer_names,
        make_pipeline_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.greedy import greedy_phase_order

    hargs = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)
    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    face_bytes = {
        dir_name(d): bufs[f"buf_{dir_name(d)}"].nbytes for d in DIRECTIONS
    }
    total_face = float(sum(face_bytes.values()))
    cost = halo_cost(hargs.nq, hargs.lx, hargs.ly, hargs.lz, hargs.radius)

    # HIGH adaptive floor: through the remote tunnel a single dispatch costs
    # ~130-140 ms RTT (probed), so per-sample costs are only trustworthy when
    # many samples amortize one dispatch — same reasoning as the driver's
    # final batch (20x floor)
    opts = BenchOpts(n_iters=8, target_secs=0.5)
    out = {"device": str(jax.devices()[0]), "config": vars(hargs).copy()
           if hasattr(hargs, "__dict__") else {
               "nq": hargs.nq, "n": hargs.lx, "radius": hargs.radius}}

    def timed(label, graph_ops_builder, n_lanes=8):
        """Benchmark a schedule built from subsets of the direction chains."""
        plat = Platform.make_n_lanes(n_lanes)
        g = Graph()
        graph_ops_builder(g)
        seq = greedy_phase_order(g, plat, HALO_PHASES)
        ex = TraceExecutor(plat, jbufs)
        t0 = time.time()
        res = EmpiricalBenchmarker(ex).benchmark(seq, opts)
        sys.stderr.write(
            f"{label}: pct50={res.pct50*1e3:.3f}ms (wall {time.time()-t0:.0f}s)\n"
        )
        return res.pct50

    # 1) compute floor: pack-only and unpack-only chains (recv buffers are
    # pre-filled zeros — the unpack's cost is the slice write, independent of
    # values)
    def packs_only(g):
        for d in DIRECTIONS:
            ops = direction_ops(hargs, d, engine="rdma")
            g.start_then(ops[0])
            g.then_finish(ops[0])

    def unpacks_only(g):
        for d in DIRECTIONS:
            ops = direction_ops(hargs, d, engine="rdma")
            g.start_then(ops[-1])
            g.then_finish(ops[-1])

    t_pack = timed("packs x6 (8 lanes)", packs_only)
    t_unpack = timed("unpacks x6 (8 lanes)", unpacks_only)
    t_compute = t_pack + t_unpack  # unpacks serialize on U (SSA); packs overlap

    # 2) host round trip, serialized (naive's transfer regime): one direction
    def host_one(g):
        d = DIRECTIONS[0]
        ops = direction_ops(hargs, d, engine="host")
        g.start_then(ops[0])
        for a, b in zip(ops, ops[1:]):
            g.then(a, b)
        g.then_finish(ops[-1])

    t_host1 = timed("host round trip x1", host_one, n_lanes=2)

    # 3) host round trip, all six posted before any await (aggregate)
    def host_all(g):
        for d in DIRECTIONS:
            ops = direction_ops(hargs, d, engine="host")
            g.start_then(ops[0])
            for a, b in zip(ops, ops[1:]):
                g.then(a, b)
            g.then_finish(ops[-1])

    t_host6 = timed("host round trips x6 overlapped", host_all)

    # 4) on-chip DMA copy (rdma loopback), one direction and all six
    def rdma_one(g):
        d = DIRECTIONS[0]
        ops = direction_ops(hargs, d, engine="rdma")
        g.start_then(ops[0])
        for a, b in zip(ops, ops[1:]):
            g.then(a, b)
        g.then_finish(ops[-1])

    def rdma_all(g):
        for d in DIRECTIONS:
            ops = direction_ops(hargs, d, engine="rdma")
            g.start_then(ops[0])
            for a, b in zip(ops, ops[1:]):
                g.then(a, b)
            g.then_finish(ops[-1])

    t_rdma1 = timed("rdma chain x1", rdma_one, n_lanes=2)
    t_rdma6 = timed("rdma chains x6", rdma_all)

    one_face = float(face_bytes[dir_name(DIRECTIONS[0])])
    # bytes over the host path: spill + fetch = 2 crossings per face
    bw = {
        "host_serial_gbs": 2 * one_face / (t_host1 - (t_pack + t_unpack) / 6) / 1e9
        if t_host1 > (t_pack + t_unpack) / 6 else 2 * one_face / t_host1 / 1e9,
        "host_aggregate_gbs": 2 * total_face / (t_host6 - t_compute) / 1e9
        if t_host6 > t_compute else 2 * total_face / t_host6 / 1e9,
        "rdma_copy_gbs": 2 * one_face / (t_rdma1 - (t_pack + t_unpack) / 6) / 1e9
        if t_rdma1 > (t_pack + t_unpack) / 6 else 2 * one_face / t_rdma1 / 1e9,
    }

    out.update(
        bytes_per_iter={
            "hbm_bytes": cost.hbm_bytes,
            "xfer_bytes_all_host": cost.xfer_bytes,
            "face_bytes_total": total_face,
        },
        measured_ms={
            "packs_x6": t_pack * 1e3,
            "unpacks_x6": t_unpack * 1e3,
            "host_roundtrip_x1": t_host1 * 1e3,
            "host_roundtrip_x6_overlapped": t_host6 * 1e3,
            "rdma_chain_x1": t_rdma1 * 1e3,
            "rdma_chains_x6": t_rdma6 * 1e3,
        },
        achievable_bandwidth=bw,
    )

    # bounds for the two disciplines at the flagship config
    host_serial = 2 * total_face / (bw["host_serial_gbs"] * 1e9)
    naive_bound = t_compute + host_serial
    half_host = total_face  # mixed winner: 3 of 6 faces on the host path
    searched_bound = max(t_compute, half_host / (bw["host_aggregate_gbs"] * 1e9))
    out["bounds_ms"] = {
        "t_compute": t_compute * 1e3,
        "naive_all_host_serial": naive_bound * 1e3,
        "searched_mixed_ideal_overlap": searched_bound * 1e3,
    }

    # menu-aware compute floor: t_compute above is the XLA slice/DUS chain,
    # but the schedule chooses per-face kernels from a 3-way menu
    # (ops/halo_pallas.py), and the r4k+ winners run batched-Pallas z-unpacks
    # far below the XLA DUS chain — so the honest floor per face is the MIN
    # over the measured kernel variants (experiments/kernel_microbench.py,
    # fetch-fenced chain slopes).  Without this the winner "beats the bound",
    # which just means the bound was computed for kernels it doesn't use.
    micro_path = Path(__file__).parent / "KERNEL_MICROBENCH.json"
    if micro_path.exists():
        micro = json.loads(micro_path.read_text())
        t_menu = 0.0
        per_axis = {}
        for a in ("px", "py", "pz"):
            r = micro["faces"][a]
            pmin = min(
                max(r[f"pack_{v}_ms_derived"], 0.02)
                for v in ("xla", "row", "batched")
            )
            umin = min(
                max(r[f"unpack_{v}_ms"], 0.02)
                for v in ("xla", "row", "batched")
            )
            per_axis[a] = {"pack_min_ms": pmin, "unpack_min_ms": umin}
            t_menu += 2 * (pmin + umin)  # both +/- faces per axis
        xfer_rdma = 2 * total_face / (bw["rdma_copy_gbs"] * 1e9) * 1e3
        out["bounds_menu_ms"] = {
            "t_compute_menu": t_menu,
            "per_axis": per_axis,
            "xfer_all_rdma_serial": xfer_rdma,
            "searched_all_rdma_ideal_overlap": max(t_menu, xfer_rdma),
        }

    # fold in the driver's measured verdict when present (BENCH_r04 written by
    # the driver later; fall back to the most recent bench CSV's finals)
    argv = sys.argv[1:]
    if len(argv) >= 2:
        naive_ms, searched_ms = float(argv[0]), float(argv[1])
        out["driver_measured_ms"] = {"naive": naive_ms, "searched": searched_ms}
        out["fraction_of_achievable"] = {
            "naive": naive_bound * 1e3 / naive_ms,
            "searched": searched_bound * 1e3 / searched_ms,
        }
        if "bounds_menu_ms" in out:
            out["fraction_of_achievable"]["searched_vs_menu_bound"] = (
                out["bounds_menu_ms"]["searched_all_rdma_ideal_overlap"]
                / searched_ms
            )

    path = Path(__file__).parent / "EXTERNAL_BASELINES.json"
    db = json.loads(path.read_text())
    db["entries"] = [e for e in db["entries"] if e.get("workload") != "halo_pipeline"]
    db["entries"].append({"workload": "halo_pipeline", **out})
    path.write_text(json.dumps(db, indent=1))
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
