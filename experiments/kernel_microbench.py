"""Per-face pack/unpack kernel microbench on the real chip: XLA slice/DUS vs
per-row window-DMA Pallas kernel vs batched-row prefetching kernel
(ops/halo_pallas.py).

Measurement method — two tunnel pitfalls probed on this backend:

* ``block_until_ready`` returns before device execution completes through the
  remote-tunnel PJRT backend (the library benchmarker already knows this,
  bench/benchmarker.py:20-25), so every timing is fenced by a device->host
  fetch of one element of the result.
* a single kernel dispatch costs a ~6-12 ms tunnel round trip, far above the
  0.1-5 ms kernels being compared, so each measurement runs a K-length
  ``fori_loop`` chain of data-dependent applications inside ONE program and
  reports the (K_hi - K_lo) wall-time slope — fixed dispatch+fetch overhead
  cancels.

Findings at the flagship geometry (written to KERNEL_MICROBENCH.json): the
unpack kernel family is face-direction-dependent by >20x — XLA's aliased
narrow DUS wins z-faces (no lane-tile window amplification), the Pallas
window kernels win y-faces by ~4x, i.e. exactly the storage-order
kernel-family question the menu exposes to the search.

Run on the TPU: python experiments/kernel_microbench.py   (TZ_FACES=xyz)
"""

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

K_LO, K_HI = 4, 44
REPS = 9


def main():
    import jax

    from tenzing_tpu.bench.compile_cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp
    import jax.lax as lax

    from tenzing_tpu.models.halo import HaloArgs, _face_slices, dir_name
    from tenzing_tpu.models.halo_pipeline import _padded_shape
    from tenzing_tpu.ops.halo_pallas import (
        _face_bx,
        pack_face_pallas,
        pack_face_pallas_batched,
        unpack_face_pallas,
        unpack_face_pallas_batched,
    )

    n = 512
    args = HaloArgs(nq=3, lx=n, ly=n, lz=n, radius=3)
    rng = np.random.default_rng(0)
    pad = _padded_shape(args.local_shape())
    U0 = jnp.asarray(rng.random(pad, dtype=np.float32))

    def slope(mk_chain):
        """(wall(K_HI) - wall(K_LO)) / (K_HI - K_LO), median over REPS,
        each wall fetch-fenced."""
        walls = {}
        for K in (K_LO, K_HI):
            cj = jax.jit(mk_chain(K))
            float(cj(U0, jnp.float32(0.0)))  # warm / compile
            ts = []
            for i in range(REPS):
                t0 = time.perf_counter()
                float(cj(U0, jnp.float32(i + 1.0)))
                ts.append(time.perf_counter() - t0)
            walls[K] = float(np.median(ts))
        return (walls[K_HI] - walls[K_LO]) / (K_HI - K_LO)

    out = {"config": {"nq": 3, "n": n, "radius": 3, "padded": list(pad)},
           "method": f"fetch-fenced fori_loop chain slope K={K_LO}->{K_HI}, "
                     f"median of {REPS}",
           "faces": {}}
    axes = {"x": (1, 0, 0), "y": (0, 1, 0), "z": (0, 0, 1)}
    # one face per axis sign-class is enough (±d are geometrically congruent)
    for a in os.environ.get("TZ_FACES", "xyz"):
        d = axes[a]
        ps, sz = _face_slices(args, d, "pack")
        us, _ = _face_slices(args, d, "unpack")
        ps, sz, us = tuple(ps), tuple(sz), tuple(us)
        face0 = jnp.asarray(rng.random(sz, dtype=np.float32))

        # numerics first (device-side compare: np round-trips 2 GB through
        # the tunnel)
        want_p = lax.dynamic_slice(U0, ps, sz)
        for fn, nm in [(pack_face_pallas, "row"),
                       (pack_face_pallas_batched, "batched")]:
            assert bool(jnp.allclose(fn(U0, ps, sz), want_p)), f"pack {nm} {d}"
        want_u = lax.dynamic_update_slice(U0, face0, us)
        for fn, nm in [(unpack_face_pallas, "row"),
                       (unpack_face_pallas_batched, "batched")]:
            assert bool(jnp.allclose(fn(U0, face0, us), want_u)), \
                f"unpack {nm} {d}"
        del want_p, want_u

        unpacks = {
            "xla": lambda U, f: lax.dynamic_update_slice(U, f, us),
            "row": lambda U, f: unpack_face_pallas(U, f, us),
            "batched": lambda U, f: unpack_face_pallas_batched(U, f, us),
        }
        packs = {
            "xla": lambda U: lax.dynamic_slice(U, ps, sz),
            "row": lambda U: pack_face_pallas(U, ps, sz),
            "batched": lambda U: pack_face_pallas_batched(U, ps, sz),
        }
        r = {"bx": _face_bx(args, d),
             "face_mb": round(float(np.prod(sz)) * 4 / 1e6, 2)}
        for nm, kern in unpacks.items():
            def mk_chain(K, kern=kern):
                def chain(U, s):
                    def body(t, Uc):
                        return kern(Uc, face0 + s + jnp.float32(t))
                    Uo = lax.fori_loop(0, K, body, U)
                    return Uo[0, us[1], us[2], us[3]]
                return chain
            r[f"unpack_{nm}_ms"] = round(slope(mk_chain) * 1e3, 4)
        # pack alone can't be chained (static starts -> a pack-only loop body
        # is loop-invariant and XLA hoists it); chain the pack∘unpack round
        # trip each schedule actually uses (pack reads the interior edge,
        # unpack writes the disjoint ghost shell, so the composition neither
        # converges nor self-feeds) and derive pack = roundtrip - unpack
        for nm in unpacks:
            pk, up = packs[nm], unpacks[nm]

            def mk_chain(K, pk=pk, up=up):
                def chain(U, s):
                    def body(t, Uc):
                        return up(Uc, pk(Uc) + s + jnp.float32(t))
                    Uo = lax.fori_loop(0, K, body, U)
                    return Uo[0, us[1], us[2], us[3]]
                return chain
            rt = slope(mk_chain) * 1e3
            r[f"roundtrip_{nm}_ms"] = round(rt, 4)
            r[f"pack_{nm}_ms_derived"] = round(rt - r[f"unpack_{nm}_ms"], 4)
        out["faces"][dir_name(d)] = r
        print(dir_name(d), json.dumps(r), flush=True)

    path = Path(__file__).parent / "KERNEL_MICROBENCH.json"
    if path.exists():
        prev = json.loads(path.read_text())
        if (prev.get("method"), prev.get("config")) == (out["method"],
                                                        out["config"]):
            prev["faces"].update(out["faces"])
            out = prev
    path.write_text(json.dumps(out, indent=1))
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
