#!/usr/bin/env python
"""Device-side profiler evidence of schedule overlap (VERDICT r2 item 3/5).

Host-side phase counters match the reference (counters.hpp); SURVEY §5 maps
device-side profiling to JAX profiler traces.  This script captures an
``xplane`` trace of the halo pipeline under (a) the naive fully-serialized
schedule and (b) the searched/greedy 2-lane overlap schedule, on the real
chip, then PARSES the traces (jax.profiler.ProfileData) and measures how much
wall time has a host-transfer (DMA/copy) event concurrent with a device
compute event — the quantity the whole framework exists to create.

Artifacts:
* ``experiments/traces/halo_naive/`` and ``.../halo_overlap/`` — raw xplane
  trace directories (loadable in TensorBoard's profile plugin or xprof);
* ``experiments/PROFILE_OVERLAP.json`` — the parsed concurrency summary.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

TRACE_ROOT = Path(__file__).parent / "traces"


def build(n=256):
    import jax.numpy as jnp  # noqa: F401

    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        greedy_overlap_order,
        host_buffer_names,
        make_pipeline_buffers,
        naive_order,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    hargs = HaloArgs(nq=3, lx=n, ly=n, lz=n, radius=3)
    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    plat2 = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat2, jbufs)
    naive = naive_order(hargs, Platform.make_n_lanes(1))
    overlap = greedy_overlap_order(hargs, plat2)
    return ex, {"halo_naive": naive, "halo_overlap": overlap}


def capture(ex, name, order, iters=3):
    from tenzing_tpu.utils.profiling import capture_trace

    return capture_trace(ex, order, TRACE_ROOT / name, iters=iters)


def analyze(trace_dir: Path):
    from tenzing_tpu.utils.profiling import analyze_trace

    return analyze_trace(trace_dir)


def main() -> int:
    import jax

    sys.stderr.write(f"backend: {jax.devices()}\n")
    ex, orders = build()
    out = {"device": str(jax.devices()[0]), "schedules": {}}
    for name, order in orders.items():
        tdir, wall = capture(ex, name, order)
        summary = analyze(tdir)
        summary["wall_s"] = round(wall, 3)
        out["schedules"][name] = summary
        sys.stderr.write(f"{name}: {json.dumps(summary)}\n")
    ov = out["schedules"].get("halo_overlap", {})
    nv = out["schedules"].get("halo_naive", {})
    if "transfer_concurrent_with_compute_ms" in ov:
        out["verdict"] = {
            "overlap_schedule_concurrency_ms":
                ov["transfer_concurrent_with_compute_ms"],
            "naive_schedule_concurrency_ms":
                nv.get("transfer_concurrent_with_compute_ms"),
        }
    path = Path(__file__).parent / "PROFILE_OVERLAP.json"
    path.write_text(json.dumps(out, indent=1))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
