#!/usr/bin/env python
"""Device-side profiler evidence of schedule overlap (VERDICT r2 item 3/5).

Host-side phase counters match the reference (counters.hpp); SURVEY §5 maps
device-side profiling to JAX profiler traces.  This script captures an
``xplane`` trace of the halo pipeline under (a) the naive fully-serialized
schedule and (b) the searched/greedy 2-lane overlap schedule, on the real
chip, then PARSES the traces (jax.profiler.ProfileData) and measures how much
wall time has a host-transfer (DMA/copy) event concurrent with a device
compute event — the quantity the whole framework exists to create.

Artifacts:
* ``experiments/traces/halo_naive/`` and ``.../halo_overlap/`` — raw xplane
  trace directories (loadable in TensorBoard's profile plugin or xprof);
* ``experiments/PROFILE_OVERLAP.json`` — the parsed concurrency summary.
"""

import glob
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

TRACE_ROOT = Path(__file__).parent / "traces"


def build(n=256):
    import jax.numpy as jnp  # noqa: F401

    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        greedy_overlap_order,
        host_buffer_names,
        make_pipeline_buffers,
        naive_order,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    hargs = HaloArgs(nq=3, lx=n, ly=n, lz=n, radius=3)
    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    plat2 = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat2, jbufs)
    naive = naive_order(hargs, Platform.make_n_lanes(1))
    overlap = greedy_overlap_order(hargs, plat2)
    return ex, {"halo_naive": naive, "halo_overlap": overlap}


def capture(ex, name, order, iters=3):
    import jax

    run_n = ex.prepare_n(order)
    run_n(1)  # compile + warm
    out_dir = TRACE_ROOT / name
    out_dir.mkdir(parents=True, exist_ok=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(str(out_dir)):
        run_n(iters)
    wall = time.perf_counter() - t0
    return out_dir, wall


def _events(plane):
    for line in plane.lines:
        lname = line.name
        for ev in line.events:
            yield lname, ev


def analyze(trace_dir: Path):
    """Concurrency between transfer (DMA/copy) and compute events on the
    device planes of the newest xplane file under ``trace_dir``."""
    from jax.profiler import ProfileData

    paths = sorted(glob.glob(str(trace_dir / "**" / "*.xplane.pb"),
                             recursive=True))
    if not paths:
        return {"error": f"no xplane under {trace_dir}"}
    data = ProfileData.from_file(paths[-1])
    xfers, computes = [], []
    for plane in data.planes:
        pname = plane.name.lower()
        if not ("tpu" in pname or "device" in pname or "xla" in pname):
            continue
        for lname, ev in _events(plane):
            nm = (ev.name or "").lower()
            iv = (ev.start_ns, ev.end_ns)
            if iv[1] <= iv[0]:
                continue
            if any(k in nm for k in ("copy", "dma", "transfer", "infeed",
                                     "outfeed", "send", "recv")):
                xfers.append(iv)
            # NOTE: no outer control events ("while"/"loop" span the whole
            # program and would make every DMA look concurrent with compute)
            elif any(k in nm for k in ("fusion", "dynamic", "slice", "pad",
                                       "convert", "reshape", "add",
                                       "concatenate")):
                computes.append(iv)

    def merge(ivs):
        """Coalesce intervals so busy time and intersections count each
        nanosecond once (overlapping events must not double-count)."""
        out = []
        for a, b in sorted(ivs):
            if out and a <= out[-1][1]:
                out[-1][1] = max(out[-1][1], b)
            else:
                out.append([a, b])
        return out

    def total(ivs):
        return sum(b - a for a, b in merge(ivs))

    overlap_ns = 0
    computes_merged = merge(computes)
    for a, b in merge(xfers):
        for c, d in computes_merged:
            if c >= b:
                break
            lo, hi = max(a, c), min(b, d)
            if hi > lo:
                overlap_ns += hi - lo
    return {
        "xplane": paths[-1],
        "n_transfer_events": len(xfers),
        "n_compute_events": len(computes),
        "transfer_busy_ms": total(xfers) / 1e6,
        "compute_busy_ms": total(computes) / 1e6,
        "transfer_concurrent_with_compute_ms": overlap_ns / 1e6,
    }


def main() -> int:
    import jax

    sys.stderr.write(f"backend: {jax.devices()}\n")
    ex, orders = build()
    out = {"device": str(jax.devices()[0]), "schedules": {}}
    for name, order in orders.items():
        tdir, wall = capture(ex, name, order)
        summary = analyze(tdir)
        summary["wall_s"] = round(wall, 3)
        out["schedules"][name] = summary
        sys.stderr.write(f"{name}: {json.dumps(summary)}\n")
    ov = out["schedules"].get("halo_overlap", {})
    nv = out["schedules"].get("halo_naive", {})
    if "transfer_concurrent_with_compute_ms" in ov:
        out["verdict"] = {
            "overlap_schedule_concurrency_ms":
                ov["transfer_concurrent_with_compute_ms"],
            "naive_schedule_concurrency_ms":
                nv.get("transfer_concurrent_with_compute_ms"),
        }
    path = Path(__file__).parent / "PROFILE_OVERLAP.json"
    path.write_text(json.dumps(out, indent=1))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
