"""Can the existing menu compose to the 3.4 ms bound? (VERDICT r4 item 1.)

The round-4 verdict: the searched halo winner (12.56 ms) sits at 27% of the
builder's own menu-aware achievable bound (3.39 ms = per-face kernel minima
from experiments/KERNEL_MICROBENCH.json + all-rdma transfers ideally
overlapped).  Three possible answers — the search can't reach the region, the
bound is wrong, or the all-rdma regime has an unmodeled cost — and this
experiment separates them by *constructing the bound's schedule directly*:
per-face argmin kernels, all-rdma engines, paired await/unpack discipline,
driven through the same SDP machinery the solvers use (solve/local.drive +
phase_policy(prefer=...)), then measured as one decorrelated PAIRED batch
against naive (the driver's screen/final protocol, bench.py).

Variants probed: the microbench-argmin map, the flat-kernel map (pallasf
skips the XLA flatten pass where sz%128==0), lane counts {3, 8}, priorities
{phase, paired}.  Results land in experiments/MENU_INCUMBENT.json; whichever
wins becomes the ``greedy-menu-*`` incumbent family in bench.py.

Run on the real chip AFTER any driver bench (host CPU is in the measured
path).
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# the per-face kernel argmin measured by experiments/kernel_microbench.py
# (KERNEL_MICROBENCH.json, fetch-fenced chain slopes): x-packs per-row,
# y/z-packs batched, x/y-unpacks batched, z-unpacks XLA DUS
MENU_BEST = {
    "pack_px": ".pallas", "pack_mx": ".pallas",
    "pack_py": ".pallasb", "pack_my": ".pallasb",
    "pack_pz": ".pallasb", "pack_mz": ".pallasb",
    "unpack_px": ".pallasb", "unpack_mx": ".pallasb",
    "unpack_py": ".pallasb", "unpack_my": ".pallasb",
    "unpack_pz": ".xla", "unpack_mz": ".xla",
}
# the flat twins where legal (x/y faces): staging emitted/consumed directly
# in the kernel, no separate XLA flatten/unflatten relayout pass — the pass
# profile_winner measured at ~10 ms/iter across the r4 winner's schedule
MENU_FLAT = dict(MENU_BEST)
MENU_FLAT.update({
    "pack_px": ".pallasf", "pack_mx": ".pallasf",
    "pack_py": ".pallasf", "pack_my": ".pallasf",
    "unpack_px": ".pallasf", "unpack_mx": ".pallasf",
    "unpack_py": ".pallasf", "unpack_my": ".pallasf",
})


def mk_prefer(kernel_map, engine=".rdma"):
    def prefer(op_name, choices):
        if op_name.startswith("xfer_"):
            return next((c for c in choices if c.endswith(engine)), None)
        want = kernel_map.get(op_name)
        if want is not None:
            hit = next((c for c in choices if c.endswith(want)), None)
            if hit is not None:
                return hit
        return next((c for c in choices if c.endswith(".xla")), None)

    return prefer


def main() -> int:
    import jax

    from tenzing_tpu.bench.benchmarker import (
        BenchOpts,
        BenchResult,
        EmpiricalBenchmarker,
    )
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        HALO_PHASES,
        build_graph,
        host_buffer_names,
        make_pipeline_buffers,
        naive_order,
        paired_priority,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.local import drive, phase_policy
    from tenzing_tpu.utils.numeric import paired_speedup

    hargs = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)
    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    g = build_graph(hargs, impl_choice=True, xfer_choice=True)
    naive_seq = naive_order(hargs, Platform.make_n_lanes(1))

    variants = []
    for label, kmap, nl, pri in (
        ("menu-best-3l", MENU_BEST, 3, None),
        ("menu-best-3l-paired", MENU_BEST, 3, paired_priority("rdma")),
        ("menu-best-8l", MENU_BEST, 8, None),
        ("menu-flat-3l", MENU_FLAT, 3, None),
        ("menu-flat-3l-paired", MENU_FLAT, 3, paired_priority("rdma")),
        ("menu-flat-8l", MENU_FLAT, 8, None),
    ):
        plat = Platform.make_n_lanes(nl)
        seq, _ = drive(g, plat, phase_policy(
            plat, HALO_PHASES, mk_prefer(kmap), priority=pri))
        variants.append((label, seq))

    ex = TraceExecutor(Platform.make_n_lanes(8), jbufs)
    emp = EmpiricalBenchmarker(ex)

    # screen: one decorrelated paired batch, moderate floor (driver screen)
    screen_opts = BenchOpts(n_iters=8, target_secs=0.1, max_retries=2)
    t0 = time.time()
    times = emp.benchmark_batch_times(
        [naive_seq] + [s for _, s in variants], screen_opts, seed=11)
    rows = {}
    for (label, _), ts in zip(variants, times[1:]):
        res = BenchResult.from_times(ts)
        m, lo, hi = paired_speedup(times[0], ts, seed=12)
        rows[label] = {"pct50_ms": res.pct50 * 1e3,
                       "paired_vs_naive": [m, lo, hi]}
        sys.stderr.write(
            f"{label}: pct50={res.pct50*1e3:.3f}ms paired={m:.4f} "
            f"[{lo:.4f},{hi:.4f}]\n")
    naive_res = BenchResult.from_times(times[0])
    out = {
        "device": str(jax.devices()[0]),
        "protocol": "one decorrelated paired batch, n_iters=8, floor 0.1s",
        "naive_pct50_ms": naive_res.pct50 * 1e3,
        "variants": rows,
        "wall_s": round(time.time() - t0, 1),
    }
    path = Path(__file__).parent / "MENU_INCUMBENT.json"
    path.write_text(json.dumps(out, indent=1))
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
