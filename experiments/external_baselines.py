#!/usr/bin/env python
"""Searched winners vs STRONG EXTERNAL baselines, with fraction-of-peak.

VERDICT r2 weak #3: the 4.33x attention and 1.506x MoE wins were vs this
framework's own serialized naive order; nothing compared against an external
implementation or reported utilization.  This script runs, on the real chip:

* blockwise attention (bench config b=4, n=8k, d=128): our best schedule
  (bf16 Pallas kernel menu) vs ONE fused ``jax.nn.dot_product_attention``
  call (XLA's own flash path) in f32 and bf16 — same shapes, same
  scalar-reduce fencing, measured as one decorrelated paired batch
  (CallableRunner + benchmark_batch_times);
* MoE dispatch/combine (t=8k, d=512, dff=2048, E=8): our best schedule
  (bf16-staged greedy-overlap pipeline) vs a single-jit XLA MoE with the
  SAME routing tables and NO staging hop — the strongest single-chip
  implementation of the layer;

and reports achieved TFLOP/s + fraction of v5e bf16 peak for every entry
(bench/roofline.py).  Results land in experiments/EXTERNAL_BASELINES.json and
the README table.
"""

import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def repeat_fenced(body, *args):
    """``run_n(n)``: n executions of ``body(*args) -> array`` inside ONE
    compiled program, chained by a datatie so XLA cannot hoist the
    loop-invariant body, fenced by a device_get of one reduced scalar — the
    executor's prepare_n discipline for external callables (one tunnel round
    trip per measurement, however fast the kernel)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tenzing_tpu.runtime.executor import _clean, _scalarize, datatie

    def f(n, *arrs):
        def step(i, acc):
            tied = tuple(datatie(a, acc) for a in arrs)
            out = body(*tied)
            return _clean(_scalarize(jnp.sum(out)))

        return lax.fori_loop(0, n, step, jnp.zeros((), jnp.float32))

    # arrays go through as runtime parameters — closure capture would embed
    # them as compile-time constants in the lowered HLO (tens of MB)
    f_n = jax.jit(f)
    return lambda n: jax.device_get(f_n(jnp.int32(n), *args))


def measure_set(run_ns: dict, n_iters: int = 30, target_secs: float = 0.1):
    """Paired decorrelated batch over named run_n callables -> {name: times}."""
    from tenzing_tpu.bench.benchmarker import (
        BenchOpts,
        BenchResult,
        EmpiricalBenchmarker,
        RepeatCallableRunner,
    )

    emp = EmpiricalBenchmarker(RepeatCallableRunner(run_ns))
    names = list(run_ns)
    for nm in names:  # warm/compile one at a time, with visibility
        t0 = time.time()
        run_ns[nm](1)
        sys.stderr.write(f"  warm {nm}: {time.time()-t0:.1f}s\n")
    times = emp.benchmark_batch_times(
        names, BenchOpts(n_iters=n_iters, target_secs=target_secs), seed=11
    )
    sys.stderr.write("  batch done\n")
    return {n: ts for n, ts in zip(names, times)}, {
        n: BenchResult.from_times(ts) for n, ts in zip(names, times)
    }


def attn_entry():
    import jax
    import jax.numpy as jnp

    from tenzing_tpu.bench.roofline import attention_cost
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import ChooseOp, State
    from tenzing_tpu.models.ring_attention import (
        BlockedAttention,
        RingAttnArgs,
        make_blocked_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.utils.numeric import paired_speedup

    aargs = RingAttnArgs(n_devices=8, batch=4, seq_local=1024, head_dim=128)
    bufs, want = make_blocked_buffers(aargs, seed=0)
    jbufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    g = Graph()
    op = BlockedAttention(aargs, impl_choice=True, fused_choice=True)
    g.start_then(op)
    g.then_finish(op)
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, jbufs)

    def schedule_for(engine_suffix, kernel_suffix):
        st = State(g)
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            pick = next(
                (d for d in ds if isinstance(d, ChooseOp)
                 and d.choice.name().endswith(engine_suffix)),
                None,
            ) or next(
                (d for d in ds if isinstance(d, ChooseOp)
                 and d.choice.name().endswith(kernel_suffix)),
                ds[0],
            )
            st = st.apply(pick)
        return st.sequence

    # our two menu optima: (a) per-block chain, every block on the bf16
    # Pallas MXU kernel (the r2-r4 winner); (b) the fused single-kernel
    # flash with VMEM-resident softmax state (the r5 HBM-traffic fix)
    seq_chain = schedule_for(".chain", ".pallas_bf16")
    seq_fused = schedule_for(".fused_bf16", ".pallas_bf16")
    ours_prog = ex.compile(seq_chain)
    fused_prog = ex.compile(seq_fused)

    b, n, d = aargs.batch, aargs.seq_local * aargs.n_devices, aargs.head_dim
    q4 = jbufs["Q"].reshape(b, n, 1, d)
    k4 = jbufs["K"].reshape(b, n, 1, d)
    v4 = jbufs["V"].reshape(b, n, 1, d)

    def fused(q, k, v):
        return jax.nn.dot_product_attention(q, k, v, scale=aargs.scale)

    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q4, k4, v4))

    # numerics: our O agrees with the dense host reference (fetch O only —
    # fetching every buffer through the tunnel costs ~100 MB)
    sys.stderr.write("attn: numerics check...\n")
    o_ours = np.asarray(ours_prog(jbufs)["O"])
    np.testing.assert_allclose(o_ours, want, atol=0.05)
    o_fused = np.asarray(fused_prog(jbufs)["O"])
    np.testing.assert_allclose(o_fused, want, atol=0.05)
    sys.stderr.write("attn: numerics ok; measuring...\n")
    # CONTROL for the bf16 anomaly (VERDICT r4 item 3): a hand-written f32
    # attention that MATERIALIZES the (n, n) score matrix.  Measured (r5):
    # compiled memory analysis shows NEITHER precision gets a flash lowering
    # from XLA on this backend — f32 dot_product_attention materializes one
    # 1.074 GB n^2 temp (and times identically to this hand-written
    # materializing control, 4.70 vs 4.71 ms), while the bf16 lowering
    # allocates TWO n^2 temps (2.148 GB) and runs ~23x slower than its f32
    # twin at ~0.3% of HBM peak — a degenerate bf16 lowering (giant-tensor
    # relayout/conversion passes), not bf16 arithmetic (an f32-softmax bf16
    # variant is equally slow).  The searched Pallas menu is the only flash
    # path measured on this chip.
    def materializing_f32(q, k, v):
        import jax.numpy as _jnp

        s = _jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=_jnp.float32) * aargs.scale
        p = jax.nn.softmax(s, axis=-1)
        return _jnp.einsum("bhqk,bkhd->bqhd", p, v,
                           preferred_element_type=_jnp.float32)

    fns = {
        "searched_bf16_menu": ex.prepare_n(seq_chain),
        "searched_fused_bf16": ex.prepare_n(seq_fused),
        "xla_fused_f32": repeat_fenced(fused, q4, k4, v4),
        "xla_fused_bf16": repeat_fenced(fused, qb, kb, vb),
        "xla_materializing_f32": repeat_fenced(materializing_f32, q4, k4, v4),
    }
    times, results = measure_set(fns)
    # bytes/element per entry: the fused-bf16 baseline's Q/K/V really are
    # bf16 arrays in HBM (2 bytes); the searched menu reads the f32 buffers
    # and casts to bf16 inside the kernel (the MXU-width win, not an HBM
    # one), so its HBM cost stays f32
    costs = {
        "searched_bf16_menu": attention_cost(b, n, d, bytes_per_el=4),
        "searched_fused_bf16": attention_cost(b, n, d, bytes_per_el=4),
        "xla_fused_f32": attention_cost(b, n, d, bytes_per_el=4),
        "xla_fused_bf16": attention_cost(b, n, d, bytes_per_el=2),
        "xla_materializing_f32": attention_cost(b, n, d, bytes_per_el=4),
    }
    entry = {"workload": "blocked_attention", "config": {"b": b, "n": n, "d": d}}
    for name, res in results.items():
        entry[name] = {
            "pct50_ms": res.pct50 * 1e3,
            **{k: round(v, 4)
               for k, v in costs[name].utilization(res.pct50).items()},
        }
    # the bf16 "fused" row is a degenerate lowering, not a fair baseline:
    # flag it so no one quotes a paired ratio against it (the control row
    # proves the cause — materializing f32 costs the same)
    entry["xla_fused_bf16"]["anomalous_baseline"] = True
    entry["xla_fused_bf16"]["cause"] = (
        "degenerate XLA bf16 lowering: memory analysis shows 2.148 GB of "
        "n^2 temps (two score-matrix copies) vs the f32 lowering's "
        "1.074 GB, running ~23x slower than the f32 twin at ~0.3% of HBM "
        "peak; not bf16 arithmetic (f32-softmax variant equally slow) and "
        "not flash-vs-materializing (neither XLA lowering is flash — the "
        "f32 path times identically to the materializing control)"
    )
    ours_best = min(("searched_bf16_menu", "searched_fused_bf16"),
                    key=lambda nm: results[nm].pct50)
    entry["ours_best"] = ours_best
    entry["mfu_ceiling_note"] = (
        "the fused single-kernel variant (attn_fused_pallas, VMEM-resident "
        "state, removes ~0.8 GB/iter of acc/m/l HBM round trips) measures "
        "within a few % of the chain — HBM state traffic is NOT the binding "
        "constraint; the remaining gap to peak is the in-kernel "
        "s->softmax->PV dependency chain (MXU idles during the VPU exp over "
        "each n*nkv score tile; Mosaic does not software-pipeline the "
        "independent QK^T(t+1) into that window). Closing it needs "
        "cross-step software pipelining inside the kernel, not block-size "
        "tuning (probed: fused bkv=1024 changes nothing)."
    )
    for name in ("xla_fused_f32", "xla_fused_bf16"):
        m, lo, hi = paired_speedup(times[name], times[ours_best], seed=5)
        entry[f"ours_vs_{name}"] = {"paired": round(m, 4),
                                    "ci": [round(lo, 4), round(hi, 4)]}
    entry["ours_vs_xla_fused_bf16"]["do_not_quote"] = (
        "denominator is the anomalous non-flash lowering; quote "
        "ours_vs_xla_fused_f32 instead"
    )
    return entry


def moe_entry():
    import jax
    import jax.numpy as jnp

    from tenzing_tpu.bench.roofline import moe_cost
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.moe_pipeline import (
        MoEPipeArgs,
        greedy_overlap_order,
        host_buffer_names,
        make_pipe_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.utils.numeric import paired_speedup

    margs = MoEPipeArgs()
    bufs, want, cap = make_pipe_buffers(margs, seed=0, with_expected=True,
                                        staging="bf16")
    jbufs = TraceExecutor.place_host_buffers(
        bufs, host_buffer_names(margs, staging="bf16"))
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, jbufs)
    order = greedy_overlap_order(margs, cap, plat, staging="bf16")

    # single-jit XLA MoE: same routing tables, no staging hop — gather,
    # per-expert gelu MLP, weighted scatter, all fused by XLA in one program
    X = jbufs["X"]
    W1, W2 = jbufs["W1"], jbufs["W2"]
    idx = [jbufs[f"idx_{c}"] for c in range(margs.n_chunks)]
    w = [jbufs[f"w_{c}"] for c in range(margs.n_chunks)]
    tc = margs.chunk_tokens

    def xla_moe(X, W1, W2, idx, w):
        ys = []
        for c in range(margs.n_chunks):
            xc = X[c * tc : (c + 1) * tc]
            slots = xc[idx[c]]  # (E, C, d)
            h = jax.nn.gelu(jnp.einsum(
                "ecd,edf->ecf", slots, W1, preferred_element_type=jnp.float32))
            out = jnp.einsum(
                "ecf,efd->ecd", h.astype(slots.dtype), W2,
                preferred_element_type=jnp.float32)
            y = jnp.zeros((tc, margs.d_model), jnp.float32)
            ys.append(
                y.at[idx[c].reshape(-1)].add(
                    w[c].reshape(-1, 1) * out.reshape(-1, margs.d_model))
            )
        return jnp.concatenate(ys)

    sys.stderr.write("moe: numerics check...\n")
    y_ours = np.asarray(ex.compile(order)(jbufs)["Y"])
    np.testing.assert_allclose(y_ours, want, atol=0.15, rtol=0.05)
    sys.stderr.write("moe: numerics ok; measuring...\n")
    fns = {
        "searched_bf16_staged": ex.prepare_n(order),
        "xla_single_jit": repeat_fenced(
            lambda X_, W1_, W2_: xla_moe(X_, W1_, W2_, idx, w), X, W1, W2),
    }
    times, results = measure_set(fns)
    cost_staged = moe_cost(margs.tokens, margs.d_model, margs.d_ff, staged=True,
                           n_experts=margs.n_experts)
    cost_plain = moe_cost(margs.tokens, margs.d_model, margs.d_ff, staged=False,
                          n_experts=margs.n_experts)
    entry = {"workload": "moe_pipeline",
             "config": {"tokens": margs.tokens, "d": margs.d_model,
                        "dff": margs.d_ff, "experts": margs.n_experts}}
    entry["searched_bf16_staged"] = {
        "pct50_ms": results["searched_bf16_staged"].pct50 * 1e3,
        **{k: round(v, 4) for k, v in
           cost_staged.utilization(results["searched_bf16_staged"].pct50).items()},
    }
    entry["xla_single_jit"] = {
        "pct50_ms": results["xla_single_jit"].pct50 * 1e3,
        **{k: round(v, 4) for k, v in
           cost_plain.utilization(results["xla_single_jit"].pct50).items()},
    }
    m, lo, hi = paired_speedup(
        times["xla_single_jit"], times["searched_bf16_staged"], seed=5)
    entry["ours_vs_xla_single_jit"] = {"paired": round(m, 4),
                                       "ci": [round(lo, 4), round(hi, 4)]}
    # label the comparison honestly (VERDICT r4 weak #7): this row measures
    # the STAGED pipeline variant (host-staged dispatch/combine hops) against
    # the no-hop single-jit upper bound — a diagnostic of the staging tax,
    # NOT the searched winner.  The driver's searched winner (BENCH moe runs)
    # is the kernel-menu schedule BASELINE.md quotes at within ~8% of
    # single-jit.
    entry["ours_vs_xla_single_jit"]["diagnostic_row"] = (
        "staged-variant vs no-hop upper bound; not the searched winner — "
        "see BENCH moe runs for the headline schedule"
    )
    return entry


def main() -> int:
    import jax

    sys.stderr.write(f"backend: {jax.devices()}\n")
    out = {"device": str(jax.devices()[0]), "entries": []}
    for name, fn in (("attention", attn_entry), ("moe", moe_entry)):
        t0 = time.time()
        entry = fn()
        entry["wall_s"] = round(time.time() - t0, 1)
        out["entries"].append(entry)
        sys.stderr.write(f"{name}: {json.dumps(entry)}\n")
    path = Path(__file__).parent / "EXTERNAL_BASELINES.json"
    # merge by workload: other scripts (halo_roofline.py) own other entries
    if path.exists():
        prev = json.loads(path.read_text())
        mine = {e.get("workload") for e in out["entries"]}
        out["entries"] += [
            e for e in prev.get("entries", []) if e.get("workload") not in mine
        ]
    path.write_text(json.dumps(out, indent=1))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
