#!/usr/bin/env python
"""On-device numerics validation: real TPU, real (non-interpret) Pallas.

The CPU test suite runs every kernel in Pallas interpret mode (SURVEY.md §4:
the reference's CI is likewise a CPU subset); this script is the device tier —
it executes each single-chip workload's searched program on the actual chip,
with the Pallas kernels compiled by Mosaic, and checks the outputs against the
host float64 references.  Writes ``experiments/TPU_NUMERICS.json`` so the
validation is a recorded artifact, and is importable by the opt-in pytest
wrapper (tests/test_device_numerics.py, gated on TENZING_TPU_DEVICE_TESTS=1).

Run: ``python experiments/device_numerics.py`` (needs a TPU backend).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_spmv(results):
    """SpMV compound with the Pallas kernel choice forced (device Mosaic)."""
    import jax.numpy as jnp
    import numpy as np

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import ChooseOp, State
    from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers
    from tenzing_tpu.runtime.executor import TraceExecutor

    bufs, want = make_spmv_buffers(m=2048, nnz_per_row=8, seed=3)
    x_sizes = {"x_local": int(bufs["x_local"].shape[0]),
               "x_remote": int(bufs["x_remote"].shape[0])}
    g = Graph()
    g.start_then(SpMVCompound(impl_choice=True, x_sizes=x_sizes))
    g.then_finish(SpMVCompound(impl_choice=True, x_sizes=x_sizes))
    plat = Platform.make_n_lanes(1)
    st = State(g)
    picked_pallas = 0
    while not st.is_terminal():
        ds = st.get_decisions(plat)
        pick = next((d for d in ds if isinstance(d, ChooseOp)
                     and ".pallas" in d.choice.name()), ds[0])
        if isinstance(pick, ChooseOp) and ".pallas" in pick.choice.name():
            picked_pallas += 1
        st = st.apply(pick)
    ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
    out = ex.run(st.sequence)
    err = float(np.max(np.abs(np.asarray(out["y"]) - want)
                       / (np.abs(want) + 1e-6)))
    results["spmv_pallas"] = {"pallas_choices": picked_pallas,
                              "max_rel_err": err, "ok": err < 2e-3}


def check_attention(results):
    """Blocked attention, f32 and bf16 Pallas kernels on the MXU."""
    import jax.numpy as jnp
    import numpy as np

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import ChooseOp, State
    from tenzing_tpu.models.ring_attention import (
        BlockedAttention,
        RingAttnArgs,
        make_blocked_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    args = RingAttnArgs(n_devices=4, batch=2, seq_local=256, head_dim=128)
    bufs, want = make_blocked_buffers(args, seed=4)
    # note: on this backend f32 and bf16 kernels produce identical outputs —
    # xla_allow_excess_precision truncates f32 matmul operands to bf16 on the
    # MXU anyway, so the bf16 menu entry costs no additional precision here
    # and its speedup is HBM bandwidth (half-width K/V block loads)
    for suffix, tol, key in ((".pallas", 2e-3, "attn_pallas_f32"),
                             (".pallas_bf16", 4e-2, "attn_pallas_bf16")):
        g = Graph()
        g.start_then(BlockedAttention(args, impl_choice=True))
        g.then_finish(BlockedAttention(args, impl_choice=True))
        plat = Platform.make_n_lanes(1)
        st = State(g)
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            pick = next((d for d in ds if isinstance(d, ChooseOp)
                         and d.choice.name().endswith(suffix)), ds[0])
            st = st.apply(pick)
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        out = ex.run(st.sequence)
        err = float(np.max(np.abs(np.asarray(out["O"]) - want)))
        results[key] = {"max_abs_err": err, "ok": err < tol}


def check_moe_pipeline(results):
    """MoE dispatch/combine through real host-staged DMAs + the Pallas
    hidden-tiled expert kernel.

    Two-tier check: the Pallas schedule must match the XLA schedule *on the
    device* tightly (kernel equivalence), and both match the float64 host
    reference at the platform's matmul precision — this backend runs with
    ``xla_allow_excess_precision``, under which f32 matmuls truncate their
    operands to bf16 on the MXU (measured: an f32 dot of bf16-rounded inputs
    is bit-identical to the f32 dot), so device-vs-host carries an inherent
    ~1e-2 deviation that is a platform property, not a kernel defect."""
    import jax.numpy as jnp
    import numpy as np

    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import ChooseOp, State
    from tenzing_tpu.models.moe_pipeline import (
        MoEPipeArgs,
        build_graph,
        host_buffer_names,
        make_pipe_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    args = MoEPipeArgs(n_experts=4, tokens=1024, d_model=256, d_ff=1024,
                       n_chunks=2)
    bufs, want, cap = make_pipe_buffers(args, seed=5)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names(args))
    g = build_graph(args, cap, impl_choice=True)
    plat = Platform.make_n_lanes(2)
    outs = {}
    for suffix in (".pallas", ".xla"):
        st = State(g)
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            pick = next((d for d in ds if isinstance(d, ChooseOp)
                         and d.choice.name().endswith(suffix)), ds[0])
            st = st.apply(pick)
        ex = TraceExecutor(plat, jbufs)
        outs[suffix] = np.asarray(ex.run(st.sequence)["Y"])
    kernel_err = float(np.max(np.abs(outs[".pallas"] - outs[".xla"])))
    host_err = float(np.max(np.abs(outs[".pallas"] - want)))
    results["moe_pipeline_pallas"] = {
        "pallas_vs_xla_max_abs": kernel_err,
        "vs_host_f64_max_abs": host_err,
        "ok": kernel_err < 1e-5 and host_err < 5e-2,
    }


def check_halo_pipeline(results):
    """Halo pipeline: pack -> host round trip -> unpack with the Pallas
    pack/unpack kernels (small grid; the bench covers the 512^3 scale)."""
    import jax.numpy as jnp
    import numpy as np

    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import ChooseOp, State
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        build_graph,
        host_buffer_names,
        make_pipeline_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    args = HaloArgs(nq=2, lx=16, ly=16, lz=128, radius=2)
    bufs, want = make_pipeline_buffers(args, seed=6)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    g = build_graph(args, impl_choice=True)
    plat = Platform.make_n_lanes(2)
    st = State(g)
    while not st.is_terminal():
        ds = st.get_decisions(plat)
        pick = next((d for d in ds if isinstance(d, ChooseOp)
                     and ".pallas" in d.choice.name()), ds[0])
        st = st.apply(pick)
    ex = TraceExecutor(plat, jbufs)
    out = ex.run(st.sequence)
    err = float(np.max(np.abs(np.asarray(out["U"]) - want)))
    results["halo_pipeline_pallas"] = {"max_abs_err": err, "ok": err == 0.0}


CHECKS = (check_spmv, check_attention, check_moe_pipeline, check_halo_pipeline)


def run_all() -> dict:
    import jax

    devs = jax.devices()
    results: dict = {
        "backend": str(devs),
        "is_tpu": jax.default_backend() == "tpu",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    for check in CHECKS:
        t0 = time.time()
        check(results)
        sys.stderr.write(f"{check.__name__} done ({time.time()-t0:.0f}s)\n")
    results["all_ok"] = all(
        v.get("ok") for v in results.values() if isinstance(v, dict)
    )
    return results


def main() -> int:
    results = run_all()
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "TPU_NUMERICS.json")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results, indent=2))
    return 0 if results["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
