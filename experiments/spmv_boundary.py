#!/usr/bin/env python
"""Measure the SpMV transfer-vs-compute boundary (VERDICT r2 item 7).

The distributed-SpMV paired verdict is null (~1.00) at the reference config
and stays null as the band widens (experiments/spmv_crossover_bw*.csv).
This script explains WHY with measurements instead of a bare null: per
density (nnz per row) it runs the SAME iteration with a local (no-host)
exchange and with the host-staged exchange as one paired decorrelated batch
— the paired host/local ratio isolates the exchange's share of the
iteration, which is the only thing schedule search could hide (Amdahl).

Measured (v5e): the iteration is COMPUTE-bound at every density — the
irregular x-gather + SpMV costs ~43 ms at the reference config while the
host exchange's paired share is 1.0041 [0.984, 1.0123] (indistinguishable
from zero, shrinking with density: 1.0007 at 16x the nnz), so the maximum
paired speedup any schedule could achieve is ~1.004-1.012, exactly
bracketing the measured 1.000-1.005 search verdicts.  The artifact
(experiments/SPMV_BOUNDARY.json) turns round 2's bare null into a
characterized boundary: the null is structural on one chip, not a missed
search.
"""

import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def measure_pair(orders, ex, n_iters=16, target=0.1):
    """Paired decorrelated batch (the repo's own drift-canceling tool): the
    exchange's incremental cost is ~0.3 ms on a ~41 ms iteration, far below
    the run-to-run drift between separate benchmark calls, so only a paired
    ratio measures it honestly."""
    from tenzing_tpu.bench.benchmarker import (
        BenchOpts,
        BenchResult,
        EmpiricalBenchmarker,
    )
    from tenzing_tpu.utils.numeric import paired_speedup

    emp = EmpiricalBenchmarker(ex)
    times = emp.benchmark_batch_times(
        orders, BenchOpts(n_iters=n_iters, target_secs=target), seed=4)
    results = [BenchResult.from_times(ts) for ts in times]
    # host/local paired ratio: > 1 by exactly the exchange's share
    m, lo, hi = paired_speedup(times[1], times[0], seed=5)
    return results, (m, lo, hi)


def first_schedule(g, plat):
    from tenzing_tpu.solve.dfs import get_all_sequences

    return get_all_sequences(g, plat, max_seqs=1)[0].sequence


def build(m, nnz_per_row, exchange):
    import jax.numpy as jnp

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.spmv import (
        SpMVCompound,
        make_spmv_buffers,
        spmv_host_buffer_names,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    bufs, _ = make_spmv_buffers(m=m, nnz_per_row=nnz_per_row, bw=m, seed=0)
    jbufs = TraceExecutor.place_host_buffers(bufs, spmv_host_buffer_names())
    g = Graph()
    g.start_then(SpMVCompound(exchange=exchange))
    g.then_finish(SpMVCompound(exchange=exchange))
    plat = Platform.make_n_lanes(1)
    return g, plat, TraceExecutor(plat, jbufs)


def main() -> int:
    import argparse

    argparse.ArgumentParser(description=__doc__).parse_args()
    import jax

    sys.stderr.write(f"backend: {jax.devices()}\n")
    out = {"device": str(jax.devices()[0]), "m": 150_000, "points": []}
    m = 150_000
    # per density: the iteration with a LOCAL (no-host) exchange vs the SAME
    # iteration with the host-staged exchange, measured as one paired batch —
    # the host/local ratio isolates the exchange's share (what search could
    # hide) from the dominant gather/SpMV compute
    for nnz_per_row in (10, 40, 160):
        gl, plat, _ = build(m, nnz_per_row, exchange="local")
        gh, _, ex = build(m, nnz_per_row, exchange="host")
        orders = [first_schedule(gl, plat), first_schedule(gh, plat)]
        results, (ratio, lo, hi) = measure_pair(orders, ex)
        pt = {
            "nnz_per_row": nnz_per_row,
            "local_pct50_ms": results[0].pct50 * 1e3,
            "host_pct50_ms": results[1].pct50 * 1e3,
            "host_over_local_paired": round(ratio, 4),
            "ci": [round(lo, 4), round(hi, 4)],
        }
        out["points"].append(pt)
        sys.stderr.write(json.dumps(pt) + "\n")
    p10 = out["points"][0]
    out["exchange_fraction_of_iteration"] = round(
        1.0 - 1.0 / max(p10["host_over_local_paired"], 1.0), 4)
    out["max_possible_paired_speedup"] = p10["host_over_local_paired"]
    out["conclusion"] = (
        "compute (the irregular x-gather + SpMV) dominates at every "
        "density — the host exchange's paired share of the iteration bounds "
        "any schedule's paired speedup (Amdahl) at "
        f"{out['max_possible_paired_speedup']}, bracketing the measured "
        "1.000-1.005 search verdicts: the schedule-invariance is structural "
        "on one chip, not a missed search"
    )
    (Path(__file__).parent / "SPMV_BOUNDARY.json").write_text(
        json.dumps(out, indent=1))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
