"""Hardware validation of the split (post/wait) remote-DMA kernels.

Runs on the real TPU (no JAX_PLATFORMS=cpu): exercises the semaphore-passing
split kernels of ops/rdma.py that the Pallas interpreter cannot represent —
(a) the loopback copy split (``rdma_start_loopback``/``rdma_wait_loopback``),
(b) the mesh-shift split on a size-1 axis (``rdma_shift_post``/
``rdma_shift_wait`` — degenerates to the loopback descriptor, which is the
only shift the one-chip environment can execute for real), and (c) the
``RdmaShiftStart`` op end-to-end through the TraceExecutor with a separate
``AwaitTransfer`` settling the in-flight semaphores (VERDICT r3 item 2's
loopback-on-hardware leg; the multi-chip structure leg is the 8-CPU dryrun).

Writes experiments/RDMA_SPLIT_TPU.json.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tenzing_tpu.ops.rdma import (
        rdma_shift_post,
        rdma_shift_wait,
        rdma_start_loopback,
        rdma_wait_loopback,
    )

    out = {"device": str(jax.devices()[0]), "checks": {}}
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((4096, 1024), dtype=np.float32))  # 16 MB

    # (a) loopback copy split
    @jax.jit
    def loop_split(x):
        send, recv, y = rdma_start_loopback(x)
        return rdma_wait_loopback(x, send, recv, y)

    y = jax.device_get(loop_split(x))
    assert np.array_equal(y, np.asarray(x)), "loopback split mismatch"
    out["checks"]["loopback_copy_split"] = "allclose"

    # (b) mesh-shift split, size-1 axis (loopback descriptor)
    @jax.jit
    def shift_split(x):
        send, recv, y = rdma_shift_post(x, (), None, 1)
        return rdma_shift_wait(x, send, recv, y, (), None, 1)

    y = jax.device_get(shift_split(x))
    assert np.array_equal(y, np.asarray(x)), "shift split mismatch"
    out["checks"]["shift_split_axis1"] = "allclose"

    # (c) RdmaShiftStart + AwaitTransfer through the executor: the post op
    # stashes the wait closure in ctx.inflight, the await runs the wait kernel
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.operation import DeviceOp
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.ops.comm_ops import AwaitTransfer
    from tenzing_tpu.ops.rdma import RdmaShiftStart
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.dfs import get_all_sequences

    class Scale(DeviceOp):
        def __init__(self):
            super().__init__("scale")

        def reads(self):
            return ["y"]

        def writes(self):
            return ["z"]

        def apply(self, bufs, ctx):
            return {"z": 2.0 * bufs["y"]}

    g = Graph()
    post = RdmaShiftStart("shift", "x", "y", axis="sp", shift=1)
    await_ = AwaitTransfer("await_y", "y")
    scale = Scale()
    g.start_then(post)
    g.then(post, await_)
    g.then(await_, scale)
    g.then_finish(scale)
    plat = Platform.make_n_lanes(2)
    bufs = {"x": x, "y": jnp.zeros_like(x), "z": jnp.zeros_like(x)}
    ex = TraceExecutor(plat, bufs)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    res = ex.run(st.sequence)
    assert np.array_equal(jax.device_get(res["z"]), 2.0 * np.asarray(x))
    ops = [op.desc() for op in st.sequence.vector()]
    assert any("shift" in o for o in ops) and any("await_y" in o for o in ops)
    out["checks"]["executor_shift_post_await"] = {
        "schedule": ops, "result": "allclose",
    }

    path = Path(__file__).parent / "RDMA_SPLIT_TPU.json"
    path.write_text(json.dumps(out, indent=1))
    print(json.dumps(out["checks"], indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
