#!/usr/bin/env python
"""Lane-overlap exit test (SURVEY.md §7.1 step 4, VERDICT r1 item 2).

Proves on real TPU hardware that the framework's schedule space is physically
meaningful: three legal schedules of the SAME op DAG — the reference's
pack -> post -> await -> unpack pipeline plus independent interior compute
(ops_halo_exchange.cu's overlap structure) — time measurably differently:

* ``serial``   : 1 lane, await before compute  -> pack + T + unpack + M
* ``overlap1`` : 1 lane, compute between post and await -> pack + max(T,M) + unpack
* ``overlap2`` : 2 lanes, compute on its own lane       -> max(pack+T+unpack, M)

where T = async host round-trip DMA of a 64 MB buffer (the single-chip async
transfer; PCIe on hardware) and M = a chain of 4096^3 bf16 matmuls (MXU).

Everything runs through the real stack: Graph -> hand-picked legal orders ->
TraceExecutor (data-dependency tokens) -> EmpiricalBenchmarker (repeat-inside-
program, device-fetch fenced).  Writes experiments/LANE_OVERLAP_TPU.json and
prints one JSON line per schedule.

Run: JAX_PLATFORMS='' python experiments/lane_overlap.py  (TPU)
     python experiments/lane_overlap.py --smoke           (CPU, correctness only)
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny CPU config")
    ap.add_argument("--iters", type=int, default=15)
    args = ap.parse_args()
    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.operation import DeviceOp, Finish, Start
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.sequence import Sequence
    from tenzing_tpu.ops.comm_ops import AwaitTransfer, HostFetchStart, HostSpillStart
    from tenzing_tpu.runtime.executor import TraceExecutor

    N = 256 if args.smoke else 4096
    K = 2 if args.smoke else 16
    CM = 1024 if args.smoke else 16 * 1024 * 1024  # copy elements (x4 bytes)

    class MatChain(DeviceOp):
        def reads(self):
            return ["a"]

        def writes(self):
            return ["a"]

        def apply(self, bufs, ctx):
            a = bufs["a"]
            for _ in range(K):
                a = jnp.tanh(a @ a)
            return {"a": a}

    class PackOp(DeviceOp):
        def reads(self):
            return ["c"]

        def writes(self):
            return ["cs"]

        def apply(self, bufs, ctx):
            return {"cs": bufs["c"] * 1.0001}

    class UnpackOp(DeviceOp):
        def reads(self):
            return ["cr"]

        def writes(self):
            return ["c"]

        def apply(self, bufs, ctx):
            return {"c": bufs["cr"] * 0.9999}

    pack = PackOp("pack")
    spill = HostSpillStart("spill", "cs", "hc")
    fetch = HostFetchStart("fetch", "hc", "cr")
    await_ = AwaitTransfer("await_cr", "cr")
    unpack = UnpackOp("unpack")
    mm = MatChain("interior")

    g = Graph()
    g.start_then(pack)
    g.then(pack, spill)
    g.then(spill, fetch)
    g.then(fetch, await_)
    g.then(await_, unpack)
    g.then_finish(unpack)
    g.start_then(mm)
    g.then_finish(mm)

    plat = Platform.make_n_lanes(2)
    l0, l1 = plat.lanes[0], plat.lanes[1]

    # hc lives in host memory from the start: the loop carry keeps each
    # buffer's memory space stable across iterations
    host_sh = jax.sharding.SingleDeviceSharding(jax.devices()[0], memory_kind="pinned_host")
    bufs = {
        "a": jnp.ones((N, N), jnp.bfloat16),
        "c": jnp.ones((CM // 1024, 1024), jnp.float32),
        "cs": jnp.zeros((CM // 1024, 1024), jnp.float32),
        "hc": jax.device_put(jnp.zeros((CM // 1024, 1024), jnp.float32), host_sh),
        "cr": jnp.zeros((CM // 1024, 1024), jnp.float32),
    }
    ex = TraceExecutor(plat, bufs)
    bench = EmpiricalBenchmarker(ex)

    schedules = {
        # 1 lane, compute after await: fully serialized pipeline
        "serial": Sequence(
            [Start(), pack.bind(l0), spill, fetch, await_, unpack.bind(l0), mm.bind(l0), Finish()]
        ),
        # 1 lane, compute posted between post and await: DMA hides compute
        "overlap1": Sequence(
            [Start(), pack.bind(l0), spill, fetch, mm.bind(l0), await_, unpack.bind(l0), Finish()]
        ),
        # 2 lanes: compute on its own lane
        "overlap2": Sequence(
            [Start(), pack.bind(l0), spill, fetch, mm.bind(l1), await_, unpack.bind(l0), Finish()]
        ),
    }

    opts = BenchOpts(
        n_iters=max(5, args.iters), target_secs=0.005 if args.smoke else 0.25
    )
    out = {"device": str(jax.devices()[0]), "backend": jax.default_backend()}
    for name, order in schedules.items():
        res = bench.benchmark(order, opts)
        out[name] = {"pct50_ms": res.pct50 * 1e3, "pct10_ms": res.pct10 * 1e3}
        print(json.dumps({"schedule": name, "pct50_ms": round(res.pct50 * 1e3, 3)}))

    if not args.smoke:
        s, o1, o2 = (out[k]["pct50_ms"] for k in ("serial", "overlap1", "overlap2"))
        out["serial_over_overlap1"] = round(s / o1, 3)
        out["serial_over_overlap2"] = round(s / o2, 3)
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "LANE_OVERLAP_TPU.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps({"artifact": path, "serial_over_overlap1": out["serial_over_overlap1"],
                          "serial_over_overlap2": out["serial_over_overlap2"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
