#!/usr/bin/env python
"""SpMV transfer-bound crossover sweep (VERDICT r2 item 7).

Round 2's distributed-SpMV verdict was a NULL result at exactly one point in
config space (paired 0.999 at m=150k, band bw=m/8: the host-staged x exchange
is too small relative to the local compute for any schedule to hide).  This
sweep scales the exchange by widening the band — remote columns grow with the
half-width — and runs the full anytime driver (bench.py: MCTS search, paired
screen, paired final verdict) at each point, recording where schedule search
starts to pay: the measured crossover boundary, replacing the bare null.

Writes experiments/SPMV_CROSSOVER.json and one recorded search DB per config
(spmv_crossover_bw*.csv).  Run on the real chip; ~10 min per point.
"""

import json
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

HERE = Path(__file__).parent
REPO = HERE.parent

M = 150_000
FRACTIONS = (0.125, 0.5, 1.0)  # band half-width as a fraction of m


def run_point(frac: float, mcts_iters: int) -> dict:
    bw = int(M * frac)
    csv = HERE / f"spmv_crossover_bw{bw}.csv"
    cmd = [
        sys.executable, str(REPO / "bench.py"), "--workload", "spmv",
        "--m", str(M), "--spmv-bw", str(bw),
        "--mcts-iters", str(mcts_iters), "--dump-csv", str(csv),
    ]
    sys.stderr.write("+ " + " ".join(cmd) + "\n")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=str(REPO))
    sys.stderr.write(out.stderr[-2000:] + "\n")
    lines = out.stdout.strip().splitlines()
    if out.returncode != 0 or not lines:
        # record the failure point; never lose the points already measured
        return {"bw": bw, "bw_frac": frac, "csv": csv.name,
                "rc": out.returncode, "error": out.stderr[-500:]}
    rec = json.loads(lines[-1])
    rec.update(bw=bw, bw_frac=frac, csv=csv.name, rc=out.returncode)
    return rec


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--mcts-iters", type=int, default=32)
    ap.add_argument("--fractions", type=float, nargs="*", default=FRACTIONS)
    args = ap.parse_args()
    points = [run_point(f, args.mcts_iters) for f in args.fractions]
    out = {"m": M, "points": points}
    (HERE / "SPMV_CROSSOVER.json").write_text(json.dumps(out, indent=1))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
