#!/usr/bin/env python
"""Driver benchmark: searched schedule vs naive sequential ordering on the
distributed-SpMV iteration (reference config: m=150000 rows, nnz=10*m, band
matrix, 2 lanes — spmv_run_strategy.cuh:44-47; protocol BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": <best searched pct50, us>, "unit": "us",
   "vs_baseline": <naive_pct50 / best_pct50>}

vs_baseline > 1 means the searched schedule beats the naive sequential order.

``--smoke`` runs a tiny CPU-friendly configuration (used by tests/CI).
"""

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny CPU config")
    ap.add_argument("--m", type=int, default=None, help="matrix rows")
    ap.add_argument("--candidates", type=int, default=8, help="max unique schedules to time")
    ap.add_argument("--iters", type=int, default=20, help="measurements per schedule")
    args = ap.parse_args()

    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.operation import BoundDeviceOp
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.resources import Lane
    from tenzing_tpu.core.sequence import Sequence
    from tenzing_tpu.core import sequence as sequence_mod
    from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.dfs import get_all_sequences
    from tenzing_tpu.core.state import State

    m = args.m if args.m is not None else (512 if args.smoke else 150_000)
    bufs, _ = make_spmv_buffers(m=m, nnz_per_row=10, seed=0)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}

    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, bufs)
    bench = EmpiricalBenchmarker(ex)
    opts = BenchOpts(n_iters=max(5, args.iters), target_secs=0.002 if args.smoke else 0.01)

    # naive baseline: expand the compound, bind every device op to lane 0,
    # execute in topological (frontier) order — the reference's "sequential
    # ordering on one stream" baseline (BASELINE.json north star)
    naive_plat = Platform.make_n_lanes(1)
    naive_state = State(g)
    while not naive_state.is_terminal():
        naive_state = naive_state.apply(naive_state.get_decisions(naive_plat)[0])
    naive_order = naive_state.sequence
    t0 = time.time()
    naive = bench.benchmark(naive_order, opts)
    sys.stderr.write(f"naive: pct50={naive.pct50*1e6:.1f}us (wall {time.time()-t0:.0f}s)\n")

    # search: enumerate 2-lane schedules, dedup by bijection equivalence, time a
    # capped candidate set
    states = get_all_sequences(g, plat, max_seqs=200)
    uniq = []
    for st in states:
        if not any(sequence_mod.get_equivalence(st.sequence, u.sequence) for u in uniq):
            uniq.append(st)
        if len(uniq) >= 8 * args.candidates:
            break
    if len(uniq) > args.candidates:  # spread candidates across the space
        stride = len(uniq) / args.candidates
        uniq = [uniq[int(i * stride)] for i in range(args.candidates)]
    best = None
    best_res = None
    for i, st in enumerate(uniq):
        t0 = time.time()
        res = bench.benchmark(st.sequence, opts)
        sys.stderr.write(
            f"sched {i}/{len(uniq)}: pct50={res.pct50*1e6:.1f}us "
            f"(wall {time.time()-t0:.0f}s)\n"
        )
        if best_res is None or res.pct50 < best_res.pct50:
            best, best_res = st, res

    value_us = best_res.pct50 * 1e6
    vs = naive.pct50 / best_res.pct50
    print(
        json.dumps(
            {
                "metric": "spmv_iter_pct50_searched_m%d" % m,
                "value": round(value_us, 2),
                "unit": "us",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
