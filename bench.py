#!/usr/bin/env python
"""Driver benchmark: searched schedule vs naive sequential ordering.

Workloads (``--workload``):
* ``spmv`` (default, the headline metric): distributed-SpMV iteration
  (reference config: m=150000 rows, nnz=10*m, band matrix, 2 lanes —
  spmv_run_strategy.cuh:44-47; protocol BASELINE.md).
* ``attn``: single-chip blockwise (flash) attention over a long context —
  the kernel menu (XLA vs Pallas MXU) plus order x lane space.

The search is anytime and starts from the naive incumbent: MCTS (FastMin
strategy) spends a fixed compile budget exploring the order x lane x kernel
space; the reported best is min over {naive} + searched candidates, so
vs_baseline >= 1 and exceeds 1 exactly when the search discovers a schedule
faster than the naive sequential order (all ops on one lane, first kernel
choice).

Prints ONE JSON line:
  {"metric": ..., "value": <best pct50, us>, "unit": "us",
   "vs_baseline": <naive_pct50 / best_pct50>}

``--smoke`` runs a tiny CPU-friendly configuration (used by tests/CI).
"""

import argparse
import json
import sys
import time


def build_spmv(args):
    import jax.numpy as jnp

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers

    m = args.m if args.m is not None else (512 if args.smoke else 150_000)
    bufs, _ = make_spmv_buffers(m=m, nnz_per_row=10, seed=0)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    # impl_choice: the kernel menu (XLA gather vs Pallas vreg-gather) is part
    # of the searched space alongside order and lane assignment
    g = Graph()
    g.start_then(SpMVCompound(impl_choice=True))
    g.then_finish(SpMVCompound(impl_choice=True))
    return g, bufs, f"spmv_iter_pct50_searched_m{m}"


def build_attn(args):
    import jax.numpy as jnp

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.models.ring_attention import (
        BlockedAttention,
        RingAttnArgs,
        make_blocked_buffers,
    )

    if args.smoke:
        aargs = RingAttnArgs(n_devices=4, batch=1, seq_local=16, head_dim=8)
    else:
        # 8k context in 8 blocks of 1024, head dim 128
        aargs = RingAttnArgs(n_devices=8, batch=4, seq_local=1024, head_dim=128)
    bufs, _ = make_blocked_buffers(aargs, seed=0)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    g = Graph()
    g.start_then(BlockedAttention(aargs, impl_choice=True))
    g.then_finish(BlockedAttention(aargs, impl_choice=True))
    n_ctx = aargs.n_devices * aargs.seq_local
    return g, bufs, f"attn_blockwise_pct50_searched_n{n_ctx}"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny CPU config")
    ap.add_argument("--workload", choices=("spmv", "attn"), default="spmv")
    ap.add_argument("--m", type=int, default=None, help="matrix rows (spmv)")
    ap.add_argument("--mcts-iters", type=int, default=10, help="MCTS iterations (compile budget)")
    ap.add_argument("--iters", type=int, default=20, help="measurements per schedule")
    args = ap.parse_args()

    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.mcts import MctsOpts, explore
    from tenzing_tpu.solve.mcts.strategies import FastMin

    g, bufs, metric = (build_spmv if args.workload == "spmv" else build_attn)(args)
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, bufs)
    bench = EmpiricalBenchmarker(ex)
    opts = BenchOpts(n_iters=max(5, args.iters), target_secs=0.002 if args.smoke else 0.01)

    # naive incumbent: every device op on lane 0, topological order, first
    # kernel choice — the reference's "sequential ordering on one stream"
    # baseline (BASELINE.json)
    naive_plat = Platform.make_n_lanes(1)
    naive_state = State(g)
    while not naive_state.is_terminal():
        naive_state = naive_state.apply(naive_state.get_decisions(naive_plat)[0])
    t0 = time.time()
    naive = bench.benchmark(naive_state.sequence, opts)
    sys.stderr.write(f"naive: pct50={naive.pct50*1e6:.1f}us (wall {time.time()-t0:.0f}s)\n")

    # directed search over the 2-lane order x lane x kernel space
    t0 = time.time()
    res = explore(
        g,
        plat,
        bench,
        MctsOpts(n_iters=args.mcts_iters, bench_opts=opts, seed=0),
        strategy=FastMin,
    )
    for i, s in enumerate(res.sims):
        sys.stderr.write(f"mcts {i}: pct50={s.result.pct50*1e6:.1f}us\n")
    sys.stderr.write(f"mcts wall {time.time()-t0:.0f}s, tree={res.tree_size}\n")

    best = min(
        [(naive.pct50, naive)] + [(s.result.pct50, s.result) for s in res.sims],
        key=lambda t: t[0],
    )[1]
    value_us = best.pct50 * 1e6
    vs = naive.pct50 / best.pct50
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value_us, 2),
                "unit": "us",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
