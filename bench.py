#!/usr/bin/env python
"""Driver benchmark: searched schedule vs naive sequential ordering.

Workloads (``--workload``):
* ``halo`` (default, the north-star metric — BASELINE.md): the 3D
  halo-exchange pipeline (nQ=3, 512^3 cells, radius 3, the reference config
  halo_run_strategy.hpp:42-49) as six pack -> post -> await -> unpack chains
  whose transfers are async host round-trip DMAs; MCTS searches order x lane x
  kernel (XLA slice vs Pallas plane-DMA) against the fully-synchronous naive
  serialization.
* ``spmv``: distributed-SpMV iteration (reference config: m=150000 rows,
  nnz=10*m, band matrix, 2 lanes — spmv_run_strategy.cuh:44-47).
* ``attn``: single-chip blockwise (flash) attention over a long context —
  the kernel menu (XLA vs Pallas MXU) plus order x lane space.
* ``moe``: single-chip MoE dispatch/combine pipeline — routed tokens staged
  through async host round-trip DMAs to the resident experts (the
  expert-parallel network-hop analog), searched over order x lane x
  expert-kernel (XLA vs Pallas) across independent microbatch chunk chains.

The search is anytime and starts from the naive incumbent: MCTS (FastMin
strategy) spends a fixed compile budget exploring the schedule space.  The
verdict comes from a decorrelated *final batch* (reference batch benchmark,
benchmarker.cpp:21-76): naive and the top distinct candidates are re-measured
together, visited in a fresh random order per iteration, and ``vs_baseline``
is the best candidate's **paired per-iteration speedup** over naive (median of
naive[k]/cand[k] with a bootstrap CI, utils.numeric.paired_speedup) — drift
common to both schedules cancels instead of masquerading as, or drowning, a
schedule difference.  vs_baseline >= 1, exceeding 1 exactly when the search
discovers a schedule faster than naive under the paired measurement.

Prints ONE JSON line:
  {"metric": ..., "value": <best pct50, us>, "unit": "us",
   "vs_baseline": <naive_pct50 / best_pct50>}

On backend-init failure (e.g. the TPU tunnel is down — the way round 1's
BENCH died, VERDICT r1 item 1) the device is probed first with one retry, and
failure still prints a parseable JSON line with an ``error`` field.

``--smoke`` runs a tiny CPU-friendly configuration (used by tests/CI).
"""

import argparse
import json
import sys
import time


def probe_backend(retries: int = 1, wait_secs: float = 15.0):
    """Initialize the JAX backend, retrying once on transient tunnel failure.
    Returns the device list; raises after the final retry."""
    import jax

    for attempt in range(retries + 1):
        try:
            return jax.devices()
        except RuntimeError as e:
            sys.stderr.write(f"backend init failed (attempt {attempt + 1}): {e}\n")
            if attempt == retries:
                raise
            time.sleep(wait_secs)
            # a failed init is cached; clear and retry once
            import jax.extend as jex

            jex.backend.clear_backends()
    raise AssertionError("unreachable")  # pragma: no cover


def metric_for(workload: str, args) -> str:
    """The metric name for a workload config — the single source both the
    success path (build_* return) and the backend-init-failure path use, so
    the two always land in the same metric series."""
    if workload == "halo":
        return f"halo_iter_pct50_searched_n{4 if args.smoke else args.halo_n}"
    if workload == "spmv":
        m = args.m if args.m is not None else (512 if args.smoke else 150_000)
        return f"spmv_iter_pct50_searched_m{m}"
    if workload == "moe":
        t = 32 if args.smoke else args.moe_tokens
        return f"moe_pipe_pct50_searched_t{t}"
    n_ctx = 4 * 16 if args.smoke else 8 * 1024
    return f"attn_blockwise_pct50_searched_n{n_ctx}"


def build_halo(args):
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        build_graph,
        host_buffer_names,
        make_pipeline_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    if args.smoke:
        hargs = HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1)
    else:
        n = args.halo_n
        hargs = HaloArgs(nq=3, lx=n, ly=n, lz=n, radius=3)
    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    # kernel menu only where a real TPU compiles it; interpret-mode Pallas
    # would dominate a CPU smoke timing
    impl_choice = not args.smoke
    g = build_graph(hargs, impl_choice=impl_choice)
    return g, jbufs, metric_for("halo", args), hargs


def build_spmv(args):
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.models.spmv import (
        SpMVCompound,
        make_spmv_buffers,
        spmv_host_buffer_names,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    m = args.m if args.m is not None else (512 if args.smoke else 150_000)
    bufs, _ = make_spmv_buffers(m=m, nnz_per_row=10, seed=0)
    jbufs = TraceExecutor.place_host_buffers(bufs, spmv_host_buffer_names())
    # impl_choice: the kernel menu (XLA gather vs Pallas vreg-gather) is part
    # of the searched space alongside order and lane assignment; known x sizes
    # prune Pallas choices that would only alias the XLA path (ADVICE r1).
    # exchange="host": the x exchange is a posted async host round-trip DMA
    # (the reference's MPI hop), so the post/wait split gives the search a
    # real transfer to hide behind the local SpMV
    x_sizes = {"x_local": int(jbufs["x_local"].shape[0]),
               "x_remote": int(jbufs["x_remote"].shape[0])}
    mk = lambda: SpMVCompound(impl_choice=True, x_sizes=x_sizes, exchange="host")
    g = Graph()
    g.start_then(mk())
    g.then_finish(mk())
    return g, jbufs, metric_for("spmv", args)


def build_moe(args):
    from tenzing_tpu.models.moe_pipeline import (
        MoEPipeArgs,
        build_graph,
        host_buffer_names,
        make_pipe_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    if args.smoke:
        margs = MoEPipeArgs(n_experts=4, tokens=32, d_model=8, d_ff=16,
                            n_chunks=2)
    else:
        margs = MoEPipeArgs(tokens=args.moe_tokens)
    # the searched space includes the staging-precision menu (f32 vs
    # half-width bf16 transfers) on the real chip
    staging = "f32" if args.smoke else "choice"
    bufs, _, cap = make_pipe_buffers(margs, seed=0, with_expected=False,
                                     staging=staging)
    jbufs = TraceExecutor.place_host_buffers(
        bufs, host_buffer_names(margs, staging=staging))
    impl_choice = not args.smoke  # same rationale as build_halo
    g = build_graph(margs, cap, impl_choice=impl_choice, staging=staging)
    return g, jbufs, metric_for("moe", args), (margs, cap)


def build_attn(args):
    import jax.numpy as jnp

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.models.ring_attention import (
        BlockedAttention,
        RingAttnArgs,
        make_blocked_buffers,
    )

    if args.smoke:
        aargs = RingAttnArgs(n_devices=4, batch=1, seq_local=16, head_dim=8)
    else:
        # 8k context in 8 blocks of 1024, head dim 128
        aargs = RingAttnArgs(n_devices=8, batch=4, seq_local=1024, head_dim=128)
    bufs, _ = make_blocked_buffers(aargs, seed=0)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    g = Graph()
    g.start_then(BlockedAttention(aargs, impl_choice=True))
    g.then_finish(BlockedAttention(aargs, impl_choice=True))
    return g, bufs, metric_for("attn", args)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny CPU config")
    ap.add_argument("--workload", choices=("halo", "spmv", "attn", "moe"),
                    default="halo")
    ap.add_argument("--moe-tokens", type=int, default=8192,
                    help="total tokens (moe)")
    ap.add_argument("--m", type=int, default=None, help="matrix rows (spmv)")
    ap.add_argument("--halo-n", type=int, default=512, help="cells per side (halo)")
    ap.add_argument("--mcts-iters", type=int, default=24, help="MCTS iterations (compile budget)")
    ap.add_argument("--iters", type=int, default=20, help="measurements per schedule")
    ap.add_argument("--dump-csv", default=None, help="write searched results as CSV rows")
    args = ap.parse_args()

    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")

    metric_name = metric_for(args.workload, args)
    try:
        devs = probe_backend()
        sys.stderr.write(f"backend: {devs}\n")
    except Exception as e:  # still emit a parseable line (VERDICT r1 item 1)
        print(
            json.dumps(
                {
                    "metric": metric_name,
                    "value": -1.0,
                    "unit": "us",
                    "vs_baseline": 0.0,
                    "error": f"backend init failed: {e}",
                }
            )
        )
        return 0

    from tenzing_tpu.bench.benchmarker import (
        BenchOpts,
        CachingBenchmarker,
        EmpiricalBenchmarker,
        result_row,
    )
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.mcts import MctsOpts, explore
    from tenzing_tpu.solve.mcts.strategies import FastMin

    build = {"halo": build_halo, "spmv": build_spmv, "attn": build_attn,
             "moe": build_moe}[args.workload]
    built = build(args)
    g, bufs, metric = built[0], built[1], built[2]
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, bufs)
    emp = EmpiricalBenchmarker(ex)
    bench = CachingBenchmarker(emp)
    opts = BenchOpts(n_iters=max(5, args.iters), target_secs=0.002 if args.smoke else 0.02)

    # naive incumbent: the fully-synchronous serialization on one lane (the
    # reference's "sequential ordering on one stream" baseline, BASELINE.json)
    naive_plat = Platform.make_n_lanes(1)
    if args.workload == "halo":
        from tenzing_tpu.models.halo_pipeline import naive_order

        naive_seq = naive_order(built[3], naive_plat)
    elif args.workload == "moe":
        from tenzing_tpu.models.moe_pipeline import naive_order

        naive_seq = naive_order(built[3][0], built[3][1], naive_plat)
    else:
        naive_state = State(g)
        while not naive_state.is_terminal():
            naive_state = naive_state.apply(naive_state.get_decisions(naive_plat)[0])
        naive_seq = naive_state.sequence
    t0 = time.time()
    naive = bench.benchmark(naive_seq, opts)
    sys.stderr.write(f"naive: pct50={naive.pct50*1e6:.1f}us (wall {time.time()-t0:.0f}s)\n")

    # anytime search: heuristic incumbents first, then the directed search.
    # For halo the domain heuristic is the post-all-before-await-any overlap
    # discipline — the one the reference's graph hard-codes via its
    # every-post-before-any-wait edges (ops_halo_exchange.cu:249-256)
    incumbents = []
    if args.workload == "attn" and not args.smoke:
        # kernel incumbent: the serialized order with every block choosing the
        # bf16 Pallas kernel (double MXU throughput) — the likely winner the
        # directed search should start from, and the final batch must include
        from tenzing_tpu.core.state import ChooseOp
        from tenzing_tpu.solve.mcts.mcts import SimResult

        st = State(g)
        while not st.is_terminal():
            ds = st.get_decisions(naive_plat)
            pick = next(
                (d for d in ds if isinstance(d, ChooseOp)
                 and d.choice.name().endswith(".pallas_bf16")),
                ds[0],
            )
            st = st.apply(pick)
        t0 = time.time()
        bf16 = bench.benchmark(st.sequence, opts)
        sys.stderr.write(
            f"bf16-kernel incumbent: pct50={bf16.pct50*1e6:.1f}us "
            f"(wall {time.time()-t0:.0f}s)\n"
        )
        incumbents.append(SimResult(order=st.sequence, result=bf16))
    if args.workload in ("halo", "moe"):
        from tenzing_tpu.solve.mcts.mcts import SimResult

        if args.workload == "halo":
            from tenzing_tpu.models.halo_pipeline import greedy_overlap_order

            greedy_seqs = [("greedy-overlap", greedy_overlap_order(built[3], plat))]
        else:
            from tenzing_tpu.models.moe_pipeline import greedy_overlap_order

            margs_, cap_ = built[3]
            greedy_seqs = [
                ("greedy-overlap", greedy_overlap_order(margs_, cap_, plat))
            ]
            if not args.smoke:
                # the half-width-transfer incumbent (bf16 staging): the
                # likely winner the search should start from
                greedy_seqs.append((
                    "greedy-overlap-bf16",
                    greedy_overlap_order(margs_, cap_, plat, staging="bf16"),
                ))
        for label, greedy_seq in greedy_seqs:
            t0 = time.time()
            greedy = bench.benchmark(greedy_seq, opts)
            sys.stderr.write(
                f"{label} incumbent: pct50={greedy.pct50*1e6:.1f}us "
                f"(wall {time.time()-t0:.0f}s)\n"
            )
            incumbents.append(SimResult(order=greedy_seq, result=greedy))

    # directed search over the 2-lane order x lane x kernel space
    t0 = time.time()
    res = explore(
        g,
        plat,
        bench,
        MctsOpts(n_iters=args.mcts_iters, bench_opts=opts, seed=0),
        strategy=FastMin,
    )
    for i, s in enumerate(res.sims):
        sys.stderr.write(f"mcts {i}: pct50={s.result.pct50*1e6:.1f}us\n")
    sys.stderr.write(f"mcts wall {time.time()-t0:.0f}s, tree={res.tree_size}\n")
    res.sims = incumbents + res.sims

    # decorrelated final: re-measure naive and the top candidates *together*,
    # visiting them in a fresh random order per iteration so slow system drift
    # cannot masquerade as a schedule difference (reference batch benchmark,
    # benchmarker.cpp:21-76).  Search-time measurements are noisy relative to
    # the margins here, so the top 3 *distinct* schedules by pct50 advance to
    # the final (equivalent rollouts share one cached result — don't spend the
    # budget re-timing one program thrice).  All programs are already compiled
    # (executor cache) — pure measurement cost.
    from dataclasses import replace

    from tenzing_tpu.core.sequence import get_equivalence

    # heuristic incumbents always advance: search-time measurements drift
    # with system conditions, and a polluted early measurement must not
    # knock the domain-heuristic schedule out of the (clean, paired) final
    top = list(incumbents)
    for s in sorted(res.sims, key=lambda s: s.result.pct50):
        if s.result.pct50 >= naive.pct50 * 1.1 or len(top) == 3 + len(incumbents):
            break
        if not any(get_equivalence(s.order, t.order) for t in top):
            top.append(s)
    finals = []
    if top:
        from tenzing_tpu.bench.benchmarker import BenchResult
        from tenzing_tpu.utils.numeric import paired_speedup

        # the verdict batch buys CI width with pure measurement time (no
        # recompiles): 3x the iterations, and a 20x measurement floor so each
        # per-iteration time averages several program executions (the
        # reference's adaptive >=10ms floor, benchmarker.cpp:83-119) — single
        # -execution jitter otherwise dominates the paired ratios and the
        # bootstrap CI straddles 1.0 on runs where the margin is real
        fin_opts = replace(
            opts, n_iters=3 * opts.n_iters, target_secs=20 * opts.target_secs
        )
        fin_times = emp.benchmark_batch_times(
            [naive_seq] + [s.order for s in top], fin_opts, seed=1
        )
        finals = [BenchResult.from_times(ts) for ts in fin_times]
        fin_naive, fin_cands = finals[0], finals[1:]
        sys.stderr.write(
            "final batch: naive=%.1fus candidates=[%s]us\n"
            % (
                fin_naive.pct50 * 1e6,
                ", ".join("%.1f" % (r.pct50 * 1e6) for r in fin_cands),
            )
        )
        # the verdict is the *paired* per-iteration speedup: iteration k runs
        # every schedule back-to-back, so naive[k]/cand[k] cancels the drift
        # common to both — far tighter than comparing pct50s across the run
        paired = [paired_speedup(fin_times[0], ts, seed=2) for ts in fin_times[1:]]
        best_i = max(range(len(paired)), key=lambda i: paired[i][0])
        m, lo, hi = paired[best_i]
        sys.stderr.write(
            "paired speedup vs naive: best=%.4f [%.4f, %.4f] 95%% CI "
            "(all: %s)\n"
            % (m, lo, hi, ", ".join("%.4f" % p[0] for p in paired))
        )
        # a win requires the bootstrap CI to exclude 1.0, not just the bare
        # median — otherwise sampling noise reports a spurious speedup on
        # roughly half of no-difference runs
        if m > 1.0 and lo > 1.0:
            value_us = fin_cands[best_i].pct50 * 1e6
            vs = m
        else:
            value_us = fin_naive.pct50 * 1e6
            vs = 1.0
    else:
        value_us = naive.pct50 * 1e6
        vs = 1.0

    if args.dump_csv:
        # One row per distinct schedule.  The decorrelated final-batch results
        # *supersede* the search-time measurements for naive and the finalists
        # (CsvBenchmarker returns the first equivalence match, so appending
        # duplicate rows would leave the finals unreachable) — the headline
        # verdict is replayable from the recorded database.
        results = [naive] + [s.result for s in res.sims]
        if finals:
            results[0] = finals[0]
            for r, s in zip(finals[1:], top):
                # identity, not ==: sync ops compare kind-only, so two distinct
                # schedules can be ==-equal and .index() would mis-attribute
                idx = next(i for i, s2 in enumerate(res.sims) if s2 is s)
                results[1 + idx] = r
        orders = [naive_seq] + [s.order for s in res.sims]
        rows = [result_row(i, r, o) for i, (r, o) in enumerate(zip(results, orders))]
        with open(args.dump_csv, "w") as f:
            f.write("\n".join(rows) + "\n")
        sys.stderr.write(f"csv: {args.dump_csv} ({len(rows)} rows)\n")
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value_us, 2),
                "unit": "us",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
