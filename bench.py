#!/usr/bin/env python
"""Driver benchmark CLI: searched schedule vs naive sequential ordering.

A thin argparse shim over the library driver
(``tenzing_tpu/bench/driver.py`` — ISSUE 7): flags parse into a typed
:class:`~tenzing_tpu.bench.driver.DriverRequest`, the whole search→gate→
JSON loop runs in :func:`~tenzing_tpu.bench.driver.run`, and this file
prints the returned verdict as ONE JSON line:

  {"metric": ..., "value": <best pct50, us>, "unit": "us",
   "vs_baseline": <naive_pct50 / best_pct50>}

Workloads, search structure, verdict semantics, fault/perf/attrib meta
blocks: see the driver module docstring (it carries the monolith's full
documentation).  The schedule-serving subsystem (``python -m
tenzing_tpu.serve``, docs/serving.md) calls the same driver API — a cold
request's queued work item is exactly a serialized DriverRequest, so a
queue drainer and this CLI produce identical driver JSON.

On backend-init failure (e.g. the TPU tunnel is down — the way round 1's
BENCH died, VERDICT r1 item 1) the device is probed first with one retry,
and failure still prints a parseable JSON line with an ``error`` field.

``--smoke`` runs a tiny CPU-friendly configuration (used by tests/CI).
"""

import argparse
import json
import sys

# re-exports: the workload builders and menu recipes lived here for six
# rounds and are imported by example/experiment scripts by their old names
from tenzing_tpu.bench.driver import (  # noqa: F401
    ALIAS_UNPACK,
    BUILDERS,
    DriverConfigError,
    DriverRequest,
    DriverResult,
    alias_unpack_choice,
    build_attn,
    build_halo,
    build_moe,
    build_spmv,
    metric_for,
    probe_backend,
    workload_cost,
)
from tenzing_tpu.bench.driver import run as run_driver


def build_arg_parser() -> argparse.ArgumentParser:
    """The CLI surface.  Every ``dest`` and default must match a
    :class:`DriverRequest` field — tests/test_driver.py asserts the two
    agree, so a new flag cannot silently miss the library API."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny CPU config")
    ap.add_argument("--workload", choices=("halo", "spmv", "attn", "moe"),
                    default="halo")
    ap.add_argument("--moe-tokens", type=int, default=8192,
                    help="total tokens (moe)")
    ap.add_argument("--m", type=int, default=None, help="matrix rows (spmv)")
    ap.add_argument("--spmv-bw", type=int, default=None,
                    help="band half-width (spmv); larger -> bigger remote exchange")
    ap.add_argument("--halo-n", type=int, default=512, help="cells per side (halo)")
    ap.add_argument("--lanes", type=int, default=None,
                    help="search-platform lanes (default: 8 for halo, else 2)")
    # raised 40 -> 56 in r5: informed playouts (rollout_policy) made MCTS a
    # producing solver (the r5c winner was a rollout), and the multi-fidelity
    # screen floor keeps the marginal iteration cheap (~2-3 s)
    ap.add_argument("--mcts-iters", type=int, default=56, help="MCTS iterations (compile budget)")
    ap.add_argument("--iters", type=int, default=20, help="measurements per schedule (screen/final)")
    ap.add_argument("--search-iters", type=int, default=6,
                    help="measurements per schedule during MCTS (cheap phase)")
    ap.add_argument("--climb-budget", type=int, default=44,
                    help="hill-climb benchmark budget after MCTS")
    ap.add_argument("--prefetch-compiles", type=int, default=2, metavar="N",
                    help="background compile workers for the async compile "
                         "pipeline (docs/performance.md): the solvers hint "
                         "upcoming candidates and their XLA compiles overlap "
                         "device measurement; 0 disables (serialized "
                         "compiles, bit-identical search behavior)")
    ap.add_argument("--dump-csv", default=None, help="write searched results as CSV rows")
    ap.add_argument("--trace-out", default=None,
                    help="directory for the telemetry bundle: trace.jsonl "
                         "(machine) + trace.json (Chrome trace-event, load "
                         "in Perfetto); enables span tracing")
    ap.add_argument("--metrics-json", default=None,
                    help="write the metrics registry (solver phase timings, "
                         "benchmark cache hit rate, measurement counts) as "
                         "JSON to this path")
    ap.add_argument("--seed-csv", default=None,
                    help="glob of recorded search CSVs; their best distinct "
                         "schedules are warm-start candidates and a climb "
                         "seed (default: this workload's round-4+ databases; "
                         "'' disables)")
    ap.add_argument("--seed-topk", type=int, default=3,
                    help="recorded schedules to carry as candidates")
    ap.add_argument("--learn-train", nargs="+", default=None,
                    metavar="CORPUS",
                    help="train the schedule-cost surrogate on these "
                         "recorded-search CSV globs (labels: in-file ratio "
                         "vs each file's naive anchor), save it to "
                         "--learn-model, print a summary JSON line and exit "
                         "(docs/learn.md)")
    ap.add_argument("--learn-trace", nargs="*", default=None,
                    metavar="TRACE",
                    help="telemetry-bundle JSONL globs joined onto the "
                         "training corpus by schedule digest (provenance "
                         "counts; used with --learn-train)")
    ap.add_argument("--learn-model", default=None,
                    help="surrogate model JSON: written by --learn-train, "
                         "read by --learn-screen")
    ap.add_argument("--learn-screen", action="store_true",
                    help="prescreen MCTS rollouts with the --learn-model "
                         "surrogate, escalating only plausible-top-k / "
                         "uncertain candidates to the device; also prunes "
                         "hill-climb neighbors the model can rule out")
    ap.add_argument("--checkpoint", default=None, metavar="DIR",
                    help="checkpoint directory (docs/robustness.md): the "
                         "measurement journal is appended as each "
                         "measurement lands, solver cursors snapshot "
                         "atomically, deterministic-failure quarantine "
                         "persists, and SIGINT writes a final snapshot")
    ap.add_argument("--resume", action="store_true",
                    help="restore the --checkpoint journal into the "
                         "benchmark cache before searching: already-"
                         "measured schedules never touch the device again "
                         "and the deterministic search reconstructs to the "
                         "kill point")
    ap.add_argument("--measure-timeout", type=float, default=None,
                    metavar="SECS",
                    help="watchdog wall-clock bound per measurement: a hung "
                         "compile/fetch surfaces as a transient timeout "
                         "(retried with backoff) instead of blocking the "
                         "search forever")
    ap.add_argument("--inject-faults", default=None,
                    metavar="KIND:RATE:SEED[,...]",
                    help="seeded chaos (fault/inject.py): deterministically "
                         "inject transient errors / hangs / deterministic "
                         "failures / device loss / schedule corruption "
                         "into every measurement (kinds: transient, hang, "
                         "deterministic, device_lost, corrupt)")
    ap.add_argument("--inject-hang-secs", type=float, default=60.0,
                    help="how long an injected hang stalls (pair with "
                         "--measure-timeout to exercise the watchdog)")
    ap.add_argument("--profile-winner", action="store_true",
                    help="attribution profiling of the final incumbent "
                         "(docs/observability.md, 'Attribution'): per-op "
                         "stepped timing of the winner (and naive, for the "
                         "decision diff), critical path / overlap "
                         "efficiency / dispatch overhead, stamped as an "
                         "``attrib`` block in the driver JSON; with "
                         "--trace-out also writes explain.json and "
                         "per-lane Gantt tracks into the Perfetto trace")
    ap.add_argument("--profile-repeats", type=int, default=7,
                    metavar="N",
                    help="timed repeats per op in --profile-winner "
                         "stepped profiling (median minus calibrated "
                         "fetch overhead)")
    ap.add_argument("--fuse-winner", action="store_true",
                    help="megakernel fusion of the reported schedule "
                         "(docs/performance.md, 'Megakernel fusion'): "
                         "partition it into fusible regions, lower each "
                         "into one Pallas kernel (runtime/fused.py), sweep "
                         "the roofline-pruned tile menu, gate the fused "
                         "outputs against the stepped program (allclose + "
                         "re-verified), and stamp the ``perf.fused`` block "
                         "(regions, tiles, dispatch overhead before/after)")
    ap.add_argument("--fuse-search-tiles", action="store_true",
                    help="run the megakernel tile-count decision nodes in "
                         "the driver's search path (docs/performance.md): "
                         "a FuseTileChoice planted in the choice graph is "
                         "searched by MCTS/DFS/hill-climb like any kernel "
                         "menu, every measurement lowers through the "
                         "schedule's fuse_tile.tN directive, and the "
                         "``perf.fuse_search_tiles`` block records the "
                         "menu and the chosen count")
    ap.add_argument("--chunk", action="store_true",
                    help="T3-style op chunking (docs/performance.md, "
                         "'Chunked overlap'): expand the workload's "
                         "expensive ops into searchable n-way chunked "
                         "variants (core/chunking.py) so a transfer "
                         "overlaps its own producer/consumer; chunk "
                         "counts are roofline-pruned menu entries the "
                         "solvers search like any kernel choice, and the "
                         "driver stamps the ``perf.chunked`` provenance "
                         "block (menus, searched/chosen counts, hidden "
                         "comm estimated vs measured)")
    ap.add_argument("--synth-collectives", action="store_true",
                    help="searchable synthesized collectives "
                         "(docs/performance.md, 'Synthesized collectives'): "
                         "decompose the workload's collective exchanges "
                         "into chunk-routed point-to-point sketches over "
                         "the mesh/host topology (collectives/synth.py) "
                         "and put each priced instantiation next to the "
                         "fixed engine in one ChooseOp; the solvers search "
                         "them like any kernel menu, the independent "
                         "verifier certifies every synthesized projection, "
                         "and the driver stamps the ``perf.synth`` "
                         "provenance block (menus, searched/chosen "
                         "sketches, est vs measured comm, verdict)")
    ap.add_argument("--no-verify", action="store_true",
                    help="disable the independent schedule-soundness "
                         "verifier (docs/robustness.md): the guard in the "
                         "measurement stack, the solver accept points, and "
                         "the final winner-vs-naive result-integrity gate")
    ap.add_argument("--verify-tol", type=float, default=0.02,
                    metavar="RTOL",
                    help="relative tolerance of the result-integrity "
                         "gate's winner-vs-naive output comparison (loose "
                         "enough for bf16-staging menu choices)")
    ap.add_argument("--search-workers", type=int, default=0, metavar="N",
                    help="distributed search fleet "
                         "(docs/performance.md, 'Distributed search'): run "
                         "the climb jobs across N solver worker processes "
                         "over the file control plane, with this process "
                         "as the single measurement owner; 1 (with "
                         "--measure-batch 1) is the serialized inline "
                         "path, bit-identical to the legacy climb loop; "
                         "0 disables the fleet entirely")
    ap.add_argument("--measure-batch", type=int, default=0, metavar="K",
                    help="fuse up to K candidate schedules from distinct "
                         "workers into one device measurement round "
                         "(grouped batch seeds keep each worker's paired "
                         "permutation stream intact), with prefetch hints "
                         "compiling round i+1 during round i; 0 disables "
                         "the fleet")
    return ap


def main() -> int:
    ap = build_arg_parser()
    args = ap.parse_args()
    try:
        res = run_driver(DriverRequest(**vars(args)))
    except DriverConfigError as e:
        ap.error(str(e))  # exits 2, same message/stream as the monolith
    print(json.dumps(res.verdict))
    return 0


if __name__ == "__main__":
    sys.exit(main())
