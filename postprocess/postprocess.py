#!/usr/bin/env python
"""Offline analysis of solver result databases: performance classes + design rules.

Parity target: reference ``postprocess/postprocess.py:27-120`` — sort schedules by
10th-percentile time, locate performance-class boundaries by convolving with a step
function and finding peaks, then fit a decision tree over schedule features to
extract human-readable rules for why some schedules are fast.

Input: the pipe-delimited rows dumped by the solvers
(``idx|pct01|pct10|pct50|pct90|pct99|stddev|op-json|op-json|...``,
tenzing_tpu/bench/benchmarker.py result_row — same shape as reference
mcts.cpp:13-31 / dfs.cpp:84-105).

Schedule features (the TPU analog of the reference's stream-assignment features):
  * ``lane:<op>=<k>``  — device op <op> is bound to lane k
  * ``before:<a><b``   — op a precedes op b in the total order
The decision-tree rules are printed as indented if/else text.

Usage: python postprocess/postprocess.py results.csv [--max-depth 3]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Dict, List, Tuple

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tenzing_tpu.bench.benchmarker import split_fidelity  # noqa: E402

DELIM = "|"


def load_rows(text: str) -> List[dict]:
    """Parse result rows into {times: {...}, ops: [op-json dicts]}."""
    out = []
    for line in text.splitlines():
        if not line.strip():
            continue
        cells = line.split(DELIM)
        try:
            times = {
                "pct01": float(cells[1]),
                "pct10": float(cells[2]),
                "pct50": float(cells[3]),
                "pct90": float(cells[4]),
                "pct99": float(cells[5]),
                "stddev": float(cells[6]),
            }
            # multi-fidelity dumps (round 5): screen rows were measured at
            # a ~1 ms floor and would smear the class boundaries — excluded
            # via the one shared parsing rule
            fid, ops_at = split_fidelity(cells)
            if fid != "full":
                continue
            ops = [json.loads(c) for c in cells[ops_at:]]
        except (IndexError, ValueError):
            # truncated/malformed row (e.g. a dump cut mid-write): skip it,
            # like CsvBenchmarker's strict=False loader
            continue
        out.append({"times": times, "ops": ops})
    return out


def class_boundaries(sorted_times: np.ndarray, rel_height: float = 0.05) -> List[int]:
    """Indices where the sorted time curve steps up: convolve with a step kernel
    and take peaks (reference postprocess.py class-boundary detection)."""
    from scipy.signal import find_peaks

    n = len(sorted_times)
    if n < 4:
        return []
    k = max(2, n // 50)
    # step response at i: mean of the k times at/after i minus mean of the k before
    resp = np.zeros(n)
    for i in range(k, n - k + 1):
        resp[i] = sorted_times[i : i + k].mean() - sorted_times[i - k : i].mean()
    span = float(sorted_times[-1] - sorted_times[0])
    if span <= 0:
        return []
    peaks, _ = find_peaks(resp, height=rel_height * span)
    return [int(p) for p in peaks]


def schedule_features(rows: List[dict]) -> Tuple[np.ndarray, List[str]]:
    """Binary/ordinal feature matrix over lane assignments and pairwise order."""
    # collect device-op names (those serialized with a lane binding)
    lane_ops: List[str] = []
    all_names: List[str] = []
    seen = set()
    for r in rows:
        for op in r["ops"]:
            # scheduler-inserted sync ops carry no name; they are per-schedule
            # artifacts, not design features
            if "name" not in op:
                continue
            name = op["name"]
            if name not in seen:
                seen.add(name)
                all_names.append(name)
                if "lane" in op:
                    lane_ops.append(name)
    sentinel_names = {
        op["name"]
        for r in rows
        for op in r["ops"]
        if op.get("kind") in ("start", "finish") and "name" in op
    }
    feats: List[str] = [f"lane:{n}" for n in lane_ops]
    pairs = [
        (a, b) for i, a in enumerate(all_names) for b in all_names[i + 1 :]
        if a not in sentinel_names and b not in sentinel_names
    ]
    feats += [f"before:{a}<{b}" for a, b in pairs]
    X = np.zeros((len(rows), len(feats)), dtype=np.float32)
    for ri, r in enumerate(rows):
        pos = {}
        for i, op in enumerate(r["ops"]):
            if "name" not in op:
                continue
            name = op["name"]
            pos.setdefault(name, i)
            if "lane" in op and name in lane_ops:
                X[ri, lane_ops.index(name)] = float(op["lane"])
        for pi, (a, b) in enumerate(pairs):
            if a in pos and b in pos:
                X[ri, len(lane_ops) + pi] = 1.0 if pos[a] < pos[b] else 0.0
    return X, feats


def fit_rules(X: np.ndarray, classes: np.ndarray, feats: List[str], max_depth: int = 3) -> str:
    """Decision tree over schedule features -> indented rule text (reference
    postprocess.py sklearn tree fit + export)."""
    from sklearn.tree import DecisionTreeClassifier, export_text

    clf = DecisionTreeClassifier(max_depth=max_depth, random_state=0)
    clf.fit(X, classes)
    return export_text(clf, feature_names=feats)


def plot_classes(
    sorted_times: np.ndarray, bounds: List[int], out_path: str
) -> None:
    """Sorted-pct10 curve with performance-class boundary markers — the
    reference postprocess's matplotlib figure (its step-response/peak view),
    saved to ``out_path``."""
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 4))
    ax.plot(np.arange(len(sorted_times)), sorted_times * 1e3, lw=1.5)
    for b in bounds:
        ax.axvline(b - 0.5, ls="--", lw=1)
    ax.set_xlabel("schedule (sorted by pct10)")
    ax.set_ylabel("pct10 iteration time [ms]")
    ax.set_title(
        f"{len(sorted_times)} schedules, {len(bounds) + 1} performance classes"
    )
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)


def analyze(text: str, max_depth: int = 3, stream=None, plot_path=None) -> dict:
    stream = stream or sys.stdout
    rows = load_rows(text)
    if not rows:
        stream.write("no rows\n")
        return {"n": 0}
    times = np.array([r["times"]["pct10"] for r in rows])
    order = np.argsort(times)
    sorted_times = times[order]
    bounds = class_boundaries(sorted_times)
    # class id per schedule: how many boundaries its sorted rank passes
    ranks = np.empty(len(rows), dtype=int)
    ranks[order] = np.arange(len(rows))
    classes = np.zeros(len(rows), dtype=int)
    for b in bounds:
        classes += (ranks >= b).astype(int)
    stream.write(
        f"{len(rows)} schedules, pct10 range [{sorted_times[0]:.3e}, "
        f"{sorted_times[-1]:.3e}] s, {len(bounds) + 1} performance classes\n"
    )
    for c in range(classes.max() + 1):
        sel = classes == c
        stream.write(
            f"  class {c}: n={int(sel.sum())}, pct10 in "
            f"[{times[sel].min():.3e}, {times[sel].max():.3e}]\n"
        )
    rules = ""
    if classes.max() > 0:
        X, feats = schedule_features(rows)
        rules = fit_rules(X, classes, feats, max_depth)
        stream.write("design rules (decision tree over schedule features):\n")
        stream.write(rules)
    if plot_path:
        plot_classes(sorted_times, bounds, plot_path)
        stream.write(f"figure: {plot_path}\n")
    return {"n": len(rows), "boundaries": bounds, "classes": classes.tolist(), "rules": rules}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("csv", help="solver result database (pipe-delimited)")
    ap.add_argument("--max-depth", type=int, default=3)
    ap.add_argument("--plot", default=None, metavar="PNG",
                    help="save the sorted-pct10 class figure here")
    args = ap.parse_args()
    with open(args.csv) as f:
        analyze(f.read(), args.max_depth, plot_path=args.plot)
    return 0


if __name__ == "__main__":
    sys.exit(main())
