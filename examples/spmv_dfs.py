#!/usr/bin/env python
"""Exhaustive DFS schedule enumeration on the SpMV iteration DAG.

Parity target: reference ``tenzing-dfs/examples/spmv.cu`` (maxSeqs=15000 cap,
band matrix, benchmark every deduplicated complete schedule).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from examples import _driver


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    _driver.add_common_args(ap)
    ap.add_argument("--matrix-m", type=int, default=150_000)
    ap.add_argument("--nnz-per-row", type=int, default=10)
    ap.add_argument("--max-seqs", type=int, default=15_000,
                    help="enumeration cap (reference spmv.cu:117)")
    ap.add_argument("--matrix", default=None,
                    help="MatrixMarket .mtx input instead of the random band "
                         "matrix (reference spmv.cu:35-37)")
    ap.add_argument("--batch", action="store_true",
                    help="decorrelated batch benchmarking: every schedule "
                         "visited once per iteration in random order "
                         "(reference benchmarker.cpp:21-76)")
    args = ap.parse_args()
    _driver.setup(args)

    import jax.numpy as jnp

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.spmv import (
        SpMVCompound,
        make_spmv_buffers,
        read_matrix_market,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.dfs import DfsOpts, explore

    mat = read_matrix_market(args.matrix) if args.matrix else None
    bufs, _ = make_spmv_buffers(m=args.matrix_m, nnz_per_row=args.nnz_per_row,
                                seed=args.seed, matrix=mat)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    plat = Platform.make_n_lanes(args.lanes)
    bench = EmpiricalBenchmarker(TraceExecutor(plat, bufs))
    res = explore(
        g, plat, bench,
        DfsOpts(max_seqs=args.max_seqs,
                bench_opts=BenchOpts(n_iters=args.benchmark_iters),
                batch=args.batch, batch_seed=args.seed),
    )
    _driver.emit(res, args.dump_csv)
    return 0


if __name__ == "__main__":
    sys.exit(main())
