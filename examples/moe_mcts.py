#!/usr/bin/env python
"""MCTS schedule search on the single-chip MoE dispatch/combine pipeline.

The expert-parallel benchmark workload (models/moe_pipeline.py): routed tokens
staged through async host round-trip DMAs to the resident experts, searched
over order x lane x expert-kernel across independent microbatch chunk chains.
Follows the reference per-workload driver shape
(tenzing-mcts/examples/spmv_run_strategy.cuh) with ``--strategy`` selecting
the search strategy.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from examples import _driver


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    _driver.add_common_args(ap)
    _driver.add_mcts_args(ap)
    ap.add_argument("--tokens", type=int, default=8192)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--d-ff", type=int, default=2048)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--no-impl-choice", action="store_true",
                    help="drop the XLA-vs-Pallas expert kernel menu")
    args = ap.parse_args()
    _driver.setup(args)

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.moe_pipeline import (
        MoEPipeArgs,
        build_graph,
        host_buffer_names,
        make_pipe_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.mcts import MctsOpts, explore, strategies

    margs = MoEPipeArgs(n_experts=args.experts, tokens=args.tokens,
                        d_model=args.d_model, d_ff=args.d_ff,
                        n_chunks=args.chunks)
    bufs, _, cap = make_pipe_buffers(margs, seed=args.seed, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names(margs))
    g = build_graph(margs, cap, impl_choice=not args.no_impl_choice)
    plat = Platform.make_n_lanes(args.lanes)
    bench = EmpiricalBenchmarker(TraceExecutor(plat, jbufs))
    res = explore(
        g,
        plat,
        bench,
        MctsOpts(
            n_iters=args.mcts_iters,
            bench_opts=BenchOpts(n_iters=args.benchmark_iters),
            expand_rollout=not args.no_expand_rollout,
            dump_tree=args.dump_tree,
            seed=args.seed,
        ),
        strategy=getattr(strategies, args.strategy),
    )
    _driver.emit(res, args.dump_csv)
    return 0


if __name__ == "__main__":
    sys.exit(main())
