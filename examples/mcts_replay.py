#!/usr/bin/env python
"""Replay-driven MCTS: search a *recorded* result database instead of a device.

Parity target: the reference's CSV-replay drivers
(``tenzing-mcts/examples/mcts_csv_*.cu``, built around CsvBenchmarker,
benchmarker.cpp:169-223) — search-algorithm experiments with no machine in the
loop.  Each strategy runs MCTS against the recorded timings; the report shows
how quickly each one finds the database's best schedule, the reference's
search-quality signal (SURVEY.md §6: MCTS-found min vs the recorded
distribution).

Best with a database covering the whole search space (a full deduplicated DFS
dump — ``examples/spmv_dfs.py --max-seqs`` at least the space size); rollouts
are matched modulo redundant-sync cleanup (CsvBenchmarker ``normalize=True``).
A rollout landing on an unrecorded schedule scores as the database's worst
result (pessimistic prior); the report counts these misses so a capped dump
still yields an honest — if coarser — comparison.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from examples import _driver


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    _driver.add_common_args(ap)
    ap.add_argument("--csv", required=True, help="recorded result database")
    ap.add_argument("--workload", choices=("spmv", "halo"), default="spmv",
                    help="the graph the database rows anchor against")
    ap.add_argument("--mcts-iters", type=int, default=64)
    ap.add_argument("--strategies", default="Random,FastMin,Coverage,AntiCorrelation",
                    help="comma-separated strategy names to compare")
    args = ap.parse_args()
    _driver.setup(args)

    from tenzing_tpu.bench.benchmarker import (
        BenchOpts,
        CsvBenchmarker,
        split_fidelity,
    )
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.solve.mcts import MctsOpts, explore, strategies

    if args.workload == "halo":
        # the round-3 flagship space: kernel menu x transfer-engine menu
        # (halo_search_tpu_r3*.csv record searches over this graph)
        from tenzing_tpu.models.halo import HaloArgs
        from tenzing_tpu.models.halo_pipeline import build_graph

        g = build_graph(HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3),
                        impl_choice=True, xfer_choice=True)
    else:
        from tenzing_tpu.models.spmv import SpMVCompound

        g = Graph()
        g.start_then(SpMVCompound())
        g.then_finish(SpMVCompound())
    db = CsvBenchmarker.from_file(args.csv, g, normalize=True, strict=False)
    if not db.entries:
        raise SystemExit(
            f"no row of {args.csv} deserializes against the "
            f"--workload {args.workload} graph ({len(db.skipped)} skipped) — "
            "workload/CSV mismatch?"
        )
    # the optimum comes from the RAW pct50 column of every FULL-fidelity
    # recorded row: rows recorded from a different graph shape (e.g.
    # pre-choice incumbent schedules) may not deserialize for replay
    # matching, but their TIMES are still the database's ground truth — the
    # iterations-to-optimum signal must not silently improve because the
    # best row was unmatchable.  Multi-fidelity screen rows (``fid=screen``
    # cell, round 5) are excluded on BOTH sides: their ~1 ms-floor pct50s
    # are off-regime bookkeeping, and CsvBenchmarker already refuses to
    # answer queries from them.
    def row_pct50(line):
        parts = line.split("|")
        try:
            if split_fidelity(parts)[0] != "full":
                return float("inf")
            return float(parts[3])
        except (IndexError, ValueError):  # truncated/malformed row: skip,
            return float("inf")           # like the strict=False loader
    with open(args.csv) as f:
        recorded_best = min(
            (row_pct50(line) for line in f if line.strip()), default=float("inf")
        )
    skipped = f", {len(db.skipped)} rows unmatchable for replay" if db.skipped else ""
    sys.stderr.write(
        f"database: {len(db.entries)} schedules{skipped}, best pct50 "
        f"{recorded_best*1e6:.1f}us\n"
    )

    class _PessimisticReplay:
        """Unrecorded rollouts score as the worst recorded result."""

        def __init__(self, inner):
            self.inner = inner
            # worst over FULL-fidelity rows only (screen rows are off-regime
            # and excluded from the lookup cache anyway)
            full = [r for (_, r), f in zip(inner.entries, inner.fidelities)
                    if f == "full"] or [r for _, r in inner.entries]
            self.worst = max(full, key=lambda r: r.pct50)
            self.misses = 0

        def benchmark(self, order, opts=None):
            try:
                return self.inner.benchmark(order, opts)
            except KeyError:
                self.misses += 1
                return self.worst

    plat = Platform.make_n_lanes(args.lanes)
    for name in args.strategies.split(","):
        strat = getattr(strategies, name)
        replay = _PessimisticReplay(db)
        res = explore(
            g, plat, replay,
            MctsOpts(n_iters=args.mcts_iters, bench_opts=BenchOpts(),
                     seed=args.seed),
            strategy=strat,
        )
        # iterations-to-best: the search-quality signal
        best_so_far, hit_at = float("inf"), None
        for i, s in enumerate(res.sims):
            if s.result.pct50 < best_so_far:
                best_so_far = s.result.pct50
            if hit_at is None and best_so_far <= recorded_best:
                hit_at = i
        miss = f", {replay.misses} unrecorded rollouts" if replay.misses else ""
        print(
            f"{name}: best {best_so_far*1e6:.1f}us over {len(res.sims)} "
            f"benchmarked rollouts{miss}; recorded optimum "
            f"{'hit at iter %d' % hit_at if hit_at is not None else 'not reached'}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
