#!/usr/bin/env python
"""MCTS schedule search on the 3D halo exchange over a device mesh.

Parity target: reference ``tenzing-mcts/examples/halo_{min_time,coverage,
anticorr,balance}.cu`` via ``halo_run_strategy.hpp`` (nQ=3, 512^3 cells/rank,
nGhost=3, 2 streams; rank grid from prime factorization of world size) — here
the device grid is a 3D JAX mesh, factorized the same way, and ``--strategy``
selects the search strategy.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from examples import _driver


def mesh_shape_for(n: int):
    """Near-cubic 3D factorization of the device count (reference
    halo_run_strategy.hpp:80-98 prime-factor rank grid)."""
    from tenzing_tpu.utils.numeric import prime_factors

    dims = [1, 1, 1]
    for f in sorted(prime_factors(n), reverse=True):
        dims[dims.index(min(dims))] *= f
    return tuple(sorted(dims, reverse=True))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    _driver.add_common_args(ap)
    _driver.add_mcts_args(ap)
    ap.add_argument("--nq", type=int, default=3)
    ap.add_argument("--cells", type=int, default=512,
                    help="cells per shard per axis (reference 512)")
    ap.add_argument("--radius", type=int, default=3, help="ghost radius")
    args = ap.parse_args()
    _driver.setup(args)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import HaloArgs, HaloExchange, make_halo_buffers
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.mcts import MctsOpts, explore, strategies

    devs = jax.devices()
    mx, my, mz = mesh_shape_for(len(devs))
    mesh = Mesh(np.array(devs).reshape(mx, my, mz), ("x", "y", "z"))
    hargs = HaloArgs(nq=args.nq, lx=args.cells, ly=args.cells, lz=args.cells,
                     radius=args.radius)
    bufs, specs, _ = make_halo_buffers((mx, my, mz), hargs, seed=args.seed)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    g = Graph()
    he = HaloExchange(hargs)
    g.start_then(he)
    g.then_finish(he)
    plat = Platform.make_n_lanes(args.lanes, mesh=mesh, specs=specs)
    bench = EmpiricalBenchmarker(TraceExecutor(plat, bufs))
    res = explore(
        g,
        plat,
        bench,
        MctsOpts(
            n_iters=args.mcts_iters,
            bench_opts=BenchOpts(n_iters=args.benchmark_iters),
            expand_rollout=not args.no_expand_rollout,
            dump_tree=args.dump_tree,
            seed=args.seed,
        ),
        strategy=getattr(strategies, args.strategy),
    )
    _driver.emit(res, args.dump_csv)
    return 0


if __name__ == "__main__":
    sys.exit(main())
