"""Shared CLI driver for the example programs.

Parity target: reference ``tenzing-mcts/examples/halo_run_strategy.hpp`` /
``spmv_run_strategy.cuh`` — argparse CLI, init + reproduce stamp, graph build,
platform, solver run, pipe-delimited CSV to stdout.  One parametrized driver with
``--strategy`` replaces the reference's one-main-per-(workload x strategy) because
strategies are runtime values here, not template parameters.
"""

from __future__ import annotations

import argparse
import os
import sys


def add_common_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--benchmark-iters", type=int, default=50,
                    help="measurements per schedule (reference bench nIters=50)")
    ap.add_argument("--lanes", type=int, default=2, help="virtual lanes (streams)")
    ap.add_argument("--dump-csv", default=None, help="also write results to this path")
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU backend with 8 virtual devices (testing)")
    ap.add_argument("--seed", type=int, default=0)


def add_mcts_args(ap: argparse.ArgumentParser) -> None:
    from tenzing_tpu.solve.mcts import strategies

    ap.add_argument("--mcts-iters", type=int, default=300,
                    help="search iterations (reference spmv_run_strategy.cuh:125)")
    ap.add_argument("--strategy", default="FastMin",
                    choices=[s for s in dir(strategies)
                             if isinstance(getattr(strategies, s), type)
                             and issubclass(getattr(strategies, s), strategies.StrategyBase)
                             and s not in ("StrategyBase", "_SiblingNormalized")])
    ap.add_argument("--no-expand-rollout", action="store_true",
                    help="do not materialize rollout paths in the tree")
    ap.add_argument("--dump-tree", action="store_true",
                    help="periodic graphviz dumps of the search tree")


def setup(args) -> None:
    """Backend forcing + init gate + reproduce stamp (reference drivers call
    tenzing::init + reproduce::dump_with_cli first, halo_run_strategy.hpp:23-27)."""
    if args.cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    from tenzing_tpu.utils import initgate, reproduce

    initgate.init()
    reproduce.dump_with_cli()


def emit(result, dump_csv_path=None) -> None:
    """Pipe-delimited rows to stdout (reference CSV dump), best to stderr."""
    text = result.dump_csv(dump_csv_path)
    sys.stdout.write(text)
    best = result.best()
    if best is not None:
        sys.stderr.write(
            f"best: pct10={best.result.pct10 * 1e6:.2f}us "
            f"pct50={best.result.pct50 * 1e6:.2f}us over {len(result.sims)} schedules\n"
        )
