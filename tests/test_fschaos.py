"""Chaos acceptance harness smoke (fault/fschaos.py; ISSUE 19): the
unwritable drill — burst ENOSPC latches the store read-only, the
store_unwritable alert fires, claims pause, space 'frees', the probe
clears the latch and the alert resolves — is fast and deterministic,
so it runs in tier 1.  The full fleet phase (real supervisor + seeded
fs faults + member SIGKILL) is the slow acceptance gate."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fschaos(tmp_path, *argv, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TENZING_FSINJECT", None)
    return subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.fault.fschaos",
         "--workdir", str(tmp_path / "chaos"), *argv],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


def test_unwritable_drill_fires_and_resolves(tmp_path):
    """ENOSPC burst -> read-only latch -> alert fires -> daemon pauses;
    space freed -> probe clears the latch -> alert resolves."""
    p = _fschaos(tmp_path, "--skip-fleet", "--seed", "4242")
    assert p.returncode == 0, p.stdout + p.stderr
    verdict = json.loads(p.stdout.strip().splitlines()[-1])
    assert verdict["kind"] == "fschaos_verdict" and verdict["ok"]
    drill = verdict["drill"]
    assert drill["fired"] and drill["resolved"]
    assert drill["probe_write_denials"] > 0  # the outage was real


@pytest.mark.slow
def test_fleet_survives_hostile_fs_with_sigkill(tmp_path):
    """One seeded hostile-fs fleet run (the quick acceptance shape the
    CI chaos smoke also drives): supervisor + members under injected
    EIO/ENOSPC/torn-rename/skew, a member SIGKILLed mid-drain — no
    acknowledged-record loss, exactly-once effect, service answers."""
    p = _fschaos(tmp_path, "--quick", "--seed", "777", timeout=560)
    assert p.returncode == 0, p.stdout + p.stderr
    verdict = json.loads(p.stdout.strip().splitlines()[-1])
    inv = verdict["invariants"]
    assert inv["no_record_loss"] and inv["exactly_once"]
    assert inv["service_answered"]
    assert inv["unwritable_fired_and_resolved"]
