"""Single-chip halo pipeline: post/wait split, numerics, overlap orderings,
and the Pallas pack/unpack kernel menu."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.halo import DIRECTIONS, HaloArgs, _face_slices, dir_name
from tenzing_tpu.models.halo_pipeline import (
    build_graph,
    host_buffer_names,
    make_pipeline_buffers,
    naive_order,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences

ARGS = HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1)


def _executor(args=ARGS, n_lanes=2):
    bufs, want = make_pipeline_buffers(args, seed=0)
    host_sh = jax.sharding.SingleDeviceSharding(
        jax.devices()[0], memory_kind="pinned_host"
    )
    jbufs = {}
    for k, v in bufs.items():
        if k in host_buffer_names():
            jbufs[k] = jax.device_put(jnp.asarray(v), host_sh)
        else:
            jbufs[k] = jnp.asarray(v)
    return TraceExecutor(Platform.make_n_lanes(n_lanes), jbufs), want


@pytest.mark.needs_pinned_host
def test_naive_order_numerics():
    ex, want = _executor(n_lanes=1)
    out = ex.run(naive_order(ARGS, ex.platform))
    np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)


@pytest.mark.needs_pinned_host
def test_searched_schedules_same_answer():
    """Any legal order x lane assignment computes the periodic ghost fill."""
    ex, want = _executor()
    g = build_graph(ARGS)
    states = get_all_sequences(g, ex.platform, max_seqs=4)
    assert states
    for st in states:
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)


def test_overlap_orderings_exist():
    """The enumerated space must contain schedules with work between a fetch
    post and its await — the overlap freedom the post/wait split exists for
    (VERDICT r1 item 3 exit test)."""
    g = build_graph(ARGS)
    plat = Platform.make_n_lanes(1)
    found = False
    for st in get_all_sequences(g, plat, max_seqs=200):
        names = [op.name() for op in st.sequence.vector()]
        for d in DIRECTIONS:
            nd = dir_name(d)
            i = names.index(f"fetch_{nd}")
            j = names.index(f"await_{nd}")
            between = [
                n
                for n in names[i + 1 : j]
                if not n.startswith(("spill", "fetch", "await"))
            ]
            if between:
                found = True
                break
        if found:
            break
    assert found, "no enumerated schedule overlaps compute with an in-flight fetch"


def test_naive_is_fully_synchronous():
    """The baseline awaits every transfer immediately: no op between fetch and
    await, directions strictly sequential."""
    order = naive_order(ARGS, Platform.make_n_lanes(1))
    names = [op.name() for op in order.vector()]
    for d in DIRECTIONS:
        nd = dir_name(d)
        assert names.index(f"await_{nd}") == names.index(f"fetch_{nd}") + 1


def test_pallas_pack_matches_xla_slice():
    from tenzing_tpu.ops.halo_pallas import pack_face_pallas

    rng = np.random.default_rng(3)
    u = jnp.asarray(rng.random((2, 6, 6, 6), dtype=np.float32))
    for d in DIRECTIONS:
        starts, sizes = _face_slices(ARGS, d, "pack")
        got = pack_face_pallas(u, tuple(starts), tuple(sizes), interpret=True)
        want = jax.lax.dynamic_slice(u, starts, sizes)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_pallas_unpack_matches_xla_update():
    from tenzing_tpu.ops.halo_pallas import unpack_face_pallas

    rng = np.random.default_rng(4)
    u = jnp.asarray(rng.random((2, 6, 6, 6), dtype=np.float32))
    for d in DIRECTIONS:
        starts, sizes = _face_slices(ARGS, d, "unpack")
        face = jnp.asarray(rng.random(tuple(sizes), dtype=np.float32))
        got = unpack_face_pallas(u, face, tuple(starts), interpret=True)
        want = jax.lax.dynamic_update_slice(u, face, starts)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_batched_pallas_kernels_match_xla():
    """Batched-row prefetching kernels == XLA slice/DUS for every direction."""
    from tenzing_tpu.ops.halo_pallas import (
        pack_face_pallas_batched,
        unpack_face_pallas_batched,
    )

    rng = np.random.default_rng(5)
    u = jnp.asarray(rng.random((2, 6, 6, 6), dtype=np.float32))
    for d in DIRECTIONS:
        starts, sizes = _face_slices(ARGS, d, "pack")
        got = pack_face_pallas_batched(
            u, tuple(starts), tuple(sizes), interpret=True
        )
        want = jax.lax.dynamic_slice(u, starts, sizes)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))
        ustarts, _ = _face_slices(ARGS, d, "unpack")
        face = jnp.asarray(rng.random(tuple(sizes), dtype=np.float32))
        got = unpack_face_pallas_batched(
            u, face, tuple(ustarts), interpret=True
        )
        want = jax.lax.dynamic_update_slice(u, face, ustarts)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_batched_pallas_multi_block_pipeline():
    """A geometry whose rows exceed the per-slot VMEM cap (nb > 1) exercises
    the two-slot prefetch/write-back rotation, including the final-step drain
    of BOTH slots."""
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.ops.halo_pallas import (
        _face_bx,
        pack_face_pallas_batched,
        unpack_face_pallas_batched,
    )

    # nq=2 with nb=2 gives total=4 grid steps: the steady-state slot-reuse
    # wait (write-back t-1 drained before refetching into slot b) only
    # executes at t >= 1 prefetches, which total=2 never reaches
    args = HaloArgs(nq=2, lx=64, ly=2, lz=1200, radius=2)
    d = (0, 1, 0)
    bx = _face_bx(args, d)
    starts, sizes = _face_slices(args, d, "pack")
    assert 1 < bx < sizes[1], f"geometry must split into multiple blocks, bx={bx}"
    rng = np.random.default_rng(6)
    shape = args.local_shape()
    pad = (shape[0], shape[1], -(-shape[2] // 8) * 8, -(-shape[3] // 128) * 128)
    u = jnp.asarray(rng.random(pad, dtype=np.float32))
    got = pack_face_pallas_batched(u, tuple(starts), tuple(sizes), interpret=True)
    want = jax.lax.dynamic_slice(u, starts, sizes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    ustarts, _ = _face_slices(args, d, "unpack")
    face = jnp.asarray(rng.random(tuple(sizes), dtype=np.float32))
    got = unpack_face_pallas_batched(u, face, tuple(ustarts), interpret=True)
    want = jax.lax.dynamic_update_slice(u, face, ustarts)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_flat_pallas_kernels_match_reference():
    """Direct-flat kernels (dense staging emitted/consumed with the relayout
    in VMEM) == XLA slice+flatten / unflatten+DUS on every lane-aligned
    face."""
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import flatten_face
    from tenzing_tpu.ops.halo_pallas import (
        _flat_ok,
        pack_face_flat_pallas,
        unpack_face_flat_pallas,
    )

    from tenzing_tpu.models.halo_pipeline import _padded_shape

    args = HaloArgs(nq=1, lx=8, ly=64, lz=128, radius=2)
    rng = np.random.default_rng(7)
    pad = _padded_shape(args.local_shape())
    u = jnp.asarray(rng.random(pad, dtype=np.float32))
    covered = 0
    for d in DIRECTIONS:
        if not _flat_ok(args, d):
            continue
        covered += 1
        ps, sz = _face_slices(args, d, "pack")
        us, _ = _face_slices(args, d, "unpack")
        want = flatten_face(jax.lax.dynamic_slice(u, ps, sz), sz)
        got = pack_face_flat_pallas(u, tuple(ps), tuple(sz), interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))
        flat = jnp.asarray(rng.random(want.shape, dtype=np.float32))
        wantu = jax.lax.dynamic_update_slice(u, flat.reshape(tuple(sz)), us)
        gotu = unpack_face_flat_pallas(u, flat, tuple(us), tuple(sz),
                                       interpret=True)
        np.testing.assert_allclose(np.asarray(gotu), np.asarray(wantu))
    assert covered >= 4  # x and y faces; z excluded by the lane gate


def test_flat_gate_excludes_lane_thin_faces():
    """z-faces (trailing dim = radius) fail the sz % 128 gate — Mosaic cannot
    lower the sub-lane-width relayout (probed on v5e) — and stay off the
    flat menu while x/y faces at the flagship geometry get the extra
    entry."""
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.ops.halo_pallas import PackChoice, UnpackChoice, _flat_ok

    args = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)
    assert _flat_ok(args, (1, 0, 0)) and _flat_ok(args, (0, 1, 0))
    assert not _flat_ok(args, (0, 0, 1))
    assert any(
        c.name().endswith(".pallasf")
        for c in UnpackChoice(args, (0, 1, 0)).choices()
    )
    assert not any(
        c.name().endswith(".pallasf")
        for c in PackChoice(args, (0, 0, 1)).choices()
    )


def test_batched_variant_on_menu_only_when_it_differs():
    """At the flagship geometry y/z faces batch >1 row per DMA, so the menu
    grows to 3; x-faces degenerate to the per-row kernel (BX=1) and stay
    at 2."""
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.ops.halo_pallas import PackChoice, UnpackChoice, _face_bx

    args = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)
    assert _face_bx(args, (1, 0, 0)) == 1
    assert _face_bx(args, (0, 1, 0)) > 1
    assert _face_bx(args, (0, 0, 1)) > 1
    # x: xla + pallas + pallasf (bx=1 keeps pallasb off); y: all four;
    # z: xla + pallas + pallasb (lane gate keeps pallasf off)
    assert len(PackChoice(args, (1, 0, 0)).choices()) == 3
    assert len(PackChoice(args, (0, 1, 0)).choices()) == 4
    assert len(UnpackChoice(args, (0, 0, 1)).choices()) == 3


@pytest.mark.needs_pinned_host
def test_impl_choice_graph_enumerates_kernel_menu():
    """With impl_choice=True the solver sees ChooseOp decisions for pack/unpack
    and every resolved schedule still computes the right answer."""
    ex, want = _executor()
    g = build_graph(ARGS, impl_choice=True)
    states = get_all_sequences(g, ex.platform, max_seqs=40)
    assert states
    seen_pallas = False
    for st in states:
        names = [op.name() for op in st.sequence.vector()]
        seen_pallas = seen_pallas or any(n.endswith(".pallas") for n in names)
    assert seen_pallas, "kernel menu never resolved to a Pallas variant"
    for st in states[:2]:
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)


@pytest.mark.needs_pinned_host
def test_single_device_numerics_subprocess():
    """Regression: on a SINGLE device (no xla_force_host_platform_device_count,
    the configuration the real TPU bench runs in), spilling 4D faces with tiny
    trailing dims through pinned_host corrupted the round-trip (partial-stripe
    copies; reproduced on CPU and TPU v5e).  The (rows, 128) staging layout
    must survive — this runs where conftest's 8-device env cannot mask it."""
    import subprocess
    import sys as _sys

    code = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
import jax, jax.numpy as jnp, numpy as np
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.halo import HaloArgs
from tenzing_tpu.models.halo_pipeline import (
    host_buffer_names, make_pipeline_buffers, naive_order)
from tenzing_tpu.runtime.executor import TraceExecutor
args = HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1)
bufs, want = make_pipeline_buffers(args, seed=0)
host_sh = jax.sharding.SingleDeviceSharding(jax.devices()[0], memory_kind="pinned_host")
jbufs = {k: (jax.device_put(jnp.asarray(v), host_sh) if k in host_buffer_names()
             else jnp.asarray(v)) for k, v in bufs.items()}
plat = Platform.make_n_lanes(1)
U = np.asarray(TraceExecutor(plat, jbufs).run(naive_order(args, plat))["U"])
assert (U == want).all(), f"{(U != want).sum()} corrupted elements"
print("SINGLE_DEVICE_OK")
"""
    out = subprocess.run(
        [_sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={k: v for k, v in os.environ.items() if k != "XLA_FLAGS"},
    )
    assert "SINGLE_DEVICE_OK" in out.stdout, out.stdout + out.stderr


@pytest.mark.needs_pinned_host
def test_pipeline_benchmarkable_smoke():
    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker

    ex, _ = _executor(n_lanes=1)
    bench = EmpiricalBenchmarker(ex)
    res = bench.benchmark(
        naive_order(ARGS, ex.platform), BenchOpts(n_iters=3, target_secs=0.0005)
    )
    assert res.pct50 > 0.0


@pytest.mark.needs_pinned_host
def test_greedy_overlap_order_legal_disciplined_and_correct():
    """The greedy incumbent (bench.py's anytime seed): every prefix passes the
    sync oracle, every transfer is posted before any await (the discipline the
    reference graph hard-codes, ops_halo_exchange.cu:249-256), packs alternate
    lanes, and the result is numerically right."""
    from tenzing_tpu.core.event_synchronizer import EventSynchronizer
    from tenzing_tpu.core.sequence import Sequence
    from tenzing_tpu.models.halo_pipeline import greedy_overlap_order

    plat = Platform.make_n_lanes(2)
    order = greedy_overlap_order(ARGS, plat)
    g = build_graph(ARGS)
    ops = order.vector()
    for i, op in enumerate(ops):
        assert EventSynchronizer.is_synced(g, Sequence(ops[:i]), op), op.desc()
    names = [op.desc() for op in ops]
    first_await = min(i for i, n in enumerate(names) if n.startswith("await"))
    last_post = max(i for i, n in enumerate(names) if n.startswith(("spill", "fetch")))
    assert last_post < first_await
    lanes = {n.split("@")[1] for n in names if n.startswith("pack") and "@" in n}
    assert len(lanes) == 2
    ex, want = _executor()
    out = ex.run(order)
    np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)


@pytest.mark.needs_pinned_host
def test_index_tie_survives_compilation():
    """The INDEX_TIE pack's token edge must survive XLA compilation as a
    DYNAMIC slice start (the select-derived zero on the direction axis).
    Guards against a clamp-analysis improvement folding it to a static slice
    — which would compile every halo schedule to the same unordered program
    (probed: adding the zero on a full-extent axis was folded exactly so)."""
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        build_graph,
        host_buffer_names,
        make_pipeline_buffers,
        naive_order,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    args = HaloArgs(nq=1, lx=8, ly=8, lz=8, radius=2)
    bufs, _ = make_pipeline_buffers(args, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, jbufs)
    seq = naive_order(args, Platform.make_n_lanes(1))
    compiled = ex.compiled_text(seq)
    assert "dynamic-slice" in compiled, (
        "pack token edges folded to static slices — INDEX_TIE ordering lost"
    )
