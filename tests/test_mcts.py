"""MCTS solver + strategies (reference tenzing-mcts/ mcts_node.hpp, mcts.hpp,
strategy headers)."""

import pytest

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import BoundDeviceOp, DeviceOp, NoOp
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.solve.mcts import MctsOpts, explore
from tenzing_tpu.solve.mcts.strategies import ALL_STRATEGIES, FastMin, Random


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


class FakePlatform:
    def __init__(self, n):
        self.lanes = [Lane(i) for i in range(n)]

    def provision_events(self, events):
        return None


class OverlapRewardBench:
    """Schedules using both lanes are 'faster' — a deterministic stand-in for
    real hardware overlap."""

    def __init__(self):
        self.calls = 0

    def benchmark(self, order, opts=None):
        self.calls += 1
        lanes = {
            op.lane().id for op in order if isinstance(op, BoundDeviceOp)
        }
        t = 1.0 if len(lanes) > 1 else 2.0
        return BenchResult(t, t, t, t, t, 0.0)


def two_indep_device_graph():
    g = Graph()
    a, b = KOp("a"), KOp("b")
    g.start_then(a)
    g.start_then(b)
    g.then_finish(a)
    g.then_finish(b)
    return g


def test_mcts_finds_overlapped_schedule():
    g = two_indep_device_graph()
    bench = OverlapRewardBench()
    res = explore(
        g,
        FakePlatform(2),
        bench,
        MctsOpts(n_iters=64, seed=1),
        strategy=FastMin,
    )
    assert res.sims
    best = res.best()
    assert best.result.pct10 == 1.0
    lanes = {op.lane().id for op in best.order if isinstance(op, BoundDeviceOp)}
    assert len(lanes) == 2


def test_mcts_caches_equivalent_rollouts():
    """Repeated rollouts that reduce to an already-timed schedule must not hit
    the underlying benchmarker again (VERDICT r1 weak #5): with a small space
    and many iterations, inner-benchmark calls < recorded sims."""
    g = two_indep_device_graph()
    bench = OverlapRewardBench()
    res = explore(g, FakePlatform(2), bench, MctsOpts(n_iters=64, seed=1))
    assert res.sims
    assert bench.calls < len(res.sims), (bench.calls, len(res.sims))

    # and opting out restores one inner call per iteration
    bench2 = OverlapRewardBench()
    res2 = explore(
        g, FakePlatform(2), bench2,
        MctsOpts(n_iters=16, seed=1, cache_benchmarks=False),
    )
    assert bench2.calls == len(res2.sims)


def test_mcts_stops_when_space_exhausted():
    # one NoOp: the whole space is a single schedule
    g = Graph()
    g.start_then(NoOp("x"))
    g.then_finish(NoOp("x"))
    bench = OverlapRewardBench()
    res = explore(g, FakePlatform(1), bench, MctsOpts(n_iters=500, seed=0))
    assert bench.calls < 500  # stopped early on fully-visited root
    assert res.tree_size >= 1


def test_mcts_seeded_deterministic():
    g = two_indep_device_graph()
    r1 = explore(g, FakePlatform(2), OverlapRewardBench(), MctsOpts(n_iters=16, seed=7))
    r2 = explore(g, FakePlatform(2), OverlapRewardBench(), MctsOpts(n_iters=16, seed=7))
    assert [s.order.desc() for s in r1.sims] == [s.order.desc() for s in r2.sims]


@pytest.mark.parametrize("name", sorted(ALL_STRATEGIES))
def test_every_strategy_runs(name):
    g = two_indep_device_graph()
    res = explore(
        g,
        FakePlatform(2),
        OverlapRewardBench(),
        MctsOpts(n_iters=12, seed=3),
        strategy=ALL_STRATEGIES[name],
    )
    assert res.sims and res.best() is not None


def test_tree_dump_and_counters(tmp_path):
    g = two_indep_device_graph()
    opts = MctsOpts(
        n_iters=8,
        seed=0,
        dump_tree=True,
        dump_tree_prefix=str(tmp_path / "tree"),
        dump_csv_path=str(tmp_path / "mcts.csv"),
    )
    res = explore(g, FakePlatform(2), OverlapRewardBench(), opts)
    dots = list(tmp_path.glob("tree_*.dot"))
    assert dots
    assert "digraph mcts" in dots[0].read_text()
    assert (tmp_path / "mcts.csv").read_text().strip()
    assert res.counters is not None and "SELECT" in res.counters.seconds
    assert res.counters.report().startswith("phase counters:")


def test_rejected_rollouts_emit_candidate_failed_events():
    """A rollout whose schedule fails to compile/run must leave a structured
    search.candidate_failed event (schedule id + exception class) in the
    trace, not just a stderr note (ISSUE 2 satellite)."""
    from tenzing_tpu.obs.tracer import Tracer, set_tracer

    class ExplodingBench:
        def benchmark(self, order, opts=None):
            raise RuntimeError("liveness exceeds device memory")

    tr = Tracer(enabled=True)
    prev = set_tracer(tr)
    try:
        g = two_indep_device_graph()
        res = explore(g, FakePlatform(2), ExplodingBench(),
                      MctsOpts(n_iters=6, seed=0))
        assert res.sims == []  # every rollout rejected, none recorded
        evs = [e for e in tr.events() if e.name == "search.candidate_failed"]
        assert evs
        assert evs[0].attrs["where"] == "mcts.rollout"
        assert evs[0].attrs["error"] == "RuntimeError"
        assert evs[0].attrs["schedule"]  # attributable schedule id
    finally:
        set_tracer(prev)


def test_expand_rollout_materializes_tree():
    g = two_indep_device_graph()
    r_noexp = explore(
        g, FakePlatform(2), OverlapRewardBench(), MctsOpts(n_iters=10, seed=2)
    )
    r_exp = explore(
        g,
        FakePlatform(2),
        OverlapRewardBench(),
        MctsOpts(n_iters=10, seed=2, expand_rollout=True),
    )
    assert r_exp.tree_size >= r_noexp.tree_size
