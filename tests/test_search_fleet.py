"""Distributed search fleet (search/fleet.py, ISSUE 20).

Acceptance coverage:

* grouped permutation reproducibility: a fused K-candidate round with
  ``group_seeds`` visits (and times) each group **bit-identically** to that
  group's solo ``benchmark_batch_times`` call — the measurement owner can
  pack strangers from other workers into one device round without
  perturbing any worker's paired accept decisions;
* the file control plane's monotonic snapshot exchange and winner-takes-all
  claim registry, and ``SharedSearchState``'s improvement-only incumbent
  publishing over it;
* the worker<->owner file protocol: a fused round answers each request with
  its own slice, hints forward to the prefetcher, singles answer inline,
  and errors round-trip with their fault class (``DeviceLostError``
  survives the process boundary);
* rank-agreed MCTS subtree partitioning: disjoint, covering, never empty;
* ``run_serialized`` (the ``--search-workers 1 --measure-batch 1`` path) is
  bit-identical to the direct legacy ``hill_climb`` invocation;
* a real two-subprocess fleet over the device-free spmv graph: every job
  completes, fused rounds fire, incumbents and claims cross the fleet.
"""

import hashlib
import os

import pytest

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    BenchResult,
    CsvBenchmarker,
    EmpiricalBenchmarker,
    result_row,
    schedule_id,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.schedule import remove_redundant_syncs
from tenzing_tpu.core.sequence import canonical_key
from tenzing_tpu.core.state import State
from tenzing_tpu.fault.errors import DeviceLostError
from tenzing_tpu.models.spmv import SpMVCompound
from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics
from tenzing_tpu.parallel.control_plane import FileControlPlane
from tenzing_tpu.search.fleet import (
    FleetBenchmarker,
    FleetJob,
    MeasureOwner,
    SharedSearchState,
    _opts_from_json,
    _opts_to_json,
    _result_from_json,
    _result_to_json,
    claim_key,
    resolve_prefer,
    run_fleet,
    run_serialized,
)
from tenzing_tpu.solve.dfs import enumerate_schedules
from tenzing_tpu.solve.local import LocalOpts, hill_climb
from tenzing_tpu.solve.mcts.mcts import Node, prune_to_subtree
from tenzing_tpu.solve.mcts.strategies import FastMin


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        yield reg
    finally:
        set_metrics(prev)


def _graph():
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    return g


def _synth_result(seq) -> BenchResult:
    key = canonical_key(remove_redundant_syncs(seq))
    h = hashlib.sha256(repr(key).encode()).digest()
    t = 1.0 + int.from_bytes(h[:8], "big") / float(1 << 64)
    return BenchResult.from_times([t, t, t])


@pytest.fixture(scope="module")
def corpus():
    states = enumerate_schedules(_graph(), Platform.make_n_lanes(2),
                                 max_seqs=10_000)
    assert 4 <= len(states) < 10_000
    return [st.sequence for st in states]


# -- identity / serialization ------------------------------------------------


def test_claim_key_canonical_and_stable(corpus):
    a, b = corpus[0], corpus[1]
    assert claim_key(a) == claim_key(a)
    assert len(claim_key(a)) == 32
    assert int(claim_key(a), 16) >= 0  # hex digest
    assert claim_key(a) != claim_key(b)
    # canonical: redundant-sync removal does not change the claim
    assert claim_key(remove_redundant_syncs(a)) == claim_key(a)


def test_json_round_trips():
    j = FleetJob(index=3, budget=17, seed=9, lanes=6,
                 phases=("pack", "unpack"), prefer="recorded",
                 chosen={"xfer_a": "xfer_a.rdma"}, kind="mcts",
                 subtree=(1, 4))
    assert FleetJob.from_json(j.to_json()) == j
    assert FleetJob.from_json(FleetJob(index=0, budget=1,
                                       seed=2).to_json()).phases == ("",)
    opts = BenchOpts(n_iters=7, max_retries=3, target_secs=0.25)
    rt = _opts_from_json(_opts_to_json(opts))
    assert (rt.n_iters, rt.max_retries, rt.target_secs) == (7, 3, 0.25)
    res = BenchResult.from_times([0.5, 0.25, 0.75])
    assert _result_from_json(_result_to_json(res)) == res


def test_resolve_prefer_names_driver_policies():
    from tenzing_tpu.bench import driver

    assert resolve_prefer(FleetJob(0, 1, 2)) is driver.generic_xla_prefer
    assert resolve_prefer(
        FleetJob(0, 1, 2, prefer="halo_alias")) is driver.halo_alias_prefer
    assert resolve_prefer(
        FleetJob(0, 1, 2, prefer="moe_bf16")) is driver.moe_bf16_prefer
    rec = resolve_prefer(FleetJob(0, 1, 2, prefer="recorded",
                                  chosen={"op": "op.host"}))
    assert rec("op", ["op.xla", "op.host"]) == "op.host"
    assert rec("other", ["other.xla", "other.host"]) == "other.xla"


# -- control plane / shared state --------------------------------------------


def test_file_control_plane_snapshots_and_claims(tmp_path):
    root = str(tmp_path / "ctrl")
    cp0 = FileControlPlane(root, 0, 2)
    cp1 = FileControlPlane(root, 1, 2)
    cp0.publish("incumbent", {"cost_s": 2.0})
    cp1.publish("incumbent", {"cost_s": 1.0})
    cp0.publish("incumbent", {"cost_s": 1.5})  # replaces rank 0's snapshot
    snaps = cp1.gather("incumbent")
    assert snaps == {0: {"cost_s": 1.5}, 1: {"cost_s": 1.0}}
    assert cp1.gather("incumbent", include_self=False) == {0: {"cost_s": 1.5}}
    # winner-takes-all: first claimant owns the key, rivals lose
    assert cp0.claim("visited", "k1") is True
    assert cp1.claim("visited", "k1") is False
    assert cp1.claim("visited", "k2") is True
    assert cp0.claim_count("visited") == 2


def test_shared_search_state_claims_and_incumbents(tmp_path, registry,
                                                   corpus):
    root = str(tmp_path / "ctrl")
    s0 = SharedSearchState(FileControlPlane(root, 0, 2))
    s1 = SharedSearchState(FileControlPlane(root, 1, 2))
    assert s0.claim(corpus[0]) is True
    assert s1.claim(corpus[0]) is False  # rank 0 already paid for it
    assert s1.claim(corpus[1]) is True
    assert (s0.claimed, s0.claim_misses) == (1, 0)
    assert (s1.claimed, s1.claim_misses) == (1, 1)
    assert registry.counter("search.fleet.claim_misses").value == 1
    s0.note_incumbent(2.0, corpus[0])
    s1.note_incumbent(1.0, corpus[1])
    s0.note_incumbent(3.0, corpus[2])  # worse: not published
    assert s0.cp.gather("incumbent")[0]["cost_s"] == 2.0
    assert s0.global_best() == (1, 1.0)


# -- grouped permutation reproducibility (the fused-round contract) ----------


class VisitRecorder(EmpiricalBenchmarker):
    """EmpiricalBenchmarker with the device replaced by a deterministic
    visit log: ``_measure`` records which schedule ran when and answers a
    time that depends only on (schedule, its own visit count) — so two
    calls produce identical times iff they visit identically."""

    def __init__(self):  # no runner/control plane: both paths overridden
        self.visits = []
        self._counts = {}
        self._overhead = 0.0

    def _runner_for(self, order):
        key = schedule_id(order)

        def run_n(n):
            pass

        run_n.key = key
        return run_n, 0

    def _measure(self, run_n, n_samples, opts, fences_per_sample=0):
        k = run_n.key
        c = self._counts[k] = self._counts.get(k, 0) + 1
        self.visits.append(k)
        h = int(hashlib.sha256(k.encode()).hexdigest()[:12], 16)
        return (h % 9973 + c) / 1e6, n_samples


def test_fused_group_seeds_bit_identical_to_solo(corpus):
    """The satellite-2 contract: a group's per-iteration visit order (and
    therefore its times, accept decisions, everything downstream) depends
    only on its own ``(orders, seed)`` — never on the strangers sharing
    the fused round."""
    ga, gb = corpus[:2], corpus[2:4]
    opts = BenchOpts(n_iters=4, max_retries=1)
    fused = VisitRecorder()
    t_fused = fused.benchmark_batch_times(
        ga + gb, opts, seed=5, group_seeds=[(2, 5), (2, 9)])
    solo_a, solo_b = VisitRecorder(), VisitRecorder()
    t_a = solo_a.benchmark_batch_times(ga, opts, seed=5)
    t_b = solo_b.benchmark_batch_times(gb, opts, seed=9)
    assert t_fused[:2] == t_a and t_fused[2:] == t_b
    keys_a = {schedule_id(o) for o in ga}
    assert [k for k in fused.visits if k in keys_a] == solo_a.visits
    assert [k for k in fused.visits if k not in keys_a] == solo_b.visits


def test_bad_group_partition_rejected(corpus):
    with pytest.raises(ValueError, match="partition"):
        VisitRecorder().benchmark_batch_times(
            corpus[:3], BenchOpts(n_iters=1), group_seeds=[(2, 5)])
    with pytest.raises(ValueError, match="partition"):
        VisitRecorder().benchmark_batch_times(
            corpus[:2], BenchOpts(n_iters=1), group_seeds=[(2, 5), (0, 9)])


# -- worker<->owner file protocol --------------------------------------------


class SynthBench:
    """Owner-side benchmark stack stand-in: deterministic per-schedule
    answers (hash of the canonical form), batch protocol included."""

    def __init__(self, fail=None):
        self.fail = fail
        self.group_seeds_seen = []

    def benchmark(self, order, opts=None):
        if self.fail is not None:
            exc = self.fail(order)
            if exc is not None:
                raise exc
        return _synth_result(order)

    def benchmark_batch_times(self, orders, opts=None, seed=0,
                              times_out=None, group_seeds=None):
        self.group_seeds_seen.append(group_seeds)
        n = (opts or BenchOpts()).n_iters
        return [[_synth_result(o).pct50] * n for o in orders]


def _mk_fleet_dir(tmp_path):
    d = str(tmp_path / "fleet")
    for sub in ("jobs", "mq", "ctrl"):
        os.makedirs(os.path.join(d, sub))
    return d


def test_owner_answers_fused_round_per_request(tmp_path, registry, corpus):
    d = _mk_fleet_dir(tmp_path)
    g = _graph()
    bench = SynthBench()
    owner = MeasureOwner(d, g, bench, measure_batch=4)
    owner.heartbeat()
    p1 = FleetBenchmarker(d, 1, g, timeout_secs=5.0)
    p2 = FleetBenchmarker(d, 2, g, timeout_secs=5.0)
    opts = BenchOpts(n_iters=3, max_retries=1)
    r1 = p1._submit("batch", corpus[:2], opts, seed=5)
    r2 = p2._submit("batch", corpus[2:4], opts, seed=9)
    owner.drain(busy_workers=2)
    assert owner.rounds == 1 and owner.fused_orders == 4
    assert owner.occupancy() == 1.0
    assert bench.group_seeds_seen == [[(2, 5), (2, 9)]]
    assert registry.counter("search.fleet.rounds").value == 1
    assert registry.counter("search.fleet.fused_orders").value == 4
    t1 = [list(ts) for ts in p1._await(r1)["times"]]
    t2 = [list(ts) for ts in p2._await(r2)["times"]]
    assert t1 == [[_synth_result(o).pct50] * 3 for o in corpus[:2]]
    assert t2 == [[_synth_result(o).pct50] * 3 for o in corpus[2:4]]
    # the high-level proxy call fills the times_out contract too
    r3 = p1._submit("batch", corpus[:1], opts, seed=1)
    owner.drain(busy_workers=1)  # every busy worker pending -> fires at 1
    acc = [[]]
    out = p1._await(r3)
    assert [list(ts) for ts in out["times"]] == [
        [_synth_result(corpus[0]).pct50] * 3]
    assert owner.rounds == 2 and owner.occupancy() == 5 / 8
    del acc


def test_owner_forwards_hints_and_singles(tmp_path, registry, corpus):
    d = _mk_fleet_dir(tmp_path)
    g = _graph()

    class Prefetcher:
        def __init__(self):
            self.seen = []

        def prefetch(self, orders):
            self.seen.extend(orders)
            return len(orders)

    pf = Prefetcher()
    owner = MeasureOwner(d, g, SynthBench(), measure_batch=4, prefetcher=pf)
    owner.heartbeat()
    proxy = FleetBenchmarker(d, 0, g, timeout_secs=5.0)
    assert proxy.prefetch(corpus[:3]) == 3
    rid = proxy._submit("single", corpus[:1], BenchOpts(n_iters=2), 0)
    owner.drain(busy_workers=1)
    assert owner.hints == 3 and owner.singles == 1 and owner.rounds == 0
    assert [canonical_key(o) for o in pf.seen] == [
        canonical_key(o) for o in corpus[:3]]
    assert registry.counter("search.fleet.hints").value == 3
    assert registry.counter("search.fleet.singles").value == 1
    res = _result_from_json(proxy._await(rid)["result"])
    assert res == _synth_result(corpus[0])


def test_owner_error_round_trip_preserves_fault_class(tmp_path, registry,
                                                      corpus):
    d = _mk_fleet_dir(tmp_path)
    g = _graph()
    bench = SynthBench(fail=lambda o: ValueError("synthetic owner failure"))
    owner = MeasureOwner(d, g, bench, measure_batch=2)
    owner.heartbeat()
    proxy = FleetBenchmarker(d, 0, g, timeout_secs=5.0)
    rid = proxy._submit("single", corpus[:1], BenchOpts(n_iters=1), 0)
    owner.drain(busy_workers=1)
    with pytest.raises(RuntimeError, match=r"\[owner\] ValueError"):
        proxy._await(rid)
    # a device loss is fatal on BOTH sides: the owner re-raises after
    # answering, and the worker reconstructs the DeviceLostError type
    bench.fail = lambda o: DeviceLostError("tunnel collapsed")
    rid = proxy._submit("single", corpus[:1], BenchOpts(n_iters=1), 0)
    with pytest.raises(DeviceLostError):
        owner.drain(busy_workers=1)
    with pytest.raises(DeviceLostError, match="tunnel collapsed"):
        proxy._await(rid)


# -- subtree partitioning ----------------------------------------------------


def _first_branching_node(plat):
    """Walk the deterministic decision tree down to the first node with
    more than one child (the spmv root's only decision is the compound
    expansion) — ``prune_to_subtree`` works on any Node."""
    node = Node(State(_graph()), FastMin)
    node.ensure_children(plat)
    while len(node.children) == 1:
        node = node.children[0]
        node.ensure_children(plat)
    assert len(node.children) >= 2
    return node


def test_mcts_subtree_slices_disjoint_covering_nonempty():
    plat = Platform.make_n_lanes(2)
    all_keys = [c.decision.key()
                for c in _first_branching_node(plat).children]
    seen = []
    for k in range(2):
        node = _first_branching_node(plat)
        prune_to_subtree(node, plat, (k, 2))
        keys = [c.decision.key() for c in node.children]
        assert keys  # never empty
        seen.extend(keys)
    assert sorted(seen) == sorted(all_keys)  # disjoint AND covering
    # more ranks than children: the empty slice degrades to one child
    for k in range(len(all_keys) + 2):
        node = _first_branching_node(plat)
        prune_to_subtree(node, plat, (k, len(all_keys) + 2))
        assert len(node.children) >= 1


# -- backward-compat bit-identity --------------------------------------------


def test_run_serialized_bit_identical_to_legacy_climb(corpus):
    g = _graph()
    rows = [result_row(i, _synth_result(s), s)
            for i, s in enumerate(corpus)]
    opts = BenchOpts(n_iters=3, max_retries=1)
    jobs = [FleetJob(index=0, budget=5, seed=3, lanes=2),
            FleetJob(index=1, budget=4, seed=7, lanes=2)]
    fr = run_serialized(g, jobs, CsvBenchmarker(rows, g, normalize=True),
                        opts)
    assert fr.stats["workers"] == 1 and fr.stats["measure_batch"] == 1
    assert fr.stats["failed_jobs"] == 0
    assert fr.stats["distinct_candidates"] >= 1
    for j, jr in zip(jobs, fr.jobs):
        r = hill_climb(
            g, Platform.make_n_lanes(2),
            CsvBenchmarker(rows, g, normalize=True), j.phases,
            prefer=resolve_prefer(j),
            opts=LocalOpts(budget=j.budget, bench_opts=opts, seed=j.seed,
                           paired=True))
        assert [(canonical_key(s.order), s.result.pct50)
                for s in jr.sims] == [
            (canonical_key(s.order), s.result.pct50) for s in r.sims]
        assert canonical_key(jr.final.order) == canonical_key(r.final.order)
        assert jr.final.result == r.final.result


# -- the fleet end to end ----------------------------------------------------


def test_fleet_end_to_end_two_workers(tmp_path, registry):
    """Two real worker subprocesses over the device-free spmv smoke graph,
    this process as the measurement owner: every job completes, at least
    one fused round fires, incumbents and claims cross the fleet, and the
    ``perf.distributed`` stats block is fully populated."""
    from tenzing_tpu.bench.driver import DriverRequest, graph_for

    req = DriverRequest(workload="spmv", smoke=True)
    g, _ = graph_for(req)
    jobs = [FleetJob(index=0, budget=4, seed=2, lanes=2),
            FleetJob(index=1, budget=4, seed=3, lanes=2)]
    fr = run_fleet(g, req.to_json(), jobs, SynthBench(),
                   BenchOpts(n_iters=3, max_retries=1), n_workers=2,
                   measure_batch=4, verify=False,
                   fleet_dir=str(tmp_path / "fleet"), lease_ttl=5.0)
    st = fr.stats
    assert st["failed_jobs"] == 0 and len(fr.jobs) == 2
    for jr in fr.jobs:
        assert jr.final is not None and jr.sims
        assert jr.worker in ("worker-r0", "worker-r1")
    assert st["rounds"] >= 1
    assert 0.0 < st["batch_occupancy"] <= 1.0
    assert st["candidates"] == sum(len(jr.sims) for jr in fr.jobs)
    assert 1 <= st["distinct_candidates"] <= st["candidates"]
    assert st["best_cost_us"] == pytest.approx(
        min(s.result.pct50 for jr in fr.jobs for s in jr.sims) * 1e6,
        rel=1e-6)
    assert st["claimed_keys"] >= 1
    assert st["incumbent_costs_s"]  # at least one worker published
    assert st["worker_restarts"] == 0
    assert registry.counter("search.fleet.rounds").value == st["rounds"]
    # the fleet dir we own survives for inspection: done docs exist
    for j in jobs:
        assert os.path.exists(
            os.path.join(str(tmp_path / "fleet"), "jobs",
                         f"job-{j.index}.done.json"))
