"""Pallas version-compat shim (ops/pallas_compat.py): CompilerParams
resolution with unknown-kwarg dropping, and the typeof/eval_shape fallback
out_struct rides on — the pieces that keep the pallas-importing suites
alive across the supported jax range."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tenzing_tpu.ops.pallas_compat import (
    compiler_params,
    compiler_params_cls,
    typeof,
)
from tenzing_tpu.ops.common import out_struct


def test_compiler_params_resolves_on_this_jax():
    cls = compiler_params_cls()
    assert cls is not None
    p = compiler_params(dimension_semantics=("arbitrary", "arbitrary"))
    assert isinstance(p, cls)
    assert tuple(p.dimension_semantics) == ("arbitrary", "arbitrary")


def test_compiler_params_drops_unknown_kwargs():
    # a field no released class carries: must be silently dropped, not a
    # TypeError — the whole point of the shim (0.4.37 has no
    # has_side_effects; the rdma kernels pass it unconditionally)
    p = compiler_params(dimension_semantics=("arbitrary",),
                        definitely_not_a_real_field_xyz=True)
    known = {f.name for f in dataclasses.fields(type(p))}
    assert "definitely_not_a_real_field_xyz" not in known


def test_typeof_works_with_or_without_jax_typeof():
    t = typeof(jnp.zeros((4, 2)))
    assert tuple(t.shape) == (4, 2)
    # the vma probe out_struct performs must never raise
    assert isinstance(getattr(t, "vma", frozenset()), frozenset)


def test_out_struct_shapes_and_dtype():
    s = out_struct((3, 5), jnp.float32, jnp.zeros((3, 5)))
    assert tuple(s.shape) == (3, 5) and s.dtype == jnp.float32


def test_kernels_import_and_run_via_shim():
    """The acceptance the satellite exists for: the kernels that pass
    compiler params compile and run in interpret mode on THIS jax."""
    from tenzing_tpu.ops.attention_pallas import attn_fused_pallas

    b, n, d = 1, 8, 8
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, n, d)), jnp.float32)
    acc = jnp.zeros((b, n, d))
    m = jnp.full((b, n, d), -1e30)
    l = jnp.zeros((b, n, d))
    acc2, m2, l2 = attn_fused_pallas(q, k, v, acc, m, l, 1.0, bkv=n)
    o = np.asarray(acc2 / l2)
    s = np.asarray(q) @ np.asarray(k).transpose(0, 2, 1)
    p = np.exp(s - s.max(axis=2, keepdims=True))
    p /= p.sum(axis=2, keepdims=True)
    np.testing.assert_allclose(o, p @ np.asarray(v), rtol=1e-5, atol=1e-5)


def test_halo_and_rdma_modules_import():
    # module-level CompilerParams construction used to fail the import of
    # every suite touching these on older jax
    import tenzing_tpu.ops.halo_pallas  # noqa: F401
    import tenzing_tpu.ops.rdma  # noqa: F401
