"""TraceExecutor: schedules compile to XLA programs with the schedule's
happens-before structure; numerics must match plain evaluation for EVERY legal
schedule (the by-construction race-freedom of SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


class MatMul(DeviceOp):
    def __init__(self, name, a, b, out):
        super().__init__(name)
        self._a, self._b, self._out = a, b, out

    def reads(self):
        return [self._a, self._b]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: bufs[self._a] @ bufs[self._b]}


class Add(DeviceOp):
    def __init__(self, name, a, b, out):
        super().__init__(name)
        self._a, self._b, self._out = a, b, out

    def reads(self):
        return [self._a, self._b]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: bufs[self._a] + bufs[self._b]}


def diamond_graph():
    """y1 = x@w1; y2 = x@w2; z = y1+y2 — two independent matmuls then a join."""
    g = Graph()
    m1 = MatMul("m1", "x", "w1", "y1")
    m2 = MatMul("m2", "x", "w2", "y2")
    add = Add("add", "y1", "y2", "z")
    g.start_then(m1)
    g.start_then(m2)
    g.then(m1, add)
    g.then(m2, add)
    g.then_finish(add)
    return g


def make_bufs(n=8):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    return {
        "x": jax.random.normal(k1, (n, n), jnp.float32),
        "w1": jax.random.normal(k2, (n, n), jnp.float32),
        "w2": jax.random.normal(k3, (n, n), jnp.float32),
        "y1": jnp.zeros((n, n), jnp.float32),
        "y2": jnp.zeros((n, n), jnp.float32),
        "z": jnp.zeros((n, n), jnp.float32),
    }


def expected(bufs):
    return bufs["x"] @ bufs["w1"] + bufs["x"] @ bufs["w2"]


def test_every_searched_schedule_computes_the_same_answer():
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    bufs = make_bufs()
    ex = TraceExecutor(plat, bufs)
    states = get_all_sequences(g, plat, max_seqs=50)
    assert len(states) >= 2
    want = expected(bufs)
    for st in states:
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["z"]), np.asarray(want), rtol=1e-5)


def test_lowered_hlo_contains_barrier_chains():
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    bufs = make_bufs()
    ex = TraceExecutor(plat, bufs)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    txt = ex.lowered_text(st.sequence)
    assert "opt-barrier" in txt or "OptimizationBarrier" in txt or "optimization_barrier" in txt


def test_compile_cache_hits():
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, make_bufs())
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    f1 = ex.compile(st.sequence)
    f2 = ex.compile(st.sequence)
    assert f1 is f2


def test_undeclared_buffer_write_raises():
    class Rogue(DeviceOp):
        def apply(self, bufs, ctx):
            return {"ghost": jnp.zeros(())}

    g = Graph()
    g.start_then(Rogue("r"))
    g.then_finish(Rogue("r"))
    plat = Platform.make_n_lanes(1)
    ex = TraceExecutor(plat, {"x": jnp.zeros((2,))})
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    with pytest.raises(KeyError, match="undeclared"):
        ex.run(st.sequence)


class Shift(DeviceOp):
    """ppermute ring shift over mesh axis 'd' — an ICI comm op."""

    def reads(self):
        return ["v"]

    def writes(self):
        return ["v"]

    def apply(self, bufs, ctx):
        n = jax.lax.axis_size("d")
        perm = [(i, (i + 1) % n) for i in range(n)]
        return {"v": jax.lax.ppermute(bufs["v"], "d", perm)}


def test_mesh_sharded_schedule_with_collective():
    from jax.sharding import Mesh, PartitionSpec as P

    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("d",))
    plat = Platform.make_n_lanes(2, mesh=mesh, specs={"v": P("d")})
    bufs = {"v": jnp.arange(8, dtype=jnp.float32)}
    g = Graph()
    g.start_then(Shift("shift"))
    g.then_finish(Shift("shift"))
    ex = TraceExecutor(plat, bufs)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    out = ex.run(st.sequence)
    np.testing.assert_array_equal(np.asarray(out["v"]), np.roll(np.arange(8.0), 1))


def test_empirical_benchmarker_smoke():
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, make_bufs())
    bench = EmpiricalBenchmarker(ex)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    res = bench.benchmark(st.sequence, BenchOpts(n_iters=5, target_secs=0.001))
    assert res.pct50 > 0.0
    assert res.pct01 <= res.pct50 <= res.pct99
