"""TraceExecutor: schedules compile to XLA programs with the schedule's
happens-before structure; numerics must match plain evaluation for EVERY legal
schedule (the by-construction race-freedom of SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


class MatMul(DeviceOp):
    def __init__(self, name, a, b, out):
        super().__init__(name)
        self._a, self._b, self._out = a, b, out

    def reads(self):
        return [self._a, self._b]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: bufs[self._a] @ bufs[self._b]}


class Add(DeviceOp):
    def __init__(self, name, a, b, out):
        super().__init__(name)
        self._a, self._b, self._out = a, b, out

    def reads(self):
        return [self._a, self._b]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: bufs[self._a] + bufs[self._b]}


def diamond_graph():
    """y1 = x@w1; y2 = x@w2; z = y1+y2 — two independent matmuls then a join."""
    g = Graph()
    m1 = MatMul("m1", "x", "w1", "y1")
    m2 = MatMul("m2", "x", "w2", "y2")
    add = Add("add", "y1", "y2", "z")
    g.start_then(m1)
    g.start_then(m2)
    g.then(m1, add)
    g.then(m2, add)
    g.then_finish(add)
    return g


def make_bufs(n=8):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    return {
        "x": jax.random.normal(k1, (n, n), jnp.float32),
        "w1": jax.random.normal(k2, (n, n), jnp.float32),
        "w2": jax.random.normal(k3, (n, n), jnp.float32),
        "y1": jnp.zeros((n, n), jnp.float32),
        "y2": jnp.zeros((n, n), jnp.float32),
        "z": jnp.zeros((n, n), jnp.float32),
    }


def expected(bufs):
    return bufs["x"] @ bufs["w1"] + bufs["x"] @ bufs["w2"]


def test_every_searched_schedule_computes_the_same_answer():
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    bufs = make_bufs()
    ex = TraceExecutor(plat, bufs)
    states = get_all_sequences(g, plat, max_seqs=50)
    assert len(states) >= 2
    want = expected(bufs)
    for st in states:
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["z"]), np.asarray(want), rtol=1e-5)


@pytest.mark.needs_tie_hlo
def test_token_ties_survive_compilation():
    """The ordering tokens are data dependencies (select-based ties) precisely
    because the TPU backend strips ``opt-barrier`` post-optimization (measured
    on v5e, see runtime/executor.py docstring).  The compiled — not just
    lowered — HLO must still contain the tie selects."""
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    bufs = make_bufs()
    ex = TraceExecutor(plat, bufs)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    txt = ex.compiled_text(st.sequence)
    assert "select(" in txt or "select.s" in txt or " select" in txt


@pytest.mark.needs_tie_hlo
def test_different_schedules_compile_to_different_programs():
    """A fully-serialized 1-lane order and a 2-lane order of the same DAG must
    not lower to the same executable — otherwise the search space is
    physically meaningless (VERDICT r1 weak #2)."""
    g = diamond_graph()
    bufs = make_bufs()
    plat1 = Platform.make_n_lanes(1)
    ex1 = TraceExecutor(plat1, bufs)
    st1 = get_all_sequences(g, plat1, max_seqs=1)[0]

    plat2 = Platform.make_n_lanes(2)
    ex2 = TraceExecutor(plat2, bufs)
    # find a schedule that actually uses both lanes
    st2 = None
    for st in get_all_sequences(g, plat2, max_seqs=200):
        lanes = {
            op.lane().id
            for op in st.sequence.vector()
            if hasattr(op, "lane") and callable(getattr(op, "lane", None))
            and op.lanes() and len(op.lanes()) == 1
        }
        if len(lanes) >= 2:
            st2 = st
            break
    assert st2 is not None
    assert ex1.compiled_text(st1.sequence) != ex2.compiled_text(st2.sequence)


def test_compile_cache_hits():
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, make_bufs())
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    f1 = ex.compile(st.sequence)
    f2 = ex.compile(st.sequence)
    assert f1 is f2


def test_undeclared_buffer_write_raises():
    class Rogue(DeviceOp):
        def apply(self, bufs, ctx):
            return {"ghost": jnp.zeros(())}

    g = Graph()
    g.start_then(Rogue("r"))
    g.then_finish(Rogue("r"))
    plat = Platform.make_n_lanes(1)
    ex = TraceExecutor(plat, {"x": jnp.zeros((2,))})
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    with pytest.raises(KeyError, match="undeclared"):
        ex.run(st.sequence)


class Shift(DeviceOp):
    """ppermute ring shift over mesh axis 'd' — an ICI comm op."""

    def reads(self):
        return ["v"]

    def writes(self):
        return ["v"]

    def apply(self, bufs, ctx):
        n = jax.lax.axis_size("d")
        perm = [(i, (i + 1) % n) for i in range(n)]
        return {"v": jax.lax.ppermute(bufs["v"], "d", perm)}


@pytest.mark.needs_shard_map
def test_mesh_sharded_schedule_with_collective():
    from jax.sharding import Mesh, PartitionSpec as P

    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("d",))
    plat = Platform.make_n_lanes(2, mesh=mesh, specs={"v": P("d")})
    bufs = {"v": jnp.arange(8, dtype=jnp.float32)}
    g = Graph()
    g.start_then(Shift("shift"))
    g.then_finish(Shift("shift"))
    ex = TraceExecutor(plat, bufs)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    out = ex.run(st.sequence)
    np.testing.assert_array_equal(np.asarray(out["v"]), np.roll(np.arange(8.0), 1))


def test_empirical_benchmarker_smoke():
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, make_bufs())
    bench = EmpiricalBenchmarker(ex)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    res = bench.benchmark(st.sequence, BenchOpts(n_iters=5, target_secs=0.001))
    assert res.pct50 > 0.0
    assert res.pct01 <= res.pct50 <= res.pct99


def test_prepare_n_runs_schedule_repeatedly():
    """run_n(n) iterates the schedule inside one program, carrying buffers —
    n applications of the DAG to its own outputs."""
    g = diamond_graph()
    plat = Platform.make_n_lanes(1)
    bufs = make_bufs()
    ex = TraceExecutor(plat, bufs)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    run_n = ex.prepare_n(st.sequence)
    run_n(1)
    run_n(3)  # same compiled program, dynamic trip count


def test_benchmark_batch_random_permutation():
    """Batch benchmarking returns one result per schedule (reference
    benchmarker.cpp:21-76 decorrelation variant)."""
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, make_bufs())
    bench = EmpiricalBenchmarker(ex)
    states = get_all_sequences(g, plat, max_seqs=3)
    orders = [s.sequence for s in states]
    results = bench.benchmark_batch(orders, BenchOpts(n_iters=4, target_secs=0.0005), seed=7)
    assert len(results) == len(orders)
    for r in results:
        assert r.pct50 > 0.0


def test_caching_benchmarker_dedups_equivalent_schedules():
    from tenzing_tpu.bench.benchmarker import CachingBenchmarker

    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, make_bufs())
    bench = CachingBenchmarker(EmpiricalBenchmarker(ex))
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    opts = BenchOpts(n_iters=3, target_secs=0.0005)
    r1 = bench.benchmark(st.sequence, opts)
    r2 = bench.benchmark(st.sequence, opts)
    assert r1 is r2
    assert bench.hits == 1 and bench.misses == 1


def test_benchmark_batch_times_iteration_aligned():
    """benchmark_batch_times returns iteration-aligned raw series (one value
    per schedule per iteration) — the input contract of paired_speedup."""
    g = diamond_graph()
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, make_bufs())
    bench = EmpiricalBenchmarker(ex)
    orders = [s.sequence for s in get_all_sequences(g, plat, max_seqs=2)]
    times = bench.benchmark_batch_times(
        orders, BenchOpts(n_iters=4, target_secs=0.0005), seed=7
    )
    assert len(times) == len(orders)
    assert all(len(ts) == 4 for ts in times)
    assert all(t > 0.0 for ts in times for t in ts)


def test_benchmark_batch_times_fills_times_out_in_place():
    """times_out is the mid-flight accumulator a signal handler snapshots for
    partial dumps (solve/dfs.py batch path)."""
    g = diamond_graph()
    plat = Platform.make_n_lanes(1)
    ex = TraceExecutor(plat, make_bufs())
    bench = EmpiricalBenchmarker(ex)
    orders = [s.sequence for s in get_all_sequences(g, plat, max_seqs=2)]
    acc = [[] for _ in orders]
    out = bench.benchmark_batch_times(
        orders, BenchOpts(n_iters=3, target_secs=1e-4), seed=0, times_out=acc
    )
    assert out is acc
    assert all(len(ts) == 3 for ts in acc)
    with pytest.raises(ValueError):
        bench.benchmark_batch_times(orders, BenchOpts(n_iters=1), times_out=[[]])
