"""Cross-run warm-start ranking (bench/recorded.py): in-file-ratio ranking,
regime robustness, dedup, anchor handling."""

import numpy as np

from tenzing_tpu.bench.benchmarker import CSV_DELIM, result_row, BenchResult
from tenzing_tpu.bench.recorded import naive_anchor_of, rank_recorded
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.halo import HaloArgs
from tenzing_tpu.models.halo_pipeline import build_graph, naive_order
from tenzing_tpu.solve.dfs import get_all_sequences

ARGS = HaloArgs(nq=1, lx=4, ly=4, lz=4, radius=1)


def _res(pct50: float) -> BenchResult:
    return BenchResult(pct01=pct50, pct10=pct50, pct50=pct50,
                       pct90=pct50, pct99=pct50, stddev=0.0)


def _db(path, naive_s, scheds):
    """Write a synthetic database: naive row 0 + (seq, pct50) rows."""
    rows = [result_row(0, _res(naive_s), naive_order(ARGS, Platform.make_n_lanes(1)))]
    for i, (seq, s) in enumerate(scheds):
        rows.append(result_row(i + 1, _res(s), seq))
    path.write_text("\n".join(rows) + "\n")
    return str(path)


def test_in_file_ratio_beats_cross_regime_absolute(tmp_path):
    """A 2x discovery recorded in a slow regime must outrank a 1.2x schedule
    from a fast regime even though the latter's absolute time is smaller."""
    g = build_graph(ARGS)
    plat = Platform.make_n_lanes(2)
    seqs = [st.sequence for st in get_all_sequences(g, plat, max_seqs=8)]
    assert len(seqs) >= 3
    slow = _db(tmp_path / "slow.csv", 0.100, [(seqs[0], 0.050)])  # ratio 2.0
    fast = _db(tmp_path / "fast.csv", 0.012, [(seqs[1], 0.010)])  # ratio 1.2
    out = rank_recorded([slow, fast], g, topk=2)
    assert len(out) == 2
    assert abs(out[0][1] - 2.0) < 1e-9   # the slow-regime discovery leads
    assert abs(out[1][1] - 1.2) < 1e-9


def test_dedup_and_topk(tmp_path):
    g = build_graph(ARGS)
    plat = Platform.make_n_lanes(2)
    seqs = [st.sequence for st in get_all_sequences(g, plat, max_seqs=8)]
    # same schedule recorded twice at different ratios -> carried once, best
    a = _db(tmp_path / "a.csv", 0.100, [(seqs[0], 0.040), (seqs[1], 0.080)])
    b = _db(tmp_path / "b.csv", 0.100, [(seqs[0], 0.090)])
    out = rank_recorded([a, b], g, topk=5)
    ratios = [round(r, 3) for _, r in out]
    # dup of seqs[0] (1.111 in file b) dropped with its best ratio kept;
    # naive rows (ratio 1.0) filtered as non-winners
    assert ratios == [2.5, 1.25]
    out1 = rank_recorded([a, b], g, topk=1)
    assert len(out1) == 1 and abs(out1[0][1] - 2.5) < 1e-9


def test_missing_anchor_and_unreadable_file(tmp_path):
    g = build_graph(ARGS)
    plat = Platform.make_n_lanes(2)
    seqs = [st.sequence for st in get_all_sequences(g, plat, max_seqs=4)]
    # file whose first row is not index 0 -> no anchor -> contributes nothing
    noanchor = tmp_path / "noanchor.csv"
    noanchor.write_text(result_row(7, _res(0.05), seqs[0]) + "\n")
    assert naive_anchor_of(str(noanchor)) is None
    garbled = tmp_path / "garbled.csv"
    garbled.write_text("not|a|valid|row\n")
    msgs = []
    out = rank_recorded([str(noanchor), str(garbled)], g, topk=3,
                        log=msgs.append)
    assert out == []
    assert any("carrying top 0" in m for m in msgs)


def test_naive_anchor_rejects_screen_fidelity_row0(tmp_path):
    """A file whose row 0 is a SCREEN-fidelity naive has no regime-honest
    anchor: its ~100x-cheaper measurement floor would corrupt every in-file
    ratio, so naive_anchor_of must return None (the dump side asserts the
    row-0-is-full-naive invariant at write time, bench.py --dump-csv)."""
    naive = naive_order(ARGS, Platform.make_n_lanes(1))
    screen0 = tmp_path / "screen0.csv"
    screen0.write_text(
        result_row(0, _res(0.001), naive, fidelity="screen") + "\n")
    assert naive_anchor_of(str(screen0)) is None
    full0 = tmp_path / "full0.csv"
    full0.write_text(result_row(0, _res(0.1), naive) + "\n")
    assert naive_anchor_of(str(full0)) == 0.1
    # explicit fid=full tag is equivalent to the legacy untagged row
    tagged = tmp_path / "tagged.csv"
    tagged.write_text(result_row(0, _res(0.2), naive, fidelity="full") + "\n")
    assert naive_anchor_of(str(tagged)) == 0.2
    # and rank_recorded treats the screen-anchored file as anchorless
    g = build_graph(ARGS)
    rows = [result_row(0, _res(0.001), naive, fidelity="screen")]
    plat = Platform.make_n_lanes(2)
    seqs = [st.sequence for st in get_all_sequences(build_graph(ARGS), plat,
                                                    max_seqs=2)]
    rows.append(result_row(1, _res(0.0001), seqs[0]))
    db = tmp_path / "screendb.csv"
    db.write_text("\n".join(rows) + "\n")
    assert rank_recorded([str(db)], g, topk=3) == []


def test_stale_rows_skipped_against_narrower_graph(tmp_path):
    """Rows recorded against the menu graph deserialize against the same
    graph; rows from a DIFFERENT structural variant are skipped, not fatal."""
    g_menu = build_graph(ARGS, impl_choice=True)
    plat = Platform.make_n_lanes(2)
    seqs = [st.sequence for st in get_all_sequences(g_menu, plat, max_seqs=6)]
    path = _db(tmp_path / "menu.csv", 0.100, [(seqs[-1], 0.025)])
    # same file read against the plain graph: the naive row (plain ops)
    # resolves, menu-resolved ops may not — either way no crash
    g_plain = build_graph(ARGS)
    out = rank_recorded([path], g_plain, topk=3)
    for seq, ratio in out:
        assert ratio > 0
