"""Remote-DMA comm ops (VERDICT r2 item 2): the make_async_remote_copy +
semaphore realization of Isend/Irecv/Wait (reference ops_mpi.hpp:17-146),
exercised in Pallas TPU-interpret mode on the virtual CPU mesh — kernel
numerics, the menu wiring in the halo and pipeline graphs, and the executor's
split start/await settlement plumbing."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.state import ChooseOp, State
from tenzing_tpu.ops.rdma import RdmaCopyStart, rdma_shift_fused
from tenzing_tpu.runtime.executor import TraceExecutor


@pytest.mark.needs_shard_map
def test_shift_fused_matches_roll_1d():
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("x",))
    x = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16)

    @jax.jit
    def f(x):
        return jax.shard_map(
            lambda v: rdma_shift_fused(v, ("x",), "x", 1, collective_id=1),
            mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_vma=False,
        )(x)

    np.testing.assert_array_equal(np.asarray(f(x)), np.roll(np.asarray(x), 1, 0))


@pytest.mark.parametrize("axis,dim", [("x", 0), ("y", 1), ("z", 2)])
@pytest.mark.needs_shard_map
def test_shift_fused_matches_roll_3d_mesh(axis, dim):
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("x", "y", "z"))
    x = jnp.arange(2 * 2 * 2 * 16, dtype=jnp.float32).reshape(2, 2, 2, 16)

    @jax.jit
    def f(x):
        return jax.shard_map(
            lambda v: rdma_shift_fused(v, ("x", "y", "z"), axis, 1, collective_id=2),
            mesh=mesh, in_specs=P("x", "y", "z"), out_specs=P("x", "y", "z"),
            check_vma=False,
        )(x)

    np.testing.assert_array_equal(
        np.asarray(f(x)), np.roll(np.asarray(x), 1, dim)
    )


@pytest.mark.needs_shard_map
def test_shift_axis_size_one_is_loopback_copy():
    """n=1 degenerates to the self copy (no barrier, the single-chip case)."""
    devs = np.array(jax.devices()[:1])
    mesh = Mesh(devs, ("x",))
    x = jnp.arange(32, dtype=jnp.float32).reshape(2, 16)

    @jax.jit
    def f(x):
        return jax.shard_map(
            lambda v: rdma_shift_fused(v, ("x",), "x", 1),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
        )(x)

    np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x))


def _choose_all(g: Graph, plat, suffix: str) -> State:
    """Drive the SDP to a terminal state, preferring the ``suffix`` choice at
    every ChoiceOp and the first decision otherwise."""
    st = State(g)
    while not st.is_terminal():
        ds = st.get_decisions(plat)
        pick = next(
            (d for d in ds if isinstance(d, ChooseOp)
             and d.choice.name().endswith(suffix)),
            ds[0],
        )
        st = st.apply(pick)
    return st


def _pipeline_fixture():
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        build_graph,
        host_buffer_names,
        make_pipeline_buffers,
    )

    args = HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1)
    bufs, want = make_pipeline_buffers(args, seed=0)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    g = build_graph(args, xfer_choice=True)
    plat = Platform.make_n_lanes(2)
    return g, jbufs, want, plat, args


@pytest.mark.parametrize("engine", [".host", ".rdma"])
@pytest.mark.needs_pinned_host
def test_pipeline_transfer_menu_both_engines_correct(engine):
    """The halo pipeline's transfer-engine ChoiceOp: both the host round trip
    and the device-resident RDMA copy must produce the exchanged grid."""
    g, jbufs, want, plat, args = _pipeline_fixture()
    st = _choose_all(g, plat, engine)
    names = [op.desc() for op in st.sequence.vector()]
    if engine == ".rdma":
        assert any("xfer_" in n and ".rdma" in n for n in names)
        assert not any(n.startswith("spill_") for n in names)
    else:
        assert any(n.startswith("spill_") for n in names)
    ex = TraceExecutor(plat, jbufs)
    out = ex.run(st.sequence)
    r = args.radius
    U = np.asarray(out["U"])
    np.testing.assert_allclose(
        U[:, : args.lx + 2 * r, : args.ly + 2 * r, : args.lz + 2 * r],
        want[:, : args.lx + 2 * r, : args.ly + 2 * r, : args.lz + 2 * r],
    )


@pytest.mark.needs_pinned_host
def test_pipeline_rdma_benchmark_loop_runs():
    """The split/fused RDMA path must survive the benchmark hot loop's
    fori_loop carry (prepare_n): the inflight closure settles within one
    iteration and nothing leaks into the carry."""
    g, jbufs, want, plat, args = _pipeline_fixture()
    st = _choose_all(g, plat, ".rdma")
    ex = TraceExecutor(plat, jbufs)
    run_n = ex.prepare_n(st.sequence)
    run_n(2)  # raises on any carry-structure mismatch


@pytest.mark.needs_shard_map
def test_halo_mesh_exchange_menu_both_engines_correct():
    """The mesh halo's exchange ChoiceOp (XLA collective-permute vs Pallas
    remote DMA) — both engines fill every ghost face with the periodic
    neighbor's interior edge on the 2x2x2 mesh."""
    from tenzing_tpu.models.halo import HaloArgs, add_to_graph, make_halo_buffers
    from tenzing_tpu.solve.dfs import structural_variants

    args = HaloArgs(nq=1, lx=2, ly=2, lz=2, radius=1)
    bufs, specs, want = make_halo_buffers((2, 2, 2), args, seed=0)
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("x", "y", "z"))
    plat = Platform.make_n_lanes(1, mesh=mesh, specs=specs)
    g = add_to_graph(Graph(), args, xfer_choice=True)
    ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
    for engine in (".xla", ".rdma"):
        st = _choose_all(g, plat, engine)
        assert any(engine in op.desc() for op in st.sequence.vector())
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["U"]), want)


@pytest.mark.needs_pinned_host
def test_rdma_copy_start_serdes_roundtrip():
    """Graph-anchored serdes finds the RDMA op inside the ChoiceOp menu."""
    from tenzing_tpu.core.serdes import sequence_from_json, sequence_to_json

    g, jbufs, want, plat, args = _pipeline_fixture()
    st = _choose_all(g, plat, ".rdma")
    payload = sequence_to_json(st.sequence)
    back = sequence_from_json(payload, g)
    assert [o.desc() for o in back.vector()] == [
        o.desc() for o in st.sequence.vector()
    ]


@pytest.mark.needs_pinned_host
def test_moe_pipeline_rdma_engine_correct():
    """The MoE chunk chains' rdma staging variant produces the routed MoE
    output (engine dimension of the staging menu, models/moe_pipeline.py)."""
    from tenzing_tpu.models.moe_pipeline import (
        MoEPipeArgs,
        greedy_overlap_order,
        host_buffer_names,
        make_pipe_buffers,
    )

    margs = MoEPipeArgs(n_experts=4, tokens=32, d_model=8, d_ff=16, n_chunks=2)
    bufs, want, cap = make_pipe_buffers(margs, seed=0)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names(margs))
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, jbufs)
    order = greedy_overlap_order(margs, cap, plat, engine="rdma")
    names = [op.desc() for op in order.vector()]
    assert any(".rdma" in n for n in names)
    assert not any(n.startswith("spilld") for n in names)
    out = ex.run(order)
    np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-3, atol=2e-5)


def test_moe_staging_choice_includes_engines():
    """staging="choice" exposes the full prec x engine menu (4 variants)."""
    from tenzing_tpu.models.moe_pipeline import MoEPipeArgs, build_graph
    from tenzing_tpu.solve.dfs import structural_variants

    margs = MoEPipeArgs(n_experts=2, tokens=8, d_model=4, d_ff=8, n_chunks=1)
    g = build_graph(margs, cap=8, staging="choice")
    variants = structural_variants(g)
    assert len(variants) == 4
