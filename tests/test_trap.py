"""utils/trap.py lifecycle (ISSUE 3 satellite): register/unregister handler
installation, multi-callback dispatch, and failure isolation — a raising dump
callback must not silence the others (the reference's partial-dump guarantee,
src/trap.cpp:9-35)."""

import signal

import pytest

from tenzing_tpu.utils import trap


@pytest.fixture(autouse=True)
def _clean_trap_state():
    """Tests must never leak a trap installation into the rest of the suite
    (a stray handler would intercept pytest's own Ctrl-C)."""
    assert not trap.installed()
    yield
    for cb in trap.callbacks():
        trap.unregister_handler(cb)
    assert not trap.installed()


def test_register_installs_and_unregister_restores_handlers():
    prev_int = signal.getsignal(signal.SIGINT)
    prev_abrt = signal.getsignal(signal.SIGABRT)

    def dump():
        pass

    trap.register_handler(dump)
    assert trap.installed()
    assert signal.getsignal(signal.SIGINT) is trap._handler
    assert signal.getsignal(signal.SIGABRT) is trap._handler
    trap.unregister_handler(dump)
    assert not trap.installed()
    assert signal.getsignal(signal.SIGINT) is prev_int
    assert signal.getsignal(signal.SIGABRT) is prev_abrt


def test_handler_survives_until_last_unregister():
    """Nested solver registrations (MCTS inside bench.py's telemetry trap)
    keep ONE installed handler until the last callback unregisters."""
    a, b = (lambda: None), (lambda: None)
    trap.register_handler(a)
    installed_handler = signal.getsignal(signal.SIGINT)
    trap.register_handler(b)
    # second registration does not re-install (the previous-handler map
    # must keep the ORIGINAL pre-trap handlers, not the trap itself)
    assert signal.getsignal(signal.SIGINT) is installed_handler
    trap.unregister_handler(a)
    assert trap.installed()
    assert signal.getsignal(signal.SIGINT) is installed_handler
    trap.unregister_handler(b)
    assert not trap.installed()


def test_multiple_callbacks_run_in_registration_order():
    order = []
    a = lambda: order.append("a")  # noqa: E731
    b = lambda: order.append("b")  # noqa: E731
    trap.register_handler(a)
    trap.register_handler(b)
    failed = trap.run_callbacks()
    assert failed == 0
    assert order == ["a", "b"]


def test_raising_callback_does_not_prevent_the_others(capsys):
    ran = []

    def bad():
        raise RuntimeError("dump exploded")

    def good():
        ran.append(True)

    trap.register_handler(bad)
    trap.register_handler(good)
    failed = trap.run_callbacks()
    assert failed == 1
    assert ran == [True]  # the good callback still ran
    assert "dump exploded" in capsys.readouterr().err


def test_unregister_unknown_callback_is_noop():
    known = lambda: None  # noqa: E731
    trap.register_handler(known)
    trap.unregister_handler(lambda: None)  # never registered
    assert trap.installed()
    assert trap.callbacks() == [known]
    trap.unregister_handler(known)


def test_callbacks_registered_during_dispatch_do_not_run_this_round():
    """run_callbacks iterates a snapshot: a callback registering another
    callback mid-dispatch must not grow the current round (the signal path
    must terminate)."""
    ran = []

    def second():
        ran.append("second")

    def first():
        ran.append("first")
        trap.register_handler(second)

    trap.register_handler(first)
    trap.run_callbacks()
    assert ran == ["first"]
    # the newly-registered callback runs on the NEXT dispatch
    ran.clear()
    trap.run_callbacks()
    assert ran == ["first", "second"]
