"""Independent schedule-soundness verifier (ISSUE 4).

The acceptance gates:

* **soundness-of-the-synthesizer** (fuzz): every schedule the
  EventSynchronizer-driven construction emits — exhaustive DFS terminals,
  randomized rollouts, and their ``remove_redundant_syncs`` cleanups,
  across the model suite — passes the independent verifier: 0 false
  positives.
* **minimality-of-the-detector**: dropping any single *load-bearing* sync
  from a verified schedule is detected (100%), where load-bearing is
  decided by the ORIGINAL oracle (``EventSynchronizer.is_synced`` over the
  evolved graph) — two independently-implemented judgments must agree on
  every mutation, in both directions (a genuinely redundant drop must NOT
  be flagged).
"""

import random

import pytest

from tenzing_tpu.core.event_synchronizer import EventSynchronizer
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import NoOp
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.schedule import remove_redundant_syncs
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.state import State
from tenzing_tpu.core.sync_ops import (
    EventRecord,
    EventSync,
    LaneSync,
    SyncOp,
    WaitEvent,
)
from tenzing_tpu.fault.inject import corrupt_schedule
from tenzing_tpu.models.spmv import SpMVCompound
from tenzing_tpu.solve.dfs import enumerate_schedules, expand_all
from tenzing_tpu.verify import ScheduleVerifier, verify_schedule


def _spmv_graph():
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    return g


def _halo_graph():
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import build_graph

    return build_graph(HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1),
                       impl_choice=False, xfer_choice=False)


def _moe_graph():
    from tenzing_tpu.models.moe_pipeline import (
        MoEPipeArgs,
        build_graph,
        make_pipe_buffers,
    )

    margs = MoEPipeArgs(n_experts=4, tokens=32, d_model=8, d_ff=16,
                        n_chunks=2)
    _, _, cap = make_pipe_buffers(margs, seed=0, with_expected=False,
                                  staging="f32")
    return build_graph(margs, cap, impl_choice=False, staging="f32")


def synth_sound(graph, seq) -> bool:
    """The ORIGINAL oracle's judgment of a complete sequence: every
    non-sync op must be ``is_synced`` against the prefix that precedes it —
    exactly the incremental criterion the synthesizer enforced while
    building the schedule (core/event_synchronizer.py)."""
    ops = seq.vector()
    for i, op in enumerate(ops):
        if isinstance(op, SyncOp):
            continue
        if not EventSynchronizer.is_synced(graph, Sequence(ops[:i]), op):
            return False
    return True


def _random_rollouts(graph, platform, n, seed):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        st = State(graph)
        while not st.is_terminal():
            ds = st.get_decisions(platform)
            st = st.apply(ds[rng.randrange(len(ds))])
        out.append(st)
    return out


# -- soundness of the synthesizer (fuzz: 0 false positives) -----------------

def test_spmv_exhaustive_space_verifies_clean():
    g = _spmv_graph()
    states = enumerate_schedules(g, Platform.make_n_lanes(2), max_seqs=10_000)
    assert len(states) >= 3
    ver = ScheduleVerifier(g)
    for st in states:
        for seq in (st.sequence, remove_redundant_syncs(st.sequence)):
            v = ver(seq)
            assert v.ok, f"false positive: {v.witness()}\n{seq.desc()}"
    assert ver.unsound == 0


@pytest.mark.parametrize("mk_graph,n", [(_halo_graph, 10), (_moe_graph, 10),
                                        (_spmv_graph, 10)])
def test_randomized_rollouts_verify_clean(mk_graph, n):
    g = mk_graph()
    ver = ScheduleVerifier(g)
    for nl in (2, 3):
        for st in _random_rollouts(g, Platform.make_n_lanes(nl), n, seed=nl):
            for seq in (st.sequence, remove_redundant_syncs(st.sequence)):
                v = ver(seq)
                assert v.ok, f"false positive: {v.witness()}\n{seq.desc()}"
            # the fuzz is only meaningful if the oracle agrees the
            # schedules were legal in the first place
            assert synth_sound(st.graph, st.sequence)


# -- minimality of the detector (100% single-drop detection) ----------------

def test_every_single_dropped_sync_is_detected():
    """Both judges — the EventSynchronizer-derived oracle and the
    independent verifier — must agree on EVERY single-sync-drop mutation of
    every (cleaned) schedule in the exhaustive SpMV space: a load-bearing
    drop is detected, a redundant drop is not flagged."""
    g = _spmv_graph()
    states = enumerate_schedules(g, Platform.make_n_lanes(2), max_seqs=10_000)
    ver = ScheduleVerifier(g)
    n_mutations = n_detected = 0
    for st in states:
        for seq in (st.sequence, remove_redundant_syncs(st.sequence)):
            ops = seq.vector()
            for i, op in enumerate(ops):
                if not isinstance(op, SyncOp):
                    continue
                mut = Sequence(ops[:i] + ops[i + 1:])
                n_mutations += 1
                oracle_sound = synth_sound(st.graph, mut)
                got = ver(mut)
                assert got.ok == oracle_sound, (
                    f"judges disagree (oracle sound={oracle_sound}, "
                    f"verifier {got.witness()}) after dropping "
                    f"{op.desc()} from {seq.desc()}")
                if not oracle_sound:
                    n_detected += 1
                    assert any(v.kind in ("dep", "race:raw", "race:war",
                                          "race:waw")
                               for v in got.violations)
    assert n_mutations > 100
    assert n_detected > 50  # the space genuinely contains load-bearing syncs


def oracle_unsound_check(evolved_unbound):
    """``corrupt_schedule`` effectiveness check from the ORIGINAL oracle:
    bind the evolved graph with the lanes the order itself carries (the
    oracle skips unbound predecessors as free), then ask is_synced."""
    from tenzing_tpu.core.operation import BoundDeviceOp

    def check(seq) -> bool:
        assign = {op: op.lane() for op in seq
                  if isinstance(op, BoundDeviceOp)}
        bound = evolved_unbound.apply_lane_assignment(
            {v: assign[v] for v in evolved_unbound.vertices()
             if v in assign})
        return not synth_sound(bound, seq)

    return check


def test_corrupt_schedule_mutations_always_caught():
    """fault/inject.corrupt_schedule with the oracle as its effectiveness
    check only emits mutations the oracle deems unsound — and the
    independent verifier must catch every one (the chaos guarantee)."""
    g = _spmv_graph()
    check = oracle_unsound_check(expand_all(g.clone()))
    states = enumerate_schedules(g, Platform.make_n_lanes(2), max_seqs=10_000)
    ver = ScheduleVerifier(g)
    n = 0
    for st in states:
        seq = remove_redundant_syncs(st.sequence)
        for seed in (1, 2, 3):
            mut = corrupt_schedule(seq, seed, unsound_check=check)
            if mut is None:
                continue
            n += 1
            assert not ver(mut).ok, (
                f"verifier missed a corruption of {seq.desc()} -> "
                f"{mut.desc()}")
    assert n > 50


# -- targeted unit coverage --------------------------------------------------

def _two_lane_chain():
    """start -> a@lane0 -> b@lane1 -> finish with explicit syncs."""
    from tenzing_tpu.core.operation import DeviceOp

    class Dev(DeviceOp):
        def __init__(self, name, buf_in, buf_out):
            super().__init__(name)
            self._r, self._w = buf_in, buf_out

        def reads(self):
            return [self._r]

        def writes(self):
            return [self._w]

    g = Graph()
    a, b = Dev("a", "x", "y"), Dev("b", "y", "z")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    a0, b1 = a.bind(Lane(0)), b.bind(Lane(1))
    e0, e1 = Event(0), Event(1)
    seq = Sequence([
        g.start(), a0, EventRecord(Lane(0), e0), WaitEvent(Lane(1), e0),
        b1, EventRecord(Lane(1), e1), EventSync(e1), g.finish(),
    ])
    return g, seq, a0, b1


def test_hand_built_schedule_verifies_and_labels_races():
    g, seq, a0, b1 = _two_lane_chain()
    assert verify_schedule(seq, g).ok
    ops = seq.vector()
    # drop the WaitEvent: a -> b is now unordered, and it conflicts on "y"
    # (a writes, b reads) -> race:raw with the buffer as the witness
    mut = Sequence([o for o in ops if not isinstance(o, WaitEvent)])
    v = verify_schedule(mut, g)
    assert not v.ok
    assert v.violations[0].kind == "race:raw"
    assert v.violations[0].resource == "y"
    assert "happens-before" in v.witness()
    # drop the EventSync: b -> finish unordered; finish reads/writes
    # nothing -> plain dep violation
    mut2 = Sequence([o for o in ops if not isinstance(o, EventSync)])
    v2 = verify_schedule(mut2, g)
    assert not v2.ok
    assert v2.violations[0].kind == "dep"
    # reorder: wait before its record observes nothing -> unordered + warned
    i_rec = next(i for i, o in enumerate(ops) if isinstance(o, EventRecord))
    i_wait = next(i for i, o in enumerate(ops) if isinstance(o, WaitEvent))
    swapped = list(ops)
    swapped[i_rec], swapped[i_wait] = swapped[i_wait], swapped[i_rec]
    v3 = verify_schedule(Sequence(swapped), g)
    assert not v3.ok
    assert any("dangling wait" in w for w in v3.warnings)


def test_structural_defects_flagged():
    g, seq, a0, b1 = _two_lane_chain()
    ops = seq.vector()
    # missing op
    v = verify_schedule(Sequence([o for o in ops if o is not b1]), g)
    assert not v.ok and any(x.kind == "missing_op" for x in v.violations)
    # duplicated op
    v = verify_schedule(Sequence(ops + [b1]), g)
    assert not v.ok and any(x.kind == "duplicate_op" for x in v.violations)
    # unbound device op
    from tenzing_tpu.core.operation import unbound

    ops2 = [unbound(o) if o is b1 else o for o in ops]
    v = verify_schedule(Sequence(ops2), g)
    assert not v.ok and any(x.kind == "unbound_op" for x in v.violations)


def test_dangling_record_warns_but_stays_sound():
    g, seq, a0, b1 = _two_lane_chain()
    ops = seq.vector()
    extra = Sequence(ops[:-1] + [EventRecord(Lane(1), Event(7)), ops[-1]])
    v = verify_schedule(extra, g)
    assert v.ok
    assert any("dangling record" in w for w in v.warnings)


def test_lane_sync_orders_device_then_host():
    g, seq, a0, b1 = _two_lane_chain()
    ops = seq.vector()
    # replace record+sync before finish with a LaneSync on lane 1
    pruned = [o for o in ops
              if not isinstance(o, EventSync)
              and not (isinstance(o, EventRecord) and o.lane() == Lane(1))]
    i_fin = len(pruned) - 1
    with_ls = pruned[:i_fin] + [LaneSync(Lane(1))] + pruned[i_fin:]
    assert verify_schedule(Sequence(with_ls), g).ok
    assert not verify_schedule(Sequence(pruned), g).ok


def test_verdict_json_and_verifier_cache():
    g, seq, _, _ = _two_lane_chain()
    ver = ScheduleVerifier(g)
    assert ver(seq).ok and ver(seq).ok
    assert ver.checked == 1  # second call answered from the verdict cache
    assert ver("not-a-sequence").ok  # non-Sequence orders are vacuous
    j = ver(seq).to_json()
    assert j["ok"] is True and j["violations"] == []
    ops = seq.vector()
    bad = ver(Sequence([o for o in ops if not isinstance(o, WaitEvent)]))
    j = bad.to_json()
    assert j["ok"] is False and j["violations"][0]["kind"] == "race:raw"


def test_host_ops_need_no_sync_among_themselves():
    g = Graph()
    a, b = NoOp("h1"), NoOp("h2")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    assert verify_schedule(Sequence([g.start(), a, b, g.finish()]), g).ok
    # ...but reversing host program order breaks the dep
    assert not verify_schedule(Sequence([g.start(), b, a, g.finish()]), g).ok


# -- the measurement-stack guard ---------------------------------------------

def test_resilient_guard_quarantines_unsound_schedules():
    from tenzing_tpu.bench.benchmarker import BenchResult, schedule_id
    from tenzing_tpu.fault import (
        Quarantine,
        ResilientBenchmarker,
        UnsoundScheduleError,
    )
    from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics

    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        g, seq, _, _ = _two_lane_chain()
        ops = seq.vector()
        bad = Sequence([o for o in ops if not isinstance(o, WaitEvent)])

        class Inner:
            calls = 0

            def benchmark(self, order, opts=None):
                self.calls += 1
                return BenchResult.from_times([1.0])

        inner = Inner()
        quar = Quarantine()
        rb = ResilientBenchmarker(inner, quarantine=quar,
                                  verifier=ScheduleVerifier(g))
        assert rb.benchmark(seq).pct50 == 1.0  # sound passes through
        with pytest.raises(UnsoundScheduleError):
            rb.benchmark(bad)
        assert inner.calls == 1  # the unsound schedule was NEVER measured
        assert schedule_id(bad) in quar.entries
        assert reg.counter("verify.unsound").value == 1
    finally:
        set_metrics(prev)


def test_solver_accept_points_reject_unsound(tmp_path):
    """All three solvers refuse a candidate their ``verify`` gate rejects
    (here: a gate that rejects everything — so every accept point must
    fire) without crashing and without measuring anything."""
    from tenzing_tpu.bench.benchmarker import BenchResult
    from tenzing_tpu.solve.dfs import DfsOpts
    from tenzing_tpu.solve.dfs import explore as dfs_explore
    from tenzing_tpu.solve.local import LocalOpts, hill_climb
    from tenzing_tpu.solve.mcts import MctsOpts, explore

    class RejectAll:
        def __call__(self, order):
            from tenzing_tpu.verify.soundness import Soundness, Violation

            return Soundness(ok=False, violations=[
                Violation(kind="dep", a="x", b="y", a_pos=0, b_pos=1)])

    class Inner:
        calls = 0

        def benchmark(self, order, opts=None):
            self.calls += 1
            return BenchResult.from_times([1.0])

    g = _spmv_graph()
    plat = Platform.make_n_lanes(2)
    inner = Inner()
    res = explore(g, plat, inner, MctsOpts(n_iters=4, seed=1,
                                           verify=RejectAll()))
    assert res.sims == [] and inner.calls == 0
    res = dfs_explore(g, plat, inner, DfsOpts(max_seqs=5,
                                              verify=RejectAll()))
    assert res.sims == [] and inner.calls == 0
    with pytest.raises(RuntimeError, match="incumbent"):
        hill_climb(g, plat, inner, phases=("spmv",),
                   opts=LocalOpts(budget=2, verify=RejectAll()))
    assert inner.calls == 0
