"""Drain-fleet acceptance (ISSUE 14): N daemons work-stealing one
queue — zero double-runs by the lease protocol, drain-rate scaling
measured, the audit mined from the fleet's own status documents.

The scaling test drives real :class:`DrainDaemon` instances through
the shipped :func:`stub_spawner` (fixed-cost 0.5s drains — the
device-wait-dominated regime, deterministic, no device), through the
same :func:`run_fleet` / :func:`measure_scaling` entry points the CLI
uses.  The subprocess launcher's argv is golden-checked so the CLI and
the harness cannot drift.
"""

import json
import os

from tenzing_tpu.bench.driver import DriverRequest
from tenzing_tpu.serve.fingerprint import fingerprint_of
from tenzing_tpu.serve.fleet import (
    FleetOpts,
    _daemon_cmd,
    audit_completions,
    copy_queue_items,
    measure_scaling,
    run_fleet,
    stub_spawner,
)
from tenzing_tpu.serve.store import WorkQueue


def _enqueue_n(qdir, n=4):
    """n distinct spmv work items (distinct m -> distinct digests)."""
    q = WorkQueue(qdir)
    fps = []
    for i in range(n):
        req = DriverRequest(workload="spmv", m=512 + 200 * i)
        fp = fingerprint_of(req)
        q.enqueue(fp, req.to_json(), reason="cold")
        fps.append(fp)
    return q, fps


def test_fleet_scaling_two_daemons_four_items(tmp_path):
    """THE fleet acceptance: 2 daemons drain a 4-item queue with zero
    double-runs and a measured drain rate >= 1.5x the single-daemon
    rate on the same queue (the items are identical per rung)."""
    src = str(tmp_path / "src-q")
    _enqueue_n(src, n=4)
    # heartbeat 0.5: every heartbeat is a status + snapshot fsync pair
    # per member, and fsync jitter on a noisy host is the main
    # wall-clock noise this measurement fights
    opts = FleetOpts(queue_dir=src, store_path="",  # per-rung stores
                     idle_exit_secs=0.25, poll_secs=0.05,
                     heartbeat_secs=0.5, owner_prefix="t")
    # 1.0s fixed-cost drains: the scaling signal (seconds) must dwarf
    # host jitter; wall-clock outcomes retry up to 4 times (a stalled
    # rung on an oversubscribed CI host is not the protocol property
    # under test) — correctness assertions (exactly-once, full drain)
    # hold on EVERY attempt, never retried past
    for attempt in range(4):
        doc = measure_scaling(opts, [1, 2],
                              str(tmp_path / f"scale{attempt}"),
                              log=lambda m: None,
                              spawn=stub_spawner(1.0),
                              drain_label="stub:1.0s")
        assert doc["kind"] == "drain_fleet_scaling"
        assert doc["drain"] == "stub:1.0s"
        assert doc["double_runs_total"] == 0
        by_n = {r["n_daemons"]: r for r in doc["rungs"]}
        for n in (1, 2):
            r = by_n[n]
            assert r["drained"] == 4, r
            assert r["queue_after"] == 0, r
            assert r["double_runs"] == {}, r
            assert r["audit_complete"] is True
        # scheduling-dependent outcomes (participation, wall-clock
        # speedup) are retry-guarded together: a noisy host can stall
        # one member's thread start or a rung's wall clock, and neither
        # is the protocol property under test
        two = by_n[2]
        owners = {o for owners_ in two["completed_by"].values()
                  for o in owners_}
        if len(owners) == 2 and two["speedup_vs_n1"] >= 1.5:
            break
    assert len(owners) == 2, two["completed_by"]
    assert two["speedup_vs_n1"] >= 1.5, doc


def test_fleet_single_run_audit_and_rates(tmp_path):
    qdir = str(tmp_path / "q")
    _enqueue_n(qdir, n=3)
    opts = FleetOpts(queue_dir=qdir,
                     store_path=str(tmp_path / "store.json"),
                     n=2, idle_exit_secs=0.25, poll_secs=0.05,
                     heartbeat_secs=0.1, owner_prefix="s")
    doc = run_fleet(opts, spawn=stub_spawner(0.2), log=lambda m: None)
    assert doc["items_before"] == 3 and doc["drained"] == 3
    assert doc["queue_after"] == 0
    assert doc["double_runs"] == {}
    assert doc["drain_rate_per_s"] > 0
    assert len(doc["daemons"]) == 2
    assert all(d["rc"] == 0 for d in doc["daemons"])
    # every completion attributed to exactly one owner
    assert sorted(doc["completed_by"]) == sorted(
        fp.exact_digest for fp in _enqueue_n(str(tmp_path / "ref"), 3)[1])
    assert all(len(v) == 1 for v in doc["completed_by"].values())


def test_audit_flags_double_runs(tmp_path):
    """A fabricated pair of status docs claiming the same exact digest
    completed twice must surface in double_runs — the audit is the
    exactly-once proof, so it must actually be able to fail."""
    qdir = str(tmp_path / "q")
    os.makedirs(qdir)
    for owner in ("f-0", "f-1"):
        with open(os.path.join(qdir, f"status-{owner}.json"), "w") as f:
            json.dump({"counters": {"completed": 1},
                       "history": [{"exact": "deadbeef",
                                    "outcome": "completed"}]}, f)
    audit = audit_completions(qdir, ["f-0", "f-1"])
    assert audit["double_runs"] == {"deadbeef": ["f-0", "f-1"]}
    assert audit["audit_complete"] is True
    # a missing status doc demotes the audit to incomplete, not a crash
    audit2 = audit_completions(qdir, ["f-0", "f-1", "f-2"])
    assert audit2["audit_complete"] is False


def test_copy_queue_items_copies_only_items(tmp_path):
    src = str(tmp_path / "src")
    _enqueue_n(src, n=2)
    # protocol litter that must NOT ride along into a fresh rung
    for name in ("lease-aaa.json", "fail-bbb.json", "poison-ccc.json",
                 "status-x.json"):
        with open(os.path.join(src, name), "w") as f:
            f.write("{}")
    os.makedirs(os.path.join(src, "ckpt-ddd"))
    dst = str(tmp_path / "dst")
    assert copy_queue_items(src, dst) == 2
    names = sorted(os.listdir(dst))
    assert len(names) == 2 and all(n.startswith("work-") for n in names)
    # the copies are valid, drainable items
    assert len(WorkQueue(dst)) == 2


def test_daemon_cmd_golden(tmp_path):
    """The member argv: one place (fleet.py _daemon_cmd), golden-checked
    so the subprocess launcher and a hand-reproduced member agree."""
    opts = FleetOpts(queue_dir="Q", store_path="S", n=2,
                     overrides={"mcts_iters": 6},
                     trace_dir=str(tmp_path / "tr"),
                     idle_exit_secs=3.0)
    cmd = _daemon_cmd(opts, 1)
    joined = " ".join(cmd)
    assert "-m tenzing_tpu.serve.daemon" in joined
    assert "--queue Q" in joined and "--store S" in joined
    assert "--owner fleet-1" in joined
    assert "--idle-exit 3.0" in joined
    assert "--override mcts_iters=6" in joined
    assert f"--trace-out {tmp_path / 'tr'}/daemon-1.jsonl" in joined


def test_fleet_items_keep_trace_ids(tmp_path):
    """Items enqueued under a trace context carry it into the fleet
    doc's stitched-per-item accounting (the envelope is what links a
    drain back to the query that caused it)."""
    from tenzing_tpu.obs import context as obs_context
    from tenzing_tpu.serve.fleet import _item_traces

    qdir = str(tmp_path / "q")
    q = WorkQueue(qdir)
    req = DriverRequest(workload="spmv", m=512)
    ctx = obs_context.new_trace()
    q.enqueue(fingerprint_of(req), req.to_json(), reason="cold",
              trace=ctx)
    traces = _item_traces(q)
    fp = fingerprint_of(req)
    assert traces == {fp.exact_digest: ctx.trace_id}


def test_fleet_exit_code_policy():
    """Nonzero on a double run OR a dead member; undrained items are
    data, not failure (a transient-failing item legitimately waits)."""
    from tenzing_tpu.serve.fleet import fleet_exit_code

    ok = {"kind": "drain_fleet", "double_runs": {}, "queue_after": 3,
          "daemons": [{"rc": 0}, {"rc": 0}]}
    assert fleet_exit_code(ok) == 0
    assert fleet_exit_code({**ok, "double_runs": {"x": ["a", "b"]}}) == 1
    assert fleet_exit_code(
        {**ok, "daemons": [{"rc": 0}, {"rc": 1, "error": "boom"}]}) == 1
    scale_ok = {"kind": "drain_fleet_scaling", "double_runs_total": 0,
                "rungs": [{"daemons": [{"rc": 0}]},
                          {"daemons": [{"rc": 0}, {"rc": 0}]}]}
    assert fleet_exit_code(scale_ok) == 0
    assert fleet_exit_code({**scale_ok, "double_runs_total": 1}) == 1
    bad_rung = {**scale_ok,
                "rungs": [{"daemons": [{"rc": -9}]},
                          {"daemons": [{"rc": 0}, {"rc": 0}]}]}
    assert fleet_exit_code(bad_rung) == 1


def test_daemon_cmd_item_timeout_zero_passes_through(tmp_path):
    """--item-timeout 0 means "watchdog disabled" to the daemon; the
    member argv must pass the 0 through, not omit the flag (omission
    silently reinstates the daemon's 3600s default)."""
    opts = FleetOpts(queue_dir="Q", store_path="S",
                     item_timeout_secs=0.0)
    cmd = " ".join(_daemon_cmd(opts, 0))
    assert "--item-timeout 0.0" in cmd
    none_opts = FleetOpts(queue_dir="Q", store_path="S",
                          item_timeout_secs=None)
    assert "--item-timeout" not in " ".join(_daemon_cmd(none_opts, 0))
