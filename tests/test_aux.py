"""Aux subsystems: init gate, reproduce stamp, postprocess analysis, example CLIs.

Reference analogs: init.cpp (notice gate), reproduce.cpp (stamp),
postprocess/postprocess.py (class boundaries + decision-tree rules), examples/
drivers (SURVEY.md §2.3, §5).
"""

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tenzing_tpu.utils import initgate, reproduce

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_init_notice_once(monkeypatch):
    initgate._reset_for_tests()
    monkeypatch.delenv(initgate.ACK_ENV, raising=False)
    buf = io.StringIO()
    initgate.init(stream=buf)
    assert initgate.ACK_ENV in buf.getvalue()
    buf2 = io.StringIO()
    initgate.init(stream=buf2)  # one-shot (reference init.cpp:24-41)
    assert buf2.getvalue() == ""
    assert initgate.is_initialized()


def test_init_ack_silences(monkeypatch):
    initgate._reset_for_tests()
    monkeypatch.setenv(initgate.ACK_ENV, "1")
    buf = io.StringIO()
    initgate.init(stream=buf)
    assert buf.getvalue() == ""


def test_reproduce_stamp():
    buf = io.StringIO()
    line = reproduce.dump_with_cli(["prog", "--flag"], stream=buf)
    d = json.loads(line)
    assert d["argv"] == ["prog", "--flag"]
    assert d["tenzing_tpu"]
    assert "hash" in d["git"]  # tests run inside the repo checkout
    assert buf.getvalue().strip() == line


def _fake_rows(n_fast=20, n_slow=20):
    """Two clear performance classes separated by lane:spmv assignment."""
    rows = []
    idx = 0
    for lane, base in ((0, 1e-4), (1, 5e-4)):
        for i in range(n_fast if lane == 0 else n_slow):
            t = base * (1 + 0.01 * i)
            ops = [
                {"kind": "start", "name": "start"},
                {"kind": "device", "name": "spmv", "lane": lane},
                {"kind": "device", "name": "scatter", "lane": 1 - lane},
                {"kind": "finish", "name": "finish"},
            ]
            cells = [str(idx)] + [repr(t)] * 5 + [repr(0.0)] + [json.dumps(o) for o in ops]
            rows.append("|".join(cells))
            idx += 1
    return "\n".join(rows)


def test_postprocess_classes_and_rules():
    sys.path.insert(0, REPO)
    from postprocess.postprocess import analyze, class_boundaries, load_rows

    text = _fake_rows()
    rows = load_rows(text)
    assert len(rows) == 40 and rows[0]["ops"][1]["name"] == "spmv"
    buf = io.StringIO()
    out = analyze(text, stream=buf)
    assert out["n"] == 40
    assert len(out["boundaries"]) == 1  # the 5x gap, and only it
    assert "lane:" in out["rules"]  # the tree explains the split by a lane feature
    assert "performance classes" in buf.getvalue()


def test_class_boundaries_flat_is_one_class():
    from postprocess.postprocess import class_boundaries

    assert class_boundaries(np.full(100, 3.0)) == []


def test_postprocess_plot_writes_figure(tmp_path):
    """--plot saves the sorted-pct10 class figure (the reference postprocess's
    matplotlib output)."""
    pytest.importorskip("matplotlib")
    from postprocess.postprocess import plot_classes

    out = str(tmp_path / "classes.png")
    plot_classes(np.sort(np.random.default_rng(0).random(20)), [7, 13], out)
    assert os.path.getsize(out) > 1000


def test_example_spmv_dfs_smoke():
    """Tiny end-to-end run of the DFS example CLI on CPU (reference CI runs
    build + CPU subset only, SURVEY.md §4)."""
    p = subprocess.run(
        [sys.executable, "examples/spmv_dfs.py", "--cpu", "--matrix-m", "64",
         "--max-seqs", "4", "--benchmark-iters", "3"],
        capture_output=True, text=True, cwd=REPO, timeout=600,
    )
    assert p.returncode == 0, p.stderr
    lines = [l for l in p.stdout.splitlines() if l.strip()]
    assert lines and all("|" in l for l in lines)
    assert "best:" in p.stderr


def test_example_spmv_mcts_smoke():
    p = subprocess.run(
        [sys.executable, "examples/spmv_mcts.py", "--cpu", "--matrix-m", "64",
         "--mcts-iters", "3", "--benchmark-iters", "3", "--strategy", "Coverage"],
        capture_output=True, text=True, cwd=REPO, timeout=600,
    )
    assert p.returncode == 0, p.stderr
    assert p.stdout.strip()


@pytest.mark.needs_pinned_host
def test_example_moe_mcts_smoke():
    p = subprocess.run(
        [sys.executable, "examples/moe_mcts.py", "--cpu", "--tokens", "32",
         "--experts", "4", "--d-model", "8", "--d-ff", "16", "--chunks", "2",
         "--no-impl-choice", "--mcts-iters", "3", "--benchmark-iters", "3"],
        capture_output=True, text=True, cwd=REPO, timeout=600,
    )
    assert p.returncode == 0, p.stderr
    assert p.stdout.strip()


def test_postprocess_excludes_screen_fidelity_rows():
    """load_rows keeps legacy + fid=full rows and drops fid=screen rows —
    the shared split_fidelity rule (bench.benchmarker) applied to the
    offline analysis."""
    import json as _json

    from postprocess.postprocess import load_rows

    op = _json.dumps({"kind": "device", "name": "a", "lane": 0})
    rows = "\n".join([
        "0|1.0|1.0|1.0|1.0|1.0|0.0|" + op,                  # legacy = full
        "1|2.0|2.0|2.0|2.0|2.0|0.0|fid=screen|" + op,       # dropped
        "2|3.0|3.0|3.0|3.0|3.0|0.0|fid=full|" + op,         # kept
    ])
    out = load_rows(rows)
    assert [r["times"]["pct50"] for r in out] == [1.0, 3.0]
    assert all(r["ops"] == [_json.loads(op)] for r in out)
