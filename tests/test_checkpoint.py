"""fault/checkpoint.py: atomic digest-checked snapshots, the crash-safe
measurement journal, and cache restore (the --resume substrate)."""

import json
import os

import pytest

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    BenchResult,
    CachingBenchmarker,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.fault import (
    CheckpointError,
    JournalingBenchmarker,
    SearchCheckpoint,
    atomic_write_json,
    read_checked_json,
)
from tenzing_tpu.fault.checkpoint import (
    PROVENANCE_DEGRADED,
    PROVENANCE_MEASURED,
)
from tenzing_tpu.models.spmv import SpMVCompound
from tenzing_tpu.solve.dfs import enumerate_schedules


def _graph():
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    return g


@pytest.fixture(scope="module")
def seqs():
    """A few distinct real schedules to journal (device-free)."""
    states = enumerate_schedules(_graph(), Platform.make_n_lanes(2),
                                 max_seqs=6)
    assert len(states) >= 3
    return [st.sequence for st in states]


def _res(t):
    return BenchResult.from_times([t, t * 1.01, t * 0.99])


class CountingBench:
    def __init__(self):
        self.calls = 0

    def benchmark(self, order, opts=None):
        self.calls += 1
        return _res(5.0)


# -- atomic envelope --------------------------------------------------------

def test_atomic_write_round_trips(tmp_path):
    path = str(tmp_path / "state.json")
    atomic_write_json(path, {"a": 1, "nested": {"b": [1, 2]}})
    assert read_checked_json(path) == {"a": 1, "nested": {"b": [1, 2]}}
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


def test_corrupt_digest_raises(tmp_path):
    path = str(tmp_path / "state.json")
    atomic_write_json(path, {"a": 1})
    doc = json.load(open(path))
    doc["payload"]["a"] = 2  # tamper without updating the digest
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(CheckpointError, match="digest"):
        read_checked_json(path)


def test_version_mismatch_raises(tmp_path):
    path = str(tmp_path / "state.json")
    atomic_write_json(path, {"a": 1})
    doc = json.load(open(path))
    doc["version"] = 999
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(CheckpointError, match="version"):
        read_checked_json(path)


def test_truncated_file_raises(tmp_path):
    path = tmp_path / "state.json"
    atomic_write_json(str(path), {"a": 1})
    path.write_text(path.read_text()[:-10])  # torn write simulation
    with pytest.raises(CheckpointError):
        read_checked_json(str(path))


# -- state snapshots --------------------------------------------------------

def test_save_state_merge_semantics(tmp_path):
    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    ck.save_state(config={"workload": "spmv"})
    ck.save_state(mcts={"it": 3})
    ck.save_state(mcts={"it": 4}, done=True)
    got = SearchCheckpoint(str(tmp_path / "ckpt")).load_state()
    assert got == {"config": {"workload": "spmv"}, "mcts": {"it": 4},
                   "done": True}


def test_load_state_absent_is_none(tmp_path):
    assert SearchCheckpoint(str(tmp_path / "ckpt")).load_state() is None


# -- measurement journal ----------------------------------------------------

def test_journal_round_trips_sequences_and_results(tmp_path, seqs):
    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    opts = BenchOpts(n_iters=7, max_retries=2, target_secs=0.01)
    ck.record(seqs[0], opts, _res(1.0))
    ck.record(seqs[1], None, _res(2.0), provenance=PROVENANCE_DEGRADED)
    got = ck.load_measurements(_graph())
    assert len(got) == 2
    (s0, o0, r0, p0), (s1, o1, r1, p1) = got
    assert o0 == opts and o1 is None
    assert r0.pct50 == 1.0 and r1.pct50 == 2.0  # exact float round-trip
    assert r0.times is not None
    assert p0 == PROVENANCE_MEASURED and p1 == PROVENANCE_DEGRADED
    from tenzing_tpu.core.sequence import canonical_key

    assert canonical_key(s0) == canonical_key(seqs[0])


def test_journal_skips_torn_tail_line(tmp_path, seqs):
    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    ck.record(seqs[0], None, _res(1.0))
    ck.close()
    with open(ck.journal_path, "a") as f:
        f.write('{"opts": null, "prov": "measured", "resu')  # killed mid-write
    notes = []
    got = ck.load_measurements(_graph(), log=notes.append)
    assert len(got) == 1
    assert notes and "skipped" in notes[0]


def test_restore_into_answers_without_device(tmp_path, seqs):
    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    opts = BenchOpts(n_iters=3, max_retries=1, target_secs=0.001)
    ck.record(seqs[0], opts, _res(1.5))
    ck.record(seqs[1], opts, _res(2.5))
    inner = CountingBench()
    cache = CachingBenchmarker(inner)
    n = ck.restore_into(cache, _graph())
    assert n == 2
    # restored schedules never touch the device, results are bit-identical
    assert cache.benchmark(seqs[0], opts).pct50 == 1.5
    assert cache.benchmark(seqs[1], opts).pct50 == 2.5
    assert inner.calls == 0
    # a different fidelity (opts) is a different measurement: device
    other = BenchOpts(n_iters=99)
    cache.benchmark(seqs[0], other)
    assert inner.calls == 1
    # an unseen schedule: device
    cache.benchmark(seqs[2], opts)
    assert inner.calls == 2


def test_restore_skips_non_measured_provenance(tmp_path, seqs):
    """Degraded/model rows journal for the record but must re-measure on a
    healthy resumed device — they are predictions, not measurements."""
    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    ck.record(seqs[0], None, _res(1.0), provenance=PROVENANCE_DEGRADED)
    ck.record(seqs[1], None, _res(2.0), provenance="model")
    ck.record(seqs[2], None, _res(3.0))
    cache = CachingBenchmarker(CountingBench())
    assert ck.restore_into(cache, _graph()) == 1


def test_later_journal_lines_supersede(tmp_path, seqs):
    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    ck.record(seqs[0], None, _res(1.0))
    ck.record(seqs[0], None, _res(9.0))  # re-measured later in the run
    cache = CachingBenchmarker(CountingBench())
    ck.restore_into(cache, _graph())
    assert cache.benchmark(seqs[0], None).pct50 == 9.0


def test_journaling_benchmarker_records_each_measurement(tmp_path, seqs):
    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    inner = CountingBench()
    jb = JournalingBenchmarker(inner, ck)
    opts = BenchOpts()
    jb.benchmark(seqs[0], opts)
    jb.benchmark(seqs[1], opts)
    assert inner.calls == 2
    got = ck.load_measurements(_graph())
    assert len(got) == 2
    assert all(p == PROVENANCE_MEASURED for *_, p in got)


def test_journaling_benchmarker_tags_degraded(tmp_path, seqs):
    class DegradedInner:
        degraded = True

        def was_degraded(self, order):
            return True

        def benchmark(self, order, opts=None):
            return _res(4.0)

    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    JournalingBenchmarker(DegradedInner(), ck).benchmark(seqs[0], None)
    (_, _, _, prov), = ck.load_measurements(_graph())
    assert prov == PROVENANCE_DEGRADED


# -- paired-batch journal + resume (ISSUE 4 satellite) ----------------------

import hashlib

from tenzing_tpu.core.sequence import canonical_key


class SynthBatchBench:
    """Deterministic device stand-in offering the batch protocol: times are
    a pure function of the schedule's canonical identity, so an original
    run and its resume are comparable bit-for-bit."""

    def __init__(self):
        self.calls = 0
        self.batch_calls = 0

    def _t(self, order):
        h = hashlib.sha256(repr(canonical_key(order)).encode()).digest()
        return 1.0 + int.from_bytes(h[:8], "big") / float(1 << 64)

    def benchmark(self, order, opts=None):
        self.calls += 1
        t = self._t(order)
        return BenchResult.from_times([t, t, t])

    def benchmark_batch_times(self, orders, opts=None, seed=0,
                              times_out=None):
        self.batch_calls += 1
        times = [[self._t(o)] * 4 for o in orders]
        if times_out is not None:
            for dst, src in zip(times_out, times):
                dst.clear()
                dst.extend(src)
            return times_out
        return times


def test_batch_journal_round_trips(tmp_path, seqs):
    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    opts = BenchOpts(n_iters=4)
    ck.record_batch(["ida", "idb"], opts, 17, [[1.0, 2.0], [3.0, 4.0]])
    ck.record_batch(["ida", "idb"], opts, 18, [[5.0], [6.0]])
    got = SearchCheckpoint(str(tmp_path / "ckpt")).load_batches()
    key = (("ida", "idb"), 17, (opts.n_iters, opts.max_retries,
                                opts.target_secs))
    assert got[key] == [[1.0, 2.0], [3.0, 4.0]]
    assert len(got) == 2
    # measurement loader skips batch lines without noise
    assert SearchCheckpoint(str(tmp_path / "ckpt")).load_measurements(
        _graph()) == []


def test_journaling_batch_replayed_on_resume(tmp_path, seqs):
    from tenzing_tpu.bench.benchmarker import schedule_id

    ck = SearchCheckpoint(str(tmp_path / "ckpt"))
    inner1 = SynthBatchBench()
    jb1 = JournalingBenchmarker(inner1, ck)
    opts = BenchOpts(n_iters=2)
    t1 = jb1.benchmark_batch_times(seqs[:2], opts, seed=9)
    assert inner1.batch_calls == 1
    # same key, same process: answered from the in-memory batch cache
    assert jb1.benchmark_batch_times(seqs[:2], opts, seed=9) == t1
    assert inner1.batch_calls == 1
    # a different seed is a different decorrelation draw: re-measured
    jb1.benchmark_batch_times(seqs[:2], opts, seed=10)
    assert inner1.batch_calls == 2

    # restart: restore_into finds the JournalingBenchmarker on the chain
    ck2 = SearchCheckpoint(str(tmp_path / "ckpt"))
    inner2 = SynthBatchBench()
    jb2 = JournalingBenchmarker(inner2, ck2)
    bench2 = CachingBenchmarker(jb2)
    ck2.restore_into(bench2, _graph())
    out = jb2.benchmark_batch_times(seqs[:2], opts, seed=9,
                                    times_out=[[], []])
    assert out == t1
    assert inner2.batch_calls == 0  # replayed, not re-run


def test_resumed_paired_climb_runs_zero_batches(tmp_path):
    """The ROADMAP paired-resume item: a resumed paired hill-climb answers
    its incumbent measurement from the journal and EVERY accept batch from
    the batch journal — 0 compiles, 0 device batches — and reconstructs the
    identical accepted chain."""
    from tenzing_tpu.solve.local import LocalOpts, hill_climb

    g = _graph()
    plat = Platform.make_n_lanes(2)
    phases = ("scatter", "exchange", "spmv", "y_add")
    # budget generous enough that the climb CONVERGES (a full sweep with no
    # improvement) instead of stopping mid-sweep on budget: a converged
    # climb replays to the identical end state with nothing left to try
    lopts = dict(budget=200, paired=True, seed=11,
                 bench_opts=BenchOpts(n_iters=2))

    ckdir = str(tmp_path / "ckpt")
    ck1 = SearchCheckpoint(ckdir)
    inner1 = SynthBatchBench()
    bench1 = CachingBenchmarker(JournalingBenchmarker(inner1, ck1))
    res1 = hill_climb(g, plat, bench1, phases,
                      opts=LocalOpts(checkpoint=ck1, **lopts))
    assert inner1.batch_calls > 0  # the climb genuinely ran accept batches

    ck2 = SearchCheckpoint(ckdir)
    inner2 = SynthBatchBench()
    bench2 = CachingBenchmarker(JournalingBenchmarker(inner2, ck2))
    restored = ck2.restore_into(bench2, g)
    assert restored > 0
    res2 = hill_climb(g, plat, bench2, phases,
                      opts=LocalOpts(checkpoint=ck2, **lopts))

    assert inner2.calls == 0  # zero compiles / measurements
    assert inner2.batch_calls == 0  # zero accept batches re-run
    assert canonical_key(res2.final.order) == canonical_key(res1.final.order)
    assert [s.result.pct50 for s in res2.sims] == \
        [s.result.pct50 for s in res1.sims]
    # the climb cursor round-tripped through the snapshot
    assert SearchCheckpoint(ckdir).load_state()["climb"]["n_sims"] == \
        len(res2.sims)
