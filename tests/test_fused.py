"""Megakernel fusion backend (runtime/fused.py): region partitioning with
hand-computed boundaries, fused-vs-stepped equality on CPU interpret mode
(bit-level where deterministic, allclose under re-associating tilings),
searchable tile decision nodes through all three solvers, and roofline
pruning of the tile menu."""

import numpy as np
import pytest

import jax.numpy as jnp

from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
from tenzing_tpu.bench.roofline import Cost, prune_tilings
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.state import State
from tenzing_tpu.core.sync_ops import (
    EventRecord,
    EventSync,
    LaneSync,
    WaitEvent,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.runtime.fused import (
    FusedExecutor,
    FuseTile,
    FuseTileChoice,
    partition_regions,
    region_axes,
    region_tile_counts,
    tiles_of,
    with_tile_menu,
)
from tenzing_tpu.verify import verify_schedule


class RowScale(DeviceOp):
    """Row-independent toy op: out = 2 * a (tiled along axis 0)."""

    def __init__(self, name, a, out):
        super().__init__(name)
        self._a, self._out = a, out

    def reads(self):
        return [self._a]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: bufs[self._a] * 2.0}

    def fusible(self):
        return True

    def fuse_tiling(self):
        return {self._a: 0, self._out: 0}


class RowSum(DeviceOp):
    """Row-independent reduce: out[i] = sum(a[i, :]) + b[i]."""

    def __init__(self, name, a, b, out):
        super().__init__(name)
        self._a, self._b, self._out = a, b, out

    def reads(self):
        return [self._a, self._b]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: jnp.sum(bufs[self._a], axis=1) + bufs[self._b]}

    def fusible(self):
        return True

    def fuse_tiling(self):
        return {self._a: 0, self._b: 0, self._out: 0}


class Unfusible(DeviceOp):
    """A compute op that never opted into fusion (default protocol)."""

    def __init__(self, name, a, out):
        super().__init__(name)
        self._a, self._out = a, out

    def reads(self):
        return [self._a]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: bufs[self._a] + 1.0}


def _members(segments):
    return [[m.name() for m in seg.members]
            for kind, seg in segments if kind == "region"]


class TestPartitioner:
    """Hand-computed fusion boundaries."""

    def test_single_lane_schedule_fuses_to_one_region(self):
        l0 = Lane(0)
        ops = [RowScale("a", "x", "y").bind(l0),
               EventRecord(l0, Event(0)),  # outgoing snapshot: deferred
               RowScale("b", "y", "z").bind(l0),
               LaneSync(l0)]  # trailing host sync: boundary after the run
        segs = partition_regions(ops)
        assert _members(segs) == [["a", "b"]]
        kinds = [k for k, _ in segs]
        assert kinds == ["region", "op", "op"]  # fused, deferred rec, sync
        assert isinstance(segs[1][1], EventRecord)

    def test_cross_lane_sync_splits_region(self):
        l0, l1 = Lane(0), Lane(1)
        e = Event(0)
        ops = [RowScale("a", "x", "y").bind(l0),
               EventRecord(l0, e),
               WaitEvent(l1, e),  # incoming wait: boundary
               RowScale("b", "y", "z").bind(l1)]
        segs = partition_regions(ops)
        assert _members(segs) == [["a"], ["b"]]

    def test_comm_op_splits_region(self):
        from tenzing_tpu.ops.comm_ops import HostSpillStart

        l0 = Lane(0)
        ops = [RowScale("a", "x", "y").bind(l0),
               HostSpillStart("spill", "y", "h"),
               RowScale("b", "x", "z").bind(l0)]
        segs = partition_regions(ops)
        assert _members(segs) == [["a"], ["b"]]
        # and the host-resident buffer the spill produced stays unfusible
        ops2 = ops[:2] + [RowScale("c", "h", "z").bind(l0)]
        segs2 = partition_regions(ops2)
        assert _members(segs2) == [["a"]]  # c reads host-space h: unfused

    def test_unfusible_op_splits_region(self):
        l0 = Lane(0)
        ops = [RowScale("a", "x", "y").bind(l0),
               Unfusible("u", "y", "w").bind(l0),
               RowScale("b", "w", "z").bind(l0)]
        segs = partition_regions(ops)
        assert _members(segs) == [["a"], ["b"]]

    def test_multi_lane_independent_chains_fuse_together(self):
        # no syncs between the lanes => no cross-lane deps by soundness
        l0, l1 = Lane(0), Lane(1)
        ops = [RowScale("a0", "x", "y").bind(l0),
               RowScale("b0", "u", "v").bind(l1),
               RowScale("a1", "y", "z").bind(l0)]
        segs = partition_regions(ops)
        assert _members(segs) == [["a0", "b0", "a1"]]
        region = segs[0][1]
        assert [l.id for l in region.lanes()] == [0, 1]

    def test_min_ops_replays_small_runs_unfused(self):
        l0 = Lane(0)
        ops = [RowScale("a", "x", "y").bind(l0), LaneSync(l0)]
        segs = partition_regions(ops, min_ops=2)
        assert _members(segs) == []
        assert [type(s).__name__ for _, s in segs] == \
            ["BoundDeviceOp", "LaneSync"]


class TestTiling:
    def test_region_axes_consistent(self):
        l0 = Lane(0)
        segs = partition_regions([RowScale("a", "x", "y").bind(l0),
                                  RowScale("b", "y", "z").bind(l0)])
        axes = region_axes(segs[0][1])
        assert axes == {"x": 0, "y": 0, "z": 0}

    def test_region_axes_mismatch_disables_tiling(self):
        class FullReader(RowScale):
            def fuse_tiling(self):
                return {self._a: None, self._out: 0}

        l0 = Lane(0)
        # "y" is written tiled by a but read FULL by b: no decomposition
        segs = partition_regions([RowScale("a", "x", "y").bind(l0),
                                  FullReader("b", "y", "z").bind(l0)])
        assert region_axes(segs[0][1]) is None
        assert region_tile_counts(segs[0][1], {"x": (8,), "y": (8,),
                                               "z": (8,)}) == [1]

    def test_tile_counts_divide_every_extent(self):
        l0 = Lane(0)
        segs = partition_regions([RowScale("a", "x", "y").bind(l0)])
        region = segs[0][1]
        assert region_tile_counts(region, {"x": (12, 4), "y": (12, 4)}) \
            == [1, 2, 4]  # 8 does not divide 12
        assert region_tile_counts(region, {"x": (16, 4), "y": (16, 4)}) \
            == [1, 2, 4, 8, 16]

    def test_prune_tilings_floor_ceiling_and_fallback(self):
        # 8 MiB of traffic: t=2 leaves 4 MiB/tile (fine at 1 MiB floor),
        # t=16 leaves 0.5 MiB (under the floor: cannot help)
        c = Cost(flops=0.0, hbm_bytes=8 * 2**20)
        assert prune_tilings(c, [1, 2, 16]) == [1, 2]
        # vmem ceiling: per-tile working set must fit
        assert prune_tilings(c, [1, 2], vmem_bytes=2 * 2**20) == [1]
        # 1 always survives, even alone
        assert prune_tilings(Cost(0.0, 10.0), [1, 2, 4]) == [1]


class TestTileDecisionNodes:
    """Tile counts as ordinary choice-graph decisions, searched by all
    three solvers against a FusedExecutor-backed benchmark."""

    def _workload(self, m=16, k=8):
        g = Graph()
        a = RowScale("sc", "x", "y")
        b = RowSum("rs", "y", "bias", "out")
        g.start_then(a)
        g.then(a, b)
        g.then_finish(b)
        g = with_tile_menu(g, [1, 2, 4])
        bufs = {
            "x": jnp.asarray(np.random.default_rng(0).random((m, k)),
                             jnp.float32),
            "y": jnp.zeros((m, k), jnp.float32),
            "bias": jnp.ones((m,), jnp.float32),
            "out": jnp.zeros((m,), jnp.float32),
        }
        return g, bufs

    def test_directive_rides_schedule_and_projects(self):
        g, bufs = self._workload()
        plat = Platform.make_n_lanes(1)
        st = State(g)
        # drive to terminal, preferring the t=2 choice
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            pick = next((d for d in ds
                         if getattr(d, "choice", None) is not None
                         and d.choice.name().endswith(".t2")), ds[0])
            st = st.apply(pick)
        seq = st.sequence
        assert tiles_of(seq) == 2
        verdict = verify_schedule(seq, g)
        assert verdict.ok, verdict.witness()
        # the fused executor honors the searched directive
        ex = TraceExecutor(plat, bufs)
        fex = FusedExecutor(ex, min_tile_bytes=0)
        plan = fex.plan(seq)
        assert plan.tiles_requested == 2
        assert [r.tiles for r in plan.regions] == [2]

    def test_serdes_roundtrip_of_directive(self):
        from tenzing_tpu.core.serdes import (
            sequence_from_json,
            sequence_to_json,
        )

        g, _ = self._workload()
        seq = Sequence([FuseTile(4)])
        back = sequence_from_json(sequence_to_json(seq), g)
        assert tiles_of(back) == 4

    def test_dfs_enumerates_tile_alternatives(self):
        from tenzing_tpu.solve.dfs import DfsOpts, explore

        g, bufs = self._workload()
        plat = Platform.make_n_lanes(1)
        ex = TraceExecutor(plat, bufs)
        bench = EmpiricalBenchmarker(FusedExecutor(ex, min_tile_bytes=0))
        res = explore(g, plat, bench,
                      DfsOpts(max_seqs=64, dump_csv_path="/dev/null",
                              bench_opts=BenchOpts(n_iters=2,
                                                   target_secs=0.0002)))
        seen = {tiles_of(s.order) for s in res.sims}
        assert seen == {1, 2, 4}

    def test_hill_climb_searches_tiles(self):
        from tenzing_tpu.solve.local import LocalOpts, hill_climb

        g, bufs = self._workload()
        plat = Platform.make_n_lanes(1)
        ex = TraceExecutor(plat, bufs)
        bench = EmpiricalBenchmarker(FusedExecutor(ex, min_tile_bytes=0))

        def prefer(op_name, choices):
            return next((c for c in choices if c.endswith(".t1")), None)

        res = hill_climb(
            g, plat, bench, phases=("sc", "rs"), prefer=prefer,
            opts=LocalOpts(budget=6, seed=0,
                           bench_opts=BenchOpts(n_iters=2,
                                                target_secs=0.0002)))
        assert res.sims
        seen = {tiles_of(s.order) for s in res.sims}
        assert 1 in seen and len(seen) >= 2  # flip moves explored the menu

    def test_mcts_searches_tiles(self):
        from tenzing_tpu.solve.mcts import MctsOpts, explore

        g, bufs = self._workload()
        plat = Platform.make_n_lanes(1)
        ex = TraceExecutor(plat, bufs)
        bench = EmpiricalBenchmarker(FusedExecutor(ex, min_tile_bytes=0))
        res = explore(g, plat, bench,
                      MctsOpts(n_iters=10, seed=3,
                               bench_opts=BenchOpts(n_iters=2,
                                                    target_secs=0.0002),
                               screen_opts=BenchOpts(n_iters=2,
                                                     target_secs=0.0002)))
        seen = {tiles_of(s.order) for s in res.sims}
        assert len(seen) >= 2

    def test_fused_results_match_unfused_for_every_tile(self):
        g, bufs = self._workload()
        plat = Platform.make_n_lanes(1)
        ex = TraceExecutor(plat, bufs)
        for want in (1, 2, 4):
            st = State(g)
            while not st.is_terminal():
                ds = st.get_decisions(plat)
                pick = next((d for d in ds
                             if getattr(d, "choice", None) is not None
                             and d.choice.name().endswith(f".t{want}")),
                            ds[0])
                st = st.apply(pick)
            out_s = ex.run(st.sequence)
            out_f = FusedExecutor(ex, min_tile_bytes=0).run(st.sequence)
            for name in out_s:
                np.testing.assert_allclose(
                    np.asarray(out_f[name]), np.asarray(out_s[name]),
                    rtol=1e-6)


def _naive(graph, n_lanes=1):
    plat = Platform.make_n_lanes(n_lanes)
    st = State(graph)
    while not st.is_terminal():
        st = st.apply(st.get_decisions(plat)[0])
    return st.sequence, plat


class TestFusedVsSteppedAttn:
    """CPU interpret-mode equality on the attn workload."""

    def _setup(self):
        from tenzing_tpu.models.ring_attention import (
            BlockedAttention,
            RingAttnArgs,
            make_blocked_buffers,
        )

        aargs = RingAttnArgs(n_devices=4, batch=1, seq_local=16, head_dim=8)
        bufs, want = make_blocked_buffers(aargs, seed=0)
        g = Graph()
        op = BlockedAttention(aargs)
        g.start_then(op)
        g.then_finish(op)
        seq, plat = _naive(g)
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        return g, seq, ex, want

    def test_single_tile_bit_identical(self):
        g, seq, ex, _ = self._setup()
        fex = FusedExecutor(ex, min_tile_bytes=0)
        plan = fex.plan(seq)
        assert len(plan.regions) == 1
        assert plan.regions[0].n_ops == 5  # 4 folds + finalize
        out_s, out_f = ex.run(seq), fex.run(seq)
        for name in out_s:
            assert np.array_equal(np.asarray(out_s[name]),
                                  np.asarray(out_f[name])), name

    def test_tiled_allclose_and_correct(self):
        g, seq, ex, want = self._setup()
        out_s = ex.run(seq)
        for t in (2, 4):
            fex = FusedExecutor(ex, tiles=t, min_tile_bytes=0)
            assert [r.tiles for r in fex.plan(seq).regions] == [t]
            out_f = fex.run(seq)
            for name in out_s:
                np.testing.assert_allclose(
                    np.asarray(out_f[name]), np.asarray(out_s[name]),
                    rtol=1e-5, atol=1e-6, err_msg=name)
            np.testing.assert_allclose(np.asarray(out_f["O"]), want,
                                       rtol=1e-3, atol=1e-4)

    def test_invalid_tile_request_falls_back_to_divisor(self):
        g, seq, ex, _ = self._setup()
        # n=64 rows: 64 % 3 != 0 is unreachable via power-of-two menus, but
        # an explicit weird request must degrade to its best valid divisor
        fex = FusedExecutor(ex, tiles=6, min_tile_bytes=0)
        assert [r.tiles for r in fex.plan(seq).regions] == [2]  # 2 | 6

    def test_verifier_passes_original_schedule(self):
        g, seq, ex, _ = self._setup()
        assert verify_schedule(seq, g).ok


class TestFusedVsSteppedSpmv:
    """CPU equality on the spmv workload (local exchange, tiling collapses
    to 1 because x_remote is written tiled but gathered whole)."""

    def _setup(self):
        from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers

        bufs, want = make_spmv_buffers(m=64, nnz_per_row=4, seed=1)
        g = Graph()
        op = SpMVCompound(exchange="local")
        g.start_then(op)
        g.then_finish(op)
        seq, plat = _naive(g)
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        return g, seq, ex, want

    def test_one_region_menu_collapses_to_single_tile(self):
        g, seq, ex, _ = self._setup()
        fex = FusedExecutor(ex, min_tile_bytes=0)
        plan = fex.plan(seq)
        assert len(plan.regions) == 1
        assert plan.regions[0].n_ops == 5
        # exchange writes x_remote tiled, spmv_remote gathers it whole:
        # the region admits no common decomposition
        assert plan.tile_menu == [1]

    def test_bit_identical_and_correct(self):
        g, seq, ex, want = self._setup()
        out_s = ex.run(seq)
        out_f = FusedExecutor(ex, min_tile_bytes=0).run(seq)
        for name in out_s:
            assert np.array_equal(np.asarray(out_s[name]),
                                  np.asarray(out_f[name])), name
        np.testing.assert_allclose(np.asarray(out_f["y"]), want, rtol=1e-4)

    def test_two_lane_searched_schedule_fused_matches(self):
        from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers
        from tenzing_tpu.core.schedule import make_schedules_random

        bufs, want = make_spmv_buffers(m=32, nnz_per_row=3, seed=2)
        g = Graph()
        op = SpMVCompound(exchange="local")
        g.start_then(op)
        g.then_finish(op)
        plat = Platform.make_n_lanes(2)
        jbufs = {k: jnp.asarray(v) for k, v in bufs.items()}
        # several random legal schedules through the full decision process
        import random

        rng = random.Random(7)
        for trial in range(3):
            st = State(g)
            while not st.is_terminal():
                ds = st.get_decisions(plat)
                st = st.apply(ds[rng.randrange(len(ds))])
            seq = st.sequence
            assert verify_schedule(seq, g).ok
            ex = TraceExecutor(plat, jbufs)
            out_s = ex.run(seq)
            out_f = FusedExecutor(ex, min_tile_bytes=0).run(seq)
            for name in out_s:
                np.testing.assert_allclose(
                    np.asarray(out_f[name]), np.asarray(out_s[name]),
                    rtol=1e-6, err_msg=f"trial {trial} {name}")


class TestBenchmarkPath:
    def test_prepare_n_and_caching(self):
        g, bufs = TestTileDecisionNodes()._workload()
        seq, plat = _naive(g)
        ex = TraceExecutor(plat, bufs)
        fex = FusedExecutor(ex, min_tile_bytes=0)
        run_n = fex.prepare_n(seq)
        run_n(2)
        c0 = ex.compile_count
        # plan + program both cached: repeat costs no new compile
        run_n2 = fex.prepare_n(seq)
        run_n2(2)
        assert ex.compile_count == c0
        assert fex.plan(seq) is fex.plan(seq)

    def test_fused_timeline_has_fewer_units(self):
        """The attribution join the driver stamps: the fused sequence's
        stepped program has one unit per region, so its sum-of-parts can
        only shed dispatch overhead."""
        g, seq, ex, _ = TestFusedVsSteppedAttn()._setup()
        fex = FusedExecutor(ex, min_tile_bytes=0)
        fseq = fex.fused_order(seq)
        stepped_units = [p for p, fn in ex.op_stepped(seq) if fn is not None]
        fused_units = [p for p, fn in ex.op_stepped(fseq) if fn is not None]
        assert len(fused_units) < len(stepped_units)
        out_s = ex.run(seq)
        out_f = ex.run(fseq)  # the fused order runs through the inner too
        for name in out_s:
            assert np.array_equal(np.asarray(out_s[name]),
                                  np.asarray(out_f[name])), name


class TestTileMenuGraph:
    def test_with_tile_menu_forces_directive_first(self):
        g, _ = TestTileDecisionNodes()._workload()
        plat = Platform.make_n_lanes(1)
        st = State(g)
        # the only frontier decisions at the root resolve/execute the menu
        # (plus compound expansion), never a device op
        names_before_directive = []
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            st = st.apply(ds[0])
            ops = [o.name() for o in st.sequence
                   if not o.name().startswith("start")]
            if any(n.startswith("fuse_tile.") for n in ops):
                break
            names_before_directive = ops
        assert all(n.startswith("fuse_tile") or n == "start"
                   for n in names_before_directive) or \
            names_before_directive == []

    def test_choice_lists_menu(self):
        c = FuseTileChoice([1, 2, 8])
        assert [o.name() for o in c.choices()] == \
            ["fuse_tile.t1", "fuse_tile.t2", "fuse_tile.t8"]
        with pytest.raises(ValueError):
            FuseTileChoice([])

    def test_tiles_of_default(self):
        assert tiles_of(Sequence([])) == 1


class TestSyncSoundness:
    def test_deferred_record_overwaits_never_underwaits(self):
        """An EventRecord inside a region is re-emitted after the fused op:
        the downstream consumer then waits for the WHOLE region — more
        than before, never less.  Numerics must be unchanged."""
        l0, l1 = Lane(0), Lane(1)
        e = Event(0)
        ops = [RowScale("a", "x", "y").bind(l0),
               EventRecord(l0, e),
               RowScale("b", "y", "z").bind(l0),
               WaitEvent(l1, e),
               RowScale("c", "z", "w").bind(l1)]
        seq = Sequence(ops)
        bufs = {"x": jnp.ones((4, 4)), "y": jnp.zeros((4, 4)),
                "z": jnp.zeros((4, 4)), "w": jnp.zeros((4, 4))}
        plat = Platform.make_n_lanes(2)
        ex = TraceExecutor(plat, bufs)
        fex = FusedExecutor(ex, min_tile_bytes=0)
        segs = partition_regions(seq.vector())
        # wait splits: [a, b] fuse (record deferred past them), c alone
        assert _members(segs) == [["a", "b"], ["c"]]
        out_s, out_f = ex.run(seq), fex.run(seq)
        for name in out_s:
            assert np.array_equal(np.asarray(out_s[name]),
                                  np.asarray(out_f[name])), name
