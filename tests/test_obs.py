"""Telemetry subsystem (ISSUE 1): tracer span nesting/attributes, JSONL
round-trip, Chrome trace-event schema validity, metrics percentiles,
disabled-tracer no-op, counters shim, progress reporter."""

import io
import json
import time

import pytest

from tenzing_tpu.obs.export import (
    chrome_trace,
    read_jsonl,
    to_jsonl,
    write_chrome_trace,
    write_jsonl,
)
from tenzing_tpu.obs.metrics import MetricsRegistry, get_metrics, set_metrics
from tenzing_tpu.obs.progress import ProgressReporter, set_reporter
from tenzing_tpu.obs.tracer import Tracer, get_tracer, set_tracer
from tenzing_tpu.utils.counters import Counters
from tenzing_tpu.utils.numeric import percentile


@pytest.fixture
def tracer():
    """A fresh enabled tracer installed as the process-global one."""
    tr = Tracer(enabled=True)
    prev = set_tracer(tr)
    try:
        yield tr
    finally:
        set_tracer(prev)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        yield reg
    finally:
        set_metrics(prev)


# -- tracer ----------------------------------------------------------------

def test_span_nesting_and_attributes(tracer):
    with tracer.span("outer", a=1) as outer:
        with tracer.span("inner") as inner:
            inner.set("b", 2)
        outer.set("done", True)
    spans = {s.name: s for s in tracer.spans()}
    assert set(spans) == {"outer", "inner"}
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["outer"].parent_id is None
    assert spans["outer"].attrs == {"a": 1, "done": True}
    assert spans["inner"].attrs == {"b": 2}
    # inner closed first and fits inside outer
    assert spans["inner"].ts_us >= spans["outer"].ts_us
    assert spans["inner"].dur_us <= spans["outer"].dur_us


def test_sibling_spans_share_parent(tracer):
    with tracer.span("p") as p:
        with tracer.span("c1"):
            pass
        with tracer.span("c2"):
            pass
    spans = {s.name: s for s in tracer.spans()}
    assert spans["c1"].parent_id == spans["c2"].parent_id == p.span_id


def test_events_and_rank_tagging(tracer):
    tracer.set_rank(3)
    tracer.event("hello", x=1)
    with tracer.span("s"):
        pass
    assert tracer.events()[0].pid == 3
    assert tracer.spans()[0].pid == 3


def test_disabled_tracer_is_noop():
    tr = Tracer(enabled=False)
    with tr.span("x", a=1) as sp:
        sp.set("b", 2)  # must not raise
        tr.event("y")
    assert tr.spans() == [] and tr.events() == []
    # near-zero overhead: a disabled span is a shared constant, no recording
    t0 = time.perf_counter()
    for _ in range(10_000):
        with tr.span("hot"):
            pass
    assert time.perf_counter() - t0 < 1.0
    # the disabled path allocates nothing per call
    assert tr.span("a") is tr.span("b")


def test_exception_still_closes_span(tracer):
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("x")
    assert len(tracer.spans()) == 1
    assert tracer.spans()[0].dur_us >= 0


# -- JSONL sink ------------------------------------------------------------

def test_jsonl_round_trip(tracer, tmp_path):
    with tracer.span("s1", k="v"):
        tracer.event("e1", n=7)
    path = str(tmp_path / "trace.jsonl")
    write_jsonl(tracer, path)
    records = read_jsonl(path)
    # timestamp order: the span's ts is its START, before the event inside it
    assert [r["kind"] for r in records] == ["span", "event"]
    ev = next(r for r in records if r["kind"] == "event")
    sp = next(r for r in records if r["kind"] == "span")
    assert ev["name"] == "e1" and ev["attrs"] == {"n": 7}
    assert sp["name"] == "s1" and sp["attrs"] == {"k": "v"}
    assert sp["dur_us"] >= 0 and sp["parent"] is None
    # every line is independently parseable
    lines = to_jsonl(tracer).splitlines()
    assert all(json.loads(line) for line in lines)


# -- Chrome trace-event sink (Perfetto) ------------------------------------

def test_chrome_trace_schema(tracer, tmp_path):
    tracer.set_rank(1)
    with tracer.span("phase.outer", a=1):
        with tracer.span("phase.inner"):
            pass
        tracer.event("marker", m=2)
    doc = chrome_trace(tracer)
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = doc["traceEvents"]
    phs = {e["ph"] for e in events}
    assert phs == {"M", "X", "i"}
    for e in events:
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
            assert e["dur"] >= 0
            assert isinstance(e["args"], dict)
        if e["ph"] == "i":
            assert e["s"] in ("g", "p", "t")
        if e["ph"] == "M":
            # tracks are named (ISSUE 6 satellite): per-rank process rows
            # plus a thread_name row per (pid, tid) so attribution lane
            # tracks and plain spans render as one grouped trace
            assert e["name"] in ("process_name", "thread_name")
            if e["name"] == "process_name":
                assert e["args"]["name"] == "rank 1"
            else:
                assert e["args"]["name"] in ("main", f"thread {e['tid']}")
    # the whole document serializes (what Perfetto actually loads)
    path = str(tmp_path / "trace.json")
    write_chrome_trace(tracer, path)
    loaded = json.load(open(path))
    assert len(loaded["traceEvents"]) == len(events)


def test_chrome_trace_nonfinite_attrs_serialize(tracer, tmp_path):
    with tracer.span("s", obj=object()):
        pass
    write_chrome_trace(tracer, str(tmp_path / "t.json"))  # default=str


# -- metrics ---------------------------------------------------------------

def test_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(0.25)
    for v in [1.0, 2.0, 3.0, 4.0]:
        reg.histogram("h").observe(v)
    doc = reg.to_json()
    assert doc["counters"]["c"] == 5
    assert doc["gauges"]["g"] == 0.25
    h = doc["histograms"]["h"]
    assert h["count"] == 4 and h["sum"] == 10.0
    assert h["min"] == 1.0 and h["max"] == 4.0 and h["mean"] == 2.5
    assert json.dumps(doc)  # serializable as-is


def test_histogram_percentiles_match_numeric():
    reg = MetricsRegistry()
    xs = [float(i) for i in range(1, 101)]
    for v in xs:
        reg.histogram("h").observe(v)
    s = reg.histogram("h").summary()
    xs_sorted = sorted(xs)
    assert s["p50"] == percentile(xs_sorted, 50)
    assert s["p90"] == percentile(xs_sorted, 90)
    assert s["p99"] == percentile(xs_sorted, 99)


def test_empty_histogram_summary():
    assert MetricsRegistry().histogram("h").summary() == {"count": 0,
                                                          "sum": 0.0}


def test_registry_timer():
    reg = MetricsRegistry()
    with reg.timer("t.seconds"):
        pass
    s = reg.histogram("t.seconds").summary()
    assert s["count"] == 1 and s["sum"] >= 0


# -- utils.counters shim over obs.metrics ----------------------------------

def test_counters_shim_legacy_api(registry):
    c = Counters()
    with c.phase("SELECT"):
        pass
    with c.phase("SELECT"):
        pass
    with c.phase("BENCHMARK"):
        pass
    assert set(c.seconds) == {"SELECT", "BENCHMARK"}
    assert c.counts["SELECT"] == 2 and c.counts["BENCHMARK"] == 1
    assert all(v >= 0 for v in c.seconds.values())
    rep = c.report()
    assert rep.startswith("phase counters:")
    assert "SELECT" in rep and "x2" in rep


def test_counters_mirror_into_global_metrics(registry):
    c = Counters(prefix="mcts.phase")
    with c.phase("ROLLOUT"):
        pass
    doc = get_metrics().to_json()
    assert doc["histograms"]["mcts.phase.ROLLOUT.seconds"]["count"] == 1


def test_counters_phases_emit_spans_when_tracing(tracer, registry):
    c = Counters(prefix="dfs.phase")
    with c.phase("BENCHMARK"):
        pass
    with c.phase("DEDUP", span=False):  # hot-loop path stays spanless
        pass
    assert [s.name for s in tracer.spans()] == ["dfs.phase.BENCHMARK"]


def test_counters_isolated_between_instances(registry):
    a, b = Counters(), Counters()
    with a.phase("X"):
        pass
    assert "X" in a.seconds and "X" not in b.seconds


# -- progress reporter -----------------------------------------------------

def test_reporter_writes_stream_and_event_stream(tracer):
    buf = io.StringIO()
    rep = ProgressReporter(stream=buf)
    prev = set_reporter(rep)
    try:
        rep.warn("dfs budget exhausted", variants_left=2)
    finally:
        set_reporter(prev)
    assert buf.getvalue() == "dfs budget exhausted\n"
    evs = tracer.events()
    assert len(evs) == 1 and evs[0].name == "progress.warn"
    assert evs[0].attrs["message"] == "dfs budget exhausted"
    assert evs[0].attrs["variants_left"] == 2


def test_reporter_silent_stream_keeps_events(tracer):
    rep = ProgressReporter(stream=None)
    rep.info("quiet")
    assert tracer.events()[0].attrs["message"] == "quiet"


# -- solver integration: the event/span stream end to end ------------------

def _tiny_graph():
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.operation import NoOp

    g = Graph()
    a, b = NoOp("a"), NoOp("b")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    return g


class _FakePlatform:
    def __init__(self, n=2):
        from tenzing_tpu.core.resources import Lane

        self.lanes = [Lane(i) for i in range(n)]

    def provision_events(self, events):
        pass


class _FakeBench:
    def __init__(self):
        self.calls = 0

    def benchmark(self, order, opts=None):
        from tenzing_tpu.bench.benchmarker import BenchResult

        self.calls += 1
        t = 1.0 / self.calls
        return BenchResult.from_times([t, t, t])


def test_dfs_explore_emits_counters_and_spans(tracer, registry, monkeypatch):
    from tenzing_tpu.solve.dfs import DfsOpts, explore

    monkeypatch.setenv("TENZING_TPU_NATIVE", "0")  # force the Python walk
    res = explore(_tiny_graph(), _FakePlatform(1), _FakeBench(),
                  DfsOpts(max_seqs=4))
    assert res.sims
    assert res.counters is not None
    assert "BENCHMARK" in res.counters.seconds
    assert "SELECT" in res.counters.seconds
    assert "DEDUP" in res.counters.seconds
    names = [s.name for s in tracer.spans()]
    assert "dfs.explore" in names and "dfs.iter" in names
    iter_spans = [s for s in tracer.spans() if s.name == "dfs.iter"]
    assert all("schedule" in s.attrs and "pct50" in s.attrs
               for s in iter_spans)
    doc = get_metrics().to_json()
    assert doc["histograms"]["dfs.phase.BENCHMARK.seconds"]["count"] >= 1


def test_mcts_explore_emits_iteration_spans(tracer, registry):
    from tenzing_tpu.solve.mcts import MctsOpts, explore

    res = explore(_tiny_graph(), _FakePlatform(2), _FakeBench(),
                  MctsOpts(n_iters=6, seed=0, cache_benchmarks=False))
    assert res.sims
    iters = [s for s in tracer.spans() if s.name == "mcts.iter"]
    assert iters
    measured = [s for s in iters if "pct50" in s.attrs]
    assert measured
    assert all("schedule" in s.attrs for s in measured)
    assert any("tree_size" in s.attrs for s in iters)
    # the phase spans nest under the iteration span
    phase = [s for s in tracer.spans() if s.name.startswith("mcts.phase.")]
    ids = {s.span_id for s in iters}
    assert phase and all(s.parent_id in ids for s in phase)


def test_solver_run_exports_valid_bundle(tracer, registry, tmp_path):
    """End-to-end: a solver run's trace exports as schema-valid Chrome JSON
    + JSONL, and the metrics JSON carries solver phase timings — the same
    bundle ``bench.py --trace-out/--metrics-json`` archives."""
    from tenzing_tpu.solve.mcts import MctsOpts, explore

    explore(_tiny_graph(), _FakePlatform(2), _FakeBench(),
            MctsOpts(n_iters=4, seed=1, cache_benchmarks=False))
    write_chrome_trace(tracer, str(tmp_path / "trace.json"))
    doc = json.load(open(tmp_path / "trace.json"))
    assert doc["traceEvents"]
    for e in doc["traceEvents"]:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert "ts" in e and e["dur"] >= 0
    names = {e["name"] for e in doc["traceEvents"]}
    assert "mcts.explore" in names and "mcts.iter" in names
    write_jsonl(tracer, str(tmp_path / "trace.jsonl"))
    kinds = {r["kind"] for r in read_jsonl(str(tmp_path / "trace.jsonl"))}
    assert kinds == {"span"}  # this run emitted no instant events
    metrics = get_metrics().to_json()
    assert json.dumps(metrics)
    assert any(k.startswith("mcts.phase.") for k in metrics["histograms"])


def test_caching_benchmarker_cache_telemetry(tracer, registry):
    from tenzing_tpu.bench.benchmarker import CachingBenchmarker
    from tenzing_tpu.core.operation import NoOp
    from tenzing_tpu.core.sequence import Sequence

    bench = CachingBenchmarker(_FakeBench())
    order = Sequence([NoOp("a")])
    bench.benchmark(order)
    bench.benchmark(order)
    assert bench.hits == 1 and bench.misses == 1
    assert bench.hit_rate == 0.5
    doc = get_metrics().to_json()
    assert doc["counters"]["bench.cache.hits"] == 1
    assert doc["counters"]["bench.cache.misses"] == 1
    assert doc["gauges"]["bench.cache.hit_rate"] == 0.5
    evs = [e for e in tracer.events() if e.name == "bench.cache"]
    assert [e.attrs["hit"] for e in evs] == [False, True]
    assert evs[0].attrs["schedule"] == evs[1].attrs["schedule"]


def test_bench_result_to_json_carries_raw_times():
    from tenzing_tpu.bench.benchmarker import BenchResult

    res = BenchResult.from_times([3.0, 1.0, 2.0])
    res.fetch_overhead = 0.25
    doc = res.to_json()
    assert doc["times"] == [3.0, 1.0, 2.0]  # raw order, not sorted
    assert doc["fetch_overhead"] == 0.25
    # percentiles re-derivable offline from the archived raw series
    assert BenchResult.from_times(doc["times"]).pct50 == res.pct50
    # replayed results without provenance serialize without the keys
    bare = BenchResult(pct50=1.0)
    assert "times" not in bare.to_json()
    assert "fetch_overhead" not in bare.to_json()


def test_bench_result_equality_ignores_provenance():
    from tenzing_tpu.bench.benchmarker import BenchResult

    a = BenchResult.from_times([1.0, 1.0])
    b = BenchResult(pct01=1.0, pct10=1.0, pct50=1.0, pct90=1.0, pct99=1.0,
                    stddev=0.0)
    assert a == b


# -- interrupt hardening (ISSUE 3 satellites) --------------------------------

def test_export_flushes_open_spans_and_resolves_parents(tracer):
    """An export taken mid-run (the interrupted-search case) must keep the
    in-flight spans — closed as copies with ``flushed: true`` — and emit no
    record whose parent id is missing from the bundle."""
    with tracer.span("mcts.explore"):
        with tracer.span("mcts.iter", it=3):
            with tracer.span("bench.benchmark"):
                text = to_jsonl(tracer)
    recs = [json.loads(line) for line in text.splitlines()]
    spans = {r["id"]: r for r in recs if r["kind"] == "span"}
    names = {r["name"] for r in spans.values()}
    assert {"mcts.explore", "mcts.iter", "bench.benchmark"} <= names
    for r in spans.values():
        assert r["attrs"].get("flushed") is True
        if r["parent"] is not None:
            assert r["parent"] in spans  # no dangling parent ids
    # flushed durations are up-to-now, monotone down the stack
    by_name = {r["name"]: r for r in spans.values()}
    assert by_name["mcts.explore"]["dur_us"] >= \
        by_name["mcts.iter"]["dur_us"] >= \
        by_name["bench.benchmark"]["dur_us"] >= 0


def test_flushed_span_not_duplicated_once_closed(tracer):
    with tracer.span("outer"):
        mid = to_jsonl(tracer)
    final = to_jsonl(tracer)
    assert sum(1 for line in mid.splitlines()
               if json.loads(line)["name"] == "outer") == 1
    outer = [json.loads(line) for line in final.splitlines()
             if json.loads(line)["name"] == "outer"]
    assert len(outer) == 1  # the finished record replaces the flushed copy
    assert "flushed" not in outer[0]["attrs"]


def test_export_flushes_other_threads_open_spans(tracer):
    """An interrupt on the main thread must still see in-flight spans of
    worker threads (the DFS batch / watchdog threads)."""
    import threading

    started = threading.Event()
    release = threading.Event()

    def worker():
        with tracer.span("bench.batch"):
            started.set()
            release.wait(5.0)

    t = threading.Thread(target=worker)
    t.start()
    try:
        assert started.wait(5.0)
        recs = [json.loads(line) for line in to_jsonl(tracer).splitlines()]
        flushed = [r for r in recs if r["name"] == "bench.batch"]
        assert len(flushed) == 1 and flushed[0]["attrs"]["flushed"] is True
    finally:
        release.set()
        t.join(5.0)


def test_export_does_not_block_on_held_tracer_lock(tracer):
    """The trap-path guarantee: exporting while another thread holds the
    tracer lock (the interrupted thread, in the real deadlock) completes
    via the lock-free fallback instead of hanging."""
    import threading

    with tracer.span("held"):
        pass
    tracer._lock.acquire()
    try:
        out = {}

        def export():
            out["jsonl"] = to_jsonl(tracer)
            out["chrome"] = chrome_trace(tracer)

        t = threading.Thread(target=export, daemon=True)
        t.start()
        t.join(5.0)
        assert not t.is_alive(), "export deadlocked on the tracer lock"
    finally:
        tracer._lock.release()
    assert any(json.loads(line)["name"] == "held"
               for line in out["jsonl"].splitlines())
    assert any(e.get("name") == "held"
               for e in out["chrome"]["traceEvents"])


def test_metrics_to_json_does_not_block_on_held_locks(registry):
    import threading

    registry.counter("c").inc(3)
    h = registry.histogram("h")
    h.observe(1.0)
    h.observe(2.0)
    # both the registry lock and an instrument lock are held by "the
    # interrupted thread"
    registry._lock.acquire()
    h._lock.acquire()
    try:
        out = {}
        t = threading.Thread(
            target=lambda: out.update(doc=registry.to_json(block=False)),
            daemon=True)
        t.start()
        t.join(5.0)
        assert not t.is_alive(), "to_json deadlocked on instrument locks"
    finally:
        h._lock.release()
        registry._lock.release()
    assert out["doc"]["counters"]["c"] == 3
    assert out["doc"]["histograms"]["h"]["count"] == 2


def test_chrome_trace_includes_flushed_spans_with_valid_schema(tracer,
                                                               tmp_path):
    with tracer.span("open.one"):
        doc = chrome_trace(tracer)
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert any(e["name"] == "open.one" and e["args"].get("flushed")
               for e in xs)
    for e in xs:
        assert e["dur"] >= 0 and isinstance(e["ts"], float)
