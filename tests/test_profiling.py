"""Device-side xplane profiling (SURVEY §5 tracing, now
obs/attrib/xplane.py — the attribution profiler's multi-chip fallback):
trace capture via the executor + programmatic overlap analysis.  On CPU the
xplane has no device planes, so the concurrency numbers are zero — the
capture/parse machinery and the interval algebra are what these tests pin;
the on-TPU evidence lives in experiments/PROFILE_OVERLAP.json.  The
``utils/profiling.py`` shim's re-export identity is pinned in
tests/test_attrib.py."""

import numpy as np

import pytest

from tenzing_tpu.obs.attrib.xplane import (
    analyze_trace,
    capture_trace,
    merge_intervals,
)


def test_merge_intervals_coalesces_and_counts_once():
    ivs = [(0, 10), (5, 15), (20, 30), (30, 40), (50, 60)]
    merged = merge_intervals(ivs)
    assert merged == [[0, 15], [20, 40], [50, 60]]
    assert sum(b - a for a, b in merged) == 45


@pytest.mark.needs_profile_data
def test_capture_trace_produces_parseable_xplane(tmp_path):
    import jax.numpy as jnp

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.operation import DeviceOp
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State
    from tenzing_tpu.runtime.executor import TraceExecutor

    class Mul(DeviceOp):
        def reads(self):
            return ["x"]

        def writes(self):
            return ["y"]

        def apply(self, bufs, ctx):
            return {"y": bufs["x"] * 2.0}

    g = Graph()
    m = Mul("m")
    g.start_then(m)
    g.then_finish(m)
    plat = Platform.make_n_lanes(1)
    ex = TraceExecutor(plat, {"x": jnp.ones((8, 8)), "y": jnp.zeros((8, 8))})
    st = State(g)
    while not st.is_terminal():
        st = st.apply(st.get_decisions(plat)[0])
    tdir, wall = capture_trace(ex, st.sequence, tmp_path / "t", iters=2)
    assert wall > 0
    summary = analyze_trace(tdir)
    # CPU traces may expose no device planes; the parse must still succeed
    # and return the full key set (or a clear error about a missing xplane)
    if "error" not in summary:
        assert {"transfer_busy_ms", "compute_busy_ms",
                "transfer_concurrent_with_compute_ms"} <= set(summary)
