"""Recorded-traffic replay (ISSUE 13 tentpole; docs/observability.md
"Watchtower"): the listen loop records every admitted request —
served / shed / timeout outcomes, batch members individually, kwargs
verbatim — and ``serve/replay.py --from-recorded`` reconstructs the
empirical query trace (tier mix, workloads, inter-arrival QPS) from
those logs instead of the synthetic generator.  Plus the per-tenant
shed/timeout counter satellite and the observable-recorder satellite
(``uptime_s`` + request-log position in metric snapshots).
"""

import os
import threading
import time

import pytest

from tenzing_tpu.bench.driver import DriverRequest
from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics
from tenzing_tpu.serve.fingerprint import fingerprint_of
from tenzing_tpu.serve.listen import ListenOpts, ServeLoop
from tenzing_tpu.serve.replay import trace_from_recorded
from tenzing_tpu.serve.reqlog import RequestLog, read_request_log
from tenzing_tpu.serve.store import ScheduleStore

REQ = DriverRequest(workload="spmv", m=512)


class _StubService:
    def __init__(self, delay=0.0):
        self.delay = delay
        self.store = ScheduleStore(None)

    def query(self, req):
        from tenzing_tpu.serve.resolver import Resolution

        if self.delay:
            time.sleep(self.delay)
        return Resolution(tier="exact", fingerprint=fingerprint_of(REQ),
                          provenance={"stub": True})

    def stats(self):
        return {"stub": True}


def _collect():
    docs, lock = [], threading.Lock()

    def respond(doc):
        with lock:
            docs.append(doc)

    return docs, respond


def _loop(tmp_path, delay=0.0, **opts):
    defaults = dict(max_pending=8, workers=1, request_timeout_secs=60.0,
                    handle_signals=False,
                    status_path=str(tmp_path / "status.json"),
                    record_dir=str(tmp_path / "reqlog"),
                    record_segment_records=4)
    defaults.update(opts)
    return ServeLoop(_StubService(delay=delay), ListenOpts(**defaults))


# -- the listen loop records -------------------------------------------------

def test_served_and_shed_outcomes_recorded(tmp_path):
    loop = _loop(tmp_path)
    loop.start()
    docs, respond = _collect()
    for i in range(3):
        loop.submit({"op": "query", "id": i, "tenant": "t-a",
                     "request": {"workload": "spmv", "m": 512 + i}},
                    respond)
    loop.stop()
    # intake stopped: this one sheds — and is still recorded (offered
    # load is offered load)
    loop.submit({"op": "query", "id": 9,
                 "request": {"workload": "spmv", "m": 900}}, respond)
    loop.drain(timeout=10.0)
    data = read_request_log(str(tmp_path / "reqlog"))
    assert len(data["records"]) == 4
    by_outcome = {}
    for r in data["records"]:
        by_outcome.setdefault(r["outcome"], []).append(r)
    assert len(by_outcome["served"]) == 3
    served = by_outcome["served"][0]
    # everything --from-recorded needs: verbatim kwargs, tier, digests,
    # latency + phases, the response's own trace id
    assert served["request"] == {"workload": "spmv", "m": 512}
    assert served["tier"] == "exact"
    assert served["workload"] == "spmv"
    assert served["exact"] and served["bucket"]
    assert served["resolve_us"] > 0
    assert "serialize" in served["phase_us"]
    assert served["tenant"] == "t-a"
    resp = next(d for d in docs if d.get("id") == 0)
    assert served["trace_id"] == resp["trace_id"]
    shed = by_outcome["shed"][0]
    assert shed["request"] == {"workload": "spmv", "m": 900}
    assert "tier" not in shed


def test_timeout_outcome_recorded(tmp_path):
    loop = _loop(tmp_path, delay=1.0, request_timeout_secs=0.2)
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "query", "id": 1,
                 "request": {"workload": "spmv", "m": 512}}, respond)
    t0 = time.time()
    while not docs and time.time() - t0 < 5.0:
        time.sleep(0.02)
    loop.drain(timeout=10.0)
    data = read_request_log(str(tmp_path / "reqlog"))
    outcomes = [r["outcome"] for r in data["records"]]
    assert outcomes == ["timeout"]
    assert data["records"][0]["error_class"] == "transient"
    assert data["records"][0]["request"] == {"workload": "spmv", "m": 512}


def test_batch_members_recorded_individually(tmp_path):
    loop = _loop(tmp_path)
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "batch", "id": 1, "tenant": "t-b", "requests": [
        {"workload": "spmv", "m": 512},
        {"request": {"workload": "spmv", "m": 513}, "tenant": "t-c"}]},
        respond)
    loop.drain(timeout=10.0)
    data = read_request_log(str(tmp_path / "reqlog"))
    assert len(data["records"]) == 2
    assert [r["op"] for r in data["records"]] == ["batch", "batch"]
    assert sorted(r["request"]["m"] for r in data["records"]) == [512, 513]
    # the per-member tenant override sticks
    assert sorted(r["tenant"] for r in data["records"]) == ["t-b", "t-c"]


def test_snapshot_carries_uptime_and_reqlog_position(tmp_path):
    loop = _loop(tmp_path)
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "query", "id": 0,
                 "request": {"workload": "spmv", "m": 512}}, respond)
    loop.submit({"op": "metrics", "id": 1}, respond)
    loop.drain(timeout=10.0)
    m = next(d for d in docs if d.get("id") == 1)["metrics"]
    assert m["uptime_s"] >= 0
    rl = m["reqlog"]
    assert rl["dir"] == str(tmp_path / "reqlog")
    assert rl["records"] + rl["buffered"] + rl["dropped_sampling"] >= 1
    # the drain sealed the buffer: the final summary shows it published
    # (the metrics op itself is liveness probing, never traffic)
    s = loop.summary()
    assert s["reqlog"]["buffered"] == 0
    assert s["reqlog"]["records"] == 1


def test_recording_off_by_default(tmp_path):
    loop = ServeLoop(_StubService(), ListenOpts(
        max_pending=8, workers=1, handle_signals=False,
        status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "query", "id": 0,
                 "request": {"workload": "spmv", "m": 512}}, respond)
    loop.drain(timeout=10.0)
    assert "reqlog" not in loop.summary()
    assert not os.path.exists(str(tmp_path / "reqlog"))


# -- per-tenant shed/timeout counters (satellite) ----------------------------

def test_tenant_shed_and_timeout_counters_capped(tmp_path):
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        loop = _loop(tmp_path, delay=1.0, request_timeout_secs=0.2,
                     tenant_cap=1, max_pending=8)
        loop.start()
        docs, respond = _collect()
        # t-a times out (admitted first: owns a per-tenant series)
        loop.submit({"op": "query", "id": 0, "tenant": "t-a",
                     "request": {"workload": "spmv", "m": 512}}, respond)
        t0 = time.time()
        while not docs and time.time() - t0 < 5.0:
            time.sleep(0.02)
        loop.stop()
        # draining: everything sheds; t-z is over the cap -> "other"
        loop.submit({"op": "query", "id": 1, "tenant": "t-a",
                     "request": {"workload": "spmv", "m": 512}}, respond)
        loop.submit({"op": "query", "id": 2, "tenant": "t-z",
                     "request": {"workload": "spmv", "m": 512}}, respond)
        loop.drain(timeout=10.0)
        assert reg.counter("serve.timeout.t-a").value == 1
        assert reg.counter("serve.shed.t-a").value == 1
        assert reg.counter("serve.shed.other").value == 1
        assert "serve.shed.t-z" not in reg.to_json()["counters"]
    finally:
        set_metrics(prev)


# -- trace reconstruction ----------------------------------------------------

def test_trace_from_recorded_roundtrip(tmp_path):
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1", segment_records=8)
    tiers = ["exact"] * 6 + ["near", "near", "cold", "exact"]
    for i, tier in enumerate(tiers):
        rl.append({"v": 1, "ts": 1000.0 + i * 0.01,
                   "trace_id": f"{i:016x}", "op": "query",
                   "outcome": "served", "tier": tier,
                   "workload": "spmv" if i % 2 else "halo",
                   "resolve_us": 100.0,
                   "request": {"workload": "spmv" if i % 2 else "halo",
                               "m": 500 + i}})
    rl.flush()
    trace, info = trace_from_recorded(d)
    assert len(trace) == 10
    # arrival order, kwargs verbatim, tier as the kind
    assert [t["request"]["m"] for t in trace] == list(range(500, 510))
    assert trace[0]["kind"] == "exact" and trace[8]["kind"] == "cold"
    assert info["records"] == 10
    assert info["mix"] == {"cold": 0.1, "exact": 0.7, "near": 0.2}
    assert info["workloads"] == ["halo", "spmv"]
    # 10 requests over 90ms of inter-arrival -> ~111 qps
    assert info["qps_estimate"] == pytest.approx(100.0, rel=0.2)
    assert info["outcomes"] == {"served": 10}
    assert info["dropped_sampling"] == 0


def test_trace_from_recorded_includes_shed_and_empty_kwargs(tmp_path):
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1")
    rl.append({"v": 1, "ts": 1.0, "trace_id": "a" * 16, "op": "query",
               "outcome": "shed", "request": {"workload": "halo"}})
    # {"op": "query"} with no body: a valid all-defaults DriverRequest —
    # a log of default-shape queries must not reconstruct as empty
    rl.append({"v": 1, "ts": 2.0, "trace_id": "b" * 16, "op": "query",
               "outcome": "served", "tier": "exact", "request": {}})
    rl.flush()
    trace, info = trace_from_recorded(d)
    assert len(trace) == 2  # shed = offered load; {} = defaults
    assert [t["kind"] for t in trace] == ["shed", "exact"]
    assert info["outcomes"] == {"served": 1, "shed": 1}


def test_trace_from_recorded_slow_stream_qps_not_zeroed(tmp_path):
    """A trickle recorded over minutes must estimate a small nonzero
    QPS (3-decimal rounding), not a falsy 0.0 that would silently
    repace the replay at the synthetic default."""
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1")
    for i in range(10):  # 9 intervals over 900s -> 0.01 qps
        rl.append({"v": 1, "ts": 1000.0 + i * 100.0,
                   "trace_id": f"{i:016x}", "op": "query",
                   "outcome": "served", "tier": "exact",
                   "request": {"workload": "spmv", "m": 512}})
    rl.flush()
    _, info = trace_from_recorded(d)
    assert info["qps_estimate"] == 0.01


def test_trace_from_recorded_skips_off_schema_kwargs(tmp_path):
    """A shed/errored request's kwargs were recorded verbatim WITHOUT
    ever passing DriverRequest validation — an off-schema record must
    be skipped and counted, never crash the whole replay."""
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1")
    rl.append({"v": 1, "ts": 1.0, "trace_id": "a" * 16, "op": "query",
               "outcome": "served", "tier": "exact", "resolve_us": 50.0,
               "request": {"workload": "spmv", "m": 512}})
    rl.append({"v": 1, "ts": 2.0, "trace_id": "b" * 16, "op": "query",
               "outcome": "error",
               "request": {"workload": "halo", "bogus_flag": 1}})
    rl.flush()
    notes = []
    trace, info = trace_from_recorded(d, log=notes.append)
    assert len(trace) == 1 and trace[0]["kind"] == "exact"
    assert info["records"] == 1 and info["unreplayable"] == 1
    assert any("unreplayable" in n for n in notes)


def test_trace_from_recorded_empty_raises(tmp_path):
    d = str(tmp_path / "rl")
    os.makedirs(d)
    with pytest.raises(ValueError):
        trace_from_recorded(d)
