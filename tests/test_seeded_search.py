"""Warm-started MCTS (seed decision paths) + the paired halo discipline."""

import numpy as np
import pytest

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult, CachingBenchmarker
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.sequence import canonical_key
from tenzing_tpu.models.halo import HaloArgs
from tenzing_tpu.models.halo_pipeline import (
    HALO_PHASES,
    build_graph,
    greedy_overlap_order,
    host_buffer_names,
    make_pipeline_buffers,
    paired_overlap_order,
    paired_priority,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.local import LocalOpts, drive, phase_policy
from tenzing_tpu.solve.mcts import MctsOpts, explore
from tenzing_tpu.solve.mcts.strategies import FastMin

ARGS = HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1)


def make_executor(engine="host"):
    bufs, want = make_pipeline_buffers(ARGS, seed=0)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    return jbufs, want


class CountingBench:
    """Counts real benchmark calls; returns schedule-independent times."""

    def __init__(self):
        self.calls = 0

    def benchmark(self, order, opts=None):
        self.calls += 1
        return BenchResult.from_times([1.0 + 0.001 * self.calls] * 3)


@pytest.mark.needs_pinned_host
def test_paired_order_numerics():
    """The paired await/unpack incumbent is a legal schedule with correct
    results, for both the phase and the mixed-engine realizations."""
    for engine in ("host", "mixed"):
        bufs, want = make_executor()
        plat = Platform.make_n_lanes(4)
        seq = paired_overlap_order(ARGS, plat, engine=engine)
        names = [op.name() for op in seq.vector()]
        # paired discipline: each direction's unpack comes right after its own
        # await, i.e. some await appears AFTER the first unpack (no all-awaits
        # barrier like the greedy phase discipline)
        first_unpack = next(i for i, n in enumerate(names) if n.startswith("unpack"))
        assert any(n.startswith("await") for n in names[first_unpack:]), names
        ex = TraceExecutor(plat, bufs)
        out = ex.run(seq)
        np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)


def test_paired_differs_from_greedy():
    plat = Platform.make_n_lanes(4)
    paired = paired_overlap_order(ARGS, plat, engine="host")
    greedy = greedy_overlap_order(ARGS, plat, engine="host")
    assert canonical_key(paired) != canonical_key(greedy)


def test_seeded_explore_materializes_path():
    """Seeds are consumed as the first iterations: the seed schedule is
    benchmarked exactly as driven, and the tree statistics cover its path."""
    g = build_graph(ARGS)
    plat = Platform.make_n_lanes(2)
    seq, decs = drive(g, plat, phase_policy(plat, HALO_PHASES))
    bench = CountingBench()
    res = explore(
        g, plat, bench,
        MctsOpts(n_iters=3, bench_opts=BenchOpts(n_iters=2), seed=0,
                 cache_benchmarks=False),
        strategy=FastMin,
        seeds=[decs],
    )
    assert len(res.sims) == 3
    # first sim IS the seed schedule, as recorded (no redundant-sync cleanup)
    assert canonical_key(res.sims[0].order) == canonical_key(seq)
    # the seed path was materialized into the tree (visits down the path)
    assert res.tree_size > len(decs) // 2


def test_seeded_explore_cache_hit_free():
    """A seed whose schedule was pre-benchmarked by the driver is a cache hit
    — the warm start costs no device time."""
    g = build_graph(ARGS)
    plat = Platform.make_n_lanes(2)
    seq, decs = drive(g, plat, phase_policy(plat, HALO_PHASES))
    inner = CountingBench()
    bench = CachingBenchmarker(inner)
    opts = BenchOpts(n_iters=2)
    bench.benchmark(seq, opts)  # the driver's incumbent measurement
    before = inner.calls
    explore(
        g, plat, bench,
        MctsOpts(n_iters=1, bench_opts=opts, seed=0),
        strategy=FastMin,
        seeds=[decs],
    )
    assert bench.hits >= 1
    assert inner.calls == before  # seed iteration cost no real benchmark


def test_solvers_survive_uncompilable_schedules():
    """A schedule that fails to compile/run is a reject (climb) or a
    penalized dead end (MCTS) — never a crash (observed on hardware: a climb
    neighbor whose liveness exceeded HBM)."""
    from tenzing_tpu.solve.local import hill_climb as hc

    g = build_graph(ARGS)
    plat = Platform.make_n_lanes(2)

    class FlakyBench:
        """Fails every benchmark except the first (the incumbent)."""

        def __init__(self):
            self.calls = 0

        def benchmark(self, order, opts=None):
            self.calls += 1
            if self.calls > 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: out of hbm")
            return BenchResult.from_times([1.0] * 3)

    res = hc(g, plat, FlakyBench(), phases=HALO_PHASES,
             opts=LocalOpts(budget=5, bench_opts=BenchOpts(n_iters=2)))
    assert res.final is not None  # incumbent survives; neighbors rejected
    assert len(res.sims) == 1

    class AlwaysFail:
        def benchmark(self, order, opts=None):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of hbm")

    mres = explore(
        g, plat, AlwaysFail(),
        MctsOpts(n_iters=2, bench_opts=BenchOpts(n_iters=2), seed=0,
                 cache_benchmarks=False),
        strategy=FastMin,
    )
    assert mres.sims == []  # no fake measurements recorded
    assert mres.tree_size > 1  # the search still ran and backpropagated
