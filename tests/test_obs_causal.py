"""Causal latency observatory (ISSUE 16; docs/observability.md "Causal
analysis"): golden hand-computed critical paths over synthetic span
bundles (fast-path hit, exclusive-path hit, cold item through queue
wait + drain child + merge, batch members sharing one trace_id),
explicit ``unattributed`` residual accounting, the fleet-wide
aggregation, the differential localizer's ok/flag/floor/noise-downgrade
verdicts, and the ``python -m tenzing_tpu.obs.causal`` CLI.
"""

import json
import os
import subprocess
import sys

from tenzing_tpu.obs.causal import (
    CAUSAL_VERSION,
    aggregate,
    analyze_bundles,
    analyze_records,
    localize_phases,
    localize_segments,
)
from tenzing_tpu.obs.report import check_serve_regression


def span(name, ts, dur, tid="t1", **attrs):
    return {"kind": "span", "name": name, "ts_us": float(ts),
            "dur_us": float(dur), "pid": 1, "tid": 1,
            "attrs": {"trace_id": tid, **attrs}}


def event(name, ts, tid="t1", **attrs):
    return {"kind": "event", "name": name, "ts_us": float(ts),
            "pid": 1, "tid": 1, "attrs": {"trace_id": tid, **attrs}}


def chain_of(trace):
    return [c["segment"] for c in trace["chain"]]


# -- golden critical paths ---------------------------------------------------

def test_exclusive_path_hit_golden():
    # serve.query [100, 400] wrapping fingerprint [110,150] and
    # cache_probe [160,260]: the remainder of the query window is
    # store_walk, the lead-in is ingress — every us attributed
    recs = [
        span("serve.query", 100, 300, tier="exact", workload="halo"),
        span("serve.fingerprint", 110, 40),
        span("serve.cache_probe", 160, 100),
    ]
    t = analyze_records(recs)["t1"]
    assert chain_of(t) == ["ingress", "fingerprint", "store_walk",
                           "cache_probe", "store_walk"]
    assert t["segments_us"] == {"ingress": 10.0, "fingerprint": 40.0,
                                "store_walk": 150.0, "cache_probe": 100.0}
    assert t["window_us"] == 300.0
    assert t["unattributed_us"] == 0.0 and t["coverage"] == 1.0
    assert t["tier"] == "exact" and t["queries"] == 1


def test_fast_path_hit_golden():
    # the fast path emits its span post-hoc with ~0 duration; the real
    # latency rides resolve_us — the analyzer synthesizes the interval
    recs = [span("serve.query", 500, 0, tier="exact", fast_path=True,
                 resolve_us=42)]
    t = analyze_records(recs)["t1"]
    assert chain_of(t) == ["fast_path"]
    assert t["segments_us"] == {"fast_path": 42.0}
    assert t["window_us"] == 42.0 and t["coverage"] == 1.0


def test_cold_item_through_queue_wait_drain_merge_golden():
    # the full cold chain: resolve [0,300] enqueues at 250, a daemon
    # claims at 1000 (queue wait 750), drains with compile/measure
    # children, merges [4500,4900] — the window ends at the servable
    # point, not at post-merge housekeeping
    recs = [
        span("serve.query", 0, 300, tier="cold", workload="spmv"),
        span("serve.fingerprint", 10, 40),
        span("serve.cache_probe", 60, 100),
        event("serve.enqueue", 250, exact="e1", reason="cold"),
        span("daemon.drain", 1000, 4500, exact="e1"),
        span("executor.compile", 1100, 900),
        span("bench.benchmark", 2100, 900),
        span("serve.store.flush", 4500, 400),
    ]
    t = analyze_records(recs)["t1"]
    assert chain_of(t) == [
        "ingress", "fingerprint", "store_walk", "cache_probe",
        "store_walk", "queue_wait", "drain", "compile", "drain",
        "measure", "drain", "merge"]
    assert t["segments_us"]["queue_wait"] == 750.0
    assert t["segments_us"]["merge"] == 400.0
    assert t["window_us"] == 4900.0  # ends at the merge, not drain end
    assert t["servable"] is True
    assert t["coverage"] == 1.0 and t["unattributed_us"] == 0.0
    assert t["markers"] == [{"segment": "enqueue", "ts_us": 250.0}]
    assert t["queue_wait_us"] == 750.0
    assert t["service_us"] == 4150.0  # window - queue wait (no residual)
    # ISSUE 16 acceptance shape: enqueue -> queue wait -> drain -> merge
    # in order, queue wait a distinct segment, coverage >= 0.9
    segs = chain_of(t)
    assert [s for s in segs if s in ("queue_wait", "merge")] == \
        ["queue_wait", "merge"]
    assert segs.index("queue_wait") < segs.index("drain")
    assert t["coverage"] >= 0.9


def test_batch_members_share_trace_and_residual_accounts():
    # two queries in one trace with an uncovered gap between them: the
    # gap is explicit unattributed, and the books balance exactly —
    # sum(segments) + unattributed == window
    recs = [
        span("serve.query", 0, 100, tier="exact"),
        span("serve.fingerprint", 10, 80),
        span("serve.query", 300, 100, tier="exact"),
        span("serve.fingerprint", 310, 80),
    ]
    t = analyze_records(recs)["t1"]
    assert t["queries"] == 2
    assert chain_of(t) == ["ingress", "fingerprint", "store_walk",
                           "unattributed",
                           "ingress", "fingerprint", "store_walk"]
    assert t["window_us"] == 400.0
    assert t["unattributed_us"] == 200.0
    assert t["coverage"] == 0.5
    total = sum(t["segments_us"].values()) + t["unattributed_us"]
    assert abs(total - t["window_us"]) < 1e-6
    # and the chain itself tiles the window with no gaps or overlaps
    edges = [(c["start_us"], c["end_us"]) for c in t["chain"]]
    assert edges[0][0] == 0.0 and edges[-1][1] == t["window_us"]
    assert all(a[1] == b[0] for a, b in zip(edges, edges[1:]))


def test_traces_separated_and_housekeeping_dropped():
    recs = [
        span("serve.query", 0, 100, tid="a", tier="exact"),
        span("serve.query", 0, 200, tid="b", tier="near"),
        # no trace_id: process-local housekeeping, not request latency
        {"kind": "span", "name": "serve.query", "ts_us": 0.0,
         "dur_us": 999.0, "attrs": {}},
        {"kind": "other", "name": "noise"},
    ]
    out = analyze_records(recs)
    assert sorted(out) == ["a", "b"]
    assert out["a"]["window_us"] == 100.0
    assert out["b"]["tier"] == "near"


# -- aggregation -------------------------------------------------------------

def test_aggregate_rollup_and_pct99_ranking():
    recs = []
    # nine quick fast-path hits and one slow cold request: the tail
    # ranking must attribute the pct99 to the cold chain's segments
    for i in range(9):
        recs.append(span("serve.query", 1000 * i, 0, tid=f"f{i}",
                         tier="exact", fast_path=True, resolve_us=50))
    recs += [
        span("serve.query", 0, 300, tid="cold1", tier="cold"),
        event("serve.enqueue", 250, tid="cold1"),
        span("daemon.drain", 1000, 4000, tid="cold1"),
        span("serve.store.flush", 4500, 500, tid="cold1"),
    ]
    traces = analyze_records(recs)
    agg = aggregate(traces)
    assert agg["n_traces"] == 10
    assert agg["by_tier"]["exact"]["count"] == 9
    assert agg["by_tier"]["exact"]["segments_us"]["fast_path"][
        "p50_us"] == 50.0
    assert agg["by_tier"]["cold"]["count"] == 1
    top = agg["pct99_ranking"][0]
    assert top["segment"] in ("drain", "queue_wait")
    assert agg["decomposition"]["queue_wait_us"]["p99_us"] == 750.0


# -- differential localization -----------------------------------------------

def _phase(p99, count=64):
    return {"count": count, "pct50_us": p99 / 2, "pct99_us": p99,
            "sum_us": p99 * count}


def test_localizer_ok_when_nothing_moved():
    base = {"fingerprint": _phase(10.0), "cache_probe": _phase(20.0)}
    fresh = {"fingerprint": _phase(12.0), "cache_probe": _phase(21.0)}
    loc = localize_segments(fresh, base)
    assert loc["moved"] == []
    assert {c["segment"] for c in loc["compared"]} == \
        {"fingerprint", "cache_probe"}


def test_localizer_flags_the_moved_segment():
    base = {"fingerprint": _phase(10.0), "cache_probe": _phase(20.0)}
    fresh = {"fingerprint": _phase(11.0), "cache_probe": _phase(62.0)}
    loc = localize_segments(fresh, base)
    assert [m["segment"] for m in loc["moved"]] == ["cache_probe"]
    assert loc["moved"][0]["ratio"] == 3.1


def test_localizer_noise_guards():
    # a 3x ratio on a sub-floor phase is not movement (2us -> 6us sits
    # under the 5us absolute floor), nor is a thin sample (count < 8),
    # and a raised measured wake floor suppresses small deltas too
    base = {"tiny": _phase(2.0), "thin": _phase(10.0, count=3),
            "real": _phase(10.0)}
    fresh = {"tiny": _phase(6.0), "thin": _phase(90.0, count=3),
             "real": _phase(30.0)}
    loc = localize_segments(fresh, base)
    assert [m["segment"] for m in loc["moved"]] == ["real"]
    assert "thin" in loc["skipped"]
    # same data under a 25us measured floor: real's 20us delta is
    # within the host's own wake noise — nothing moved
    loc = localize_segments(fresh, base, floor_us=25.0)
    assert loc["moved"] == [] and loc["delta_floor_us"] == 25.0


def _serve_doc(pct99=100.0, phases=None, samples=None, noise_p99=None):
    doc = {
        "kind": "serve_trace_replay",
        "segmented": {
            "resolve_us": {"exact": {"count": 64, "pct50_us": 50.0,
                                     "pct99_us": pct99}},
            "verifier_calls": 0, "shed": 0,
            "exact_samples_us": samples or [],
            **({"phases_us": phases} if phases else {}),
        },
    }
    if noise_p99 is not None:
        doc["host_noise"] = {
            "version": 1, "samples": 64, "host": "h",
            "timer_wake_us": {"count": 64, "p50_us": noise_p99 / 2,
                              "p99_us": noise_p99, "runs_z": 0.1,
                              "iid": True},
            "hot_spin_us": {"count": 64, "p50_us": 1.0, "p99_us": 2.0,
                            "runs_z": 0.1, "iid": True},
        }
    return doc


def test_localize_phases_uses_fresh_doc_wake_floor():
    base = _serve_doc(phases={"cache_probe": _phase(10.0)})
    fresh = _serve_doc(phases={"cache_probe": _phase(30.0)},
                       noise_p99=25.0)
    # delta 20us < the recorded 25us wake floor: not movement
    assert localize_phases(fresh, base)["moved"] == []
    fresh = _serve_doc(phases={"cache_probe": _phase(120.0)},
                       noise_p99=25.0)
    assert [m["segment"] for m in localize_phases(fresh, base)["moved"]] \
        == ["cache_probe"]


def _iid_samples(n=64, seed=1):
    # seeded uniform jitter: passes the runs test (|Z| < 1.96), so the
    # noise downgrade stays out of the way of the verdict under test
    import random

    rng = random.Random(seed)
    return [90.0 + rng.random() * 2 for _ in range(n)]


def test_serve_gate_names_the_doctored_phase():
    # ISSUE 16 acceptance: the gate says WHICH phase regressed, not
    # just that a pct99 did
    samples = _iid_samples()
    base = _serve_doc(phases={"fingerprint": _phase(10.0),
                              "cache_probe": _phase(20.0)})
    fresh = _serve_doc(pct99=100.0,
                       phases={"fingerprint": _phase(10.5),
                               "cache_probe": _phase(65.0)},
                       samples=samples)
    verdict = check_serve_regression(fresh, base)
    assert verdict["verdict"] == "regression"
    assert any("phase 'cache_probe' pct99 regressed 3.2x" in r
               for r in verdict["reasons"])
    assert [m["segment"] for m in
            verdict["checks"]["segments"]["moved"]] == ["cache_probe"]


def test_serve_gate_downgrades_cross_host_comparison():
    # same doctored regression, but the fresh doc's measured floors are
    # 10x the baseline host's: the hosts are not comparable — verdict
    # downgrades to inconclusive instead of blaming the code
    samples = _iid_samples()
    base = _serve_doc(phases={"cache_probe": _phase(20.0)}, noise_p99=5.0)
    fresh = _serve_doc(pct99=400.0,
                       phases={"cache_probe": _phase(200.0)},
                       samples=samples, noise_p99=50.0)
    verdict = check_serve_regression(fresh, base)
    assert verdict["verdict"] == "inconclusive"
    assert any("hosts are not comparable" in r for r in verdict["reasons"])
    assert "timer-wake" in verdict["checks"]["host_floors"]
    # the floor-vs-tail read is recorded for the report to render
    assert verdict["checks"]["host_noise"]["ratio"] == 8.0
    assert "serving-bound" in verdict["checks"]["host_noise"]["line"]


def test_serve_gate_matching_hosts_do_not_downgrade():
    base = _serve_doc(noise_p99=10.0)
    fresh = _serve_doc(pct99=95.0, noise_p99=12.0)
    verdict = check_serve_regression(fresh, base)
    assert verdict["verdict"] == "ok"
    assert "host_floors" not in verdict["checks"]


# -- bundles + CLI -----------------------------------------------------------

def _write_bundle(path, recs, header=None):
    with open(path, "w") as f:
        if header is not None:
            f.write(json.dumps(header) + "\n")
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_analyze_bundles_exemplar_header_supplies_tenant(tmp_path):
    p = str(tmp_path / "exemplar-aa-slow-0.jsonl")
    _write_bundle(p, [span("serve.query", 0, 100, tid="aa", tier="exact"),
                      span("serve.fingerprint", 10, 80, tid="aa")],
                  header={"kind": "exemplar", "trace_id": "aa",
                          "record": {"tenant": "acme",
                                     "resolve_us": 100.0}})
    out = analyze_bundles([p])
    assert out["aa"]["tenant"] == "acme"
    agg = aggregate(out)
    assert agg["by_tenant"]["acme"]["count"] == 1


def test_causal_cli_analysis_and_diff(tmp_path):
    bundle = str(tmp_path / "trace.jsonl")
    _write_bundle(bundle, [
        span("serve.query", 0, 300, tier="cold"),
        event("serve.enqueue", 250),
        span("daemon.drain", 1000, 4000),
        span("serve.store.flush", 4500, 500),
    ])
    out = str(tmp_path / "causal.json")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.obs.causal", bundle,
         "--out", out], capture_output=True, text=True, timeout=120,
        env=env)
    assert r.returncode == 0, r.stderr
    doc = json.load(open(out))
    assert doc["kind"] == "causal_analysis"
    assert doc["version"] == CAUSAL_VERSION
    t = doc["traces"]["t1"]
    assert t["coverage"] >= 0.9
    segs = [c["segment"] for c in t["chain"]]
    assert segs.index("queue_wait") < segs.index("drain") < \
        segs.index("merge")
    # --diff: doctored phase -> exit 1, names the segment
    base_doc = _serve_doc(phases={"cache_probe": _phase(20.0)})
    fresh_doc = _serve_doc(phases={"cache_probe": _phase(65.0)})
    fb, bb = str(tmp_path / "f.json"), str(tmp_path / "b.json")
    json.dump(fresh_doc, open(fb, "w"))
    json.dump(base_doc, open(bb, "w"))
    r = subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.obs.causal",
         "--diff", fb, bb], capture_output=True, text=True, timeout=120,
        env=env)
    assert r.returncode == 1, (r.stdout, r.stderr)
    diff = json.loads(r.stdout)
    assert diff["kind"] == "causal_diff"
    assert [m["segment"] for m in diff["moved"]] == ["cache_probe"]
    # clean pair -> exit 0
    json.dump(base_doc, open(fb, "w"))
    r = subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.obs.causal",
         "--diff", fb, bb], capture_output=True, text=True, timeout=120,
        env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    # no bundles and no --diff: usage error
    r = subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.obs.causal"],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 2
