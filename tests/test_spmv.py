"""SpMV workload: data structures, split, compound graph, end-to-end numerics
(reference test/test_expand_spmv.cu:16-51 and the C12 data layer)."""

import numpy as np
import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.state import State
from tenzing_tpu.models.spmv import (
    CooMat,
    CsrMat,
    SpMVCompound,
    make_spmv_buffers,
    part_by_rows,
    get_owner,
    random_band_matrix,
    random_matrix,
    split_local_remote,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


def test_coo_to_csr_roundtrip():
    coo = CooMat(
        3,
        3,
        np.array([2, 0, 0]),
        np.array([1, 0, 2]),
        np.array([5.0, 1.0, 2.0], dtype=np.float32),
    )
    csr = coo.to_csr()
    dense = csr.toarray()
    want = np.zeros((3, 3), dtype=np.float32)
    want[2, 1], want[0, 0], want[0, 2] = 5.0, 1.0, 2.0
    np.testing.assert_array_equal(dense, want)


def test_band_matrix_stays_in_band():
    m, bw = 100, 5
    a = random_band_matrix(m, bw, 500, seed=1)
    for i in range(m):
        for j in range(a.indptr[i], a.indptr[i + 1]):
            assert abs(int(a.cols[j]) - i) <= bw


def test_slab_spmv_matches_dense():
    a = random_matrix(50, 40, 300, seed=2)
    vals, cols = a.to_slab()
    x = np.random.default_rng(0).random(40, dtype=np.float32)
    y = np.sum(vals * x[cols], axis=1)
    np.testing.assert_allclose(y, a.toarray() @ x, rtol=1e-5)


def test_slab_width_truncation_rejected():
    a = random_matrix(50, 40, 300, seed=2)
    with pytest.raises(ValueError, match="truncate"):
        a.to_slab(width=1)


def test_retain_rows():
    a = random_matrix(20, 20, 100, seed=3)
    sub = a.retain_rows(5, 12)
    np.testing.assert_allclose(sub.toarray(), a.toarray()[5:12], rtol=1e-6)


def test_partition():
    assert part_by_rows(10, 3) == [(0, 4), (4, 7), (7, 10)]
    assert get_owner(10, 3, 0) == 0
    assert get_owner(10, 3, 5) == 1
    assert get_owner(10, 3, 9) == 2


def test_split_local_remote_reassembles():
    a = random_matrix(30, 30, 200, seed=4)
    sp = split_local_remote(a, 0, 15)
    x = np.random.default_rng(1).random(30, dtype=np.float32)
    y_loc = sp.local.toarray() @ x[:15]
    y_rem = sp.remote.toarray() @ x[sp.remote_cols]
    np.testing.assert_allclose(y_loc + y_rem, a.toarray() @ x, rtol=1e-4)
    # remote columns are all outside the local range
    assert all(c >= 15 for c in sp.remote_cols)


def test_spmv_compound_expansion():
    # reference test_expand_spmv.cu: ExpandOp yields the compound's interior
    g = Graph()
    comp = SpMVCompound()
    g.start_then(comp)
    g.then_finish(comp)
    plat = Platform.make_n_lanes(2)
    s = State(g)
    ds = s.get_decisions(plat)
    assert len(ds) == 1 and "Expand" in ds[0].desc()
    s2 = s.apply(ds[0])
    names = {op.name() for op in s2.graph.vertices()}
    assert {"spmv_local", "scatter", "exchange", "spmv_remote", "y_add"} <= names


def test_spmv_end_to_end_all_schedules_correct():
    bufs, want = make_spmv_buffers(m=256, nnz_per_row=4, seed=0)
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, bufs)
    states = get_all_sequences(g, plat, max_seqs=8)
    assert states
    for st in states:
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=2e-3)


def test_read_matrix_market(tmp_path):
    """MatrixMarket loader parity (reference mm reader, spmv.cu:23,35-37):
    general/symmetric/pattern variants against hand-built dense answers."""
    from tenzing_tpu.models.spmv import read_matrix_market

    gen = tmp_path / "gen.mtx"
    gen.write_text(
        "%%MatrixMarket matrix coordinate real general\n"
        "% a comment\n"
        "3 4 4\n"
        "1 1 2.5\n"
        "2 3 -1.0\n"
        "3 4 4.0\n"
        "1 2 0.5\n"
    )
    a = read_matrix_market(str(gen))
    want = np.zeros((3, 4), dtype=np.float32)
    want[0, 0], want[1, 2], want[2, 3], want[0, 1] = 2.5, -1.0, 4.0, 0.5
    np.testing.assert_array_equal(a.toarray(), want)

    sym = tmp_path / "sym.mtx"
    sym.write_text(
        "%%MatrixMarket matrix coordinate real symmetric\n"
        "3 3 3\n"
        "1 1 1.0\n"
        "3 1 2.0\n"
        "3 2 3.0\n"
    )
    s = read_matrix_market(str(sym))
    wants = np.array([[1, 0, 2], [0, 0, 3], [2, 3, 0]], dtype=np.float32)
    np.testing.assert_array_equal(s.toarray(), wants)

    pat = tmp_path / "pat.mtx"
    pat.write_text(
        "%%MatrixMarket matrix coordinate pattern general\n"
        "2 2 2\n"
        "1 2\n"
        "2 1\n"
    )
    p = read_matrix_market(str(pat))
    np.testing.assert_array_equal(
        p.toarray(), np.array([[0, 1], [1, 0]], dtype=np.float32)
    )

    with pytest.raises(ValueError):
        bad = tmp_path / "bad.mtx"
        bad.write_text("%%MatrixMarket matrix array real general\n2 2\n1\n2\n3\n4\n")
        read_matrix_market(str(bad))


def test_spmv_workload_from_mtx(tmp_path):
    """A loaded .mtx drives the full workload path (make_spmv_buffers(matrix=...))
    and every enumerated schedule computes the right y."""
    from tenzing_tpu.models.spmv import read_matrix_market

    rng = np.random.default_rng(3)
    m, nnz = 64, 400
    rows = rng.integers(0, m, nnz) + 1
    cols = rng.integers(0, m, nnz) + 1
    vals = rng.random(nnz)
    path = tmp_path / "rand.mtx"
    path.write_text(
        f"%%MatrixMarket matrix coordinate real general\n{m} {m} {nnz}\n"
        + "".join(f"{r} {c} {v:.6f}\n" for r, c, v in zip(rows, cols, vals))
    )
    mat = read_matrix_market(str(path))
    bufs, want = make_spmv_buffers(matrix=mat)
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, bufs)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    out = ex.run(st.sequence)
    np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=2e-3)


def test_read_matrix_market_truncated_raises(tmp_path):
    from tenzing_tpu.models.spmv import read_matrix_market

    t1 = tmp_path / "t1.mtx"
    t1.write_text("%%MatrixMarket matrix coordinate real general\n% only a comment\n")
    with pytest.raises(ValueError, match="truncated"):
        read_matrix_market(str(t1))
    t2 = tmp_path / "t2.mtx"
    t2.write_text("%%MatrixMarket matrix coordinate real general\n3 3 4\n1 1 1.0\n")
    with pytest.raises(ValueError, match="promised"):
        read_matrix_market(str(t2))


@pytest.mark.needs_pinned_host
def test_spmv_host_exchange_schedules_correct():
    """exchange="host": the x exchange is a posted async host round-trip with
    the post/wait split (the reference's PostSend/WaitRecv analog,
    ops_spmv.cuh:217-304); the post and await are distinct schedulable
    vertices, overlap orderings exist in the enumerated space, and a sample of
    schedules stays numerically right."""
    from tenzing_tpu.models.spmv import spmv_host_buffer_names

    bufs, want = make_spmv_buffers(m=128, nnz_per_row=4, seed=1)
    jbufs = TraceExecutor.place_host_buffers(bufs, spmv_host_buffer_names())
    g = Graph()
    g.start_then(SpMVCompound(exchange="host"))
    g.then_finish(SpMVCompound(exchange="host"))
    plat = Platform.make_n_lanes(2)
    states = get_all_sequences(g, plat, max_seqs=500)
    names = {op.name() for op in states[0].sequence}
    assert {"spill_x", "fetch_x", "await_x"} <= names
    ex = TraceExecutor(plat, jbufs)
    for st in states[:6]:
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=2e-3)
    # overlap orderings exist: some schedule computes spmv_local between the
    # fetch post and the await
    def overlapped(st):
        ns = [op.name() for op in st.sequence]
        return ("await_x" in ns and "spmv_local" in ns
                and ns.index("fetch_x") < ns.index("spmv_local") < ns.index("await_x"))

    assert any(overlapped(st) for st in states)
