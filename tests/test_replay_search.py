"""Replay-driven search: MCTS over a recorded database with no device in the
benchmark loop (the reference's mcts_csv driver workflow, CsvBenchmarker
benchmarker.cpp:169-223).

The DFS solver records raw terminal sequences; MCTS cleans every rollout with
``remove_redundant_syncs`` before benchmarking — ``normalize=True`` bridges
the two by matching modulo the cleanup (identical execution semantics)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    CsvBenchmarker,
    EmpiricalBenchmarker,
    result_row,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import enumerate_schedules
from tenzing_tpu.solve.mcts import MctsOpts, explore, strategies


def _graph():
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    return g


@pytest.fixture(scope="module")
def recorded_db():
    """Benchmark the FULL deduplicated 2-lane space of the tiny SpMV DAG on
    CPU and dump it, as examples/spmv_dfs.py would."""
    plat = Platform.make_n_lanes(2)
    states = enumerate_schedules(_graph(), plat, max_seqs=10_000)
    assert len(states) < 10_000  # complete coverage, not a capped subset
    bufs, _ = make_spmv_buffers(m=64, nnz_per_row=3, seed=0)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    bench = EmpiricalBenchmarker(TraceExecutor(plat, bufs))
    opts = BenchOpts(n_iters=2, target_secs=1e-4)
    rows = [
        result_row(i, bench.benchmark(st.sequence, opts), st.sequence)
        for i, st in enumerate(states)
    ]
    return CsvBenchmarker(rows, _graph(), normalize=True), len(states)


def test_mcts_replays_recorded_database_without_device(recorded_db):
    """Every MCTS rollout must resolve against the recorded full space —
    KeyError here would mean the replay bridge (normalize) is broken."""
    db, n = recorded_db
    assert len(db.entries) == n
    plat = Platform.make_n_lanes(2)
    res = explore(
        _graph(),
        plat,
        db,
        MctsOpts(n_iters=12, bench_opts=BenchOpts(), seed=3),
        strategy=strategies.FastMin,
    )
    assert res.sims
    best = min(s.result.pct50 for s in res.sims)
    recorded_best = min(r.pct50 for _, r in db.entries)
    assert best >= recorded_best  # replay cannot invent a faster schedule


def test_normalize_matches_cleaned_query(recorded_db):
    """A raw recorded sequence and its cleaned form answer identically."""
    from tenzing_tpu.core.schedule import remove_redundant_syncs

    db, _ = recorded_db
    raw, res = db.entries[0]
    assert db.benchmark(raw).pct50 == res.pct50
    assert db.benchmark(remove_redundant_syncs(raw)).pct50 == res.pct50
