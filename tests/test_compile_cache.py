"""Unit coverage for bench/compile_cache.py (previously untested): the
persistent-XLA-cache knobs land in jax.config, the TZ_COMPILE_CACHE override
wins, and the threshold parameter is honored — the CI cache step
(.github/workflows/ci.yml) keys on this directory staying stable."""

import jax
import pytest

from tenzing_tpu.bench.compile_cache import enable_compile_cache


@pytest.fixture
def restore_jax_cache_config():
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    yield
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


def test_default_path_and_threshold(monkeypatch, restore_jax_cache_config):
    monkeypatch.delenv("TZ_COMPILE_CACHE", raising=False)
    path = enable_compile_cache()
    assert path == "/tmp/tz_jax_cache"
    assert jax.config.jax_compilation_cache_dir == path
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 1.0


def test_env_override_and_custom_threshold(monkeypatch, tmp_path,
                                           restore_jax_cache_config):
    want = str(tmp_path / "cache")
    monkeypatch.setenv("TZ_COMPILE_CACHE", want)
    path = enable_compile_cache(min_compile_secs=0.25)
    assert path == want
    assert jax.config.jax_compilation_cache_dir == want
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.25
