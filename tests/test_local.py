"""Neighborhood search (solve/local.py): policy drive, one-substitution
replay, and hill climbing against a deterministic fake benchmarker."""

import numpy as np
import pytest

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult, CachingBenchmarker
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.state import ChooseOp, State
from tenzing_tpu.models.halo import HaloArgs
from tenzing_tpu.models.halo_pipeline import HALO_PHASES, build_graph
from tenzing_tpu.solve.local import (
    LocalOpts,
    drive,
    hill_climb,
    phase_policy,
    replay_with_substitution,
)

PHASES = HALO_PHASES
ARGS = HaloArgs(nq=1, lx=2, ly=2, lz=2, radius=1)


def mk(prefer=None, lanes=2):
    g = build_graph(ARGS, xfer_choice=True)
    plat = Platform.make_n_lanes(lanes)
    return g, plat, phase_policy(plat, PHASES, prefer)


def test_drive_resolves_choice_graph_to_terminal():
    g, plat, pol = mk()
    seq, decisions = drive(g, plat, pol)
    names = [op.desc() for op in seq.vector()]
    assert names[0] == "start" and names[-1] == "finish"
    # default preference takes the first (host) choice everywhere
    assert any(n.startswith("spill_") for n in names)
    assert len(decisions) > len(names) - 2  # choices/expands/assigns on top


def test_prefer_callback_selects_engines():
    prefer = lambda op, choices: next(c for c in choices if c.endswith(".rdma"))
    g, plat, pol = mk(prefer)
    seq, _ = drive(g, plat, pol)
    names = [op.desc() for op in seq.vector()]
    assert any(".rdma" in n for n in names)
    assert not any(n.startswith("spill_") for n in names)


def test_replay_with_substitution_flips_one_choice():
    g, plat, pol = mk()
    seq, decisions = drive(g, plat, pol)
    # find the first ChooseOp decision and substitute the other engine
    i = next(j for j, d in enumerate(decisions) if isinstance(d, ChooseOp))
    st = State(g)
    for d in decisions[:i]:
        st = st.apply(d)
    alts = [d for d in st.get_decisions(plat)
            if isinstance(d, ChooseOp) and d.op.name() == decisions[i].op.name()
            and d.key() != decisions[i].key()]
    assert alts
    seq2, dec2 = replay_with_substitution(g, plat, decisions, i, alts[0], pol)
    names2 = [op.desc() for op in seq2.vector()]
    assert names2[-1] == "finish"
    # exactly one direction's transfer now uses the other engine
    assert sum(1 for n in names2 if ".rdma" in n) == 1


class RiggedBenchmarker:
    """Deterministic: schedules using more rdma transfers are faster."""

    def __init__(self):
        self.calls = 0

    def benchmark(self, order, opts=None):
        self.calls += 1
        n_rdma = sum(1 for op in order.vector() if ".rdma" in op.desc())
        t = 1.0 - 0.1 * n_rdma
        return BenchResult(pct01=t, pct10=t, pct50=t, pct90=t, pct99=t, stddev=0.0)


def test_failed_candidates_emit_structured_events():
    """A schedule the benchmarker rejects leaves a search.candidate_failed
    trace event with the schedule id and exception class, and increments
    the counter (ISSUE 2 satellite)."""
    from tenzing_tpu.obs.metrics import MetricsRegistry, get_metrics, set_metrics
    from tenzing_tpu.obs.tracer import Tracer, set_tracer

    class ExplodingBench:
        def benchmark(self, order, opts=None):
            raise ValueError("cannot compile")

    tr = Tracer(enabled=True)
    prev_tr = set_tracer(tr)
    prev_reg = set_metrics(MetricsRegistry())
    try:
        g, plat, _ = mk()
        with pytest.raises(RuntimeError, match="nothing to climb from"):
            hill_climb(g, plat, ExplodingBench(), PHASES,
                       opts=LocalOpts(budget=4, seed=0))
        evs = [e for e in tr.events() if e.name == "search.candidate_failed"]
        assert evs and evs[0].attrs["where"] == "local.measure"
        assert evs[0].attrs["error"] == "ValueError"
        assert evs[0].attrs["schedule"]
        assert get_metrics().counter("search.candidate_failed").value == 1
    finally:
        set_tracer(prev_tr)
        set_metrics(prev_reg)


def test_hill_climb_discovers_the_rigged_optimum_direction():
    g, plat, _ = mk()
    bench = CachingBenchmarker(RiggedBenchmarker())
    res = hill_climb(
        g, plat, bench, PHASES,
        opts=LocalOpts(budget=40, bench_opts=BenchOpts(n_iters=1), seed=3),
    )
    best = res.best()
    assert best is not None
    start = res.sims[0].result.pct50  # the all-host incumbent
    assert best.result.pct50 < start  # climbed toward rdma flips
    assert any(".rdma" in op.desc() for op in best.order.vector())
