"""SDP State semantics — NoOp graph (reference test/test_noop_graph.cpp:10-44) and
device graph (reference test/test_gpu_graph.cu:41-119)."""

import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import BoundDeviceOp, DeviceOp, NoOp
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.core.state import (
    AssignLane,
    ExecuteOp,
    State,
    get_equivalence,
)


class FakePlatform:
    def __init__(self, n):
        self.lanes = [Lane(i) for i in range(n)]


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


def test_noop_graph_decisions():
    g = Graph()
    op1 = NoOp("op1")
    g.start_then(op1)
    g.then_finish(op1)
    s = State(g)
    # initial state has Start in the sequence
    assert s.sequence.contains(g.start())
    ds = s.get_decisions(FakePlatform(2))
    assert len(ds) == 1
    assert isinstance(ds[0], ExecuteOp) and ds[0].op == op1
    s2 = s.apply(ds[0])
    assert len(s2.sequence) == 2
    # then finish
    ds2 = s2.get_decisions(FakePlatform(2))
    assert len(ds2) == 1 and ds2[0].op == g.finish()
    s3 = s2.apply(ds2[0])
    assert s3.is_terminal()


def test_device_graph_lane_assignment():
    g = Graph()
    k = KOp("k")
    g.start_then(k)
    g.then_finish(k)
    plat = FakePlatform(2)
    s = State(g)
    ds = s.get_decisions(plat)
    # one AssignLane per lane (reference test_gpu_graph.cu:60-80)
    assert len(ds) == 2
    assert all(isinstance(d, AssignLane) for d in ds)
    assert {d.lane for d in ds} == {Lane(0), Lane(1)}
    # assigning lane 0 vs lane 1 yields equivalent states (test_gpu_graph.cu:83-93)
    s0, s1 = s.apply(ds[0]), s.apply(ds[1])
    assert get_equivalence(s0, s1)
    # after binding, an execute decision appears
    ds0 = s0.get_decisions(plat)
    assert len(ds0) == 1 and isinstance(ds0[0], ExecuteOp)
    assert isinstance(ds0[0].op, BoundDeviceOp)


def test_state_frontier_dedups_equivalent_lane_choices():
    g = Graph()
    k = KOp("k")
    g.start_then(k)
    g.then_finish(k)
    s = State(g)
    succs = s.frontier(FakePlatform(2))
    # lane0 and lane1 bindings are equivalent -> one survivor (ref defect fixed)
    assert len(succs) == 1
    succs_nodedup = s.frontier(FakePlatform(2), dedup=False)
    assert len(succs_nodedup) == 2


def test_full_enumeration_two_independent_noops():
    g = Graph()
    a, b = NoOp("a"), NoOp("b")
    g.start_then(a)
    g.start_then(b)
    g.then_finish(a)
    g.then_finish(b)
    plat = FakePlatform(1)

    # exhaustive DFS over states: both interleavings reach terminal
    terminals = []
    stack = [State(g)]
    while stack:
        st = stack.pop()
        if st.is_terminal():
            terminals.append(st)
            continue
        stack.extend(st.frontier(plat, dedup=False))
    assert len(terminals) == 2
    descs = {t.sequence.desc() for t in terminals}
    assert descs == {"start, a, b, finish", "start, b, a, finish"}
