"""Cost models + CallableRunner (VERDICT r2 weak #3: absolute yardsticks)."""

import jax.numpy as jnp

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    CallableRunner,
    EmpiricalBenchmarker,
)
from tenzing_tpu.bench.roofline import (
    V5E_PEAK_BF16_FLOPS,
    attention_cost,
    halo_cost,
    moe_cost,
    spmv_cost,
)


def test_attention_cost_counts_both_matmuls():
    c = attention_cost(batch=2, seq=1024, head_dim=128)
    assert c.flops == 4.0 * 2 * 1024 * 1024 * 128
    assert c.hbm_bytes == 4.0 * 2 * 1024 * 128 * 4
    u = c.utilization(1e-3)
    assert abs(u["mxu_frac"] - c.flops / 1e-3 / V5E_PEAK_BF16_FLOPS) < 1e-12


def test_moe_cost_staged_adds_transfer_bytes():
    plain = moe_cost(1024, 64, 256, staged=False)
    staged = moe_cost(1024, 64, 256, staged=True)
    assert plain.flops == staged.flops == 4.0 * 1024 * 64 * 256
    assert plain.xfer_bytes == 0.0
    assert staged.xfer_bytes == 4.0 * 1024 * 64 * 4


def test_halo_cost_is_byte_bound():
    c = halo_cost(nq=3, lx=512, ly=512, lz=512, radius=3)
    assert c.flops == 0.0
    faces = 2 * 3 * (512 * 512 * 3) * 3  # 3 axis pairs x face cells x nq
    assert c.hbm_bytes == 4.0 * faces * 4
    assert c.xfer_bytes == 2.0 * faces * 4


def test_spmv_cost():
    c = spmv_cost(m=1000, nnz=10_000)
    assert c.flops == 20_000


def test_callable_runner_measures_named_fns():
    import jax

    f = jax.jit(lambda x: (x * 2).sum())
    x = jnp.ones((64,))
    emp = EmpiricalBenchmarker(CallableRunner({
        "a": lambda: jax.device_get(f(x)),
        "b": lambda: jax.device_get(f(x + 1)),
    }))
    times = emp.benchmark_batch_times(
        ["a", "b"], BenchOpts(n_iters=3, target_secs=1e-4), seed=0
    )
    assert len(times) == 2 and all(len(ts) == 3 for ts in times)
    res = emp.benchmark("a", BenchOpts(n_iters=3, target_secs=1e-4))
    assert res.pct50 > 0


def test_repeat_callable_runner_one_fence_per_measurement():
    import jax
    from jax import lax

    from tenzing_tpu.bench.benchmarker import RepeatCallableRunner

    calls = []

    def make_run_n():
        from tenzing_tpu.runtime.executor import datatie

        x = jnp.ones((64, 64))
        # datatie keeps the body loop-carried so XLA cannot fold the loop
        f = jax.jit(lambda n: lax.fori_loop(
            0, n, lambda i, a: datatie(x, a).sum(), jnp.zeros(())))

        def run_n(n):
            calls.append(n)
            jax.device_get(f(jnp.int32(n)))

        return run_n

    emp = EmpiricalBenchmarker(RepeatCallableRunner({"k": make_run_n()}))
    res = emp.benchmark("k", BenchOpts(n_iters=3, target_secs=1e-4))
    assert res.pct50 > 0
    # the adaptive floor converges by growing n inside ONE dispatch, not by
    # multiplying fenced calls: every recorded call is a single run_n(n)
    assert len(calls) >= 4  # warmup + 3 iters (+ growth probes)
