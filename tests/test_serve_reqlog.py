"""Watchtower data plane (ISSUE 13; docs/observability.md
"Watchtower"): the production request log — deterministic sampling,
sealed-segment publish, salvage-on-damage reads, rotation with a
retention cap, the observable position block — and the tail-sampled
exemplar store (slowest-K per window, interesting outcomes kept
immediately, bounded files, stitchable bundles).
"""

import json
import os

import pytest

from tenzing_tpu.serve.reqlog import (
    ExemplarStore,
    RequestLog,
    read_exemplars,
    read_request_log,
    record_digest,
    sampled_in,
)


def _rec(i, tier="exact", outcome="served", trace=None, ts=None):
    return {"v": 1, "ts": 1000.0 + i * 0.01 if ts is None else ts,
            "trace_id": trace or f"{i:016x}", "tenant": "t", "op": "query",
            "outcome": outcome, "tier": tier, "workload": "spmv",
            "exact": "e" * 12, "bucket": "b" * 12,
            "resolve_us": 100.0 + i,
            "request": {"workload": "spmv", "m": 512 + i}}


# -- request log -------------------------------------------------------------

def test_append_publish_read_roundtrip(tmp_path):
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1", segment_records=4)
    for i in range(10):
        assert rl.append(_rec(i)) is True
    rl.flush()
    pos = rl.position()
    assert pos["records"] == 10 and pos["buffered"] == 0
    assert pos["segments"] == 3  # 4 + 4 + flush(2)
    data = read_request_log(d)
    assert len(data["records"]) == 10
    assert data["segments"] == 3
    assert data["damaged"] == 0 and data["checksum_failed"] == 0
    # ts-ordered, kwargs verbatim
    ts = [r["ts"] for r in data["records"]]
    assert ts == sorted(ts)
    assert data["records"][3]["request"] == {"workload": "spmv", "m": 515}


def test_full_buffer_rotates_without_request_path_io(tmp_path):
    """A full buffer becomes a PENDING sealed batch with zero I/O on
    the appending (request-path) thread; the heartbeat-side
    publish_pending pays the fsyncs — unless the pending backlog blows
    the cap, where inline publish (backpressure) beats unbounded
    memory."""
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1", segment_records=2,
                    pending_batch_cap=2)
    for i in range(4):
        rl.append(_rec(i))
    assert not os.path.exists(d)  # two batches pending, no file yet
    assert rl.position()["buffered"] == 4
    assert rl.publish_pending() == 2
    assert rl.position()["buffered"] == 0
    assert len(read_request_log(d)["records"]) == 4
    # storm: the 3rd rotation exceeds cap=2 -> published inline
    for i in range(6):
        rl.append(_rec(10 + i))
    assert rl.position()["segments"] >= 5
    assert rl.position()["buffered"] == 0


def test_sampling_deterministic_and_counted(tmp_path):
    traces = [f"{i:016x}" for i in range(200)]
    kept = {t for t in traces if sampled_in(t, 0.5)}
    # the draw is a stable hash: same verdicts on a second evaluation,
    # and roughly half the population admitted
    assert kept == {t for t in traces if sampled_in(t, 0.5)}
    assert 60 <= len(kept) <= 140
    assert all(sampled_in(t, 1.0) for t in traces)
    assert not any(sampled_in(t, 0.0) for t in traces)
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1", sample=0.5, segment_records=1000)
    n_in = sum(1 for i, t in enumerate(traces)
               if rl.append(_rec(i, trace=t)))
    assert n_in == len(kept)
    pos = rl.position()
    assert pos["dropped_sampling"] == len(traces) - len(kept)
    rl.flush()
    data = read_request_log(d)
    # coverage is reconstructable from the log alone: the header's
    # cumulative dropped count survives the writer process
    assert data["dropped_sampling"] == len(traces) - len(kept)
    assert len(data["records"]) == len(kept)


def test_dropped_sampling_sums_across_writers(tmp_path):
    """Two loops recording into one directory: each header's cumulative
    drop count is per-writer — max within an owner, summed across them
    (one writer's coverage must not shadow the other's)."""
    d = str(tmp_path / "rl")
    for owner, n_drop in (("w1", 3), ("w2", 5)):
        rl = RequestLog(d, owner=owner, sample=0.0)
        for i in range(n_drop):
            assert rl.append(_rec(i)) is False
        rl.sample = 1.0
        rl.append(_rec(99, trace=owner * 8))
        rl.flush()
    data = read_request_log(d)
    assert data["dropped_sampling"] == 8
    assert len(data["records"]) == 2


def test_unserializable_record_coerced_not_fatal(tmp_path):
    """A stray non-JSON value in request kwargs must cost a lossless-ish
    coercion (default=str), never the segment publish — one poisoned
    record must not discard the rest of the buffer."""
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1", segment_records=100)
    rec = _rec(0)
    rec["request"]["blob"] = b"\x00raw"
    assert rl.append(rec) is True
    rl.append(_rec(1))
    rl.flush()
    data = read_request_log(d)
    assert len(data["records"]) == 2 and data["damaged"] == 0
    assert isinstance(data["records"][0]["request"]["blob"], str)


def test_rotation_retention_cap(tmp_path):
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1", segment_records=1, retain_segments=3)
    for i in range(6):
        rl.append(_rec(i))
    rl.flush()
    names = [n for n in os.listdir(d) if n.endswith(".jsonl")]
    assert len(names) == 3
    assert rl.position()["segments_reclaimed"] == 3
    data = read_request_log(d)
    # the newest 3 records survive the rotation
    assert [r["request"]["m"] for r in data["records"]] == [515, 516, 517]


def test_salvage_on_damage(tmp_path):
    d = str(tmp_path / "rl")
    rl = RequestLog(d, owner="t1", segment_records=2)
    for i in range(6):
        rl.append(_rec(i))
    rl.flush()
    names = sorted(n for n in os.listdir(d) if n.endswith(".jsonl"))
    assert len(names) == 3
    # bit-flip one record (checksum mismatch)
    p0 = os.path.join(d, names[0])
    lines = open(p0).read().splitlines()
    bad = json.loads(lines[1])
    bad["record"]["resolve_us"] = 999999.0  # checksum now stale
    lines[1] = json.dumps(bad, sort_keys=True)
    open(p0, "w").write("\n".join(lines) + "\n")
    # torn tail line on another
    p1 = os.path.join(d, names[1])
    open(p1, "a").write('{"sha256": "zz", "reco')
    # truncation on the third (drop the last line below the header count)
    p2 = os.path.join(d, names[2])
    lines2 = open(p2).read().splitlines()
    open(p2, "w").write("\n".join(lines2[:-1]) + "\n")
    data = read_request_log(d)
    assert data["checksum_failed"] == 1
    assert data["torn_lines"] == 1
    assert data["damaged"] == 3
    # every checksum-valid record salvaged: 6 - 1 flipped - 1 truncated
    assert len(data["records"]) == 4
    # read-only: nothing quarantined or renamed
    assert sorted(n for n in os.listdir(d) if n.endswith(".jsonl")) == names


def test_newer_version_skipped_loudly(tmp_path):
    d = str(tmp_path / "rl")
    os.makedirs(d)
    header = {"kind": "reqlog_segment", "version": 99, "n_records": 1}
    rec = _rec(0)
    body = json.dumps(header) + "\n" + json.dumps(
        {"sha256": record_digest(rec), "record": rec}) + "\n"
    open(os.path.join(d, "req-1-x-1.jsonl"), "w").write(body)
    notes = []
    data = read_request_log(d, log=notes.append)
    assert data["newer_skipped"] == 1
    assert data["records"] == []  # future data is not readable data
    assert any("newer version" in n for n in notes)


def test_reader_missing_dir_raises(tmp_path):
    with pytest.raises(OSError):
        read_request_log(str(tmp_path / "nope"))


# -- exemplars ---------------------------------------------------------------

def test_interesting_outcomes_written_immediately(tmp_path):
    d = str(tmp_path / "ex")
    ex = ExemplarStore(d, k=2)
    p = ex.offer(_rec(0, outcome="shed", trace="aa" * 8),
                 interesting="shed")
    assert p is not None and os.path.exists(p)
    ex.offer(_rec(1, outcome="timeout", trace="bb" * 8),
             interesting="timeout")
    headers = read_exemplars(d)
    assert {h["reason"] for h in headers} == {"shed", "timeout"}
    assert headers[0]["record"]["request"]["workload"] == "spmv"
    assert ex.written == 2


def test_slowest_k_per_window(tmp_path):
    d = str(tmp_path / "ex")
    ex = ExemplarStore(d, k=2)
    for i, us in enumerate([50, 900, 120, 80, 700, 60]):
        rec = _rec(i, trace=f"{i:02d}" * 8)
        rec["resolve_us"] = float(us)
        assert ex.offer(rec) is None  # candidates buffer until the roll
    assert read_exemplars(d) == []
    written = ex.roll()
    assert len(written) == 2
    headers = read_exemplars(d)
    assert all(h["reason"] == "slow" for h in headers)
    assert sorted(h["record"]["resolve_us"] for h in headers) == [700, 900]
    # the window closed: a second roll writes nothing new
    assert ex.roll() == []


def test_exemplars_sharing_a_trace_do_not_overwrite(tmp_path):
    """Every member of a shed/errored batch carries the pending's ONE
    trace_id; each must land its own bundle (and be counted once)."""
    d = str(tmp_path / "ex")
    ex = ExemplarStore(d, cap=8)
    paths = [ex.offer(_rec(i, trace="ab" * 8), interesting="shed")
             for i in range(3)]
    assert len(set(paths)) == 3
    assert len(read_exemplars(d)) == 3
    assert ex.written == 3


def test_exemplar_immediate_budget_bounds_a_shed_storm(tmp_path):
    """Interesting outcomes write on the request path — a shed storm
    must cost at most the per-window budget in bundle writes (the rest
    counted suppressed), and the budget refills at the roll."""
    d = str(tmp_path / "ex")
    ex = ExemplarStore(d, k=1, cap=64, immediate_per_window=3)
    written = [ex.offer(_rec(i, trace=f"{i:02d}" * 8),
                        interesting="shed") for i in range(10)]
    assert sum(1 for p in written if p) == 3
    assert ex.suppressed == 7
    assert len(read_exemplars(d)) == 3
    ex.roll()  # window closes: the budget refills
    assert ex.offer(_rec(11, trace="ee" * 8),
                    interesting="timeout") is not None


def test_exemplar_cap_eviction(tmp_path):
    d = str(tmp_path / "ex")
    ex = ExemplarStore(d, k=1, cap=3)
    for i in range(5):
        p = ex.offer(_rec(i, trace=f"{i:02d}" * 8), interesting="error")
        os.utime(p, (1000 + i, 1000 + i))  # distinct mtimes for eviction
    files = [n for n in os.listdir(d) if n.startswith("exemplar-")]
    assert len(files) == 3
    # newest-by-mtime survive
    assert any("0404" in n for n in files)
    assert not any("0000" in n for n in files)


def test_exemplar_bundle_carries_trace_spans_and_stitches(tmp_path):
    from tenzing_tpu.obs import context as obs_context
    from tenzing_tpu.obs.export import read_jsonl, stitch_records
    from tenzing_tpu.obs.tracer import Tracer

    tracer = Tracer(enabled=True)
    ctx = obs_context.new_trace()
    with obs_context.use(ctx):
        with tracer.span("serve.query", tier="exact"):
            pass
    with tracer.span("unrelated.span"):
        pass
    d = str(tmp_path / "ex")
    ex = ExemplarStore(d, tracer=tracer)
    rec = _rec(0, trace=ctx.trace_id)
    path = ex.offer(rec, interesting="timeout")
    recs = read_jsonl(path)
    # line 0 the header, then ONLY this trace's span records
    assert recs[0]["kind"] == "exemplar"
    assert recs[0]["trace_id"] == ctx.trace_id
    spans = [r for r in recs[1:] if r.get("kind") == "span"]
    assert [s["name"] for s in spans] == ["serve.query"]
    assert read_exemplars(d)[0]["n_trace_records"] == 1
    # directly stitchable: the header line is skipped, the span merges
    _, summary = stitch_records([("exemplar", recs)])
    assert ctx.trace_id in summary["traces"]
    assert "serve.query" in summary["traces"][ctx.trace_id]["names"]
