"""Disaster recovery for the serve plane (serve/dr.py; ISSUE 19):
backup -> rm -rf -> restore round-trips byte-identical, merge-restore
into a live store is a superset of both sides, tampered generations
are refused without --force, and fsck's exit codes are a CI gate
(clean / damaged / unreadable) with orphan adoption its only write."""

import json
import os
import shutil
import subprocess
import sys

import pytest

from tenzing_tpu.bench.driver import DriverRequest, graph_for
from tenzing_tpu.serve import dr
from tenzing_tpu.serve.fingerprint import fingerprint_of
from tenzing_tpu.serve.segments import SegmentedStore
from tenzing_tpu.serve.store import open_store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def spmv():
    """(graph, fingerprints, sequences) — same neighborhood as
    tests/test_serve_segments.py."""
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State

    req = DriverRequest(workload="spmv", m=512)
    g, _ = graph_for(req)

    def drive(picks, n_lanes=2):
        plat = Platform.make_n_lanes(n_lanes)
        st = State(g)
        i = 0
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            st = st.apply(ds[picks[i % len(picks)] % len(ds)])
            i += 1
        return st.sequence

    fps = {
        "a": fingerprint_of(req),
        "b": fingerprint_of(DriverRequest(workload="spmv", m=500)),
        "c": fingerprint_of(DriverRequest(workload="spmv", m=100000)),
    }
    seqs = [drive(p) for p in ([0], [1, 2, 0], [2, 1, 0])]
    return g, fps, seqs


def _seed_store(store_dir, spmv, keys=("a", "b")):
    _, fps, seqs = spmv
    s = SegmentedStore(str(store_dir))
    for i, k in enumerate(keys):
        s.add(fps[k], seqs[i % len(seqs)], pct50_us=10.0 + i,
              vs_naive=2.0, verified=True)
    s.flush()
    return s


def _tree_bytes(store_dir):
    """rel-path -> content for every store file (segments + manifest)."""
    out = {}
    for root, _dirs, names in os.walk(store_dir):
        for n in names:
            p = os.path.join(root, n)
            rel = os.path.relpath(p, store_dir)
            if rel.startswith("backups") or rel.endswith(".lock"):
                continue  # lock files are lease artifacts, not content
            with open(p, "rb") as f:
                out[rel] = f.read()
    return out


def _one_segment(store_dir):
    segdir = os.path.join(str(store_dir), "segments")
    return os.path.join(segdir, sorted(
        n for n in os.listdir(segdir) if n.endswith(".jsonl"))[0])


# -- round trip ---------------------------------------------------------------

def test_backup_rm_restore_byte_identical(tmp_path, spmv):
    """The acceptance drill: backup, destroy the store, restore — every
    catalogued file comes back byte-for-byte, and fsck gates clean."""
    store = tmp_path / "store"
    _seed_store(store, spmv)
    before = _tree_bytes(store)

    cat = dr.backup_store(str(store), out_dir=str(tmp_path / "bk"))
    assert cat["n_files"] == len(cat["files"]) >= 2  # segments + manifest

    shutil.rmtree(store)
    out = dr.restore_store(str(store), cat["generation"])
    assert out["mode"] == "verbatim"
    assert _tree_bytes(store) == before

    doc = dr.fsck_store(str(store), check_backups=False)
    assert doc["ok"] and doc["rc"] == dr.RC_CLEAN and doc["records"] == 2


def test_merge_restore_is_a_superset_of_both_sides(tmp_path, spmv):
    """Restore into a LIVE store: records written after the snapshot
    survive, records lost since the snapshot come back."""
    _, fps, seqs = spmv
    store = tmp_path / "store"
    _seed_store(store, spmv, keys=("a",))
    cat = dr.backup_store(str(store), out_dir=str(tmp_path / "bk"))

    # post-snapshot progress that a verbatim restore would clobber
    live = SegmentedStore(str(store))
    live.add(fps["b"], seqs[1], pct50_us=5.0, vs_naive=3.0, verified=True)
    live.flush()

    out = dr.restore_store(str(store), cat["generation"])
    assert out["mode"] == "merge"
    after = open_store(str(store))
    assert after.best(fps["a"].exact_digest) is not None  # snapshot side
    assert after.best(fps["b"].exact_digest) is not None  # post-snapshot


# -- tamper + refusal ---------------------------------------------------------

def test_tampered_generation_refused_without_force(tmp_path, spmv):
    store = tmp_path / "store"
    _seed_store(store, spmv)
    cat = dr.backup_store(str(store), out_dir=str(tmp_path / "bk"))
    gen = cat["generation"]

    # segments are captured by hard link: rewrite (not append) a copy so
    # the damage stays inside the generation
    victim = os.path.join(gen, sorted(
        rel for rel in cat["files"] if rel.endswith(".jsonl"))[0])
    blob = open(victim, "rb").read()
    os.unlink(victim)
    with open(victim, "wb") as f:
        f.write(blob[:-7] + b"garbage")

    verdict = dr.verify_backup(gen)
    assert not verdict["ok"] and verdict["mismatched"]

    shutil.rmtree(store)
    with pytest.raises(dr.DrError):
        dr.restore_store(str(store), gen)
    out = dr.restore_store(str(store), gen, force=True)
    assert out["damaged_skipped"]  # reported, not silently dropped


def test_generation_without_catalog_is_an_aborted_backup(tmp_path):
    gen = tmp_path / "bk" / "gen-123-1"
    os.makedirs(gen / "segments")
    with pytest.raises(dr.DrError):
        dr.load_catalog(str(gen))
    with pytest.raises(dr.DrError):
        dr.restore_store(str(tmp_path / "store"), str(gen))


# -- fsck ---------------------------------------------------------------------

def test_fsck_exit_codes_clean_damaged_unreadable(tmp_path, spmv):
    store = tmp_path / "store"
    _seed_store(store, spmv)
    assert dr.fsck_store(str(store), check_backups=False)["rc"] == \
        dr.RC_CLEAN

    # flip a byte inside a record line: sha256 mismatch = damage
    seg = _one_segment(store)
    blob = bytearray(open(seg, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(seg, "wb") as f:
        f.write(blob)
    doc = dr.fsck_store(str(store), check_backups=False)
    assert doc["rc"] == dr.RC_DAMAGED and doc["errors"]

    assert dr.fsck_store(str(tmp_path / "nope"),
                         check_backups=False)["rc"] == dr.RC_UNREADABLE


def test_fsck_adopts_orphan_segments(tmp_path, spmv):
    """A published-but-unindexed segment (a writer that died between
    publish and manifest update) is a warning read-only, and joins the
    manifest under --adopt — fsck's only permitted write."""
    _, fps, seqs = spmv
    store = tmp_path / "store"
    _seed_store(store, spmv, keys=("a",))

    donor = tmp_path / "donor"
    d = SegmentedStore(str(donor))
    d.add(fps["c"], seqs[2], pct50_us=7.0, vs_naive=4.0, verified=True)
    d.flush()
    shutil.copy2(_one_segment(donor),
                 os.path.join(str(store), "segments",
                              os.path.basename(_one_segment(donor))))

    doc = dr.fsck_store(str(store), check_backups=False)
    assert doc["orphan_segments"] and doc["rc"] == dr.RC_CLEAN

    doc = dr.fsck_store(str(store), adopt=True, check_backups=False)
    assert doc["adopted_orphans"]
    doc = dr.fsck_store(str(store), check_backups=False)
    assert not doc["orphan_segments"]
    assert open_store(str(store)).best(fps["c"].exact_digest) is not None


def test_fsck_stamp_feeds_report_follow(tmp_path, spmv):
    store = tmp_path / "store"
    _seed_store(store, spmv)
    dr.fsck_store(str(store), stamp=True, check_backups=False)
    doc = json.load(open(os.path.join(str(store), dr.FSCK_STAMP)))
    assert doc["kind"] == "fsck" and doc["ok"] and doc["rc"] == 0


# -- the CLI gate -------------------------------------------------------------

def test_serve_cli_backup_restore_fsck_round_trip(tmp_path, spmv):
    """The operator surface: ``serve backup`` then ``serve restore``
    (latest generation by default) then ``serve fsck`` exiting 0."""
    store = tmp_path / "store"
    _seed_store(store, spmv)
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def serve(*argv):
        return subprocess.run(
            [sys.executable, "-m", "tenzing_tpu.serve", *argv],
            cwd=REPO, env=env, capture_output=True, text=True)

    # --out: the default generations root lives INSIDE the store, and
    # this drill is about losing the store
    p = serve("backup", "--store", str(store), "--out",
              str(tmp_path / "bk"))
    assert p.returncode == 0, p.stderr
    shutil.rmtree(store)
    p = serve("restore", "--store", str(store), "--out",
              str(tmp_path / "bk"))
    assert p.returncode == 0, p.stderr
    p = serve("fsck", "--store", str(store), "--stamp", "--no-backups")
    assert p.returncode == 0, p.stderr
    assert json.load(open(os.path.join(
        str(store), dr.FSCK_STAMP)))["ok"]
