"""Self-healing fleet supervisor acceptance (ISSUE 18).

Unit layers drive :class:`Supervisor` tick-by-tick with fake member
handles and a synthetic clock (no sleeps): the crash-loop breaker's
closed/open/half-open cycle, restart backoff, quarantine + the
``supervisor_crash_loop`` page, the scaling policy's hysteresis /
cooldown / poison suppression, adoption from live status docs, and the
retention GC's never-touch-live rules.

The chaos layer runs the real thing: ``python -m
tenzing_tpu.serve.supervisor`` subprocesses over a real queue — a
member SIGKILLed mid-drain restarts and completes its item exactly
once via journal resume; a SIGKILLed *supervisor* is succeeded by one
that adopts the still-running member instead of double-spawning (and a
third contender is excluded by the controller lease, rc 3); a
crash-looping member ends the run quarantined with the breaker open,
the alert firing, and rc 1.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from tenzing_tpu.bench.driver import DriverRequest
from tenzing_tpu.fault.backoff import BackoffPolicy
from tenzing_tpu.obs.alerts import evaluate
from tenzing_tpu.serve.fingerprint import fingerprint_of
from tenzing_tpu.serve.store import WorkQueue
from tenzing_tpu.serve.supervisor import (
    ALERTS_NAME,
    CrashLoopBreaker,
    MemberSlot,
    Supervisor,
    SupervisorOpts,
    _subprocess_member_spawn,
    gc_stale_artifacts,
    supervisor_exit_code,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- harness -----------------------------------------------------------------

class FakeHandle:
    """A member handle the test scripts: alive until ``die(rc)``."""

    def __init__(self, owner):
        self.owner = owner
        self.pid = 99999
        self.returncode = None
        self.signals = []
        self._alive = True

    def alive(self):
        return self._alive

    def send_signal(self, sig):
        self.signals.append(sig)

    def die(self, rc):
        self._alive, self.returncode = False, rc


def _sup(tmp_path, spawn=None, **kw):
    qdir = str(tmp_path / "q")
    store = str(tmp_path / "store")
    os.makedirs(qdir, exist_ok=True)
    os.makedirs(store, exist_ok=True)
    opts = SupervisorOpts(queue_dir=qdir, store_path=store,
                          handle_signals=False, compact_interval_secs=0,
                          gc_interval_secs=0, **kw)
    spawn = spawn or (lambda o, s: FakeHandle(s.owner))
    return Supervisor(opts, spawn=spawn, log=lambda m: None)


# -- the breaker -------------------------------------------------------------

def test_breaker_open_half_open_close_cycle():
    br = CrashLoopBreaker(max_restarts=3, window_secs=60.0,
                          quarantine_secs=100.0, probe_ok_secs=5.0)
    t = 1000.0
    assert br.allow_spawn(t)
    assert br.record_crash(t) == "closed"
    assert br.record_crash(t + 1) == "closed"
    assert br.record_crash(t + 2) == "open"          # 3rd in window
    assert not br.allow_spawn(t + 50)                # quarantined
    assert br.allow_spawn(t + 103)                   # quarantine over
    assert br.state == "half_open"
    br.spawned(t + 103)
    assert not br.allow_spawn(t + 104)               # one probe only
    assert br.record_crash(t + 105) == "open"        # probe died
    assert not br.allow_spawn(t + 106)
    assert br.allow_spawn(t + 206)                   # second probe
    br.spawned(t + 206)
    br.note_healthy(t + 212)                         # probe survived
    assert br.state == "closed" and br.restarts == []
    # the forgotten window really is forgotten: one new crash stays
    # closed instead of instantly re-tripping
    assert br.record_crash(t + 300) == "closed"


def test_breaker_window_slides():
    br = CrashLoopBreaker(max_restarts=3, window_secs=10.0)
    assert br.record_crash(0.0) == "closed"
    assert br.record_crash(1.0) == "closed"
    # the first two crashes age out: no trip
    assert br.record_crash(12.0) == "closed"
    assert br.record_crash(13.0) == "closed"
    assert br.record_crash(14.0) == "open"


# -- restart / quarantine / alert --------------------------------------------

def test_restart_backoff_quarantine_alert_and_recovery(tmp_path):
    sup = _sup(tmp_path,
               breaker_max_restarts=3, breaker_window_secs=60.0,
               breaker_quarantine_secs=50.0,
               backoff=BackoffPolicy(retries=10**6, base_secs=0.5,
                                     factor=2.0, max_secs=30.0,
                                     jitter=0.25))
    t = 1000.0
    sup._scale_up(t)
    slot = sup.slots[0]
    br = sup._breaker_of(slot.owner)
    assert slot.state(br) == "running"

    # crash 1: bounded backoff (deterministic without an rng)
    slot.handle.die(3)
    sup._member_tick(slot, t + 1)
    assert slot.handle is None and slot.restarts == 1
    assert slot.next_spawn_at == pytest.approx(t + 1.5)
    sup._member_tick(slot, t + 1.2)                  # still backing off
    assert slot.handle is None and slot.state(br) == "restarting"
    sup._member_tick(slot, t + 1.6)                  # respawned
    assert slot.handle is not None

    # crash 2: backoff doubles
    slot.handle.die(1)
    sup._member_tick(slot, t + 2)
    assert slot.next_spawn_at == pytest.approx(t + 3.0)
    sup._member_tick(slot, t + 3.1)

    # crash 3 inside the window: breaker OPEN, slot quarantined
    slot.handle.die(1)
    sup._member_tick(slot, t + 4)
    assert br.state == "open"
    assert slot.state(br) == "quarantined"
    assert slot.next_spawn_at == 0.0
    sup._member_tick(slot, t + 20)                   # quarantine holds
    assert slot.handle is None

    # the status doc + alert ledger + watchtower all carry the page
    sup._write_status("supervising")
    doc = json.load(open(sup.status_path))
    assert doc["kind"] == "supervisor"
    assert doc["breakers"][slot.owner]["state"] == "open"
    assert doc["members"][0]["state"] == "quarantined"
    book = json.load(open(os.path.join(sup.opts.queue_dir, ALERTS_NAME)))
    entry = book["alerts"][f"supervisor_crash_loop:{slot.owner}"]
    assert entry["state"] == "firing" and entry["severity"] == "page"
    fired = [a for a in evaluate([sup.store_base], [sup.opts.queue_dir])
             if a.rule == "supervisor_crash_loop"]
    assert len(fired) == 1 and fired[0].subject == slot.owner

    # quarantine expires -> one half-open probe; healthy run closes it
    sup._member_tick(slot, t + 56)
    assert slot.handle is not None and br.state == "half_open"
    sup._member_tick(slot, t + 62)                   # >= probe_ok_secs up
    assert br.state == "closed" and slot.backoff_i == 0
    sup._write_status("supervising")
    book = json.load(open(os.path.join(sup.opts.queue_dir, ALERTS_NAME)))
    entry = book["alerts"][f"supervisor_crash_loop:{slot.owner}"]
    assert entry["state"] == "resolved"


def test_wedged_heartbeat_is_killed_then_restarted(tmp_path):
    sup = _sup(tmp_path, stale_secs=10.0)
    t = 1000.0
    sup._scale_up(t)
    slot = sup.slots[0]
    # a live handle whose status doc heartbeat went silent 20s ago
    with open(os.path.join(sup.opts.queue_dir,
                           f"status-{slot.owner}.json"), "w") as f:
        json.dump({"owner": slot.owner, "pid": 1, "state": "draining",
                   "heartbeat_at": t - 20}, f)
    sup._member_tick(slot, t + 15)                   # uptime > stale too
    assert slot.wedged is True
    assert slot.handle.signals == [signal.SIGKILL]
    slot.handle.die(-9)
    sup._member_tick(slot, t + 16)
    assert slot.restarts == 1 and slot.wedged is False
    assert sup.counters["wedged"] == 1


def test_clean_exit_is_not_a_crash(tmp_path):
    sup = _sup(tmp_path)
    t = 1000.0
    sup._scale_up(t)
    slot = sup.slots[0]
    slot.handle.die(0)
    sup._member_tick(slot, t + 1)
    assert slot.restarts == 0 and slot.clean_exits == 1
    assert sup._breaker_of(slot.owner).restarts == []


# -- scaling policy ----------------------------------------------------------

def test_scaling_hysteresis_cooldown_and_poison_suppression(
        tmp_path, monkeypatch):
    sup = _sup(tmp_path, min_daemons=1, max_daemons=4,
               scale_hold_ticks=3, cooldown_secs=10.0)
    rec = {"n": 1}
    monkeypatch.setattr(
        "tenzing_tpu.serve.supervisor.backlog_summary",
        lambda stores, queues, max_daemons=None, quarantined_owners=None: {
            "recommended_daemons": rec["n"]})
    t = 1000.0
    sup._scale_up(t)                                 # the min fill
    assert sup._active_n() == 1

    # a one-tick spike is hysteresis-filtered
    rec["n"] = 3
    sup._scale_tick(t + 1)
    rec["n"] = 1
    sup._scale_tick(t + 2)
    sup._scale_tick(t + 3)
    sup._scale_tick(t + 4)
    assert sup._active_n() == 1
    assert sup.counters["scale_up"] == 1             # the min fill only

    # a persistent desire scales up ONE step per action
    rec["n"] = 3
    sup._scale_tick(t + 5)
    sup._scale_tick(t + 6)
    sup._scale_tick(t + 7)                           # 3rd hold tick
    assert sup._active_n() == 2
    # cooldown gates the next step...
    sup._scale_tick(t + 8)
    sup._scale_tick(t + 9)
    assert sup._active_n() == 2
    # ...then the still-persistent desire takes the second step
    sup._scale_tick(t + 18)
    sup._scale_tick(t + 19)
    sup._scale_tick(t + 20)
    assert sup._active_n() == 3

    # poison domination suppresses scale-up
    rec["n"] = 4
    monkeypatch.setattr(Supervisor, "_poison_dominated", lambda s: True)
    for dt in (31, 32, 33, 34):
        sup._scale_tick(t + dt)
    assert sup._active_n() == 3
    assert sup._scaling_state["suppressed_poison"] is True
    monkeypatch.setattr(Supervisor, "_poison_dominated", lambda s: False)

    # scale-down SIGTERMs the YOUNGEST member
    rec["n"] = 1
    youngest = max((s for s in sup.slots.values()), key=lambda s: s.k)
    for dt in (45, 46, 47):
        sup._scale_tick(t + dt)
    assert youngest.stopping is True
    assert youngest.handle.signals == [signal.SIGTERM]
    older = [s for s in sup.slots.values() if s is not youngest]
    assert all(not s.stopping for s in older)
    # desired never drops below min_daemons
    assert sup._scaling_state["desired"] >= 1


def test_recommendation_is_clamped_by_max_daemons(tmp_path, monkeypatch):
    sup = _sup(tmp_path, min_daemons=1, max_daemons=2,
               scale_hold_ticks=1, cooldown_secs=0.0)
    monkeypatch.setattr(
        "tenzing_tpu.serve.supervisor.backlog_summary",
        lambda stores, queues, max_daemons=None, quarantined_owners=None: {
            "recommended_daemons": min(50, max_daemons or 50)})
    t = 1000.0
    sup._scale_up(t)
    for dt in range(1, 6):
        sup._scale_tick(t + dt)
    assert sup._active_n() == 2                      # the hard ceiling


# -- adoption ----------------------------------------------------------------

def test_adoption_from_live_status_docs(tmp_path):
    sup = _sup(tmp_path, owner_prefix="fleet")
    qdir = sup.opts.queue_dir
    now = time.time()
    sleeper = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"])
    try:
        def _doc(owner, **kw):
            with open(os.path.join(qdir, f"status-{owner}.json"),
                      "w") as f:
                json.dump({"owner": owner, "pid": sleeper.pid,
                           "state": "draining", "heartbeat_at": now,
                           "started_at": now - 5, **kw}, f)

        _doc("fleet-0")                              # adoptable
        _doc("fleet-1", state="stopped")             # said goodbye
        _doc("fleet-2", heartbeat_at=now - 9999)     # stale heartbeat
        _doc("fleet-3", pid=2 ** 30)                 # dead pid
        assert sup._adopt(now) == 1
        assert sorted(sup.slots) == [0]
        slot = sup.slots[0]
        assert slot.adopted is True
        assert slot.handle.pid == sleeper.pid
        assert sup.counters["adopted"] == 1

        # the adopted member dying is healed like any other death
        sleeper.kill()
        sleeper.wait()
        sup._member_tick(slot, time.time())
        assert slot.handle is None and slot.restarts == 1
    finally:
        if sleeper.poll() is None:
            sleeper.kill()
            sleeper.wait()


# -- scale-down under load: zero loss ----------------------------------------

def _stub_member_spawner(drain_secs):
    """Real in-process DrainDaemons (full lease/claim/status protocol)
    with a fixed-cost stub drain, duck-typed for the supervisor."""
    from tenzing_tpu.serve.daemon import DaemonOpts, DrainDaemon

    def runner(item_path, payload, timeout):
        time.sleep(drain_secs)
        return {"metric": "stub", "value": 1.0, "unit": "us"}

    class _Handle:
        def __init__(self, daemon):
            self._daemon = daemon
            self.returncode = None

            def go():
                daemon.run()
                self.returncode = 0

            self.thread = threading.Thread(target=go, daemon=True)
            self.thread.start()

        def stop(self):                              # the SIGTERM path
            self._daemon.stop()

    def spawn(opts, slot):
        d = DrainDaemon(DaemonOpts(
            queue_dir=opts.queue_dir, store_path=opts.store_path,
            owner=slot.owner, handle_signals=False, in_process=True,
            idle_exit_secs=opts.member_idle_exit_secs or 0.3,
            poll_secs=0.05, lease_ttl_secs=opts.member_lease_ttl_secs,
            heartbeat_secs=0.2, backoff_base_secs=0.01),
            runner=runner, log=lambda m: None)
        return _Handle(d)

    return spawn


def test_scale_down_under_load_loses_nothing(tmp_path):
    """THE scale-down acceptance: SIGTERM the youngest member while the
    queue is still draining — every item completes exactly once (the
    in-flight item is protected by the daemon's own lease protocol),
    proven by the fleet's status-history audit."""
    sup = _sup(tmp_path, spawn=_stub_member_spawner(0.3),
               min_daemons=2, max_daemons=2, tick_secs=0.05,
               heartbeat_secs=0.5, scale_hold_ticks=10**6,
               member_idle_exit_secs=0.4, drain_exit=True,
               max_run_secs=60.0)
    q = WorkQueue(sup.opts.queue_dir)
    fps = []
    for i in range(6):
        req = DriverRequest(workload="spmv", m=512 + 200 * i)
        fp = fingerprint_of(req)
        q.enqueue(fp, req.to_json(), reason="cold")
        fps.append(fp.exact_digest)

    out = {}
    th = threading.Thread(
        target=lambda: out.update(sup.run()), daemon=True)
    th.start()
    t0 = time.time()
    while time.time() - t0 < 20.0:
        running = [s for s in sup.slots.values()
                   if s.handle is not None and not s.stopping]
        if len(running) == 2 and q.leases():
            break
        time.sleep(0.02)
    else:
        pytest.fail("two members never started draining")
    sup._scale_down(time.time())                     # mid-drain SIGTERM
    th.join(timeout=60.0)
    assert not th.is_alive(), "supervisor never drained"

    assert out["reason"] == "drained"
    assert out["double_runs"] == {}
    assert out["audit_complete"] is True
    assert out["queue_after"] == 0 and len(q) == 0
    assert sup.counters["scale_down"] == 1
    completed = set(out["completed_by"])
    assert completed == set(fps), "an item was lost in the scale-down"
    assert all(len(v) == 1 for v in out["completed_by"].values())
    assert supervisor_exit_code(out) == 0


# -- retention GC ------------------------------------------------------------

def test_gc_sweeps_dead_owners_never_live_ones(tmp_path):
    d = str(tmp_path / "q")
    os.makedirs(d)
    now = time.time()
    old = now - 7200

    def _status(owner, state, hb):
        with open(os.path.join(d, f"status-{owner}.json"), "w") as f:
            json.dump({"owner": owner, "state": state,
                       "heartbeat_at": hb}, f)

    def _aged(path, text="{}"):
        with open(path, "w") as f:
            f.write(text)
        os.utime(path, (old, old))

    _status("dead", "stopped", old)                  # swept
    _status("gone", "interrupted", old)              # swept
    _status("fresh", "stopped", now - 10)            # inside retention
    _status("wedged", "draining", old)               # LIVE evidence
    _status("keep-0", "stopped", old)                # keep_owners
    _aged(os.path.join(d, "metrics-dead-0.json"))    # orphaned ring
    _aged(os.path.join(d, "metrics-dead-1.json"))
    _aged(os.path.join(d, "metrics-wedged-0.json"))  # owner still live
    _aged(os.path.join(d, "alerts-dead.json"),
          json.dumps({"alerts": {"x": {"state": "resolved"}}}))
    _aged(os.path.join(d, "alerts-loud.json"),
          json.dumps({"alerts": {"x": {"state": "firing"}}}))
    os.makedirs(os.path.join(d, "exemplars"))
    _aged(os.path.join(d, "exemplars", "exemplar-1.jsonl"))

    counts = gc_stale_artifacts([d], retention_secs=3600.0, now=now,
                                keep_owners=["keep-0"])
    assert counts == {"status": 2, "metrics": 2, "alerts": 1,
                      "exemplars": 1}
    left = sorted(os.listdir(d))
    assert "status-dead.json" not in left
    assert "status-gone.json" not in left
    assert "status-fresh.json" in left               # too young
    assert "status-wedged.json" in left              # never touch live
    assert "status-keep-0.json" in left              # pinned
    assert "metrics-wedged-0.json" in left
    assert "alerts-loud.json" in left                # still firing
    assert not os.listdir(os.path.join(d, "exemplars"))
    # idempotent: a second sweep finds nothing
    again = gc_stale_artifacts([d], retention_secs=3600.0, now=now,
                               keep_owners=["keep-0"])
    assert sum(again.values()) == 0


# -- spawner argv (golden) ---------------------------------------------------

def test_member_spawn_argv_golden(tmp_path, monkeypatch):
    captured = {}

    def fake_popen(cmd, **kw):
        captured["cmd"], captured["kw"] = cmd, kw
        raise RuntimeError("captured")

    monkeypatch.setattr(
        "tenzing_tpu.serve.supervisor.subprocess.Popen", fake_popen)
    opts = SupervisorOpts(queue_dir="/q", store_path="/s",
                          listen_socket="/tmp/x.sock",
                          listen_args=["--busy-poll-us", "50"])

    with pytest.raises(RuntimeError):
        _subprocess_member_spawn(
            opts, MemberSlot(k=-1, owner="fleet-listen", kind="listen"))
    cmd = captured["cmd"]
    # flags AFTER the subcommand: serve/__main__.py attaches --store/
    # --queue to each subparser
    assert cmd[1:4] == ["-m", "tenzing_tpu.serve", "listen"]
    assert cmd[4:] == ["--store", "/s", "--queue", "/q",
                       "--socket", "/tmp/x.sock",
                       "--owner", "fleet-listen", "--busy-poll-us", "50"]
    assert captured["kw"]["start_new_session"] is True

    # default daemon member: fleet.py's argv with the idle-exit pair
    # stripped (a supervised member never idle-exits on its own)
    with pytest.raises(RuntimeError):
        _subprocess_member_spawn(opts, MemberSlot(k=0, owner="fleet-0"))
    assert "--idle-exit" not in captured["cmd"]
    assert "tenzing_tpu.serve.daemon" in captured["cmd"]
    opts.member_idle_exit_secs = 1.5
    with pytest.raises(RuntimeError):
        _subprocess_member_spawn(opts, MemberSlot(k=0, owner="fleet-0"))
    i = captured["cmd"].index("--idle-exit")
    assert captured["cmd"][i + 1] == "1.5"

    # the chaos hook substitutes {owner}
    opts.member_argv = [sys.executable, "-c", "print('{owner}')"]
    with pytest.raises(RuntimeError):
        _subprocess_member_spawn(opts, MemberSlot(k=2, owner="fleet-2"))
    assert captured["cmd"][-1] == "print('fleet-2')"


# -- chaos acceptances (real subprocesses) -----------------------------------

def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _wait_for(pred, timeout_s, what):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        v = pred()
        if v:
            return v
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def _sup_cmd(qdir, store, *extra):
    return [sys.executable, "-m", "tenzing_tpu.serve.supervisor",
            "--queue", qdir, "--store", store,
            "--min-daemons", "1", "--max-daemons", "1",
            "--tick", "0.2", "--heartbeat", "0.3",
            "--compact-interval", "0", "--gc-interval", "0",
            "--scale-hold-ticks", "1000000", *extra]


def test_chaos_member_sigkill_restart_resume_exactly_once(tmp_path):
    """THE supervisor chaos acceptance: a member SIGKILLed mid-drain is
    restarted through backoff; the restarted member reclaims the
    expired item lease, resumes from the checkpoint journal, and the
    item's effect lands exactly once — the supervisor drains out rc 0
    with a clean status-history audit."""
    qdir = str(tmp_path / "q")
    store = str(tmp_path / "store.json")
    q = WorkQueue(qdir)
    req = DriverRequest(workload="attn", smoke=True, mcts_iters=6,
                        climb_budget=6, search_iters=2, iters=6,
                        inject_faults="transient:0.3:7,hang:0.05:11",
                        inject_hang_secs=1.0, measure_timeout=300.0)
    fp = fingerprint_of(req)
    q.enqueue(fp, req.to_json(), reason="cold")
    ckpt = q.checkpoint_dir_for(fp.exact_digest)
    jpath = os.path.join(ckpt, "measurements.jsonl")

    proc = subprocess.Popen(
        _sup_cmd(qdir, store, "--member-lease-ttl", "2",
                 "--member-heartbeat", "0.3", "--member-poll", "0.2",
                 "--member-idle-exit", "1.0", "--backoff-base", "0.3",
                 "--drain-exit"),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        def _journal_lines():
            if not os.path.exists(jpath):
                return 0
            with open(jpath) as f:
                return sum(1 for line in f if line.strip())

        member = _wait_for(
            lambda: _read_json(os.path.join(qdir, "status-fleet-0.json")),
            60.0, "the member's status doc")
        prior = _wait_for(lambda: _journal_lines() >= 2, 300.0,
                          "two journaled measurements") and \
            _journal_lines()
        # SIGKILL the member's whole session: daemon AND drain child
        # die with no chance to release the lease or flush anything
        os.killpg(int(member["pid"]), signal.SIGKILL)
        out, err = proc.communicate(timeout=560)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, err[-2000:]
    summary = json.loads(out.splitlines()[-1])
    assert summary["reason"] == "drained"
    assert summary["counters"]["restarts"] == 1
    assert summary["double_runs"] == {} and summary["audit_complete"]
    assert summary["queue_after"] == 0 and len(q) == 0
    # the restarted member really resumed the dead one's journal
    log = open(os.path.join(ckpt, "drain.log")).read()
    resumes = [line for line in log.splitlines()
               if line.startswith("resume: ")]
    assert resumes, "the restarted drain must resume from the journal"
    assert int(resumes[-1].split()[1]) >= prior >= 2
    verdict = json.load(open(os.path.join(ckpt, "verdict.json")))
    assert verdict["fault"]["resumed"] is True


def test_chaos_supervisor_sigkill_successor_adopts(tmp_path):
    """Supervisor SIGKILL-survivability: the successor adopts the
    still-running member from its live status doc (zero double-spawns),
    a third contender is excluded by the controller lease (rc 3), and
    shutdown reaps the adopted member."""
    qdir = str(tmp_path / "q")
    store = str(tmp_path / "store")
    os.makedirs(store)

    a = subprocess.Popen(
        _sup_cmd(qdir, store, "--owner", "supA", "--lease-ttl", "1.5",
                 "--member-heartbeat", "0.3"),
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        text=True)
    b = c = None
    member = None
    try:
        member = _wait_for(
            lambda: _read_json(os.path.join(qdir, "status-fleet-0.json")),
            60.0, "the member's status doc")
        member_pid = int(member["pid"])
        _wait_for(lambda: (_read_json(
            os.path.join(qdir, "status-supervisor.json")) or {}
        ).get("owner") == "supA", 30.0, "supA's heartbeat")
        a.send_signal(signal.SIGKILL)                # no goodbye
        a.wait()
        os.kill(member_pid, 0)                       # member survived
        time.sleep(1.8)                              # age the lease

        b = subprocess.Popen(
            _sup_cmd(qdir, store, "--owner", "supB", "--lease-ttl",
                     "1.5", "--member-heartbeat", "0.3",
                     "--max-run-secs", "6"),
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        _wait_for(lambda: (_read_json(
            os.path.join(qdir, "status-supervisor.json")) or {}
        ).get("owner") == "supB", 30.0, "supB's takeover heartbeat")
        # a third contender is excluded while B holds the lease
        c = subprocess.run(
            _sup_cmd(qdir, store, "--owner", "supC",
                     "--max-run-secs", "1"),
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert c.returncode == 3, c.stdout + c.stderr
        assert json.loads(c.stdout.splitlines()[-1])["reason"] == \
            "lease_held"

        bout, berr = b.communicate(timeout=60)
        assert b.returncode == 0, berr[-2000:]
        summary = json.loads(bout.splitlines()[-1])
        assert summary["reason"] == "max_run_secs"
        assert summary["counters"]["adopted"] == 1
        assert summary["counters"].get("spawned", 0) == 0, \
            "adoption must not double-spawn"
        assert summary["members"]["fleet-0"]["adopted"] is True
        # shutdown reaped the adopted member
        _wait_for(lambda: not _pid_alive(member_pid), 30.0,
                  "the adopted member to be reaped")
    finally:
        for p in (a, b):
            if p is not None and p.poll() is None:
                p.kill()
                p.communicate()
        if member is not None and _pid_alive(int(member["pid"])):
            try:
                os.killpg(int(member["pid"]), signal.SIGKILL)
            except OSError:
                pass


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def test_chaos_crash_loop_member_ends_quarantined_rc1(tmp_path):
    """A member that exits 3 on every spawn trips the breaker: the run
    drains out degraded — breaker open, ``supervisor_crash_loop``
    firing in the ledger, rc 1."""
    qdir = str(tmp_path / "q")
    store = str(tmp_path / "store")
    os.makedirs(qdir)
    os.makedirs(store)
    r = subprocess.run(
        _sup_cmd(qdir, store, "--tick", "0.05", "--heartbeat", "0.1",
                 "--backoff-base", "0.05", "--backoff-max", "0.1",
                 "--breaker-max-restarts", "2", "--breaker-window", "60",
                 "--breaker-quarantine", "300", "--drain-exit",
                 "--member-argv", json.dumps(
                     [sys.executable, "-c", "import sys; sys.exit(3)"])),
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    summary = json.loads(r.stdout.splitlines()[-1])
    assert summary["breakers"]["fleet-0"]["state"] == "open"
    assert summary["counters"]["quarantined"] == 1
    assert summary["counters"]["restarts"] == 2
    assert summary["double_runs"] == {}
    book = json.load(open(os.path.join(qdir, ALERTS_NAME)))
    entry = book["alerts"]["supervisor_crash_loop:fleet-0"]
    assert entry["state"] == "firing"
    assert supervisor_exit_code(summary) == 1
