"""3D halo exchange on a 2x2x2 virtual mesh (reference C11 semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.halo import (
    DIRECTIONS,
    HaloArgs,
    HaloExchange,
    add_to_graph,
    dir_name,
    make_halo_buffers,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


def make_setup(args=None, mesh_shape=(2, 2, 2)):
    from jax.sharding import Mesh

    args = args if args is not None else HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1)
    devs = np.array(jax.devices()[: np.prod(mesh_shape)]).reshape(mesh_shape)
    mesh = Mesh(devs, ("x", "y", "z"))
    bufs, specs, want = make_halo_buffers(mesh_shape, args, seed=0)
    plat = Platform.make_n_lanes(2, mesh=mesh, specs=specs)
    g = Graph()
    comp = HaloExchange(args)
    g.start_then(comp)
    g.then_finish(comp)
    ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
    return g, plat, ex, want


def test_graph_shape():
    g = add_to_graph(Graph(), HaloArgs())
    # 6 directions x (pack, post, await, unpack) + start/finish: the post and
    # the wait are separate vertices (reference Isend/Wait split)
    assert g.vertex_size() == 26
    for d in DIRECTIONS:
        n = dir_name(d)

        pack = [v for v in g.vertices() if v.name() == f"pack_{n}"][0]
        assert [s.name() for s in g.succs(pack)] == [f"exchange_{n}.xla"]
        post = g.succs(pack)[0]
        assert [s.name() for s in g.succs(post)] == [f"await_{n}"]


@pytest.mark.needs_shard_map
def test_halo_exchange_correct_2x2x2():
    g, plat, ex, want = make_setup()
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    out = ex.run(st.sequence)
    np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)


@pytest.mark.needs_shard_map
def test_halo_exchange_schedules_agree():
    g, plat, ex, want = make_setup()
    states = get_all_sequences(g, plat, max_seqs=3)
    for st in states:
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)


@pytest.mark.needs_shard_map
def test_halo_1d_mesh():
    # degenerate 4x1x1 mesh: only x faces move data across shards
    from jax.sharding import Mesh

    args = HaloArgs(nq=1, lx=4, ly=4, lz=4, radius=2)
    g, plat, ex, want = make_setup(args=args, mesh_shape=(4, 1, 1))
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    out = ex.run(st.sequence)
    np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)
