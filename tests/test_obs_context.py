"""Fleet telemetry plane (ISSUE 12): cross-process trace context
(mint / envelope / env round-trips, ambient span+event stamping,
process-default inheritance), bounded tracer retention with drop
counters, the cross-process bundle stitcher, metric-snapshot ring +
SLO block, the SERVE_BENCH-family regression check, and the --follow
fleet view's renderer."""

import json
import os
import threading
import time

import pytest

from tenzing_tpu.obs import context as obs_context
from tenzing_tpu.obs.context import TRACE_ENV, TraceContext, new_trace
from tenzing_tpu.obs.export import stitch, write_jsonl
from tenzing_tpu.obs.metrics import (
    MetricsRegistry,
    MetricsSnapshotWriter,
    SloConfig,
    baseline_pct99_from,
    get_metrics,
    latest_snapshots,
    set_metrics,
)
from tenzing_tpu.obs.report import check_serve_regression, fleet_lines
from tenzing_tpu.obs.tracer import Tracer, set_tracer


@pytest.fixture
def tracer():
    tr = Tracer(enabled=True)
    prev = set_tracer(tr)
    try:
        yield tr
    finally:
        set_tracer(prev)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        yield reg
    finally:
        set_metrics(prev)


# -- context minting / round-trips ------------------------------------------

def test_mint_and_roundtrips():
    ctx = new_trace()
    assert len(ctx.trace_id) == 16 and len(ctx.span_id) == 16
    assert ctx.trace_id != new_trace().trace_id  # urandom, not seeded
    # envelope round-trip
    back = obs_context.from_json(ctx.to_json())
    assert back == ctx
    # env round-trip
    env = obs_context.to_env({}, ctx)
    assert env[TRACE_ENV] == ctx.to_env_value()
    assert obs_context.from_env(env) == ctx
    # a child shares the trace, renames the hop
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id


def test_malformed_inputs_never_raise():
    assert obs_context.from_json(None) is None
    assert obs_context.from_json("nope") is None
    assert obs_context.from_json({}) is None
    assert obs_context.from_json({"trace_id": ""}) is None
    # a missing span_id degrades, never fails (torn envelope key)
    assert obs_context.from_json({"trace_id": "abc"}).span_id == "0"
    assert obs_context.from_env({}) is None
    assert obs_context.from_env({TRACE_ENV: ""}) is None
    assert obs_context.to_env({}, None) == {}


# -- ambient stamping -------------------------------------------------------

def test_spans_and_events_stamp_ambient_context(tracer):
    ctx = new_trace()
    with obs_context.use(ctx):
        with tracer.span("outer", a=1):
            with tracer.span("inner"):
                pass
        tracer.event("ev", n=2)
    spans = {s.name: s for s in tracer.spans()}
    # the root span carries trace_id + the remote parent hop; nested
    # spans carry only trace_id (their parent chain is in-process)
    assert spans["outer"].attrs["trace_id"] == ctx.trace_id
    assert spans["outer"].attrs["parent_span"] == ctx.span_id
    assert spans["outer"].attrs["a"] == 1
    assert spans["inner"].attrs == {"trace_id": ctx.trace_id}
    assert tracer.events()[0].attrs == {"trace_id": ctx.trace_id, "n": 2}


def test_no_context_means_no_stamp(tracer):
    with tracer.span("s", k="v"):
        pass
    tracer.event("e")
    assert tracer.spans()[0].attrs == {"k": "v"}
    assert tracer.events()[0].attrs == {}


def test_use_none_is_noop(tracer):
    with obs_context.use(None):
        assert obs_context.current() is None
        with tracer.span("s"):
            pass
    assert "trace_id" not in tracer.spans()[0].attrs


def test_process_default_inherited_by_worker_threads(tracer):
    ctx = new_trace()
    prev = obs_context.set_process_default(ctx)
    try:
        def worker():
            with tracer.span("w"):
                pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert tracer.spans()[0].attrs["trace_id"] == ctx.trace_id
        # a thread-local use() wins over the process default
        other = new_trace()
        with obs_context.use(other):
            assert obs_context.current() == other
        assert obs_context.current() == ctx
    finally:
        obs_context.set_process_default(prev)
    assert obs_context.current() is prev


# -- bounded tracer retention -----------------------------------------------

def test_span_event_rings_evict_oldest_and_count_drops():
    tr = Tracer(enabled=True, max_spans=3, max_events=2)
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
        tr.event(f"e{i}")
    assert [s.name for s in tr.spans()] == ["s2", "s3", "s4"]
    assert [e.name for e in tr.events()] == ["e3", "e4"]
    ret = tr.retention()
    assert ret["dropped_spans"] == 2 and ret["dropped_events"] == 3
    assert ret["spans"] == 3 and ret["max_spans"] == 3
    tr.clear()
    assert tr.retention()["dropped_spans"] == 0


def test_snapshot_prunes_dead_thread_state():
    tr = Tracer(enabled=True)
    # overlap the threads (barrier) so the OS cannot recycle idents —
    # four genuinely distinct threads leave four stack/tid entries
    barrier = threading.Barrier(4)

    def worker():
        with tr.span("w"):
            barrier.wait(timeout=10)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(tr._open_stacks) == 4
    assert len({s.tid for s in tr.spans()}) == 4
    tr.snapshot()
    # dead threads' empty stacks and tid mappings are gone; the live
    # (current) thread's state survives only if it recorded anything
    alive = {t.ident for t in threading.enumerate()}
    assert set(tr._open_stacks) <= alive
    assert set(tr._tids) <= alive
    # the recorded spans themselves are untouched
    assert sum(1 for s in tr.spans() if s.name == "w") == 4


# -- stitcher ---------------------------------------------------------------

def test_stitch_groups_bundles_by_trace_id(tmp_path):
    ctx = new_trace()
    bundles = []
    for name, spans in (("ingress", ["serve.query"]),
                        ("daemon", ["daemon.drain", "serve.store.flush"])):
        tr = Tracer(enabled=True)
        with obs_context.use(ctx):
            for s in spans:
                with tr.span(s):
                    pass
        # plus one context-less span that must NOT join the trace
        with tr.span("background"):
            pass
        p = str(tmp_path / f"{name}.jsonl")
        write_jsonl(tr, p)
        bundles.append(p)
    out = str(tmp_path / "merged.json")
    summary = stitch(bundles, out_path=out)
    t = summary["traces"][ctx.trace_id]
    assert t["n_processes"] == 2
    assert t["processes"] == ["daemon.jsonl", "ingress.jsonl"]
    assert set(t["names"]) == {"serve.query", "daemon.drain",
                               "serve.store.flush"}
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    # each bundle is its own Perfetto process, named
    names = {e["args"]["name"] for e in evs if e.get("ph") == "M"
             and e["name"] == "process_name"}
    assert {"ingress.jsonl", "daemon.jsonl"} <= names
    pids = {e["pid"] for e in evs if e.get("ph") == "X"}
    assert len(pids) == 2
    # flow arrows tie the trace across processes (s first, f last)
    flows = [e for e in evs if e.get("cat") == "trace"
             and e.get("id") == ctx.trace_id]
    assert [f["ph"] for f in flows].count("s") == 1
    assert [f["ph"] for f in flows].count("f") == 1
    assert len(flows) == 3  # one anchor per trace-stamped span


def test_stitch_dedups_colliding_basenames(tmp_path):
    ctx = new_trace()
    paths = []
    for sub in ("a", "b"):
        d = tmp_path / sub
        d.mkdir()
        tr = Tracer(enabled=True)
        with obs_context.use(ctx):
            with tr.span("x"):
                pass
        p = str(d / "trace.jsonl")
        write_jsonl(tr, p)
        paths.append(p)
    summary = stitch(paths)
    assert summary["traces"][ctx.trace_id]["n_processes"] == 2
    assert sorted(summary["bundles"]) == ["a/trace.jsonl", "b/trace.jsonl"]


# -- metric snapshots + SLO -------------------------------------------------

def test_snapshot_ring_bound_and_latest(tmp_path, registry, tracer):
    registry.counter("c").inc(3)
    w = MetricsSnapshotWriter(str(tmp_path), "own", ring=3,
                              registry=registry, tracer=tracer)
    for _ in range(7):
        w.write(state="serving", extra={"queue_depth": 1})
    files = sorted(p for p in os.listdir(tmp_path)
                   if p.startswith("metrics-own-"))
    assert len(files) == 3  # the ring bound, not 7 files
    latest = latest_snapshots(str(tmp_path))
    assert set(latest) == {"own"}
    doc = latest["own"]
    assert doc["seq"] == 6 and doc["state"] == "serving"
    assert doc["metrics"]["counters"]["c"] == 3
    assert doc["queue_depth"] == 1
    assert "dropped_spans" in doc["tracer"]


def test_slo_block_target_and_burn(registry):
    hist = registry.histogram("serve.resolve_us.exact")
    for v in (100.0, 120.0, 400.0):
        hist.observe(v)
    slo = SloConfig(target_us=500.0, baseline_pct99_us=300.0)
    b = slo.block(registry)
    assert b["within_target"] is True
    assert b["pct99_us"] == 400.0
    assert b["burn"] == "degrading"  # 400/300 > 1.05
    assert b["vs_baseline"] == round(400.0 / 300.0, 4)
    improving = SloConfig(target_us=200.0, baseline_pct99_us=10_000.0)
    b2 = improving.block(registry)
    assert b2["within_target"] is False and b2["burn"] == "improving"
    flat = SloConfig(baseline_pct99_us=401.0)
    assert flat.block(registry)["burn"] == "flat"
    # an empty registry yields a block with no verdicts, never a crash
    empty = SloConfig(target_us=1.0).block(MetricsRegistry())
    assert empty["pct99_us"] is None and "within_target" not in empty


def test_baseline_pct99_from_replay_doc(tmp_path):
    p = tmp_path / "SERVE_BENCH_rX.json"
    p.write_text(json.dumps({
        "kind": "serve_trace_replay",
        "segmented": {"resolve_us": {"exact": {"pct99_us": 261.0}}}}))
    assert baseline_pct99_from(str(p)) == 261.0
    assert baseline_pct99_from(str(tmp_path / "missing.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert baseline_pct99_from(str(bad)) is None


# -- SERVE_BENCH-family regression check ------------------------------------

def _replay_doc(pct99, verifier=0, shed=0, samples=None):
    if samples is None:
        import random

        rng = random.Random(0)  # i.i.d.-looking: passes the runs test
        samples = [pct99 + rng.uniform(-5, 5) for _ in range(64)]
    return {
        "kind": "serve_trace_replay",
        "segmented": {
            "resolve_us": {"exact": {"pct99_us": pct99, "count": 100}},
            "verifier_calls": verifier,
            "shed": shed,
            "exact_samples_us": samples,
        },
    }


def test_check_serve_regression_ok_and_flagged():
    base = _replay_doc(260.0)
    ok = check_serve_regression(_replay_doc(280.0), base, tol=0.25)
    assert ok["verdict"] == "ok" and not ok["reasons"]
    bad = check_serve_regression(_replay_doc(400.0), base, tol=0.25)
    assert bad["verdict"] == "regression"
    assert any("pct99" in r for r in bad["reasons"])
    # design-guarantee secondaries: verifier calls / shed reappearing
    ver = check_serve_regression(_replay_doc(261.0, verifier=3), base,
                                 tol=0.25)
    assert ver["verdict"] == "regression"
    assert any("verifier" in r for r in ver["reasons"])
    sh = check_serve_regression(_replay_doc(261.0, shed=5), base, tol=0.25)
    assert any("shed" in r for r in sh["reasons"])


def test_check_serve_regression_noise_downgrades():
    base = _replay_doc(260.0)
    # a monotone ramp fails the runs test: the would-be regression is
    # inconclusive (drift/interference), same semantics as the bench gate
    drifty = _replay_doc(900.0, samples=[100.0 + 10 * i for i in range(64)])
    v = check_serve_regression(drifty, base, tol=0.25)
    assert v["verdict"] == "inconclusive"
    assert "runs_test_z" in v["checks"]


# -- follow view renderer ---------------------------------------------------

def test_fleet_lines_renders_serve_and_daemon_state(tmp_path, registry,
                                                    tracer):
    store = tmp_path / "store"
    queue = tmp_path / "queue"
    store.mkdir()
    queue.mkdir()
    now = time.time()
    (store / "status-svc.json").write_text(json.dumps({
        "kind": "serve_loop", "owner": "svc", "state": "serving",
        "heartbeat_at": now, "queue_depth": 2, "in_flight": 1,
        "counters": {"served_exact": 8, "served_near": 1,
                     "served_cold": 1, "shed": 3, "timeouts": 0}}))
    registry.gauge("serve.queue_age_s").set(1.5)
    registry.histogram("serve.resolve_us.exact").observe(42.0)
    MetricsSnapshotWriter(str(store), "svc", registry=registry,
                          tracer=tracer,
                          slo=SloConfig(target_us=100.0)).write()
    (queue / "status-d1.json").write_text(json.dumps({
        "owner": "d1", "state": "draining", "heartbeat_at": now,
        "item": {"exact": "abcd" * 4, "since": now - 5},
        "counters": {"claimed": 3, "completed": 2, "retried": 1,
                     "poisoned": 0}}))
    text = "\n".join(fleet_lines([str(store)], [str(queue)]))
    assert "serve  svc: serving" in text
    assert "mix exact:8 (80%)" in text
    assert "slo:" in text and "target 100us [OK]" in text
    assert "daemon d1: draining" in text
    assert "claimed 3, completed 2" in text
    assert "queue " in text and "depth 0" in text
    # missing dirs are reported, not created
    text2 = "\n".join(fleet_lines([], [str(tmp_path / "nope")]))
    assert "missing directory" in text2
    assert not (tmp_path / "nope").exists()


# -- review-hardening regressions ---------------------------------------------

def test_windowed_histogram_tracks_recent_not_first(registry):
    """An SLO block must read CURRENT traffic: windowed retention keeps
    the most recent max_raw observations (first-N retention would
    freeze the pct99 at pre-warm-up traffic forever)."""
    from tenzing_tpu.obs.metrics import Histogram

    h = registry.histogram("serve.resolve_us.exact", max_raw=8,
                           window=True)
    for v in [10.0] * 8 + [1000.0] * 8:  # regression after the cap fills
        h.observe(v)
    s = h.summary()
    assert s["window"] is True and s["raw_retained"] == 8
    assert s["count"] == 16
    assert s["p99"] == 1000.0, "windowed pct99 must see the regression"
    slo = SloConfig(target_us=100.0, histogram="serve.resolve_us.exact")
    assert slo.block(registry)["within_target"] is False
    # plain histograms keep the documented prefix semantics
    plain = Histogram("x", max_raw=8)
    for v in [10.0] * 8 + [1000.0] * 8:
        plain.observe(v)
    sp = plain.summary()
    assert sp["truncated"] is True and sp["p99"] == 10.0


def test_stitch_labels_unique_for_identical_ckpt_layout(tmp_path):
    """Every drain child writes ckpt-<exact>/trace/trace.jsonl — labels
    must grow path components until the processes separate, or two
    children merge into one Perfetto row and n_processes undercounts."""
    ctx = new_trace()
    paths = []
    for exact in ("ckpt-aaaa", "ckpt-bbbb"):
        d = tmp_path / exact / "trace"
        d.mkdir(parents=True)
        tr = Tracer(enabled=True)
        with obs_context.use(ctx):
            with tr.span("bench.benchmark"):
                pass
        p = str(d / "trace.jsonl")
        write_jsonl(tr, p)
        paths.append(p)
    summary = stitch(paths)
    assert summary["traces"][ctx.trace_id]["n_processes"] == 2
    assert sorted(summary["bundles"]) == [
        "ckpt-aaaa/trace/trace.jsonl", "ckpt-bbbb/trace/trace.jsonl"]


def test_mixed_family_regression_check_is_a_usage_error(tmp_path):
    """--check SERVE_BENCH vs --baseline BENCH (a mis-wired gate) must
    exit 2, not vacuously pass with empty checks."""
    from tenzing_tpu.obs.report import main as report_main

    serve_doc = tmp_path / "serve.json"
    serve_doc.write_text(json.dumps(_replay_doc(260.0)))
    bench_doc = tmp_path / "bench.json"
    bench_doc.write_text(json.dumps({"metric": "m", "value": 1.0,
                                     "vs_baseline": 1.2}))
    assert report_main(["--check", str(serve_doc),
                        "--baseline", str(bench_doc)]) == 2
    assert report_main(["--check", str(bench_doc),
                        "--baseline", str(serve_doc)]) == 2
    # same-family pairs still work through the same CLI
    assert report_main(["--check", str(serve_doc),
                        "--baseline", str(serve_doc)]) == 0


def test_latest_snapshots_survives_seq_reset_across_restart(tmp_path,
                                                            registry,
                                                            tracer):
    """A restarted process starts at seq 0 while the dead incarnation's
    high-seq docs still occupy other ring slots: wall-clock ordering
    must pick the LIVE process's snapshot."""
    w1 = MetricsSnapshotWriter(str(tmp_path), "own", ring=4,
                               registry=registry, tracer=tracer)
    w1.seq = 90  # the old incarnation died at seq 93
    for _ in range(4):
        w1.write(state="serving")
    # the restart: fresh writer, seq 0, strictly later wall clock
    w2 = MetricsSnapshotWriter(str(tmp_path), "own", ring=4,
                               registry=registry, tracer=tracer)
    time.sleep(0.01)
    w2.write(state="idle")
    latest = latest_snapshots(str(tmp_path))
    assert latest["own"]["seq"] == 0
    assert latest["own"]["state"] == "idle"


def test_mint_buffer_unique_and_fork_reset():
    """The buffered urandom pool (ISSUE 14: one syscall per 4 KiB, not
    per id): ids stay 16-hex and unique across refills, and the buffer
    resets empty on the fork hook so a child can never replay the
    parent's entropy window."""
    from tenzing_tpu.obs import context as obs_context

    ids = {obs_context._mint_id() for _ in range(2000)}  # spans refills
    assert len(ids) == 2000
    assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids)
    obs_context._mint_reset()
    assert obs_context._mint_buf == b"" and obs_context._mint_pos == 0
    assert len(obs_context._mint_id()) == 16  # refills transparently
