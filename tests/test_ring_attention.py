"""Ring attention: DAG shape, schedule search, and sharded numerics vs dense
attention (the long-context workload; models/ring_attention.py)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.ring_attention import (
    RingAttention,
    RingAttnArgs,
    make_ring_buffers,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


def _graph(args, impl_choice=False):
    g = Graph()
    g.start_then(RingAttention(args, impl_choice=impl_choice))
    g.then_finish(RingAttention(args, impl_choice=impl_choice))
    return g


def _mesh(nsp):
    devs = np.array(jax.devices()[:nsp])
    return Mesh(devs, ("sp",))


def _has_kind(s, suffix):
    """Whether a schedule contains an op whose name ends with ``suffix``
    (exact suffix: '.pallas' must not match '.pallas_bf16')."""
    return any(op.name().endswith(suffix) for op in s.sequence)


class TestDagShape:
    def test_rotate_overlaps_compute(self):
        """rotate_s and attn_s must be DAG-independent (the searched overlap)."""
        args = RingAttnArgs(n_devices=4)
        g = RingAttention(args).graph()
        by_name = {v.name(): v for v in g.vertices()}
        for s in range(3):
            a, r = by_name[f"attn_{s}"], by_name[f"rotate_{s}"]
            assert r not in g.succs(a) and a not in g.succs(r)

    def test_war_edge_protects_double_buffer(self):
        """attn_{s-1} -> rotate_s: the buffer rotate_s overwrites has been read."""
        args = RingAttnArgs(n_devices=4)
        g = RingAttention(args).graph()
        by_name = {v.name(): v for v in g.vertices()}
        for s in range(1, 3):
            assert by_name[f"rotate_{s}"] in g.succs(by_name[f"attn_{s - 1}"])

    def test_schedule_space_is_nontrivial(self):
        args = RingAttnArgs(n_devices=4)
        plat = Platform.make_n_lanes(2)
        seqs = get_all_sequences(_graph(args), plat, max_seqs=50)
        assert len(seqs) > 1  # order x lane freedom exists


class TestNumerics:
    @pytest.mark.parametrize("nsp", [2, 4])
    @pytest.mark.needs_shard_map
    def test_matches_dense_attention(self, nsp):
        args = RingAttnArgs(n_devices=nsp, batch=2, seq_local=16, head_dim=8)
        bufs, specs, want = make_ring_buffers(args, seed=1)
        plat = Platform.make_n_lanes(2, mesh=_mesh(nsp), specs=specs)
        g = _graph(args)
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        order = get_all_sequences(g, plat, max_seqs=1)[0].sequence
        out = ex.run(order)
        np.testing.assert_allclose(np.asarray(out["O"]), want, rtol=2e-4, atol=2e-5)

    @pytest.mark.needs_shard_map
    def test_every_schedule_is_equivalent(self):
        """A handful of distinct schedules must all compute the same O."""
        args = RingAttnArgs(n_devices=2, batch=1, seq_local=8, head_dim=8)
        bufs, specs, want = make_ring_buffers(args, seed=2)
        plat = Platform.make_n_lanes(2, mesh=_mesh(2), specs=specs)
        seqs = get_all_sequences(_graph(args), plat, max_seqs=6)
        assert len(seqs) >= 2
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        for s in seqs:
            out = ex.run(s.sequence)
            np.testing.assert_allclose(np.asarray(out["O"]), want, rtol=2e-4, atol=2e-5)

    def test_blocked_single_device_matches(self):
        """BlockedAttention (no mesh): blockwise flash over resident K/V."""
        from tenzing_tpu.models.ring_attention import (
            BlockedAttention,
            make_blocked_buffers,
        )

        args = RingAttnArgs(n_devices=4, batch=2, seq_local=8, head_dim=8)
        from tenzing_tpu.solve.dfs import enumerate_schedules

        bufs, want = make_blocked_buffers(args, seed=5)
        plat = Platform.make_n_lanes(2)
        g = Graph()
        g.start_then(BlockedAttention(args, impl_choice=True))
        g.then_finish(BlockedAttention(args, impl_choice=True))
        # fair-share enumeration covers every kernel-menu variant (all-xla,
        # all-pallas f32/bf16, and mixes)
        seqs = enumerate_schedules(g, plat, max_seqs=96)
        pallas = [s for s in seqs
                  if _has_kind(s, ".pallas") and not _has_kind(s, ".pallas_bf16")]
        bf16 = [s for s in seqs if _has_kind(s, ".pallas_bf16")]
        xla = [s for s in seqs
               if not _has_kind(s, ".pallas") and not _has_kind(s, ".pallas_bf16")]
        assert pallas and bf16 and xla
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        for s in (pallas[0], xla[0]):
            out = ex.run(s.sequence)
            np.testing.assert_allclose(np.asarray(out["O"]), want, rtol=2e-4, atol=2e-5)
        # bf16 MXU inputs: ~8-bit mantissa, so a looser but still-tight bound
        out = ex.run(bf16[0].sequence)
        np.testing.assert_allclose(np.asarray(out["O"]), want, rtol=3e-2, atol=3e-2)

    @pytest.mark.needs_shard_map
    def test_pallas_impl_matches(self):
        """The Pallas kernel choice computes the same O (interpret mode)."""
        args = RingAttnArgs(n_devices=2, batch=1, seq_local=8, head_dim=8)
        bufs, specs, want = make_ring_buffers(args, seed=3)
        plat = Platform.make_n_lanes(1, mesh=_mesh(2), specs=specs)
        seqs = get_all_sequences(_graph(args, impl_choice=True), plat, max_seqs=90)
        pallas = [s for s in seqs
                  if _has_kind(s, ".pallas") and not _has_kind(s, ".pallas_bf16")]
        bf16 = [s for s in seqs if _has_kind(s, ".pallas_bf16")]
        assert pallas and bf16
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        out = ex.run(pallas[0].sequence)
        np.testing.assert_allclose(np.asarray(out["O"]), want, rtol=2e-4, atol=2e-5)
        out = ex.run(bf16[0].sequence)
        np.testing.assert_allclose(np.asarray(out["O"]), want, rtol=3e-2, atol=3e-2)


class TestFusedBlockAttn:
    def test_fused_engine_choice_matches(self):
        """The fused single-kernel flash alternatives (AttnEngineChoice)
        compute the same O as the per-block chain and the dense host
        reference (interpret mode)."""
        from tenzing_tpu.models.ring_attention import (
            BlockedAttention,
            make_blocked_buffers,
        )
        from tenzing_tpu.solve.dfs import enumerate_schedules

        args = RingAttnArgs(n_devices=4, batch=2, seq_local=8, head_dim=8)
        bufs, want = make_blocked_buffers(args, seed=5)
        plat = Platform.make_n_lanes(2)
        g = Graph()
        op = BlockedAttention(args, impl_choice=True, fused_choice=True)
        g.start_then(op)
        g.then_finish(op)
        # 3^4 per-block chain variants enumerate before the 2 fused
        # structural variants — the budget must cover all 83
        seqs = enumerate_schedules(g, plat, max_seqs=128)
        fused = [s for s in seqs
                 if _has_kind(s, ".fused") and not _has_kind(s, ".fused_bf16")]
        fused_bf16 = [s for s in seqs if _has_kind(s, ".fused_bf16")]
        chain = [s for s in seqs
                 if any(op.name().startswith("attn_0.") for op in s.sequence)]
        assert fused and fused_bf16 and chain
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        for s in (fused[0], chain[0]):
            out = ex.run(s.sequence)
            np.testing.assert_allclose(
                np.asarray(out["O"]), want, rtol=2e-4, atol=2e-5)
        out = ex.run(fused_bf16[0].sequence)
        np.testing.assert_allclose(
            np.asarray(out["O"]), want, rtol=3e-2, atol=3e-2)

    def test_fused_kernel_equals_chained_kernel(self):
        """attn_fused_pallas == chained attn_block_pallas on the same state
        (ragged n exercises the q-tile padding path)."""
        from tenzing_tpu.ops.attention_pallas import (
            attn_block_pallas,
            attn_fused_pallas,
        )

        b, n, d, nkv, bkv = 1, 24, 16, 64, 16
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.standard_normal((b, n, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, nkv, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, nkv, d)), jnp.float32)
        acc = jnp.zeros((b, n, d), jnp.float32)
        m = jnp.full((b, n, d), -1e30, jnp.float32)
        l = jnp.zeros((b, n, d), jnp.float32)
        scale = 1 / np.sqrt(d)
        a1, m1, l1 = acc, m, l
        for s in range(nkv // bkv):
            a1, m1, l1 = attn_block_pallas(
                q, k[:, s * bkv:(s + 1) * bkv], v[:, s * bkv:(s + 1) * bkv],
                a1, m1, l1, scale)
        a2, m2, l2 = attn_fused_pallas(q, k, v, acc, m, l, scale, bkv=bkv)
        np.testing.assert_allclose(np.asarray(a1), np.asarray(a2),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(m1), np.asarray(m2),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=2e-5, atol=2e-5)
