"""Replay of a real recorded TPU search (VERDICT r1 item 6).

``experiments/halo_search_tpu.csv`` is the dumped result database of an MCTS
search over the single-chip halo pipeline (reference config nQ=3, 512^3 cells,
radius 3) run on a TPU v5e: row 0 is the naive sequential baseline, the
remaining rows are searched candidates over order x lane x kernel choice.
These tests drive CsvBenchmarker and postprocess with that real data — the
reference's offline-replay workflow (benchmarker.cpp:169-223,
postprocess.py:27-120) — instead of synthesized rows.
"""

import os

import pytest

from tenzing_tpu.bench.benchmarker import CsvBenchmarker
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.halo import HaloArgs
from tenzing_tpu.models.halo_pipeline import build_graph, naive_order

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSV_PATH = os.path.join(REPO, "experiments", "halo_search_tpu.csv")

# the configuration the search was recorded at (BASELINE.md halo config)
ARGS = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)


@pytest.fixture(scope="module")
def db():
    """The searched rows, anchored to the impl_choice graph (recorded ops carry
    .xla/.pallas choice names, which graph-anchored deserialization resolves by
    descending into the menus).  Row 0 — the naive baseline — was recorded from
    the pre-choice graph and is skipped here; ``db_naive`` covers it."""
    g = build_graph(ARGS, impl_choice=True)
    return CsvBenchmarker.from_file(CSV_PATH, g, strict=False)


@pytest.fixture(scope="module")
def db_naive():
    g = build_graph(ARGS, impl_choice=False)
    return CsvBenchmarker.from_file(CSV_PATH, g, strict=False)


def test_all_recorded_rows_deserialize(db, db_naive):
    # 13 recorded rows: 12 searched (choice graph) + 1 naive (plain graph)
    assert len(db.entries) == 12 and db.skipped == [0]
    assert len(db_naive.entries) == 1 and len(db_naive.skipped) == 12
    for seq, res in list(db.entries) + list(db_naive.entries):
        assert len(seq) >= 32  # 30 pipeline ops + start/finish (+ syncs)
        assert res.pct50 > 0


def test_recorded_rows_answer_their_own_queries(db):
    for seq, res in db.entries:
        assert db.benchmark(seq).pct50 == res.pct50


def test_naive_order_matches_recorded_baseline_row(db_naive):
    """The naive schedule as the framework builds it today must be
    bijection-equivalent to the recorded naive row — guards the serdes
    round-trip and the naive_order construction against drift."""
    plat = Platform.make_n_lanes(2)
    res = db_naive.benchmark(naive_order(ARGS, plat))
    assert res.pct50 == db_naive.entries[0][1].pct50


def test_searched_beats_naive_outside_noise(db, db_naive):
    """The north-star signal (BASELINE.md), on real recorded data: the best
    searched schedule beats the naive baseline by more than one stddev of
    either measurement."""
    naive = db_naive.entries[0][1]
    best = min((r for _, r in db.entries), key=lambda r: r.pct50)
    assert best.pct50 < naive.pct50
    margin = naive.pct50 - best.pct50
    assert margin > max(best.stddev, naive.stddev), (
        f"margin {margin*1e3:.2f} ms not outside noise "
        f"(stddev {naive.stddev*1e3:.2f}/{best.stddev*1e3:.2f} ms)"
    )


def test_round2_recording_also_replays():
    """The round-2 full-budget recording (naive + greedy-overlap incumbent +
    24 MCTS iterations, same config; incumbent/naive rows carry the
    decorrelated final-batch measurements) loads and shows the same structure:
    best candidate under naive."""
    path = os.path.join(REPO, "experiments", "halo_search_tpu_r2.csv")
    n_rows = sum(1 for line in open(path) if line.strip())
    g = build_graph(ARGS, impl_choice=True)
    db2 = CsvBenchmarker.from_file(path, g, strict=False)
    # rows 0 (naive) and 1 (greedy incumbent) come from the pre-choice graph
    g_plain = build_graph(ARGS, impl_choice=False)
    db2_plain = CsvBenchmarker.from_file(path, g_plain, strict=False)
    assert len(db2.entries) == n_rows - 2 and db2.skipped == [0, 1]
    assert len(db2_plain.entries) == 2
    naive = db2_plain.entries[0][1]
    cands = [db2_plain.entries[1][1]] + [r for _, r in db2.entries]
    assert min(r.pct50 for r in cands) < naive.pct50

    # and the postprocess analyzer handles the full-budget recording too
    import io

    from postprocess.postprocess import analyze

    with open(path) as f:
        out = analyze(f.read(), stream=io.StringIO())
    assert out["n"] == n_rows


def test_round2c_recording_replays_with_decisive_margin():
    """The round-2 re-run under the tightened verdict protocol (3x final
    iterations, 20x measurement floor — bench.py): paired speedup 1.198,
    95% CI [1.189, 1.207].  The recording replays, and the recorded final
    -batch margin itself is decisive: best candidate under naive by more
    than both stddevs."""
    path = os.path.join(REPO, "experiments", "halo_search_tpu_r2c.csv")
    n_rows = sum(1 for line in open(path) if line.strip())
    g = build_graph(ARGS, impl_choice=True)
    db = CsvBenchmarker.from_file(path, g, strict=False)
    g_plain = build_graph(ARGS, impl_choice=False)
    db_plain = CsvBenchmarker.from_file(path, g_plain, strict=False)
    assert len(db.entries) == n_rows - 2 and db.skipped == [0, 1]
    assert len(db_plain.entries) == 2
    naive = db_plain.entries[0][1]
    best = min(
        [db_plain.entries[1][1]] + [r for _, r in db.entries],
        key=lambda r: r.pct50,
    )
    assert best.pct50 < naive.pct50
    assert naive.pct50 - best.pct50 > max(best.stddev, naive.stddev)


def test_moe_recording_replays_with_decisive_margin():
    """The MoE dispatch/combine pipeline search recorded on TPU v5e
    (bench.py --workload moe, 8192 tokens, 8 experts, 4 chunk chains):
    paired speedup 1.506, 95% CI [1.498, 1.517] — the searched software
    -pipelined schedule hides the host round-trip DMAs behind expert
    compute.  Rows 0/1 (naive, greedy incumbent) are from the plain graph,
    the rest from the kernel-choice graph."""
    from tenzing_tpu.models.moe_pipeline import (
        MoEPipeArgs,
        build_graph as moe_build,
        make_pipe_buffers,
        naive_order as moe_naive,
    )

    path = os.path.join(REPO, "experiments", "moe_search_tpu.csv")
    n_rows = sum(1 for line in open(path) if line.strip())
    margs = MoEPipeArgs()  # the bench config: 8192 tokens, 8 experts, 4 chunks
    _bufs, _want, cap = make_pipe_buffers(margs, seed=0, with_expected=False)
    db = CsvBenchmarker.from_file(path, moe_build(margs, cap, impl_choice=True),
                                  strict=False)
    db_plain = CsvBenchmarker.from_file(path, moe_build(margs, cap),
                                        strict=False)
    assert len(db.entries) == n_rows - 2 and db.skipped == [0, 1]
    assert len(db_plain.entries) == 2
    naive = db_plain.entries[0][1]
    best = min(
        [db_plain.entries[1][1]] + [r for _, r in db.entries],
        key=lambda r: r.pct50,
    )
    # stddev is dominated by the host-hiccup outlier tail (recorded naive:
    # pct99 22 ms vs pct50 6.6 ms), so the robust margin criterion is
    # percentile-based: the best schedule's *median* beats even naive's 1st
    # percentile, and the margin exceeds naive's pct10-pct90 spread
    assert best.pct50 < naive.pct01
    assert naive.pct50 - best.pct50 > naive.pct90 - naive.pct10
    # today's naive construction is bijection-equivalent to the recorded row
    res = db_plain.benchmark(moe_naive(margs, cap, Platform.make_n_lanes(1)))
    assert res.pct50 == naive.pct50


def test_attn_bf16_recording_replays_with_decisive_margin():
    """The blocked-attention search recorded on TPU v5e with the 3-way kernel
    menu (XLA / Pallas f32 / Pallas bf16 — bench.py --workload attn, 8k
    context): paired speedup 4.329, 95% CI [4.284, 4.347].  Every row —
    naive, the all-bf16 incumbent, and the MCTS candidates — anchors to the
    kernel-choice graph."""
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.models.ring_attention import BlockedAttention, RingAttnArgs

    path = os.path.join(REPO, "experiments", "attn_search_tpu_bf16.csv")
    n_rows = sum(1 for line in open(path) if line.strip())
    aargs = RingAttnArgs(n_devices=8, batch=4, seq_local=1024, head_dim=128)
    g = Graph()
    g.start_then(BlockedAttention(aargs, impl_choice=True))
    g.then_finish(BlockedAttention(aargs, impl_choice=True))
    db = CsvBenchmarker.from_file(path, g, strict=True)
    assert len(db.entries) == n_rows
    naive = db.entries[0][1]
    best_seq, best = min(db.entries, key=lambda e: e[1].pct50)
    assert best.pct50 < naive.pct01  # decisive under percentile criterion
    # the winning schedule uses the bf16 kernel on every block
    n_bf16 = sum(1 for op in best_seq if op.name().endswith(".pallas_bf16"))
    assert n_bf16 == 8


def test_postprocess_on_real_recorded_data():
    """Class-boundary + decision-tree analysis runs on the real CSV and finds
    the searched-fast vs naive-slow structure."""
    from postprocess.postprocess import analyze, load_rows

    with open(CSV_PATH) as f:
        text = f.read()
    import io

    rows = load_rows(text)
    assert len(rows) == 13
    out = analyze(text, stream=io.StringIO())
    assert out["n"] == 13
    assert len(out["classes"]) == 13
    assert max(out["classes"]) >= 0


# -- round-3 recorded databases (transfer-engine menu in the space) ----------

R3C_PATH = os.path.join(REPO, "experiments", "halo_search_tpu_r3c.csv")
ATTN_R3_PATH = os.path.join(REPO, "experiments", "attn_search_tpu_r3.csv")


@pytest.fixture(scope="module")
def db_r3c():
    """The 1.337x flagship database: rows mix host-staged, RDMA and
    mixed-engine schedules over the full kernel x engine choice graph."""
    g = build_graph(ARGS, impl_choice=True, xfer_choice=True)
    return CsvBenchmarker.from_file(R3C_PATH, g, strict=False)


def test_r3_flagship_rows_deserialize_and_answer(db_r3c):
    # the searched rows anchor against the menus (incl. RdmaCopyStart inside
    # TransferChoice and spill/fetch inside the HostRoundTrip compound); the
    # naive row was recorded from the engine-free graph and may be skipped
    assert len(db_r3c.entries) >= 90
    engines = set()
    for seq, res in db_r3c.entries:
        assert res.pct50 > 0
        names = [op.desc() for op in seq.vector()]
        engines.add("rdma" if any(".rdma" in n for n in names) else "host")
        assert db_r3c.benchmark(seq).pct50 == res.pct50
    assert engines == {"rdma", "host"}  # both engines present in the record


def test_r3_attn_rows_deserialize_and_answer():
    import jax.numpy as jnp  # noqa: F401

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.models.ring_attention import BlockedAttention, RingAttnArgs

    aargs = RingAttnArgs(n_devices=8, batch=4, seq_local=1024, head_dim=128)
    g = Graph()
    g.start_then(BlockedAttention(aargs, impl_choice=True))
    g.then_finish(BlockedAttention(aargs, impl_choice=True))
    db = CsvBenchmarker.from_file(ATTN_R3_PATH, g, strict=False)
    assert len(db.entries) >= 90
    for seq, res in list(db.entries)[:10]:
        assert db.benchmark(seq).pct50 == res.pct50
