"""Drain-daemon acceptance (ISSUE 9): leased claims, crash-resume,
poison quarantine — the serve→search→serve loop closed end-to-end.

The protocol tests drive :class:`DrainDaemon` in-process with stub
runners (claim exclusivity, reclaim, retry/poison policy, status JSON)
— no device, no search.  The chaos acceptance runs the real thing: a
cold attn-smoke work item drained by the real subprocess runner under
seeded transient+hang injection, the daemon SIGKILLed mid-item, and a
restarted daemon reclaiming the expired lease and completing the item
via checkpoint resume (journaled measurements replayed, store warmed,
re-query answers exact-tier) — the item's effect lands exactly once.
"""

import collections
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from tenzing_tpu.bench.driver import DriverConfigError, DriverRequest
from tenzing_tpu.fault.checkpoint import atomic_write_json, read_checked_json
from tenzing_tpu.fault.errors import (
    DeterministicScheduleError,
    DeviceLostError,
    TransientError,
)
from tenzing_tpu.serve.daemon import (
    DaemonOpts,
    DrainDaemon,
    apply_overrides,
    parse_override,
)
from tenzing_tpu.serve.fingerprint import fingerprint_of
from tenzing_tpu.serve.store import ScheduleStore, WorkQueue

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _enqueue(qdir, m=512, **req_kw):
    q = WorkQueue(qdir)
    req = DriverRequest(workload="spmv", m=m, **req_kw)
    fp = fingerprint_of(req)
    q.enqueue(fp, req.to_json(), reason="cold")
    return q, fp


def _opts(tmp_path, **kw):
    base = dict(queue_dir=str(tmp_path / "q"),
                store_path=str(tmp_path / "store.json"),
                once=True, handle_signals=False, heartbeat_secs=0.1,
                backoff_base_secs=0.01, owner="t")
    base.update(kw)
    return DaemonOpts(**base)


def _ok_verdict(*_a, **_k):
    return {"metric": "m", "value": 1.0, "unit": "us", "vs_baseline": 1.2}


def test_drain_completes_deletes_item_and_lease_after_merge(tmp_path):
    q, fp = _enqueue(str(tmp_path / "q"))
    d = DrainDaemon(_opts(tmp_path),
                    runner=lambda p, pl, t: _ok_verdict(), log=lambda m: None)
    s = d.run()
    assert s["drained"] == 1 and s["counters"]["completed"] == 1
    assert len(q) == 0
    assert not os.path.exists(q.lease_path_for(fp.exact_digest))
    assert not os.path.exists(q.fail_path_for(fp.exact_digest))
    # the store was flushed by the merge step (empty drain CSV → 0
    # records admitted, but the store file exists and loads)
    assert os.path.exists(str(tmp_path / "store.json"))
    h = d.history[-1]
    assert h["outcome"] == "completed" and h["resumed"] is False
    # status JSON: the liveness document a probe reads
    st = json.load(open(d.status_path))
    assert st["owner"] == "t" and st["state"] == "stopped"
    assert st["counters"]["completed"] == 1
    assert st["history"][-1]["exact"] == fp.exact_digest


def test_claim_is_exclusive_and_lease_heartbeat_renews(tmp_path):
    q, fp = _enqueue(str(tmp_path / "q"))
    a = DrainDaemon(_opts(tmp_path, owner="a"), runner=_ok_verdict,
                    log=lambda m: None)
    b = DrainDaemon(_opts(tmp_path, owner="b"), runner=_ok_verdict,
                    log=lambda m: None)
    exact = fp.exact_digest
    lease = a._claim(exact)
    assert lease is not None
    assert b._claim(exact) is None  # fresh lease: rival must lose
    before = os.path.getmtime(lease)
    time.sleep(0.05)
    assert a._renew(lease) is True
    assert os.path.getmtime(lease) >= before
    doc = json.load(open(lease))
    assert doc["owner"] == "a" and doc["exact"] == exact
    a._release(lease)
    assert not os.path.exists(lease)


def test_expired_lease_is_reclaimed_live_lease_is_not(tmp_path):
    q, fp = _enqueue(str(tmp_path / "q"))
    exact = fp.exact_digest
    lease = q.lease_path_for(exact)
    with open(lease, "w") as f:
        json.dump({"owner": "dead-worker"}, f)
    past = time.time() - 999
    os.utime(lease, (past, past))
    d = DrainDaemon(_opts(tmp_path, lease_ttl_secs=60),
                    runner=lambda p, pl, t: _ok_verdict(), log=lambda m: None)
    s = d.run()
    assert s["counters"]["reclaimed"] == 1 and s["counters"]["completed"] == 1
    # fresh lease: not reclaimable, item not claimable
    q2, fp2 = _enqueue(str(tmp_path / "q2"), m=500)
    l2 = q2.lease_path_for(fp2.exact_digest)
    with open(l2, "w") as f:
        json.dump({"owner": "alive"}, f)
    d2 = DrainDaemon(_opts(tmp_path, queue_dir=str(tmp_path / "q2"),
                           lease_ttl_secs=300),
                     runner=lambda p, pl, t: _ok_verdict(),
                     log=lambda m: None)
    s2 = d2.run()
    assert s2["counters"]["claimed"] == 0 and len(q2) == 1


def test_renew_detects_lost_lease_by_nonce(tmp_path):
    q, fp = _enqueue(str(tmp_path / "q"))
    d = DrainDaemon(_opts(tmp_path), runner=_ok_verdict, log=lambda m: None)
    lease = d._claim(fp.exact_digest)
    # a rival reclaims during our stall: same path, rival's claim nonce
    # (inode numbers recycle on unlink, so the payload nonce is the
    # lease identity)
    os.unlink(lease)
    with open(lease, "w") as f:
        json.dump({"owner": "rival", "nonce": "rival-1-2"}, f)
    assert d._renew(lease) is False
    assert d._lease_lost.is_set()
    # and release must NOT delete a lease that is no longer ours
    d._release(lease)
    assert os.path.exists(lease)


def test_transient_failure_retries_then_leaves_item(tmp_path):
    q, fp = _enqueue(str(tmp_path / "q"))
    calls = []

    def flaky(item_path, payload, timeout):
        calls.append(1)
        raise TransientError("tunnel reset")

    d = DrainDaemon(_opts(tmp_path, retries=2), runner=flaky,
                    log=lambda m: None)
    s = d.run()
    assert len(calls) == 3  # 1 + 2 bounded retries (fault/backoff.py)
    assert s["counters"]["retried"] == 2
    assert s["counters"]["failed_transient"] == 1
    assert s["counters"]["poisoned"] == 0
    assert len(q) == 1  # the item survives for a later pass / worker
    # the failure history records the transient (economics, not poison)
    fails = json.load(open(q.fail_path_for(fp.exact_digest)))
    assert fails["attempts"][-1]["error_class"] == "transient"


def test_poison_after_n_deterministic_failures_survives_restarts(tmp_path):
    q, fp = _enqueue(str(tmp_path / "q"))
    exact = fp.exact_digest

    def broken(item_path, payload, timeout):
        raise DeterministicScheduleError("bad request, forever")

    # two separate daemon processes-worth of attempts: the count is
    # persistent (fail-<exact>.json), not in-memory
    d1 = DrainDaemon(_opts(tmp_path, max_failures=2), runner=broken,
                     log=lambda m: None)
    assert d1.run()["counters"]["poisoned"] == 0
    assert os.path.exists(q.fail_path_for(exact))
    d2 = DrainDaemon(_opts(tmp_path, max_failures=2), runner=broken,
                     log=lambda m: None)
    s2 = d2.run()
    assert s2["counters"]["poisoned"] == 1
    poison = read_checked_json(q.poison_path_for(exact))
    assert poison["kind"] == "poisoned_request"
    assert len(poison["attempts"]) == 2
    assert all(a["error_class"] == "deterministic"
               for a in poison["attempts"])
    assert poison["exact"] == exact
    assert poison["request"]["workload"] == "spmv"
    # item + sidecar are gone; the queue never offers the item again
    assert len(q) == 0
    assert not os.path.exists(q.fail_path_for(exact))
    d3 = DrainDaemon(_opts(tmp_path, max_failures=2), runner=broken,
                     log=lambda m: None)
    assert d3.run()["counters"]["claimed"] == 0
    # and the rot is visible: queue stats carry the poison set
    st = q.stats()
    assert st["poisoned"] == [f"poison-{exact}.json"]


def test_device_lost_stops_the_daemon(tmp_path):
    qdir = str(tmp_path / "q")
    q, _ = _enqueue(qdir, m=500)
    _enqueue(qdir, m=512)

    def dead(item_path, payload, timeout):
        raise DeviceLostError("chip rebooted")

    d = DrainDaemon(_opts(tmp_path, once=False, idle_exit_secs=30),
                    runner=dead, log=lambda m: None)
    s = d.run()  # must stop after the FIRST device-lost, not spin
    assert d.history[-1]["outcome"] == "device_lost"
    assert s["counters"]["claimed"] == 1
    assert len(q) == 2  # nothing consumed


def test_two_concurrent_daemons_zero_double_runs(tmp_path):
    """The acceptance bullet: two daemons, one multi-item queue, every
    item drained exactly once."""
    qdir = str(tmp_path / "q")
    for m in (500, 512, 520, 540):
        _enqueue(qdir, m=m)
    runs = collections.Counter()
    lock = threading.Lock()

    def runner(item_path, payload, timeout):
        with lock:
            runs[item_path] += 1
        time.sleep(0.15)  # hold the lease long enough for real overlap
        return _ok_verdict()

    ds = [DrainDaemon(_opts(tmp_path, owner=o), runner=runner,
                      log=lambda m: None) for o in ("a", "b")]
    ts = [threading.Thread(target=d.run) for d in ds]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(runs) == 4 and all(v == 1 for v in runs.values()), runs
    assert sum(d.counters["completed"] for d in ds) == 4
    assert len(WorkQueue(qdir)) == 0


def test_graceful_stop_releases_lease_and_stamps_interrupted(tmp_path):
    q, fp = _enqueue(str(tmp_path / "q"))
    d = DrainDaemon(_opts(tmp_path, once=False), log=lambda m: None)

    def slow(item_path, payload, timeout):
        d.stop()  # a stop request lands mid-drain...
        return _ok_verdict()  # ...the in-flight item still finishes

    d._runner = slow
    s = d.run()
    assert s["counters"]["completed"] == 1
    assert not os.path.exists(q.lease_path_for(fp.exact_digest))
    st = json.load(open(d.status_path))
    assert st["state"] in ("stopped", "interrupted")


def test_override_identity_guard_and_parsing():
    req = DriverRequest(workload="spmv", m=512).to_json()
    # budget overrides pass and apply
    eff = apply_overrides(req, {"mcts_iters": 4, "climb_budget": 2})
    assert eff.mcts_iters == 4 and eff.m == 512
    # identity overrides refuse: the merged record would land under a
    # different fingerprint than the queued request's
    with pytest.raises(DriverConfigError):
        apply_overrides(req, {"m": 4096})
    with pytest.raises(DriverConfigError):
        apply_overrides(req, {"no_such_field": 1})
    assert parse_override("mcts_iters=8") == ("mcts_iters", 8)
    assert parse_override("inject_faults=transient:0.3:7") == \
        ("inject_faults", "transient:0.3:7")
    with pytest.raises(ValueError):
        parse_override("not-a-pair")


def test_report_queue_section_mines_daemon_state(tmp_path):
    """The report CLI's queue section (ISSUE 9 satellite): lease ages,
    daemon status + heartbeat staleness, poison quarantine, per-item
    drain economics — all from the queue directory alone."""
    from tenzing_tpu.obs.report import queue_section

    qdir = str(tmp_path / "q")
    q, fp = _enqueue(qdir)

    def broken(item_path, payload, timeout):
        raise DeterministicScheduleError("always broken")

    d = DrainDaemon(_opts(tmp_path, max_failures=1), runner=broken,
                    log=lambda m: None)
    d.run()
    # leave a live lease + a torn item behind for the section to show
    q2, fp2 = _enqueue(qdir, m=500)
    with open(q.lease_path_for(fp2.exact_digest), "w") as f:
        json.dump({"owner": "someone", "nonce": "x"}, f)
    with open(os.path.join(qdir, "work-torn.json"), "w") as f:
        f.write("{")
    text = "\n".join(queue_section(qdir))
    assert "poisoned" in text and fp.exact_digest[:12] in text
    assert "someone" in text  # the lease owner with its heartbeat age
    assert "work-torn.json" in text
    assert "daemon `t`" in text  # the status document
    assert "| item | outcome |" in text  # per-item drain economics


def test_torn_item_is_counted_and_visible(tmp_path):
    from tenzing_tpu.obs.metrics import get_metrics

    qdir = str(tmp_path / "q")
    q, fp = _enqueue(qdir)
    with open(os.path.join(qdir, "work-torn.json"), "w") as f:
        f.write("{")
    before = get_metrics().counter("serve.queue.torn").value
    items = q.items()
    assert len(items) == 1  # the drainer still never crashes on it
    assert [os.path.basename(p) for p in q.torn_paths] == ["work-torn.json"]
    assert get_metrics().counter("serve.queue.torn").value == before + 1
    # re-scanning the SAME damage does not inflate the counter...
    q.items()
    assert get_metrics().counter("serve.queue.torn").value == before + 1
    # ...but a rewrite (new damage) counts again
    time.sleep(0.01)
    with open(os.path.join(qdir, "work-torn.json"), "w") as f:
        f.write("{{")
    os.utime(os.path.join(qdir, "work-torn.json"))
    q.items()
    assert get_metrics().counter("serve.queue.torn").value >= before + 1
    # the torn set rides queue stats (serve stats / report CLI)
    assert "work-torn.json" in q.stats()["torn"]


# -- the chaos acceptance (real driver, real subprocesses) -------------------

def _wait_journal(jpath, n, timeout_s=300.0):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        if os.path.exists(jpath):
            with open(jpath) as f:
                got = sum(1 for line in f if line.strip())
            if got >= n:
                return got
        time.sleep(0.1)
    raise AssertionError(f"journal never reached {n} lines")


def test_chaos_sigkill_mid_item_reclaim_resume_exactly_once(tmp_path):
    """SIGKILL the daemon (and its drain child) mid-item under seeded
    transient+hang injection; a restarted daemon reclaims the expired
    lease and completes via checkpoint resume — journaled measurements
    replayed (the driver's ``resume:`` line + ``fault.resumed``), store
    warmed, re-query exact — the item's effect lands exactly once.

    Telemetry-plane acceptance rides along (ISSUE 12): the work item is
    enqueued under a trace context, and the SUCCESSOR daemon — which
    never saw the originating process — resumes the drain under the
    SAME trace_id (re-read from the envelope), stamping it into its own
    bundle and its drain child's."""
    from tenzing_tpu.obs.context import new_trace

    qdir = str(tmp_path / "q")
    store = str(tmp_path / "store.json")
    q = WorkQueue(qdir)
    req = DriverRequest(workload="attn", smoke=True, mcts_iters=6,
                        climb_budget=6, search_iters=2, iters=6,
                        inject_faults="transient:0.3:7,hang:0.05:11",
                        inject_hang_secs=1.0, measure_timeout=300.0)
    fp = fingerprint_of(req)
    ctx = new_trace()
    q.enqueue(fp, req.to_json(), reason="cold", trace=ctx)
    exact = fp.exact_digest
    ckpt = q.checkpoint_dir_for(exact)

    daemon = subprocess.Popen(
        [sys.executable, "-m", "tenzing_tpu.serve.daemon",
         "--queue", qdir, "--store", store,
         "--poll", "0.2", "--heartbeat", "0.3", "--lease-ttl", "2"],
        cwd=REPO, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        prior = _wait_journal(os.path.join(ckpt, "measurements.jsonl"), 2)
    finally:
        # SIGKILL the whole group: daemon AND its drain child die with
        # no chance to release the lease or flush anything
        os.killpg(daemon.pid, signal.SIGKILL)
        daemon.wait()
    assert os.path.exists(q.lease_path_for(exact)), \
        "a SIGKILLed worker must leave its lease behind (mtime now stale)"
    assert len(q) == 1, "the item must survive the kill"
    time.sleep(2.2)  # age the lease past the TTL

    daemon_bundle = str(tmp_path / "daemon.jsonl")
    r = subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.serve.daemon",
         "--queue", qdir, "--store", store, "--once", "--lease-ttl", "2",
         "--trace-out", daemon_bundle],
        cwd=REPO, capture_output=True, text=True, timeout=500)
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.splitlines()[-1])
    assert summary["counters"]["reclaimed"] == 1, summary
    assert summary["counters"]["completed"] == 1, summary

    # checkpoint resume actually replayed the dead worker's measurements
    log = open(os.path.join(ckpt, "drain.log")).read()
    resumes = [line for line in log.splitlines()
               if line.startswith("resume: ")]
    assert resumes, "the restarted drain must resume from the journal"
    restored = int(resumes[-1].split()[1])
    assert restored >= prior >= 2
    verdict = json.load(open(os.path.join(ckpt, "verdict.json")))
    assert verdict["fault"]["resumed"] is True
    assert verdict["fault"]["injected"]  # the chaos spec really fired

    # exactly once: item + lease consumed, store warmed, re-query exact
    assert len(q) == 0
    assert not os.path.exists(q.lease_path_for(exact))
    st = ScheduleStore(store)
    assert st.best(exact) is not None
    from tenzing_tpu.serve.resolver import Resolver

    res = Resolver(st).resolve(req)
    assert res.tier == "exact"
    assert res.provenance["compiles"] == 0

    # the successor — a fresh process that never met the enqueuer —
    # drained under the envelope's trace_id: its own bundle (daemon.drain
    # + the store merge) and its drain child's both carry it, and the
    # stitcher ties the two processes into one trace
    from tenzing_tpu.obs.export import read_jsonl, stitch

    drain_spans = [rec for rec in read_jsonl(daemon_bundle)
                   if rec.get("name") == "daemon.drain"]
    assert drain_spans, "successor daemon recorded no drain span"
    assert drain_spans[0]["attrs"]["trace_id"] == ctx.trace_id
    merge_spans = [rec for rec in read_jsonl(daemon_bundle)
                   if rec.get("name") == "serve.store.flush"]
    assert merge_spans
    assert merge_spans[0]["attrs"]["trace_id"] == ctx.trace_id
    child_bundle = os.path.join(ckpt, "trace", "trace.jsonl")
    assert os.path.exists(child_bundle), \
        "the traced daemon's child must archive its own bundle"
    child_traced = [rec for rec in read_jsonl(child_bundle)
                    if (rec.get("attrs") or {}).get("trace_id")
                    == ctx.trace_id]
    assert child_traced, "child spans must carry the item's trace_id"
    merged = stitch([daemon_bundle, child_bundle])
    t = merged["traces"][ctx.trace_id]
    assert t["n_processes"] == 2
    assert "daemon.drain" in t["names"]
    assert "serve.store.flush" in t["names"]


def test_malformed_item_poisons_through_the_real_child(tmp_path):
    """A deterministic-failure item (unknown workload → DriverConfigError
    before any backend touch) lands in the poison quarantine through the
    real subprocess runner — the error class crosses the process
    boundary via the verdict report, not stderr scraping."""
    qdir = str(tmp_path / "q")
    store = str(tmp_path / "store.json")
    q = WorkQueue(qdir)
    good = DriverRequest(workload="spmv", m=512)
    fp = fingerprint_of(good)
    bad = good.to_json()
    bad["workload"] = "bogus"
    os.makedirs(qdir, exist_ok=True)
    atomic_write_json(q.path_for(fp.exact_digest), {
        "kind": "search_request", "reason": "cold",
        "fingerprint": fp.to_json(), "request": bad,
        "checkpoint": q.checkpoint_dir_for(fp.exact_digest),
    })
    r = subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.serve.daemon",
         "--queue", qdir, "--store", store, "--once", "--max-failures", "1"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.splitlines()[-1])
    assert summary["counters"]["poisoned"] == 1, summary
    poison = read_checked_json(q.poison_path_for(fp.exact_digest))
    assert poison["attempts"][-1]["error_class"] == "deterministic"
    assert "bogus" in poison["attempts"][-1]["message"]
    assert len(q) == 0


# -- fleet telemetry plane (ISSUE 12): trace-context propagation -------------

def test_drain_runs_under_item_trace_context(tmp_path):
    """The trace context stamped into the work-item envelope at enqueue
    is ambient for the whole drain: the daemon.drain span AND the store
    merge's serve.warm / serve.store.flush spans carry its trace_id."""
    from tenzing_tpu.obs.context import new_trace
    from tenzing_tpu.obs.tracer import Tracer, set_tracer

    qdir = str(tmp_path / "q")
    q = WorkQueue(qdir)
    req = DriverRequest(workload="spmv", m=512)
    fp = fingerprint_of(req)
    ctx = new_trace()
    q.enqueue(fp, req.to_json(), reason="cold", trace=ctx)
    item = read_checked_json(q.path_for(fp.exact_digest))
    assert item["trace"] == ctx.to_json()

    tr = Tracer(enabled=True)
    prev = set_tracer(tr)
    try:
        d = DrainDaemon(_opts(tmp_path),
                        runner=lambda p, pl, t: _ok_verdict(),
                        log=lambda m: None)
        s = d.run()
        assert s["counters"]["completed"] == 1
    finally:
        set_tracer(prev)
    spans = {s.name: s for s in tr.spans()}
    assert spans["daemon.drain"].attrs["trace_id"] == ctx.trace_id
    assert spans["serve.warm"].attrs["trace_id"] == ctx.trace_id
    assert spans["serve.store.flush"].attrs["trace_id"] == ctx.trace_id
    # an item enqueued WITHOUT a trace drains unstamped (no leakage of
    # the previous item's context through the process default)
    q.enqueue(fp, req.to_json(), reason="cold")
    tr2 = Tracer(enabled=True)
    prev = set_tracer(tr2)
    try:
        DrainDaemon(_opts(tmp_path),
                    runner=lambda p, pl, t: _ok_verdict(),
                    log=lambda m: None).run()
    finally:
        set_tracer(prev)
    drain2 = [s for s in tr2.spans() if s.name == "daemon.drain"]
    assert drain2 and "trace_id" not in drain2[0].attrs


def test_exec_item_adopts_envelope_then_env_and_restores(tmp_path,
                                                         monkeypatch):
    """exec_item prefers the envelope's trace (the SIGKILL-survivable
    copy), falls back to the env var, and restores the process default
    on the way out (the in-process drain loop must not leak item N's
    context into item N+1)."""
    from tenzing_tpu.obs import context as obs_context
    from tenzing_tpu.obs.context import TRACE_ENV, new_trace
    from tenzing_tpu.serve import daemon as daemon_mod

    seen = {}

    def fake_run(req):
        seen["ctx"] = obs_context.current()

        class R:
            verdict = {"metric": "m", "value": 1.0}

        return R()

    import tenzing_tpu.bench.driver as driver_mod

    monkeypatch.setattr(driver_mod, "run", fake_run)
    q = WorkQueue(str(tmp_path / "q"))
    req = DriverRequest(workload="spmv", m=512)
    fp = fingerprint_of(req)
    env_ctx = new_trace()
    monkeypatch.setenv(TRACE_ENV, env_ctx.to_env_value())
    # envelope wins over env
    envelope_ctx = new_trace()
    path = q.enqueue(fp, req.to_json(), reason="cold", trace=envelope_ctx)
    daemon_mod.exec_item(read_checked_json(path), path)
    assert seen["ctx"].trace_id == envelope_ctx.trace_id
    assert obs_context.current() is None  # restored
    # env is the fallback when the envelope has no trace
    path = q.enqueue(fp, req.to_json(), reason="cold")
    daemon_mod.exec_item(read_checked_json(path), path)
    assert seen["ctx"].trace_id == env_ctx.trace_id
    assert obs_context.current() is None
