"""Lint (ISSUE 1 satellite): no bare ``print(`` in tenzing_tpu/ library code.

All human-facing output must flow through ``obs.progress.ProgressReporter``
(progress/diagnostics) or an explicit stream write (``sys.stdout.write`` for
machine-readable dumps like the CSV partial-dump paths) — a bare ``print``
bypasses both the telemetry event stream and stream discipline, and one
stray line on stdout corrupts the drivers' one-JSON-line protocol.

Tokenize-based (not regex): ``print`` inside strings, comments, and
docstrings does not trip it.  The allowlist exists for CLI dump paths not
yet migrated to the reporter — currently empty; add ``"subdir/file.py"``
(path relative to tenzing_tpu/) entries only with a migration plan.
"""

import io
import tokenize
from pathlib import Path

LIBRARY_ROOT = Path(__file__).resolve().parent.parent / "tenzing_tpu"

# relative-to-tenzing_tpu paths allowed to keep bare print() until migrated
ALLOWLIST: set = set()


def _print_calls(source: str):
    """(line, col) of every ``print(`` call in ``source``."""
    toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    hits = []
    for i, tok in enumerate(toks):
        if tok.type == tokenize.NAME and tok.string == "print":
            # attribute access (x.print) is not the builtin
            prev = next((t for t in reversed(toks[:i])
                         if t.type not in (tokenize.NL, tokenize.NEWLINE,
                                           tokenize.INDENT, tokenize.DEDENT,
                                           tokenize.COMMENT)), None)
            if prev is not None and prev.type == tokenize.OP and prev.string == ".":
                continue
            nxt = next((t for t in toks[i + 1:]
                        if t.type not in (tokenize.NL, tokenize.NEWLINE,
                                          tokenize.COMMENT)), None)
            if nxt is not None and nxt.type == tokenize.OP and nxt.string == "(":
                hits.append((tok.start[0], tok.start[1]))
    return hits


def test_no_bare_print_in_library_code():
    offenders = []
    for path in sorted(LIBRARY_ROOT.rglob("*.py")):
        rel = path.relative_to(LIBRARY_ROOT).as_posix()
        if rel in ALLOWLIST:
            continue
        for line, col in _print_calls(path.read_text()):
            offenders.append(f"tenzing_tpu/{rel}:{line}:{col}")
    assert not offenders, (
        "bare print() in library code (route through "
        "obs.progress.get_reporter() or an explicit stream write):\n  "
        + "\n  ".join(offenders)
    )


def test_allowlist_entries_still_exist():
    """A stale allowlist entry hides nothing — prune it."""
    for rel in ALLOWLIST:
        assert (LIBRARY_ROOT / rel).is_file(), f"stale allowlist entry: {rel}"


def test_print_detector_self_check():
    src = (
        "x = 'print(not me)'\n"
        "# print(also not me)\n"
        "obj.print('method, not builtin')\n"
        "print('caught')\n"
    )
    assert _print_calls(src) == [(4, 0)]
