"""Canonical-key dedup agrees with the pairwise bijection scan.

VERDICT r2 weak #5: ``solve/dfs.py`` and ``core/state.py`` paid O(n^2)
pairwise ``get_equivalence`` scans although ``canonical_key``
(core/sequence.py:123) decides sequence bijection-equivalence in O(1) per
lookup.  These tests pin the replacement to the semantic ground truth: on
graphs whose enumeration mixes lane bindings, sync events and parallel
branches, the canonical-key dedup keeps exactly one representative per
pairwise-equivalence class (reference dedup semantics dfs.hpp:88-113,
state.cpp:121).
"""

import itertools
import random

import pytest

from tenzing_tpu.core import sequence as sequence_mod
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp, NoOp
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.core.sequence import Sequence, canonical_key
from tenzing_tpu.core.state import State
from tenzing_tpu.solve.dfs import (
    _dedup_terminal_states,
    get_all_sequences,
    get_unique_sequences,
)


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


class FakePlatform:
    def __init__(self, n):
        self.lanes = [Lane(i) for i in range(n)]


def fork_graph(n_dev: int = 2, n_cpu: int = 1) -> Graph:
    """n_dev independent device ops (lane choices + cross-lane syncs) plus
    n_cpu independent host ops — a space with many bijection duplicates."""
    g = Graph()
    for i in range(n_dev):
        op = KOp(f"k{i}")
        g.start_then(op)
        g.then_finish(op)
    for i in range(n_cpu):
        op = NoOp(f"c{i}")
        g.start_then(op)
        g.then_finish(op)
    return g


def pairwise_unique(seqs):
    """The ground-truth dedup: first representative per pairwise class."""
    uniq = []
    for s in seqs:
        if not any(sequence_mod.get_equivalence(s, u) for u in uniq):
            uniq.append(s)
    return uniq


@pytest.mark.parametrize("n_dev,n_cpu,n_lanes", [(1, 1, 2), (2, 0, 2), (2, 1, 2), (3, 0, 3)])
def test_terminal_dedup_matches_pairwise(n_dev, n_cpu, n_lanes):
    g = fork_graph(n_dev, n_cpu)
    plat = FakePlatform(n_lanes)
    raw = [st.sequence for st in get_all_sequences(g, plat, max_seqs=5000)]
    want = pairwise_unique(raw)
    got = [st.sequence for st in get_unique_sequences(g, plat, max_seqs=5000)]
    # same class count, and classes correspond 1:1 under pairwise equivalence
    assert len(got) == len(want)
    for s in got:
        assert any(sequence_mod.get_equivalence(s, w) for w in want)
    for w in want:
        assert any(sequence_mod.get_equivalence(w, s) for s in got)


def test_dedup_terminal_states_matches_pairwise():
    g = fork_graph(2, 1)
    plat = FakePlatform(2)
    states = get_all_sequences(g, plat, max_seqs=5000)
    got = _dedup_terminal_states(states)
    want = pairwise_unique([st.sequence for st in states])
    assert len(got) == len(want)
    for st in got:
        assert any(sequence_mod.get_equivalence(st.sequence, w) for w in want)


def test_canonical_key_iff_equivalence_random_orders():
    """Property check on random op orders: keys equal <=> bijection exists."""
    g = fork_graph(2, 1)
    plat = FakePlatform(2)
    seqs = [st.sequence for st in get_all_sequences(g, plat, max_seqs=5000)]
    rng = random.Random(0)
    sample = rng.sample(seqs, min(20, len(seqs)))
    for a, b in itertools.combinations(sample, 2):
        eq = bool(sequence_mod.get_equivalence(a, b))
        assert (canonical_key(a) == canonical_key(b)) == eq


def test_frontier_dedup_matches_pairwise():
    """State.frontier's bucketed dedup = the unbucketed pairwise dedup."""
    from tenzing_tpu.core.state import get_equivalence as state_eq

    g = fork_graph(2, 1)
    plat = FakePlatform(2)
    # walk a few levels, comparing bucketed vs pairwise dedup at each step
    level = [State(g)]
    for _ in range(4):
        nxt = []
        for st in level:
            if st.is_terminal():
                continue
            succs = st.frontier(plat, dedup=False)
            want = []
            for s in succs:
                if not any(state_eq(s, t) for t in want):
                    want.append(s)
            got = st.frontier(plat, dedup=True)
            assert len(got) == len(want)
            for s in got:
                assert any(state_eq(s, t) for t in want)
            nxt.extend(got)
        level = nxt[:6]  # keep the walk small
        if not level:
            break
