"""Serving-store concurrency, merge algebra, schema evolution, and
corruption handling (ISSUE 7 satellite; docs/serving.md).

The store's contract is the fleet story: independently-written stores
must combine without loss (two writers, disjoint and overlapping), merge
must be commutative and idempotent (merge order across hosts is
arbitrary), a schema bump must load old records, and a corrupt store
file must be quarantined for post-mortem — never fatal, never silently
clobbered.
"""

import json
import os

import pytest

from tenzing_tpu.bench.driver import DriverRequest, graph_for
from tenzing_tpu.serve.fingerprint import fingerprint_of
from tenzing_tpu.serve.store import (
    RECORD_SCHEMA,
    ScheduleStore,
    WorkQueue,
    merge_records,
    migrate_record,
)


@pytest.fixture(scope="module")
def spmv():
    """(graph, fingerprints, sequences): one workload neighborhood with
    enough distinct schedules to exercise every store path."""
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State

    req = DriverRequest(workload="spmv", m=512)
    g, _ = graph_for(req)

    def drive(picks, n_lanes=2):
        plat = Platform.make_n_lanes(n_lanes)
        st = State(g)
        i = 0
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            st = st.apply(ds[picks[i % len(picks)] % len(ds)])
            i += 1
        return st.sequence

    fps = {
        "a": fingerprint_of(req),
        "b": fingerprint_of(DriverRequest(workload="spmv", m=500)),
    }
    seqs = [drive(p) for p in ([0], [1, 2, 0], [2, 1, 0], [1, 0, 2])]
    return g, fps, seqs


def test_two_writers_disjoint_fingerprints(tmp_path, spmv):
    _, fps, seqs = spmv
    path = str(tmp_path / "store.json")
    a = ScheduleStore(path, tenant="host-a")
    b = ScheduleStore(path, tenant="host-b")  # loaded before a flushed
    a.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    a.flush()
    b.add(fps["b"], seqs[2], pct50_us=12.0, vs_naive=1.5)
    b.flush()  # flush re-reads + merges: a's record must survive
    merged = ScheduleStore(path)
    assert len(merged) == 2
    assert merged.best(fps["a"].exact_digest)["vs_naive"] == 2.0
    assert merged.best(fps["b"].exact_digest)["vs_naive"] == 1.5
    tenants = {r["provenance"]["tenant"] for r in merged.records()}
    assert tenants == {"host-a", "host-b"}


def test_two_writers_overlapping_fingerprint(tmp_path, spmv):
    _, fps, seqs = spmv
    path = str(tmp_path / "store.json")
    a = ScheduleStore(path, tenant="host-a")
    b = ScheduleStore(path, tenant="host-b")
    # same fingerprint, same schedule: the better measurement must win
    # regardless of flush order, and both source sets must survive
    a.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    b.add(fps["a"], seqs[1], pct50_us=8.0, vs_naive=2.5)
    # and a second schedule only one writer knows about
    b.add(fps["a"], seqs[2], pct50_us=11.0, vs_naive=1.8)
    a.flush()
    b.flush()
    merged = ScheduleStore(path)
    assert len(merged) == 2  # two distinct schedules under one exact
    assert merged.best(fps["a"].exact_digest)["vs_naive"] == 2.5


def _store_doc(store: ScheduleStore) -> str:
    return json.dumps(store.to_json(), sort_keys=True)


def test_merge_commutative_and_idempotent(tmp_path, spmv):
    _, fps, seqs = spmv

    def mk(tag, entries):
        s = ScheduleStore(str(tmp_path / f"{tag}.json"), tenant=tag)
        for fp, seq, pct, vs in entries:
            s.add(fp, seq, pct50_us=pct, vs_naive=vs)
        return s

    def x():
        return mk("x", [(fps["a"], seqs[1], 10.0, 2.0),
                        (fps["a"], seqs[2], 11.0, 1.8),
                        (fps["b"], seqs[3], 9.0, 2.2)])

    def y():
        return mk("y", [(fps["a"], seqs[1], 9.0, 2.4),   # conflict: better
                        (fps["b"], seqs[1], 14.0, 1.2)])  # disjoint slot

    xy = x()
    xy.merge_from(y())
    yx = y()
    yx.merge_from(x())
    assert _store_doc(xy) == _store_doc(yx)  # commutative
    xyx = x()
    xyx.merge_from(y())
    xyx.merge_from(y())
    xyx.merge_from(x())
    assert _store_doc(xyx) == _store_doc(xy)  # idempotent
    # conflict resolved to the better record, sources/tenant of winner
    assert xy.best(fps["a"].exact_digest)["vs_naive"] == 2.4


def test_merge_records_flags_sticky_and_sources_union():
    a = {"schema": RECORD_SCHEMA, "exact": "e", "bucket": "b", "key": "k",
         "ops": [], "workload": "spmv", "vs_naive": 2.0, "pct50_us": 10.0,
         "sources": ["s1"], "flags": {"needs_refinement": True}}
    b = {"schema": RECORD_SCHEMA, "exact": "e", "bucket": "b", "key": "k",
         "ops": [], "workload": "spmv", "vs_naive": 2.5, "pct50_us": 9.0,
         "sources": ["s2"], "flags": {"unsound": False}}
    m1, m2 = merge_records(a, b), merge_records(b, a)
    assert m1 == m2
    assert m1["vs_naive"] == 2.5
    assert m1["sources"] == ["s1", "s2"]
    assert m1["flags"] == {"needs_refinement": True, "unsound": False}


def test_schema_v1_record_loads_with_defaults(tmp_path, spmv):
    _, fps, seqs = spmv
    path = str(tmp_path / "store.json")
    s = ScheduleStore(path)
    s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    s.flush()
    doc = json.load(open(path))
    (exact, by_key), = doc["entries"].items()
    (key, rec), = by_key.items()
    # rewrite as a schema-1 record: predates sources/flags/provenance
    for gone in ("sources", "flags", "provenance"):
        rec.pop(gone)
    rec["schema"] = 1
    json.dump(doc, open(path, "w"))
    loaded = ScheduleStore(path)
    assert loaded.skipped == 0
    got = loaded.best(fps["a"].exact_digest)
    assert got["schema"] == RECORD_SCHEMA  # migrated in place
    assert got["sources"] == [] and got["flags"] == {}
    assert got["vs_naive"] == 2.0


def test_newer_schema_record_skipped_loudly(tmp_path, spmv):
    _, fps, seqs = spmv
    path = str(tmp_path / "store.json")
    s = ScheduleStore(path)
    s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    s.flush()
    doc = json.load(open(path))
    next(iter(next(iter(doc["entries"].values())).values()))["schema"] = \
        RECORD_SCHEMA + 1
    json.dump(doc, open(path, "w"))
    notes = []
    loaded = ScheduleStore(path, log=notes.append)
    assert loaded.skipped == 1 and len(loaded) == 0
    assert any("skipped record" in n for n in notes)
    # migrate_record's contract directly: never mis-read the future
    assert migrate_record({"schema": RECORD_SCHEMA + 1}) is None


def test_corrupt_store_quarantined_not_fatal(tmp_path, spmv):
    _, fps, seqs = spmv
    path = str(tmp_path / "store.json")
    with open(path, "w") as f:
        f.write('{"version": 1, "entries": {trunca')  # torn write
    notes = []
    s = ScheduleStore(path, log=notes.append)  # must not raise
    assert len(s) == 0
    assert any("quarantined" in n for n in notes)
    # the damaged bytes moved aside for post-mortem...
    corpses = [p for p in os.listdir(tmp_path)
               if p.startswith("store.json.corrupt-")]
    assert len(corpses) == 1
    # ...and a fresh flush starts a clean, loadable file
    s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    s.flush()
    assert len(ScheduleStore(path)) == 1


def test_simultaneous_flushes_lose_nothing(tmp_path, spmv):
    """The flock around flush()'s read-merge-rename: two writers
    flushing at the same moment must both land (without the lock, both
    re-read the same disk state and the second rename drops the
    first's records)."""
    import threading

    _, fps, seqs = spmv
    path = str(tmp_path / "store.json")
    a = ScheduleStore(path, tenant="t-a")
    b = ScheduleStore(path, tenant="t-b")
    a.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    b.add(fps["b"], seqs[2], pct50_us=12.0, vs_naive=1.5)
    barrier = threading.Barrier(2)

    def go(store):
        barrier.wait()
        store.flush()

    ts = [threading.Thread(target=go, args=(s,)) for s in (a, b)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(ScheduleStore(path)) == 2
    assert os.path.exists(path + ".lock")


def test_merge_tie_preserves_one_sided_provenance():
    """A driver-verdict stamp (warm --bench) on one twin must survive
    merging with an unstamped twin in BOTH orders — the tiebreak picks
    a winner, but provenance keys the winner lacks fill from the
    loser."""
    base = {"schema": RECORD_SCHEMA, "exact": "e", "bucket": "b",
            "key": "k", "ops": [], "workload": "spmv", "vs_naive": 2.0,
            "pct50_us": 10.0, "sources": [], "flags": {}}
    stamped = dict(base, provenance={"tenant": "a", "fid": "full",
                                     "driver": {"best_vs_baseline": 2.9,
                                                "verified": True}})
    plain = dict(base, provenance={"tenant": "b", "fid": "full"})
    for m in (merge_records(stamped, plain), merge_records(plain, stamped)):
        assert m["provenance"]["driver"]["verified"] is True, m


def test_flag_idempotent_set_does_not_rewrite(tmp_path, spmv):
    _, fps, seqs = spmv
    path = str(tmp_path / "store.json")
    s = ScheduleStore(path)
    rec = s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    s.flag(rec["exact"], rec["key"], needs_refinement=True)
    mtime = os.path.getmtime(path)
    stat = os.stat(path)
    # the hot serving path re-flags on every near query: an already-set
    # flag must not pay another read-merge-fsync-rename cycle
    s.flag(rec["exact"], rec["key"], needs_refinement=True)
    assert os.stat(path).st_ino == stat.st_ino  # no atomic replace ran
    assert os.path.getmtime(path) == mtime


def test_flush_creates_missing_store_directory(tmp_path, spmv):
    # the CLI promises "created on first flush" — the .lock sidecar
    # must not trip over the not-yet-existing directory first
    _, fps, seqs = spmv
    path = str(tmp_path / "new" / "nested" / "store.json")
    s = ScheduleStore(path)
    s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    s.flush()
    assert len(ScheduleStore(path)) == 1


def test_flush_does_not_inflate_load_merge_counters(tmp_path, spmv):
    from tenzing_tpu.obs.metrics import get_metrics

    _, fps, seqs = spmv
    path = str(tmp_path / "store.json")
    s = ScheduleStore(path)
    s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    s.flush()
    loaded = get_metrics().counter("serve.store.loaded").value
    merged = get_metrics().counter("serve.store.merged").value
    for _ in range(3):  # flush bookkeeping is not a load or a merge
        s.flush()
    assert get_metrics().counter("serve.store.loaded").value == loaded
    assert get_metrics().counter("serve.store.merged").value == merged


def test_structurally_malformed_store_never_fatal(tmp_path):
    """Valid JSON with wrong shapes (null slot, list record) must load
    as skips, not crash construction — flush()'s re-read runs under the
    flock and the CLI/report construct stores on arbitrary files."""
    path = str(tmp_path / "store.json")
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": {
            "aaa": None,                      # malformed slot
            "bbb": {"k1": ["not", "a", "record"], "k2": "nope"},
        }}, f)
    notes = []
    s = ScheduleStore(path, log=notes.append)  # must not raise
    assert len(s) == 0 and s.skipped == 3
    assert any("malformed slot" in n for n in notes)
    assert migrate_record(["not", "a", "dict"]) is None


def test_workqueue_ensure_skips_valid_rewrite(tmp_path, spmv):
    _, fps, _ = spmv
    q = WorkQueue(str(tmp_path / "queue"))
    req = DriverRequest(workload="spmv", m=512)
    p1 = q.ensure(fps["a"], req.to_json(), reason="cold")
    mtime = os.path.getmtime(p1)
    ino = os.stat(p1).st_ino
    # the hot path: an identical re-ensure must not rewrite the item
    p2 = q.ensure(fps["a"], req.to_json(), reason="cold")
    assert p1 == p2
    assert os.stat(p1).st_ino == ino and os.path.getmtime(p1) == mtime
    # a torn item IS re-asserted
    with open(p1, "w") as f:
        f.write("{")
    q.ensure(fps["a"], req.to_json(), reason="cold")
    from tenzing_tpu.fault.checkpoint import read_checked_json

    assert read_checked_json(p1)["reason"] == "cold"


def test_readonly_load_leaves_corrupt_file_in_place(tmp_path):
    # valid JSON, wrong version: parses fine but fails store validation
    path = str(tmp_path / "store.json")
    with open(path, "w") as f:
        json.dump({"version": 99, "entries": {}}, f)
    notes = []
    s = ScheduleStore(path, log=notes.append, quarantine_corrupt=False)
    assert len(s) == 0
    assert os.path.exists(path), "read-only load must not rename"
    assert not [p for p in os.listdir(tmp_path) if ".corrupt-" in p]
    assert any("left in place" in n for n in notes)


def test_workqueue_checkpoint_format_and_idempotence(tmp_path, spmv):
    from tenzing_tpu.fault.checkpoint import read_checked_json

    _, fps, _ = spmv
    q = WorkQueue(str(tmp_path / "queue"))
    # read-only use must not materialize the directory (a typo'd
    # --queue path would silently shadow the real queue); first enqueue
    # creates it
    assert len(q) == 0 and not os.path.isdir(q.dir)
    req = DriverRequest(workload="spmv", m=512)
    p1 = q.enqueue(fps["a"], req.to_json(), reason="cold")
    assert os.path.isdir(q.dir)
    p2 = q.enqueue(fps["a"], req.to_json(), reason="cold")  # re-assert
    assert p1 == p2 and len(q) == 1  # keyed by exact digest: no piling
    payload = read_checked_json(p1)  # the digest-checked envelope parses
    assert payload["kind"] == "search_request"
    assert payload["fingerprint"]["exact"] == fps["a"].exact_digest
    rt = DriverRequest(**payload["request"])
    assert rt.workload == "spmv" and rt.m == 512
    # a torn item never crashes a drainer
    with open(os.path.join(q.dir, "work-torn.json"), "w") as f:
        f.write("{")
    assert len(q.items()) == 1
    # ...and is VISIBLE, not silently dropped (ISSUE 9 satellite): the
    # scan records the torn set for serve stats / the report CLI
    assert [os.path.basename(p) for p in q.torn_paths] == ["work-torn.json"]
    st = q.stats()
    assert st["depth"] == 1 and st["torn"] == ["work-torn.json"]


def test_workqueue_concurrent_writers_one_valid_item(tmp_path, spmv):
    """Two writers asserting the same fingerprint concurrently (the
    fleet-rate near-miss path): exactly one item file survives, and it
    is a VALID digest-checked envelope — atomic_write_json's
    tmp+fsync+rename means last-wins, never torn."""
    import threading

    from tenzing_tpu.fault.checkpoint import read_checked_json

    _, fps, _ = spmv
    q = WorkQueue(str(tmp_path / "queue"))
    req = DriverRequest(workload="spmv", m=512)
    barrier = threading.Barrier(2)
    errors = []

    def writer(tenant):
        try:
            barrier.wait()
            for i in range(25):
                q.ensure(fps["a"], req.to_json(), reason=f"cold-{tenant}")
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    ts = [threading.Thread(target=writer, args=(t,)) for t in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    names = [n for n in os.listdir(q.dir) if n.startswith("work-")]
    assert names == [f"work-{fps['a'].exact_digest}.json"]
    payload = read_checked_json(q.path_for(fps["a"].exact_digest))
    assert payload["kind"] == "search_request"
    assert payload["reason"].startswith("cold-")
    assert DriverRequest(**payload["request"]).m == 512


def test_workqueue_torn_item_reassert_under_concurrent_ensure(tmp_path, spmv):
    """The ensure() torn-item re-assert path raced by a second ensure:
    whatever interleaving wins, the surviving file is a valid envelope
    for the fingerprint."""
    import threading

    from tenzing_tpu.fault.checkpoint import read_checked_json

    _, fps, _ = spmv
    q = WorkQueue(str(tmp_path / "queue"))
    req = DriverRequest(workload="spmv", m=512)
    path = q.ensure(fps["a"], req.to_json(), reason="cold")
    with open(path, "w") as f:
        f.write("{not json")  # torn by a crashed writer
    barrier = threading.Barrier(2)

    def reassert():
        barrier.wait()
        q.ensure(fps["a"], req.to_json(), reason="cold")

    ts = [threading.Thread(target=reassert) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    payload = read_checked_json(path)  # valid again, digest-checked
    assert payload["fingerprint"]["exact"] == fps["a"].exact_digest
