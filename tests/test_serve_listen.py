"""Listen-mode service loop + admission-serving acceptance (ISSUE 11;
docs/serving.md "Listen mode"): exact hits served from the sealed cache
with ZERO per-query verifier invocations, lazy verification exactly once
for unstamped records, unsound-at-admission never served, bounded-queue
load shedding with retry_after, the per-request watchdog, graceful
drain + status doc, the batch op, the socket transport, and the
resolver bounded-cache re-put fix.
"""

import json
import os
import socket
import threading
import time

import pytest

from tenzing_tpu.bench.driver import DriverRequest, graph_for
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.serve.fingerprint import fingerprint_of, schedule_key
from tenzing_tpu.serve.listen import ListenOpts, ServeLoop
from tenzing_tpu.serve.resolver import Resolver
from tenzing_tpu.serve.service import ScheduleService
from tenzing_tpu.serve.store import ScheduleStore

REQ = DriverRequest(workload="spmv", m=512)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """A synthetic recorded database for spmv/512 (the same shape the
    serving tests mine) — row 0 the naive anchor, then distinct 2-lane
    schedules beating it."""
    import itertools

    from tenzing_tpu.bench.benchmarker import BenchResult, result_row
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State

    d = tmp_path_factory.mktemp("listen_corpus")
    g, _ = graph_for(REQ)

    def drive(n_lanes, picks):
        plat = Platform.make_n_lanes(n_lanes)
        st = State(g)
        i = 0
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            st = st.apply(ds[picks[i % len(picks)] % len(ds)])
            i += 1
        return st.sequence

    naive = drive(1, [0])
    alts, seen = [], set()
    for picks in itertools.product((0, 1, 2), repeat=3):
        s = drive(2, list(picks))
        k = schedule_key(s)
        if k not in seen:
            seen.add(k)
            alts.append(s)
        if len(alts) >= 6:
            break
    rows = [result_row(0, BenchResult.from_times([2.0, 2.1, 2.05]), naive)]
    for i, a in enumerate(alts):
        t = 1.0 + 0.1 * i
        rows.append(result_row(
            i + 1, BenchResult.from_times([t, t * 1.02, t * 0.99]), a))
    path = d / "spmv_search.csv"
    path.write_text("\n".join(rows) + "\n")
    return {"csv": str(path), "graph": g, "alts": alts}


@pytest.fixture()
def warmed(tmp_path, corpus):
    """A freshly-warmed SEGMENTED service per test (the exact cache and
    counters are per-instance state)."""
    svc = ScheduleService(str(tmp_path / "store"),
                          queue_dir=str(tmp_path / "queue"))
    summary = svc.warm(REQ, [corpus["csv"]], topk=2, train=False)
    assert summary["added"] == 2
    assert summary["admission"] == {"verified": 2, "rejected_unsound": 0}
    return svc


# -- admission-time verification / exact cache -------------------------------

def test_exact_hit_zero_verifier_calls_then_cache(warmed):
    fallback0 = get_metrics().counter("serve.verify_fallback").value
    r1 = warmed.query(REQ)
    assert r1.tier == "exact"
    p = r1.provenance
    assert p["verified"] is True
    assert p["verified_at_admission"] is True
    assert p["verifier_calls"] == 0
    assert p["cache_hit"] is False
    assert p["compiles"] == 0 and p["measurements"] == 0
    r2 = warmed.query(REQ)
    assert r2.provenance["cache_hit"] is True
    assert r2.sequence is r1.sequence  # the sealed cached answer
    assert get_metrics().counter("serve.verify_fallback").value == fallback0


def test_unstamped_record_lazy_verifies_exactly_once(tmp_path, corpus):
    """A record that arrived without an admission stamp (e.g. merged
    from a legacy store) is verified lazily on first serve, then cached
    — one verifier invocation total, not one per query."""
    store = ScheduleStore(str(tmp_path / "legacy.json"))
    fp = fingerprint_of(REQ)
    store.add(fp, corpus["alts"][0], pct50_us=10.0, vs_naive=2.0)  # no stamp
    resolver = Resolver(store, graph_builder=lambda r: (corpus["graph"], {}))
    fallback0 = get_metrics().counter("serve.verify_fallback").value
    r1 = resolver.resolve(REQ)
    assert r1.tier == "exact"
    assert r1.provenance["verifier_calls"] == 1
    assert r1.provenance["verified_at_admission"] is False
    r2 = resolver.resolve(REQ)
    assert r2.provenance["cache_hit"] is True
    assert get_metrics().counter(
        "serve.verify_fallback").value == fallback0 + 1


def test_store_mutation_invalidates_exact_cache(warmed, corpus):
    r1 = warmed.query(REQ)
    assert warmed.query(REQ).provenance["cache_hit"] is True
    # a merge/add anywhere bumps the store generation: the next query
    # re-walks the records instead of serving a possibly-beaten answer
    fp = fingerprint_of(REQ)
    warmed.store.add(fp, corpus["alts"][-1], pct50_us=0.5, vs_naive=9.0,
                     verified=True)
    r3 = warmed.query(REQ)
    assert r3.provenance["cache_hit"] is False
    assert r3.vs_naive == 9.0  # the better record won, not the stale one
    assert r1.vs_naive != 9.0


def test_flagging_served_record_invalidates_exact_cache(warmed):
    """A record flagged unsound AFTER it was cached must never be
    served again: flag() bumps the store generation (and the hit path
    re-checks flags), so the runner-up answers instead."""
    r1 = warmed.query(REQ)
    assert r1.tier == "exact"
    assert warmed.query(REQ).provenance["cache_hit"] is True
    warmed.store.flag(r1.record["exact"], r1.record["key"], unsound=True)
    r2 = warmed.query(REQ)
    assert r2.tier == "exact"
    assert r2.record["key"] != r1.record["key"], \
        "flagged-unsound record served from the stale cache"


def test_unsound_at_admission_stored_flagged_never_served(tmp_path, corpus):
    """An unsound record is admitted flagged (visible) and skipped by
    the exact tier without any verifier call."""
    svc = ScheduleService(str(tmp_path / "store"),
                          queue_dir=str(tmp_path / "queue"))
    fp = fingerprint_of(REQ)
    svc.store.add(fp, corpus["alts"][0], pct50_us=1.0, vs_naive=9.0,
                  verified=False)   # flagged unsound at admission
    svc.store.add(fp, corpus["alts"][1], pct50_us=2.0, vs_naive=1.5,
                  verified=True)    # the sound runner-up
    svc.store.flush()
    fallback0 = get_metrics().counter("serve.verify_fallback").value
    res = svc.query(REQ)
    assert res.tier == "exact"
    assert res.vs_naive == 1.5  # the unsound 9.0 "best" never served
    assert res.provenance["verifier_calls"] == 0
    assert get_metrics().counter("serve.verify_fallback").value == fallback0
    st = svc.store.stats()
    assert st["admission"]["unsound"] == 1


def test_cache_put_represent_key_updates_in_place(corpus):
    """The satellite fix: re-putting a present key at cap must update in
    place, not evict an oldest entry (which shrank the cache by one and
    could evict the very entry being refreshed)."""
    r = Resolver(ScheduleStore(None))
    r.cache_cap = 3
    cache = {}
    for k in ("a", "b", "c"):
        r._cache_put(cache, k, k.upper())
    assert list(cache) == ["a", "b", "c"]
    r._cache_put(cache, "b", "B2")   # re-put at cap
    assert cache == {"a": "A", "b": "B2", "c": "C"}  # nothing evicted
    r._cache_put(cache, "d", "D")    # a genuinely new key still evicts
    assert list(cache) == ["b", "c", "d"]


# -- the serve loop ----------------------------------------------------------

class _StubService:
    """A service whose query latency the tests control."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.store = ScheduleStore(None)
        self.calls = 0

    def query(self, req):
        from tenzing_tpu.serve.resolver import Resolution

        self.calls += 1
        if self.delay:
            time.sleep(self.delay)
        return Resolution(tier="exact", fingerprint=fingerprint_of(REQ),
                          provenance={"stub": True})

    def stats(self):
        return {"stub": True}


def _collect():
    docs, lock = [], threading.Lock()

    def respond(doc):
        with lock:
            docs.append(doc)

    return docs, respond


def test_bounded_queue_sheds_with_retry_after(tmp_path):
    svc = _StubService(delay=0.4)
    loop = ServeLoop(svc, ListenOpts(
        max_pending=1, workers=1, request_timeout_secs=30.0,
        shed_retry_after_secs=0.125, handle_signals=False,
        status_path=str(tmp_path / "status.json")))
    shed0 = get_metrics().counter("serve.shed").value
    loop.start()
    docs, respond = _collect()
    for i in range(4):
        loop.submit({"op": "query", "id": i,
                     "request": {"workload": "spmv", "m": 512}}, respond)
    loop.drain(timeout=10.0)
    shed = [d for d in docs if d.get("shed")]
    ok = [d for d in docs if d.get("ok")]
    assert len(docs) == 4
    assert shed, "nothing shed at max_pending=1"
    assert all(d["retry_after"] == 0.125 for d in shed)
    assert all(d["error_class"] == "transient" for d in shed)
    assert len(ok) + len(shed) == 4
    assert loop.counters["shed"] == len(shed)
    assert get_metrics().counter("serve.shed").value == shed0 + len(shed)


def test_busy_poll_workers_drain_correctly(tmp_path):
    """``busy_poll_us`` changes the worker wakeup path (bounded spin
    before the blocking wait), never the results: every request is
    answered exactly once, and drain/stop still terminate promptly."""
    svc = _StubService(delay=0.0)
    loop = ServeLoop(svc, ListenOpts(
        max_pending=32, workers=2, request_timeout_secs=30.0,
        busy_poll_us=200.0, handle_signals=False,
        status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    for i in range(12):
        loop.submit({"op": "query", "id": i,
                     "request": {"workload": "spmv", "m": 512}}, respond)
    assert loop.drain(timeout=10.0) is True
    assert len(docs) == 12
    assert sum(1 for d in docs if d.get("ok")) == 12
    assert sorted(d["id"] for d in docs) == list(range(12))
    assert svc.calls == 12


def test_watchdog_times_out_stuck_request(tmp_path):
    svc = _StubService(delay=1.0)
    loop = ServeLoop(svc, ListenOpts(
        max_pending=4, workers=1, request_timeout_secs=0.2,
        handle_signals=False, status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "query", "id": 1,
                 "request": {"workload": "spmv", "m": 512}}, respond)
    t0 = time.time()
    while not docs and time.time() - t0 < 5.0:
        time.sleep(0.02)
    assert docs, "watchdog never answered"
    assert docs[0]["timed_out"] is True
    assert docs[0]["error_class"] == "transient"
    assert time.time() - t0 < 0.9  # answered before the worker finished
    loop.drain(timeout=10.0)
    assert len(docs) == 1  # the late worker result was discarded
    assert loop.counters["timeouts"] == 1


def test_graceful_drain_answers_queued_and_stamps_status(tmp_path):
    svc = _StubService(delay=0.05)
    status = str(tmp_path / "status.json")
    loop = ServeLoop(svc, ListenOpts(
        max_pending=16, workers=2, request_timeout_secs=30.0,
        handle_signals=False, status_path=status))
    loop.start()
    docs, respond = _collect()
    for i in range(6):
        loop.submit({"op": "query", "id": i,
                     "request": {"workload": "spmv", "m": 512}}, respond)
    loop.stop()
    # intake stopped: a post-stop submit is shed as "draining"
    loop.submit({"op": "query", "id": 99,
                 "request": {"workload": "spmv", "m": 512}}, respond)
    assert loop.drain(timeout=10.0) is True
    assert len(docs) == 7
    assert sum(1 for d in docs if d.get("ok")) == 6
    assert [d for d in docs if d.get("shed")][0]["reason"] == "draining"
    st = json.load(open(status))
    assert st["kind"] == "serve_loop" and st["state"] == "stopped"
    assert st["counters"]["requests"] == 7


def test_batch_and_malformed_ops(warmed, tmp_path):
    loop = ServeLoop(warmed, ListenOpts(
        max_pending=8, workers=1, request_timeout_secs=60.0,
        handle_signals=False, status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "batch", "id": 1, "requests": [
        {"workload": "spmv", "m": 512},
        {"request": {"workload": "spmv", "m": 512}}]}, respond)
    loop.submit({"op": "nope", "id": 2}, respond)
    loop.drain(timeout=30.0)
    by_id = {d.get("id"): d for d in docs}
    assert len(by_id[1]["results"]) == 2
    assert by_id[1]["results"][0]["tier"] == "exact"
    assert by_id[1]["results"][1]["provenance"]["cache_hit"] is True
    assert "resolve_us" in by_id[1]["results"][0]
    assert by_id[2]["ok"] is False
    assert by_id[2]["error_class"] == "deterministic"
    assert loop.counters["batches"] == 1
    assert loop.counters["malformed"] == 1


def test_socket_transport_round_trip(warmed, tmp_path):
    sock_path = str(tmp_path / "serve.sock")
    loop = ServeLoop(warmed, ListenOpts(
        max_pending=8, workers=1, request_timeout_secs=60.0,
        handle_signals=False, socket_path=sock_path,
        status_path=str(tmp_path / "status.json")))
    result = {}

    def run():
        result["summary"] = loop.serve_socket(sock_path)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + 10
    while not os.path.exists(sock_path) and time.time() < deadline:
        time.sleep(0.02)
    cli = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    cli.connect(sock_path)
    cli.sendall((json.dumps({"op": "query", "id": 7, "request": {
        "workload": "spmv", "m": 512}}) + "\n"
        + json.dumps({"op": "ping", "id": 8}) + "\n").encode())
    cli.settimeout(60.0)
    buf = b""
    while buf.count(b"\n") < 2:
        chunk = cli.recv(1 << 16)
        assert chunk, "server closed early"
        buf += chunk
    docs = {d["id"]: d for d in
            (json.loads(l) for l in buf.decode().splitlines())}
    assert docs[7]["result"]["tier"] == "exact"
    assert docs[8]["pong"] is True
    cli.close()
    loop.stop()
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert result["summary"]["counters"]["requests"] == 2
    assert not os.path.exists(sock_path)  # cleaned up on exit


# -- fleet telemetry plane (ISSUE 12) ----------------------------------------

def test_trace_context_and_phase_breakdown(warmed, tmp_path):
    """Every response names its trace; exact resolutions carry the
    per-phase breakdown (fingerprint / cache_probe / serialize) the
    tens-of-µs profile needs; a client-supplied trace is adopted."""
    loop = ServeLoop(warmed, ListenOpts(
        max_pending=8, workers=1, request_timeout_secs=60.0,
        handle_signals=False, status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "query", "id": 1,
                 "request": {"workload": "spmv", "m": 512}}, respond)
    loop.submit({"op": "query", "id": 2,
                 "trace": {"trace_id": "feed" * 4, "span_id": "00" * 8},
                 "request": {"workload": "spmv", "m": 512}}, respond)
    loop.drain(timeout=10.0)
    by_id = {d["id"]: d for d in docs}
    d1 = by_id[1]
    assert d1["ok"] and len(d1["trace_id"]) == 16
    r1 = d1["result"]
    assert r1["trace_id"] == d1["trace_id"]  # one id, transport == tiers
    ph = r1["phase_us"]
    assert {"fingerprint", "cache_probe", "serialize"} <= set(ph)
    assert all(v >= 0 for v in ph.values())
    # the client's gateway trace id survives end to end
    assert by_id[2]["trace_id"] == "feed" * 4
    assert by_id[2]["result"]["trace_id"] == "feed" * 4
    # the per-tier latency series exists for the SLO block to read
    assert get_metrics().histogram("serve.resolve_us.exact").count >= 2


def test_metrics_verb_and_snapshot_ring(warmed, tmp_path):
    """The `metrics` op answers the same snapshot document the
    heartbeat publishes — registry, tracer retention, SLO block — and
    the drain writes a final snapshot into the bounded ring."""
    loop = ServeLoop(warmed, ListenOpts(
        max_pending=8, workers=1, request_timeout_secs=60.0,
        handle_signals=False, owner="msnap", slo_target_us=1e9,
        status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "query", "id": 0,
                 "request": {"workload": "spmv", "m": 512}}, respond)
    loop.submit({"op": "metrics", "id": 1}, respond)
    loop.drain(timeout=10.0)
    m = next(d for d in docs if d["id"] == 1)["metrics"]
    assert m["kind"] == "metrics_snapshot" and m["owner"] == "msnap"
    assert "counters" in m["metrics"] and "dropped_spans" in m["tracer"]
    slo = m["slo"]
    assert slo["target_us"] == 1e9
    assert slo["histogram"] == "serve.resolve_us.exact"
    # the drain wrote a ring snapshot next to the status doc
    from tenzing_tpu.obs.metrics import latest_snapshots

    latest = latest_snapshots(str(tmp_path))
    assert "msnap" in latest
    assert latest["msnap"]["state"] == "stopped"
    assert latest["msnap"]["queue_depth"] == 0


def test_tenant_histograms_bounded(warmed, tmp_path):
    """Per-tenant latency series are admitted up to tenant_cap; later
    tenants aggregate under `other` — client-controlled labels cannot
    grow the registry without bound."""
    from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics

    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        loop = ServeLoop(warmed, ListenOpts(
            max_pending=8, workers=1, request_timeout_secs=60.0,
            handle_signals=False, tenant_cap=2,
            status_path=str(tmp_path / "status.json")))
        loop.start()
        docs, respond = _collect()
        for i, tenant in enumerate(("t-a", "t-b", "t-c", "t-d", "t-a")):
            loop.submit({"op": "query", "id": i, "tenant": tenant,
                         "request": {"workload": "spmv", "m": 512}},
                        respond)
        loop.drain(timeout=10.0)
        assert all(d.get("ok") for d in docs)
        names = set(reg.histograms())
        assert "serve.tenant.t-a.resolve_us" in names
        assert "serve.tenant.t-b.resolve_us" in names
        assert "serve.tenant.other.resolve_us" in names
        assert "serve.tenant.t-c.resolve_us" not in names
        assert "serve.tenant.t-d.resolve_us" not in names
        assert reg.histogram("serve.tenant.t-a.resolve_us").count == 2
        assert reg.histogram("serve.tenant.other.resolve_us").count == 2
        assert reg.counter("serve.tenant.other.exact").value == 2
    finally:
        set_metrics(prev)


def test_cold_work_item_carries_ingress_trace(tmp_path, corpus):
    """The ingress-minted trace context rides the cold work item's
    checkpoint envelope — the daemon drain it causes links back to this
    exact query (the tentpole linkage, asserted end-to-end in
    tests/test_daemon.py)."""
    from tenzing_tpu.fault.checkpoint import read_checked_json

    svc = ScheduleService(str(tmp_path / "store"),
                          queue_dir=str(tmp_path / "queue"))
    loop = ServeLoop(svc, ListenOpts(
        max_pending=8, workers=1, request_timeout_secs=60.0,
        handle_signals=False, status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    loop.submit({"op": "query", "id": 1,
                 "request": {"workload": "spmv", "m": 512}}, respond)
    loop.drain(timeout=10.0)
    d = docs[0]
    assert d["result"]["tier"] == "cold"
    item = read_checked_json(d["result"]["work_item"])
    assert item["trace"]["trace_id"] == d["trace_id"]


# -- per-tenant fair admission (ISSUE 14 satellite) --------------------------

def test_tenant_cap_sheds_over_cap_tenant_only(tmp_path):
    """One tenant's burst hits its own in-flight cap (shed with reason
    tenant_cap, counted per tenant) while another tenant and untagged
    requests still admit — the burst can no longer starve the rest by
    filling the global bound."""
    from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics

    prev = set_metrics(MetricsRegistry())
    try:
        svc = _StubService(delay=0.3)
        loop = ServeLoop(svc, ListenOpts(
            max_pending=16, workers=1, tenant_max_pending=2,
            request_timeout_secs=30.0, handle_signals=False,
            status_path=str(tmp_path / "status.json")))
        loop.start()
        docs, respond = _collect()
        # tenant "hog" bursts 6 deep: 2 admit (its cap), 4 shed
        for i in range(6):
            loop.submit({"op": "query", "id": f"hog-{i}",
                         "tenant": "hog",
                         "request": {"workload": "spmv", "m": 512}},
                        respond)
        # a second tenant and an untagged request admit despite the burst
        loop.submit({"op": "query", "id": "quiet", "tenant": "quiet",
                     "request": {"workload": "spmv", "m": 512}}, respond)
        loop.submit({"op": "query", "id": "untagged",
                     "request": {"workload": "spmv", "m": 512}}, respond)
        loop.drain(timeout=20.0)
        by_id = {d.get("id"): d for d in docs}
        shed = [d for d in docs if d.get("shed")]
        assert all(d["reason"] == "tenant_cap" for d in shed), shed
        assert len(shed) == 4
        assert all(str(d["id"]).startswith("hog-") for d in shed)
        assert by_id["quiet"]["ok"] is True
        assert by_id["untagged"]["ok"] is True
        # the shed burst is measured per tenant (the PR-13 counters)
        from tenzing_tpu.obs.metrics import get_metrics

        assert get_metrics().counter("serve.shed.hog").value == 4
        assert get_metrics().counter("serve.shed.quiet").value == 0
    finally:
        set_metrics(prev)


def test_tenant_cap_default_derivation_and_disable(tmp_path):
    svc = _StubService()
    loop = ServeLoop(svc, ListenOpts(max_pending=64, handle_signals=False,
                                     status_path=str(tmp_path / "s1.json")))
    assert loop._tenant_pending_cap() == 32  # default: max_pending // 2
    loop2 = ServeLoop(svc, ListenOpts(
        max_pending=64, tenant_max_pending=0, handle_signals=False,
        status_path=str(tmp_path / "s2.json")))
    assert loop2._tenant_pending_cap() == 0  # 0 disables

    # disabled: a burst beyond any per-tenant bound reaches the global
    # queue instead of tenant_cap shedding
    svc3 = _StubService(delay=0.2)
    loop3 = ServeLoop(svc3, ListenOpts(
        max_pending=4, workers=1, tenant_max_pending=0,
        request_timeout_secs=30.0, handle_signals=False,
        status_path=str(tmp_path / "s3.json")))
    loop3.start()
    docs, respond = _collect()
    for i in range(8):
        loop3.submit({"op": "query", "id": i, "tenant": "hog",
                      "request": {"workload": "spmv", "m": 512}}, respond)
    loop3.drain(timeout=20.0)
    shed = [d for d in docs if d.get("shed")]
    assert shed and all(d["reason"] == "queue-full" for d in shed)


def test_non_string_tenant_never_crashes_admission(tmp_path):
    """Client input: an unhashable (or otherwise non-string) tenant
    value must not crash submit() — pre-guard it DoS'd the whole stdin
    loop with one request.  Such requests admit uncapped, like untagged
    ones, and stay invisible to per-tenant telemetry."""
    svc = _StubService()
    loop = ServeLoop(svc, ListenOpts(
        max_pending=8, workers=1, tenant_max_pending=1,
        request_timeout_secs=30.0, handle_signals=False,
        status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    for i, tenant in enumerate(({"x": 1}, [1, 2], 5, None)):
        loop.submit({"op": "query", "id": i, "tenant": tenant,
                     "request": {"workload": "spmv", "m": 512}}, respond)
    loop.drain(timeout=10.0)
    assert len(docs) == 4
    assert all(d.get("ok") for d in docs), docs
    assert loop._tenant_live == {}  # nothing leaked into the counts


def test_tenant_cap_weighs_batch_members(tmp_path):
    """A batch payload counts its member requests against the tenant
    cap — one batch slot must not smuggle N sub-requests past the
    fairness bound a single-query burst would have shed on."""
    svc = _StubService(delay=0.3)
    loop = ServeLoop(svc, ListenOpts(
        max_pending=16, workers=1, tenant_max_pending=3,
        request_timeout_secs=30.0, handle_signals=False,
        status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    member = {"request": {"workload": "spmv", "m": 512}}
    # 4 members > cap 3: shed outright, reason tenant_cap
    loop.submit({"op": "batch", "id": "big", "tenant": "hog",
                 "requests": [dict(member) for _ in range(4)]}, respond)
    # 2 members fit; a following 2-member batch would exceed (2+2 > 3)
    loop.submit({"op": "batch", "id": "ok", "tenant": "hog",
                 "requests": [dict(member) for _ in range(2)]}, respond)
    loop.submit({"op": "batch", "id": "over", "tenant": "hog",
                 "requests": [dict(member) for _ in range(2)]}, respond)
    loop.drain(timeout=20.0)
    by_id = {d.get("id"): d for d in docs}
    assert by_id["big"].get("shed") and \
        by_id["big"]["reason"] == "tenant_cap", by_id["big"]
    assert by_id["ok"]["ok"] is True and len(by_id["ok"]["results"]) == 2
    assert by_id["over"].get("shed") and \
        by_id["over"]["reason"] == "tenant_cap", by_id["over"]
    assert loop._tenant_live == {}  # weights fully released on drain


def test_tenant_cap_charges_batch_members_to_their_own_tenant(tmp_path):
    """Member-level tenant tags cannot smuggle past the cap: a batch
    with NO top-level tenant whose members all tag one tenant charges
    that tenant — the same effective-tenant rule execution and
    telemetry apply (r.get("tenant", payload_tenant))."""
    svc = _StubService(delay=0.3)
    loop = ServeLoop(svc, ListenOpts(
        max_pending=16, workers=1, tenant_max_pending=3,
        request_timeout_secs=30.0, handle_signals=False,
        status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, respond = _collect()
    member = {"tenant": "hog", "request": {"workload": "spmv", "m": 512}}
    # untagged batch, 5 hog-tagged members > cap 3: shed whole
    loop.submit({"op": "batch", "id": "smuggle",
                 "requests": [dict(member) for _ in range(5)]}, respond)
    # a mixed batch within every member tenant's cap admits
    loop.submit({"op": "batch", "id": "mixed",
                 "requests": [dict(member),
                              {"tenant": "quiet",
                               "request": {"workload": "spmv", "m": 512}}]},
                respond)
    loop.drain(timeout=20.0)
    by_id = {d.get("id"): d for d in docs}
    assert by_id["smuggle"].get("shed") and \
        by_id["smuggle"]["reason"] == "tenant_cap", by_id["smuggle"]
    assert by_id["mixed"]["ok"] is True, by_id["mixed"]
    assert loop._tenant_live == {}


def test_derived_tenant_cap_is_work_conserving(tmp_path):
    """The DERIVED default cap only bites once a second distinct tenant
    exists: a sole tagged tenant keeps the full global queue (fairness
    against nobody is pure waste), and the newcomer's first submission
    activates the cap for the hog's next burst."""
    from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics

    prev = set_metrics(MetricsRegistry())
    try:
        svc = _StubService(delay=0.25)
        loop = ServeLoop(svc, ListenOpts(
            max_pending=16, workers=1,  # derived cap would be 8
            request_timeout_secs=30.0, handle_signals=False,
            status_path=str(tmp_path / "status.json")))
        loop.start()
        docs, respond = _collect()
        # sole tenant: a 10-deep burst (over the derived cap of 8) all
        # admits — only the global bound applies
        for i in range(10):
            loop.submit({"op": "query", "id": f"a{i}", "tenant": "hog",
                         "request": {"workload": "spmv", "m": 512}},
                        respond)
        assert not [d for d in docs if d.get("shed")]
        # a second tenant appears: its submission registers it, and the
        # hog's NEXT submissions hit the now-active derived cap
        loop.submit({"op": "query", "id": "q", "tenant": "quiet",
                     "request": {"workload": "spmv", "m": 512}}, respond)
        for i in range(4):
            loop.submit({"op": "query", "id": f"b{i}", "tenant": "hog",
                         "request": {"workload": "spmv", "m": 512}},
                        respond)
        loop.drain(timeout=30.0)
        shed = [d for d in docs if d.get("shed")]
        assert shed, "derived cap never activated after second tenant"
        assert all(d["reason"] == "tenant_cap" for d in shed)
        assert all(str(d["id"]).startswith("b") for d in shed), shed
    finally:
        set_metrics(prev)


def test_member_tenant_shed_charged_to_over_cap_tenant(tmp_path):
    """A tenant_cap shed caused by a MEMBER tenant of an untagged batch
    charges serve.shed.<that tenant> — the cap's own actions must be
    visible in the fairness counters it claims as its measurement."""
    from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics
    from tenzing_tpu.obs.metrics import get_metrics as _gm

    prev = set_metrics(MetricsRegistry())
    try:
        svc = _StubService(delay=0.3)
        loop = ServeLoop(svc, ListenOpts(
            max_pending=16, workers=1, tenant_max_pending=2,
            request_timeout_secs=30.0, handle_signals=False,
            status_path=str(tmp_path / "status.json")))
        loop.start()
        docs, respond = _collect()
        member = {"tenant": "noisy",
                  "request": {"workload": "spmv", "m": 512}}
        loop.submit({"op": "batch", "id": "b",
                     "requests": [dict(member) for _ in range(5)]},
                    respond)
        loop.drain(timeout=20.0)
        assert docs[0].get("shed") and docs[0]["reason"] == "tenant_cap"
        assert _gm().counter("serve.shed.noisy").value == 1
    finally:
        set_metrics(prev)
