"""Fault layer units (ISSUE 3): error taxonomy, the shared backoff helper,
persistent quarantine, ResilientBenchmarker (watchdog / classified retry /
rank agreement / degradation), and the seeded fault-injection harness."""

import json
import os
import threading
import time

import pytest

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult, schedule_id
from tenzing_tpu.fault import (
    BackoffPolicy,
    DeviceLostError,
    FaultClass,
    FaultInjectingBenchmarker,
    InjectSpec,
    InjectedDeterministicError,
    InjectedTransientError,
    MeasurementTimeout,
    Quarantine,
    QuarantinedScheduleError,
    ResilientBenchmarker,
    TransientError,
    classify_error,
    fault_code,
    parse_inject_specs,
    retry_call,
)
from tenzing_tpu.fault.inject import _schedule_fails
from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics
from tenzing_tpu.obs.tracer import Tracer, set_tracer
from tenzing_tpu.parallel.control_plane import ControlPlane


@pytest.fixture
def tracer():
    tr = Tracer(enabled=True)
    prev = set_tracer(tr)
    try:
        yield tr
    finally:
        set_tracer(prev)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        yield reg
    finally:
        set_metrics(prev)


def _ok(t=1.0):
    return BenchResult.from_times([t, t, t])


class ScriptedBench:
    """Pops one scripted behavior per call: an exception instance to raise,
    a float to sleep (then succeed), or None to succeed."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def benchmark(self, order, opts=None):
        self.calls += 1
        step = self.script.pop(0) if self.script else None
        if isinstance(step, BaseException):
            raise step
        if isinstance(step, float):
            time.sleep(step)
        return _ok()


# -- taxonomy ---------------------------------------------------------------

@pytest.mark.parametrize("exc,want", [
    (InjectedTransientError("x"), FaultClass.TRANSIENT),
    (MeasurementTimeout("x"), FaultClass.TRANSIENT),
    (TransientError("x"), FaultClass.TRANSIENT),
    (DeviceLostError("x"), FaultClass.DEVICE_LOST),
    (InjectedDeterministicError("x"), FaultClass.DETERMINISTIC),
    (TimeoutError("anything"), FaultClass.TRANSIENT),
    (ConnectionResetError("peer"), FaultClass.TRANSIENT),
    (RuntimeError("connection reset by peer"), FaultClass.TRANSIENT),
    (RuntimeError("UNAVAILABLE: tunnel hiccup"), FaultClass.TRANSIENT),
    (RuntimeError("DEADLINE_EXCEEDED while fetching"), FaultClass.TRANSIENT),
    (RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating"),
     FaultClass.DETERMINISTIC),
    (RuntimeError("failed to compile HLO"), FaultClass.DETERMINISTIC),
    (ValueError("operand shape mismatch"), FaultClass.DETERMINISTIC),
    (RuntimeError("device lost: chip rebooted"), FaultClass.DEVICE_LOST),
    # unknown errors default to deterministic (see fault/errors.py rationale)
    (RuntimeError("mysterious"), FaultClass.DETERMINISTIC),
])
def test_classification(exc, want):
    assert classify_error(exc) == want


def test_fault_codes_are_severity_ordered():
    assert (fault_code(TransientError("x"))
            < fault_code(ValueError("shape"))
            < fault_code(DeviceLostError("x")))
    # the rank-agreement protocol allreduce-maxes these codes: the mapping
    # must be a bijection so the worst class round-trips
    assert FaultClass.FROM_CODE[FaultClass.CODES[FaultClass.TRANSIENT]] == \
        FaultClass.TRANSIENT


# -- backoff ----------------------------------------------------------------

def test_backoff_policy_growth_and_cap():
    p = BackoffPolicy(base_secs=1.0, factor=2.0, max_secs=5.0, jitter=0.0)
    assert [p.delay(k) for k in range(4)] == [1.0, 2.0, 4.0, 5.0]


def test_backoff_jitter_bounds():
    import random

    p = BackoffPolicy(base_secs=1.0, factor=1.0, jitter=0.5)
    rng = random.Random(0)
    ds = [p.delay(0, rng) for _ in range(100)]
    assert all(0.5 <= d <= 1.5 for d in ds)
    assert len(set(ds)) > 1  # actually jittered


def test_retry_call_retries_transient_then_succeeds(tracer, registry):
    sleeps = []
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientError("flaky")
        return "ok"

    out = retry_call(fn, policy=BackoffPolicy(retries=3, base_secs=0.25,
                                              factor=2.0, jitter=0.0),
                     where="test", sleep=sleeps.append)
    assert out == "ok" and calls["n"] == 3
    assert sleeps == [0.25, 0.5]
    retries = [e for e in tracer.events() if e.name == "fault.retry"]
    assert len(retries) == 2
    assert retries[0].attrs["where"] == "test"
    assert retries[0].attrs["error_class"] == FaultClass.TRANSIENT
    assert retries[0].attrs["attempt"] == 1
    assert registry.counter("fault.retries").value == 2


def test_retry_call_does_not_retry_deterministic(registry):
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise ValueError("shape mismatch")

    with pytest.raises(ValueError):
        retry_call(fn, sleep=lambda s: None)
    assert calls["n"] == 1
    assert registry.counter("fault.retries").value == 0


def test_retry_call_exhausts_and_reraises():
    with pytest.raises(TransientError):
        retry_call(lambda: (_ for _ in ()).throw(TransientError("always")),
                   policy=BackoffPolicy(retries=2, base_secs=0.0),
                   sleep=lambda s: None)


def test_retry_call_on_retry_hook_runs_before_sleep():
    seen = []
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] == 1:
            raise TransientError("once")
        return 1

    retry_call(fn, policy=BackoffPolicy(retries=1, base_secs=0.1, jitter=0.0),
               on_retry=lambda e, a, d: seen.append((type(e).__name__, a, d)),
               sleep=lambda s: seen.append(("slept", s)))
    assert seen == [("TransientError", 0, 0.1), ("slept", 0.1)]


# -- quarantine -------------------------------------------------------------

def test_quarantine_persists_across_instances(tmp_path, registry):
    path = str(tmp_path / "q.json")
    q = Quarantine(path)
    sid = q.add("sched-a", ValueError("bad shape"), FaultClass.DETERMINISTIC)
    assert q.check("sched-a")["error"] == "ValueError"
    assert q.check("sched-b") is None
    # a fresh instance (a restarted process) still refuses the candidate
    q2 = Quarantine(path)
    assert len(q2) == 1
    assert q2.check("sched-a")["error_class"] == FaultClass.DETERMINISTIC
    assert q2.key("sched-a") == sid
    # no torn temp files left behind
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


def test_quarantine_add_is_idempotent(tmp_path, registry):
    q = Quarantine(str(tmp_path / "q.json"))
    q.add("s", ValueError("x"), FaultClass.DETERMINISTIC)
    q.add("s", ValueError("y"), FaultClass.DETERMINISTIC)
    assert len(q) == 1
    assert q.check("s")["message"] == "x"  # first verdict wins
    assert registry.counter("fault.quarantined").value == 1


def test_quarantine_unreadable_file_is_empty_but_reported(tmp_path):
    path = tmp_path / "q.json"
    path.write_text("{ not json")
    notes = []
    q = Quarantine(str(path), log=notes.append)
    assert len(q) == 0
    assert notes and "unreadable" in notes[0]


def test_quarantine_version_mismatch_is_empty(tmp_path):
    path = tmp_path / "q.json"
    path.write_text(json.dumps({"version": 99, "entries": {"x": {}}}))
    notes = []
    q = Quarantine(str(path), log=notes.append)
    assert len(q) == 0 and notes


# -- ResilientBenchmarker ---------------------------------------------------

def _resilient(inner, **kw):
    kw.setdefault("policy", BackoffPolicy(retries=3, base_secs=0.0,
                                          jitter=0.0))
    kw.setdefault("sleep", lambda s: None)
    return ResilientBenchmarker(inner, **kw)


def test_resilient_retries_transient(tracer, registry):
    inner = ScriptedBench([TransientError("flake"), TransientError("flake")])
    rb = _resilient(inner)
    res = rb.benchmark("sched", BenchOpts())
    assert res.pct50 == 1.0 and inner.calls == 3
    errs = [e for e in tracer.events() if e.name == "fault.error"]
    assert len(errs) == 2
    assert all(e.attrs["error_class"] == FaultClass.TRANSIENT for e in errs)
    assert registry.counter(
        f"fault.errors.{FaultClass.TRANSIENT}").value == 2


def test_resilient_transient_exhaustion_reraises():
    inner = ScriptedBench([TransientError(f"flake {i}") for i in range(9)])
    rb = _resilient(inner, policy=BackoffPolicy(retries=2, base_secs=0.0))
    with pytest.raises(TransientError):
        rb.benchmark("sched")
    assert inner.calls == 3  # first + 2 retries, bounded


def test_resilient_quarantines_deterministic(tmp_path, tracer, registry):
    qpath = str(tmp_path / "q.json")
    inner = ScriptedBench([ValueError("bad shape forever")])
    rb = _resilient(inner, quarantine=Quarantine(qpath))
    with pytest.raises(ValueError):
        rb.benchmark("sched-broken")
    assert inner.calls == 1  # no retry for a deterministic failure
    # second query never reaches the device — quarantine answers
    with pytest.raises(QuarantinedScheduleError):
        rb.benchmark("sched-broken")
    assert inner.calls == 1
    # ... even in a fresh process (the persistent file)
    rb2 = _resilient(ScriptedBench([]), quarantine=Quarantine(qpath))
    with pytest.raises(QuarantinedScheduleError):
        rb2.benchmark("sched-broken")
    assert registry.counter("fault.quarantine_hits").value == 2
    assert [e.name for e in tracer.events()
            if e.name.startswith("fault.quarantine")] == [
        "fault.quarantine", "fault.quarantine_hit", "fault.quarantine_hit"]


def test_resilient_watchdog_times_out_hang_and_retries(tracer):
    inner = ScriptedBench([30.0])  # first call hangs "forever"
    rb = _resilient(inner, timeout_secs=0.1)
    res = rb.benchmark("sched")  # times out, retry succeeds
    assert res.pct50 == 1.0
    errs = [e for e in tracer.events() if e.name == "fault.error"]
    assert len(errs) == 1 and errs[0].attrs["error"] == "MeasurementTimeout"
    assert errs[0].attrs["error_class"] == FaultClass.TRANSIENT


def test_resilient_device_lost_without_fallback_is_fatal():
    inner = ScriptedBench([DeviceLostError("gone")])
    rb = _resilient(inner)
    with pytest.raises(DeviceLostError):
        rb.benchmark("sched")
    assert inner.calls == 1


def test_resilient_degrades_to_fallback(tracer, registry):
    class Fallback:
        def __init__(self):
            self.calls = 0

        def benchmark(self, order, opts=None):
            self.calls += 1
            return _ok(9.0)

    inner = ScriptedBench([DeviceLostError("gone")])
    fb = Fallback()
    rb = _resilient(inner, fallback=fb)
    res = rb.benchmark("sched-a")
    assert res.pct50 == 9.0 and rb.degraded
    assert rb.was_degraded("sched-a") and not rb.was_degraded("sched-b")
    # every subsequent query is answered by the fallback, device untouched
    rb.benchmark("sched-b")
    assert rb.was_degraded("sched-b")
    assert inner.calls == 1 and fb.calls == 2
    assert registry.counter("fault.degraded").value == 1
    assert any(e.name == "fault.degraded" for e in tracer.events())


class TwoRankCP(ControlPlane):
    """A control plane simulating a peer rank: ``agree_fault`` maxes the
    local code with a scripted peer code per call."""

    def __init__(self, peer_codes):
        self.peer_codes = list(peer_codes)
        self.seen = []

    def size(self):
        return 2

    def agree_fault(self, code):
        peer = self.peer_codes.pop(0) if self.peer_codes else 0
        self.seen.append(int(code))
        return max(int(code), peer)


def test_rank_agreement_peer_transient_forces_local_retry():
    """The local rank measured fine, but a peer reported a transient fault:
    the local rank must discard its result and retry in lockstep."""
    inner = ScriptedBench([])
    # agreement calls alternate pre/post per attempt: pre=0, post=peer-fault
    cp = TwoRankCP(peer_codes=[0, FaultClass.CODES[FaultClass.TRANSIENT],
                               0, 0])
    rb = _resilient(inner, control_plane=cp)
    res = rb.benchmark("sched")
    assert res.pct50 == 1.0
    assert inner.calls == 2  # re-measured after the peer's failure


def test_rank_agreement_peer_deterministic_quarantines_everywhere(tmp_path):
    inner = ScriptedBench([])
    cp = TwoRankCP(peer_codes=[0, FaultClass.CODES[FaultClass.DETERMINISTIC]])
    q = Quarantine(str(tmp_path / "q.json"))
    rb = _resilient(inner, control_plane=cp, quarantine=q)
    with pytest.raises(QuarantinedScheduleError):
        rb.benchmark("sched-peer-broken")
    # the local rank quarantined the candidate although IT measured fine —
    # rank-coherent: the peer's verdict is everyone's verdict
    assert q.check("sched-peer-broken") is not None


def test_resilient_is_rank_coherent_and_forwards_through_wrappers():
    from tenzing_tpu.bench.benchmarker import CachingBenchmarker

    rb = _resilient(ScriptedBench([]))
    assert rb.rank_coherent
    assert CachingBenchmarker(rb).rank_coherent
    assert not CachingBenchmarker(ScriptedBench([])).rank_coherent


def test_resilient_batch_retry_clears_partial_times_in_place():
    class Batchy:
        def __init__(self):
            self.calls = 0

        def benchmark(self, order, opts=None):
            return _ok()

        def benchmark_batch_times(self, orders, opts=None, seed=0,
                                  times_out=None):
            self.calls += 1
            if self.calls == 1:
                if times_out is not None:
                    times_out[0].append(0.5)  # partial data, then die
                raise TransientError("mid-batch flake")
            out = [[1.0], [2.0]]
            if times_out is not None:
                for t, o in zip(times_out, out):
                    t.extend(o)
                return times_out
            return out

    inner = Batchy()
    rb = _resilient(inner)
    t0, t1 = [], []
    times = rb.benchmark_batch_times(["a", "b"], BenchOpts(), seed=0,
                                     times_out=[t0, t1])
    assert inner.calls == 2
    # the caller's lists were cleared in place before the retry: no stale
    # partial measurement prefixes the aligned series
    assert t0 == [1.0] and t1 == [2.0]
    assert times[0] is t0


def test_keyboard_interrupt_passes_straight_through():
    inner = ScriptedBench([KeyboardInterrupt()])
    rb = _resilient(inner)
    with pytest.raises(KeyboardInterrupt):
        rb.benchmark("sched")
    assert inner.calls == 1  # never retried, never quarantined


# -- fault injection --------------------------------------------------------

def test_parse_inject_specs():
    specs = parse_inject_specs("transient:0.25:7,hang:0.02:11")
    assert specs == [InjectSpec("transient", 0.25, 7),
                     InjectSpec("hang", 0.02, 11)]
    for bad in ("transient", "transient:0.5", "bogus:0.5:1",
                "transient:1.5:1", ""):
        with pytest.raises(ValueError):
            parse_inject_specs(bad)


def test_injection_is_seed_deterministic(registry):
    def run(seed):
        inj = FaultInjectingBenchmarker(
            ScriptedBench([]), [InjectSpec("transient", 0.5, seed)])
        pattern = []
        for i in range(40):
            try:
                inj.benchmark(f"s{i}")
                pattern.append(0)
            except InjectedTransientError:
                pattern.append(1)
        return pattern, inj

    p1, inj1 = run(3)
    p2, _ = run(3)
    p3, _ = run(4)
    assert p1 == p2          # same seed, same fault schedule
    assert p1 != p3          # different seed, different schedule
    assert inj1.injected["transient"] == sum(p1) > 0
    assert inj1.calls == 40


def test_deterministic_injection_keyed_by_schedule_identity():
    spec = InjectSpec("deterministic", 0.5, 123)
    inj = FaultInjectingBenchmarker(ScriptedBench([]), [spec])
    # find one schedule that fails and one that passes under this seed
    fails = next(f"s{i}" for i in range(50)
                 if _schedule_fails(schedule_id(f"s{i}"), spec))
    passes = next(f"s{i}" for i in range(50)
                  if not _schedule_fails(schedule_id(f"s{i}"), spec))
    for _ in range(3):  # the SAME schedules fail/pass on every attempt
        with pytest.raises(InjectedDeterministicError):
            inj.benchmark(fails)
        inj.benchmark(passes)


def test_hang_injection_stalls_then_proceeds():
    naps = []
    inj = FaultInjectingBenchmarker(
        ScriptedBench([]), [InjectSpec("hang", 1.0, 5)],
        hang_secs=12.5, sleep=naps.append)
    res = inj.benchmark("s")
    assert res.pct50 == 1.0  # a hang is a stall, not an error
    assert naps == [12.5]


def test_device_lost_injection():
    inj = FaultInjectingBenchmarker(
        ScriptedBench([]), [InjectSpec("device_lost", 1.0, 5)])
    with pytest.raises(DeviceLostError):
        inj.benchmark("s")


def test_injected_hang_plus_watchdog_end_to_end(tracer):
    """The composition the chaos harness relies on: an injected hang makes
    the watchdog fire, the timeout classifies transient, the retry passes
    (rate keeps the second draw clean), and the whole failure is visible as
    classified fault.* telemetry."""
    from tenzing_tpu.fault.inject import _attempt_fires

    # a seed whose first draw injects the hang and whose second does not,
    # so the retry after the watchdog timeout recovers (draws are keyed on
    # schedule identity + attempt counter — rank-agreed by construction)
    rate = 0.6

    def draws(s):
        spec = InjectSpec("hang", rate, s)
        sid = schedule_id("sched")
        return (_attempt_fires(sid, 0, spec), _attempt_fires(sid, 1, spec))

    seed = next(s for s in range(1000)
                if draws(s)[0] and not draws(s)[1])
    inj = FaultInjectingBenchmarker(
        ScriptedBench([]), [InjectSpec("hang", rate, seed)],
        hang_secs=30.0)  # real sleep on a daemon thread, abandoned
    rb = _resilient(inj, timeout_secs=0.1)
    res = rb.benchmark("sched")
    assert res.pct50 == 1.0
    names = [e.name for e in tracer.events()]
    assert "fault.injected" in names
    assert "fault.error" in names and "fault.retry" in names


def test_resilient_batch_under_watchdog_isolates_caller_lists():
    """With the watchdog armed, a timed-out batch abandons a worker thread
    that still holds its list references — so each attempt must get fresh
    private lists, and the caller's only ever receive a COMPLETED
    attempt's aligned series (no stale interleaved appends)."""
    seen_lists = []

    class Batchy:
        def __init__(self):
            self.calls = 0

        def benchmark(self, order, opts=None):
            return _ok()

        def benchmark_batch_times(self, orders, opts=None, seed=0,
                                  times_out=None):
            self.calls += 1
            seen_lists.append(times_out)
            if self.calls == 1:
                times_out[0].append(99.0)  # partial garbage, then hang
                time.sleep(30.0)
            for t, v in zip(times_out, ([1.0], [2.0])):
                t.extend(v)
            return times_out

    inner = Batchy()
    rb = _resilient(inner, timeout_secs=0.05)
    t0, t1 = [], []
    rb.benchmark_batch_times(["a", "b"], BenchOpts(), times_out=[t0, t1])
    assert inner.calls == 2
    # the caller's lists were never handed to the supervised inner call...
    assert all(lst is not t0 and lst is not t1
               for attempt in seen_lists for lst in attempt)
    # ...and carry exactly the completed attempt's series, garbage-free
    assert t0 == [1.0] and t1 == [2.0]


def test_injection_draws_agree_across_instances():
    """The rank-agreement substrate (ROADMAP multi-host chaos item): draws
    are keyed on (kind, seed, schedule identity, per-schedule attempt
    counter) — two injector instances fed the same benchmark-call sequence
    (what the broadcast protocol guarantees every rank sees) make
    IDENTICAL draws, with no shared RNG state.  A restarted process
    re-counts attempts from zero, so a resumed run replays the same
    faults too."""
    specs = [InjectSpec("transient", 0.4, 3), InjectSpec("hang", 0.1, 5)]

    def run():
        naps = []
        inj = FaultInjectingBenchmarker(ScriptedBench([]), specs,
                                        hang_secs=1.0, sleep=naps.append)
        pattern = []
        # repeated queries of the same schedules: the attempt counter must
        # advance the draw (a retry is a fresh coin flip, same on all ranks)
        for i in [0, 1, 2, 0, 0, 1, 2, 2, 0, 1] * 4:
            try:
                inj.benchmark(f"s{i}")
                pattern.append(0)
            except InjectedTransientError:
                pattern.append(1)
        return pattern, len(naps), inj

    p1, n1, inj1 = run()
    p2, n2, _ = run()
    assert p1 == p2 and n1 == n2  # rank-agreed by construction
    assert sum(p1) > 0 and n1 > 0  # both channels actually fired
    # ...and the same schedule is NOT deterministically fated: different
    # attempts of one schedule draw independently
    by_attempt = [p1[i] for i, q in enumerate([0, 1, 2, 0, 0, 1, 2, 2, 0, 1]
                                              * 4) if q == 0]
    assert 0 < sum(by_attempt) < len(by_attempt)


def test_corrupt_injection_mutates_by_schedule_identity(registry):
    """corrupt: draws by schedule identity, mutates via corrupt_schedule,
    and records original -> mutated ids for accountability."""
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.fault import corrupt_schedule
    from tenzing_tpu.models.spmv import SpMVCompound
    from tenzing_tpu.solve.dfs import enumerate_schedules

    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    states = enumerate_schedules(g, Platform.make_n_lanes(2), max_seqs=40)
    spec = InjectSpec("corrupt", 0.5, 11)

    seen = {}

    class Recorder:
        def benchmark(self, order, opts=None):
            seen[schedule_id(order)] = order
            return BenchResult.from_times([1.0])

    inj = FaultInjectingBenchmarker(Recorder(), [spec])
    for st in states:
        inj.benchmark(st.sequence)
    assert inj.injected["corrupt"] > 0
    assert set(inj.corrupted) != set(inj.corrupted.values())
    for orig, mutated in inj.corrupted.items():
        assert mutated in seen  # the mutation went DOWN the stack
        assert orig != mutated
    # replay: identical mutations (content-keyed, no RNG state)
    inj2 = FaultInjectingBenchmarker(Recorder(), [spec])
    for st in states:
        inj2.benchmark(st.sequence)
    assert inj2.corrupted == inj.corrupted
    # corrupt_schedule without sync ops has nothing to mutate
    from tenzing_tpu.core.sequence import Sequence

    assert corrupt_schedule(Sequence([g.start(), g.finish()]), 1) is None


def test_injector_forwards_degraded_provenance():
    """A corrupt injector stacked between the journaling layer and the
    resilient wrapper must forward was_degraded — otherwise fallback
    answers would journal as provenance 'measured' and a resumed run
    would replay predictions as device measurements."""
    class DegradedInner:
        def was_degraded(self, order):
            return order == "degraded-one"

        def benchmark(self, order, opts=None):
            return BenchResult.from_times([1.0])

    inj = FaultInjectingBenchmarker(DegradedInner(),
                                    [InjectSpec("corrupt", 1.0, 1)])
    assert inj.was_degraded("degraded-one") is True
    assert inj.was_degraded("other") is False
    # ...and stays False-safe over an inner without the method
    assert FaultInjectingBenchmarker(
        ScriptedBench([]), [InjectSpec("corrupt", 1.0, 1)]
    ).was_degraded("x") is False


def test_exempt_ids_skip_identity_keyed_kinds_only():
    """bench.py registers its naive baseline here: identity-keyed
    candidate-fault kinds (deterministic/corrupt) skip exempt schedules —
    a seed deterministically breaking the BASELINE would kill every run —
    while per-attempt tunnel-fault kinds still apply to them."""
    det = InjectSpec("deterministic", 0.5, 123)
    # a schedule this seed deterministically fails
    fails = next(f"s{i}" for i in range(50)
                 if _schedule_fails(schedule_id(f"s{i}"), det))
    inj = FaultInjectingBenchmarker(ScriptedBench([]), [det],
                                    exempt_ids={schedule_id(fails)})
    inj.benchmark(fails)  # exempt: no raise
    assert inj.injected["deterministic"] == 0
    # transient still fires on an exempt schedule (per-attempt kind)
    tr = InjectSpec("transient", 1.0, 1)
    inj2 = FaultInjectingBenchmarker(ScriptedBench([]), [tr],
                                     exempt_ids={schedule_id(fails)})
    with pytest.raises(InjectedTransientError):
        inj2.benchmark(fails)
