"""Async compile pipeline (bench/pipeline.py, ISSUE 5).

Acceptance coverage:

* with prefetch enabled, a deterministic fake-runner harness produces
  results **bit-identical** to prefetch-off for all three solvers (MCTS,
  DFS, hill-climb) — hints consume no search RNG and touch no search state;
* a wall-clock test demonstrates real compile/measure overlap: total wall
  for a multi-candidate batch < serialized compile-time + measure-time;
* background compile failures surface on the foreground ``benchmark()``
  call, classified by the fault taxonomy, and deterministic ones quarantine
  exactly once — the resilient layer's protocol is unchanged;
* the pool leaks no threads: ``close()`` joins the workers, the SIGINT trap
  handler cancels pending compiles;
* the schedule-identity memo (``Sequence.cached``) serves stable values and
  invalidates on mutation.
"""

import threading
import time

import pytest

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    BenchResult,
    CachingBenchmarker,
    CsvBenchmarker,
    result_row,
    schedule_id,
)
from tenzing_tpu.bench.pipeline import PrefetchingBenchmarker
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.schedule import remove_redundant_syncs
from tenzing_tpu.core.sequence import canonical_key
from tenzing_tpu.models.spmv import SpMVCompound
from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics
from tenzing_tpu.obs.tracer import Tracer, set_tracer
from tenzing_tpu.solve.dfs import DfsOpts, enumerate_schedules
from tenzing_tpu.solve.dfs import explore as dfs_explore
from tenzing_tpu.solve.local import LocalOpts, hill_climb
from tenzing_tpu.solve.mcts import MctsOpts, explore
from tenzing_tpu.utils import trap


@pytest.fixture
def tracer():
    tr = Tracer(enabled=True)
    prev = set_tracer(tr)
    try:
        yield tr
    finally:
        set_tracer(prev)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        yield reg
    finally:
        set_metrics(prev)


def _graph():
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    return g


def _synth_result(seq) -> BenchResult:
    import hashlib

    key = canonical_key(remove_redundant_syncs(seq))
    h = hashlib.sha256(repr(key).encode()).digest()
    t = 1.0 + int.from_bytes(h[:8], "big") / float(1 << 64)
    return BenchResult.from_times([t, t, t])


@pytest.fixture(scope="module")
def corpus():
    """The full deduplicated 2-lane SpMV space as recorded CSV rows (the
    chaos-test corpus pattern: deterministic answers, no device)."""
    states = enumerate_schedules(_graph(), Platform.make_n_lanes(2),
                                 max_seqs=10_000)
    assert 3 <= len(states) < 10_000
    rows = [result_row(i, _synth_result(st.sequence), st.sequence)
            for i, st in enumerate(states)]
    return rows, [st.sequence for st in states]


def mk_db(rows):
    return CsvBenchmarker(rows, _graph(), normalize=True)


class FakeExecutor:
    """Compile stand-in: ``precompile``/``is_compiled`` against a set, with
    an optional per-compile sleep (the overlap test) and an optional
    failure oracle (the chaos tests)."""

    def __init__(self, compile_secs: float = 0.0, fail=None):
        self.compile_secs = compile_secs
        self.fail = fail
        self.compiled = set()
        self.precompiles = 0
        self._lock = threading.Lock()

    def is_compiled(self, order) -> bool:
        with self._lock:
            return schedule_id(order) in self.compiled

    def precompile(self, order) -> bool:
        if self.fail is not None:
            exc = self.fail(order)
            if exc is not None:
                raise exc
        if self.compile_secs:
            time.sleep(self.compile_secs)
        with self._lock:
            sid = schedule_id(order)
            if sid in self.compiled:
                return False
            self.compiled.add(sid)
            self.precompiles += 1
            return True


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("tz-prefetch") and t.is_alive()]


def _sims_key(sims):
    return [(canonical_key(remove_redundant_syncs(s.order)),
             s.result.pct50) for s in sims]


# -- accounting / fault surfacing -------------------------------------------


def test_prefetch_issue_hit_wasted_accounting(corpus, registry, tracer):
    rows, terminals = corpus
    ex = FakeExecutor()
    p = PrefetchingBenchmarker(mk_db(rows), executor=ex, workers=2)
    try:
        issued = p.prefetch(terminals[:3])
        assert issued == 3
        # re-hinting is deduplicated, non-Sequence orders are skipped
        assert p.prefetch(terminals[:3] + ["not-a-sequence"]) == 0
        for o in terminals[:2]:
            p.benchmark(o, None)
        assert p.hits == 2
        # let the third (speculative) compile land before close(): on a
        # loaded host it can still be queued, and close() cancels queued
        # work — which would (correctly) report wasted()==0
        deadline = time.time() + 10.0
        while p.wasted() < 1 and time.time() < deadline:
            time.sleep(0.01)
    finally:
        p.close()
    assert p.issued == 3 and p.wasted() == 1 and p.failed == 0
    assert registry.counter("pipeline.prefetch.issued").value == 3
    assert registry.counter("pipeline.prefetch.hits").value == 2
    assert registry.counter("pipeline.prefetch.wasted").value == 1
    # every background compile landed as a pipeline.precompile span
    spans = [s for s in tracer.spans() if s.name == "pipeline.precompile"]
    assert len(spans) == 3
    assert not _prefetch_threads()  # close() joined the workers
    # closed: hints are no-ops, benchmark still answers
    assert p.prefetch(terminals[3:4]) == 0
    assert p.benchmark(terminals[0], None) == mk_db(rows).benchmark(
        terminals[0], None)


def test_already_compiled_hints_are_skipped(corpus, registry):
    rows, terminals = corpus
    ex = FakeExecutor()
    ex.precompile(terminals[0])
    p = PrefetchingBenchmarker(mk_db(rows), executor=ex, workers=1)
    try:
        assert p.prefetch(terminals[:1]) == 0  # is_compiled short-circuits
        assert ex.precompiles == 1
    finally:
        p.close()


def test_queue_bound_drops_excess_hints(corpus, registry):
    rows, terminals = corpus
    n = min(len(terminals), 8)
    ex = FakeExecutor(compile_secs=0.2)
    p = PrefetchingBenchmarker(mk_db(rows), executor=ex, workers=1, depth=2)
    try:
        p.prefetch(terminals[:n])
        # worker=1, depth=2: at most 2 in flight; the rest dropped (and
        # re-hintable later), never queued unboundedly
        assert p.issued <= 2
        assert p.dropped >= n - 2
        assert registry.counter("pipeline.prefetch.dropped").value \
            == p.dropped
    finally:
        p.close()


def test_background_failure_surfaces_classified_and_quarantines_once(
        corpus, registry, tracer, tmp_path):
    """A background compile failure is recorded off the control plane and
    surfaced on the FOREGROUND benchmark() call, where the resilient layer
    classifies it (fault taxonomy), quarantines the deterministic candidate
    exactly once, and never measures it."""
    from collections import Counter

    from tenzing_tpu.fault import (
        BackoffPolicy,
        Quarantine,
        QuarantinedScheduleError,
        ResilientBenchmarker,
    )

    rows, terminals = corpus
    bad = terminals[0]
    bad_sid = schedule_id(bad)

    class CountingDb:
        def __init__(self, db):
            self.db = db
            self.by_sid = Counter()

        def benchmark(self, order, opts=None):
            self.by_sid[schedule_id(order)] += 1
            return self.db.benchmark(order, opts)

    ex = FakeExecutor(fail=lambda o: RuntimeError(
        "failed to compile: injected") if schedule_id(o) == bad_sid else None)
    counting = CountingDb(mk_db(rows))
    p = PrefetchingBenchmarker(counting, executor=ex, workers=1)
    rb = ResilientBenchmarker(
        p, quarantine=Quarantine(str(tmp_path / "q.json")),
        policy=BackoffPolicy(retries=2, base_secs=0.0, jitter=0.0),
        sleep=lambda s: None)
    try:
        assert p.prefetch([bad, terminals[1]]) == 2
        with pytest.raises(RuntimeError, match="failed to compile"):
            rb.benchmark(bad, None)
        # classified deterministic -> quarantined, never measured, and the
        # pipeline recorded the failure with its taxonomy class
        assert counting.by_sid[bad_sid] == 0
        assert p.failed == 1 and p.surfaced == 1
        evs = [e for e in tracer.events()
               if e.name == "pipeline.precompile_failed"]
        assert evs and evs[0].attrs["error_class"] == "deterministic"
        with pytest.raises(QuarantinedScheduleError):
            rb.benchmark(bad, None)
        assert counting.by_sid[bad_sid] == 0
        # the healthy hint still measures normally (and was a prefetch hit)
        rb.benchmark(terminals[1], None)
        assert counting.by_sid[schedule_id(terminals[1])] == 1
        assert p.hits == 1
    finally:
        p.close()
    assert not _prefetch_threads()


# -- fused batched rounds (the search fleet's measurement owner) ------------


class BatchDb:
    """CsvBenchmarker plus the fused-round batch protocol
    (``benchmark_batch_times``) — the shape the fleet's measurement owner
    drives (search/fleet.py): each member answered from the recorded corpus
    in one call, per-group seeds recorded for the passthrough assertion."""

    def __init__(self, db):
        self.db = db
        self.batch_calls = 0
        self.last_group_seeds = None

    def benchmark(self, order, opts=None):
        return self.db.benchmark(order, opts)

    def benchmark_batch_times(self, orders, opts=None, seed=0,
                              times_out=None, group_seeds=None):
        self.batch_calls += 1
        self.last_group_seeds = group_seeds
        out = []
        for o in orders:
            r = self.db.benchmark(o, opts)
            ts = list(r.times) if r.times else [r.pct50] * 3
            if times_out is not None:
                times_out[len(out)].extend(ts)
            out.append(ts)
        return out


def test_batched_round_full_queue_drops_hints_without_blocking(
        corpus, registry):
    """A fused measurement round over a saturated prefetch pipeline must
    DROP its members' hints and still run: the members are simply not
    prefetched (the inner batch warms them itself), the round never blocks
    behind speculative work hinted earlier, and the shed hints land on the
    ``dropped`` tally (re-hintable later)."""
    rows, terminals = corpus
    assert len(terminals) >= 6
    gate = threading.Event()

    class GatedExecutor(FakeExecutor):
        def precompile(self, order):
            gate.wait(30.0)
            return super().precompile(order)

    ex = GatedExecutor()
    inner = BatchDb(mk_db(rows))
    p = PrefetchingBenchmarker(inner, executor=ex, workers=1, depth=2)
    try:
        # saturate: 1 compile parked on the gate + 1 queued = depth
        p.prefetch(terminals[:4])
        assert p.issued == 2 and p.dropped == 2
        members = terminals[4:6]
        t0 = time.time()
        times = p.benchmark_batch_times(
            members, None, seed=3, group_seeds=[(1, 5), (1, 7)])
        wall = time.time() - t0
        # the round completed inline while the pool stayed parked
        assert wall < 5.0 and not gate.is_set()
        assert inner.batch_calls == 1
        assert inner.last_group_seeds == [(1, 5), (1, 7)]
        db = mk_db(rows)
        assert times == [[db.benchmark(o, None).pct50] * 3 for o in members]
        # both members' hints were shed, never queued behind the backlog
        assert p.dropped == 4
        assert registry.counter("pipeline.prefetch.dropped").value == 4
    finally:
        gate.set()
        p.close()
    assert not _prefetch_threads()


def test_batched_round_surfaces_stored_failure_exactly_once(
        corpus, registry):
    """A background compile failure stored for a batch member surfaces on
    the foreground join of the fused round — once.  The raise consumes the
    stored failure (the resilient layer's retry contract), so the next
    round over the same members reaches the inner batch instead of
    re-raising a stale exception."""
    rows, terminals = corpus
    bad, good = terminals[0], terminals[1]
    bad_sid = schedule_id(bad)
    ex = FakeExecutor(fail=lambda o: RuntimeError(
        "failed to compile: injected") if schedule_id(o) == bad_sid else None)
    inner = BatchDb(mk_db(rows))
    p = PrefetchingBenchmarker(inner, executor=ex, workers=1)
    try:
        assert p.prefetch([bad]) == 1
        deadline = time.time() + 10.0
        while p.failed < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert p.failed == 1
        with pytest.raises(RuntimeError, match="injected"):
            p.benchmark_batch_times([bad, good], None, seed=1)
        # surfaced before the inner batch ran, and exactly once
        assert p.surfaced == 1 and inner.batch_calls == 0
        assert registry.counter("pipeline.prefetch.surfaced").value == 1
        times = p.benchmark_batch_times([bad, good], None, seed=1)
        assert len(times) == 2 and inner.batch_calls == 1
        assert p.surfaced == 1  # consumed: no stale re-raise
        assert registry.counter("pipeline.prefetch.surfaced").value == 1
        assert p.hits == 1  # the healthy member's hint landed meanwhile
    finally:
        p.close()
    assert not _prefetch_threads()


def test_transient_background_failure_retries_through_to_real_attempt(
        corpus, registry):
    """A surfaced TRANSIENT background failure is consumed by the raise:
    the resilient retry reaches the real foreground attempt and succeeds."""
    from tenzing_tpu.fault import BackoffPolicy, ResilientBenchmarker
    from tenzing_tpu.fault.errors import TransientError

    rows, terminals = corpus
    flaky = {"armed": True}

    def fail(order):
        if flaky["armed"]:
            flaky["armed"] = False
            return TransientError("injected background flake")
        return None

    ex = FakeExecutor(fail=fail)
    p = PrefetchingBenchmarker(mk_db(rows), executor=ex, workers=1)
    rb = ResilientBenchmarker(
        p, policy=BackoffPolicy(retries=2, base_secs=0.0, jitter=0.0),
        sleep=lambda s: None)
    try:
        p.prefetch(terminals[:1])
        res = rb.benchmark(terminals[0], None)  # surfaced, retried, answered
        assert res == _synth_result(terminals[0])
        assert p.surfaced == 1
    finally:
        p.close()


def test_trap_handler_cancels_pending_compiles(corpus):
    """The SIGINT path: the trap handler only closes the intake (it must
    not touch pool locks the interrupted thread may hold); close()
    afterwards cancels the still-queued compiles and joins cleanly."""
    rows, terminals = corpus
    n = min(len(terminals), 6)
    ex = FakeExecutor(compile_secs=0.3)
    p = PrefetchingBenchmarker(mk_db(rows), executor=ex, workers=1,
                               depth=n)
    try:
        p.prefetch(terminals[:n])
        assert p.issued >= 2
        trap.run_callbacks()  # what the real SIGINT handler does
        assert p.prefetch(terminals[:n]) == 0  # closed to new work
    finally:
        p.close()
    # cancel_futures dropped the queued compiles: far fewer ran than issued
    assert ex.precompiles <= 2
    assert not _prefetch_threads()
    # close() unregistered the pipeline's trap handler
    assert p._trap_cancel not in trap.callbacks()


# -- bit-identical search behavior -------------------------------------------


def test_solvers_bit_identical_prefetch_on_vs_off(corpus, registry):
    """The acceptance criterion: for all three solvers, measured results
    with prefetch enabled are bit-identical to prefetch-off over the
    deterministic corpus."""
    rows, _ = corpus
    g = _graph()
    plat = Platform.make_n_lanes(2)

    def run_all(prefetcher):
        mcts = explore(g, plat, mk_db(rows),
                       MctsOpts(n_iters=24, seed=3, prefetch=prefetcher))
        dfs = dfs_explore(g, plat, mk_db(rows),
                          DfsOpts(max_seqs=10_000, prefetch=prefetcher))
        return mcts, dfs

    off_mcts, off_dfs = run_all(None)
    ex = FakeExecutor()
    p = PrefetchingBenchmarker(mk_db(rows), executor=ex, workers=2)
    try:
        on_mcts, on_dfs = run_all(p)
        assert p.issued > 0  # the hints actually flowed
    finally:
        p.close()
    assert _sims_key(on_mcts.sims) == _sims_key(off_mcts.sims)
    assert on_mcts.tree_size == off_mcts.tree_size
    assert _sims_key(on_dfs.sims) == _sims_key(off_dfs.sims)
    assert not _prefetch_threads()


def test_hill_climb_bit_identical_prefetch_on_vs_off():
    """Hill-climb neighbor batches are materialized before the measure loop
    either way (pure replay): the accepted chain and every measured
    neighbor are identical with and without prefetch."""
    from tests.test_local import PHASES, RiggedBenchmarker, mk

    def climb(prefetcher):
        g, plat, _ = mk()
        return hill_climb(
            g, plat, CachingBenchmarker(RiggedBenchmarker()), PHASES,
            opts=LocalOpts(budget=18, bench_opts=BenchOpts(n_iters=1),
                           seed=3, prefetch=prefetcher),
        )

    off = climb(None)
    ex = FakeExecutor()
    p = PrefetchingBenchmarker(None, executor=ex, workers=2)
    try:
        on = climb(p)
        assert p.issued > 0
    finally:
        p.close()
    key = lambda r: ([(canonical_key(s.order), s.result.pct50)
                      for s in r.sims],
                     canonical_key(r.final.order), r.final.result.pct50)
    assert key(on) == key(off)


# -- compile/measure overlap --------------------------------------------------


def test_wall_clock_overlap_beats_serialized_compile_plus_measure(corpus):
    """The headline: for a multi-candidate batch, pipelined wall <
    serialized compile + measure.  Compile is simulated at 80 ms (sleep —
    GIL-released, like XLA), measurement at 30 ms; with 4 workers the
    compiles hide almost entirely behind the measurements."""
    rows, terminals = corpus
    n = min(len(terminals), 6)
    cands = terminals[:n]
    compile_s, measure_s = 0.08, 0.03

    class SlowDeviceBench:
        """Device stand-in that compiles inline when the program cache
        misses — exactly the lazy TraceExecutor behavior."""

        def __init__(self, ex, db):
            self.ex = ex
            self.db = db

        def benchmark(self, order, opts=None):
            if not self.ex.is_compiled(order):
                self.ex.precompile(order)  # foreground (serialized) compile
            time.sleep(measure_s)
            return self.db.benchmark(order, opts)

    # serialized reference: compile + measure per candidate, no overlap
    ex_off = FakeExecutor(compile_secs=compile_s)
    bench_off = SlowDeviceBench(ex_off, mk_db(rows))
    t0 = time.perf_counter()
    for o in cands:
        bench_off.benchmark(o, None)
    serial_wall = time.perf_counter() - t0
    assert serial_wall >= n * (compile_s + measure_s) * 0.9

    # pipelined: hint the batch, then measure in the foreground
    ex_on = FakeExecutor(compile_secs=compile_s)
    p = PrefetchingBenchmarker(SlowDeviceBench(ex_on, mk_db(rows)),
                               executor=ex_on, workers=4, depth=n)
    try:
        t0 = time.perf_counter()
        p.prefetch(cands)
        for o in cands:
            p.benchmark(o, None)
        pipe_wall = time.perf_counter() - t0
    finally:
        p.close()
    assert p.hits == n  # every foreground call found its program ready
    # generous margin (CI scheduling noise): the pipeline must clearly beat
    # the serialized sum-of-parts
    assert pipe_wall < 0.75 * serial_wall, (pipe_wall, serial_wall)


# -- schedule-identity memoization (ISSUE 5 satellite) ------------------------


def test_sequence_memo_stable_and_invalidated_on_mutation(corpus):
    from tenzing_tpu.core.resources import Event
    from tenzing_tpu.core.serdes import sequence_to_json_str
    from tenzing_tpu.core.sync_ops import EventSync

    _, terminals = corpus
    seq = terminals[0][:]  # private copy (slice -> new Sequence)
    k1 = canonical_key(seq)
    assert canonical_key(seq) is k1  # memo serves the same object
    j1 = sequence_to_json_str(seq)
    assert sequence_to_json_str(seq) is j1
    s1 = schedule_id(seq)
    assert schedule_id(seq) is s1
    seq.push_back(EventSync(Event(0)))
    # mutation invalidates every derivation
    assert canonical_key(seq) != k1
    assert sequence_to_json_str(seq) != j1
    assert schedule_id(seq) != s1
    # and the recomputed values are the true ones
    assert canonical_key(seq) == canonical_key(
        type(seq)(seq.vector()))
    assert sequence_to_json_str(seq) == sequence_to_json_str(
        type(seq)(seq.vector()))
