"""Fingerprint stability + bucketing goldens (ISSUE 7 satellite).

The serving key space only works if the same workload configuration
yields byte-identical fingerprints across process restarts, hosts, and
argument orderings — otherwise independently-warmed stores fragment
instead of merging.  These tests pin:

* the power-of-two bucket boundaries with golden cases (a silent rule
  change would re-shard every store in the fleet);
* digest independence from construction order and ``PYTHONHASHSEED``
  (the restart case, asserted across real subprocesses);
* the exact/bucket digest relationships the resolver's tiers key on;
* ``schedule_key``'s agreement with the repo-wide ``canonical_key``
  equivalence (modulo redundant syncs).
"""

import json
import subprocess
import sys

import pytest

from tenzing_tpu.bench.driver import DriverRequest
from tenzing_tpu.serve.fingerprint import (
    WorkloadFingerprint,
    fingerprint_of,
    schedule_key,
    shape_bucket,
)

# golden bucket boundaries: 2^k stays, 2^k + 1 rounds up — pinned so a
# bucketing change cannot land silently (it re-keys every store)
BUCKET_GOLDENS = [
    (0, 0), (-3, 0), (1, 1), (2, 2), (3, 4), (4, 4), (5, 8),
    (7, 8), (8, 8), (9, 16), (300, 512), (511, 512), (512, 512),
    (513, 1024), (1024, 1024), (1025, 2048), (150_000, 262_144),
]


def test_bucket_goldens():
    for n, want in BUCKET_GOLDENS:
        assert shape_bucket(n) == want, (n, shape_bucket(n), want)


def test_bucket_is_idempotent():
    # a bucket is its own bucket: re-fingerprinting a bucketed shape
    # cannot drift to a different neighborhood
    for n in (1, 2, 4, 64, 512, 4096):
        assert shape_bucket(shape_bucket(n)) == shape_bucket(n)


def test_field_order_cannot_leak_into_digest():
    a = WorkloadFingerprint(
        workload="spmv", variant="full",
        shape=(("bw", 0), ("m", 512), ("nnz_per_row", 10)),
        bucket=(("bw", 0), ("m", 512), ("nnz_per_row", 16)),
        mesh=(("lanes", 2),),
        engines=(("ici", ("a", "b")), ("pcie", ("c",))),
    )
    # same content reconstructed through to_json/from_json (dict-keyed,
    # so any ordering the JSON round-trip imposes must not matter)
    b = WorkloadFingerprint.from_json(a.to_json())
    assert a == b
    assert a.exact_digest == b.exact_digest
    assert a.bucket_digest == b.bucket_digest


def test_digest_stable_across_process_restarts():
    """Byte-identical digests under different PYTHONHASHSEEDs — the
    restart/fleet case: no Python hash() anywhere in the key path."""
    prog = (
        "from tenzing_tpu.bench.driver import DriverRequest\n"
        "from tenzing_tpu.serve.fingerprint import fingerprint_of\n"
        "f = fingerprint_of(DriverRequest(workload='spmv', m=512))\n"
        "import sys\n"
        "sys.stdout.write(f.exact_digest + ' ' + f.bucket_digest)\n"
    )
    import os
    from pathlib import Path

    repo = str(Path(__file__).resolve().parent.parent)
    outs = set()
    for seed in ("0", "1", "31337"):
        env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo)
        r = subprocess.run(
            [sys.executable, "-c", prog], capture_output=True, text=True,
            env=env, cwd=repo, check=True)
        outs.add(r.stdout.strip())
    assert len(outs) == 1, outs
    # and the in-process digests agree with the subprocess ones
    f = fingerprint_of(DriverRequest(workload="spmv", m=512))
    assert outs.pop() == f"{f.exact_digest} {f.bucket_digest}"


def test_exact_vs_bucket_relationships():
    base = fingerprint_of(DriverRequest(workload="spmv", m=512))
    near = fingerprint_of(DriverRequest(workload="spmv", m=500))
    far = fingerprint_of(DriverRequest(workload="spmv", m=100_000))
    assert base.exact_digest != near.exact_digest
    assert base.bucket_digest == near.bucket_digest  # the near-miss tier
    assert base.bucket_digest != far.bucket_digest   # the cold tier
    # workload, variant, and mesh all partition the key space
    assert fingerprint_of(DriverRequest(workload="halo")).exact_digest \
        != base.exact_digest
    assert fingerprint_of(
        DriverRequest(workload="spmv", m=512, smoke=True)).exact_digest \
        != base.exact_digest
    assert fingerprint_of(
        DriverRequest(workload="spmv", m=512, lanes=4)).exact_digest \
        != base.exact_digest


def test_fingerprint_json_roundtrip_carries_digests():
    f = fingerprint_of(DriverRequest(workload="attn", smoke=True))
    j = f.to_json()
    assert j["exact"] == f.exact_digest
    assert j["bucket_digest"] == f.bucket_digest
    assert WorkloadFingerprint.from_json(
        json.loads(json.dumps(j))).exact_digest == f.exact_digest


@pytest.fixture(scope="module")
def spmv_graph():
    from tenzing_tpu.bench.driver import graph_for

    g, _ = graph_for(DriverRequest(workload="spmv", m=512))
    return g


def _drive(g, n_lanes, picks):
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State

    plat = Platform.make_n_lanes(n_lanes)
    st = State(g)
    i = 0
    while not st.is_terminal():
        ds = st.get_decisions(plat)
        st = st.apply(ds[picks[i % len(picks)] % len(ds)])
        i += 1
    return st.sequence


def test_schedule_key_matches_canonical_equivalence(spmv_graph):
    a = _drive(spmv_graph, 2, [1, 2, 0])
    b = _drive(spmv_graph, 2, [1, 2, 0])  # independently driven twin
    c = _drive(spmv_graph, 2, [2, 1, 0])
    assert schedule_key(a) == schedule_key(b)
    assert schedule_key(a) != schedule_key(c)
    # redundant-sync normalization is part of the key (the same
    # equivalence CsvBenchmarker(normalize=True) answers under)
    from tenzing_tpu.core.schedule import remove_redundant_syncs

    assert schedule_key(a) == schedule_key(remove_redundant_syncs(a))
