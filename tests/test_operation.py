"""Op identity semantics (reference src/operation.cpp:87-100 inline tests)."""

from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    DeviceOp,
    Finish,
    NoOp,
    Start,
    keep_uniques,
    make_lane_variations,
    unbound,
)
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, LaneSync, WaitEvent


class KOp(DeviceOp):
    """Minimal fake device op (reference test_gpu_graph.cu:12-28 KernelOp)."""

    def apply(self, bufs, ctx):
        return {}


def test_name_equality():
    assert NoOp("a") == NoOp("a")
    assert NoOp("a") != NoOp("b")
    assert Start() == Start()
    assert Finish() == Finish()
    assert Start() != Finish()


def test_bound_equals_unbound():
    op = KOp("k")
    b0 = op.bind(Lane(0))
    b1 = op.bind(Lane(1))
    # lane-insensitive identity (reference operation.hpp:20-32)
    assert b0 == op
    assert b0 == b1
    assert hash(b0) == hash(op)
    assert unbound(b0) is op
    assert b0.lane() == Lane(0) and b1.lane() == Lane(1)


def test_sync_ops_compare_kind_only():
    # reference ops_cuda.hpp:15-20 dedup invariant
    assert EventRecord(Lane(0), Event(0)) == EventRecord(Lane(1), Event(5))
    assert WaitEvent(Lane(0), Event(0)) == WaitEvent(Lane(2), Event(9))
    assert EventRecord(Lane(0), Event(0)) != WaitEvent(Lane(0), Event(0))
    assert EventSync(Event(1)) != LaneSync(Lane(1))


def test_lane_variations():
    op = KOp("k")
    lanes = [Lane(0), Lane(1)]
    vars = make_lane_variations(op, lanes)
    assert [v.lane() for v in vars] == lanes
    # non-device ops pass through
    n = NoOp("n")
    assert make_lane_variations(n, lanes) == [n]
    # rebinding an already-bound op
    rb = make_lane_variations(op.bind(Lane(1)), lanes)
    assert [v.lane() for v in rb] == lanes


def test_keep_uniques():
    a, b = NoOp("a"), NoOp("b")
    assert keep_uniques([a, b, NoOp("a"), a]) == [a, b]


def test_total_order():
    ops = sorted([NoOp("b"), Finish(), NoOp("a"), Start()])
    # deterministic, stable total order usable as map keys
    assert ops == sorted(reversed(ops))
