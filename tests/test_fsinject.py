"""Hostile-filesystem fault injection (fault/fsinject.py; ISSUE 19):
the seeded spec grammar, identity-keyed draw determinism, each fault
kind's behavior through the utils/atomic.py seam, max-fires bursts, and
the env-install path subprocess fleets inherit."""

import errno
import json
import os

import pytest

from tenzing_tpu.fault import fsinject
from tenzing_tpu.fault.fsinject import (
    FsInjectSpec,
    InjectedTornRename,
    format_fs_specs,
    parse_fs_specs,
)
from tenzing_tpu.utils import atomic
from tenzing_tpu.utils.atomic import (
    atomic_dump_json,
    io_getmtime,
    read_json,
)


@pytest.fixture(autouse=True)
def _clean_backend():
    """Every test starts and ends with the well-behaved filesystem."""
    fsinject.uninstall()
    yield
    fsinject.uninstall()


# -- spec grammar ------------------------------------------------------------

def test_parse_fs_specs_golden():
    specs = parse_fs_specs("eio:0.5:7,mtime_skew:1.0:11:2.5")
    assert specs == [FsInjectSpec("eio", 0.5, 7),
                     FsInjectSpec("mtime_skew", 1.0, 11, 2.5)]


def test_parse_fs_specs_loud_errors():
    """A typo'd chaos spec must fail loudly — silently injecting nothing
    would make a green hostile-fs run meaningless."""
    with pytest.raises(ValueError):
        parse_fs_specs("eioo:0.5:7")        # unknown kind
    with pytest.raises(ValueError):
        parse_fs_specs("eio:1.5:7")         # rate out of range
    with pytest.raises(ValueError):
        parse_fs_specs("eio:0.5")           # missing seed
    with pytest.raises(ValueError):
        parse_fs_specs("")                  # empty


def test_format_fs_specs_roundtrip():
    text = "eio:0.5:7,torn_rename:0.1:3:1,mtime_coarse:1.0:9:2"
    assert format_fs_specs(parse_fs_specs(text)) == text


# -- draw determinism --------------------------------------------------------

def test_draws_are_identity_keyed_and_replayable(tmp_path):
    """The same writes against the same filenames fire the same faults
    under the same seed — a chaos run replays; a different seed is a
    different schedule."""
    def fire_pattern(seed):
        b = fsinject.FsInjectBackend(parse_fs_specs(f"eio:0.4:{seed}"))
        out = []
        for n in range(24):
            try:
                b.check("write", str(tmp_path / "seg-x.jsonl"))
                out.append(False)
            except OSError:
                out.append(True)
        return out

    a, b2 = fire_pattern(7), fire_pattern(7)
    assert a == b2 and any(a)
    assert fire_pattern(8) != a


def test_max_fires_bounds_the_burst(tmp_path):
    """An integer param on eio/enospc/stale_read caps total fires: the
    burst-then-recover schedule the unwritable drill scripts."""
    b = fsinject.install("enospc:1.0:3:2")
    fired = 0
    for _ in range(10):
        try:
            b.check("write", str(tmp_path / "f.json"))
        except OSError as e:
            assert e.errno == errno.ENOSPC
            fired += 1
    assert fired == 2 and b.injected["enospc"] == 2


# -- the seam, kind by kind --------------------------------------------------

def test_eio_fires_on_write_through_seam(tmp_path):
    fsinject.install("eio:1.0:5:1")
    with pytest.raises(OSError) as ei:
        atomic_dump_json(str(tmp_path / "doc.json"), {"k": 1})
    assert ei.value.errno == errno.EIO
    # burst exhausted: the retry lands and the file is whole
    atomic_dump_json(str(tmp_path / "doc.json"), {"k": 1})
    assert json.load(open(tmp_path / "doc.json")) == {"k": 1}


def test_torn_rename_raise_mode_leaves_temp_bytes(tmp_path):
    """param=1: the publish step raises AFTER the temp bytes landed —
    the in-process stand-in for dying between fsync and link."""
    fsinject.install("torn_rename:1.0:5:1")
    path = str(tmp_path / "doc.json")
    with pytest.raises(InjectedTornRename):
        atomic_dump_json(path, {"k": 1})
    assert not os.path.exists(path)  # never published
    fsinject.uninstall()
    atomic_dump_json(path, {"k": 2})
    assert json.load(open(path)) == {"k": 2}


def test_stale_read_serves_previous_content_once(tmp_path):
    """An injected stale read returns the *superseded* complete JSON,
    at most once per replaced version — NFS attribute-cache staleness,
    the lie the lease nonce re-read must survive."""
    path = str(tmp_path / "lease.json")
    fsinject.install("stale_read:1.0:5")
    atomic_dump_json(path, {"v": 1})
    atomic_dump_json(path, {"v": 2})  # replace: v1 snapshotted
    assert read_json(path) == {"v": 1}   # the stale lie
    assert read_json(path) == {"v": 2}   # served once; truth thereafter


def test_mtime_skew_and_coarse_shift_observed_clock(tmp_path):
    path = str(tmp_path / "lease.json")
    atomic_dump_json(path, {"v": 1})
    real = os.path.getmtime(path)
    fsinject.install("mtime_skew:1.0:5:3.5")
    assert io_getmtime(path) == pytest.approx(real - 3.5)
    fsinject.install("mtime_coarse:1.0:5:2")
    seen = io_getmtime(path)
    assert seen <= real and seen % 2 == 0


def test_env_install_is_lazy_and_inherited(tmp_path, monkeypatch):
    """utils/atomic.py installs from $TENZING_FSINJECT on first write:
    the subprocess-fleet inheritance path, no argv plumbing."""
    monkeypatch.setenv(fsinject.FSINJECT_ENV, "eio:1.0:5:1")
    # simulate a fresh process: no backend yet, env not consulted
    atomic.set_io_backend(None)
    atomic._env_checked = False
    with pytest.raises(OSError):
        atomic_dump_json(str(tmp_path / "doc.json"), {"k": 1})
    assert fsinject.installed() is not None
    assert fsinject.installed().injected["eio"] == 1


def test_injected_counters_per_kind(tmp_path):
    b = fsinject.install("eio:1.0:5:1,enospc:1.0:5:1")
    for _ in range(2):
        try:
            atomic_dump_json(str(tmp_path / "doc.json"), {"k": 1})
        except OSError:
            pass
    assert b.injected["eio"] + b.injected["enospc"] == 2
