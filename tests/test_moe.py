"""MoE expert-parallel layer: DAG shape, schedule search, sharded numerics vs
a dense host evaluation of the routed layer (models/moe.py)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.moe import MoEArgs, MoELayer, make_moe_buffers
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


def _graph(args, impl_choice=False):
    g = Graph()
    g.start_then(MoELayer(args, impl_choice=impl_choice))
    g.then_finish(MoELayer(args, impl_choice=impl_choice))
    return g


def _mesh(nep):
    devs = np.array(jax.devices()[:nep])
    return Mesh(devs, ("ep",))


class TestDagShape:
    def test_chunk_chains_are_independent(self):
        """Chunk 0's FFN and chunk 1's dispatch must be DAG-independent — the
        pipelining freedom the solver searches."""
        args = MoEArgs(n_ep=4, tokens_per_shard=8, n_chunks=2)
        g = MoELayer(args).graph()
        by_name = {v.name(): v for v in g.vertices()}
        ffn0, disp1 = by_name["ffn_0"], by_name["a2a_disp_1"]
        assert disp1 not in g.succs(ffn0) and ffn0 not in g.succs(disp1)

    def test_post_wait_split(self):
        """Compute can be scheduled between a2a post and its await: the await
        is a distinct vertex downstream of the post."""
        args = MoEArgs(n_ep=2, tokens_per_shard=4, n_chunks=1)
        g = MoELayer(args).graph()
        by_name = {v.name(): v for v in g.vertices()}
        assert by_name["await_disp_0"] in g.succs(by_name["a2a_disp_0"])
        assert by_name["ffn_0"] in g.succs(by_name["await_disp_0"])

    def test_schedule_space_is_nontrivial(self):
        args = MoEArgs(n_ep=2, tokens_per_shard=8, n_chunks=2)
        plat = Platform.make_n_lanes(2)
        seqs = get_all_sequences(_graph(args), plat, max_seqs=50)
        assert len(seqs) > 1


@pytest.mark.needs_shard_map
class TestNumerics:
    @pytest.mark.parametrize("nep", [2, 4])
    def test_matches_dense_routing(self, nep):
        args = MoEArgs(n_ep=nep, tokens_per_shard=8, d_model=8, d_ff=16,
                       n_chunks=2)
        bufs, specs, want = make_moe_buffers(args, seed=1)
        plat = Platform.make_n_lanes(2, mesh=_mesh(nep), specs=specs)
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        order = get_all_sequences(_graph(args), plat, max_seqs=1)[0].sequence
        out = ex.run(order)
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-4,
                                   atol=2e-5)

    def test_every_schedule_is_equivalent(self):
        args = MoEArgs(n_ep=2, tokens_per_shard=4, d_model=4, d_ff=8,
                       n_chunks=2)
        bufs, specs, want = make_moe_buffers(args, seed=2)
        plat = Platform.make_n_lanes(2, mesh=_mesh(2), specs=specs)
        seqs = get_all_sequences(_graph(args), plat, max_seqs=6)
        assert len(seqs) >= 2
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        for s in seqs:
            out = ex.run(s.sequence)
            np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-4,
                                       atol=2e-5)

    def test_pallas_impl_matches(self):
        """The Pallas FFN choice computes the same Y (interpret mode)."""
        from tenzing_tpu.solve.dfs import enumerate_schedules

        args = MoEArgs(n_ep=2, tokens_per_shard=4, d_model=4, d_ff=8,
                       n_chunks=1)
        bufs, specs, want = make_moe_buffers(args, seed=3)
        plat = Platform.make_n_lanes(1, mesh=_mesh(2), specs=specs)
        seqs = enumerate_schedules(_graph(args, impl_choice=True), plat,
                                   max_seqs=16)
        names = [";".join(op.name() for op in s.sequence) for s in seqs]
        pallas = [s for s, n in zip(seqs, names) if ".pallas" in n]
        assert pallas
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        out = ex.run(pallas[0].sequence)
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-4,
                                   atol=2e-5)


class TestRoutingSetup:
    def test_capacity_covers_all_tokens(self):
        """Every routed token lands in exactly one slot with its gate weight;
        total slot weight equals the sum of gate probabilities."""
        args = MoEArgs(n_ep=4, tokens_per_shard=16, n_chunks=2)
        bufs, _specs, _want = make_moe_buffers(args, seed=4)
        total_w = sum(float(bufs[f"disp_w_{c}"].sum())
                      for c in range(args.n_chunks))
        # top-1 softmax gates are each >= 1/n_ep
        n_tok = args.n_ep * args.tokens_per_shard
        assert total_w >= n_tok / args.n_ep
        for c in range(args.n_chunks):
            nz = (bufs[f"disp_w_{c}"] > 0).sum()
            assert nz == n_tok / args.n_chunks
