"""Replay of the round-4 recorded searches: the r4k/r4m databases hold the
batched-z-unpack discovery (paired 2.48-2.55 on TPU v5e) that the warm-start
machinery carries across runs.  These tests lock the artifacts into the
suite: the winning schedules must keep deserializing against the menu graph,
the warm-start ranking must keep surfacing them first, and the recorded
winner's kernel composition is pinned (batched-Pallas unpacks on both
z-faces under XLA kernels elsewhere — the combination no hand incumbent
encodes)."""

import glob
import os

import pytest

from tenzing_tpu.bench.recorded import naive_anchor_of, rank_recorded
from tenzing_tpu.core.serdes import sequence_to_json
from tenzing_tpu.models.halo import HaloArgs
from tenzing_tpu.models.halo_pipeline import build_graph

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GLOB = os.path.join(REPO, "experiments", "halo_search_tpu_r4*.csv")

ARGS = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)


@pytest.fixture(scope="module")
def ranked():
    g = build_graph(ARGS, impl_choice=True, xfer_choice=True)
    return rank_recorded(sorted(glob.glob(GLOB)), g, topk=3)


def test_databases_have_naive_anchors():
    paths = sorted(glob.glob(GLOB))
    assert len(paths) >= 6
    for p in paths:
        assert naive_anchor_of(p) is not None, p


def test_top_discoveries_beat_two_x(ranked):
    assert len(ranked) == 3
    for seq, ratio in ranked:
        assert ratio > 2.0  # the r4k+ discoveries, not incumbent-class rows


def test_winner_composition_is_the_searched_combination(ranked):
    """At least one carried discovery uses batched-Pallas unpacks on both
    z-faces with XLA packs — the context-dependent combination the climb
    found (no greedy incumbent encodes it, and the isolated microbench even
    ranks z-unpack kernels the other way)."""
    found = False
    for seq, _ in ranked:
        names = {j.get("name", "") for j in sequence_to_json(seq)}
        if {"unpack_mz.pallasb", "unpack_pz.pallasb"} <= names and any(
            n.startswith("pack_") and n.endswith(".xla") for n in names
        ):
            found = True
    assert found
