"""Synthesized collectives (ISSUE 17): topology pricing, sketch protocol,
roofline pruning goldens, step-op algebra, graph-level soundness of every
opted-in model, verifier fuzz over synthesized projections, solver
enumeration of sketch alternatives, and the directive feature markers.

The acceptance gates:

* **soundness**: every synthesized projection the synthesizer emits over the
  sketch-extended choice graphs passes the independent PR-4 verifier
  (0 false positives), and the original EventSynchronizer oracle agrees;
* **searchability**: MCTS, DFS and hill-climb all visit >= 2 distinct
  sketch alternatives with zero solver changes (synthesized decompositions
  are ordinary ChoiceOp alternatives next to the fixed engine);
* **pruning**: ``bench/roofline.py::prune_sketches`` matches hand-computed
  goldens (alpha-beta wire cost + per-step dispatch vs the fixed floor);
* **numerics** (capability-gated: CI's jax has shard_map/pinned_host):
  pure-movement sketches are bit-identical, synthesized reductions
  allclose, vs the fixed-engine reference on a real mesh.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tenzing_tpu.bench import roofline
from tenzing_tpu.bench.model import AnalyticBenchmarker
from tenzing_tpu.bench.benchmarker import BenchOpts
from tenzing_tpu.collectives.synth import (
    SKETCHES,
    SYNTH_MARK,
    AddInto,
    ConcatPieces,
    PlaceSlice,
    SlicePick,
    StaticSlice,
    SynthDirective,
    plan_host_pipe,
    plan_neighbor_shift,
    plan_rhd_all_reduce,
    plan_ring_all_reduce,
    plan_ring_all_to_all,
    sketch_cost_us,
    sketch_menu,
    synth_hidden_comm_measured_us,
    synth_menu_info,
    synth_menus,
    synths_of,
)
from tenzing_tpu.collectives.topology import (
    Topology,
    host_topology,
    mesh_topology,
    ring_topology,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.state import State
from tenzing_tpu.models.halo import HaloArgs, add_to_graph, dir_name
from tenzing_tpu.models.moe import MoEArgs, MoELayer
from tenzing_tpu.models.spmv import SpMVCompound
from tenzing_tpu.models.tp_mlp import TpMlp, TpMlpArgs, make_tp_mlp_buffers
from tenzing_tpu.verify import ScheduleVerifier
from tests.test_verify import synth_sound

TP = TpMlpArgs(n_tp=4, n_layers=1, n_chunks=1, mb_size=2, d_model=8, d_ff=16)


def _tp_graph(args=TP):
    g = Graph()
    op = TpMlp(args, synth=True, synth_relax=True)
    g.start_then(op)
    g.then_finish(op)
    return g


def _spmv_graph(n_rem=8):
    g = Graph()
    mk = lambda: SpMVCompound(x_sizes={"x_remote": n_rem},
                              exchange="host", synth=True, synth_relax=True)
    g.start_then(mk())
    g.then_finish(mk())
    return g


def _halo_graph(args=None):
    args = args if args is not None else HaloArgs(nq=2, lx=4, ly=4, lz=4,
                                                  radius=1)
    return add_to_graph(Graph(), args, synth=True, synth_relax=True)


def _moe_graph(args=None):
    args = args if args is not None else MoEArgs(
        n_ep=4, tokens_per_shard=8, d_model=8, d_ff=16, n_chunks=2)
    g = Graph()
    op = MoELayer(args, synth=True, synth_relax=True)
    g.start_then(op)
    g.then_finish(op)
    return g


def _drive(g, plat, want_suffix=None):
    """First-decision serialization, preferring choice alternatives whose
    name ends with ``want_suffix`` (the test_chunking discipline)."""
    st = State(g)
    while not st.is_terminal():
        ds = st.get_decisions(plat)
        pick = None
        if want_suffix is not None:
            pick = next(
                (d for d in ds
                 if getattr(d, "choice", None) is not None
                 and d.choice.name().endswith(want_suffix)), None)
        st = st.apply(pick or ds[0])
    return st


# -- topology ---------------------------------------------------------------


class TestTopology:
    def test_ring_links_bidirectional(self):
        t = ring_topology("tp", 4)
        assert len(t.links) == 8  # 4 nodes x 2 directions
        assert t.link("tp0", "tp1").engine == "ici"
        assert t.link("tp3", "tp0") is not None  # wraparound
        assert t.link("tp0", "tp2") is None  # no chord

    def test_link_cost_alpha_beta(self):
        t = ring_topology("tp", 2)
        l = t.link("tp0", "tp1")
        assert l.cost_us(0) == pytest.approx(l.alpha_us)
        assert l.cost_us(1 << 20) > l.cost_us(1 << 10) > l.cost_us(0)

    def test_host_topology_pcie(self):
        t = host_topology()
        assert t.link("d0", "host").engine == "pcie"
        assert t.link("host", "d0").engine == "pcie"

    def test_mesh_topology_merges_axes(self):
        t = mesh_topology({"x": 2, "y": 2}, host=False)
        assert t.link("x0", "x1") is not None
        assert t.link("y0", "y1") is not None
        assert "pcie" not in t.engines()
        th = mesh_topology({"x": 2}, host=True)
        assert "pcie" in th.engines()

    def test_min_hops_on_ring(self):
        t = ring_topology("tp", 4)
        assert t.min_hops("tp0", "tp1") == 1
        assert t.min_hops("tp0", "tp2") == 2  # either way around


# -- protocol / serdes ------------------------------------------------------


class TestProtocol:
    def test_marker_literals_agree_with_featurizer(self):
        """learn/features.py duplicates the marker + sketch vocabulary to
        stay import-light; the literals must never drift."""
        from tenzing_tpu.learn.features import _SYNTH_MARK, _SYNTH_SKETCHES

        assert _SYNTH_MARK == SYNTH_MARK
        assert _SYNTH_SKETCHES == SKETCHES

    def test_directive_name_and_roundtrip(self):
        d = SynthDirective("psum_0_0", "ring", 2)
        assert d.name() == "psum_0_0.synth.ring.c2"
        j = d.to_json()
        d2 = SynthDirective.from_json(j)
        assert (d2.base(), d2.sketch(), d2.chunks()) == ("psum_0_0", "ring", 2)

    def test_directive_rejects_unknown_sketch(self):
        with pytest.raises(ValueError, match="sketch"):
            SynthDirective("a", "butterfly", 2)

    def test_synths_of_parses_ops_and_strings(self):
        d = SynthDirective("x_exchange", "pipe", 4)
        got = synths_of([d, "psum_0_0.synth.ring.c2", "mlp_0_0", "start"])
        assert got == {"x_exchange": {"sketch": "pipe", "chunks": 4},
                       "psum_0_0": {"sketch": "ring", "chunks": 2}}

    def test_synths_of_ignores_malformed(self):
        assert synths_of(["a.synth.ring", "a.synth.butterfly.c2",
                          "a.synth.ring.cX"]) == {}

    def test_menu_info_leads_with_fixed_and_note_nonempty(self):
        m = synth_menu_info("b", "all_reduce", ["ring.c1"], {"ring.c1": 2.0},
                            {}, 5.0, "")
        assert m["menu"][0] == "fixed"
        assert m["note"]  # never empty — the perf.synth contract
        empty = sketch_menu([], host_topology(), fixed_bytes=0.0)[1]
        assert empty["note"]

    def test_sketch_menu_relax_keeps_all_and_explains(self):
        plans = [plan_ring_all_reduce("b", "s", "d", "tp", 4, (2, 8), k)
                 for k in (1, 2)]
        topo = mesh_topology({"tp": 4}, host=False)
        variants, menu = sketch_menu(plans, topo, fixed_bytes=128.0,
                                     relax=True, collective="all_reduce")
        assert len(variants) == 2
        assert menu["menu"] == ["fixed", "ring.c1", "ring.c2"]
        assert "relax" in menu["note"]
        assert set(menu["est_us"]) == {"ring.c1", "ring.c2"}

    def test_sketch_cost_prices_every_hop(self):
        p1 = plan_ring_all_reduce("b", "s", "d", "tp", 4, (2, 8), 1)
        p2 = plan_ring_all_reduce("b", "s", "d", "tp", 4, (2, 8), 2)
        topo = mesh_topology({"tp": 4}, host=False)
        # same total bytes, same hop count per chunk -> same wire cost
        # modulo per-transfer alpha (c2 posts twice as many transfers)
        assert sketch_cost_us(p2, topo) > 0
        assert p2.n_xfers == 2 * p1.n_xfers
        assert sketch_cost_us(p2, topo) > sketch_cost_us(p1, topo)


# -- roofline pruning goldens -----------------------------------------------


class TestPruneSketches:
    def test_keeps_only_below_floor(self):
        cands = {"ring.c1": {"est_us": 10.0, "steps": 1, "chunks": 1},
                 "rhd.c1": {"est_us": 40.0, "steps": 1, "chunks": 1}}
        kept, pruned = roofline.prune_sketches(cands, fixed_floor_us=20.0,
                                               dispatch_us=0.0)
        assert kept == ["ring.c1"]
        assert "rhd.c1" in pruned and "floor" in pruned["rhd.c1"]

    def test_extra_posts_pay_dispatch(self):
        # 3 steps at 25us dispatch each adds 50us over the fixed one-post
        cands = {"ring.c1": {"est_us": 10.0, "steps": 3, "chunks": 1}}
        kept, pruned = roofline.prune_sketches(cands, fixed_floor_us=20.0,
                                               dispatch_us=25.0)
        assert not kept and "dispatch" in pruned["ring.c1"]

    def test_overlap_credit_capped_by_head_chunk(self):
        # a k-chunk pipeline hides at most est*(k-1)/k, not all of it
        cands = {"pipe.c2": {"est_us": 30.0, "steps": 1, "chunks": 2}}
        kept, _ = roofline.prune_sketches(cands, fixed_floor_us=16.0,
                                          overlap_us=1e9, dispatch_us=0.0)
        assert kept == ["pipe.c2"]  # eff = 30 - 15 = 15 < 16
        kept2, _ = roofline.prune_sketches(cands, fixed_floor_us=14.0,
                                           overlap_us=1e9, dispatch_us=0.0)
        assert not kept2


# -- step-op algebra (single device, no mesh) -------------------------------


class TestStepOps:
    def _apply(self, op, bufs):
        out = dict(bufs)
        out.update(op.apply({k: jnp.asarray(v) for k, v in out.items()},
                            None))
        return {k: np.asarray(v) for k, v in out.items()}

    def test_slice_pick_place_roundtrip(self):
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        bufs = {"x": x, "piece": np.zeros((2, 3), np.float32),
                "y": np.zeros((4, 3), np.float32)}
        for j in range(2):
            bufs = self._apply(SlicePick(f"p{j}", "x", "piece", j, 2), bufs)
            bufs = self._apply(PlaceSlice(f"q{j}", "piece", "y", j, 2), bufs)
        np.testing.assert_array_equal(bufs["y"], x)

    def test_slice_pick_rejects_uneven_runtime_rows(self):
        op = SlicePick("p", "x", "d", 0, 3)
        with pytest.raises(ValueError, match="split"):
            op.apply({"x": jnp.zeros((4, 2))}, None)

    def test_add_into_accumulates(self):
        bufs = {"acc": np.ones((2, 2), np.float32),
                "p": np.full((2, 2), 2.0, np.float32)}
        bufs = self._apply(AddInto("a", "p", "acc"), bufs)
        np.testing.assert_array_equal(bufs["acc"], np.full((2, 2), 3.0))

    def test_static_slice_concat_roundtrip_uneven(self):
        x = np.arange(7, dtype=np.float32)
        bufs = {"x": x, "a": np.zeros(4, np.float32),
                "b": np.zeros(3, np.float32), "y": np.zeros(7, np.float32)}
        bufs = self._apply(StaticSlice("s0", "x", "a", 0, 4), bufs)
        bufs = self._apply(StaticSlice("s1", "x", "b", 4, 3), bufs)
        bufs = self._apply(ConcatPieces("c", ["a", "b"], "y"), bufs)
        np.testing.assert_array_equal(bufs["y"], x)


# -- plan census ------------------------------------------------------------


class TestPlans:
    def test_ring_all_reduce_census(self):
        p = plan_ring_all_reduce("b", "s", "d", "tp", 4, (4, 8), 2)
        assert p.label() == "ring.c2"
        assert p.n_xfers == 2 * 3  # k chunks x (n-1) hops
        assert len(p.chains) == 2
        names = [d.name for d in p.buffers]
        assert len(names) == len(set(names))  # no staging-name collisions

    def test_reverse_ring_is_distinct_sketch(self):
        p = plan_ring_all_reduce("b", "s", "d", "tp", 4, (4, 8), 1,
                                 reverse=True)
        assert p.sketch == "ringr"

    def test_rhd_requires_power_of_two(self):
        with pytest.raises(ValueError, match="power"):
            plan_rhd_all_reduce("b", "s", "d", "tp", 3, (4, 8))
        p = plan_rhd_all_reduce("b", "s", "d", "tp", 8, (4, 8))
        assert p.n_xfers == 3  # log2(8) hops

    def test_a2a_ring_requires_extent(self):
        with pytest.raises(ValueError, match=">= 2"):
            plan_ring_all_to_all("b", "s", "d", "ep", 1, (4, 8))

    def test_host_pipe_declares_host_space(self):
        p = plan_host_pipe("b", "s", "d", 8, 2)
        assert p.engine == "pcie"
        assert any(d.space == "host" for d in p.buffers)


# -- graph-level soundness of every opted-in model --------------------------


class TestGraphLevel:
    """Drive each model's sketch-extended graph to both a synthesized and a
    fixed-engine projection; the independent verifier and the original
    oracle must certify both, and the directive must be readable back."""

    def _check(self, g, want_suffix, expect_sites, sketch):
        plat = Platform.make_n_lanes(1)
        st = _drive(g, plat, want_suffix)
        chosen = synths_of(st.sequence)
        assert len(chosen) == expect_sites, chosen
        assert all(v["sketch"] == sketch for v in chosen.values()), chosen
        stf = _drive(g, plat, ".fixed")
        assert synths_of(stf.sequence) == {}
        for s in (st, stf):
            v = ScheduleVerifier(s.graph)(s.sequence)
            assert v.ok, f"false positive: {v.witness()}"
            assert synth_sound(s.graph, s.sequence)
        return st

    def test_spmv_pipe(self):
        g = _spmv_graph(n_rem=8)
        menus = synth_menus(g)
        assert set(menus) == {"x_exchange"}
        assert menus["x_exchange"]["menu"] == ["fixed", "pipe.c2", "pipe.c4"]
        self._check(g, "pipe.c2", 1, "pipe")

    def test_tp_mlp_all_reduce_menu(self):
        g = _tp_graph()
        menus = synth_menus(g)
        assert set(menus) == {"psum_0_0"}
        assert menus["psum_0_0"]["menu"] == [
            "fixed", "ring.c1", "ring.c2", "ringr.c1", "rhd.c1"]
        self._check(g, "rhd.c1", 1, "rhd")
        self._check(g, "ring.c2", 1, "ring")

    def test_halo_neighbor_all_faces(self):
        g = _halo_graph()
        menus = synth_menus(g)
        assert len(menus) == 6  # one exchange site per face direction
        for m in menus.values():
            assert m["menu"] == ["fixed", "neighbor.c1", "neighbor.c2"]
        self._check(g, "neighbor.c2", 6, "neighbor")

    def test_moe_a2a_both_sites(self):
        g = _moe_graph()
        menus = synth_menus(g)
        assert set(menus) == {"a2a_disp_0", "a2a_comb_0",
                              "a2a_disp_1", "a2a_comb_1"}
        for m in menus.values():
            assert m["menu"] == ["fixed", "ring.c1"]
        self._check(g, "ring.c1", 4, "ring")


# -- verifier fuzz over synthesized projections -----------------------------


class TestVerifierFuzz:
    @pytest.mark.parametrize("mk_graph,label", [(_spmv_graph, "spmv"),
                                                (_halo_graph, "halo")])
    def test_randomized_synth_rollouts_verify_clean(self, mk_graph, label):
        """Randomized sketch/chunk rollouts (biased toward synthesized
        alternatives so the fuzz actually exercises them): 0 false
        positives from the independent verifier, and the original
        EventSynchronizer oracle agrees on every projection."""
        g = mk_graph()
        ver = ScheduleVerifier(g)
        rng = random.Random(17)
        n_synthed = 0
        for _ in range(10):
            st = State(g)
            while not st.is_terminal():
                ds = st.get_decisions(Platform.make_n_lanes(2))
                pick = next(
                    (d for d in ds
                     if getattr(d, "choice", None) is not None
                     and ".synthed." in d.choice.name()
                     and rng.random() < 0.7), None)
                st = st.apply(pick or ds[rng.randrange(len(ds))])
            v = ver(st.sequence)
            assert v.ok, f"false positive: {v.witness()}"
            assert synth_sound(st.graph, st.sequence)
            n_synthed += bool(synths_of(st.sequence))
        assert ver.unsound == 0
        assert n_synthed >= 3, f"{label} fuzz barely hit synth projections"


# -- solver searchability (analytic model, no device) -----------------------


class TestSolversSearchSketches:
    """Synthesized decompositions are ordinary choice decisions: all three
    solvers visit >= 2 distinct sketch alternatives (the fixed engine
    counts as one) with zero solver changes, scored by the analytic model
    so the test needs no mesh."""

    def _bench(self):
        bufs, _, _ = make_tp_mlp_buffers(TP, seed=0, synth=True)
        return AnalyticBenchmarker({k: v.nbytes for k, v in bufs.items()})

    def _seen(self, sims):
        seen = set()
        for s in sims:
            labels = {f"{v['sketch']}.c{v['chunks']}"
                      for v in synths_of(s.order).values()}
            seen.update(labels or {"fixed"})
        return seen

    def test_dfs_enumerates_sketches(self):
        from tenzing_tpu.solve.dfs import DfsOpts, explore

        res = explore(
            _tp_graph(), Platform.make_n_lanes(1), self._bench(),
            DfsOpts(max_seqs=24, dump_csv_path="/dev/null",
                    bench_opts=BenchOpts(n_iters=1, target_secs=0.0)))
        seen = self._seen(res.sims)
        assert "fixed" in seen and len(seen) >= 2, seen

    def test_hill_climb_searches_sketches(self):
        from tenzing_tpu.solve.local import LocalOpts, hill_climb

        def prefer(op_name, choices):
            # seed fixed-engine; flip moves must explore the sketch menu
            return next((c for c in choices if c.endswith(".fixed")), None)

        res = hill_climb(
            _tp_graph(), Platform.make_n_lanes(1), self._bench(),
            phases=("mlp",), prefer=prefer,
            opts=LocalOpts(budget=8, seed=0,
                           bench_opts=BenchOpts(n_iters=1, target_secs=0.0)))
        assert res.sims
        seen = self._seen(res.sims)
        assert len(seen) >= 2, seen

    def test_mcts_searches_sketches(self):
        from tenzing_tpu.solve.mcts import MctsOpts, explore

        res = explore(
            _tp_graph(), Platform.make_n_lanes(1), self._bench(),
            MctsOpts(n_iters=16, seed=3,
                     bench_opts=BenchOpts(n_iters=1, target_secs=0.0),
                     screen_opts=BenchOpts(n_iters=1, target_secs=0.0)))
        seen = self._seen(res.sims)
        assert len(seen) >= 2, seen


# -- feature markers --------------------------------------------------------


class TestFeatureMarkers:
    def test_synth_directives_counted(self):
        from tenzing_tpu.learn.features import FEATURE_NAMES, featurize

        seq = Sequence([SynthDirective("a", "ring", 2),
                        SynthDirective("b", "pipe", 4),
                        SynthDirective("c", "ring", 1)])
        v = dict(zip(FEATURE_NAMES, featurize(seq)))
        assert v["n_synth_dir"] == 3.0
        assert v["n_synth_ring"] == 2.0
        assert v["n_synth_pipe"] == 1.0
        assert v["n_synth_neighbor"] == 0.0
        assert v["sum_synth_chunks"] == 7.0

    def test_step_names_do_not_count_as_directives(self):
        """A p2p step (``b.ring2.x0.p0``) is not a directive: only the
        ``<base>.synth.<sketch>.cK`` op carries the feature unit."""
        from tenzing_tpu.learn.features import FEATURE_NAMES, featurize

        plan = plan_ring_all_reduce("b", "s", "d", "tp", 2, (2, 4), 2)
        names = [op for chain in plan.chains for op in chain]
        v = dict(zip(FEATURE_NAMES, featurize(Sequence(names))))
        assert v["n_synth_dir"] == 0.0

    def test_save_load_contract_rejects_pre_synth_model(self, tmp_path):
        """A model saved under the pre-synth-append name list fails the
        load contract loudly instead of silently mis-predicting."""
        from tenzing_tpu.learn import RidgeEnsemble
        from tenzing_tpu.learn.features import FEATURE_NAMES

        rng = np.random.default_rng(0)
        old_names = list(FEATURE_NAMES[:-7])
        X = rng.random((8, len(old_names)))
        old = RidgeEnsemble(feature_names=old_names).fit(X, rng.random(8))
        path = str(tmp_path / "pre_synth.json")
        old.save(path)
        with pytest.raises(ValueError, match="contract"):
            RidgeEnsemble.load(path, expect_features=list(FEATURE_NAMES))


# -- measured hidden comm ---------------------------------------------------


class _FakeOp:
    def __init__(self, name, kind=""):
        self._name, self.KIND = name, kind

    def name(self):
        return self._name


class _FakeTimeline:
    def __init__(self, records):
        self.records = records


class _FakeAttrib:
    def __init__(self, records):
        self.timeline = _FakeTimeline(records)


class TestHiddenCommMeasured:
    def test_overlap_interval_sum(self):
        from tenzing_tpu.obs.attrib.timeline import OpRecord

        ops = [_FakeOp("ex.synth.neighbor.c2"),
               _FakeOp("ex.neighbor2.x0.p", kind="permute_start"),
               _FakeOp("compute_a"),
               _FakeOp("ex.neighbor2.x1.p", kind="permute_start"),
               _FakeOp("compute_b")]
        recs = [
            OpRecord("ex.neighbor2.x0.p", "", "device", 0, (1,),
                     dur_us=10.0, start_us=0.0),
            OpRecord("compute_a", "", "device", 1, (2,),
                     dur_us=10.0, start_us=5.0),  # 5us under x0.p
            OpRecord("ex.neighbor2.x1.p", "", "device", 0, (3,),
                     dur_us=4.0, start_us=15.0),
            OpRecord("compute_b", "", "device", 1, (4,),
                     dur_us=2.0, start_us=16.0),  # fully under x1.p
        ]
        got = synth_hidden_comm_measured_us(ops, _FakeAttrib(recs))
        assert got == pytest.approx(7.0)

    def test_zero_without_chosen_synth(self):
        assert synth_hidden_comm_measured_us(
            [_FakeOp("compute_a")], _FakeAttrib([])) == 0.0


# -- executed numerics (capability-gated: run in CI's capable jax) ----------


@pytest.mark.needs_shard_map
class TestExecutedNumerics:
    def test_tp_mlp_synth_matches_fixed_psum(self):
        """Every sketch the tp all-reduce menu offers must agree with the
        host reference: pure movement is exact, re-associated reductions
        allclose (the driver's integrity-gate tolerance discipline)."""
        from jax.sharding import Mesh
        from tenzing_tpu.runtime.executor import TraceExecutor

        bufs, specs, want = make_tp_mlp_buffers(TP, seed=1, synth=True)
        devs = np.array(jax.devices()[:TP.n_tp])
        plat = Platform.make_n_lanes(2, mesh=Mesh(devs, ("tp",)),
                                     specs=specs)
        ex = TraceExecutor(plat,
                           {k: jnp.asarray(v) for k, v in bufs.items()})
        g = _tp_graph()
        for suffix in (".fixed", "ring.c1", "ring.c2", "ringr.c1", "rhd.c1"):
            st = _drive(g, plat, suffix)
            out = ex.run(st.sequence)
            np.testing.assert_allclose(np.asarray(out["Y"]), want,
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=f"sketch {suffix}")

    def test_moe_synth_a2a_bit_identical(self):
        """The ring all-to-all is pure movement: synthesized routing must
        reproduce the fused ``lax.all_to_all`` output exactly."""
        from jax.sharding import Mesh
        from tenzing_tpu.models.moe import make_moe_buffers
        from tenzing_tpu.runtime.executor import TraceExecutor

        args = MoEArgs(n_ep=4, tokens_per_shard=8, d_model=8, d_ff=16,
                       n_chunks=2)
        bufs, specs, want = make_moe_buffers(args, seed=0, synth=True)
        devs = np.array(jax.devices()[:args.n_ep])
        plat = Platform.make_n_lanes(2, mesh=Mesh(devs, ("ep",)),
                                     specs=specs)
        ex = TraceExecutor(plat,
                           {k: jnp.asarray(v) for k, v in bufs.items()})
        g = _moe_graph(args)
        out_fixed = ex.run(_drive(g, plat, ".fixed").sequence)
        out_ring = ex.run(_drive(g, plat, "ring.c1").sequence)
        np.testing.assert_allclose(np.asarray(out_ring["Y"]), want,
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(out_ring["Y"]),
                                      np.asarray(out_fixed["Y"]))

    def test_halo_synth_shift_bit_identical(self):
        """Chunked neighbor-exchange is pure movement: every face arrives
        bit-identical to the fused shift."""
        from jax.sharding import Mesh
        from tenzing_tpu.models.halo import make_halo_buffers
        from tenzing_tpu.runtime.executor import TraceExecutor

        args = HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1)
        mesh_shape = (2, 2, 2)
        bufs, specs, want = make_halo_buffers(mesh_shape, args, seed=0,
                                              synth=True)
        devs = np.array(jax.devices()[:8]).reshape(mesh_shape)
        plat = Platform.make_n_lanes(2, mesh=Mesh(devs, ("x", "y", "z")),
                                     specs=specs)
        ex = TraceExecutor(plat,
                           {k: jnp.asarray(v) for k, v in bufs.items()})
        g = _halo_graph(args)
        out = ex.run(_drive(g, plat, "neighbor.c2").sequence)
        np.testing.assert_allclose(np.asarray(out["U"]), want, rtol=1e-6)
