"""Pallas kernel tests (interpret mode — runs anywhere; device execution of the
same kernels is exercised by the TPU bench) and the implementation-ChoiceOp
search path (reference ChoiceOp menu, operation.hpp:90-93 / state.cpp:61-65)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")


def _band(m, bw, nnz, seed=0):
    from tenzing_tpu.models.spmv import random_band_matrix

    return random_band_matrix(m, bw, nnz, seed=seed)


class TestEllSpmvPallas:
    def test_matches_reference_matvec(self):
        from tenzing_tpu.ops import ell_spmv_pallas

        a = _band(300, 40, 3000, seed=1)
        v, c = a.to_slab()
        x = np.random.default_rng(0).random(a.n, dtype=np.float32)
        got = ell_spmv_pallas(jnp.asarray(v), jnp.asarray(c), jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), a.matvec(x), rtol=2e-3)

    def test_wide_slab_and_row_padding(self):
        # slab wider than one vreg (w > 128) and m not a block multiple
        from tenzing_tpu.ops import ell_spmv_pallas

        a = _band(67, 300, 67 * 150, seed=2)
        v, c = a.to_slab()
        assert v.shape[1] > 128
        x = np.random.default_rng(1).random(a.n, dtype=np.float32)
        got = ell_spmv_pallas(jnp.asarray(v), jnp.asarray(c), jnp.asarray(x), block_m=32)
        np.testing.assert_allclose(np.asarray(got), a.matvec(x), rtol=2e-3)

    def test_supports_gate(self):
        from tenzing_tpu.ops.spmv_pallas import LANES, MAX_X_BLOCKS, supports

        assert supports(LANES * MAX_X_BLOCKS)
        assert not supports(LANES * MAX_X_BLOCKS + 1)

    def test_pallas_op_fallback_large_x(self):
        # SpMVPallasOp guards on supports(): huge x silently takes the XLA path
        from tenzing_tpu.models.spmv import SpMVPallasOp
        from tenzing_tpu.ops.spmv_pallas import LANES, MAX_X_BLOCKS

        n = LANES * MAX_X_BLOCKS + LANES
        rng = np.random.default_rng(0)
        bufs = {
            "x": jnp.asarray(rng.random(n, dtype=np.float32)),
            "vals": jnp.asarray(rng.random((16, 3), dtype=np.float32)),
            "cols": jnp.asarray(rng.integers(0, n, size=(16, 3)), jnp.int32),
            "y": jnp.zeros(16, jnp.float32),
        }
        out = SpMVPallasOp("k", "x", "y", "vals", "cols").apply(bufs, None)
        want = np.sum(np.asarray(bufs["vals"]) * np.asarray(bufs["x"])[np.asarray(bufs["cols"])], axis=1)
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=1e-5)


class TestImplChoiceSearch:
    """The kernel menu is part of the searched space: a ChooseOp decision per
    implementation, and every completed schedule computes the right answer."""

    def _graph(self):
        from tenzing_tpu.core.graph import Graph
        from tenzing_tpu.models.spmv import SpMVCompound

        g = Graph()
        g.start_then(SpMVCompound(impl_choice=True))
        g.then_finish(SpMVCompound(impl_choice=True))
        return g

    def test_choice_decisions_enumerated(self):
        from tenzing_tpu.core.platform import Platform
        from tenzing_tpu.core.state import ChooseOp, ExpandOp, State

        plat = Platform.make_n_lanes(1)
        s = State(self._graph())
        (d,) = s.get_decisions(plat)
        assert isinstance(d, ExpandOp)
        s = s.apply(d)
        chooses = [d for d in s.get_decisions(plat) if isinstance(d, ChooseOp)]
        # spmv_local offers both kernels at the initial frontier
        descs = {d.choice.name() for d in chooses}
        assert "spmv_local.xla" in descs and "spmv_local.pallas" in descs

    def test_both_impls_compute_correctly(self):
        from tenzing_tpu.core.platform import Platform
        from tenzing_tpu.models.spmv import make_spmv_buffers
        from tenzing_tpu.runtime.executor import TraceExecutor
        from tenzing_tpu.solve.dfs import get_all_sequences

        bufs, want = make_spmv_buffers(m=96, nnz_per_row=4, bw=12, seed=3)
        plat = Platform.make_n_lanes(1)
        seqs = get_all_sequences(self._graph(), plat, max_seqs=40)
        names = [";".join(op.name() for op in s.sequence) for s in seqs]
        pallas_scheds = [
            s for s, n in zip(seqs, names) if ".pallas" in n
        ]
        xla_scheds = [s for s, n in zip(seqs, names) if ".pallas" not in n]
        assert pallas_scheds and xla_scheds
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        for sched in (pallas_scheds[0], xla_scheds[0]):
            out = ex.run(sched.sequence)
            np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=2e-3)


class TestFfnPallas:
    def test_single_matches_xla(self):
        import jax

        from tenzing_tpu.ops.ffn_pallas import ffn_pallas

        rng = np.random.default_rng(0)
        x = rng.standard_normal((37, 8)).astype(np.float32)  # ragged rows
        w1 = rng.standard_normal((8, 16)).astype(np.float32)
        w2 = rng.standard_normal((16, 8)).astype(np.float32)
        want = jax.nn.gelu(x @ w1) @ w2
        got = ffn_pallas(jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2),
                         interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_batched_tiles_hidden_dim(self):
        """Ragged rows AND a hidden dim that is not a multiple of the tile:
        the zero-padded hidden tiles must contribute exactly 0."""
        import jax

        from tenzing_tpu.ops.ffn_pallas import ffn_pallas_batched

        rng = np.random.default_rng(1)
        # dff=520 > the 512 hidden tile: two k-tiles, the second zero-padded
        # by 504 — exercises both the in-place accumulation and the padding
        e, c, d, dff = 2, 11, 8, 520
        x = rng.standard_normal((e, c, d)).astype(np.float32)
        w1 = rng.standard_normal((e, d, dff)).astype(np.float32)
        w2 = rng.standard_normal((e, dff, d)).astype(np.float32)
        want = np.stack([
            np.asarray(jax.nn.gelu(x[i] @ w1[i]) @ w2[i]) for i in range(e)
        ])
        got = ffn_pallas_batched(jnp.asarray(x), jnp.asarray(w1),
                                 jnp.asarray(w2), interpret=True)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)
