"""T3-style op chunking (ISSUE 10): protocol, roofline pruning goldens,
chunked-vs-unchunked numerics, verifier fuzz over chunked projections,
solver enumeration of chunk counts, and the directive feature markers.

The acceptance gates:

* **soundness**: every chunked schedule the synthesizer emits over the
  chunk-extended choice graphs passes the independent PR-4 verifier
  (0 false positives), and the original EventSynchronizer oracle agrees;
* **numerics**: ``chunks=1`` is the op itself (bit-identical by
  construction); ``chunks>1`` re-associates the accumulation across chunk
  boundaries and must be allclose to the unchunked evaluation — for the
  naive serialization AND randomized 2-lane schedules;
* **searchability**: MCTS, DFS and hill-climb all visit >= 2 distinct
  chunk counts with zero solver changes (chunked expansions are ordinary
  ChoiceOp alternatives);
* **pruning**: ``bench/roofline.py::prune_chunkings`` matches hand-computed
  goldens (traffic floor, dispatch+combine cost vs the hidden-comm bound).
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tenzing_tpu.bench import roofline
from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
from tenzing_tpu.core.chunking import (
    CHUNK_MARK,
    ChunkChoice,
    ChunkDirective,
    ChunkedOp,
    chunk_menus,
    chunk_variants,
    chunks_of,
    menu_info,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.state import State
from tenzing_tpu.models.ring_attention import (
    BlockAttnStep,
    BlockedAttention,
    RingAttnArgs,
    fold_chunk_menu,
    make_blocked_buffers,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import enumerate_schedules
from tenzing_tpu.verify import ScheduleVerifier

ATTN = RingAttnArgs(n_devices=2, batch=1, seq_local=8, head_dim=4)


def _attn_graph(args=ATTN, impl_choice=False):
    g = Graph()
    op = BlockedAttention(args, impl_choice=impl_choice, chunk=True,
                          chunk_relax=True)
    g.start_then(op)
    g.then_finish(op)
    return g


def _drive(g, plat, want_suffix=None):
    """First-decision serialization, preferring choice alternatives whose
    name ends with ``want_suffix``."""
    st = State(g)
    while not st.is_terminal():
        ds = st.get_decisions(plat)
        pick = None
        if want_suffix is not None:
            pick = next(
                (d for d in ds
                 if getattr(d, "choice", None) is not None
                 and d.choice.name().endswith(want_suffix)), None)
        st = st.apply(pick or ds[0])
    return st.sequence


def _has_chunk(seq) -> bool:
    return bool(chunks_of(seq))


class TestProtocol:
    def test_chunk_counts_always_contain_one(self):
        step = BlockAttnStep("attn_0", 0, ATTN)
        counts = step.chunk_counts()
        assert 1 in counts and counts == sorted(counts)
        assert all(ATTN.seq_local % n == 0 for n in counts)

    def test_split_partials_chain_through_directive(self):
        step = BlockAttnStep("attn_0", 0, ATTN)
        v = ChunkedOp(step, 2)
        g = v.graph()
        names = [op.name() for op in g.vertices()]
        assert f"attn_0{CHUNK_MARK}2" in names
        assert "attn_0.c2p0" in names and "attn_0.c2p1" in names
        # serial chain: directive -> p0 -> p1 (the combine is the
        # accumulating state the partials thread through)
        by = {op.name(): op for op in g.vertices()}
        assert by["attn_0.c2p0"] in g.succs(by[f"attn_0{CHUNK_MARK}2"])
        assert by["attn_0.c2p1"] in g.succs(by["attn_0.c2p0"])

    def test_chunked_op_guards(self):
        step = BlockAttnStep("attn_0", 0, ATTN)
        with pytest.raises(ValueError):
            ChunkedOp(step, 1)  # 1 = the op itself, never an expansion
        from tenzing_tpu.models.ring_attention import BlockAttnStepPallas

        with pytest.raises(ValueError):
            ChunkedOp(BlockAttnStepPallas("attn_0.pallas", 0, ATTN), 2)
        with pytest.raises(ValueError):
            step.split(3)  # 8 columns do not split 3 ways
        # a partial never re-splits
        assert not step.split(2)[0].chunkable()

    def test_chunks_of_parses_directives(self):
        seq = [ChunkDirective("ffn_0.xla", 4), ChunkDirective("attn_1", 2)]
        assert chunks_of(seq) == {"ffn_0.xla": 4, "attn_1": 2}
        assert chunks_of([]) == {}

    def test_directive_serdes_roundtrip(self):
        from tenzing_tpu.core.serdes import (
            sequence_from_json,
            sequence_to_json,
        )

        g = _attn_graph()
        seq = Sequence([ChunkDirective("attn_0", 2)])
        back = sequence_from_json(sequence_to_json(seq), g)
        assert chunks_of(back) == {"attn_0": 2}

    def test_chunked_schedule_serdes_roundtrip(self):
        """An executed chunked schedule (directive + partials) re-anchors
        against the choice graph: partials resolve by name through the
        ChunkedOp alternative's sub-graph."""
        from tenzing_tpu.core.serdes import (
            sequence_from_json,
            sequence_to_json,
        )

        g = _attn_graph()
        plat = Platform.make_n_lanes(2)
        seq = _drive(g, plat, want_suffix=".chunked.c2")
        assert _has_chunk(seq)
        back = sequence_from_json(sequence_to_json(seq), g)
        assert [op.name() for op in back] == [op.name() for op in seq]
        assert chunks_of(back) == chunks_of(seq)

    def test_chunk_menus_collects_choice_metadata(self):
        menus = chunk_menus(_attn_graph())
        assert set(menus) == {f"attn_{s}" for s in range(ATTN.n_devices)}
        for m in menus.values():
            assert m["counts"] == [1, 2, 4]
        # kernel-menu variant (impl_choice) keys on the wrapped .xla name
        menus = chunk_menus(_attn_graph(impl_choice=True))
        assert set(menus) == {f"attn_{s}.xla" for s in range(ATTN.n_devices)}

    def test_menu_info_normalizes(self):
        m = menu_info("x", [4, 2, 2], {2: 10.0, 4: None})
        assert m["counts"] == [1, 2, 4]  # 1 injected, dedup, sorted
        assert m["est_hidden_us"] == {2: 10.0}  # None estimates dropped

    def test_chunk_variants_skips_degenerate_counts(self):
        step = BlockAttnStep("attn_0", 0, ATTN)
        vs = chunk_variants(step, [1, 2, 2, 4])
        assert [v.chunks() for v in vs] == [2, 4]

    def test_marker_strings_agree_across_modules(self):
        """learn/features.py duplicates the directive markers to stay
        import-light; the literals must agree or the surrogate silently
        zeroes chunked schedules."""
        from tenzing_tpu.learn import features
        from tenzing_tpu.runtime.fused import TILE_PREFIX

        assert features._CHUNK_MARK == CHUNK_MARK
        assert features._TILE_PREFIX == TILE_PREFIX


class TestPruneChunkings:
    def test_traffic_floor_golden(self):
        # 8 MiB of traffic, no comm model: n=2 leaves 4 MiB/chunk (fine at
        # the 1 MiB floor), n=16 leaves 0.5 MiB (all prologue: dropped)
        c = roofline.Cost(flops=0.0, hbm_bytes=8 * 2**20)
        assert roofline.prune_chunkings(c, [1, 2, 16]) == [1, 2]
        # 1 always survives, even alone
        assert roofline.prune_chunkings(
            roofline.Cost(0.0, 10.0), [1, 2, 4]) == [1]

    def test_hidden_comm_bound_golden(self):
        # an op whose analytic floor is exactly 1000 us
        c = roofline.Cost(flops=roofline.V5E_PEAK_BF16_FLOPS * 1e-3,
                          hbm_bytes=8 * 2**20)
        assert roofline.op_roofline_us(c) == pytest.approx(1000.0)
        assert roofline.hidden_comm_bound_us(c, 1, 500.0) == 0.0
        # n=2 exposes the tail half: min(comm, 500)
        assert roofline.hidden_comm_bound_us(c, 2, 300.0) == \
            pytest.approx(300.0)
        assert roofline.hidden_comm_bound_us(c, 2, 800.0) == \
            pytest.approx(500.0)
        # n=4 exposes 3/4 of the op
        assert roofline.hidden_comm_bound_us(c, 4, 1e9) == \
            pytest.approx(750.0)

    def test_comm_rule_golden(self):
        c = roofline.Cost(flops=roofline.V5E_PEAK_BF16_FLOPS * 1e-3,
                          hbm_bytes=8 * 2**20)
        # n=2 hides up to 500 us for one extra dispatch (25 us): survives
        assert roofline.prune_chunkings(c, [1, 2], comm_us=500.0) == [1, 2]
        # only 10 us of comm exists — under the dispatch floor: dropped
        assert roofline.prune_chunkings(c, [1, 2], comm_us=10.0) == [1]
        # a combine pass costing ~1000 us/partial swamps the 500 us bound
        combine = roofline.V5E_PEAK_HBM_BYTES * 1e-3
        assert roofline.prune_chunkings(
            c, [1, 2], comm_us=500.0, combine_bytes=combine) == [1]
        # no comm to hide prunes every n > 1 (the honest single-chip attn
        # answer fold_chunk_menu reports un-relaxed)
        assert roofline.prune_chunkings(c, [1, 2, 4], comm_us=0.0) == [1]

    def test_model_menus_relaxed_and_pruned(self):
        counts, est = fold_chunk_menu(ATTN, relax=True)
        assert counts == [1, 2, 4] and est == {}
        # full-size blocked attn has no neighboring transfer: all pruned
        counts, _ = fold_chunk_menu(
            RingAttnArgs(n_devices=8, batch=4, seq_local=1024, head_dim=128))
        assert counts == [1]
        # MoE pipe full-size: the combine-side DMA is real comm — the
        # roofline keeps at least one n>1 and prices its hidden bound
        from tenzing_tpu.models.moe_pipeline import (
            MoEPipeArgs,
            ffn_chunk_menu,
        )

        counts, est = ffn_chunk_menu(MoEPipeArgs(tokens=8192), cap=4096)
        assert any(n > 1 for n in counts)
        assert all(est[n] > 0 for n in counts if n > 1)


class TestChunkedNumerics:
    def test_naive_chunked_matches_unchunked_per_count(self):
        bufs, want = make_blocked_buffers(ATTN, seed=3)
        plat = Platform.make_n_lanes(1)
        g = _attn_graph()
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        for n in (2, 4):
            seq = _drive(g, plat, want_suffix=f".chunked.c{n}")
            assert set(chunks_of(seq).values()) == {n}
            out = ex.run(seq)
            np.testing.assert_allclose(np.asarray(out["O"]), want,
                                       rtol=2e-4, atol=2e-5)

    def test_chunks_one_is_bit_identical(self):
        """The unchunked menu entry IS the original op: resolving the
        chunk choice to it produces the same program as the chunk-free
        graph, bit for bit."""
        bufs, _ = make_blocked_buffers(ATTN, seed=4)
        plat = Platform.make_n_lanes(1)
        jb = {k: jnp.asarray(v) for k, v in bufs.items()}
        ex = TraceExecutor(plat, jb)
        plain = Graph()
        op = BlockedAttention(ATTN)
        plain.start_then(op)
        plain.then_finish(op)
        out_plain = ex.run(_drive(plain, plat))
        seq1 = _drive(_attn_graph(), plat)  # first choice = the op itself
        assert not _has_chunk(seq1)
        out_c1 = TraceExecutor(plat, jb).run(seq1)
        assert np.array_equal(np.asarray(out_plain["O"]),
                              np.asarray(out_c1["O"]))

    def test_randomized_two_lane_chunked_schedules_match(self):
        bufs, want = make_blocked_buffers(ATTN, seed=5)
        plat = Platform.make_n_lanes(2)
        g = _attn_graph()
        seqs = [s.sequence for s in enumerate_schedules(g, plat,
                                                        max_seqs=64)]
        chunked = [s for s in seqs if _has_chunk(s)]
        assert len(chunked) >= 2  # the space genuinely contains them
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        for s in chunked[:3]:
            out = ex.run(s)
            np.testing.assert_allclose(np.asarray(out["O"]), want,
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.needs_pinned_host
    def test_moe_pipe_chunked_matches_dense_routing(self):
        from tenzing_tpu.models.moe_pipeline import (
            MoEPipeArgs,
            build_graph,
            host_buffer_names,
            make_pipe_buffers,
        )

        margs = MoEPipeArgs(n_experts=4, tokens=32, d_model=8, d_ff=16,
                            n_chunks=2)
        bufs, want, cap = make_pipe_buffers(margs, seed=1)
        g = build_graph(margs, cap, chunk=True, chunk_relax=True)
        plat = Platform.make_n_lanes(2)
        jbufs = TraceExecutor.place_host_buffers(
            bufs, host_buffer_names(margs))
        ex = TraceExecutor(plat, jbufs)
        seq = _drive(g, plat, want_suffix=".chunked.c2")
        assert _has_chunk(seq)
        out = ex.run(seq)
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-3,
                                   atol=2e-5)


class TestPartialFolds:
    """Direct-apply equality: the n partials' accumulating updates fold to
    the whole op's output on plain arrays (the multichip models' split
    protocol, testable without a mesh)."""

    def test_moe_expert_ffn_fold(self):
        from tenzing_tpu.models.moe import ExpertFFN, MoEArgs

        ma = MoEArgs(n_ep=4, tokens_per_shard=16, d_model=8, d_ff=16)
        rng = np.random.default_rng(0)
        bufs = {
            "recv_disp_0": jnp.asarray(
                rng.standard_normal((4, 4, 8)), jnp.float32),
            "W1": jnp.asarray(rng.standard_normal((1, 8, 16)), jnp.float32),
            "W2": jnp.asarray(rng.standard_normal((1, 16, 8)), jnp.float32),
            "ffn_out_0": jnp.zeros((4, 4, 8), jnp.float32),
        }
        op = ExpertFFN("ffn_0", 0, ma)
        want = op.apply(dict(bufs), None)["ffn_out_0"]
        for n in (2, 4):
            acc = dict(bufs)
            for p in op.split(n):
                acc.update(p.apply(acc, None))
            np.testing.assert_allclose(np.asarray(acc["ffn_out_0"]),
                                       np.asarray(want), rtol=1e-6)

    def test_pipeline_stage_fold(self):
        from tenzing_tpu.models.pipeline import StageCompute

        rng = np.random.default_rng(1)
        op = StageCompute("compute_0_0", 0, 0, mb_rows=4)
        bufs = {
            "act_0_0": jnp.asarray(rng.standard_normal((8, 8)), jnp.float32),
            "W": jnp.asarray(rng.standard_normal((1, 8, 8)), jnp.float32),
            "out_0": jnp.zeros((8, 8), jnp.float32),
        }
        want = op.apply(dict(bufs), None)["out_0"]
        for n in (2, 4):
            acc = dict(bufs)
            for p in op.split(n):
                acc.update(p.apply(acc, None))
            np.testing.assert_allclose(np.asarray(acc["out_0"]),
                                       np.asarray(want), rtol=1e-6)

    def test_tp_mlp_fold(self):
        from tenzing_tpu.models.tp_mlp import TpLayerPartial

        rng = np.random.default_rng(2)
        op = TpLayerPartial("mlp_0_0", 0, 0, mb_rows=4)
        bufs = {
            "X_0": jnp.asarray(rng.standard_normal((4, 8)), jnp.float32),
            "W1": jnp.asarray(rng.standard_normal((2, 8, 8)), jnp.float32),
            "W2": jnp.asarray(rng.standard_normal((2, 8, 8)), jnp.float32),
            "part_0_0": jnp.zeros((4, 8), jnp.float32),
        }
        want = op.apply(dict(bufs), None)["part_0_0"]
        for n in (2, 4):
            acc = dict(bufs)
            for p in op.split(n):
                acc.update(p.apply(acc, None))
            np.testing.assert_allclose(np.asarray(acc["part_0_0"]),
                                       np.asarray(want), rtol=1e-6)

    def test_partials_reject_indivisible_runtime_rows(self):
        """Regression (review): chunk validity is checked against the
        build-time extent, but a sharded layout (e.g. tp_mlp's dp axis)
        can hand the partial FEWER runtime rows — rows=2 with n_parts=4
        used to slice 0 rows per partial and return an all-zero output
        silently.  The apply must fail at trace time instead."""
        from tenzing_tpu.models.pipeline import StageCompute
        from tenzing_tpu.models.tp_mlp import TpLayerPartial

        rng = np.random.default_rng(5)
        mlp = TpLayerPartial("mlp_0_0", 0, 0, mb_rows=4)
        bufs = {
            "X_0": jnp.asarray(rng.standard_normal((2, 8)), jnp.float32),
            "W1": jnp.asarray(rng.standard_normal((2, 8, 8)), jnp.float32),
            "W2": jnp.asarray(rng.standard_normal((2, 8, 8)), jnp.float32),
            "part_0_0": jnp.zeros((2, 8), jnp.float32),
        }
        [part] = [p for p in mlp.split(4) if p._part == 0][:1]
        with pytest.raises(ValueError, match="do not split"):
            part.apply(bufs, None)

        stage = StageCompute("compute_0_0", 0, 0, mb_rows=4)
        sbufs = {
            "act_0_0": jnp.asarray(rng.standard_normal((6, 8)), jnp.float32),
            "W": jnp.asarray(rng.standard_normal((1, 8, 8)), jnp.float32),
            "out_0": jnp.zeros((6, 8), jnp.float32),
        }
        with pytest.raises(ValueError, match="do not split"):
            stage.split(4)[0].apply(sbufs, None)

    def test_moe_pipe_expert_fold(self):
        from tenzing_tpu.models.moe_pipeline import (
            ExpertFFNPipe,
            MoEPipeArgs,
            make_pipe_buffers,
        )

        margs = MoEPipeArgs(n_experts=4, tokens=32, d_model=8, d_ff=16,
                            n_chunks=2)
        bufs, _, cap = make_pipe_buffers(margs, seed=3, with_expected=False)
        op = ExpertFFNPipe("ffn_0", 0, margs, cap)
        jb = {k: jnp.asarray(v) for k, v in bufs.items()}
        # the expert input: reuse the send staging buffer as the received
        # table (contents arbitrary for the fold identity)
        jb["recv_0"] = jnp.asarray(
            np.random.default_rng(4).standard_normal(
                jb["send_0"].shape), jnp.float32)
        want = op.apply(dict(jb), None)["out_0"]
        for n in (2, 4):
            acc = dict(jb)
            for p in op.split(n):
                acc.update(p.apply(acc, None))
            np.testing.assert_allclose(np.asarray(acc["out_0"]),
                                       np.asarray(want), rtol=1e-6)


class TestVerifierFuzz:
    """The PR-4 verifier certifies chunked projections as-is: every
    schedule the synthesizer emits over the chunk-extended graphs passes
    (0 false positives), and the original oracle agrees."""

    def _graphs(self):
        from tenzing_tpu.models.pipeline import Pipeline, PipelineArgs
        from tenzing_tpu.models.tp_mlp import TpMlp, TpMlpArgs

        def tp():
            g = Graph()
            op = TpMlp(TpMlpArgs(n_tp=2), chunk=True, chunk_relax=True)
            g.start_then(op)
            g.then_finish(op)
            return g

        def pp():
            g = Graph()
            op = Pipeline(PipelineArgs(n_pp=2, n_microbatches=2,
                                       n_chains=2),
                          chunk=True, chunk_relax=True)
            g.start_then(op)
            g.then_finish(op)
            return g

        return [_attn_graph(), _attn_graph(impl_choice=True), tp(), pp()]

    def test_randomized_chunked_rollouts_verify_clean(self):
        from tests.test_verify import synth_sound

        for gi, g in enumerate(self._graphs()):
            ver = ScheduleVerifier(g)
            rng = random.Random(100 + gi)
            n_chunked = 0
            for _ in range(8):
                st = State(g)
                while not st.is_terminal():
                    ds = st.get_decisions(Platform.make_n_lanes(2))
                    # bias toward chunked alternatives so the fuzz
                    # actually exercises chunked projections
                    pick = next(
                        (d for d in ds
                         if getattr(d, "choice", None) is not None
                         and ".chunked.c" in d.choice.name()
                         and rng.random() < 0.7), None)
                    st = st.apply(pick or ds[rng.randrange(len(ds))])
                v = ver(st.sequence)
                assert v.ok, f"false positive: {v.witness()}"
                assert synth_sound(st.graph, st.sequence)
                n_chunked += bool(_has_chunk(st.sequence))
            assert n_chunked >= 1  # the fuzz saw real chunked schedules
            assert ver.unsound == 0

    def test_projection_resolves_executed_count_not_first(self):
        """Regression (found by this fuzz): compound choice alternatives
        all share start/finish sentinel names, so the projection used to
        resolve every such choice to its FIRST compound alternative — a
        ``.chunked.c4`` schedule projected as the ``.c2`` expansion and
        verified ``missing_op``.  The sentinel-skipping resolution must
        project the executed count."""
        from tenzing_tpu.verify.soundness import project_graph

        g = _attn_graph()
        plat = Platform.make_n_lanes(1)
        seq = _drive(g, plat, want_suffix=".chunked.c4")
        assert set(chunks_of(seq).values()) == {4}
        names = frozenset(op.name() for op in seq)
        evolved, notes = project_graph(g, names)
        assert not notes
        vnames = {v.name() for v in evolved.vertices()}
        assert "attn_0.c4p0" in vnames and "attn_0.c2p0" not in vnames
        assert ScheduleVerifier(g)(seq).ok

    def test_projection_resolves_fused_engine_choice(self):
        """Same latent bug, pre-existing surface: the attn engine choice's
        first alternative is a compound (BlockChain) — a schedule
        executing the fused kernel must not project as the chain."""
        from tenzing_tpu.verify.soundness import project_graph

        g = Graph()
        op = BlockedAttention(ATTN, fused_choice=True)
        g.start_then(op)
        g.then_finish(op)
        plat = Platform.make_n_lanes(1)
        seq = _drive(g, plat, want_suffix=".fused_bf16")
        assert any(o.name().endswith(".fused_bf16") for o in seq)
        evolved, notes = project_graph(
            g, frozenset(o.name() for o in seq))
        assert not notes
        vnames = {v.name() for v in evolved.vertices()}
        assert "attn_blocks.fused_bf16" in vnames
        assert "attn_0" not in vnames
        assert ScheduleVerifier(g)(seq).ok

    def test_out_of_graph_tile1_directive_goes_after_start(self):
        """Regression (driver review): the driver completes out-of-graph
        sequences (naive baseline, greedy seeds, recorded rows) with a
        ``fuse_tile.t1`` directive when ``--fuse-search-tiles`` planted a
        tile choice.  The planted choice is a successor of the ``start``
        sentinel, so the directive must be inserted AFTER the leading
        start op — at position 0 it precedes its projected predecessor
        and the verifier rejects the schedule, demoting naive wins to
        ``verified: false`` and silently discarding warm starts."""
        from tenzing_tpu.runtime.fused import FuseTile, with_tile_menu

        def mk():
            g = Graph()
            op = BlockedAttention(ATTN)
            g.start_then(op)
            g.then_finish(op)
            return g

        plat = Platform.make_n_lanes(1)
        ops = list(_drive(mk(), plat).vector())
        assert ops[0].name() == "start"
        ver = ScheduleVerifier(with_tile_menu(mk(), [1, 2]))
        before = Sequence([FuseTile(1)] + ops)
        after = Sequence([ops[0], FuseTile(1)] + ops[1:])
        assert not ver(before).ok
        assert ver(after).ok

    def test_exhaustive_small_space_verifies_clean(self):
        g = _attn_graph()
        ver = ScheduleVerifier(g)
        states = enumerate_schedules(g, Platform.make_n_lanes(2),
                                     max_seqs=64)
        chunked = [s for s in states if _has_chunk(s.sequence)]
        assert chunked
        for st in states:
            v = ver(st.sequence)
            assert v.ok, f"false positive: {v.witness()}"
        assert ver.unsound == 0


class TestSolversSearchChunks:
    """Chunk counts are ordinary choice decisions: all three solvers visit
    >= 2 distinct counts with zero solver changes."""

    def _bench(self, plat, bufs):
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        return EmpiricalBenchmarker(ex)

    def _seen_counts(self, sims):
        seen = set()
        for s in sims:
            cs = set(chunks_of(s.order).values())
            seen.update(cs or {1})
        return seen

    def test_dfs_enumerates_chunk_alternatives(self):
        from tenzing_tpu.solve.dfs import DfsOpts, explore

        bufs, _ = make_blocked_buffers(ATTN, seed=0)
        plat = Platform.make_n_lanes(1)
        res = explore(
            _attn_graph(), plat, self._bench(plat, bufs),
            DfsOpts(max_seqs=24, dump_csv_path="/dev/null",
                    bench_opts=BenchOpts(n_iters=2, target_secs=0.0002)))
        seen = self._seen_counts(res.sims)
        assert 1 in seen and len(seen) >= 2

    def test_hill_climb_searches_chunks(self):
        from tenzing_tpu.solve.local import LocalOpts, hill_climb

        bufs, _ = make_blocked_buffers(ATTN, seed=0)
        plat = Platform.make_n_lanes(1)

        def prefer(op_name, choices):
            # seed unchunked; flip moves must explore the chunk menu
            return next(
                (c for c in choices if not c.endswith((".c2", ".c4"))),
                None)

        res = hill_climb(
            _attn_graph(), plat, self._bench(plat, bufs),
            phases=("attn",), prefer=prefer,
            opts=LocalOpts(budget=6, seed=0,
                           bench_opts=BenchOpts(n_iters=2,
                                                target_secs=0.0002)))
        assert res.sims
        seen = self._seen_counts(res.sims)
        assert 1 in seen and len(seen) >= 2

    def test_mcts_searches_chunks(self):
        from tenzing_tpu.solve.mcts import MctsOpts, explore

        bufs, _ = make_blocked_buffers(ATTN, seed=0)
        plat = Platform.make_n_lanes(1)
        res = explore(
            _attn_graph(), plat, self._bench(plat, bufs),
            MctsOpts(n_iters=12, seed=3,
                     bench_opts=BenchOpts(n_iters=2, target_secs=0.0002),
                     screen_opts=BenchOpts(n_iters=2, target_secs=0.0002)))
        seen = self._seen_counts(res.sims)
        assert len(seen) >= 2


class TestFeatureMarkers:
    def test_directive_features_counted(self):
        from tenzing_tpu.learn.features import FEATURE_NAMES, featurize
        from tenzing_tpu.runtime.fused import FuseTile

        seq = Sequence([ChunkDirective("ffn_0", 2),
                        ChunkDirective("attn_1.xla", 4), FuseTile(8)])
        v = dict(zip(FEATURE_NAMES, featurize(seq)))
        assert v["n_chunk_dir"] == 2.0
        assert v["sum_chunk_counts"] == 6.0
        assert v["n_fuse_tile_dir"] == 1.0
        assert v["sum_fuse_tiles"] == 8.0

    def test_feature_names_append_only(self):
        """Directive coordinates sit at the END of the vector in append
        order (chunk/tile four, then the synth seven): every pre-existing
        coordinate keeps its position, so corpora featurized before an
        append stay consistent."""
        from tenzing_tpu.learn.features import FEATURE_NAMES

        assert FEATURE_NAMES[-11:-7] == ["n_chunk_dir", "sum_chunk_counts",
                                         "n_fuse_tile_dir", "sum_fuse_tiles"]
        assert FEATURE_NAMES[-7:] == ["n_synth_dir", "n_synth_ring",
                                      "n_synth_ringr", "n_synth_rhd",
                                      "n_synth_neighbor", "n_synth_pipe",
                                      "sum_synth_chunks"]
        assert FEATURE_NAMES.index("n_ops") == 0  # prefix unchanged

    def test_save_load_contract_rejects_pre_append_model(self, tmp_path):
        """A model saved under the pre-append name list fails the load
        contract loudly instead of silently mis-predicting with a
        truncated vector."""
        from tenzing_tpu.learn import RidgeEnsemble
        from tenzing_tpu.learn.features import FEATURE_NAMES, featurize

        rng = np.random.default_rng(0)
        old_names = list(FEATURE_NAMES[:-4])
        X = rng.random((8, len(old_names)))
        y = rng.random(8)
        old = RidgeEnsemble(feature_names=old_names).fit(X, y)
        path = str(tmp_path / "old.json")
        old.save(path)
        with pytest.raises(ValueError, match="contract"):
            RidgeEnsemble.load(path, expect_features=list(FEATURE_NAMES))
        # and the current featurizer round-trips
        Xn = np.asarray([featurize(Sequence([ChunkDirective("a", 2)]))])
        cur = RidgeEnsemble(feature_names=list(FEATURE_NAMES)).fit(
            np.repeat(Xn, 8, axis=0), y)
        path2 = str(tmp_path / "new.json")
        cur.save(path2)
        RidgeEnsemble.load(path2, expect_features=list(FEATURE_NAMES))


class TestHiddenCommMeasured:
    def test_overlap_accounting_on_synthetic_timeline(self):
        """hidden_comm_measured_us sums exactly the comm-interval overlap
        with partial intervals — hand-built Gantt, no device."""
        from tenzing_tpu.core.chunking import hidden_comm_measured_us
        from tenzing_tpu.obs.attrib.analysis import Attribution
        from tenzing_tpu.obs.attrib.timeline import OpRecord, OpTimeline

        class FakeXfer:
            KIND = "all_to_all_start"  # in bench/model.py ICI_KINDS

            def name(self):
                return "a2a_0"

        class FakeOp:
            KIND = "noop"

            def name(self):
                return "x"

        ops = [ChunkDirective("ffn_0", 2), FakeOp(), FakeXfer(), FakeOp()]
        recs = [
            OpRecord("ffn_0.chunk.c2", "", "host", None, (0,), 0.0, 0.0),
            OpRecord("ffn_0.c2p0", "", "device", 0, (1,), 100.0, 0.0),
            OpRecord("a2a_0", "", "device", 1, (2,), 80.0, 60.0),
            OpRecord("ffn_0.c2p1", "", "device", 0, (3,), 100.0, 100.0),
        ]
        at = Attribution(timeline=OpTimeline(records=recs))
        # comm [60, 140) overlaps p0 [0,100) by 40 and p1 [100,200) by 40
        assert hidden_comm_measured_us(ops, at) == pytest.approx(80.0)
        # unchunked schedule: nothing to attribute
        assert hidden_comm_measured_us([FakeXfer()], at) == 0.0
