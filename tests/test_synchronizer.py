"""EventSynchronizer truth table (reference event_synchronizer.hpp:29-242)."""

from tenzing_tpu.core.event_synchronizer import EventSynchronizer
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp, NoOp, Start
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, LaneSync, WaitEvent


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


def two_op_graph(a, b):
    g = Graph()
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    return g


def test_host_then_host_free():
    a, b = NoOp("a"), NoOp("b")
    g = two_op_graph(a, b)
    seq = Sequence([g.start(), a])
    assert EventSynchronizer.is_synced(g, seq, b)
    assert EventSynchronizer.make_syncs(g, seq, b) == []


def test_host_then_device_free():
    a, k = NoOp("a"), KOp("k").bind(Lane(0))
    g = two_op_graph(a, k)
    seq = Sequence([g.start(), a])
    assert EventSynchronizer.is_synced(g, seq, k)


def test_device_then_device_same_lane_free():
    a, b = KOp("a").bind(Lane(0)), KOp("b").bind(Lane(0))
    g = two_op_graph(a, b)
    seq = Sequence([g.start(), a])
    assert EventSynchronizer.is_synced(g, seq, b)


def test_device_then_device_cross_lane_needs_record_then_wait():
    a, b = KOp("a").bind(Lane(0)), KOp("b").bind(Lane(1))
    g = two_op_graph(a, b)
    seq = Sequence([g.start(), a])
    assert not EventSynchronizer.is_synced(g, seq, b)

    # step 1: a fresh EventRecord on the pred's lane
    syncs = EventSynchronizer.make_syncs(g, seq, b)
    assert len(syncs) == 1
    rec = syncs[0]
    assert isinstance(rec, EventRecord) and rec.lane() == Lane(0)
    seq.push_back(rec)
    assert not EventSynchronizer.is_synced(g, seq, b)

    # step 2: the matching WaitEvent on the op's lane
    syncs = EventSynchronizer.make_syncs(g, seq, b)
    assert len(syncs) == 1
    w = syncs[0]
    assert isinstance(w, WaitEvent) and w.lane() == Lane(1) and w.event() == rec.event()
    seq.push_back(w)
    assert EventSynchronizer.is_synced(g, seq, b)
    assert EventSynchronizer.make_syncs(g, seq, b) == []


def test_device_then_host_needs_record_then_sync():
    a, c = KOp("a").bind(Lane(0)), NoOp("c")
    g = two_op_graph(a, c)
    seq = Sequence([g.start(), a])
    assert not EventSynchronizer.is_synced(g, seq, c)
    rec = EventSynchronizer.make_syncs(g, seq, c)[0]
    assert isinstance(rec, EventRecord)
    seq.push_back(rec)
    es = EventSynchronizer.make_syncs(g, seq, c)[0]
    assert isinstance(es, EventSync) and es.event() == rec.event()
    seq.push_back(es)
    assert EventSynchronizer.is_synced(g, seq, c)


def test_device_then_host_lane_sync_also_counts():
    a, c = KOp("a").bind(Lane(0)), NoOp("c")
    g = two_op_graph(a, c)
    seq = Sequence([g.start(), a, LaneSync(Lane(0))])
    assert EventSynchronizer.is_synced(g, seq, c)


def test_record_before_pred_does_not_count():
    a, b = KOp("a").bind(Lane(0)), KOp("b").bind(Lane(1))
    g = two_op_graph(a, b)
    # record issued BEFORE a ran captures nothing of a
    seq = Sequence([g.start(), EventRecord(Lane(0), Event(0)), a])
    assert not EventSynchronizer.is_synced(g, seq, b)
    seq2 = Sequence([g.start(), EventRecord(Lane(0), Event(0)), a, WaitEvent(Lane(1), Event(0))])
    assert not EventSynchronizer.is_synced(g, seq2, b)


def test_two_preds_same_lane_share_one_record():
    a, b = KOp("a").bind(Lane(0)), KOp("b").bind(Lane(0))
    c = KOp("c").bind(Lane(1))
    g = Graph()
    g.start_then(a)
    g.start_then(b)
    g.then(a, c)
    g.then(b, c)
    g.then_finish(c)
    seq = Sequence([g.start(), a, b])
    syncs = EventSynchronizer.make_syncs(g, seq, c)
    # one record on lane 0 covers both preds
    assert len(syncs) == 1 and isinstance(syncs[0], EventRecord)


def test_two_preds_distinct_lanes_two_records_fresh_events():
    a, b = KOp("a").bind(Lane(0)), KOp("b").bind(Lane(1))
    c = KOp("c").bind(Lane(2))
    g = Graph()
    g.start_then(a)
    g.start_then(b)
    g.then(a, c)
    g.then(b, c)
    g.then_finish(c)
    seq = Sequence([g.start(), a, b])
    syncs = EventSynchronizer.make_syncs(g, seq, c)
    assert len(syncs) == 2
    assert {s.lane() for s in syncs} == {Lane(0), Lane(1)}
    # fresh events must be distinct
    assert syncs[0].event() != syncs[1].event()
