"""Test environment: force CPU with 8 virtual devices so multi-chip sharding tests
run anywhere (SURVEY.md §4: the reference's CI runs the CPU-tagged subset only;
device tests are opt-in).

Env vars must be set before the CPU backend initializes; the platform must be
forced via jax.config because an ambient PJRT plugin (e.g. the axon TPU tunnel)
may register itself at interpreter startup and take priority over JAX_PLATFORMS.

Capability-probed skips (ISSUE 9 satellite): some environments — notably the
pinned jax-0.4.37 CPU container — lack capabilities whole test families need
(``jax.shard_map``, a ``pinned_host`` memory space on the CPU backend, CPU
multiprocess collectives, ...).  Those tests used to FAIL there, burying real
regressions under a constant red count.  Each such family carries a
``needs_<capability>`` marker (registered in pytest.ini); the probes below run
lazily (once per session, only when a marked test is about to run) and a missing
capability turns the family into *skips* with the probe's reason — so a red
tier-1 line means a real regression, and on a fully-capable environment (CI's
current jax) every probe passes and nothing is skipped.
"""

import functools
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import pytest

jax.config.update("jax_platforms", "cpu")


# -- capability probes (lazy, cached, cheap-first) ---------------------------

@functools.lru_cache(maxsize=None)
def _has_shard_map() -> bool:
    """jax.shard_map moved out of jax.experimental after 0.4.x; the mesh
    lowering paths use the top-level name."""
    return hasattr(jax, "shard_map")


@functools.lru_cache(maxsize=None)
def _has_pinned_host() -> bool:
    """TraceExecutor.place_host_buffers needs a ``pinned_host`` memory
    space; old CPU backends expose only ``unpinned_host``."""
    try:
        kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
    except Exception:
        return False
    return "pinned_host" in kinds


@functools.lru_cache(maxsize=None)
def _has_profile_data() -> bool:
    """jax.profiler.ProfileData (the xplane parser) arrived after 0.4.37."""
    try:
        from jax.profiler import ProfileData  # noqa: F401
        return True
    except ImportError:
        return False


@functools.lru_cache(maxsize=None)
def _has_tie_hlo() -> bool:
    """Does this backend's *compiled* HLO preserve the executor's
    select-based ordering ties?  Old XLA CPU folds the whole token chain
    of a traced program away (the lowered HLO still has the selects), so
    schedule order is not physically represented and the compiled-text
    assertions cannot hold.  Probed on the smallest real program — a
    2-lane diamond through TraceExecutor — because no pure-jax repro
    folds the same way (the fold needs the full chain structure)."""
    try:
        import jax.numpy as jnp

        from tenzing_tpu.core.graph import Graph
        from tenzing_tpu.core.operation import DeviceOp
        from tenzing_tpu.core.platform import Platform
        from tenzing_tpu.core.state import State
        from tenzing_tpu.runtime.executor import TraceExecutor

        class _Add(DeviceOp):
            def __init__(self, name, src, dst):
                super().__init__(name)
                self._src, self._dst = src, dst

            def reads(self):
                return [self._src]

            def writes(self):
                return [self._dst]

            def apply(self, bufs, ctx):
                return {self._dst: bufs[self._src] + 1.0}

        g = Graph()
        a, b, c = _Add("a", "x", "u"), _Add("b", "u", "v"), _Add("c", "v", "w")
        g.start_then(a)
        g.then(a, b)
        g.then(b, c)
        g.then_finish(c)
        plat = Platform.make_n_lanes(2)
        st = State(g)
        while not st.is_terminal():
            st = st.apply(st.get_decisions(plat)[0])
        ex = TraceExecutor(plat, {k: jnp.zeros((4,), jnp.float32)
                                  for k in ("x", "u", "v", "w")})
        txt = ex.compiled_text(st.sequence)
        return ("select(" in txt) or ("select.s" in txt) or (" select" in txt)
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def _has_multiprocess_cpu() -> bool:
    """Can two CPU processes form a jax.distributed job and run a
    collective?  Old CPU backends answer 'Multiprocess computations
    aren't implemented'.  Probed with two tiny subprocesses (a few
    seconds, once per session, only when a marked test is about to run)."""
    import socket
    import subprocess
    import sys

    driver = (
        "import os, sys\n"
        "pid, port = int(sys.argv[1]), sys.argv[2]\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ.pop('XLA_FLAGS', None)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.distributed.initialize(\n"
        "    coordinator_address=f'localhost:{port}',\n"
        "    num_processes=2, process_id=pid)\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import multihost_utils\n"
        "v = multihost_utils.broadcast_one_to_all(jnp.float32(7.0))\n"
        "assert float(v) == 7.0\n"
    )
    try:
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = str(s.getsockname()[1])
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env["JAX_PLATFORMS"] = "cpu"
        procs = [subprocess.Popen([sys.executable, "-c", driver,
                                   str(pid), port], env=env,
                                  stdout=subprocess.DEVNULL,
                                  stderr=subprocess.DEVNULL)
                 for pid in (0, 1)]
        ok = True
        for p in procs:
            try:
                ok = (p.wait(timeout=120) == 0) and ok
            except subprocess.TimeoutExpired:
                p.kill()
                ok = False
        return ok
    except OSError:
        return False


_CAPABILITIES = {
    "needs_shard_map": (
        _has_shard_map,
        "jax.shard_map is unavailable (mesh lowering paths cannot run)"),
    "needs_pinned_host": (
        _has_pinned_host,
        "the CPU backend has no pinned_host memory space "
        "(TraceExecutor.place_host_buffers cannot stage host buffers)"),
    "needs_multiprocess": (
        _has_multiprocess_cpu,
        "multiprocess computations are not implemented on this CPU backend"),
    "needs_profile_data": (
        _has_profile_data,
        "jax.profiler.ProfileData (xplane parser) is unavailable"),
    "needs_tie_hlo": (
        _has_tie_hlo,
        "this backend's compiled HLO folds the select-based ordering "
        "ties away (schedule order is not physically represented)"),
}


def pytest_runtest_setup(item):
    # per-test setup, not collection: a probe (the multiprocess one costs
    # two subprocesses) only ever runs when a marked test is actually
    # about to execute — `-k`, `-m` and --collect-only stay probe-free —
    # and the lru_cache makes it once per session regardless
    for marker, (probe, why) in _CAPABILITIES.items():
        if item.get_closest_marker(marker) is None:
            continue
        if not probe():
            pytest.skip(f"environment capability absent: {why}")
