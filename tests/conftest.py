"""Test environment: force CPU with 8 virtual devices so multi-chip sharding tests
run anywhere (SURVEY.md §4: the reference's CI runs the CPU-tagged subset only;
device tests are opt-in).  Must run before jax is imported anywhere."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
