"""Test environment: force CPU with 8 virtual devices so multi-chip sharding tests
run anywhere (SURVEY.md §4: the reference's CI runs the CPU-tagged subset only;
device tests are opt-in).

Env vars must be set before the CPU backend initializes; the platform must be
forced via jax.config because an ambient PJRT plugin (e.g. the axon TPU tunnel)
may register itself at interpreter startup and take priority over JAX_PLATFORMS.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
