"""General irregular remote-column exchange (VERDICT r1 item 3): negotiation
invariants, numerics on arbitrary sparsity over the virtual 8-device mesh, the
band-matrix degeneration, and the post/wait overlap freedom."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.spmv import random_band_matrix, random_matrix
from tenzing_tpu.models.spmv_irregular import (
    IrregularSpMV,
    make_irregular_spmv_buffers,
    negotiate_exchange,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


def _graph(steps):
    g = Graph()
    g.start_then(IrregularSpMV(steps))
    g.then_finish(IrregularSpMV(steps))
    return g


def _run(a, n_sp, dp, batch, max_schedules=1, seed=0):
    bufs, specs, want, plan = make_irregular_spmv_buffers(
        a, n_sp=n_sp, batch=batch, seed=seed
    )
    devs = np.array(jax.devices()[: dp * n_sp]).reshape(dp, n_sp)
    mesh = Mesh(devs, ("dp", "sp"))
    plat = Platform.make_n_lanes(2, mesh=mesh, specs=specs)
    ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
    g = _graph(plan.steps)
    outs = []
    for st in get_all_sequences(g, plat, max_seqs=max_schedules):
        outs.append(np.asarray(ex.run(st.sequence)["Y"]))
    return outs, want, plan


def test_negotiation_covers_every_remote_column():
    a = random_matrix(64, 64, 500, seed=3)
    n_sp, block = 4, 16
    plan = negotiate_exchange(a, n_sp)
    for p in range(n_sp):
        lo, hi = p * block, (p + 1) * block
        rows = a.retain_rows(lo, hi)
        remote = np.unique(rows.cols[(rows.cols < lo) | (rows.cols >= hi)])
        got = np.concatenate(
            [plan.send_lists[d][p] for d in plan.steps]
            + [np.array([], dtype=np.int64)]
        )
        assert sorted(got) == sorted(remote.tolist())
        # each received column really is owned by the shard d hops back
        for d in plan.steps:
            for c in plan.send_lists[d][p]:
                assert plan.owner(c) == (p - d) % n_sp


@pytest.mark.needs_shard_map
def test_random_matrix_numerics_all_distances():
    """A uniform random matrix needs every cyclic distance — the case the band
    model cannot express."""
    a = random_matrix(64, 64, 600, seed=1)
    plan = negotiate_exchange(a, 4)
    assert plan.steps == [1, 2, 3]
    outs, want, _ = _run(a, n_sp=4, dp=2, batch=4, max_schedules=1)
    np.testing.assert_allclose(outs[0], want, rtol=2e-3)


@pytest.mark.needs_shard_map
def test_numerics_stable_across_schedules():
    a = random_matrix(32, 32, 200, seed=7)
    outs, want, _ = _run(a, n_sp=4, dp=1, batch=2, max_schedules=6)
    assert len(outs) == 6
    for y in outs:
        np.testing.assert_allclose(y, want, rtol=2e-3)


@pytest.mark.needs_shard_map
def test_band_matrix_degenerates_to_adjacent_steps():
    """Half-bandwidth < block: the irregular machinery retains exactly the two
    adjacent cyclic distances (the spmv_dist.py static-neighbor case)."""
    a = random_band_matrix(64, 7, 400, seed=2)
    plan = negotiate_exchange(a, 4)
    assert set(plan.steps) <= {1, 3}
    outs, want, _ = _run(a, n_sp=4, dp=2, batch=2)
    np.testing.assert_allclose(outs[0], want, rtol=2e-3)


@pytest.mark.needs_shard_map
def test_block_diagonal_needs_no_exchange():
    a = random_band_matrix(64, 0, 200, seed=4)  # diagonal only
    plan = negotiate_exchange(a, 4)
    assert plan.steps == []
    outs, want, _ = _run(a, n_sp=4, dp=1, batch=2)
    np.testing.assert_allclose(outs[0], want, rtol=2e-3)


@pytest.mark.needs_shard_map
def test_exchange_impl_choice_all_variants_correct():
    """With impl_choice the exchange realization is a ChoiceOp: per-distance
    permutes vs one padded all-to-all (the Ialltoallv analog,
    ops_mpi.hpp:82-119) vs per-distance remote DMA (the negotiated
    Isend/Irecv analog, row_part_spmv.cuh:259-423).  Every structural variant
    must be enumerated and produce the right Y."""
    from tenzing_tpu.solve.dfs import structural_variants

    a = random_matrix(64, 64, 500, seed=9)
    bufs, specs, want, plan = make_irregular_spmv_buffers(
        a, n_sp=4, batch=2, impl_choice=True
    )
    g = Graph()
    g.start_then(IrregularSpMV(plan.steps, widths=plan.widths, impl_choice=True))
    g.then_finish(IrregularSpMV(plan.steps, widths=plan.widths, impl_choice=True))
    variants = structural_variants(g)
    assert len(variants) == 3
    kinds = {
        ("a2a" if any("a2a" in v.desc() for v in var.vertices())
         else "rdma" if any("rdma" in v.desc() for v in var.vertices())
         else "permute")
        for var in variants
    }
    assert kinds == {"permute", "a2a", "rdma"}

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "sp"))
    plat = Platform.make_n_lanes(2, mesh=mesh, specs=specs)
    ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
    for var in variants:
        st = get_all_sequences(var, plat, max_seqs=1)[0]
        np.testing.assert_allclose(
            np.asarray(ex.run(st.sequence)["Y"]), want, rtol=2e-3
        )


def test_post_wait_overlap_orderings_exist():
    """The enumerated space must contain schedules where compute sits between a
    permute post and its await — the overlap freedom the split exists for
    (reference PostRecv/WaitRecv discipline, ops_spmv.cuh:217-304)."""
    a = random_matrix(32, 32, 200, seed=5)
    bufs, specs, want, plan = make_irregular_spmv_buffers(a, n_sp=4, batch=2)
    plat = Platform.make_n_lanes(1)
    g = _graph(plan.steps)
    found = False
    for st in get_all_sequences(g, plat, max_seqs=400):
        ops = [op.desc() for op in st.sequence.vector()]
        for d in plan.steps:
            post = ops.index(f"permute_{d}")
            aw = ops.index(f"await_{d}")
            between = ops[post + 1 : aw]
            if any(o.startswith(("spmv_local", "gather_")) for o in between):
                found = True
                break
        if found:
            break
    assert found, "no schedule overlaps compute with an in-flight permute"
