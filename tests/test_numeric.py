"""Numeric helpers and the runs-test (reference numeric.cpp, randomness.cpp)."""

import random

import pytest

from tenzing_tpu.bench.randomness import is_random, runs_test_z
from tenzing_tpu.utils.numeric import (
    avg,
    corr,
    med,
    percentile,
    prime_factors,
    round_up,
    stddev,
)


def test_stats():
    xs = [1.0, 2.0, 3.0, 4.0]
    assert avg(xs) == 2.5
    assert med(xs) == 2.5
    assert med([1.0, 2.0, 9.0]) == 2.0
    assert stddev([2.0, 2.0]) == 0.0


def test_corr():
    xs = [1.0, 2.0, 3.0]
    assert corr(xs, xs) == pytest.approx(1.0)
    assert corr(xs, [3.0, 2.0, 1.0]) == pytest.approx(-1.0)
    assert corr(xs, [5.0, 5.0, 5.0]) == 0.0
    with pytest.raises(ValueError):
        corr([1.0], [1.0, 2.0])


def test_prime_factors():
    assert prime_factors(12) == [2, 2, 3]
    assert prime_factors(7) == [7]
    assert prime_factors(1) == []
    # device-grid factorization use case: 8 chips -> 2x2x2
    assert prime_factors(8) == [2, 2, 2]


def test_round_up():
    assert round_up(5, 4) == 8
    assert round_up(8, 4) == 8
    with pytest.raises(ValueError):
        round_up(3, 0)


def test_percentile():
    xs = sorted(float(i) for i in range(101))
    assert percentile(xs, 1) == 1.0
    assert percentile(xs, 50) == 50.0
    assert percentile(xs, 99) == 99.0


def test_runs_test_accepts_iid_noise():
    rng = random.Random(0)
    xs = [rng.random() for _ in range(200)]
    assert is_random(xs)


def test_runs_test_rejects_drift():
    # monotone drift = 2 runs, far too few
    xs = [float(i) for i in range(200)]
    assert not is_random(xs)
    assert runs_test_z(xs) < -1.96


def test_runs_test_rejects_alternation():
    xs = [float(i % 2) for i in range(200)]
    assert not is_random(xs)
    assert runs_test_z(xs) > 1.96


def test_paired_speedup_cancels_common_mode_drift():
    """The paired per-iteration verdict: a 10% real speedup stays visible with
    a tight CI under 50% common-mode drift that would swamp unpaired pct50s."""
    from tenzing_tpu.utils.numeric import paired_speedup

    rng = random.Random(1)
    drift = [1 + 0.5 * abs((k % 40) - 20) / 20 for k in range(40)]
    base = [0.10 * d * (1 + 0.02 * rng.random()) for d in drift]
    cand = [0.09 * d * (1 + 0.02 * rng.random()) for d in drift]
    m, lo, hi = paired_speedup(base, cand, seed=0)
    assert 1.08 < m < 1.14
    assert lo > 1.05 and hi < 1.15 and lo <= m <= hi
    # deterministic under the seed
    assert (m, lo, hi) == paired_speedup(base, cand, seed=0)
    # no-difference case straddles 1.0
    same = [0.1 * d for d in drift]
    m2, lo2, hi2 = paired_speedup(same, list(same), seed=0)
    assert m2 == 1.0 and lo2 <= 1.0 <= hi2
    with pytest.raises(ValueError):
        paired_speedup([1.0], [1.0, 2.0])
