"""Serve fast path (ISSUE 14): sealed-response memoization, the
fingerprint canonicalization cache, and lock-free concurrent exact
reads.

The correctness contract is byte-identity: a memoized response, patched
with the per-request fields, must serialize to exactly the bytes a
fresh (un-memoized) serialization of the same resolution produces — for
every tier/outcome shape.  Invalidation must fire on the store
generation bump (records landing, flag mutations) and on cache
eviction.  And the lock-free snapshot path must return results
identical to the serialized exclusive path even while a writer mutates
the store under it (the hammer test).
"""

import itertools
import json
import threading

import pytest

from tenzing_tpu.bench.benchmarker import BenchResult, result_row
from tenzing_tpu.bench.driver import DriverRequest, graph_for
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.serve.fingerprint import fingerprint_of, schedule_key
from tenzing_tpu.serve.resolver import Resolver, fp_cache_key
from tenzing_tpu.serve.service import ScheduleService
from tenzing_tpu.serve.store import ScheduleStore, WorkQueue

REQ_KW = {"workload": "spmv", "m": 512}
REQ = DriverRequest(**REQ_KW)
NEAR_KW = {"workload": "spmv", "m": 500}       # same bucket
COLD_KW = {"workload": "spmv", "m": 100_000}   # different bucket


def _drive(g, n_lanes, picks):
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State

    plat = Platform.make_n_lanes(n_lanes)
    st = State(g)
    i = 0
    while not st.is_terminal():
        ds = st.get_decisions(plat)
        st = st.apply(ds[picks[i % len(picks)] % len(ds)])
        i += 1
    return st.sequence


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("fastpath_corpus")
    g, _ = graph_for(REQ)
    naive = _drive(g, 1, [0])
    alts, seen = [], set()
    for picks in itertools.product((0, 1, 2), repeat=3):
        s = _drive(g, 2, list(picks))
        k = schedule_key(s)
        if k not in seen:
            seen.add(k)
            alts.append(s)
        if len(alts) >= 6:
            break
    rows = [result_row(0, BenchResult.from_times([2.0, 2.1, 2.05]), naive)]
    for i, a in enumerate(alts):
        t = 1.0 + 0.1 * i
        rows.append(result_row(
            i + 1, BenchResult.from_times([t, t * 1.02, t * 0.99]), a))
    path = d / "spmv_search.csv"
    path.write_text("\n".join(rows) + "\n")
    return {"csv": str(path), "graph": g, "alts": alts}


def _service(tmp_path, corpus, train=True):
    svc = ScheduleService(str(tmp_path / "store.json"),
                          queue_dir=str(tmp_path / "queue"))
    svc.warm(REQ, [corpus["csv"]], topk=2, train=train)
    return svc


def _strip_request_fields(doc):
    out = dict(doc)
    out.pop("phase_us", None)
    out.pop("trace_id", None)
    return out


# -- byte identity ----------------------------------------------------------

def test_memo_byte_identity_to_fresh_serialization(corpus, tmp_path):
    """THE memo contract: for an exact cache hit, the memoized
    copy-and-patch document serializes to exactly the bytes a fresh,
    never-memoized serialization of the same resolution produces."""
    svc = _service(tmp_path, corpus, train=False)
    key = fp_cache_key(REQ_KW)
    svc.query(REQ, fp_key=key)          # walk: populates cache + memo
    memoized = svc.query(REQ, fp_key=key)   # cache hit: memo-backed
    assert memoized.memo is not None
    assert memoized.provenance["cache_hit"] is True

    # the fresh reference: a brand-new resolver over the SAME store
    # object (a disk round-trip may reorder record keys cosmetically —
    # the memo contract is about serializing the same in-memory record),
    # taken to the same cache-hit state, with the memo surgically
    # removed so its to_json serializes from scratch
    fresh_r = Resolver(svc.store, queue=None)
    fresh_r.resolve(REQ)
    fresh = fresh_r.resolve(REQ)
    assert fresh.provenance["cache_hit"] is True
    fresh.memo = None  # force the from-scratch serialization path
    fresh.phase_us = memoized.phase_us
    fresh.trace_id = memoized.trace_id
    assert json.dumps(memoized.to_json()) == json.dumps(fresh.to_json())


def test_memo_byte_identity_every_tier_shape(corpus, tmp_path):
    """Every tier/outcome shape a resolution can serialize: the memoized
    path and the fresh path agree byte-for-byte where both exist, and
    the non-memoized tiers (walk-serve, near, cold) still serialize
    with their documented fields."""
    svc = _service(tmp_path, corpus, train=True)
    key = fp_cache_key(REQ_KW)

    walk = svc.query(REQ, fp_key=key)
    assert walk.tier == "exact" and walk.memo is None
    assert walk.provenance["cache_hit"] is False
    wj = walk.to_json()
    assert {"tier", "fingerprint", "provenance", "key", "ops",
            "pct50_us", "vs_naive", "phase_us", "trace_id"} <= set(wj)

    hit = svc.query(REQ, fp_key=key)
    hj = hit.to_json()
    # identical documents modulo the per-request fields and the
    # cache-hit provenance + walk-only phase
    assert _strip_request_fields(hj)["ops"] == \
        _strip_request_fields(wj)["ops"]
    assert hj["provenance"]["cache_hit"] is True
    assert "store_walk" not in hj["phase_us"]

    near = svc.query(DriverRequest(**NEAR_KW),
                     fp_key=fp_cache_key(NEAR_KW))
    assert near.tier == "near" and near.memo is None
    nj = near.to_json()
    assert nj["provenance"]["was_predicted"] is True

    cold = svc.query(DriverRequest(**COLD_KW),
                     fp_key=fp_cache_key(COLD_KW))
    assert cold.tier == "cold" and cold.memo is None
    cj = cold.to_json()
    assert cj["work_item"]

    # re-querying near/cold through the fast path must decline (only
    # exact hits are lock-free servable)
    assert svc.resolver.resolve_fast(fp_cache_key(COLD_KW)) is None


def test_fast_path_byte_identity_and_provenance(corpus, tmp_path):
    svc = _service(tmp_path, corpus, train=False)
    key = fp_cache_key(REQ_KW)
    svc.query(REQ, fp_key=key)
    slow_hit = svc.query(REQ, fp_key=key)
    fast = svc.resolver.resolve_fast(key)
    assert fast is not None and fast.tier == "exact"
    fj, sj = fast.to_json(), slow_hit.to_json()
    sj["phase_us"] = fj["phase_us"]
    sj["trace_id"] = fj["trace_id"]
    assert json.dumps(fj) == json.dumps(sj)
    assert fast.record["key"] == slow_hit.record["key"]
    assert fast.pct50_us == slow_hit.pct50_us
    assert fast.provenance["verifier_calls"] == 0
    assert fast.phase_us.keys() == {"fingerprint", "cache_probe"}


# -- fingerprint cache ------------------------------------------------------

def test_fp_cache_key_shapes():
    assert fp_cache_key({"workload": "spmv", "m": 512}) == \
        (("m", 512), ("workload", "spmv"))
    assert fp_cache_key({}) == ()
    assert fp_cache_key(None) is None
    assert fp_cache_key("nope") is None
    # unhashable values are honestly uncacheable, never a crash
    assert fp_cache_key({"learn_train": ["a.csv"]}) is None


def test_fp_cache_counters_and_bound(corpus, tmp_path):
    svc = _service(tmp_path, corpus, train=False)
    reg = get_metrics()
    h0 = reg.counter("serve.fp_cache.hits").value
    m0 = reg.counter("serve.fp_cache.misses").value
    key = fp_cache_key(REQ_KW)
    svc.query(REQ, fp_key=key)
    assert reg.counter("serve.fp_cache.misses").value == m0 + 1
    svc.query(REQ, fp_key=key)
    assert reg.counter("serve.fp_cache.hits").value == h0 + 1
    # the cached fingerprint has both digests precomputed (the whole
    # point: probe-time digest hashing collapses to an attribute read)
    fp = svc.resolver._fp_cache[key]
    assert "exact_digest" in fp.__dict__ and "bucket_digest" in fp.__dict__
    # bounded: a sweep of distinct keys evicts oldest-first
    svc.resolver.fp_cache_cap = 4
    for m in (601, 602, 603, 604):
        kw = {"workload": "spmv", "m": m}
        svc.query(DriverRequest(**kw), fp_key=fp_cache_key(kw))
    assert len(svc.resolver._fp_cache) <= 4
    assert key not in svc.resolver._fp_cache  # the oldest fell out


# -- invalidation -----------------------------------------------------------

def test_memo_invalidates_on_store_generation_bump(corpus, tmp_path):
    svc = _service(tmp_path, corpus, train=False)
    reg = get_metrics()
    key = fp_cache_key(REQ_KW)
    svc.query(REQ, fp_key=key)
    assert svc.resolver.resolve_fast(key) is not None
    inv0 = reg.counter("serve.memo.invalidations").value
    # any record landing bumps the generation...
    svc.store.add(fingerprint_of(DriverRequest(**COLD_KW)),
                  corpus["alts"][0], pct50_us=5.0, vs_naive=1.1)
    # ...which kills the snapshot for the lock-free path immediately
    assert svc.resolver.resolve_fast(key) is None
    res = svc.query(REQ, fp_key=key)  # exclusive path refreshes
    assert res.tier == "exact"
    assert reg.counter("serve.memo.invalidations").value > inv0
    assert svc.resolver.resolve_fast(key) is not None


def test_memo_invalidates_on_flag_mutation(corpus, tmp_path):
    svc = _service(tmp_path, corpus, train=False)
    key = fp_cache_key(REQ_KW)
    res = svc.query(REQ, fp_key=key)
    assert svc.resolver.resolve_fast(key) is not None
    # a flag mutation (the unsound case above all) must invalidate:
    # store.flag bumps the generation exactly like a record landing
    svc.store.flag(res.record["exact"], res.record["key"],
                   needs_refinement=True)
    assert svc.resolver.resolve_fast(key) is None
    again = svc.query(REQ, fp_key=key)
    assert again.tier == "exact"
    assert again.record["flags"]["needs_refinement"] is True


def test_unsound_flag_never_served_after_invalidation(corpus, tmp_path):
    svc = _service(tmp_path, corpus, train=False)
    key = fp_cache_key(REQ_KW)
    res = svc.query(REQ, fp_key=key)
    served_key = res.record["key"]
    svc.store.flag(res.record["exact"], served_key, unsound=True)
    assert svc.resolver.resolve_fast(key) is None  # snapshot is stale
    again = svc.query(REQ, fp_key=key)
    # the runner-up (or a demotion) — never the flagged record
    assert again.record is None or again.record["key"] != served_key


def test_memo_invalidates_on_cache_eviction(corpus, tmp_path):
    svc = _service(tmp_path, corpus, train=False)
    reg = get_metrics()
    svc.resolver.exact_cache_cap = 1
    key = fp_cache_key(REQ_KW)
    svc.query(REQ, fp_key=key)
    inv0 = reg.counter("serve.memo.invalidations").value
    # a second fingerprint entering the size-1 cache evicts the first —
    # and the evicted sealed memo is counted as an invalidation
    kw2 = {"workload": "spmv", "m": 700}
    svc.store.add(fingerprint_of(DriverRequest(**kw2)),
                  corpus["alts"][1], pct50_us=3.0, vs_naive=1.2)
    svc.query(DriverRequest(**kw2), fp_key=fp_cache_key(kw2))
    svc.query(DriverRequest(**kw2), fp_key=fp_cache_key(kw2))
    r2 = svc.query(REQ, fp_key=key)          # misses, re-walks, evicts
    assert r2.tier == "exact"
    assert reg.counter("serve.memo.invalidations").value > inv0
    assert len(svc.resolver._exact_cache) == 1


def test_memo_counters_economics(corpus, tmp_path):
    svc = _service(tmp_path, corpus, train=False)
    reg = get_metrics()
    h0 = reg.counter("serve.memo.hits").value
    m0 = reg.counter("serve.memo.misses").value
    key = fp_cache_key(REQ_KW)
    svc.query(REQ, fp_key=key)          # walk = memo miss (seal here)
    svc.query(REQ, fp_key=key)          # cache hit = memo hit
    svc.resolver.resolve_fast(key)      # fast path = memo hit
    assert reg.counter("serve.memo.misses").value == m0 + 1
    assert reg.counter("serve.memo.hits").value == h0 + 2


# -- concurrent reads (the hammer) -----------------------------------------

def test_concurrent_fast_reads_identical_under_mutating_writer(
        corpus, tmp_path):
    """Hammer: reader threads resolve the same exact request through the
    listen-style fast-or-exclusive split while a writer keeps bumping
    the store generation (re-adding the same records — the answer never
    legitimately changes).  Every response must be identical to the
    serialized reference modulo the per-request fields, and nothing may
    error — a stale snapshot falls through to the exclusive path, never
    to a wrong answer."""
    svc = _service(tmp_path, corpus, train=False)
    key = fp_cache_key(REQ_KW)
    ref = svc.query(REQ, fp_key=key)
    ref_body = _strip_request_fields(svc.query(REQ, fp_key=key).to_json())
    lock = threading.Lock()  # the listen loop's exclusive lock, modeled
    stop = threading.Event()
    errors: list = []
    mismatches: list = []
    served = [0]

    fp = fingerprint_of(REQ)
    rec = svc.store.best(fp.exact_digest)

    def writer():
        # same content re-added: generation bumps (merge is idempotent),
        # the served answer must not change
        while not stop.is_set():
            svc.store._put(dict(rec))

    def reader():
        for _ in range(300):
            try:
                res = svc.resolver.resolve_fast(key)
                if res is None:
                    with lock:
                        res = svc.query(REQ, fp_key=key)
                body = _strip_request_fields(res.to_json())
                body["provenance"] = dict(body["provenance"],
                                          cache_hit=True)
                body.pop("phase_us", None)
                want = dict(ref_body, provenance=dict(
                    ref_body["provenance"], cache_hit=True))
                if json.dumps(body, sort_keys=True) != \
                        json.dumps(want, sort_keys=True):
                    mismatches.append((body, want))
                with lock:
                    served[0] += 1
            except Exception as e:  # pragma: no cover - the assertion
                errors.append(repr(e))

    wt = threading.Thread(target=writer, daemon=True)
    readers = [threading.Thread(target=reader, daemon=True)
               for _ in range(4)]
    wt.start()
    for t in readers:
        t.start()
    for t in readers:
        t.join(timeout=60)
    stop.set()
    wt.join(timeout=5)
    assert not errors, errors[:3]
    assert not mismatches, mismatches[:1]
    assert served[0] == 4 * 300
    assert ref.tier == "exact"


def test_listen_loop_serves_exact_hits_concurrently(corpus, tmp_path):
    """The split lock through the real ServeLoop: two slow-resolver
    stand-ins would serialize under the old global lock; with the fast
    path, exact hits resolve on worker threads concurrently (wall clock
    for K requests ~ K/workers, not K)."""
    from tenzing_tpu.serve.listen import ListenOpts, ServeLoop

    svc = _service(tmp_path, corpus, train=False)
    loop = ServeLoop(svc, ListenOpts(
        max_pending=64, workers=4, request_timeout_secs=30.0,
        handle_signals=False,
        status_path=str(tmp_path / "status.json")))
    loop.start()
    docs, lock = [], threading.Lock()

    def respond(doc):
        with lock:
            docs.append(doc)

    for i in range(32):
        loop.submit({"op": "query", "id": i, "request": dict(REQ_KW)},
                    respond)
    loop.drain(timeout=30.0)
    ok = [d for d in docs if d.get("ok")]
    assert len(ok) == 32
    tiers = {d["result"]["tier"] for d in ok}
    assert tiers == {"exact"}
    # at least the steady-state majority served from the memo
    hits = [d for d in ok
            if d["result"]["provenance"].get("cache_hit")]
    assert len(hits) >= 30
    bodies = {json.dumps(_strip_request_fields(
        {k: v for k, v in d["result"].items()
         if k not in ("resolve_us",)})) for d in hits}
    assert len(bodies) == 1  # every concurrent hit: identical bytes


def test_fp_cache_key_rejects_oversized_kwargs():
    """The key retains verbatim client kwargs for the cache's lifetime:
    a multi-megabyte string value (a valid DriverRequest path field) is
    honestly uncacheable instead of pinning memory in the serve loop."""
    small = {"workload": "spmv", "dump_csv": "x" * 100}
    assert fp_cache_key(small) is not None
    huge = {"workload": "spmv", "dump_csv": "x" * 1_000_000}
    assert fp_cache_key(huge) is None
    many = {f"k{i}": "v" * 64 for i in range(64)}
    assert fp_cache_key(many) is None  # aggregate size counts too
