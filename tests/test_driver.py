"""Driver-extraction parity (ISSUE 7 satellite): the library driver and
the bench.py CLI shim can never drift.

The extraction's contract is *identical CLI behavior*: the argparse
surface and :class:`~tenzing_tpu.bench.driver.DriverRequest` are the
same request (field set AND defaults asserted equal), config errors map
onto ``argparse.error``, and the device-free builders
(``workload_shape`` / ``graph_for``) resolve exactly the shapes the
device builders do.
"""

import dataclasses

import pytest

from tenzing_tpu.bench import driver
from tenzing_tpu.bench.driver import (
    BUILDERS,
    DriverConfigError,
    DriverRequest,
    graph_for,
    search_lanes,
    workload_shape,
)


def test_request_matches_cli_surface():
    """Every argparse dest is a DriverRequest field with the same
    default — the one test that makes `bench.py` and the library API a
    single request type instead of two slowly-diverging ones."""
    import bench

    ns = vars(bench.build_arg_parser().parse_args([]))
    fields = {f.name: f.default for f in dataclasses.fields(DriverRequest)}
    assert set(ns) == set(fields), set(ns) ^ set(fields)
    assert ns == fields


def test_request_json_round_trip():
    req = DriverRequest(workload="spmv", m=640, seed_topk=5, resume=False)
    j = req.to_json()
    assert DriverRequest(**j) == req
    import json

    assert DriverRequest(**json.loads(json.dumps(j))) == req


def test_config_errors_raise_not_exit():
    with pytest.raises(DriverConfigError, match="--resume requires"):
        driver.run(DriverRequest(resume=True))
    with pytest.raises(DriverConfigError, match="unknown workload"):
        workload_shape(DriverRequest(workload="nope"))
    # run() validates BEFORE probing the backend: a drainer fed a
    # corrupt work item gets the API's error, not a KeyError (or a
    # backend-failure verdict mislabeled into the fall-through metric)
    with pytest.raises(DriverConfigError, match="unknown workload"):
        driver.run(DriverRequest(workload="hallo"))


def test_workload_shape_goldens():
    # the builder-resolved shapes, pinned: these are the serving
    # fingerprint's inputs (a silent change re-keys every store)
    assert workload_shape(DriverRequest(workload="halo")) == \
        {"nq": 3, "n": 512, "radius": 3}
    assert workload_shape(DriverRequest(workload="halo", smoke=True)) == \
        {"nq": 2, "n": 4, "radius": 1}
    # bw=None resolves to the builder's own default (max(1, m // 8),
    # models/spmv.py) — a default request and an explicit --spmv-bw of
    # the same value must share a fingerprint
    assert workload_shape(DriverRequest(workload="spmv")) == \
        {"m": 150_000, "nnz_per_row": 10, "bw": 18_750}
    assert workload_shape(DriverRequest(workload="spmv")) == \
        workload_shape(DriverRequest(workload="spmv", spmv_bw=18_750))
    assert workload_shape(DriverRequest(workload="spmv", m=640,
                                        spmv_bw=32)) == \
        {"m": 640, "nnz_per_row": 10, "bw": 32}
    assert workload_shape(DriverRequest(workload="attn")) == \
        {"n_devices": 8, "batch": 4, "seq_local": 1024, "head_dim": 128}
    assert workload_shape(DriverRequest(workload="moe", smoke=True)) == \
        {"n_experts": 4, "tokens": 32, "d_model": 8, "d_ff": 16,
         "n_chunks": 2}
    assert workload_shape(DriverRequest(workload="moe",
                                        moe_tokens=4096)) == \
        {"tokens": 4096}


def test_search_lanes_default_rule():
    assert search_lanes(DriverRequest(workload="halo")) == 8
    assert search_lanes(DriverRequest(workload="halo", smoke=True)) == 2
    assert search_lanes(DriverRequest(workload="spmv")) == 2
    assert search_lanes(DriverRequest(workload="halo", lanes=3)) == 3


def test_builders_cover_all_workloads():
    assert set(BUILDERS) == {"halo", "spmv", "attn", "moe"}


def test_graph_for_is_device_free():
    """The serving builders never place buffers: graphs + nbytes come
    back on a CPU-only host (attn smoke and spmv full both build here;
    full-size halo deliberately skips its 2 GB buffer materialization)."""
    g, nbytes = graph_for(DriverRequest(workload="attn", smoke=True))
    assert len(list(g.vertices())) > 0
    assert nbytes and all(v >= 0 for v in nbytes.values())
    g2, nbytes2 = graph_for(DriverRequest(workload="spmv", m=512))
    assert len(list(g2.vertices())) > 0
    assert nbytes2


def test_graph_for_resolves_recorded_ops_across_nearby_shapes():
    """A schedule serialized against one shape re-materializes against a
    nearby shape's graph — the property the near-miss tier rests on."""
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.serdes import sequence_from_json, sequence_to_json
    from tenzing_tpu.core.state import State

    g1, _ = graph_for(DriverRequest(workload="spmv", m=512))
    g2, _ = graph_for(DriverRequest(workload="spmv", m=500))
    plat = Platform.make_n_lanes(2)
    st = State(g1)
    while not st.is_terminal():
        st = st.apply(st.get_decisions(plat)[0])
    seq2 = sequence_from_json(sequence_to_json(st.sequence), g2)
    assert len(seq2) == len(st.sequence)


def test_run_scope_disposes_handlers_for_repeat_calls():
    """run() is the work-queue drain step (docs/serving.md): each call's
    atexit/trap registrations must run their finalizers once and then
    disappear, so item N's SIGINT can never fire item N-1's checkpoint
    stamps and closures never pin buffers until process exit."""
    from tenzing_tpu.bench.driver import _RunScope
    from tenzing_tpu.utils import trap

    calls = []
    before = len(trap._callbacks)
    sc = _RunScope()
    sc.on_exit(lambda: calls.append("first"))
    sc.on_exit(lambda: calls.append("second"))
    sc.on_trap(lambda: calls.append("trap"))
    assert len(trap._callbacks) == before + 1
    sc.close()
    # LIFO like atexit (prefetcher.close must finalize its counters
    # before the earlier-registered telemetry flush writes them out);
    # each finalizer ran exactly once, the trap handler not at all
    assert calls == ["second", "first"]
    assert len(trap._callbacks) == before  # trap handler unregistered
    sc.close()  # idempotent: a second close re-runs nothing
    assert calls == ["second", "first"]


def test_run_scope_failed_finalizer_does_not_mask_others(capsys):
    from tenzing_tpu.bench.driver import _RunScope

    calls = []
    sc = _RunScope()
    sc.on_exit(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    sc.on_exit(lambda: calls.append("second"))
    sc.close()
    assert calls == ["second"]  # the failure was reported, not fatal


def test_bench_shim_reexports_the_builders():
    import bench

    assert bench.build_halo is driver.build_halo
    assert bench.build_attn is driver.build_attn
    assert bench.metric_for is driver.metric_for
    assert bench.ALIAS_UNPACK is driver.ALIAS_UNPACK
