"""Measured host-noise floors (ISSUE 16; docs/observability.md "Causal
analysis"): deterministic probe math against a scripted clock, the
``host_noise`` block's shape, the re-probe-on-runs-test-failure
discipline, and the floor comparison verdicts (:func:`floors_differ`,
:func:`floor_vs_tail`).
"""

from tenzing_tpu.obs.noise import (
    NOISE_VERSION,
    floor_vs_tail,
    floors_differ,
    probe_host_noise,
    probe_hot_spin,
    probe_timer_wake,
    series_summary,
)


class ScriptedClock:
    """A fake ``perf_counter``/``sleep`` pair: sleep advances by the
    request plus a scripted overshoot, each clock() read costs a fixed
    tick — the probes become pure arithmetic."""

    def __init__(self, overshoots_us, tick_us=1.0):
        self.overshoots = list(overshoots_us)
        self.tick_us = tick_us
        self.t = 0.0
        self.i = 0

    def clock(self):
        self.t += self.tick_us / 1e6
        return self.t

    def sleep(self, secs):
        over = self.overshoots[self.i % len(self.overshoots)]
        self.i += 1
        self.t += secs + over / 1e6


def test_probe_timer_wake_deterministic_golden():
    c = ScriptedClock([10.0, 20.0, 30.0, 40.0])
    xs = probe_timer_wake(samples=4, sleep_us=100.0, clock=c.clock,
                          sleeper=c.sleep)
    # each sample: requested + scripted overshoot + one clock tick
    # (float-second arithmetic reintroduces ~1e-10us rounding)
    assert [round(x, 6) for x in xs] == [11.0, 21.0, 31.0, 41.0]


def test_probe_hot_spin_shape_and_overshoot_bound():
    c = ScriptedClock([], tick_us=5.0)
    xs = probe_hot_spin(samples=3, target_us=20.0, clock=c.clock)
    # ticks of 5us against a 20us deadline: first read past the
    # deadline overshoots by < one tick + alignment
    assert len(xs) == 3
    assert all(0.0 <= x <= 10.0 for x in xs)


def test_series_summary_shape():
    s = series_summary([1.0, 2.0, 3.0, 4.0])
    assert set(s) == {"count", "p50_us", "p99_us", "mean_us", "max_us",
                      "runs_z", "iid"}
    assert s["count"] == 4 and s["max_us"] == 4.0
    assert s["mean_us"] == 2.5
    assert isinstance(s["iid"], bool)


def test_probe_host_noise_block_shape():
    c = ScriptedClock([3.0, 7.0, 5.0, 9.0, 2.0, 8.0, 4.0, 6.0])
    block = probe_host_noise(samples=16, clock=c.clock, sleeper=c.sleep)
    assert block["version"] == NOISE_VERSION
    assert block["samples"] == 16
    assert block["timer_wake_us"]["count"] == 16
    assert block["hot_spin_us"]["count"] == 16
    assert block["attempts"] >= 1
    assert isinstance(block["host"], str) and block["host"]
    assert block["measured_at"] > 0


def test_probe_host_noise_reprobes_on_runs_failure():
    # a monotone overshoot ramp fails the runs test every pass: the
    # probe retries, records the last pass, and says so via attempts
    # + iid=False — a noisy floor measurement is visible, not hidden
    c = ScriptedClock([float(i) for i in range(32)])
    block = probe_host_noise(samples=32, retries=2, clock=c.clock,
                             sleeper=c.sleep)
    assert block["attempts"] == 3
    assert block["timer_wake_us"]["iid"] is False


def _block(wake_p99, spin_p99=2.0):
    return {"timer_wake_us": {"p99_us": wake_p99, "p50_us": wake_p99 / 2},
            "hot_spin_us": {"p99_us": spin_p99, "p50_us": spin_p99 / 2}}


def test_floors_differ_verdicts():
    # close floors: comparable
    assert floors_differ(_block(10.0), _block(15.0)) is None
    # 5x wake floor gap (either direction): incomparable, and the
    # reason names the probe
    r = floors_differ(_block(50.0), _block(10.0))
    assert r is not None and "timer-wake" in r
    assert floors_differ(_block(10.0), _block(50.0)) is not None
    # hot-spin gap alone is enough
    r = floors_differ(_block(10.0, spin_p99=40.0), _block(10.0))
    assert r is not None and "hot-spin" in r
    # sub-1us floors are clamped: clock-granularity jitter cannot
    # manufacture a "different host"
    assert floors_differ(_block(10.0, spin_p99=0.01),
                         _block(10.0, spin_p99=0.9)) is None
    # a missing block never claims a host difference
    assert floors_differ(None, _block(10.0)) is None
    assert floors_differ(_block(10.0), {}) is None


def test_floor_vs_tail_verdicts():
    v = floor_vs_tail(_block(26.0), 98.8)
    assert v["ratio"] == 3.8
    assert v["host_bound"] is True
    assert "host-bound" in v["line"]
    v = floor_vs_tail(_block(10.0), 500.0)
    assert v["host_bound"] is False and "serving-bound" in v["line"]
    assert floor_vs_tail(None, 100.0) is None
    assert floor_vs_tail(_block(10.0), None) is None
