"""Single-chip MoE dispatch/combine pipeline: DAG shape, naive/greedy
schedule construction, and numerics vs the dense routed evaluation
(models/moe_pipeline.py)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.moe_pipeline import (
    MoEPipeArgs,
    build_graph,
    greedy_overlap_order,
    host_buffer_names,
    make_pipe_buffers,
    naive_order,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences

SMALL = MoEPipeArgs(n_experts=4, tokens=32, d_model=8, d_ff=16, n_chunks=2)


def _executor(args, bufs, plat):
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names(args))
    return TraceExecutor(plat, jbufs)


class TestDagShape:
    def test_chunk_chains_are_independent(self):
        bufs, _want, cap = make_pipe_buffers(SMALL, seed=0)
        g = build_graph(SMALL, cap)
        by_name = {v.name(): v for v in g.vertices()}
        f0, p1 = by_name["ffn_0"], by_name["pack_1"]
        assert p1 not in g.succs(f0) and f0 not in g.succs(p1)

    def test_schedule_space_is_nontrivial(self):
        _bufs, _want, cap = make_pipe_buffers(SMALL, seed=0)
        plat = Platform.make_n_lanes(2)
        seqs = get_all_sequences(build_graph(SMALL, cap), plat, max_seqs=50)
        assert len(seqs) > 1


@pytest.mark.needs_pinned_host
class TestNumerics:
    def test_naive_matches_dense_routing(self):
        bufs, want, cap = make_pipe_buffers(SMALL, seed=1)
        plat = Platform.make_n_lanes(1)
        ex = _executor(SMALL, bufs, plat)
        out = ex.run(naive_order(SMALL, cap, plat))
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-3,
                                   atol=2e-5)

    def test_greedy_overlap_matches(self):
        bufs, want, cap = make_pipe_buffers(SMALL, seed=2)
        plat = Platform.make_n_lanes(2)
        ex = _executor(SMALL, bufs, plat)
        out = ex.run(greedy_overlap_order(SMALL, cap, plat))
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-3,
                                   atol=2e-5)

    def test_searched_schedules_match(self):
        bufs, want, cap = make_pipe_buffers(SMALL, seed=3)
        plat = Platform.make_n_lanes(2)
        seqs = get_all_sequences(build_graph(SMALL, cap), plat, max_seqs=4)
        assert len(seqs) >= 2
        ex = _executor(SMALL, bufs, plat)
        for s in seqs:
            out = ex.run(s.sequence)
            np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-3,
                                       atol=2e-5)

    def test_pallas_ffn_choice_matches(self):
        from tenzing_tpu.solve.dfs import enumerate_schedules

        args = MoEPipeArgs(n_experts=2, tokens=16, d_model=8, d_ff=16,
                           n_chunks=1)
        bufs, want, cap = make_pipe_buffers(args, seed=4)
        plat = Platform.make_n_lanes(1)
        seqs = enumerate_schedules(build_graph(args, cap, impl_choice=True),
                                   plat, max_seqs=16)
        names = [";".join(op.name() for op in s.sequence) for s in seqs]
        pallas = [s for s, n in zip(seqs, names) if ".pallas" in n]
        assert pallas
        ex = _executor(args, bufs, plat)
        out = ex.run(pallas[0].sequence)
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-3,
                                   atol=2e-5)


@pytest.mark.needs_pinned_host
class TestStagingPrecision:
    def test_bf16_chain_matches_within_bf16_tolerance(self):
        bufs, want, cap = make_pipe_buffers(SMALL, seed=6, staging="bf16")
        plat = Platform.make_n_lanes(2)
        jbufs = TraceExecutor.place_host_buffers(
            bufs, host_buffer_names(SMALL, staging="bf16"))
        ex = TraceExecutor(plat, jbufs)
        out = ex.run(greedy_overlap_order(SMALL, cap, plat, staging="bf16"))
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=4e-2,
                                   atol=4e-2)

    def test_choice_graph_offers_both_stagings(self):
        from tenzing_tpu.solve.dfs import enumerate_schedules

        args = MoEPipeArgs(n_experts=2, tokens=8, d_model=8, d_ff=16,
                           n_chunks=1)
        bufs, want, cap = make_pipe_buffers(args, seed=7, staging="choice")
        plat = Platform.make_n_lanes(1)
        seqs = enumerate_schedules(build_graph(args, cap, staging="choice"),
                                   plat, max_seqs=16)
        f32 = [s for s in seqs
               if any(op.name().startswith("pack_") for op in s.sequence)]
        bf16 = [s for s in seqs
                if any(op.name().startswith("pack16_") for op in s.sequence)]
        assert f32 and bf16
        jbufs = TraceExecutor.place_host_buffers(
            bufs, host_buffer_names(args, staging="choice"))
        ex = TraceExecutor(plat, jbufs)
        out32 = ex.run(f32[0].sequence)
        np.testing.assert_allclose(np.asarray(out32["Y"]), want, rtol=2e-3,
                                   atol=2e-5)
        out16 = ex.run(bf16[0].sequence)
        np.testing.assert_allclose(np.asarray(out16["Y"]), want, rtol=4e-2,
                                   atol=4e-2)


class TestRouting:
    def test_every_token_lands_in_one_slot(self):
        bufs, _want, cap = make_pipe_buffers(SMALL, seed=5)
        for c in range(SMALL.n_chunks):
            nz = (bufs[f"w_{c}"] > 0).sum()
            assert nz == SMALL.chunk_tokens
            assert bufs[f"idx_{c}"].shape == (SMALL.n_experts, cap)
