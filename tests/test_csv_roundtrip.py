"""Round-trip property test for the recorded-database wire format
(`result_row` / `split_fidelity` / CsvBenchmarker parse,
tenzing_tpu/bench/benchmarker.py): op payloads containing the ``|`` cell
delimiter, rows with and without ``fid=`` tags, and numpy-typed stats must
all survive dump -> parse byte-for-byte.  The corpus ingester
(learn/dataset.py) and the warm-start loader (bench/recorded.py) both trust
exactly this contract."""

import random

import numpy as np
import pytest

from tenzing_tpu.bench.benchmarker import (
    CSV_DELIM,
    BenchResult,
    CsvBenchmarker,
    result_row,
    split_fidelity,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp, Finish, Start
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.core.sequence import Sequence, is_equivalent


class POp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


# names exercising the delimiter escape and JSON string escapes: the '|'
# cell delimiter, repeated delimiters, quotes, backslashes, unicode
NASTY_NAMES = [
    "plain",
    "a|b",
    "a|b|c||d",
    'quo"te',
    "back\\slash",
    "pipe|and\"quote\\mix",
    "unicode|π∆",
]


def _world(names):
    g = Graph()
    ops = []
    for n in names:
        op = POp(n)
        ops.append(op)
        g.start_then(op)
        g.then_finish(op)
    return g, ops


def _res(vals):
    p01, p10, p50, p90, p99, sd = vals
    return BenchResult(pct01=p01, pct10=p10, pct50=p50, pct90=p90,
                       pct99=p99, stddev=sd)


def test_delimiter_in_fidelity_tag_rejected():
    """The fid cell has no escaping: a tag containing the delimiter would
    truncate silently and shed a bogus op cell — dump refuses it."""
    g, ops = _world(["k"])
    seq = Sequence([ops[0].bind(Lane(0))])
    with pytest.raises(ValueError, match="delimiter"):
        result_row(0, _res([1, 2, 3, 4, 5, 0]), seq, fidelity="fid|tricky")


@pytest.mark.parametrize("fidelity", [None, "screen"])
def test_roundtrip_nasty_payloads(fidelity):
    g, ops = _world(NASTY_NAMES)
    seq = Sequence([Start()] + [op.bind(Lane(i % 2))
                                for i, op in enumerate(ops)] + [Finish()])
    res = _res([1e-5, 2e-5, 3e-5, 4e-5, 5e-5, 1e-6])
    row = result_row(7, res, seq, fidelity=fidelity)
    assert "\n" not in row
    cells = row.split(CSV_DELIM)
    fid, ops_at = split_fidelity(cells)
    assert fid == (fidelity if fidelity is not None else "full")
    assert ops_at == (7 if fidelity is None else 8)
    db = CsvBenchmarker([row], g)
    assert len(db.entries) == 1
    got_seq, got_res = db.entries[0]
    assert is_equivalent(got_seq, seq)
    assert db.fidelities == [fid]
    for f in ("pct01", "pct10", "pct50", "pct90", "pct99", "stddev"):
        assert getattr(got_res, f) == getattr(res, f)
    # only full-fidelity rows answer queries (the shadowing rule)
    if fid == "full":
        assert db.benchmark(seq).pct50 == res.pct50
    else:
        with pytest.raises(KeyError):
            db.benchmark(seq)


def test_roundtrip_property_random_rows():
    """Seeded property sweep: random name soups (heavy on the delimiter),
    random float stats (including numpy scalars and exotic magnitudes),
    random fid tags — parse must reproduce the row exactly."""
    rng = random.Random(1234)
    alphabet = 'ab|"\\{}[]:,π \t'
    for trial in range(40):
        names = []
        while len(names) < rng.randint(1, 5):
            n = "".join(rng.choice(alphabet)
                        for _ in range(rng.randint(1, 12)))
            if n not in names:
                names.append(n)
        g, ops = _world(names)
        seq = Sequence([op.bind(Lane(rng.randrange(3))) for op in ops])
        vals = [rng.choice([1.0, 1e-30, 1e30, 3.141592653589793e-05,
                            float(np.float64(rng.random()))])
                for _ in range(6)]
        # numpy-typed results must round-trip too (repr of np.float64 would
        # not parse back without the float() cast in result_row)
        if trial % 2:
            vals = [np.float64(v) for v in vals]
        fidelity = rng.choice([None, "screen", "s2"])
        row = result_row(trial, _res(vals), seq, fidelity=fidelity)
        fid, _ = split_fidelity(row.split(CSV_DELIM))
        assert fid == (fidelity if fidelity is not None else "full")
        db = CsvBenchmarker([row], g)
        assert len(db.entries) == 1, (names, fidelity)
        got_seq, got_res = db.entries[0]
        assert is_equivalent(got_seq, seq), names
        assert [getattr(got_res, f) for f in
                ("pct01", "pct10", "pct50", "pct90", "pct99", "stddev")
                ] == [float(v) for v in vals]


def test_legacy_rows_without_fid_cell_parse_as_full():
    g, ops = _world(["k0", "k1"])
    seq = Sequence([op.bind(Lane(0)) for op in ops])
    row = result_row(0, _res([1, 2, 3, 4, 5, 0]), seq)
    cells = row.split(CSV_DELIM)
    assert split_fidelity(cells) == ("full", 7)
    # an op json cell can never be mistaken for a fid tag: it starts with '{'
    assert cells[7].startswith("{")
