"""Cross-checks of the native (C++) search core against the Python reference
implementation: decision enumeration, exhaustive dedup'd enumeration, and
rollouts must agree exactly (same semantics, same order).

The Python side is the semantic reference (it carries the file:line provenance
to sandialabs/tenzing); the native side is the hot path.  Disagreement here is a
bug in one of them.
"""

import random

import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import NoOp
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sequence import get_equivalence as seq_equiv
from tenzing_tpu.core.state import State
from tenzing_tpu.core.event_synchronizer import EventSynchronizer
from tenzing_tpu.core.operation import BoundDeviceOp
from tenzing_tpu.models.spmv import SpMVCompound
from tenzing_tpu.native import bridge

pytestmark = pytest.mark.skipif(
    not bridge.native_available(), reason="native library unavailable"
)


class Dev(
    __import__("tenzing_tpu.core.operation", fromlist=["DeviceOp"]).DeviceOp
):
    """Minimal device op (the test_gpu_graph.cu KernelOp analog)."""

    def apply(self, bufs, ctx):  # pragma: no cover - never traced here
        return {}


def host_chain_graph():
    g = Graph()
    a, b = NoOp("a"), NoOp("b")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    return g


def device_diamond_graph():
    """start -> {da, db} -> dc -> finish, all device ops."""
    g = Graph()
    da, db, dc = Dev("da"), Dev("db"), Dev("dc")
    g.start_then(da)
    g.start_then(db)
    g.then(da, dc)
    g.then(db, dc)
    g.then_finish(dc)
    return g


def mixed_graph():
    """Device ops feeding a host op (device->host sync case)."""
    g = Graph()
    d, h = Dev("d"), NoOp("h")
    g.start_then(d)
    g.then(d, h)
    g.then_finish(h)
    return g


def spmv_graph():
    return SpMVCompound().graph()


GRAPHS = [host_chain_graph, device_diamond_graph, mixed_graph, spmv_graph]


def djson(d):
    return d.to_json()


@pytest.mark.parametrize("make", GRAPHS)
@pytest.mark.parametrize("n_lanes", [1, 2])
def test_decisions_agree_along_random_walks(make, n_lanes):
    plat = Platform.make_n_lanes(n_lanes)
    for seed in range(5):
        rng = random.Random(seed)
        st = State(make())
        while not st.is_terminal():
            py = st.get_decisions(plat)
            nat = bridge.try_decisions(st, plat)
            assert nat is not None
            assert [djson(d) for d in nat] == [djson(d) for d in py]
            st = st.apply(rng.choice(py))


@pytest.mark.parametrize("make", [host_chain_graph, device_diamond_graph, mixed_graph])
@pytest.mark.parametrize("n_lanes", [1, 2])
def test_enumeration_matches_python(make, n_lanes):
    from tenzing_tpu.solve.dfs import _dedup_terminal_states, get_all_sequences

    g = make()
    plat = Platform.make_n_lanes(n_lanes)
    py = _dedup_terminal_states(get_all_sequences(g, plat, max_seqs=100000))
    nat = bridge.try_enumerate(g, plat, max_seqs=100000)
    assert nat is not None
    assert len(nat) == len(py)
    for a, b in zip(nat, py):
        assert [op.to_json() for op in a.sequence] == [op.to_json() for op in b.sequence]


@pytest.mark.parametrize("make", [host_chain_graph, device_diamond_graph, mixed_graph])
@pytest.mark.parametrize("cap", [1, 3, 7])
def test_capped_enumeration_matches_python(make, cap):
    """Same budget -> same terminal set with TENZING_TPU_NATIVE=0 and =1: both
    paths count deduplicated terminals against the cap, in the same order
    (VERDICT r1 item 9)."""
    from tenzing_tpu.solve.dfs import get_unique_sequences

    g = make()
    plat = Platform.make_n_lanes(2)
    py = get_unique_sequences(g, plat, max_seqs=cap)
    nat = bridge.try_enumerate(g, plat, max_seqs=cap)
    assert nat is not None
    assert len(nat) == len(py) <= cap
    for a, b in zip(nat, py):
        assert [op.to_json() for op in a.sequence] == [op.to_json() for op in b.sequence]


def test_enumeration_spmv_counts():
    """The SpMV inner DAG is too big for the pairwise-python dedup to be quick,
    but counts must match on 1 lane; on 2 lanes native must produce a
    bijection-unique set."""
    g = spmv_graph()
    plat1 = Platform.make_n_lanes(1)
    from tenzing_tpu.solve.dfs import _dedup_terminal_states, get_all_sequences

    py = _dedup_terminal_states(get_all_sequences(g, plat1, max_seqs=100000))
    nat = bridge.try_enumerate(g, plat1, max_seqs=100000)
    assert len(nat) == len(py)

    nat2 = bridge.try_enumerate(g, Platform.make_n_lanes(2), max_seqs=2000)
    # no two survivors may be sequence-equivalent under lane/event bijection
    for i in range(min(30, len(nat2))):
        for j in range(i + 1, min(30, len(nat2))):
            assert not seq_equiv(nat2[i].sequence, nat2[j].sequence)


def _assert_legal_complete(graph, seq: Sequence):
    """Replay a schedule: every non-sync op must be synced at its position, and
    every graph vertex must execute exactly once."""
    bound = {}
    for op in seq:
        if isinstance(op, BoundDeviceOp):
            bound[op.unbound()] = op.lane()
    g = graph.apply_lane_assignment(bound) if bound else graph
    seen = []
    for op in seq:
        prefix = Sequence(seen)
        assert EventSynchronizer.is_synced(g, prefix, op), (
            f"op {op!r} unsynced at position {len(seen)}"
        )
        seen.append(op)
    executed_keys = {op.eq_key() for op in seq}
    for v in g.vertices():
        assert v.eq_key() in executed_keys


@pytest.mark.parametrize("make", GRAPHS)
def test_rollout_produces_legal_schedules(make):
    g = make()
    plat = Platform.make_n_lanes(2)
    for seed in range(8):
        seq = bridge.try_rollout(State(g), plat, seed)
        assert seq is not None
        _assert_legal_complete(g, seq)


def test_rollout_varies_with_seed():
    g = spmv_graph()
    plat = Platform.make_n_lanes(2)
    seqs = {tuple(op.desc() for op in bridge.try_rollout(State(g), plat, s)) for s in range(16)}
    assert len(seqs) > 1


def test_disabled_by_env(monkeypatch):
    monkeypatch.setenv("TENZING_TPU_NATIVE", "0")
    assert bridge.try_decisions(State(host_chain_graph()), Platform.make_n_lanes(1)) is None


def test_enumerate_schedules_resolves_compounds():
    """enumerate_schedules pre-expands compound ops (structural closure) and
    must match the Python path that explores ExpandOp as a decision."""
    from tenzing_tpu.solve.dfs import (
        _dedup_terminal_states,
        enumerate_schedules,
        get_all_sequences,
    )

    g = Graph()
    c = SpMVCompound()
    g.start_then(c)
    g.then_finish(c)
    plat = Platform.make_n_lanes(1)
    py = _dedup_terminal_states(get_all_sequences(g, plat, 100000))
    nat = enumerate_schedules(g, plat, 100000)
    assert len(nat) == len(py)
    for a, b in zip(nat, py):
        for x in a.sequence:
            _ = x.to_json()
    # two lanes: full deduped space of the spmv DAG
    assert len(enumerate_schedules(g, Platform.make_n_lanes(2), 100000)) == 96


def test_enumerate_honors_pinned_lane_bindings():
    """A graph whose device ops were pre-bound by the caller must keep those
    lanes on the native path, matching the Python fallback exactly."""
    from tenzing_tpu.core.resources import Lane
    from tenzing_tpu.solve.dfs import _dedup_terminal_states, get_all_sequences

    g = device_diamond_graph()
    dops = g.device_vertices()
    pinned = g.apply_lane_assignment({dops[0]: Lane(1)})  # da pinned to lane 1
    plat = Platform.make_n_lanes(2)
    py = _dedup_terminal_states(get_all_sequences(pinned, plat, max_seqs=100000))
    nat = bridge.try_enumerate(pinned, plat, max_seqs=100000)
    assert nat is not None
    assert len(nat) == len(py)
    for a, b in zip(nat, py):
        assert [op.to_json() for op in a.sequence] == [op.to_json() for op in b.sequence]
    for st in nat:
        for op in st.sequence:
            if isinstance(op, BoundDeviceOp) and op.name() == "da":
                assert op.lane().id == 1
