"""Segmented-store acceptance (ISSUE 11; docs/serving.md "Segmented
store"): backend parity with the monolithic store, flush cost bound to
the dirty set, every corruption path recovered to a superset and never
fatal (truncated segment, bit-flipped record, torn manifest,
mid-compaction SIGKILL), compaction crash-consistency + lease
exclusivity, and the report CLI strictly read-only against a damaged
tree.
"""

import hashlib
import json
import os
import subprocess
import sys
import threading

import pytest

from tenzing_tpu.bench.driver import DriverRequest, graph_for
from tenzing_tpu.serve.fingerprint import fingerprint_of
from tenzing_tpu.serve.lease import LeaseFile
from tenzing_tpu.serve.segments import (
    Compactor,
    SegmentedStore,
    record_digest,
    segment_bucket_of,
)
from tenzing_tpu.serve.store import (
    RECORD_SCHEMA,
    ScheduleStore,
    merge_records,
    open_store,
)


@pytest.fixture(scope="module")
def spmv():
    """(graph, fingerprints, sequences) — the same neighborhood the
    monolithic store tests drive (tests/test_serve_store.py)."""
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State

    req = DriverRequest(workload="spmv", m=512)
    g, _ = graph_for(req)

    def drive(picks, n_lanes=2):
        plat = Platform.make_n_lanes(n_lanes)
        st = State(g)
        i = 0
        while not st.is_terminal():
            ds = st.get_decisions(plat)
            st = st.apply(ds[picks[i % len(picks)] % len(ds)])
            i += 1
        return st.sequence

    fps = {
        "a": fingerprint_of(req),
        "b": fingerprint_of(DriverRequest(workload="spmv", m=500)),
        # a different bucket entirely (m=100000 buckets to 131072)
        "c": fingerprint_of(DriverRequest(workload="spmv", m=100000)),
    }
    seqs = [drive(p) for p in ([0], [1, 2, 0], [2, 1, 0], [1, 0, 2])]
    return g, fps, seqs


def _seg_files(store_dir):
    segdir = os.path.join(store_dir, "segments")
    if not os.path.isdir(segdir):
        return []
    return sorted(n for n in os.listdir(segdir)
                  if n.startswith("seg-") and n.endswith(".jsonl"))


def _records_doc(store):
    return json.dumps(sorted(json.dumps(r, sort_keys=True)
                             for r in store.records()))


# -- parity + dispatch -------------------------------------------------------

def test_open_store_dispatch(tmp_path):
    assert isinstance(open_store(str(tmp_path / "s.json")), ScheduleStore)
    seg = open_store(str(tmp_path / "segdir"))
    assert isinstance(seg, SegmentedStore)
    assert not isinstance(open_store(str(tmp_path / "s.json")),
                          SegmentedStore)


def test_roundtrip_parity_with_monolithic(tmp_path, spmv):
    """Same adds into both backends -> identical record sets, identical
    best answers: the resolver cannot tell them apart except by speed."""
    _, fps, seqs = spmv
    mono = ScheduleStore(str(tmp_path / "mono.json"), tenant="t")
    seg = SegmentedStore(str(tmp_path / "seg"), tenant="t")
    for s in (mono, seg):
        s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0,
              verified=True)
        s.add(fps["a"], seqs[2], pct50_us=11.0, vs_naive=1.8)
        s.add(fps["b"], seqs[3], pct50_us=9.0, vs_naive=2.2)
        s.flush()
    seg2 = SegmentedStore(str(tmp_path / "seg"))
    mono2 = ScheduleStore(str(tmp_path / "mono.json"))
    assert _records_doc(seg2) == _records_doc(mono2)
    assert seg2.best(fps["a"].exact_digest)["vs_naive"] == 2.0
    assert seg2.best(fps["a"].exact_digest)["verified_at_admission"] is True


def test_flush_cost_is_dirty_records_not_corpus(tmp_path, spmv):
    """The tentpole economics: a flush writes one segment per DIRTY
    bucket containing only the dirty records — corpus size never
    re-serializes."""
    _, fps, seqs = spmv
    s = SegmentedStore(str(tmp_path / "seg"))
    s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    s.add(fps["a"], seqs[2], pct50_us=11.0, vs_naive=1.8)
    s.flush()
    assert len(_seg_files(s.dir)) == 1
    # no dirt -> no new segment
    s.flush()
    assert len(_seg_files(s.dir)) == 1
    # one new record -> exactly one new single-record segment
    s.add(fps["a"], seqs[3], pct50_us=9.0, vs_naive=2.5)
    s.flush()
    files = _seg_files(s.dir)
    assert len(files) == 2
    new = sorted(files)[-1]
    with open(os.path.join(s.dir, "segments", new)) as f:
        header = json.loads(f.readline())
    assert header["n_records"] == 1
    # the full corpus survives on reload (distinct by schedule key:
    # two of the driven sequences may canonicalize to one slot)
    from tenzing_tpu.serve.fingerprint import schedule_key

    distinct = len({schedule_key(q) for q in (seqs[1], seqs[2], seqs[3])})
    assert len(SegmentedStore(s.dir)) == distinct


def test_two_writers_concurrent_flush(tmp_path, spmv):
    """Two stores flushing simultaneously: the manifest read-modify-write
    serializes under the flock+backoff, and segments are per-writer
    files — both land, nothing is lost."""
    _, fps, seqs = spmv
    path = str(tmp_path / "seg")
    a = SegmentedStore(path, tenant="w-a")
    b = SegmentedStore(path, tenant="w-b")
    a.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    b.add(fps["b"], seqs[2], pct50_us=12.0, vs_naive=1.5)
    barrier = threading.Barrier(2)
    errors = []

    def go(store):
        try:
            barrier.wait()
            store.flush()
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    ts = [threading.Thread(target=go, args=(s,)) for s in (a, b)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    merged = SegmentedStore(path)
    assert len(merged) == 2
    assert merged.orphan_segments == []  # both flushes indexed


# -- corruption paths --------------------------------------------------------

def _warmed(tmp_path, spmv, name="seg"):
    _, fps, seqs = spmv
    s = SegmentedStore(str(tmp_path / name))
    s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0, verified=True)
    s.add(fps["a"], seqs[2], pct50_us=11.0, vs_naive=1.8, verified=True)
    s.add(fps["b"], seqs[3], pct50_us=9.0, vs_naive=2.2, verified=True)
    s.flush()
    return s


def test_truncated_segment_salvages_prefix_and_quarantines(tmp_path, spmv):
    s = _warmed(tmp_path, spmv)
    (name,) = _seg_files(s.dir)
    path = os.path.join(s.dir, "segments", name)
    text = open(path).read()
    # cut mid-way through the LAST record line: a torn append
    open(path, "w").write(text[:int(len(text) * 0.8)])
    notes = []
    loaded = SegmentedStore(s.dir, log=notes.append)
    assert len(loaded) == 2  # the checksum-valid prefix survives
    assert loaded.salvaged == 2
    assert loaded.quarantined_segments == [name]
    corpses = [n for n in os.listdir(os.path.join(s.dir, "segments"))
               if ".corrupt-" in n]
    assert len(corpses) == 1
    assert any("quarantined damaged segment" in n for n in notes)
    # salvage is re-persisted by the next flush: a fresh load needs no
    # damaged file to see the records
    loaded.flush()
    final = SegmentedStore(s.dir)
    assert len(final) == 2 and final.quarantined_segments == []


def test_bitflipped_record_checksum_catches_it(tmp_path, spmv):
    s = _warmed(tmp_path, spmv)
    (name,) = _seg_files(s.dir)
    path = os.path.join(s.dir, "segments", name)
    lines = open(path).read().splitlines()
    # flip one byte inside the middle record's payload (line stays JSON:
    # we alter a digit of pct50_us, the checksum must catch it)
    assert '"pct50_us": 11.0' in lines[2] or '"pct50_us":11.0' in lines[2]
    lines[2] = lines[2].replace("11.0", "71.0", 1)
    open(path, "w").write("\n".join(lines) + "\n")
    loaded = SegmentedStore(s.dir, log=lambda m: None)
    assert loaded.checksum_failed == 1
    assert len(loaded) == 2  # the flipped record is dropped, rest served
    assert loaded.quarantined_segments == [name]  # rot never lingers


def test_torn_manifest_recovers_by_scan(tmp_path, spmv):
    s = _warmed(tmp_path, spmv)
    man = os.path.join(s.dir, "manifest.json")
    open(man, "w").write('{"version": 1, "segments": {tor')
    notes = []
    loaded = SegmentedStore(s.dir, log=notes.append)
    assert len(loaded) == 3  # the scan is ground truth: zero loss
    assert not os.path.exists(man)  # quarantined aside
    assert [n for n in os.listdir(s.dir) if "manifest.json.corrupt-" in n]
    assert any("recovering from segment scan" in n for n in notes)
    # the segments are now orphans; a compaction adopts them back
    summary = Compactor(s.dir, log=lambda m: None).run()
    assert summary["orphans_adopted"] + summary["buckets_compacted"] > 0
    again = SegmentedStore(s.dir)
    assert len(again) == 3 and again.orphan_segments == []


def test_readonly_load_reports_damage_without_touching(tmp_path, spmv):
    """The report CLI's contract: quarantine_corrupt=False must leave a
    damaged tree byte-for-byte intact while still reporting records."""
    s = _warmed(tmp_path, spmv)
    (name,) = _seg_files(s.dir)
    seg_path = os.path.join(s.dir, "segments", name)
    text = open(seg_path).read()
    open(seg_path, "w").write(text[:int(len(text) * 0.8)])
    open(os.path.join(s.dir, "manifest.json"), "w").write("{torn")

    def tree(d):
        out = {}
        for root, _, files in os.walk(d):
            for f in files:
                p = os.path.join(root, f)
                out[os.path.relpath(p, d)] = hashlib.sha256(
                    open(p, "rb").read()).hexdigest()
        return out

    before = tree(s.dir)
    notes = []
    ro = SegmentedStore(s.dir, log=notes.append, quarantine_corrupt=False)
    assert len(ro) == 2  # salvage in memory only
    assert tree(s.dir) == before  # NOTHING renamed, created, or rewritten
    # ...and the actual report CLI section stays read-only too
    from tenzing_tpu.obs.report import store_section

    lines = store_section([s.dir])
    assert tree(s.dir) == before
    assert any("segments" in ln for ln in lines)


# -- compaction --------------------------------------------------------------

def test_compactor_merges_reclaims_and_ledgers(tmp_path, spmv):
    _, fps, seqs = spmv
    s = SegmentedStore(str(tmp_path / "seg"))
    for i, (fp, seq, pct, vs) in enumerate([
            (fps["a"], seqs[1], 10.0, 2.0),
            (fps["a"], seqs[2], 11.0, 1.8),
            (fps["b"], seqs[3], 9.0, 2.2)]):
        s.add(fp, seq, pct50_us=pct, vs_naive=vs)
        s.flush()  # one segment per flush: a multi-segment bucket
    assert len(_seg_files(s.dir)) == 3
    before = _records_doc(SegmentedStore(s.dir))
    summary = Compactor(s.dir, log=lambda m: None).run()
    assert summary["buckets_compacted"] == 1  # a+b share one bucket
    assert summary["segments_reclaimed"] == 3
    assert summary["skipped"] is None
    files = _seg_files(s.dir)
    assert len(files) == 1
    after = SegmentedStore(s.dir)
    assert _records_doc(after) == before  # byte-identical record set
    ledger = after.manifest_doc["compactions"]
    assert ledger and ledger[-1]["output"] == files[0]
    assert len(ledger[-1]["inputs"]) == 3


def test_compactor_lease_excludes_rivals(tmp_path, spmv):
    s = _warmed(tmp_path, spmv)
    rival = LeaseFile(os.path.join(s.dir, "compact.lease"), "rival",
                      ttl_secs=300.0)
    assert rival.claim() is not None
    summary = Compactor(s.dir, log=lambda m: None).run()
    assert summary["skipped"] == "lease-held"
    assert summary["buckets_compacted"] == 0
    rival.release()
    assert Compactor(s.dir, log=lambda m: None).run()["skipped"] is None


def _compact_cli(store_dir, *extra):
    return subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.serve", "compact",
         "--store", store_dir, "--lease-ttl", "0.2", *extra],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


@pytest.mark.parametrize("window", ["segment", "manifest"])
def test_mid_compaction_sigkill_recovers_to_superset(tmp_path, spmv,
                                                     window):
    """kill -9 in either publish window (after the merged segment,
    before the manifest; after the manifest, before reclaim): every
    pre-kill record survives with a valid checksum, and the next
    compaction converges the tree."""
    _, fps, seqs = spmv
    s = SegmentedStore(str(tmp_path / f"seg-{window}"))
    for fp, seq, pct, vs in [(fps["a"], seqs[1], 10.0, 2.0),
                             (fps["a"], seqs[2], 11.0, 1.8),
                             (fps["c"], seqs[3], 9.0, 2.2)]:
        s.add(fp, seq, pct50_us=pct, vs_naive=vs)
        s.flush()
    before = _records_doc(SegmentedStore(s.dir))
    r = _compact_cli(s.dir, "--crash-after", window)
    assert r.returncode == -9, (r.returncode, r.stderr[-500:])
    # recovery: a plain load sees a SUPERSET (here: exactly the pre-kill
    # records — duplicates merge idempotently), all checksums valid
    notes = []
    crashed = SegmentedStore(s.dir, log=notes.append)
    assert _records_doc(crashed) == before
    assert crashed.checksum_failed == 0
    assert crashed.quarantined_segments == []
    # the killed compactor's lease is left behind; a successor reclaims
    # it (TTL-expired) and finishes the job
    import time

    time.sleep(0.25)
    r2 = _compact_cli(s.dir)
    assert r2.returncode == 0, r2.stderr[-500:]
    final = SegmentedStore(s.dir)
    assert _records_doc(final) == before
    assert final.orphan_segments == []
    # converged: one segment per bucket
    buckets = {segment_bucket_of(n) for n in _seg_files(s.dir)}
    assert len(_seg_files(s.dir)) == len(buckets) == 2


def test_compactor_never_reclaims_unseen_rival_segment(tmp_path, spmv,
                                                       monkeypatch):
    """A segment published by a live writer AFTER the compactor loaded
    the store must survive the pass with its record intact: the merge
    and reclaim sets are the LOADED segments, never a fresh disk scan
    (a rescan would unlink the rival's segment without its records ever
    entering the merged output — permanent loss, not a superset)."""
    import tenzing_tpu.serve.segments as segments

    _, fps, seqs = spmv
    path = str(tmp_path / "seg")
    s = SegmentedStore(path)
    s.add(fps["a"], seqs[1], pct50_us=10.0, vs_naive=2.0)
    s.flush()
    s.add(fps["a"], seqs[2], pct50_us=11.0, vs_naive=1.8)
    s.flush()
    # emulate the race deterministically: hook the compactor-store's
    # flush (the first thing run() does after its load) to let a rival
    # land a same-bucket segment inside the window
    real_flush = segments.SegmentedStore.flush
    fired = {}

    def flush_with_rival(self):
        if not fired and self.tenant == "compactor":
            fired["x"] = True
            rival = SegmentedStore(path, tenant="rival")
            rival.add(fps["b"], seqs[3], pct50_us=9.0, vs_naive=2.2)
            real_flush(rival)
        return real_flush(self)

    monkeypatch.setattr(segments.SegmentedStore, "flush",
                        flush_with_rival)
    summary = Compactor(path, log=lambda m: None).run()
    assert summary["buckets_compacted"] == 1
    final = SegmentedStore(path)
    assert final.best(fps["b"].exact_digest) is not None, \
        "rival's mid-pass record was reclaimed without being merged"
    assert final.best(fps["a"].exact_digest)["vs_naive"] == 2.0


# -- merge algebra of the admission stamp ------------------------------------

def test_admission_stamp_merges_sticky_both_orders():
    base = {"schema": RECORD_SCHEMA, "exact": "e", "bucket": "b",
            "key": "k", "ops": [], "workload": "spmv", "vs_naive": 2.0,
            "pct50_us": 10.0, "sources": [], "flags": {}}
    stamped = dict(base, verified_at_admission=True)
    for m in (merge_records(stamped, dict(base)),
              merge_records(dict(base), stamped)):
        assert m["verified_at_admission"] is True
    plain = merge_records(dict(base), dict(base))
    assert "verified_at_admission" not in plain


def test_record_digest_canonical():
    a = {"x": 1, "y": [1, 2]}
    assert record_digest({"y": [1, 2], "x": 1}) == record_digest(a)
    assert record_digest({"x": 2, "y": [1, 2]}) != record_digest(a)


# -- the shared lease protocol ----------------------------------------------

def test_lease_file_protocol(tmp_path):
    path = str(tmp_path / "x.lease")
    a = LeaseFile(path, "a", ttl_secs=300.0)
    b = LeaseFile(path, "b", ttl_secs=300.0)
    info = a.claim()
    assert info is not None and info.reclaimed is False
    assert b.claim() is None  # live rival
    assert a.owns() and a.renew()
    # expire it: b reclaims, a's renew detects the loss by nonce
    past = os.path.getmtime(path) - 1000
    os.utime(path, (past, past))
    info_b = b.claim()
    assert info_b is not None and info_b.reclaimed is True
    assert info_b.prev_owner == "a"
    assert a.renew() is False
    # a's release must not delete b's live lease
    a.release()
    assert os.path.exists(path) and b.owns()
    assert b.release() is True
    assert not os.path.exists(path)
