"""Graph-anchored schedule serdes (reference operation_serdes.cpp:14-76)."""

import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    ChoiceOp,
    CompoundOp,
    DeviceOp,
    NoOp,
)
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.serdes import (
    sequence_from_json_str,
    sequence_to_json_str,
)
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, WaitEvent


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


def test_roundtrip_with_syncs_and_bindings():
    g = Graph()
    a, b = KOp("a"), KOp("b")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    seq = Sequence(
        [
            g.start(),
            a.bind(Lane(0)),
            EventRecord(Lane(0), Event(0)),
            WaitEvent(Lane(1), Event(0)),
            b.bind(Lane(1)),
            EventRecord(Lane(1), Event(1)),
            EventSync(Event(1)),
            g.finish(),
        ]
    )
    s = sequence_to_json_str(seq)
    out = sequence_from_json_str(s, g)
    assert len(out) == len(seq)
    assert out.desc() == seq.desc()
    # device ops re-materialized as graph-anchored bound ops
    assert isinstance(out[1], BoundDeviceOp) and out[1].lane() == Lane(0)
    assert out[1].unbound() is a  # the local graph's own op object


def test_deserialize_descends_into_compound():
    class Pair(CompoundOp):
        def graph(self):
            ig = Graph()
            x = KOp("x")
            ig.start_then(x)
            ig.then_finish(x)
            return ig

    g = Graph()
    g.start_then(Pair("pair"))
    g.then_finish(Pair("pair"))
    out = sequence_from_json_str('[{"kind": "device", "name": "x", "lane": 1}]', g)
    assert isinstance(out[0], BoundDeviceOp) and out[0].name() == "x"


def test_deserialize_descends_into_choices():
    class Variant(ChoiceOp):
        def choices(self):
            return [KOp("fast"), KOp("slow")]

    g = Graph()
    g.start_then(Variant("v"))
    g.then_finish(Variant("v"))
    out = sequence_from_json_str('[{"kind": "device", "name": "slow", "lane": 0}]', g)
    assert out[0].name() == "slow"


def test_unknown_op_raises():
    g = Graph()
    with pytest.raises(KeyError):
        sequence_from_json_str('[{"kind": "device", "name": "ghost"}]', g)
