"""Graph-anchored schedule serdes (reference operation_serdes.cpp:14-76)."""

import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    ChoiceOp,
    CompoundOp,
    DeviceOp,
    NoOp,
)
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.serdes import (
    sequence_from_json_str,
    sequence_to_json_str,
)
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, WaitEvent


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


def test_roundtrip_with_syncs_and_bindings():
    g = Graph()
    a, b = KOp("a"), KOp("b")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    seq = Sequence(
        [
            g.start(),
            a.bind(Lane(0)),
            EventRecord(Lane(0), Event(0)),
            WaitEvent(Lane(1), Event(0)),
            b.bind(Lane(1)),
            EventRecord(Lane(1), Event(1)),
            EventSync(Event(1)),
            g.finish(),
        ]
    )
    s = sequence_to_json_str(seq)
    out = sequence_from_json_str(s, g)
    assert len(out) == len(seq)
    assert out.desc() == seq.desc()
    # device ops re-materialized as graph-anchored bound ops
    assert isinstance(out[1], BoundDeviceOp) and out[1].lane() == Lane(0)
    assert out[1].unbound() is a  # the local graph's own op object


def test_deserialize_descends_into_compound():
    class Pair(CompoundOp):
        def graph(self):
            ig = Graph()
            x = KOp("x")
            ig.start_then(x)
            ig.then_finish(x)
            return ig

    g = Graph()
    g.start_then(Pair("pair"))
    g.then_finish(Pair("pair"))
    out = sequence_from_json_str('[{"kind": "device", "name": "x", "lane": 1}]', g)
    assert isinstance(out[0], BoundDeviceOp) and out[0].name() == "x"


def test_deserialize_descends_into_choices():
    class Variant(ChoiceOp):
        def choices(self):
            return [KOp("fast"), KOp("slow")]

    g = Graph()
    g.start_then(Variant("v"))
    g.then_finish(Variant("v"))
    out = sequence_from_json_str('[{"kind": "device", "name": "slow", "lane": 0}]', g)
    assert out[0].name() == "slow"


def test_deserialize_descends_nested_choice_in_choice_alternative():
    """A ChoiceOp nested deeper inside a choice alternative (directly, or via
    an alternative's compound sub-graph) must resolve the same as a top-level
    one — reference operation_serdes.cpp:14-56 recurses uniformly."""

    class Inner(ChoiceOp):
        def choices(self):
            return [KOp("deep_fast"), KOp("deep_slow")]

    class Wrap(CompoundOp):
        def graph(self):
            ig = Graph()
            inner = Inner("inner")
            ig.start_then(inner)
            ig.then_finish(inner)
            return ig

    class Outer(ChoiceOp):
        def choices(self):
            # alternative 0: a ChoiceOp directly; alternative 1: a compound
            # whose sub-graph holds another ChoiceOp
            return [Inner("direct_inner"), Wrap("wrap")]

    g = Graph()
    g.start_then(Outer("outer"))
    g.then_finish(Outer("outer"))
    for name in ("deep_fast", "deep_slow"):
        out = sequence_from_json_str(
            '[{"kind": "device", "name": "%s", "lane": 2}]' % name, g
        )
        assert isinstance(out[0], BoundDeviceOp) and out[0].name() == name
        assert out[0].lane() == Lane(2)


def test_deserialize_random_nested_structures():
    """Generative: random compound/choice nestings up to depth 4; every leaf
    device op anywhere in the structure must anchor by name."""
    import random

    rng = random.Random(20260731)

    def build(depth, counter, leaves):
        roll = rng.random()
        if depth >= 4 or roll < 0.4:
            op = KOp("leaf%d" % counter[0])
            counter[0] += 1
            leaves.append(op.name())
            return op
        if roll < 0.7:
            kids = [build(depth + 1, counter, leaves) for _ in range(rng.randint(1, 3))]

            class C(ChoiceOp):
                def __init__(self, name, ks):
                    super().__init__(name)
                    self._ks = ks

                def choices(self):
                    return self._ks

            counter[0] += 1
            return C("choice%d" % counter[0], kids)
        kids = [build(depth + 1, counter, leaves) for _ in range(rng.randint(1, 3))]

        class P(CompoundOp):
            def __init__(self, name, ks):
                super().__init__(name)
                self._ks = ks

            def graph(self):
                ig = Graph()
                prev = None
                for k in self._ks:
                    if prev is None:
                        ig.start_then(k)
                    else:
                        ig.then(prev, k)
                    prev = k
                ig.then_finish(prev)
                return ig

        counter[0] += 1
        return P("comp%d" % counter[0], kids)

    for trial in range(10):
        leaves = []
        root = build(0, [trial * 1000], leaves)
        g = Graph()
        g.start_then(root)
        g.then_finish(root)
        assert leaves, "degenerate trial"
        for name in leaves:
            out = sequence_from_json_str(
                '[{"kind": "device", "name": "%s", "lane": 0}]' % name, g
            )
            assert out[0].name() == name


def test_unknown_op_raises():
    g = Graph()
    with pytest.raises(KeyError):
        sequence_from_json_str('[{"kind": "device", "name": "ghost"}]', g)


def test_comm_ops_round_trip_through_graph_anchoring():
    """Every comm-op kind (post/wait vocabulary, ops/comm_ops.py) serializes
    to JSON and re-anchors against the graph by name — the path recorded
    search databases and the schedule broadcast depend on."""
    import json

    from tenzing_tpu.core.sequence import Sequence
    from tenzing_tpu.core.serdes import sequence_from_json_str, sequence_to_json
    from tenzing_tpu.ops.comm_ops import (
        AllToAllStart,
        AwaitTransfer,
        HostFetchStart,
        HostSpillStart,
        MultiAwait,
        PermuteStart,
        PsumStart,
    )

    ops = [
        HostSpillStart("spill_x", "x", "hx"),
        HostFetchStart("fetch_x", "hx", "rx"),
        PermuteStart("perm_x", "rx", "px", axis="sp", shift=2),
        AllToAllStart("a2a_x", "px", "ax", axis="ep", split_axis=0),
        PsumStart("psum_x", "ax", "sx", axis="tp"),
        AwaitTransfer("await_x", "sx"),
        MultiAwait("mwait", ["rx", "sx"]),
    ]
    g = Graph()
    prev = None
    for op in ops:
        if prev is None:
            g.start_then(op)
        else:
            g.then(prev, op)
        prev = op
    g.then_finish(prev)
    payload = json.dumps(sequence_to_json(Sequence(ops)))
    out = sequence_from_json_str(payload, g)
    assert [o.name() for o in out] == [o.name() for o in ops]
    assert [type(o) for o in out] == [type(o) for o in ops]
    # parameters survive (the rebuilt ops are the graph's own instances)
    assert out[2].to_json()["shift"] == 2
    assert out[3].to_json()["split_axis"] == 0
    assert out[4].to_json()["axis"] == "tp"
