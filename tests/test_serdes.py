"""Graph-anchored schedule serdes (reference operation_serdes.cpp:14-76)."""

import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    ChoiceOp,
    CompoundOp,
    DeviceOp,
    NoOp,
)
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.serdes import (
    sequence_from_json_str,
    sequence_to_json_str,
)
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, WaitEvent


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


def test_roundtrip_with_syncs_and_bindings():
    g = Graph()
    a, b = KOp("a"), KOp("b")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    seq = Sequence(
        [
            g.start(),
            a.bind(Lane(0)),
            EventRecord(Lane(0), Event(0)),
            WaitEvent(Lane(1), Event(0)),
            b.bind(Lane(1)),
            EventRecord(Lane(1), Event(1)),
            EventSync(Event(1)),
            g.finish(),
        ]
    )
    s = sequence_to_json_str(seq)
    out = sequence_from_json_str(s, g)
    assert len(out) == len(seq)
    assert out.desc() == seq.desc()
    # device ops re-materialized as graph-anchored bound ops
    assert isinstance(out[1], BoundDeviceOp) and out[1].lane() == Lane(0)
    assert out[1].unbound() is a  # the local graph's own op object


def test_deserialize_descends_into_compound():
    class Pair(CompoundOp):
        def graph(self):
            ig = Graph()
            x = KOp("x")
            ig.start_then(x)
            ig.then_finish(x)
            return ig

    g = Graph()
    g.start_then(Pair("pair"))
    g.then_finish(Pair("pair"))
    out = sequence_from_json_str('[{"kind": "device", "name": "x", "lane": 1}]', g)
    assert isinstance(out[0], BoundDeviceOp) and out[0].name() == "x"


def test_deserialize_descends_into_choices():
    class Variant(ChoiceOp):
        def choices(self):
            return [KOp("fast"), KOp("slow")]

    g = Graph()
    g.start_then(Variant("v"))
    g.then_finish(Variant("v"))
    out = sequence_from_json_str('[{"kind": "device", "name": "slow", "lane": 0}]', g)
    assert out[0].name() == "slow"


def test_unknown_op_raises():
    g = Graph()
    with pytest.raises(KeyError):
        sequence_from_json_str('[{"kind": "device", "name": "ghost"}]', g)


def test_comm_ops_round_trip_through_graph_anchoring():
    """Every comm-op kind (post/wait vocabulary, ops/comm_ops.py) serializes
    to JSON and re-anchors against the graph by name — the path recorded
    search databases and the schedule broadcast depend on."""
    import json

    from tenzing_tpu.core.sequence import Sequence
    from tenzing_tpu.core.serdes import sequence_from_json_str, sequence_to_json
    from tenzing_tpu.ops.comm_ops import (
        AllToAllStart,
        AwaitTransfer,
        HostFetchStart,
        HostSpillStart,
        MultiAwait,
        PermuteStart,
        PsumStart,
    )

    ops = [
        HostSpillStart("spill_x", "x", "hx"),
        HostFetchStart("fetch_x", "hx", "rx"),
        PermuteStart("perm_x", "rx", "px", axis="sp", shift=2),
        AllToAllStart("a2a_x", "px", "ax", axis="ep", split_axis=0),
        PsumStart("psum_x", "ax", "sx", axis="tp"),
        AwaitTransfer("await_x", "sx"),
        MultiAwait("mwait", ["rx", "sx"]),
    ]
    g = Graph()
    prev = None
    for op in ops:
        if prev is None:
            g.start_then(op)
        else:
            g.then(prev, op)
        prev = op
    g.then_finish(prev)
    payload = json.dumps(sequence_to_json(Sequence(ops)))
    out = sequence_from_json_str(payload, g)
    assert [o.name() for o in out] == [o.name() for o in ops]
    assert [type(o) for o in out] == [type(o) for o in ops]
    # parameters survive (the rebuilt ops are the graph's own instances)
    assert out[2].to_json()["shift"] == 2
    assert out[3].to_json()["split_axis"] == 0
    assert out[4].to_json()["axis"] == "tp"
