"""Learned schedule-cost surrogate (tenzing_tpu/learn/): corpus ingestion +
regime normalization, featurization contract, ridge-ensemble round-trip, and
the ISSUE 2 acceptance gates — Spearman >= 0.8 on a synthetic corpus built
from bench/model.py timings plus noise, and screen/confirm search reaching
the empirical best with <= 50% of the empirical measurements (asserted via
measurement-count counters)."""

import json
import math
import random

import numpy as np
import pytest

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    BenchResult,
    result_row,
    schedule_id,
)
from tenzing_tpu.bench.model import AnalyticBenchmarker
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp, Finish, Start
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.core.sequence import Sequence, canonical_key
from tenzing_tpu.learn import (
    FEATURE_NAMES,
    Corpus,
    RidgeEnsemble,
    ScreeningBenchmarker,
    SurrogateBenchmarker,
    featurize,
    spearman,
)
from tenzing_tpu.obs.metrics import MetricsRegistry, get_metrics, set_metrics


class KOp(DeviceOp):
    """Independent device op reading one sized buffer — lane partitioning of
    these is a real scheduling problem with a dense makespan spectrum."""

    def __init__(self, name, buf):
        super().__init__(name)
        self._buf = buf

    def reads(self):
        return [self._buf]

    def apply(self, bufs, ctx):
        return {}


SIZES = [1, 3, 7, 13, 24, 40, 11, 29]  # MB per op's input buffer


def _mk_graph():
    g = Graph()
    nbytes = {}
    ops = []
    for i, s in enumerate(SIZES):
        buf = f"buf{i}"
        nbytes[buf] = s << 20
        op = KOp(f"k{i}", buf)
        ops.append(op)
        g.start_then(op)
        g.then_finish(op)
    return g, ops, nbytes


def _random_schedules(ops, n, n_lanes=2, seed=0):
    """n distinct schedules: random order x random lane binding (dedup by
    canonical key) — the diversity a depth-first enumeration of this space
    would not reach within a small cap."""
    rng = random.Random(seed)
    out, seen = [], set()
    while len(out) < n:
        perm = rng.sample(ops, len(ops))
        seq = Sequence([Start()]
                       + [op.bind(Lane(rng.randrange(n_lanes)))
                          for op in perm]
                       + [Finish()])
        k = canonical_key(seq)
        if k not in seen:
            seen.add(k)
            out.append(seq)
    return out


def _res(t):
    t = float(t)
    return BenchResult(pct01=t, pct10=t, pct50=t, pct90=t, pct99=t,
                       stddev=0.0)


def _write_db(path, naive_seq, naive_t, entries, regime, rng,
              noise=0.04, screen_rows=()):
    """Synthetic search database: naive anchor at row 0, then (seq, truth)
    rows at ``truth * regime * lognormal(noise)``; ``screen_rows`` append
    with a fid=screen tag."""
    rows = [result_row(0, _res(naive_t * regime), naive_seq)]
    for j, (seq, t) in enumerate(entries):
        meas = t * regime * math.exp(rng.normal(0.0, noise))
        rows.append(result_row(j + 1, _res(meas), seq))
    for j, (seq, t) in enumerate(screen_rows):
        rows.append(result_row(len(entries) + 1 + j, _res(t), seq,
                               fidelity="screen"))
    path.write_text("\n".join(rows) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """Graph + 64 schedules + analytic ground truth + a two-regime corpus
    (chip regimes 1.0 and 1.4 — the >1.3x swing recorded.py normalizes) +
    the surrogate trained on it."""
    tmp = tmp_path_factory.mktemp("learn_corpus")
    g, ops, nbytes = _mk_graph()
    seqs = _random_schedules(ops, 64, n_lanes=2, seed=0)
    ab = AnalyticBenchmarker(nbytes)
    truth = np.array([ab.makespan(s) for s in seqs])
    naive = Sequence([Start()] + [op.bind(Lane(0)) for op in ops]
                     + [Finish()])
    naive_t = float(ab.makespan(naive))
    rng = np.random.RandomState(7)
    # even-index schedules recorded in regime 1.0, odd in regime 1.4; the
    # first two schedules recorded in BOTH (duplicate-merge coverage); two
    # screen-fidelity rows that must be excluded from training
    a = _write_db(tmp / "a.csv", naive, naive_t,
                  [(seqs[i], truth[i]) for i in range(0, 64, 2)], 1.0, rng)
    b = _write_db(tmp / "b.csv", naive, naive_t,
                  [(seqs[i], truth[i]) for i in range(1, 64, 2)]
                  + [(seqs[0], truth[0]), (seqs[2], truth[2])], 1.4, rng,
                  screen_rows=[(seqs[1], truth[1] * 0.01),
                               (seqs[3], truth[3] * 0.01)])
    corpus = Corpus.from_files([a, b], g)
    X, y = corpus.matrices(nbytes=nbytes)
    model = RidgeEnsemble(feature_names=list(FEATURE_NAMES)).fit(X, y)
    return {
        "graph": g, "ops": ops, "nbytes": nbytes, "seqs": seqs,
        "truth": truth, "naive": naive, "naive_t": naive_t,
        "corpus": corpus, "model": model, "paths": (a, b),
    }


@pytest.fixture
def fresh_metrics():
    prev = set_metrics(MetricsRegistry())
    yield get_metrics()
    set_metrics(prev)


# -- features ---------------------------------------------------------------


def test_featurize_contract_and_determinism(world):
    s = world["seqs"][0]
    v1 = featurize(s, nbytes=world["nbytes"])
    v2 = featurize(s, nbytes=world["nbytes"])
    assert len(v1) == len(FEATURE_NAMES)
    assert v1 == v2
    names = dict(zip(FEATURE_NAMES, v1))
    assert names["n_device"] == len(SIZES)
    assert names["n_lanes"] == 2.0
    assert names["analytic_makespan"] > 0.0
    # the serial naive uses one lane at full occupancy
    nv = dict(zip(FEATURE_NAMES, featurize(world["naive"],
                                           nbytes=world["nbytes"])))
    assert nv["n_lanes"] == 1.0 and nv["serial_frac"] == 1.0
    assert nv["analytic_makespan"] > names["analytic_makespan"]


def test_featurize_comm_bytes_per_engine():
    """Transfer-post ops bucket their bytes by the analytic model's engine
    classification (ICI vs PCIe)."""
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import build_graph
    from tenzing_tpu.solve.dfs import get_unique_sequences

    g = build_graph(HaloArgs(nq=1, lx=2, ly=2, lz=2, radius=1),
                    xfer_choice=True)
    plat = Platform.make_n_lanes(2)
    seqs = [st.sequence for st in get_unique_sequences(g, plat, max_seqs=6)]
    names = set()
    for s in seqs:
        for op in s:
            for f in ("reads", "writes"):
                fn = getattr(op, f, None)
                if callable(fn):
                    names.update(fn())
    nbytes = {n: 4096 for n in names}
    vecs = [dict(zip(FEATURE_NAMES, featurize(s, nbytes=nbytes)))
            for s in seqs]
    # the choice graph resolves xfers to rdma (ICI) or host spill/fetch
    # (PCIe) — across the enumerated variants both engines appear
    assert any(v["ici_bytes"] > 0 or v["pcie_bytes"] > 0 for v in vecs)
    for v in vecs:
        assert v["n_sync"] == sum(
            v[f"n_{k}"] for k in ("event_record", "wait_event", "event_sync",
                                  "lane_sync", "lane_wait"))


# -- dataset ----------------------------------------------------------------


def test_corpus_regime_normalization_and_merge(world):
    corpus = world["corpus"]
    # 64 distinct schedules + the naive (recorded in both files, merged)
    assert len(corpus.rows) == 65
    assert corpus.n_merged == 3  # naive + seqs[0] + seqs[2] duplicates
    assert corpus.n_screen == 2
    # labels are regime-invariant: the duplicate recordings of seqs[0] came
    # from regimes 1.0 and 1.4 but its merged label must sit within noise of
    # the true log-ratio
    key0 = canonical_key(world["seqs"][0])
    row0 = next(r for r in corpus.rows if r.key == key0)
    want = math.log(world["truth"][0] / world["naive_t"])
    assert abs(row0.label - want) < 0.15
    assert row0.ratio == pytest.approx(math.exp(-row0.label))


def test_corpus_skips_anchorless_and_screen_anchor_files(tmp_path, world):
    seqs, truth = world["seqs"], world["truth"]
    # no row-0 anchor: file contributes nothing
    p1 = tmp_path / "noanchor.csv"
    p1.write_text(result_row(3, _res(truth[0]), seqs[0]) + "\n")
    # row 0 present but at screen fidelity: anchor off-regime -> excluded
    p2 = tmp_path / "screenanchor.csv"
    p2.write_text(
        result_row(0, _res(world["naive_t"] * 0.01), world["naive"],
                   fidelity="screen") + "\n"
        + result_row(1, _res(truth[1]), seqs[1]) + "\n")
    msgs = []
    corpus = Corpus.from_files([str(p1), str(p2)], world["graph"],
                               log=msgs.append)
    assert corpus.rows == []
    assert sum("no naive anchor" in m for m in msgs) == 2


def test_solver_dumps_are_anchorless(tmp_path, world):
    """DfsResult/MctsResult dumps number rows from 1: their row 0 slot is
    reserved for the driver's naive-at-final-fidelity anchor, so anchor
    readers must treat solver-internal dumps as anchorless instead of
    anchoring every ratio to an arbitrary first-enumerated terminal."""
    from tenzing_tpu.bench.recorded import naive_anchor_of
    from tenzing_tpu.solve.dfs import DfsResult
    from tenzing_tpu.solve.dfs import SimResult as DfsSim
    from tenzing_tpu.solve.mcts.mcts import MctsResult, SimResult

    seqs, truth = world["seqs"], world["truth"]
    dfs_res = DfsResult(sims=[DfsSim(order=s, result=_res(t))
                              for s, t in zip(seqs[:3], truth[:3])])
    p1 = tmp_path / "dfs.csv"
    dfs_res.dump_csv(str(p1))
    assert naive_anchor_of(str(p1)) is None
    mcts_res = MctsResult(sims=[SimResult(order=s, result=_res(t))
                                for s, t in zip(seqs[:3], truth[:3])])
    p2 = tmp_path / "mcts.csv"
    mcts_res.dump_csv(str(p2))
    assert naive_anchor_of(str(p2)) is None
    msgs = []
    assert Corpus.from_files([str(p1), str(p2)], world["graph"],
                             log=msgs.append).rows == []
    assert sum("no naive anchor" in m for m in msgs) == 2


def test_model_without_names_fails_contract_check(tmp_path, world):
    """A model saved without feature names cannot prove it matches the
    featurizer: loading with an expectation must refuse it."""
    X, y = world["corpus"].matrices(nbytes=world["nbytes"])
    anon = RidgeEnsemble().fit(X, y)  # no feature_names
    path = str(tmp_path / "anon.json")
    anon.save(path)
    RidgeEnsemble.load(path)  # no expectation: loads fine
    with pytest.raises(ValueError, match="feature contract"):
        RidgeEnsemble.load(path, expect_features=list(FEATURE_NAMES))


def test_merged_rows_join_traces_under_every_digest(tmp_path, world):
    """Bijection-equivalent spellings recorded in different files hash to
    different schedule digests; the merged row joins trace spans under ALL
    of them."""
    from tenzing_tpu.core.resources import Lane

    ops = world["ops"]
    a_seq = Sequence([Start()] + [op.bind(Lane(i % 2))
                                  for i, op in enumerate(ops)] + [Finish()])
    # same program up to the lane bijection 0<->1: same canonical key,
    # different serialized form -> different digest
    b_seq = Sequence([Start()] + [op.bind(Lane((i + 1) % 2))
                                  for i, op in enumerate(ops)] + [Finish()])
    assert canonical_key(a_seq) == canonical_key(b_seq)
    assert schedule_id(a_seq) != schedule_id(b_seq)
    rng = np.random.RandomState(0)
    pa = _write_db(tmp_path / "a.csv", world["naive"], world["naive_t"],
                   [(a_seq, 1e-3)], 1.0, rng)
    pb = _write_db(tmp_path / "b.csv", world["naive"], world["naive_t"],
                   [(b_seq, 1e-3)], 1.0, rng)
    corpus = Corpus.from_files([pa, pb], world["graph"])
    row = next(r for r in corpus.rows if r.key == canonical_key(a_seq))
    assert set(row.schedules) == {schedule_id(a_seq), schedule_id(b_seq)}
    trace = tmp_path / "t.jsonl"
    trace.write_text(json.dumps(
        {"kind": "span", "name": "bench.benchmark", "ts_us": 1.0,
         "attrs": {"schedule": schedule_id(b_seq)}}) + "\n")
    assert corpus.attach_traces([str(trace)]) == 1
    assert row.n_trace_measurements == 1


def test_corpus_attach_traces(tmp_path, world):
    corpus = Corpus.from_files([world["paths"][0]], world["graph"])
    sid = corpus.rows[1].schedule
    assert sid == schedule_id(corpus.rows[1].seq)
    trace = tmp_path / "trace.jsonl"
    recs = [
        {"kind": "span", "name": "bench.benchmark", "ts_us": 1.0,
         "attrs": {"schedule": sid, "pct50": 0.5}},
        {"kind": "span", "name": "bench.benchmark", "ts_us": 2.0,
         "attrs": {"schedule": sid, "pct50": 0.5}},
        {"kind": "span", "name": "bench.warm", "ts_us": 3.0,
         "attrs": {"schedule": sid}},  # not a measurement span
        {"kind": "event", "name": "bench.cache", "ts_us": 4.0,
         "attrs": {"schedule": sid}},
    ]
    trace.write_text("".join(json.dumps(r) + "\n" for r in recs))
    matched = corpus.attach_traces([str(trace)])
    assert matched == 2
    assert corpus.rows[1].n_trace_measurements == 2
    assert all(r.n_trace_measurements == 0
               for r in corpus.rows if r.schedule != sid)


# -- model ------------------------------------------------------------------


def test_model_save_load_roundtrip(tmp_path, world):
    model = world["model"]
    path = str(tmp_path / "model.json")
    model.save(path)
    loaded = RidgeEnsemble.load(path, expect_features=list(FEATURE_NAMES))
    X, _ = world["corpus"].matrices(nbytes=world["nbytes"])
    m1, s1 = model.predict(X)
    m2, s2 = loaded.predict(X)
    assert np.allclose(m1, m2) and np.allclose(s1, s2)
    # feature-contract drift fails loudly
    with pytest.raises(ValueError, match="feature contract"):
        RidgeEnsemble.load(path, expect_features=["bogus"])


def test_model_uncertainty_nonnegative_and_varies(world):
    X, _ = world["corpus"].matrices(nbytes=world["nbytes"])
    _, sd = world["model"].predict(X)
    assert (sd >= 0).all() and sd.max() > 0


def test_spearman_helper():
    assert spearman([1, 2, 3, 4], [10, 20, 30, 40]) == pytest.approx(1.0)
    assert spearman([1, 2, 3, 4], [40, 30, 20, 10]) == pytest.approx(-1.0)
    assert spearman([1, 1, 1, 1], [1, 2, 3, 4]) == 0.0


# -- acceptance: ranking ----------------------------------------------------


def test_surrogate_ranks_with_spearman_ge_08(world):
    """ISSUE 2 gate: on a synthetic corpus built from bench/model.py timings
    plus noise, the trained surrogate ranks schedules with Spearman >= 0.8
    vs ground truth."""
    sur = SurrogateBenchmarker(world["model"], nbytes=world["nbytes"])
    pred = [sur.predict(s)[0] for s in world["seqs"]]
    rho = spearman(pred, np.log(world["truth"]))
    assert rho >= 0.8, rho


def test_surrogate_benchmark_protocol(world):
    sur = SurrogateBenchmarker(world["model"], nbytes=world["nbytes"],
                               anchor_s=world["naive_t"])
    res = sur.benchmark(world["seqs"][0], BenchOpts(n_iters=1))
    assert res.pct01 <= res.pct50 <= res.pct99
    assert res.pct50 > 0
    # anchor scales the prediction back to seconds: within the corpus noise
    # of the analytic truth
    assert 0.5 * world["truth"][0] < res.pct50 < 2.0 * world["truth"][0]


# -- acceptance: screening economy ------------------------------------------


class CountingBench:
    """Deterministic 'empirical' benchmarker over the analytic ground truth
    at a THIRD chip regime (1.3x — neither training regime), counting
    measurements and remembering what it measured."""

    def __init__(self, seqs, truth, regime=1.3):
        self._by_key = {canonical_key(s): float(t) * regime
                        for s, t in zip(seqs, truth)}
        self.calls = 0
        self.measured = {}

    def benchmark(self, order, opts=None):
        self.calls += 1
        t = self._by_key[canonical_key(order)]
        self.measured[canonical_key(order)] = t
        return _res(t)


def test_screening_half_measurements_same_best(world, fresh_metrics):
    """ISSUE 2 gate: the screen answers >= 50% of queries from the model
    while the true best schedule still gets an empirical measurement — the
    screened search lands on the same best schedule as measuring
    everything, at <= 50% of the measurement cost (counters assert both
    sides of the economy)."""
    seqs, truth = world["seqs"], world["truth"]
    inner = CountingBench(seqs, truth)
    scr = ScreeningBenchmarker(
        SurrogateBenchmarker(world["model"], nbytes=world["nbytes"]),
        inner, escalate_topk=4, z=2.0)
    for s in seqs:
        scr.benchmark(s)
    assert scr.hits + scr.escalations == len(seqs)
    assert inner.calls == scr.escalations
    assert inner.calls <= len(seqs) // 2, inner.calls
    # same best as pure empirical search: the argmin over what WAS measured
    # equals the argmin the full sweep would have found
    best_key = canonical_key(seqs[int(np.argmin(truth))])
    assert best_key in inner.measured
    assert min(inner.measured, key=inner.measured.get) == best_key
    reg = get_metrics()
    assert reg.counter("learn.screen.escalations").value == scr.escalations
    assert reg.counter("learn.screen.surrogate_hits").value == scr.hits
    assert reg.histogram("learn.screen.abs_log_err").count == inner.calls


def test_screening_full_fidelity_always_escalates(world):
    """With screen_only_opts set, any query at another fidelity reaches the
    device — the MCTS confirm pass can never be answered by the model."""
    seqs, truth = world["seqs"], world["truth"]
    screen_opts = BenchOpts(n_iters=2, target_secs=0.001)
    confirm_opts = BenchOpts(n_iters=20, target_secs=0.02)
    inner = CountingBench(seqs, truth)
    scr = ScreeningBenchmarker(
        SurrogateBenchmarker(world["model"], nbytes=world["nbytes"]),
        inner, escalate_topk=2, z=2.0, screen_only_opts=screen_opts)
    for s in seqs[:20]:
        scr.benchmark(s, screen_opts)
    hits_before = scr.hits
    assert hits_before > 0  # the screen floor is being answered cheaply
    for s in seqs[:20]:
        scr.benchmark(s, confirm_opts)
    assert scr.hits == hits_before  # no confirm query answered by the model


def test_full_fidelity_escalations_do_not_pollute_calibration(world,
                                                              fresh_metrics):
    """Confirm-pass measurements run at a ~10-100x different floor: they
    must not feed the screen-floor bias/residual calibration or the top-k
    threshold."""
    seqs, truth = world["seqs"], world["truth"]
    screen_opts = BenchOpts(n_iters=2, target_secs=0.001)
    confirm_opts = BenchOpts(n_iters=20, target_secs=0.02)

    class RegimeBench:
        def benchmark(self, order, opts=None):
            t = float(truth[[canonical_key(s) for s in seqs]
                            .index(canonical_key(order))])
            # the confirm floor measures ~100x higher absolute times
            return _res(t * (100.0 if opts is confirm_opts else 1.0))

    scr = ScreeningBenchmarker(
        SurrogateBenchmarker(world["model"], nbytes=world["nbytes"]),
        RegimeBench(), escalate_topk=4, z=2.0,
        screen_only_opts=screen_opts)
    for s in seqs[:8]:
        scr.benchmark(s, screen_opts)
    deltas_before = list(scr._deltas)
    emp_before = list(scr._emp_logs)
    err_count = get_metrics().histogram("learn.screen.abs_log_err").count
    for s in seqs[:4]:
        scr.benchmark(s, confirm_opts)  # fidelity escalations
    assert scr._deltas == deltas_before
    assert scr._emp_logs == emp_before
    assert get_metrics().histogram(
        "learn.screen.abs_log_err").count == err_count


def test_was_predicted_tracks_model_answered_queries(world):
    """Provenance for dump paths: only surrogate-answered schedules report
    was_predicted (bench.py retags their CSV rows fid=model)."""
    seqs, truth = world["seqs"], world["truth"]
    inner = CountingBench(seqs, truth)
    scr = ScreeningBenchmarker(
        SurrogateBenchmarker(world["model"], nbytes=world["nbytes"]),
        inner, escalate_topk=4, z=2.0)
    for s in seqs:
        scr.benchmark(s)
    assert scr.hits > 0 and scr.escalations > 0
    n_pred = sum(scr.was_predicted(s) for s in seqs)
    assert n_pred == scr.hits
    for s in seqs:
        assert scr.was_predicted(s) == (
            canonical_key(s) not in inner.measured)


def test_dfs_prescreen_half_measurements_same_best(world, fresh_metrics):
    """Screen/confirm on a recorded-search fixture: DFS explore with the
    surrogate prescreen issues <= 50% of the empirical measurements of the
    pure run and still returns the same best schedule."""
    from tenzing_tpu.solve.dfs import DfsOpts, explore

    g, nbytes = world["graph"], world["nbytes"]
    plat = Platform.make_n_lanes(2)
    ab = AnalyticBenchmarker(nbytes)

    class CountingAnalytic:
        def __init__(self):
            self.calls = 0

        def benchmark(self, order, opts=None):
            self.calls += 1
            return _res(ab.makespan(order))

    cap = 24
    pure_bench = CountingAnalytic()
    pure = explore(g, plat, pure_bench, DfsOpts(max_seqs=cap))
    assert pure_bench.calls == len(pure.sims) > 0
    sur = SurrogateBenchmarker(world["model"], nbytes=nbytes)
    screened_bench = CountingAnalytic()
    screened = explore(
        g, plat, screened_bench,
        DfsOpts(max_seqs=cap, prescreen=sur,
                prescreen_keep=len(pure.sims) // 2))
    assert screened_bench.calls <= pure_bench.calls // 2
    assert screened.sims
    # same best schedule (by replayed value: ties under the analytic model
    # are genuinely the same best)
    assert (min(s.result.pct50 for s in screened.sims)
            == pytest.approx(min(s.result.pct50 for s in pure.sims)))
    reg = get_metrics()
    assert reg.counter("learn.prune.dfs_skipped").value == (
        pure_bench.calls - screened_bench.calls)


def test_local_prescreen_prunes_neighbors(world, fresh_metrics):
    """The hill-climb measures fewer neighbors with the surrogate pruner and
    still improves on its incumbent."""
    from tenzing_tpu.solve.local import LocalOpts, hill_climb

    g, nbytes = world["graph"], world["nbytes"]
    plat = Platform.make_n_lanes(2)
    ab = AnalyticBenchmarker(nbytes)

    class CountingAnalytic:
        def __init__(self):
            self.calls = 0

        def benchmark(self, order, opts=None):
            self.calls += 1
            return _res(ab.makespan(order))

    def climb(prescreen):
        bench = CountingAnalytic()
        # budget high enough that the climb ends by convergence, not budget
        # exhaustion — the measurement saving is then visible in the call
        # counts instead of both runs spending the same cap
        res = hill_climb(
            g, plat, bench, phases=("k",),
            opts=LocalOpts(budget=400, bench_opts=BenchOpts(n_iters=1),
                           seed=5, prescreen=prescreen))
        return bench.calls, res

    calls_plain, res_plain = climb(None)
    sur = SurrogateBenchmarker(world["model"], nbytes=nbytes)
    calls_screened, res_screened = climb(sur)
    skipped = get_metrics().counter("learn.prune.local_skipped").value
    assert skipped > 0
    assert calls_screened < calls_plain
    # pruning only removes predicted-worse neighbors: the climb still ends
    # at least as good as its incumbent
    assert (res_screened.final.result.pct50
            <= res_screened.sims[0].result.pct50)
