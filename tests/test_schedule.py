"""remove_redundant_syncs peephole rules (reference schedule.cpp:19-321) and the
legacy whole-space enumerators."""

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp, NoOp, Start
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.schedule import (
    make_schedules,
    make_schedules_random,
    remove_redundant_syncs,
)
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, LaneSync, WaitEvent


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


def k(name, lane):
    return KOp(name).bind(Lane(lane))


def descs(seq):
    return [op.desc() for op in seq]


def test_rule1_unconsumed_record_dropped():
    seq = Sequence([Start(), k("a", 0), EventRecord(Lane(0), Event(0))])
    out = remove_redundant_syncs(seq)
    assert descs(out) == ["start", "a@lane0"]


def test_rule2_wait_without_later_device_dropped():
    # wait on lane1 but nothing ever runs on lane1 afterwards
    seq = Sequence(
        [
            Start(),
            k("a", 0),
            EventRecord(Lane(0), Event(0)),
            WaitEvent(Lane(1), Event(0)),
        ]
    )
    out = remove_redundant_syncs(seq)
    # wait dropped; then the record is unconsumed and dropped too
    assert descs(out) == ["start", "a@lane0"]


def test_useful_record_wait_pair_kept():
    seq = Sequence(
        [
            Start(),
            k("a", 0),
            EventRecord(Lane(0), Event(0)),
            WaitEvent(Lane(1), Event(0)),
            k("b", 1),
        ]
    )
    out = remove_redundant_syncs(seq)
    assert len(out) == 5  # nothing removable


def test_rule3_duplicate_lane_syncs():
    seq = Sequence([Start(), k("a", 0), LaneSync(Lane(0)), LaneSync(Lane(0)), NoOp("c")])
    out = remove_redundant_syncs(seq)
    assert descs(out) == ["start", "a@lane0", "LaneSync(lane0)", "c"]


def test_rule4_duplicate_records_merged():
    # two records at the same lane point; consumers of the second rewritten
    seq = Sequence(
        [
            Start(),
            k("a", 0),
            EventRecord(Lane(0), Event(0)),
            EventRecord(Lane(0), Event(1)),
            WaitEvent(Lane(1), Event(0)),
            WaitEvent(Lane(1), Event(1)),
            k("b", 1),
        ]
    )
    out = remove_redundant_syncs(seq)
    # one record survives; one wait survives (the rewritten duplicate collapses
    # to an identical wait, which rule 5 then removes)
    evs = [op for op in out if isinstance(op, EventRecord)]
    assert len(evs) == 1
    waits = [op for op in out if isinstance(op, WaitEvent)]
    assert len(waits) == 1 and waits[0].event() == evs[0].event()


def test_rule5_covered_pair_dropped():
    # e0 recorded, then e1 recorded later on same lane; e1 waited first, so the
    # later wait on e0 adds nothing
    seq = Sequence(
        [
            Start(),
            k("a", 0),
            EventRecord(Lane(0), Event(0)),
            k("a2", 0),
            EventRecord(Lane(0), Event(1)),
            WaitEvent(Lane(1), Event(1)),
            WaitEvent(Lane(1), Event(0)),
            k("b", 1),
        ]
    )
    out = remove_redundant_syncs(seq)
    waits = [op for op in out if isinstance(op, WaitEvent)]
    assert len(waits) == 1 and waits[0].event() == Event(1)
    recs = [op for op in out if isinstance(op, EventRecord)]
    assert len(recs) == 1 and recs[0].event() == Event(1)


def test_rule2_keeps_transitive_sync_chain():
    # a@L0 -> (record,wait via L1) -> (record,wait) -> b@L2: the L1 hop has no
    # device op but its token is snapshotted by the second record — keep all
    seq = Sequence(
        [
            Start(),
            k("a", 0),
            EventRecord(Lane(0), Event(0)),
            WaitEvent(Lane(1), Event(0)),
            EventRecord(Lane(1), Event(1)),
            WaitEvent(Lane(2), Event(1)),
            k("b", 2),
        ]
    )
    out = remove_redundant_syncs(seq)
    assert len(out) == 7


def test_rule4_wait_advances_lane_point():
    # two records on L0 separated by a WaitEvent joining c@L2's work: they
    # capture different progress and must NOT merge
    seq = Sequence(
        [
            Start(),
            k("a", 0),
            k("c", 2),
            EventRecord(Lane(0), Event(0)),
            EventRecord(Lane(2), Event(9)),
            WaitEvent(Lane(0), Event(9)),
            EventRecord(Lane(0), Event(1)),
            WaitEvent(Lane(1), Event(0)),
            k("x", 1),
            WaitEvent(Lane(3), Event(1)),
            k("b", 3),
        ]
    )
    out = remove_redundant_syncs(seq)
    evs = [op for op in out if isinstance(op, EventRecord)]
    assert any(op.event() == Event(1) for op in evs)
    assert any(op.event() == Event(9) for op in evs)


def test_rule5_requires_effective_cover():
    # e1 waited at a point where e2 is NOT yet recorded: e0's pair must survive
    seq = Sequence(
        [
            Start(),
            k("a", 0),
            EventRecord(Lane(0), Event(0)),
            WaitEvent(Lane(1), Event(1)),  # e1 not recorded yet -> ineffective
            k("a2", 0),
            EventRecord(Lane(0), Event(1)),
            WaitEvent(Lane(1), Event(0)),
            k("b", 1),
        ]
    )
    out = remove_redundant_syncs(seq)
    waits = [op for op in out if isinstance(op, WaitEvent)]
    assert any(w.event() == Event(0) for w in waits), "load-bearing wait dropped"


def test_make_schedules_enumerates_topological_orders():
    g = Graph()
    a, b = NoOp("a"), NoOp("b")
    g.start_then(a)
    g.start_then(b)
    g.then_finish(a)
    g.then_finish(b)
    scheds = make_schedules(g)
    assert len(scheds) == 2
    assert {s.desc() for s in scheds} == {
        "start, a, b, finish",
        "start, b, a, finish",
    }


def test_make_schedules_random_seeded_deterministic():
    g = Graph()
    for n in ["a", "b", "c"]:
        g.start_then(NoOp(n))
        g.then_finish(NoOp(n))
    s1 = make_schedules_random(g, 5, seed=42)
    s2 = make_schedules_random(g, 5, seed=42)
    assert [s.desc() for s in s1] == [s.desc() for s in s2]
    s3 = make_schedules_random(g, 5, seed=7)
    assert [s.desc() for s in s1] != [s.desc() for s in s3]  # overwhelmingly likely
