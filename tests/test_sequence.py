"""Sequence semantics (reference src/sequence.cpp:169-175 and sequence.cpp:21-86)."""

from tenzing_tpu.core.operation import DeviceOp, NoOp, Start
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence, get_equivalence, is_equivalent
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, WaitEvent


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


def test_empty_sequence():
    s = Sequence()
    assert len(s) == 0
    assert not s.contains(NoOp("a"))


def test_unbound_matching():
    k = KOp("k")
    s = Sequence([Start(), k.bind(Lane(1))])
    assert s.contains_unbound(k)
    found = s.find_unbound(k)
    assert found is not None and found.lane() == Lane(1)
    assert s.find_unbound(KOp("other")) is None


def test_new_unique_event():
    s = Sequence([Start()])
    assert s.new_unique_event() == Event(0)
    s.push_back(EventRecord(Lane(0), Event(0)))
    assert s.new_unique_event() == Event(1)
    s.push_back(WaitEvent(Lane(1), Event(2)))
    assert s.new_unique_event() == Event(1)
    s.push_back(EventSync(Event(1)))
    assert s.new_unique_event() == Event(3)


def test_equivalence_lane_event_bijection():
    a, b = KOp("a"), KOp("b")

    def seq(l0, l1, e):
        return Sequence(
            [
                Start(),
                a.bind(l0),
                EventRecord(l0, e),
                WaitEvent(l1, e),
                b.bind(l1),
            ]
        )

    s1 = seq(Lane(0), Lane(1), Event(0))
    s2 = seq(Lane(1), Lane(0), Event(4))
    assert is_equivalent(s1, s2)

    # inconsistent lane mapping: a on 0 and b on 0 vs a on 0, b on 1
    s3 = seq(Lane(0), Lane(0), Event(0))
    assert not is_equivalent(s1, s3)

    # different op order is not equivalent
    s4 = Sequence([Start(), b.bind(Lane(1)), a.bind(Lane(0))])
    assert not is_equivalent(Sequence([Start(), a.bind(Lane(0)), b.bind(Lane(1))]), s4)


def test_equivalence_returns_bijection():
    a = KOp("a")
    s1 = Sequence([a.bind(Lane(0))])
    s2 = Sequence([a.bind(Lane(3))])
    e = get_equivalence(s1, s2)
    assert e and e.lanes[Lane(0)] == Lane(3)


def test_canonical_key_agrees_with_pairwise_bijection():
    """canonical_key equality must coincide with get_equivalence on every pair
    from a real enumerated schedule space (it is the O(1) lookup the
    benchmarker caches use; get_equivalence is the semantic ground truth)."""
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.sequence import canonical_key
    from tenzing_tpu.models.spmv import SpMVCompound
    from tenzing_tpu.solve.dfs import get_all_sequences

    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    plat = Platform.make_n_lanes(2)
    seqs = [s.sequence for s in get_all_sequences(g, plat, max_seqs=12)]
    assert len(seqs) >= 6
    for i, a in enumerate(seqs):
        for b in seqs[i:]:
            assert bool(get_equivalence(a, b)) == (
                canonical_key(a) == canonical_key(b)
            ), (a.desc(), b.desc())


def test_canonical_key_relabels_resources():
    from tenzing_tpu.core.sequence import canonical_key

    a = KOp("a")
    b = KOp("b")

    def seq(l0, l1, e):
        return Sequence(
            [Start(), a.bind(l0), EventRecord(l0, e), WaitEvent(l1, e),
             b.bind(l1)]
        )

    # same schedule under renamed lanes/events: identical canonical keys
    assert canonical_key(seq(Lane(0), Lane(1), Event(0))) == canonical_key(
        seq(Lane(1), Lane(0), Event(4))
    )
    # collapsing the two lanes into one changes the key
    assert canonical_key(seq(Lane(0), Lane(1), Event(0))) != canonical_key(
        seq(Lane(0), Lane(0), Event(0))
    )
