"""Chaos + checkpoint/resume acceptance (ISSUE 3).

A seeded MCTS + DFS search over a *recorded corpus* (the full deduplicated
2-lane SpMV space, rendered to CSV rows and replayed through CsvBenchmarker
— the reference's mcts_csv workflow, so no device is in the loop and every
measurement answer is deterministic) is run under seeded fault injection:
>= 20% transient failures, injected hangs caught by the watchdog, and
deterministic per-schedule failures.  The acceptance criteria:

* the chaos run crashes nowhere and finds the SAME best schedule as the
  clean run;
* every failure lands as a classified ``fault.*`` telemetry event;
* deterministic failures are quarantined — each broken candidate is
  measured at most once even across a kill + resume;
* a killed run (KeyboardInterrupt mid-measurement, the SIGINT path) leaves
  a complete, deadlock-free telemetry bundle with all in-flight spans
  closed, and ``--resume`` re-measures nothing already measured while
  reaching the same final best as an uninterrupted run.
"""

import hashlib
import json
from collections import Counter

import pytest

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    BenchResult,
    CachingBenchmarker,
    CsvBenchmarker,
    result_row,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.schedule import remove_redundant_syncs
from tenzing_tpu.core.sequence import canonical_key
from tenzing_tpu.fault import (
    BackoffPolicy,
    FaultInjectingBenchmarker,
    InjectSpec,
    JournalingBenchmarker,
    Quarantine,
    ResilientBenchmarker,
    SearchCheckpoint,
)
from tenzing_tpu.fault.inject import _schedule_fails
from tenzing_tpu.models.spmv import SpMVCompound
from tenzing_tpu.obs.export import to_jsonl
from tenzing_tpu.obs.metrics import MetricsRegistry, set_metrics
from tenzing_tpu.obs.tracer import Tracer, get_tracer, set_tracer
from tenzing_tpu.solve.dfs import DfsOpts, enumerate_schedules
from tenzing_tpu.solve.dfs import explore as dfs_explore
from tenzing_tpu.solve.mcts import MctsOpts, explore
from tenzing_tpu.utils import trap


@pytest.fixture
def tracer():
    tr = Tracer(enabled=True)
    prev = set_tracer(tr)
    try:
        yield tr
    finally:
        set_tracer(prev)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_metrics(reg)
    try:
        yield reg
    finally:
        set_metrics(prev)


def _graph():
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    return g


def _key(order):
    return canonical_key(remove_redundant_syncs(order))


def _synth_result(seq) -> BenchResult:
    """Deterministic 'measurement' from the schedule's canonical identity:
    the corpus is a pure function of the search space, so clean and chaos
    runs are comparable bit-for-bit."""
    h = hashlib.sha256(repr(_key(seq)).encode()).digest()
    t = 1.0 + int.from_bytes(h[:8], "big") / float(1 << 64)
    return BenchResult.from_times([t, t, t])


@pytest.fixture(scope="module")
def corpus():
    """The full deduplicated 2-lane space as recorded CSV rows."""
    states = enumerate_schedules(_graph(), Platform.make_n_lanes(2),
                                 max_seqs=10_000)
    assert 3 <= len(states) < 10_000  # complete coverage
    rows = [result_row(i, _synth_result(st.sequence), st.sequence)
            for i, st in enumerate(states)]
    return rows, [st.sequence for st in states]


def mk_db(rows):
    return CsvBenchmarker(rows, _graph(), normalize=True)


class CountingInner:
    """Device stand-in instrumentation: counts attempts (calls in) and
    completions (calls that returned) per (canonical key, opts) and per
    telemetry schedule id; optionally simulates a SIGINT mid-measurement
    after N attempts (running the trap callbacks exactly like the signal
    handler would, then raising KeyboardInterrupt)."""

    def __init__(self, db, interrupt_after=None, on_interrupt=None):
        self.db = db
        self.attempts = Counter()
        self.completed = Counter()
        self.by_sid = Counter()
        self.orders = {}  # sid -> the order object, for targeted re-queries
        self.total = 0
        self.interrupt_after = interrupt_after
        self.on_interrupt = on_interrupt

    def _k(self, order, opts):
        ok = (opts.n_iters, opts.max_retries, opts.target_secs) if opts \
            else None
        return (_key(order), ok)

    def benchmark(self, order, opts=None):
        from tenzing_tpu.bench.benchmarker import schedule_id

        self.total += 1
        self.attempts[self._k(order, opts)] += 1
        sid = schedule_id(order)
        self.by_sid[sid] += 1
        self.orders[sid] = order
        # >= not ==: under the watchdog, an attempt can run on an abandoned
        # worker thread where a raised interrupt is swallowed with the
        # discarded result — every later attempt must keep "delivering the
        # signal" until one propagates from a live measurement
        if self.interrupt_after is not None and \
                self.total >= self.interrupt_after:
            if self.on_interrupt is not None:
                self.on_interrupt()
            trap.run_callbacks()  # what the real SIGINT handler does
            raise KeyboardInterrupt
        res = self.db.benchmark(order, opts)
        self.completed[self._k(order, opts)] += 1
        return res


def _fast_policy():
    return BackoffPolicy(retries=8, base_secs=0.0, jitter=0.0)


def _best(sims):
    s = min(sims, key=lambda s: s.result.pct50)
    return _key(s.order), s.result.pct50


def _validate_bundle(text):
    """Every span record's parent id resolves within the bundle, and the
    in-flight search spans were flushed closed."""
    recs = [json.loads(line) for line in text.splitlines()]
    spans = {r["id"]: r for r in recs if r["kind"] == "span"}
    for r in spans.values():
        assert r["dur_us"] >= 0
        if r["parent"] is not None:
            assert r["parent"] in spans, f"dangling parent in {r['name']}"
    flushed = {r["name"] for r in spans.values()
               if r["attrs"].get("flushed")}
    assert "mcts.explore" in flushed
    assert "mcts.iter" in flushed
    return recs


# deterministic-injection channel shared by the test and its precondition
DET_SPEC = InjectSpec("deterministic", 0.12, 5)
CHAOS_SPECS = [DET_SPEC,
               InjectSpec("transient", 0.25, 31),
               InjectSpec("hang", 0.05, 53)]


def _chaos_stack(rows, quarantine_path, ckpt=None, interrupt_after=None,
                 on_interrupt=None):
    # counting sits ABOVE injection: an attempt counts whether the flaky
    # "device" completed it or not — that is what "measured at most once"
    # must bound
    inject = FaultInjectingBenchmarker(mk_db(rows), CHAOS_SPECS,
                                       hang_secs=2.5)
    counting = CountingInner(inject, interrupt_after=interrupt_after,
                             on_interrupt=on_interrupt)
    resilient = ResilientBenchmarker(
        counting, timeout_secs=1.0, policy=_fast_policy(),
        quarantine=Quarantine(quarantine_path), sleep=lambda s: None)
    layer = JournalingBenchmarker(resilient, ckpt) if ckpt else resilient
    return CachingBenchmarker(layer), counting, inject, resilient


def test_chaos_search_finds_clean_best_with_kill_and_resume(
        tmp_path, tracer, registry, corpus):
    rows, terminals = corpus
    plat = Platform.make_n_lanes(2)
    n_iters = 30

    # -- clean reference: seeded MCTS + exhaustive DFS, no faults ----------
    mcts_clean = explore(_graph(), plat, mk_db(rows),
                         MctsOpts(n_iters=n_iters, seed=3))
    dfs_clean = dfs_explore(_graph(), plat, mk_db(rows),
                            DfsOpts(max_seqs=10_000))
    assert len(dfs_clean.sims) == len(terminals)
    clean_key, clean_pct50 = _best(mcts_clean.sims + dfs_clean.sims)

    # precondition of the equality criterion: the injection seed must not
    # deterministically break the best schedule itself (a quarantined best
    # is legitimately unfindable) — in either spelling the solvers query
    from tenzing_tpu.bench.benchmarker import schedule_id

    best_raw = min(terminals, key=lambda s: _synth_result(s).pct50)
    for spelling in (best_raw, remove_redundant_syncs(best_raw)):
        assert not _schedule_fails(schedule_id(spelling), DET_SPEC)

    # -- chaos phase A: injected faults, killed mid-measurement ------------
    ckdir = str(tmp_path / "ckpt")
    qpath = str(tmp_path / "ckpt" / "quarantine.json")
    ckpt = SearchCheckpoint(ckdir)
    bundles = []
    bench_a, count_a, inject_a, _ = _chaos_stack(
        rows, qpath, ckpt=ckpt, interrupt_after=16,
        on_interrupt=lambda: bundles.append(to_jsonl(get_tracer())))
    with pytest.raises(KeyboardInterrupt):
        explore(_graph(), plat, bench_a,
                MctsOpts(n_iters=n_iters, seed=3, checkpoint=ckpt,
                         dump_csv_path=str(tmp_path / "partial.csv")))
    # the simulated SIGINT produced a complete bundle with in-flight spans
    # closed, a partial CSV, and an interrupted-cursor snapshot
    _validate_bundle(bundles[0])
    assert (tmp_path / "partial.csv").exists()
    state = SearchCheckpoint(ckdir).load_state()
    assert state["mcts"]["interrupted"] is True

    # -- chaos phase B: resume — quarantine + journal carry over -----------
    ckpt2 = SearchCheckpoint(ckdir)
    bench_b, count_b, inject_b, _ = _chaos_stack(rows, qpath, ckpt=ckpt2)
    restored = ckpt2.restore_into(bench_b, _graph())
    assert restored > 0
    res_mcts = explore(_graph(), plat, bench_b,
                       MctsOpts(n_iters=n_iters, seed=3, checkpoint=ckpt2))
    res_dfs = dfs_explore(_graph(), plat, bench_b,
                          DfsOpts(max_seqs=10_000, checkpoint=ckpt2))

    # zero crashes, and the chaos search found the clean run's best
    chaos_key, chaos_pct50 = _best(res_mcts.sims + res_dfs.sims)
    assert chaos_key == clean_key
    assert chaos_pct50 == clean_pct50

    # schedules measured before the kill were not re-measured after it
    for key, n in count_a.completed.items():
        assert count_b.completed[key] == 0, \
            "resume re-measured an already-measured schedule"

    # the chaos actually happened: >=20% transient injection rate and >=2
    # hangs (seeded — these counts are deterministic for fixed seeds)
    calls = inject_a.calls + inject_b.calls
    transients = (inject_a.injected["transient"]
                  + inject_b.injected["transient"])
    hangs = inject_a.injected["hang"] + inject_b.injected["hang"]
    dets = (inject_a.injected["deterministic"]
            + inject_b.injected["deterministic"])
    assert calls > 50
    assert transients >= 0.2 * calls
    assert hangs >= 2
    assert dets >= 1

    # every failure is a classified fault.* event: one fault.error per
    # injected failure (hangs surface as watchdog MeasurementTimeouts),
    # each carrying a taxonomy class
    errs = [e for e in tracer.events() if e.name == "fault.error"]
    assert len(errs) == transients + hangs + dets
    assert all(e.attrs["error_class"] in
               ("transient", "deterministic", "device_lost") for e in errs)
    assert any(e.attrs["error"] == "MeasurementTimeout" for e in errs)
    retries = [e for e in tracer.events() if e.name == "fault.retry"]
    assert len(retries) >= transients  # each transient/hang was retried

    # deterministic failures are quarantined, persist across the restart,
    # and each broken candidate was attempted at most once overall
    quar = Quarantine(qpath)
    assert len(quar) >= 1
    for sid in quar.entries:
        assert count_a.by_sid[sid] + count_b.by_sid[sid] <= 1
    qevents = [e for e in tracer.events() if e.name == "fault.quarantine"]
    assert {e.attrs["schedule"] for e in qevents} == set(quar.entries)
    # a re-query of a quarantined candidate — as after yet another restart
    # — is refused by the persisted quarantine without touching the device
    from tenzing_tpu.fault import QuarantinedScheduleError

    sid = next(iter(quar.entries))
    order = {**count_a.orders, **count_b.orders}[sid]
    before = count_a.by_sid[sid] + count_b.by_sid[sid]
    with pytest.raises(QuarantinedScheduleError):
        bench_b.benchmark(order, None)
    assert count_a.by_sid[sid] + count_b.by_sid[sid] == before
    assert registry.counter("fault.quarantine_hits").value >= 1


def test_resume_after_interrupt_no_remeasure_and_same_best(
        tmp_path, tracer, corpus):
    """The pure resume criterion, no chaos: kill a clean search
    mid-measurement, resume from the checkpoint, verify nothing measured
    before the kill is measured again and the final best matches an
    uninterrupted run exactly."""
    rows, _ = corpus
    plat = Platform.make_n_lanes(2)
    opts = dict(n_iters=24, seed=3)

    # uninterrupted reference
    ref_inner = CountingInner(mk_db(rows))
    ref = explore(_graph(), plat,
                  CachingBenchmarker(ResilientBenchmarker(
                      ref_inner, policy=_fast_policy())),
                  MctsOpts(**opts))
    ref_key, ref_pct50 = _best(ref.sims)
    assert ref_inner.total > 10

    # interrupted run: journaling on, SIGINT simulated mid-measurement
    ckdir = str(tmp_path / "ckpt")
    ckpt = SearchCheckpoint(ckdir)
    bundles = []
    inner1 = CountingInner(
        mk_db(rows), interrupt_after=9,
        on_interrupt=lambda: bundles.append(to_jsonl(get_tracer())))
    bench1 = CachingBenchmarker(JournalingBenchmarker(
        ResilientBenchmarker(inner1, policy=_fast_policy()), ckpt))
    with pytest.raises(KeyboardInterrupt):
        explore(_graph(), plat, bench1, MctsOpts(**opts, checkpoint=ckpt))
    _validate_bundle(bundles[0])  # complete, deadlock-free, spans closed

    # resume: restore the journal, re-run the same seeded search
    ckpt2 = SearchCheckpoint(ckdir)
    inner2 = CountingInner(mk_db(rows))
    bench2 = CachingBenchmarker(JournalingBenchmarker(
        ResilientBenchmarker(inner2, policy=_fast_policy()), ckpt2))
    restored = ckpt2.restore_into(bench2, _graph())
    assert restored == sum(inner1.completed.values()) > 0
    res = explore(_graph(), plat, bench2, MctsOpts(**opts, checkpoint=ckpt2))

    # no already-measured schedule was re-measured...
    for key in inner1.completed:
        assert inner2.attempts[key] == 0
    # ... every (schedule, fidelity) hit the device at most once overall...
    combined = inner1.completed + inner2.completed
    assert combined and max(combined.values()) == 1
    # ... and the resumed search reconstructs the reference exactly
    got_key, got_pct50 = _best(res.sims)
    assert (got_key, got_pct50) == (ref_key, ref_pct50)
    assert len(res.sims) == len(ref.sims)
    assert [s.result.pct50 for s in res.sims] == \
        [s.result.pct50 for s in ref.sims]
    # the resumed checkpoint now carries the completed cursor
    assert SearchCheckpoint(ckdir).load_state()["mcts"]["it"] == \
        opts["n_iters"] - 1


CORRUPT_SPEC = InjectSpec("corrupt", 0.25, 7)


def test_corruption_chaos_every_mutation_caught(tmp_path, tracer, registry,
                                                corpus):
    """Corruption-chaos acceptance (ISSUE 4): a seeded MCTS + exhaustive
    DFS with >= 20% schedule corruption — sync ops dropped/reordered by the
    injector, with the ORIGINAL oracle (EventSynchronizer, via
    tests/test_verify.oracle_unsound_check) deciding which mutations count,
    so the verifier under test is never consulted to pick them — must:

    * have every mutated candidate caught by the independent verifier and
      quarantined: ZERO unsound schedules measured;
    * still find the clean run's best schedule (the corruption seed is
      precondition-checked not to hit the best candidate, the same pattern
      as DET_SPEC above);
    * emit a ``verify.unsound`` event per catch.
    """
    from tenzing_tpu.bench.benchmarker import schedule_id
    from tenzing_tpu.solve.dfs import expand_all
    from tenzing_tpu.verify import ScheduleVerifier, verify_schedule

    from tests.test_verify import oracle_unsound_check

    rows, terminals = corpus
    g = _graph()
    plat = Platform.make_n_lanes(2)

    # clean reference
    mcts_clean = explore(g, plat, mk_db(rows), MctsOpts(n_iters=30, seed=3))
    dfs_clean = dfs_explore(g, plat, mk_db(rows), DfsOpts(max_seqs=10_000))
    clean_key, clean_pct50 = _best(mcts_clean.sims + dfs_clean.sims)

    # precondition: the corruption seed must not hit the best schedule in
    # either spelling the solvers query (a corrupted best is legitimately
    # unfindable — the run would catch it, but could not measure it)
    best_raw = min(terminals, key=lambda s: _synth_result(s).pct50)
    for spelling in (best_raw, remove_redundant_syncs(best_raw)):
        assert not _schedule_fails(schedule_id(spelling), CORRUPT_SPEC)

    # chaos stack: the corrupt injector sits ABOVE the resilient layer so
    # the verifier gate sees the mutated schedule (the bench.py layering)
    qpath = str(tmp_path / "quarantine.json")
    verifier = ScheduleVerifier(g)
    counting = CountingInner(mk_db(rows))
    quar = Quarantine(qpath)
    resilient = ResilientBenchmarker(
        counting, policy=_fast_policy(), quarantine=quar,
        verifier=verifier, sleep=lambda s: None)
    inject = FaultInjectingBenchmarker(
        resilient, [CORRUPT_SPEC],
        unsound_check=oracle_unsound_check(expand_all(g.clone())))
    bench = CachingBenchmarker(inject)

    res_mcts = explore(g, plat, bench, MctsOpts(n_iters=30, seed=3))
    res_dfs = dfs_explore(g, plat, bench, DfsOpts(max_seqs=10_000))

    # the chaos actually happened: >= 20% of the distinct candidates were
    # mutated (seeded by schedule identity at rate 0.25)
    assert inject.injected["corrupt"] >= 1
    assert len(inject.corrupted) >= 0.15 * len(terminals)

    # every mutated schedule was caught and quarantined; none was measured
    measured_sids = set(counting.by_sid)
    for orig, mutated in inject.corrupted.items():
        assert mutated in quar.entries, "a corruption escaped the verifier"
        assert mutated not in measured_sids
    # zero unsound schedules measured, full stop: everything that reached
    # the inner "device" re-verifies clean
    for order in counting.orders.values():
        assert verify_schedule(order, g).ok
    unsound_events = [e for e in tracer.events()
                      if e.name == "verify.unsound"]
    assert len(unsound_events) >= len(inject.corrupted)
    assert registry.counter("verify.unsound").value >= len(inject.corrupted)

    # the clean-run best was still found, with the identical measurement
    chaos_key, chaos_pct50 = _best(res_mcts.sims + res_dfs.sims)
    assert (chaos_key, chaos_pct50) == (clean_key, clean_pct50)


# compile-failure channel for the prefetch chaos test: the same seeded
# subset of schedules fails to compile in the background (FakeExecutor) AND
# in the foreground (the CompileGate below) — what a genuinely uncompilable
# candidate does with and without the pipeline
COMPILE_FAIL_SPEC = InjectSpec("deterministic", 0.1, 77)


def test_chaos_with_prefetch_matches_prefetch_off(tmp_path, tracer,
                                                  registry, corpus):
    """ISSUE 5 chaos acceptance: seeded fault injection with the async
    compile pipeline enabled must (a) produce bit-identical search results
    to prefetch-off, (b) classify background compile errors through the
    fault taxonomy and quarantine deterministic ones exactly once, and
    (c) leak no pipeline threads."""
    import threading

    from tenzing_tpu.bench.benchmarker import schedule_id
    from tenzing_tpu.bench.pipeline import PrefetchingBenchmarker

    from tests.test_pipeline_bench import FakeExecutor

    rows, terminals = corpus
    plat = Platform.make_n_lanes(2)

    def compile_fails(order) -> bool:
        return _schedule_fails(schedule_id(order), COMPILE_FAIL_SPEC)

    # precondition (the DET_SPEC pattern above): neither failure channel
    # may hit the best schedule in either spelling the solvers query
    best_raw = min(terminals, key=lambda s: _synth_result(s).pct50)
    for spelling in (best_raw, remove_redundant_syncs(best_raw)):
        assert not compile_fails(spelling)
        assert not _schedule_fails(schedule_id(spelling), DET_SPEC)
    fails = [s for s in terminals if compile_fails(s)]
    assert fails  # the compile-failure chaos actually has targets

    class CompileGate:
        """Foreground lazy-compile stand-in: the seeded subset fails before
        any measurement — above the tunnel-fault injector (a compile never
        reaches the device), below the counting layer."""

        def __init__(self, inner):
            self.inner = inner

        def benchmark(self, order, opts=None):
            if compile_fails(order):
                raise RuntimeError(
                    f"failed to compile (chaos {schedule_id(order)})")
            return self.inner.benchmark(order, opts)

    def run(qdir, prefetcher):
        inject = FaultInjectingBenchmarker(mk_db(rows), CHAOS_SPECS,
                                           hang_secs=2.5)
        counting = CountingInner(CompileGate(inject))
        quar = Quarantine(str(tmp_path / qdir / "quarantine.json"))
        resilient = ResilientBenchmarker(
            prefetcher if prefetcher is not None else counting,
            timeout_secs=1.0, policy=_fast_policy(), quarantine=quar,
            sleep=lambda s: None)
        bench = CachingBenchmarker(resilient)
        mcts = explore(_graph(), plat, bench,
                       MctsOpts(n_iters=30, seed=3,
                                prefetch=prefetcher))
        dfs = dfs_explore(_graph(), plat, bench,
                          DfsOpts(max_seqs=10_000, prefetch=prefetcher))
        return mcts, dfs, counting, quar

    off_mcts, off_dfs, off_count, off_quar = run("off", None)

    ex = FakeExecutor(fail=lambda o: RuntimeError(
        f"failed to compile (chaos {schedule_id(o)})")
        if compile_fails(o) else None)
    inject_on = FaultInjectingBenchmarker(mk_db(rows), CHAOS_SPECS,
                                          hang_secs=2.5)
    count_on = CountingInner(CompileGate(inject_on))
    p = PrefetchingBenchmarker(count_on, executor=ex, workers=2)
    try:
        # a guaranteed background-compile failure (solver hints are
        # speculative; this pins the classified-surfacing assertion)
        p.prefetch([fails[0]])
        quar_on = Quarantine(str(tmp_path / "on" / "quarantine.json"))
        resilient_on = ResilientBenchmarker(
            p, timeout_secs=1.0, policy=_fast_policy(), quarantine=quar_on,
            sleep=lambda s: None)
        bench_on = CachingBenchmarker(resilient_on)
        on_mcts = explore(_graph(), plat, bench_on,
                          MctsOpts(n_iters=30, seed=3, prefetch=p))
        on_dfs = dfs_explore(_graph(), plat, bench_on,
                             DfsOpts(max_seqs=10_000, prefetch=p))
        assert p.issued > 0
    finally:
        p.close()

    # (a) bit-identical to prefetch-off, and both find the clean best
    sims_key = lambda res: [(_key(s.order), s.result.pct50)
                            for s in res.sims]
    assert sims_key(on_mcts) == sims_key(off_mcts)
    assert sims_key(on_dfs) == sims_key(off_dfs)
    assert _best(on_mcts.sims + on_dfs.sims) == \
        _best(off_mcts.sims + off_dfs.sims) == \
        (_key(best_raw), _synth_result(best_raw).pct50)

    # (b) background failures were classified + surfaced, and every
    # deterministic failure (compile chaos or injected) quarantined with
    # the candidate measured at most once overall
    assert p.failed >= 1 and p.surfaced >= 1
    pevs = [e for e in tracer.events()
            if e.name == "pipeline.precompile_failed"]
    assert pevs and all(
        e.attrs["error_class"] == "deterministic" for e in pevs)
    assert set(quar_on.entries) == set(off_quar.entries)
    for sid in quar_on.entries:
        assert count_on.by_sid[sid] + off_count.by_sid[sid] <= 2  # <=1 each
        assert count_on.by_sid[sid] <= 1

    # (c) no leaked pipeline threads
    assert not [t for t in threading.enumerate()
                if t.name.startswith("tz-prefetch") and t.is_alive()]


def test_device_lost_without_fallback_escalates_out_of_search(corpus):
    """Device loss is fatal, never a per-candidate verdict: with no
    degradation fallback the search must abort, not grind through every
    remaining candidate re-discovering the dead chip."""
    from tenzing_tpu.fault import DeviceLostError

    rows, _ = corpus
    plat = Platform.make_n_lanes(2)
    inject = FaultInjectingBenchmarker(
        mk_db(rows), [InjectSpec("device_lost", 1.0, 9)])
    rb = ResilientBenchmarker(inject, policy=_fast_policy(),
                              sleep=lambda s: None)
    with pytest.raises(DeviceLostError):
        explore(_graph(), plat, rb, MctsOpts(n_iters=5, seed=3))
    with pytest.raises(DeviceLostError):
        dfs_explore(_graph(), plat, rb, DfsOpts(max_seqs=10_000))


def test_device_lost_with_fallback_finishes_degraded(corpus, tracer):
    """Graceful degradation: with a fallback benchmarker the search
    completes, and every post-loss answer is attributable via
    was_degraded (the fid=degraded dump tag)."""
    rows, _ = corpus
    plat = Platform.make_n_lanes(2)

    class Fallback:
        def benchmark(self, order, opts=None):
            return _synth_result(order)

    # lose the device on the 4th measurement
    inner = CountingInner(mk_db(rows))
    calls = {"n": 0}

    class LoseAfter:
        def benchmark(self, order, opts=None):
            from tenzing_tpu.fault import DeviceLostError

            calls["n"] += 1
            if calls["n"] == 4:
                raise DeviceLostError("tunnel torn down")
            return inner.benchmark(order, opts)

    rb = ResilientBenchmarker(LoseAfter(), policy=_fast_policy(),
                              fallback=Fallback(), sleep=lambda s: None)
    res = explore(_graph(), plat, CachingBenchmarker(rb),
                  MctsOpts(n_iters=12, seed=3))
    assert rb.degraded
    assert len(res.sims) == 12  # the search FINISHED
    degraded = [s for s in res.sims if rb.was_degraded(s.order)]
    assert degraded  # post-loss answers exist and are attributable
    assert any(e.name == "fault.degraded" for e in tracer.events())
