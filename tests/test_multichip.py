"""Distributed SpMV on the 8-virtual-device CPU mesh: sharded buffers, ppermute
halo exchange, every searched schedule numerically correct."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.spmv_dist import DistSpMV, make_dist_spmv_buffers
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


def make_setup(dp, sp, rows=32, batch=4):
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[: dp * sp])
    mesh = Mesh(devs.reshape(dp, sp), ("dp", "sp"))
    bufs, specs, want = make_dist_spmv_buffers(
        n_sp=sp, batch=batch, rows_per_shard=rows, nnz_per_row=4, seed=0
    )
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    plat = Platform.make_n_lanes(2, mesh=mesh, specs=specs)
    g = Graph()
    g.start_then(DistSpMV())
    g.then_finish(DistSpMV())
    return g, plat, TraceExecutor(plat, bufs), want


@pytest.mark.needs_shard_map
def test_dist_spmv_correct_on_2x4_mesh():
    g, plat, ex, want = make_setup(dp=2, sp=4)
    st = get_all_sequences(g, plat, max_seqs=1)[0]
    out = ex.run(st.sequence)
    np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-3)


@pytest.mark.needs_shard_map
def test_dist_spmv_all_schedules_agree_on_1x4_mesh():
    g, plat, ex, want = make_setup(dp=1, sp=4, rows=16, batch=2)
    states = get_all_sequences(g, plat, max_seqs=6)
    assert states
    for st in states:
        out = ex.run(st.sequence)
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-3)


@pytest.mark.needs_shard_map
def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert "y" in out
