"""Lease epoch fencing on hostile clocks (serve/lease.py; ISSUE 19
satellite): coarse or skewed observed mtimes let a rival reclaim a LIVE
lease — the first half documents that hole (it is real and allowed);
the second half proves the fencing-token registry catches the
superseded holder before any guarded effect lands."""

import json
import os

import pytest

from tenzing_tpu.fault import fsinject
from tenzing_tpu.fault.errors import FencedWriteError
from tenzing_tpu.serve.lease import (
    LeaseFile,
    check_epoch,
    epoch_registry_of,
    issued_epoch,
)
from tenzing_tpu.utils import atomic


# floors the observed mtime to the minute AND skews it a full minute
# back: any sub-minute TTL sees every lease as expired, deterministically
# (a plain coarse-only spec would flake when wall-clock sits near a
# granularity boundary)
HOSTILE_CLOCK = "mtime_coarse:1.0:{s}:60,mtime_skew:1.0:{s}:60"


@pytest.fixture(autouse=True)
def _clean_backend():
    fsinject.uninstall()
    yield
    fsinject.uninstall()


def _lease(tmp_path, owner, ttl=30.0):
    return LeaseFile(str(tmp_path / "lease-item.json"), owner,
                     ttl_secs=ttl)


# -- the hole (pre-fencing behavior, documented) ------------------------------

def test_coarse_clock_reclaims_a_live_lease(tmp_path):
    """THE HOLE: on a coarse/skewed filesystem the expiry clock lies,
    so a rival legitimately reclaims a lease whose holder is alive and
    heartbeating.  The protocol allows this — expiry decisions can only
    trust the observed clock — which is exactly why effects must be
    fenced rather than the claim prevented."""
    a = _lease(tmp_path, "alice")
    info_a = a.claim()
    assert info_a is not None and not info_a.reclaimed

    fsinject.install(HOSTILE_CLOCK.format(s=11))
    b = _lease(tmp_path, "bob")
    info_b = b.claim()
    assert info_b is not None and info_b.reclaimed  # live lease stolen
    assert info_b.prev_owner == "alice"

    # the nonce re-read catches alice at her NEXT heartbeat...
    assert not a.owns() and not a.renew()
    # ...but between heartbeats she believes she holds the lease: that
    # window is what the epoch fence closes (tests below)


def test_stale_read_defeats_the_nonce_check_alone(tmp_path):
    """THE DEEPER HOLE: an NFS-style stale read can serve the zombie
    her OWN superseded payload, so even the nonce re-read says 'still
    yours'.  owns() lies; only the fence tells the truth."""
    a = _lease(tmp_path, "alice")
    a.claim()
    stale_payload = json.load(open(a.path))  # alice's live payload

    fsinject.install(HOSTILE_CLOCK.format(s=13))
    b = _lease(tmp_path, "bob")
    assert b.claim().reclaimed

    class _StaleOnce:
        """Serve alice's superseded lease payload to one read — the
        seam protocol's read-path checkpoint, canned."""

        def __init__(self):
            self.served = False

        def check(self, op, path):
            pass

        def observe_mtime(self, path, mtime):
            return mtime

        def maybe_stale_json(self, path):
            if not self.served and path == a.path:
                self.served = True
                return stale_payload
            return None

    atomic.set_io_backend(_StaleOnce())
    try:
        assert a.owns()  # the lie: nonce check passes on stale bytes
        with pytest.raises(FencedWriteError):
            a.check_fence()  # the fence is not fooled
    finally:
        atomic.set_io_backend(None)


# -- the fix (epoch fencing) --------------------------------------------------

def test_epoch_fences_zombie_and_passes_holder(tmp_path):
    a = _lease(tmp_path, "alice")
    assert a.claim().epoch == 1

    fsinject.install(HOSTILE_CLOCK.format(s=17))
    b = _lease(tmp_path, "bob")
    assert b.claim().epoch == 2
    fsinject.uninstall()

    assert issued_epoch(a.path) == 2
    b.check_fence()  # live holder: no-op
    with pytest.raises(FencedWriteError):
        a.check_fence()  # superseded holder: refused
    with pytest.raises(FencedWriteError):
        check_epoch(a.path, 1)  # same check, functional form


def test_purge_restarts_epochs_for_fresh_work(tmp_path):
    """The completing holder purges the registry once the guarded
    effect landed: a fresh item at the same lease path restarts epochs
    from 1 rather than inheriting a dead item's history."""
    a = _lease(tmp_path, "alice")
    a.claim()
    a.release()
    a.purge_epochs()
    assert issued_epoch(a.path) == 0
    assert not os.path.isdir(epoch_registry_of(a.path))

    b = _lease(tmp_path, "bob")
    assert b.claim().epoch == 1


def test_unfenced_claim_degrades_to_nonce_checks(tmp_path):
    """A claim whose epoch marker never landed (registry unwritable)
    still holds the lease; check_fence() is then a no-op — fencing
    degrades, it never blocks the claim itself."""
    a = _lease(tmp_path, "alice")
    info = a.claim()
    assert info is not None
    a.epoch = None  # as if _record_epoch had failed
    a.check_fence()  # no raise: falls back to nonce protection
    assert a.owns()


def test_registry_trims_to_epoch_keep(tmp_path):
    """Successive reclaim generations must not grow the registry without
    bound; only the newest EPOCH_KEEP markers survive."""
    from tenzing_tpu.serve.lease import EPOCH_KEEP

    path = str(tmp_path / "lease-item.json")
    fsinject.install(HOSTILE_CLOCK.format(s=19))
    last = None
    for g in range(EPOCH_KEEP + 4):
        holder = LeaseFile(path, f"gen-{g}", ttl_secs=30.0)
        last = holder.claim()
    assert last.epoch == EPOCH_KEEP + 4
    markers = [n for n in os.listdir(epoch_registry_of(path))
               if n.startswith("c-")]
    assert len(markers) <= EPOCH_KEEP
    assert issued_epoch(path) == EPOCH_KEEP + 4
