"""Serving acceptance: the three resolution tiers, deterministically on
CPU (ISSUE 7).  Exact hits are zero-compile/zero-measurement and
re-verified; near misses carry surrogate uncertainty + ``was_predicted``
provenance and flag the answering entry for refinement; cold requests
round-trip through the checkpointed work-queue format.  Plus: the
unsound-entry guard (a poisoned store must never serve), the
uncertainty gate, store merge through the service, and the CLI.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from tenzing_tpu.bench.benchmarker import BenchResult, result_row
from tenzing_tpu.bench.driver import DriverRequest, graph_for
from tenzing_tpu.serve.fingerprint import fingerprint_of, schedule_key
from tenzing_tpu.serve.resolver import Resolver
from tenzing_tpu.serve.service import ScheduleService
from tenzing_tpu.serve.store import ScheduleStore, WorkQueue

REQ = DriverRequest(workload="spmv", m=512)
NEAR_REQ = DriverRequest(workload="spmv", m=500)      # same bucket
COLD_REQ = DriverRequest(workload="spmv", m=100_000)  # different bucket


def _drive(g, n_lanes, picks):
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State

    plat = Platform.make_n_lanes(n_lanes)
    st = State(g)
    i = 0
    while not st.is_terminal():
        ds = st.get_decisions(plat)
        st = st.apply(ds[picks[i % len(picks)] % len(ds)])
        i += 1
    return st.sequence


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """A synthetic recorded-search database for the spmv/512 workload:
    row 0 the naive anchor at full fidelity, then distinct 2-lane
    schedules beating it — the dump format the warm path mines
    (bench.py --dump-csv invariants included)."""
    import itertools

    d = tmp_path_factory.mktemp("serve_corpus")
    g, _ = graph_for(REQ)
    naive = _drive(g, 1, [0])
    alts, seen = [], set()
    for picks in itertools.product((0, 1, 2), repeat=3):
        s = _drive(g, 2, list(picks))
        k = schedule_key(s)
        if k not in seen:
            seen.add(k)
            alts.append(s)
        if len(alts) >= 8:
            break
    rows = [result_row(0, BenchResult.from_times([2.0, 2.1, 2.05]), naive)]
    for i, a in enumerate(alts):
        t = 1.0 + 0.1 * i
        rows.append(result_row(
            i + 1, BenchResult.from_times([t, t * 1.02, t * 0.99]), a))
    path = d / "spmv_search.csv"
    path.write_text("\n".join(rows) + "\n")
    return {"csv": str(path), "graph": g, "naive": naive, "alts": alts}


@pytest.fixture(scope="module")
def warmed(tmp_path_factory, corpus):
    d = tmp_path_factory.mktemp("serve_state")
    svc = ScheduleService(str(d / "store.json"),
                          queue_dir=str(d / "queue"))
    summary = svc.warm(REQ, [corpus["csv"]], topk=2)
    return {"svc": svc, "summary": summary, "dir": d}


def test_warm_mines_topk_and_trains(warmed):
    s = warmed["summary"]
    assert s["added"] == 2
    assert s["rows"] >= 8
    model = s["model"]
    assert "error" not in model
    assert model["rows"] >= 8
    # the store is self-contained: model saved next to it
    assert os.path.exists(warmed["svc"].model_path)
    # provenance carries the source corpus digest
    rec = warmed["svc"].store.best(fingerprint_of(REQ).exact_digest)
    assert len(rec["sources"]) == 1 and len(rec["sources"][0]) == 64


def test_exact_hit_zero_compile_verified(warmed):
    res = warmed["svc"].query(REQ)
    assert res.tier == "exact"
    p = res.provenance
    assert p["verified"] is True
    assert p["was_predicted"] is False
    assert p["compiles"] == 0 and p["measurements"] == 0
    # the stored winner: best in-file paired ratio of the corpus
    assert res.vs_naive == pytest.approx(2.05, rel=0.02)
    assert res.sequence is not None and len(res.sequence) > 0
    # deterministic: the same request resolves identically
    again = warmed["svc"].query(REQ)
    assert again.record["key"] == res.record["key"]


def test_near_miss_predicted_flagged_and_queued(warmed):
    svc = warmed["svc"]
    res = svc.query(NEAR_REQ)
    assert res.tier == "near"
    p = res.provenance
    assert p["was_predicted"] is True
    assert p["uncertainty"] is not None and p["uncertainty"] >= 0
    assert p["compiles"] == 0 and p["measurements"] == 0
    assert res.vs_naive is not None  # the model's predicted paired ratio
    # the answering entry is flagged for refinement...
    rec = svc.store.best(fingerprint_of(REQ).exact_digest)
    assert rec["flags"].get("needs_refinement") is True
    # ...and the requested fingerprint is queued for a background search
    items = svc.queue.items()
    reasons = {i[1]["reason"] for i in items}
    assert "refine-near-miss" in reasons
    near_fp = fingerprint_of(NEAR_REQ)
    assert any(i[1]["fingerprint"]["exact"] == near_fp.exact_digest
               for i in items)


def test_cold_writes_checkpointed_work_item(warmed):
    from tenzing_tpu.fault.checkpoint import read_checked_json

    svc = warmed["svc"]
    res = svc.query(COLD_REQ)
    assert res.tier == "cold"
    assert res.work_item is not None and os.path.exists(res.work_item)
    payload = read_checked_json(res.work_item)  # envelope digest verifies
    assert payload["kind"] == "search_request"
    assert payload["reason"] == "cold"
    # the payload IS a drainable DriverRequest; its checkpoint dir makes
    # the queued search itself kill-resumable
    drained = DriverRequest(**payload["request"])
    assert drained.workload == "spmv" and drained.m == 100_000
    assert payload["checkpoint"]


def test_uncertainty_gate_demotes_near_to_cold(warmed, tmp_path):
    svc = warmed["svc"]
    strict = Resolver(svc.store, queue=WorkQueue(str(tmp_path / "q")),
                      model=svc.model, near_max_sigma=0.0)
    res = strict.resolve(NEAR_REQ)
    assert res.tier == "cold"  # every prediction is too uncertain to serve


def test_without_model_near_demotes_to_cold(warmed, tmp_path):
    svc = warmed["svc"]
    unpriced = Resolver(svc.store, queue=WorkQueue(str(tmp_path / "q")),
                        model=None)
    assert unpriced.resolve(NEAR_REQ).tier == "cold"


def test_unsound_store_entry_flagged_not_served(corpus, tmp_path):
    """The re-verification guard: a stored schedule that fails the
    independent verifier (here: all its syncs stripped — racy by
    construction) must never be served, only flagged."""
    from tenzing_tpu.core.sequence import Sequence
    from tenzing_tpu.core.sync_ops import SyncOp

    g = corpus["graph"]
    winner = corpus["alts"][0]
    stripped = Sequence([op for op in winner if not isinstance(op, SyncOp)])
    store = ScheduleStore(str(tmp_path / "store.json"))
    store.add(fingerprint_of(REQ), stripped, pct50_us=1.0, vs_naive=99.0)
    r = Resolver(store, queue=WorkQueue(str(tmp_path / "q")))
    res = r.resolve(REQ)
    assert res.tier == "cold"  # not served
    rec = store.best(fingerprint_of(REQ).exact_digest)
    assert rec["flags"].get("unsound") is True


def test_unsound_best_does_not_block_sound_runner_up(corpus, tmp_path):
    """The exact tier walks records best-first: an unsound best record
    (here vs_naive 99 with its syncs stripped) must not permanently
    demote a fingerprint with a sound runner-up to cold — the near tier
    excludes the requester's own digest, so exact is the only tier that
    can serve it."""
    from tenzing_tpu.core.sequence import Sequence
    from tenzing_tpu.core.sync_ops import SyncOp

    winner = corpus["alts"][0]
    stripped = Sequence([op for op in winner if not isinstance(op, SyncOp)])
    store = ScheduleStore(str(tmp_path / "store.json"))
    fp = fingerprint_of(REQ)
    store.add(fp, stripped, pct50_us=1.0, vs_naive=99.0)   # poisoned best
    store.add(fp, corpus["alts"][1], pct50_us=2.0, vs_naive=1.4)  # sound
    r = Resolver(store, queue=WorkQueue(str(tmp_path / "q")))
    res = r.resolve(REQ)
    assert res.tier == "exact"
    assert res.vs_naive == 1.4  # the sound runner-up, not the poisoned 99
    assert res.provenance["verified"] is True
    bad = [rec for rec in store.records() if rec["vs_naive"] == 99.0][0]
    assert bad["flags"].get("unsound") is True


def test_merge_through_service_is_lossless(corpus, tmp_path):
    a = ScheduleService(str(tmp_path / "a.json"))
    b = ScheduleService(str(tmp_path / "b.json"))
    a.warm(REQ, [corpus["csv"]], topk=1, train=False)
    b.warm(DriverRequest(workload="spmv", m=700), [corpus["csv"]],
           topk=1, train=False)
    out = a.merge(str(tmp_path / "b.json"))
    assert out["records"] == 2
    stats = a.stats()["store"]
    assert stats["fingerprints"] == 2 and stats["records"] == 2


def test_warm_into_missing_nested_directory(corpus, tmp_path):
    """The CLI promises the store is created on first flush: warming
    into a not-yet-existing directory must create it for the store,
    the model, and (on first enqueue) the queue."""
    d = tmp_path / "fleet" / "stores"
    svc = ScheduleService(str(d / "store.json"),
                          queue_dir=str(d / "queue"))
    s = svc.warm(REQ, [corpus["csv"]], topk=1)
    assert s["added"] == 1 and "error" not in s["model"]
    assert os.path.exists(d / "store.json")
    assert os.path.exists(svc.model_path)


def test_serve_counters_land(warmed):
    from tenzing_tpu.obs.metrics import get_metrics

    reg = get_metrics()
    # the tier counters observed by the queries above (exact-hit test ran
    # two exact queries; near/cold at least one each)
    assert reg.counter("serve.exact").value >= 2
    assert reg.counter("serve.near").value >= 1
    assert reg.counter("serve.cold").value >= 1


def test_cli_query_round_trip(warmed):
    """The ``python -m tenzing_tpu.serve`` CLI answers the same exact
    hit the in-process service does, as one JSON line on stdout."""
    repo = str(Path(__file__).resolve().parent.parent)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    r = subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.serve", "query",
         "--store", str(warmed["dir"] / "store.json"),
         "--queue", str(warmed["dir"] / "queue"),
         "--workload", "spmv", "--m", "512"],
        capture_output=True, text=True, env=env, cwd=repo, check=True)
    doc = json.loads(r.stdout.strip())
    assert doc["tier"] == "exact"
    assert doc["provenance"]["verified"] is True
    assert doc["provenance"]["compiles"] == 0
    assert doc["ops"], "the answer carries the serialized schedule"
