"""Graph construction/surgery (reference src/graph.cpp:422-501 inline tests)."""

import pytest

from tenzing_tpu.core.graph import Graph, get_equivalence, is_equivalent_lane_mapping
from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    CompoundOp,
    DeviceOp,
    NoOp,
)
from tenzing_tpu.core.resources import Lane


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


def test_empty_graph():
    g = Graph()
    assert g.vertex_size() == 2  # start, finish
    assert g.start() in g and g.finish() in g


def test_then_chain():
    g = Graph()
    a, b = NoOp("a"), NoOp("b")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    assert g.vertex_size() == 4
    assert g.succs(a) == [b]
    assert g.preds(b) == [a]
    assert g.frontier([g.start()]) == [a]
    assert g.frontier([g.start(), a]) == [b]
    assert g.frontier([g.start(), a, b]) == [g.finish()]


def test_clone_membership():
    g = Graph()
    a = NoOp("a")
    g.start_then(a)
    g.then_finish(a)
    c = g.clone()
    assert c.vertex_size() == g.vertex_size()
    assert a in c
    c.then(a, NoOp("x"))
    assert NoOp("x") not in g  # clone is independent


def test_clone_but_replace_lane_binding():
    g = Graph()
    k = KOp("k")
    g.start_then(k)
    g.then_finish(k)
    g2 = g.clone_but_replace(k.bind(Lane(1)), k)
    assert g2.vertex_size() == 3
    # identity is preserved; the stored vertex object is now bound
    v = [x for x in g2.vertices() if x == k][0]
    assert isinstance(v, BoundDeviceOp) and v.lane() == Lane(1)
    # original untouched
    v0 = [x for x in g.vertices() if x == k][0]
    assert not isinstance(v0, BoundDeviceOp)


class TwoOpCompound(CompoundOp):
    def __init__(self, name):
        super().__init__(name)
        self._g = Graph()
        self._a, self._b = NoOp(name + ".a"), NoOp(name + ".b")
        self._g.start_then(self._a)
        self._g.then(self._a, self._b)
        self._g.then_finish(self._b)

    def graph(self):
        return self._g


def test_clone_but_expand():
    g = Graph()
    comp = TwoOpCompound("c")
    pre, post = NoOp("pre"), NoOp("post")
    g.start_then(pre)
    g.then(pre, comp)
    g.then(comp, post)
    g.then_finish(post)
    g2 = g.clone_but_expand(comp)
    assert comp not in g2
    assert NoOp("c.a") in g2 and NoOp("c.b") in g2
    # pre -> c.a -> c.b -> post
    assert g2.succs(pre) == [NoOp("c.a")]
    assert g2.succs(NoOp("c.a")) == [NoOp("c.b")]
    assert g2.succs(NoOp("c.b")) == [post]
    # start/finish untouched
    assert g2.vertex_size() == 6


def test_graph_equivalence_lane_bijection():
    def make(l0, l1):
        g = Graph()
        a, b = KOp("a"), KOp("b")
        g.start_then(a.bind(l0))
        g.start_then(b.bind(l1))
        g.then_finish(a.bind(l0))
        g.then_finish(b.bind(l1))
        return g

    # consistent renaming 0<->1 is equivalent
    assert is_equivalent_lane_mapping(make(Lane(0), Lane(1)), make(Lane(1), Lane(0)))
    assert is_equivalent_lane_mapping(make(Lane(0), Lane(0)), make(Lane(1), Lane(1)))
    # same-lane vs distinct-lane is NOT
    assert not is_equivalent_lane_mapping(make(Lane(0), Lane(0)), make(Lane(0), Lane(1)))


def test_use_lanes_enumeration():
    g = Graph()
    a, b = KOp("a"), KOp("b")
    g.start_then(a)
    g.then(a, b)
    g.then_finish(b)
    gs = g.use_lanes([Lane(0), Lane(1)])
    assert len(gs) == 4
    uniq = []
    for cand in gs:
        if not any(is_equivalent_lane_mapping(cand, u) for u in uniq):
            uniq.append(cand)
    # {same lane, different lanes} up to renaming
    assert len(uniq) == 2


def test_graphviz_dump():
    g = Graph()
    g.start_then(NoOp("a"))
    g.then_finish(NoOp("a"))
    dot = g.dump_graphviz()
    assert "digraph" in dot and '"a"' in dot
