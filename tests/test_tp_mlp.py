"""Tensor-parallel MLP: DAG shape, schedule search, and sharded numerics vs
the host evaluation of the unsharded layer stack (models/tp_mlp.py)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.tp_mlp import TpMlp, TpMlpArgs, make_tp_mlp_buffers
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


def _graph(args):
    g = Graph()
    g.start_then(TpMlp(args))
    g.then_finish(TpMlp(args))
    return g


def _mesh(ntp):
    devs = np.array(jax.devices()[:ntp])
    return Mesh(devs, ("tp",))


class TestDagShape:
    def test_chunk_chains_are_independent(self):
        """Chunk 0's all-reduce and chunk 1's matmuls must be DAG-independent
        — the comm/compute overlap the solver searches."""
        args = TpMlpArgs(n_tp=2, n_layers=2, n_chunks=2)
        g = TpMlp(args).graph()
        by_name = {v.name(): v for v in g.vertices()}
        p0, m1 = by_name["psum_0_0"], by_name["mlp_1_0"]
        assert m1 not in g.succs(p0) and p0 not in g.succs(m1)

    def test_post_wait_split(self):
        args = TpMlpArgs(n_tp=2, n_layers=1, n_chunks=1)
        g = TpMlp(args).graph()
        by_name = {v.name(): v for v in g.vertices()}
        assert by_name["await_0_0"] in g.succs(by_name["psum_0_0"])

    def test_schedule_space_is_nontrivial(self):
        args = TpMlpArgs(n_tp=2, n_layers=1, n_chunks=2)
        plat = Platform.make_n_lanes(2)
        seqs = get_all_sequences(_graph(args), plat, max_seqs=50)
        assert len(seqs) > 1


@pytest.mark.needs_shard_map
class TestNumerics:
    @pytest.mark.parametrize("ntp,layers,chunks", [(2, 2, 2), (4, 3, 2), (4, 1, 1)])
    def test_matches_unsharded_stack(self, ntp, layers, chunks):
        args = TpMlpArgs(n_tp=ntp, n_layers=layers, n_chunks=chunks,
                         mb_size=4, d_model=8, d_ff=16)
        bufs, specs, want = make_tp_mlp_buffers(args, seed=1)
        plat = Platform.make_n_lanes(2, mesh=_mesh(ntp), specs=specs)
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        order = get_all_sequences(_graph(args), plat, max_seqs=1)[0].sequence
        out = ex.run(order)
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-4,
                                   atol=2e-5)

    def test_dp_tp_composed_mesh(self):
        """The 2-D training layout: batch rows sharded over dp, weights over
        tp, all-reduce confined to the tp axis."""
        args = TpMlpArgs(n_tp=2, n_layers=2, n_chunks=2, mb_size=4,
                         d_model=8, d_ff=16)
        bufs, specs, want = make_tp_mlp_buffers(args, seed=4, n_dp=2)
        devs = np.array(jax.devices()[:4]).reshape(2, 2)
        mesh = Mesh(devs, ("dp", "tp"))
        plat = Platform.make_n_lanes(2, mesh=mesh, specs=specs)
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        order = get_all_sequences(_graph(args), plat, max_seqs=1)[0].sequence
        out = ex.run(order)
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-4,
                                   atol=2e-5)

    def test_every_schedule_is_equivalent(self):
        args = TpMlpArgs(n_tp=2, n_layers=1, n_chunks=2, mb_size=2,
                         d_model=4, d_ff=8)
        bufs, specs, want = make_tp_mlp_buffers(args, seed=2)
        plat = Platform.make_n_lanes(2, mesh=_mesh(2), specs=specs)
        seqs = get_all_sequences(_graph(args), plat, max_seqs=6)
        assert len(seqs) >= 2
        ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
        for s in seqs:
            out = ex.run(s.sequence)
            np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-4,
                                       atol=2e-5)
