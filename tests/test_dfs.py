"""DFS solver + CsvBenchmarker replay (reference dfs.hpp, benchmarker.cpp:169-223)."""

import pytest

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult, CsvBenchmarker, result_row
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import DeviceOp, NoOp
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.solve.dfs import DfsOpts, explore, get_all_sequences


class KOp(DeviceOp):
    def apply(self, bufs, ctx):
        return {}


class FakePlatform:
    def __init__(self, n):
        self.lanes = [Lane(i) for i in range(n)]


class CountingBenchmarker:
    """Deterministic fake: schedules get times by call order."""

    def __init__(self):
        self.calls = 0

    def benchmark(self, order, opts=None):
        self.calls += 1
        t = 1.0 / self.calls
        return BenchResult(pct01=t, pct10=t, pct50=t, pct90=t, pct99=t, stddev=0.0)


def chain_graph(names):
    g = Graph()
    prev = None
    for n in names:
        op = NoOp(n)
        if prev is None:
            g.start_then(op)
        else:
            g.then(prev, op)
        prev = op
    g.then_finish(prev)
    return g


def test_get_all_sequences_chain_has_one_schedule():
    g = chain_graph(["a", "b", "c"])
    states = get_all_sequences(g, FakePlatform(1))
    assert len(states) == 1
    assert states[0].sequence.desc() == "start, a, b, c, finish"


def test_get_all_sequences_dedups_lane_renamings():
    g = Graph()
    k = KOp("k")
    g.start_then(k)
    g.then_finish(k)
    states = get_all_sequences(g, FakePlatform(2))
    # lane0/lane1 bindings are equivalent: exactly one schedule survives
    assert len(states) == 1


def test_explore_benchmarks_each_unique_schedule():
    g = Graph()
    a, b = NoOp("a"), NoOp("b")
    g.start_then(a)
    g.start_then(b)
    g.then_finish(a)
    g.then_finish(b)
    bench = CountingBenchmarker()
    res = explore(g, FakePlatform(1), bench, DfsOpts(bench_opts=BenchOpts(n_iters=1)))
    assert bench.calls == 2
    assert len(res.sims) == 2
    best = res.best()
    assert best is not None and best.result.pct10 == 0.5


def test_explore_max_seqs_cap():
    g = Graph()
    for n in ["a", "b", "c"]:
        g.start_then(NoOp(n))
        g.then_finish(NoOp(n))
    bench = CountingBenchmarker()
    res = explore(g, FakePlatform(1), bench, DfsOpts(max_seqs=2))
    assert len(res.sims) <= 2


def test_csv_roundtrip_and_equivalence_lookup():
    g = Graph()
    x, y = KOp("x"), KOp("y")
    g.start_then(x)
    g.then(x, y)
    g.then_finish(y)
    order = Sequence([g.start(), x.bind(Lane(0)), y.bind(Lane(0)), g.finish()])
    res = BenchResult(pct01=0.1, pct10=0.2, pct50=0.3, pct90=0.4, pct99=0.5, stddev=0.01)
    row = result_row(0, res, order)
    db = CsvBenchmarker([row], g)
    # exact schedule
    assert db.benchmark(order).pct50 == 0.3
    # lane-renamed schedule matches by bijection equivalence
    renamed = Sequence([g.start(), x.bind(Lane(1)), y.bind(Lane(1)), g.finish()])
    assert db.benchmark(renamed).pct50 == 0.3
    # a different order does not
    with pytest.raises(KeyError):
        db.benchmark(Sequence([g.start(), y.bind(Lane(0)), x.bind(Lane(0)), g.finish()]))


def test_csv_handles_delimiter_in_op_name():
    g = Graph()
    x = KOp("a|b")  # hostile name containing the CSV delimiter
    g.start_then(x)
    g.then_finish(x)
    order = Sequence([g.start(), x.bind(Lane(0)), g.finish()])
    res = BenchResult(0.1, 0.1, 0.1, 0.1, 0.1, 0.0)
    db = CsvBenchmarker([result_row(0, res, order)], g)
    assert db.benchmark(order).pct10 == 0.1


def test_trap_handlers_restored_after_explore():
    import signal

    before = signal.getsignal(signal.SIGINT)
    g = chain_graph(["a"])
    explore(g, FakePlatform(1), CountingBenchmarker(), DfsOpts())
    assert signal.getsignal(signal.SIGINT) is before


def test_dfs_csv_dump_reloads(tmp_path):
    g = chain_graph(["a", "b"])
    bench = CountingBenchmarker()
    path = str(tmp_path / "results.csv")
    res = explore(g, FakePlatform(1), bench, DfsOpts(dump_csv_path=path))
    db = CsvBenchmarker.from_file(path, g)
    assert db.benchmark(res.sims[0].order).pct50 == res.sims[0].result.pct50


def test_explore_batch_mode_decorrelated():
    """DfsOpts(batch=True) benchmarks the whole enumerated set through
    benchmark_batch_times (reference benchmarker.cpp:21-76) — the one-at-a-time
    benchmark() path must NOT run, and the raw series must be iteration-aligned
    (one measurement per schedule per iteration)."""
    import jax.numpy as jnp

    from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.dfs import DfsOpts, explore

    bufs, _ = make_spmv_buffers(m=32, nnz_per_row=2, seed=0)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    plat = Platform.make_n_lanes(2)

    calls = {"batch": 0, "single": 0}

    class Counting(EmpiricalBenchmarker):
        def benchmark_batch_times(self, orders, opts=None, seed=0, times_out=None):
            calls["batch"] += 1
            calls["seed"] = seed
            return super().benchmark_batch_times(orders, opts, seed, times_out)

        def benchmark(self, order, opts=None):
            calls["single"] += 1
            return super().benchmark(order, opts)

    bench = Counting(TraceExecutor(plat, bufs))
    res = explore(
        g, plat, bench,
        DfsOpts(max_seqs=5, bench_opts=BenchOpts(n_iters=2, target_secs=1e-4),
                batch=True, batch_seed=7),
    )
    assert calls == {"batch": 1, "single": 0, "seed": 7}
    assert len(res.sims) == 5
    assert all(s.result.pct50 > 0 for s in res.sims)


def test_explore_batch_falls_back_without_batch_api(capsys):
    """batch=True with a benchmarker lacking benchmark_batch_times must warn
    on stderr and still produce results via the one-at-a-time path."""
    g = Graph()
    a, b = NoOp("a"), NoOp("b")
    g.start_then(a)
    g.start_then(b)
    g.then_finish(a)
    g.then_finish(b)
    bench = CountingBenchmarker()
    res = explore(g, FakePlatform(1), bench, DfsOpts(max_seqs=10, batch=True))
    assert len(res.sims) == 2 and bench.calls == 2
    assert "batch=True ignored" in capsys.readouterr().err
