"""Schedule attribution profiler (ISSUE 6): timeline analysis against
hand-computed critical paths / overlap efficiencies / dispatch overheads
(pure CPU, synthetic durations), the stepped timing mode on a real
executor, the winner-vs-naive decision diff (golden facts on the recorded
halo corpus), the per-lane Perfetto emission, and the report CLI's
noise-aware regression check (must flag a synthetic slowdown, pass the
unmodified committed baseline, and downgrade drift-contaminated series to
inconclusive)."""

import json
import os

import pytest

from tenzing_tpu.core.operation import DeviceOp
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, WaitEvent
from tenzing_tpu.obs.attrib import (
    OpRecord,
    OpTimeline,
    analyze,
    diff_schedules,
    explain,
    stepped_timeline,
    timeline_trace_events,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TOp(DeviceOp):
    """Minimal device op for synthetic schedules (no buffers needed —
    the analysis layer only consumes op kinds/lanes/names)."""

    def apply(self, bufs, ctx):
        return {}


def _timeline(ops, durs):
    """An OpTimeline with the given per-position durations (µs)."""
    recs = []
    for p, op in enumerate(ops):
        if getattr(op, "is_sync", lambda: False)():
            lanes = op.lanes() if hasattr(op, "lanes") else []
            recs.append(OpRecord(name=op.desc(), desc=op.desc(),
                                 kind="sync",
                                 lane=(lanes[0].id if lanes else None),
                                 positions=(p,)))
        else:
            recs.append(OpRecord(name=op.name(), desc=op.desc(),
                                 kind="device", lane=op.lane().id,
                                 positions=(p,), dur_us=durs.get(p, 0.0)))
    return OpTimeline(records=recs, schedule="t", source="synthetic",
                      n_ops=len(ops))


L0, L1 = Lane(0), Lane(1)


# -- analysis: hand-computed critical paths / efficiencies ------------------

def test_serial_same_lane_critical_path_is_sum():
    ops = [TOp("a").bind(L0), TOp("b").bind(L0)]
    at = analyze(ops, _timeline(ops, {0: 10.0, 1: 20.0}), measured_us=25.0)
    assert at.sum_of_parts_us == 30.0
    assert at.critical_path_us == 30.0
    assert at.critical_path == ["a", "b"]
    # measured (25) beats the stepped sum (30): the 5us gap is dispatch
    # overhead the fused program does not pay
    assert at.dispatch_overhead_us == 5.0
    # measured < HB bound -> the schedule achieved every permitted overlap
    assert at.overlap_efficiency == 1.0


def test_independent_lanes_overlap_and_gantt_starts():
    ops = [TOp("a").bind(L0), TOp("b").bind(L1)]
    at = analyze(ops, _timeline(ops, {0: 10.0, 1: 20.0}), measured_us=22.0)
    # no sync, no host op between them: the lanes are concurrent
    assert at.critical_path_us == 20.0
    assert at.critical_path == ["b"]
    assert at.timeline.records[0].start_us == 0.0
    assert at.timeline.records[1].start_us == 0.0
    assert at.overlap_efficiency == pytest.approx(20.0 / 22.0)
    assert at.dispatch_overhead_us == pytest.approx(8.0)
    assert at.per_lane_busy_us == {"lane 0": 10.0, "lane 1": 20.0}


def test_cross_lane_sync_serializes_the_gantt():
    e0 = Event(0)
    ops = [TOp("a").bind(L0), EventRecord(L0, e0), WaitEvent(L1, e0),
           TOp("b").bind(L1)]
    at = analyze(ops, _timeline(ops, {0: 10.0, 3: 20.0}), measured_us=30.0)
    # record/wait joins lane1 behind a: b starts at a's end
    assert at.timeline.records[3].start_us == 10.0
    assert at.critical_path_us == 30.0
    assert at.critical_path == ["a", "b"]  # syncs route but don't appear
    assert at.overlap_efficiency == 1.0
    assert at.dispatch_overhead_us == 0.0


def test_host_dispatch_orders_after_host_chain():
    # a device op joins the host chain at dispatch: an EventSync (host op)
    # between two device ops on DIFFERENT lanes still serializes them
    e0 = Event(0)
    ops = [TOp("a").bind(L0), EventRecord(L0, e0), EventSync(e0),
           TOp("b").bind(L1)]
    at = analyze(ops, _timeline(ops, {0: 10.0, 3: 20.0}))
    assert at.timeline.records[3].start_us == 10.0
    assert at.critical_path_us == 30.0


def test_efficiency_bounds_and_roofline_join():
    from tenzing_tpu.bench.roofline import Cost

    ops = [TOp("a").bind(L0), TOp("b").bind(L1)]
    tl = _timeline(ops, {0: 10.0, 1: 10.0})
    # measured slower than every bound: efficiency in (0, 1], overhead >= 0
    at = analyze(ops, tl, measured_us=100.0,
                 cost=Cost(flops=1e6, hbm_bytes=1e3))
    assert 0.0 < at.overlap_efficiency <= 1.0
    assert at.overlap_efficiency == pytest.approx(0.1)
    assert at.dispatch_overhead_us == 0.0  # clamped: measured > sum
    assert at.utilization is not None and at.utilization["tflops"] > 0
    # per-op costs join per unit
    at2 = analyze(ops, _timeline(ops, {0: 10.0, 1: 10.0}), measured_us=20.0,
                  per_op_costs={"a": Cost(flops=2e6, hbm_bytes=0.0)})
    assert "a" in at2.per_op_utilization
    assert at2.per_op_utilization["a"]["tflops"] == pytest.approx(
        2e6 / (10e-6) / 1e12)


def test_timeline_json_roundtrip():
    ops = [TOp("a").bind(L0), TOp("b").bind(L1)]
    at = analyze(ops, _timeline(ops, {0: 10.0, 1: 20.0}), measured_us=22.0)
    back = OpTimeline.from_json(json.loads(json.dumps(at.timeline.to_json())))
    assert [r.name for r in back.records] == ["a", "b"]
    assert back.records[1].dur_us == 20.0
    doc = at.to_json()
    assert doc["n_timed"] == 2 and len(doc["timeline"]) == 2


# -- stepped timing on a real executor (CPU) --------------------------------

@pytest.fixture(scope="module")
def stepped():
    import jax.numpy as jnp

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State
    from tenzing_tpu.runtime.executor import TraceExecutor

    class Mul(DeviceOp):
        def __init__(self, name, src, dst):
            super().__init__(name)
            self.s, self.d = src, dst

        def reads(self):
            return [self.s]

        def writes(self):
            return [self.d]

        def apply(self, bufs, ctx):
            return {self.d: bufs[self.s] * 2.0}

    g = Graph()
    m1, m2 = Mul("m1", "x", "y"), Mul("m2", "y", "z")
    g.start_then(m1)
    g.then(m1, m2)
    g.then_finish(m2)
    plat = Platform.make_n_lanes(2)
    ex = TraceExecutor(plat, {"x": jnp.ones((8, 8)), "y": jnp.zeros((8, 8)),
                              "z": jnp.zeros((8, 8))})
    st = State(g)
    while not st.is_terminal():
        st = st.apply(st.get_decisions(plat)[0])
    return ex, st.sequence


def test_stepped_timeline_covers_every_position(stepped):
    ex, seq = stepped
    tl = stepped_timeline(ex, seq, repeats=2)
    # every schedule position appears exactly once across the records
    covered = sorted(p for r in tl.records for p in r.positions)
    assert covered == list(range(len(seq)))
    for r in tl.records:
        if r.kind == "sync":
            assert r.dur_us == 0.0
        else:
            assert r.dur_us > 0.0
    at = analyze(seq.vector(), tl, measured_us=50.0)
    assert at.dispatch_overhead_us >= 0.0
    assert 0.0 < at.overlap_efficiency <= 1.0
    # m1 -> m2 is a data chain on one lane: both on the critical path
    assert "m1" in at.critical_path and "m2" in at.critical_path


def test_stepped_rejects_mesh_platforms(stepped):
    ex, seq = stepped

    class FakeMeshPlat:
        mesh = object()
        axis_names = ()

    ex2 = type(ex)(ex.platform, ex.init_bufs)
    ex2.platform = FakeMeshPlat()
    with pytest.raises(RuntimeError, match="single-chip"):
        ex2.op_stepped(seq)


# -- decision diff: golden facts on the recorded halo corpus ----------------

@pytest.fixture(scope="module")
def halo_corpus():
    from tenzing_tpu.bench.benchmarker import CsvBenchmarker
    from tenzing_tpu.models.halo import HaloArgs

    path = os.path.join(REPO, "experiments", "halo_search_tpu.csv")
    args = HaloArgs(nq=3, lx=512, ly=512, lz=512, radius=3)
    try:  # building the halo menu graph pulls in the Pallas kernels; skip
        # where the container's pallas API predates them (the same env
        # gate the other recorded-corpus suites hit as plain failures)
        from tenzing_tpu.models.halo_pipeline import build_graph

        db = CsvBenchmarker.from_file(
            path, build_graph(args, impl_choice=True), strict=False)
        db_naive = CsvBenchmarker.from_file(
            path, build_graph(args, impl_choice=False), strict=False)
    except (ImportError, AttributeError) as e:  # pragma: no cover - env
        pytest.skip(f"halo pipeline unavailable in this env: {e}")
    naive_seq = db_naive.entries[0][0]
    winner_seq, winner_res = min(db.entries, key=lambda e: e[1].pct50)
    return naive_seq, winner_seq


def test_halo_corpus_diff_golden(halo_corpus):
    """The recorded r1 winner's attribution facts, pinned against the
    frozen corpus: two lanes vs naive's one, 57 inversions over the 20
    shared ops, 12 kernel/engine menu choices resolved differently, and
    the event_record/event_sync vocabulary the single-lane naive
    serialization never needs (its program order IS the sync)."""
    naive_seq, winner_seq = halo_corpus
    d = diff_schedules(naive_seq.vector(), winner_seq.vector())
    assert d["lanes"]["naive_lanes"] == [0]
    assert d["lanes"]["winner_lanes"] == [0, 1]
    assert d["reorder"]["shared_ops"] == 20
    assert d["reorder"]["inversions"] == 57
    assert d["reorder"]["normalized"] == pytest.approx(0.3)
    # naive needs zero sync ops; the overlap schedule buys its two-lane
    # concurrency with 5 event_record + 5 event_sync (delta = naive -
    # winner, so additions show as negative)
    assert d["sync"]["naive"] == {}
    assert d["sync"]["winner"] == {"event_record": 5, "event_sync": 5}
    assert d["sync"]["delta"] == {"event_record": -5, "event_sync": -5}
    # 12 ops chose a different menu alternative than the naive default
    assert len(d["menu"]["changed_choices"]) == 12
    assert d["menu"]["only_in_naive"] == [] and d["menu"]["only_in_winner"] == []
    assert json.dumps(d)  # JSON-serializable as-is


def test_explain_timing_decomposition_is_exact():
    ops_n = [TOp("a").bind(L0), TOp("b").bind(L0)]
    ops_w = [TOp("a").bind(L0), TOp("b").bind(L1)]
    n_at = analyze(ops_n, _timeline(ops_n, {0: 10.0, 1: 20.0}),
                   measured_us=32.0)
    w_at = analyze(ops_w, _timeline(ops_w, {0: 9.0, 1: 18.0}),
                   measured_us=20.0)
    doc = explain(ops_n, ops_w, naive_attrib=n_at, winner_attrib=w_at)
    t = doc["timing"]
    # the three terms sum exactly to the measured delta
    assert (t["naive_hidden_us"] + t["faster_parts_us"]
            + t["winner_hidden_us"]) == pytest.approx(t["delta_us"])
    assert t["delta_us"] == pytest.approx(12.0)
    assert t["speedup"] == pytest.approx(32.0 / 20.0)
    assert doc["decisions"]["lanes"]["winner_lanes"] == [0, 1]


def test_timeline_trace_events_per_lane_tracks():
    from tenzing_tpu.obs.export import chrome_trace
    from tenzing_tpu.obs.tracer import Tracer

    ops = [TOp("a").bind(L0), TOp("b").bind(L1)]
    at = analyze(ops, _timeline(ops, {0: 10.0, 1: 20.0}), measured_us=22.0)
    evs = timeline_trace_events(at, pid=0, label="attrib/winner")
    names = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
    assert names == {"attrib/winner/lane 0", "attrib/winner/lane 1"}
    xs = [e for e in evs if e.get("ph") == "X"]
    assert {e["tid"] for e in xs} == {1000, 1001}
    # merged through the export path: spans get named tracks, extras keep
    # their own metadata, everything lands in one traceEvents list
    tr = Tracer(enabled=True)
    with tr.span("bench.benchmark"):
        pass
    doc = chrome_trace(tr, extra_events=evs)
    metas = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    assert any(m["args"]["name"] == "rank 0" for m in metas)
    assert any(m["args"]["name"] == "main" for m in metas)
    assert any(m["args"]["name"] == "attrib/winner/lane 1" for m in metas)
    assert any(e.get("cat") == "attrib" for e in doc["traceEvents"])


# -- histogram truncation surfacing (obs/metrics.py satellite) --------------

def test_histogram_summary_surfaces_truncation():
    from tenzing_tpu.obs.metrics import Histogram

    h = Histogram("h", max_raw=4)
    for v in range(10):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 10
    assert s["raw_retained"] == 4
    assert s["truncated"] is True
    h2 = Histogram("h2", max_raw=16)
    for v in range(10):
        h2.observe(float(v))
    assert "truncated" not in h2.summary()


# -- report CLI + regression check ------------------------------------------

BASELINE = os.path.join(REPO, "BENCH_r05.json")


def _baseline_parsed():
    with open(BASELINE) as f:
        return json.load(f)["parsed"]


def test_load_driver_json_wrapper_and_raw(tmp_path):
    from tenzing_tpu.obs.report import load_driver_json

    d = load_driver_json(BASELINE)
    assert d["metric"].startswith("halo_iter")
    raw = tmp_path / "raw.json"
    raw.write_text("stderr noise\n" + json.dumps(d) + "\n")
    assert load_driver_json(str(raw)) == d


def test_regression_check_passes_unmodified_baseline():
    from tenzing_tpu.obs.report import check_regression

    d = _baseline_parsed()
    v = check_regression(d, d)
    assert v["verdict"] == "ok" and not v["reasons"]


def test_regression_check_flags_synthetic_slowdown():
    from tenzing_tpu.obs.report import check_regression

    base = _baseline_parsed()
    slow = dict(base, vs_baseline=base["vs_baseline"] * 0.8)
    v = check_regression(slow, base)
    assert v["verdict"] == "regression"
    assert any("vs_baseline" in r for r in v["reasons"])
    # a slower relative value (value/naive) flags independently
    slow2 = dict(base, value=base["value"] * 1.2)
    v2 = check_regression(slow2, base)
    assert v2["verdict"] == "regression"
    # within tolerance: no flag
    v3 = check_regression(dict(base, vs_baseline=base["vs_baseline"] * 0.97),
                          base, tol=0.05)
    assert v3["verdict"] == "ok"


def test_regression_check_noise_aware_inconclusive():
    from tenzing_tpu.obs.report import check_regression

    base = _baseline_parsed()
    # a drifting series (monotonic -> 2 runs, |Z| >> 1.96) downgrades the
    # would-be regression to inconclusive: re-measure, don't flag
    slow = dict(base, vs_baseline=base["vs_baseline"] * 0.8,
                attrib={"measured_times": [1.0 + 0.01 * i
                                           for i in range(20)]})
    v = check_regression(slow, base)
    assert v["verdict"] == "inconclusive"
    # an i.i.d.-looking series keeps the flag
    import random

    from tenzing_tpu.bench.randomness import is_random

    rng = random.Random(0)
    noisy = [1.0 + rng.uniform(-0.01, 0.01) for _ in range(20)]
    assert is_random(noisy)  # sanity: the seeded series passes the runs test
    slow2 = dict(base, vs_baseline=base["vs_baseline"] * 0.8,
                 attrib={"measured_times": noisy})
    assert check_regression(slow2, base)["verdict"] == "regression"


def test_report_cli_end_to_end(tmp_path):
    from tenzing_tpu.obs.report import main

    out = tmp_path / "report.md"
    rc = main(["--csv",
               os.path.join(REPO, "experiments", "halo_search_tpu_r5*.csv"),
               "--bench", BASELINE,
               "--check", BASELINE, "--baseline", BASELINE,
               "--out", str(out)])
    assert rc == 0
    text = out.read_text()
    assert "## Recorded search databases" in text
    assert "## Driver verdicts" in text
    assert "verdict: ok" in text
    # regression exit code: a fabricated slowdown returns 1
    base = _baseline_parsed()
    slow_p = tmp_path / "slow.json"
    slow_p.write_text(json.dumps(
        dict(base, vs_baseline=base["vs_baseline"] * 0.5)))
    rc2 = main(["--check", str(slow_p), "--baseline", BASELINE,
                "--out", str(tmp_path / "r2.md")])
    assert rc2 == 1


def test_report_labels_truncated_histograms(tmp_path):
    from tenzing_tpu.obs.report import main

    mpath = tmp_path / "metrics.json"
    mpath.write_text(json.dumps({
        "counters": {}, "gauges": {},
        "histograms": {
            "long.series": {"count": 100000, "sum": 12.0, "p50": 1.0,
                            "p99": 2.0, "raw_retained": 65536,
                            "truncated": True},
            # pre-truncated-flag summary: raw_retained alone must still
            # label prefix-only (legacy metrics JSONs)
            "old.series": {"count": 500, "sum": 5.0, "p50": 1.0,
                           "p99": 2.0, "raw_retained": 100},
            "short.series": {"count": 10, "sum": 1.0, "p50": 0.1,
                             "p99": 0.2},
        }}))
    out = tmp_path / "m.md"
    assert main(["--metrics", str(mpath), "--out", str(out)]) == 0
    text = out.read_text()
    assert "prefix-only (65536/100000)" in text
    assert "prefix-only (100/500)" in text
    assert "| short.series | 10 | 1 | 0.1 | 0.2 | full |" in text


# -- utils/profiling back-compat shim ---------------------------------------

def test_profiling_shim_reexports_xplane():
    from tenzing_tpu.obs.attrib import xplane
    from tenzing_tpu.utils import profiling

    assert profiling.analyze_trace is xplane.analyze_trace
    assert profiling.capture_trace is xplane.capture_trace
    assert profiling.merge_intervals is xplane.merge_intervals
