"""Replay of a real recorded TPU DFS search over the SpMV iteration space
(VERDICT r1 item 6: DFS on the chip, recorded CSV as a fixture).

``experiments/spmv_dfs_tpu.csv`` is the dumped result database of
``examples/spmv_dfs.py`` run on a TPU v5e at the reference config (m=150000
rows, nnz=10m band matrix, 2 lanes — spmv_run_strategy.cuh:44-47) with a
capped exhaustive enumeration (reference maxSeqs cap, spmv.cu:117).  Every row
is one deduplicated complete schedule of the expanded SpMV compound.
"""

import os

import pytest

from tenzing_tpu.bench.benchmarker import CsvBenchmarker
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.models.spmv import SpMVCompound

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSV_PATH = os.path.join(REPO, "experiments", "spmv_dfs_tpu.csv")


@pytest.fixture(scope="module")
def db():
    g = Graph()
    g.start_then(SpMVCompound())
    g.then_finish(SpMVCompound())
    return CsvBenchmarker.from_file(CSV_PATH, g, strict=True)


def test_every_dfs_row_deserializes_and_answers(db):
    n_rows = sum(1 for line in open(CSV_PATH) if line.strip())
    assert len(db.entries) == n_rows and not db.skipped
    for seq, res in db.entries:
        # expanded compound: 5 pipeline ops + start/finish (+ inserted syncs)
        assert len(seq) >= 7
        assert res.pct50 > 0
        assert db.benchmark(seq).pct50 == res.pct50


def test_schedule_classes_exist_in_recorded_dfs(db):
    """The recorded space separates into performance classes (the signal
    postprocess mines; reference postprocess.py:27-120).  The tunnel's timing
    distribution is bimodal within a row, so the robust statistic is pct10 —
    the same choice the reference's ``best()`` makes (dfs.hpp Result): the
    pct10 spread across schedules must be a real fraction of the median."""
    p10 = sorted(r.pct10 for _, r in db.entries)
    spread = p10[-1] - p10[0]
    assert spread > 0.10 * p10[len(p10) // 2], (
        f"pct10 spread {spread*1e3:.2f} ms too small vs median {p10[len(p10)//2]*1e3:.2f} ms"
    )


def test_recorded_dfs_schedules_are_lane_overlapped_and_distinct(db):
    """Every deduplicated schedule in the capped enumeration binds both lanes
    (the all-one-lane serializations live past the cap), and no two recorded
    rows are bijection-equivalent — the DFS dedup held on real data."""
    from tenzing_tpu.core.operation import BoundDeviceOp
    from tenzing_tpu.core.sequence import get_equivalence

    seqs = [s for s, _ in db.entries]
    for s in seqs:
        assert {op.lane().id for op in s if isinstance(op, BoundDeviceOp)} == {0, 1}
    for i in range(len(seqs)):
        for j in range(i + 1, len(seqs)):
            assert not get_equivalence(seqs[i], seqs[j]), (i, j)


def test_postprocess_analyzes_recorded_dfs():
    import io

    from postprocess.postprocess import analyze

    with open(CSV_PATH) as f:
        text = f.read()
    out = analyze(text, stream=io.StringIO())
    assert out["n"] == sum(1 for line in text.splitlines() if line.strip())
