"""Analytic cost-model benchmarker: modeled schedule quality, no device.

VERDICT r4 item 5: the virtual-mesh dryrun validated numerics only; these
tests show the searched schedules BEAT naive under the analytic machine
model on the halo and MoE mesh graphs — the modeled analog of the reference
driving its whole search against recorded timings (benchmarker.cpp:169-223).
"""

import pytest

from tenzing_tpu.bench.benchmarker import BenchOpts
from tenzing_tpu.bench.model import AnalyticBenchmarker, ModelEnv
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.sequence import Sequence


def _halo_setup():
    from tenzing_tpu.models.halo import HaloArgs, add_to_graph

    hargs = HaloArgs(nq=2, lx=8, ly=8, lz=8, radius=1)
    g = add_to_graph(Graph(), hargs)
    # byte sizes from the real buffer shapes: one face buffer per direction
    import numpy as np

    from tenzing_tpu.models.halo import DIRECTIONS, _face_slices, dir_name

    nbytes = {"U": int(np.prod(hargs.local_shape())) * 4}
    for d in DIRECTIONS:
        _, sz = _face_slices(hargs, d, "pack")
        n = int(np.prod(sz)) * 4
        nbytes[f"buf_{dir_name(d)}"] = n
        nbytes[f"recv_{dir_name(d)}"] = n
    return g, nbytes


def _naive_seq(g, platform):
    from tenzing_tpu.core.state import State

    st = State(g)
    while not st.is_terminal():
        st = st.apply(st.get_decisions(platform)[0])
    return st.sequence


def test_halo_naive_vs_overlap_ordering():
    """The post-all-await-late discipline must model FASTER than the
    fully-synchronous naive serialization: transfers ride the ici engine
    concurrently instead of each being awaited before the next post."""
    g, nbytes = _halo_setup()
    bench = AnalyticBenchmarker(nbytes)
    naive = bench.makespan(_naive_seq(g, Platform.make_n_lanes(1)))

    from tenzing_tpu.solve.greedy import greedy_phase_order

    plat = Platform.make_n_lanes(2)
    overlap = bench.makespan(greedy_phase_order(
        g, plat, ("start", "pack", "exchange", "await", "unpack", "finish")))
    assert overlap < naive, (overlap, naive)
    # the win is the serialized ici waits: six awaited hops vs overlapped
    assert naive / overlap > 1.2, (naive, overlap)


def test_model_rewards_are_deterministic():
    g, nbytes = _halo_setup()
    bench = AnalyticBenchmarker(nbytes)
    seq = _naive_seq(g, Platform.make_n_lanes(1))
    r1 = bench.benchmark(seq, BenchOpts(n_iters=3))
    r2 = bench.benchmark(seq, BenchOpts(n_iters=3))
    assert r1.pct50 == r2.pct50 == bench.makespan(seq)
    assert r1.stddev == 0.0


def test_mcts_beats_naive_under_model_on_halo():
    """MCTS searching WITH the analytic benchmarker finds a schedule whose
    modeled makespan beats naive — device-free schedule-quality search."""
    from tenzing_tpu.solve.mcts import MctsOpts, explore
    from tenzing_tpu.solve.mcts.strategies import FastMin

    g, nbytes = _halo_setup()
    bench = AnalyticBenchmarker(nbytes)
    naive = bench.makespan(_naive_seq(g, Platform.make_n_lanes(1)))
    plat = Platform.make_n_lanes(2)
    res = explore(
        g, plat, bench,
        MctsOpts(n_iters=24, bench_opts=BenchOpts(n_iters=1), seed=0,
                 cache_benchmarks=True),
        strategy=FastMin,
    )
    best = min(s.result.pct50 for s in res.sims)
    assert best < naive, (best, naive)


def test_dfs_beats_naive_under_model_on_moe():
    from tenzing_tpu.models.moe import MoEArgs, MoELayer, make_moe_buffers
    from tenzing_tpu.solve.dfs import get_all_sequences

    margs = MoEArgs(n_ep=4, tokens_per_shard=8, d_model=8, d_ff=16,
                    n_chunks=2)
    bufs, _, _ = make_moe_buffers(margs, seed=0)
    nbytes = {k: v.nbytes for k, v in bufs.items()}
    g = Graph()
    g.start_then(MoELayer(margs))
    g.then_finish(MoELayer(margs))
    bench = AnalyticBenchmarker(nbytes)
    naive = bench.makespan(_naive_seq(g, Platform.make_n_lanes(1)))
    plat = Platform.make_n_lanes(2)
    states = get_all_sequences(g, plat, max_seqs=64)
    best = min(bench.makespan(st.sequence) for st in states)
    assert best < naive, (best, naive)


def test_env_parameters_steer_the_model():
    """A slower ici makes transfer-heavy schedules model slower — the env is
    live, not decorative."""
    g, nbytes = _halo_setup()
    seq = _naive_seq(g, Platform.make_n_lanes(1))
    fast = AnalyticBenchmarker(nbytes, ModelEnv(ici_bw=90e9)).makespan(seq)
    slow = AnalyticBenchmarker(nbytes, ModelEnv(ici_bw=9e9)).makespan(seq)
    assert slow > fast


def test_policy_rollouts_reach_discipline_floor():
    """Informed playouts (MctsOpts.rollout_policy): every rollout finishes
    as a coherent discipline, so best-seen is GUARANTEED to land at (or
    beyond) the policy's own discipline quality — random playouts carry no
    such floor (on a tiny graph they can luck into a good schedule, so the
    meaningful property is the floor, not a head-to-head).  The r5 fix for
    random-playout MCTS lagging the hill-climbs (VERDICT r4 item 2)."""
    from tenzing_tpu.solve.local import phase_policy
    from tenzing_tpu.solve.mcts import MctsOpts, explore
    from tenzing_tpu.solve.mcts.strategies import FastMin

    g, nbytes = _halo_setup()
    bench = AnalyticBenchmarker(nbytes)
    plat = Platform.make_n_lanes(2)
    phases = ("start", "pack", "exchange", "await", "unpack", "finish")

    for expand in (False, True):  # both playout modes honor the policy
        res = explore(
            g, plat, bench,
            MctsOpts(n_iters=12, bench_opts=BenchOpts(n_iters=1), seed=3,
                     rollout_policy=phase_policy(plat, phases),
                     rollout_eps=0.1, expand_rollout=expand),
            strategy=FastMin,
        )
        policy_best = min(s.result.pct50 for s in res.sims)
        from tenzing_tpu.solve.greedy import greedy_phase_order

        greedy = bench.makespan(greedy_phase_order(g, plat, phases))
        assert policy_best <= greedy * 1.05, (expand, policy_best, greedy)
        naive = bench.makespan(_naive_seq(g, Platform.make_n_lanes(1)))
        assert policy_best < naive, (expand, policy_best, naive)
