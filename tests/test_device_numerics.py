"""The recorded on-device numerics artifact (experiments/TPU_NUMERICS.json,
written by experiments/device_numerics.py on a real TPU) must exist and be
healthy — the device tier of the test strategy (SURVEY.md §4: CPU subset in
CI, device execution recorded as an artifact).  Re-run the script on a chip
to refresh it; set TENZING_TPU_DEVICE_TESTS=1 to run the checks live from
pytest (requires a TPU backend — the default conftest forces CPU, where the
live run exercises the interpret path only)."""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "experiments", "TPU_NUMERICS.json")


def test_recorded_device_numerics_artifact_is_healthy():
    with open(ARTIFACT) as f:
        rec = json.load(f)
    assert rec["is_tpu"], "artifact must be recorded on a real TPU backend"
    assert rec["all_ok"], rec
    checks = {k: v for k, v in rec.items() if isinstance(v, dict)}
    assert set(checks) == {
        "spmv_pallas",
        "attn_pallas_f32",
        "attn_pallas_bf16",
        "moe_pipeline_pallas",
        "halo_pipeline_pallas",
    }
    # the kernel-equivalence tier is tight regardless of platform precision
    assert checks["moe_pipeline_pallas"]["pallas_vs_xla_max_abs"] < 1e-5


@pytest.mark.skipif(
    os.environ.get("TENZING_TPU_DEVICE_TESTS") != "1",
    reason="live device numerics are opt-in (TENZING_TPU_DEVICE_TESTS=1)",
)
def test_live_device_numerics():
    from experiments.device_numerics import run_all

    results = run_all()
    assert results["all_ok"], results
