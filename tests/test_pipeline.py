"""Pipeline parallelism: DAG shape, schedule search, and sharded numerics vs
the host stage-stack evaluation (models/pipeline.py)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.pipeline import (
    Pipeline,
    PipelineArgs,
    make_pipeline_buffers,
)
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.solve.dfs import get_all_sequences


def _graph(args):
    g = Graph()
    g.start_then(Pipeline(args))
    g.then_finish(Pipeline(args))
    return g


def _mesh(npp):
    devs = np.array(jax.devices()[:npp])
    return Mesh(devs, ("pp",))


class TestDagShape:
    def test_chains_are_independent(self):
        """Chain 0's compute and chain 1's rotate must be DAG-independent —
        the 1F1B-style interleaving freedom."""
        args = PipelineArgs(n_pp=2, n_microbatches=4, n_chains=2)
        g = Pipeline(args).graph()
        by_name = {v.name(): v for v in g.vertices()}
        c0, r1 = by_name["compute_0_0"], by_name["rotate_1_0"]
        assert r1 not in g.succs(c0) and c0 not in g.succs(r1)

    def test_post_wait_split(self):
        """The rotate is split into a post and an await vertex, so compute can
        be scheduled between them."""
        args = PipelineArgs(n_pp=2, n_microbatches=2, n_chains=1)
        g = Pipeline(args).graph()
        by_name = {v.name(): v for v in g.vertices()}
        assert by_name["await_0_0"] in g.succs(by_name["rotate_0_0"])

    def test_schedule_space_is_nontrivial(self):
        args = PipelineArgs(n_pp=2, n_microbatches=2, n_chains=2)
        plat = Platform.make_n_lanes(2)
        seqs = get_all_sequences(_graph(args), plat, max_seqs=50)
        assert len(seqs) > 1


@pytest.mark.needs_shard_map
class TestNumerics:
    @pytest.mark.parametrize("npp,m,v", [(2, 4, 2), (4, 4, 2), (4, 4, 1)])
    def test_matches_stage_stack(self, npp, m, v):
        args = PipelineArgs(n_pp=npp, n_microbatches=m, n_chains=v,
                            mb_size=4, d_model=8)
        bufs, specs, want = make_pipeline_buffers(args, seed=1)
        plat = Platform.make_n_lanes(2, mesh=_mesh(npp), specs=specs)
        ex = TraceExecutor(plat, {k: jnp.asarray(v_) for k, v_ in bufs.items()})
        order = get_all_sequences(_graph(args), plat, max_seqs=1)[0].sequence
        out = ex.run(order)
        np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-4,
                                   atol=2e-5)

    def test_every_schedule_is_equivalent(self):
        args = PipelineArgs(n_pp=2, n_microbatches=2, n_chains=2,
                            mb_size=2, d_model=4)
        bufs, specs, want = make_pipeline_buffers(args, seed=2)
        plat = Platform.make_n_lanes(2, mesh=_mesh(2), specs=specs)
        seqs = get_all_sequences(_graph(args), plat, max_seqs=6)
        assert len(seqs) >= 2
        ex = TraceExecutor(plat, {k: jnp.asarray(v_) for k, v_ in bufs.items()})
        for s in seqs:
            out = ex.run(s.sequence)
            np.testing.assert_allclose(np.asarray(out["Y"]), want, rtol=2e-4,
                                       atol=2e-5)


class TestTrainStep:
    def _graph(self, args):
        from tenzing_tpu.models.pipeline import PipelineTrain

        g = Graph()
        g.start_then(PipelineTrain(args))
        g.then_finish(PipelineTrain(args))
        return g

    @pytest.mark.parametrize("npp,m,v", [(2, 4, 2), (4, 4, 2), (4, 2, 1)])
    @pytest.mark.needs_shard_map
    def test_dw_matches_host_backward(self, npp, m, v):
        from tenzing_tpu.models.pipeline import make_train_buffers

        args = PipelineArgs(n_pp=npp, n_microbatches=m, n_chains=v,
                            mb_size=3, d_model=6)
        bufs, specs, want = make_train_buffers(args, seed=1)
        plat = Platform.make_n_lanes(2, mesh=_mesh(npp), specs=specs)
        ex = TraceExecutor(plat, {k: jnp.asarray(v_) for k, v_ in bufs.items()})
        order = get_all_sequences(self._graph(args), plat, max_seqs=1)[0].sequence
        out = ex.run(order)
        np.testing.assert_allclose(np.asarray(out["dW"]), want, rtol=2e-3,
                                   atol=2e-4)

    def test_cross_chain_fwd_bwd_independence(self):
        """Chain 0's backward and chain 1's forward must be DAG-independent —
        the interleaved-1F1B freedom the solver searches."""
        from tenzing_tpu.models.pipeline import PipelineTrain

        args = PipelineArgs(n_pp=2, n_microbatches=4, n_chains=2)
        g = PipelineTrain(args).graph()
        by_name = {vx.name(): vx for vx in g.vertices()}
        b0, f1 = by_name["bcompute_0_0"], by_name["fcompute_1_0"]
        assert f1 not in g.succs(b0) and b0 not in g.succs(f1)

    def test_backward_strictly_after_own_forward(self):
        """Within a chain, the first backward op depends on the last forward
        compute (the stash must be complete)."""
        from tenzing_tpu.models.pipeline import PipelineTrain

        args = PipelineArgs(n_pp=2, n_microbatches=2, n_chains=1)
        g = PipelineTrain(args).graph()
        by_name = {vx.name(): vx for vx in g.vertices()}
        last_f = by_name[f"fcompute_0_{args.chain_ticks - 1}"]
        assert by_name["binject_0_0"] in g.succs(last_f)

    @pytest.mark.needs_shard_map
    def test_every_schedule_computes_same_dw(self):
        from tenzing_tpu.models.pipeline import make_train_buffers

        args = PipelineArgs(n_pp=2, n_microbatches=2, n_chains=2,
                            mb_size=2, d_model=4)
        bufs, specs, want = make_train_buffers(args, seed=3)
        plat = Platform.make_n_lanes(2, mesh=_mesh(2), specs=specs)
        seqs = get_all_sequences(self._graph(args), plat, max_seqs=4)
        assert len(seqs) >= 2
        ex = TraceExecutor(plat, {k: jnp.asarray(v_) for k, v_ in bufs.items()})
        for s in seqs:
            out = ex.run(s.sequence)
            np.testing.assert_allclose(np.asarray(out["dW"]), want, rtol=2e-3,
                                       atol=2e-4)
