"""Multi-host control plane exercised for real (VERDICT r1 item 7): two
processes over jax.distributed's CPU backend drive a tiny DFS explore —
schedule/stop broadcast (reference mpi_bcast, sequence.cpp:88-125; stop
protocol dfs.hpp:50-70), barriers, and max-over-hosts timing reduction
(benchmarker.cpp:101,145) — covering the rank!=0 paths of solve/dfs.py and
parallel/control_plane.JaxControlPlane."""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DRIVER = """
import os, sys
pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
import jax.numpy as jnp
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
from tenzing_tpu.solve.dfs import DfsOpts, explore
from tenzing_tpu.parallel.control_plane import JaxControlPlane, default_control_plane

cp = default_control_plane()
assert isinstance(cp, JaxControlPlane), type(cp)
assert cp.size() == 2 and cp.rank() == pid
assert cp.allreduce_max(float(pid)) == 1.0  # sees the other host's value
assert cp.bcast_json({"stop": False, "rank0": cp.rank() == 0})["rank0"] is True

g = Graph()
g.start_then(SpMVCompound())
g.then_finish(SpMVCompound())
plat = Platform.make_n_lanes(2)
bufs, _ = make_spmv_buffers(m=128, nnz_per_row=4, seed=0)
ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
bench = EmpiricalBenchmarker(ex, control_plane=cp)
res = explore(
    g, plat, bench,
    DfsOpts(max_seqs=3, bench_opts=BenchOpts(n_iters=2, target_secs=1e-4)),
    control_plane=cp,
)
assert len(res.sims) == 3  # rank 1 learned the count from the broadcast
fp = "&".join(s.order.desc() for s in res.sims)
print(f"RANK{pid}_OK {fp}", flush=True)
"""


MCTS_DRIVER = """
import os, sys
pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
import jax.numpy as jnp
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
from tenzing_tpu.solve.mcts import MctsOpts, explore
from tenzing_tpu.solve.mcts.strategies import FastMin
from tenzing_tpu.parallel.control_plane import default_control_plane

cp = default_control_plane()
g = Graph()
g.start_then(SpMVCompound())
g.then_finish(SpMVCompound())
plat = Platform.make_n_lanes(2)
bufs, _ = make_spmv_buffers(m=128, nnz_per_row=4, seed=0)
ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
bench = EmpiricalBenchmarker(ex, control_plane=cp)
res = explore(
    g, plat, bench,
    MctsOpts(n_iters=3, bench_opts=BenchOpts(n_iters=2, target_secs=1e-4),
             seed=0),
    strategy=FastMin,
    control_plane=cp,
)
assert len(res.sims) == 3  # rank 1 benchmarked every broadcast rollout
fp = "&".join(s.order.desc() for s in res.sims)
print(f"RANK{pid}_OK {fp}", flush=True)
"""


def _run_two_ranks(driver: str):
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", driver, str(pid), port],
            cwd=REPO,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"rank failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        for p in procs:  # a hung rank must not outlive the test
            if p.poll() is None:
                p.kill()
                p.wait()
    fp0 = [l for l in outs[0].splitlines() if l.startswith("RANK0_OK")]
    fp1 = [l for l in outs[1].splitlines() if l.startswith("RANK1_OK")]
    assert fp0 and fp1
    # the broadcast schedules re-materialized identically on both hosts
    assert fp0[0].split(" ", 1)[1] == fp1[0].split(" ", 1)[1]


CHAOS_DRIVER = """
import os, sys
pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
import jax.numpy as jnp
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers
from tenzing_tpu.runtime.executor import TraceExecutor
from tenzing_tpu.bench.benchmarker import BenchOpts, EmpiricalBenchmarker
from tenzing_tpu.fault import (
    BackoffPolicy, FaultInjectingBenchmarker, InjectSpec, ResilientBenchmarker,
)
from tenzing_tpu.solve.dfs import DfsOpts, explore
from tenzing_tpu.parallel.control_plane import default_control_plane

cp = default_control_plane()
g = Graph()
g.start_then(SpMVCompound())
g.then_finish(SpMVCompound())
plat = Platform.make_n_lanes(2)
bufs, _ = make_spmv_buffers(m=128, nnz_per_row=4, seed=0)
ex = TraceExecutor(plat, {k: jnp.asarray(v) for k, v in bufs.items()})
emp = EmpiricalBenchmarker(ex, control_plane=cp)
# rank-agreed injection draws (fault/inject.py): keyed on schedule identity
# + per-schedule attempt counter, NOT process RNG state.  If the two ranks'
# draws diverged, one rank would raise while the other entered the
# measurement barrier — a deadlock this driver would hit as a timeout.
inject = FaultInjectingBenchmarker(
    emp, [InjectSpec("transient", 0.4, 23)])
bench = ResilientBenchmarker(
    inject, control_plane=cp,
    policy=BackoffPolicy(retries=6, base_secs=0.0, jitter=0.0),
    sleep=lambda s: None)
res = explore(
    g, plat, bench,
    DfsOpts(max_seqs=4, bench_opts=BenchOpts(n_iters=2, target_secs=1e-4)),
    control_plane=cp,
)
assert len(res.sims) == 4  # every candidate survived the chaos via retries
assert inject.injected["transient"] > 0  # the chaos actually happened
fp = "&".join(s.order.desc() for s in res.sims)
fp += f" injected={inject.injected['transient']} calls={inject.calls}"
print(f"RANK{pid}_OK {fp}", flush=True)
"""


@pytest.mark.needs_multiprocess
def test_two_process_dfs_explore():
    _run_two_ranks(DRIVER)


def test_two_process_injection_agreement():
    """Multi-host chaos (the ROADMAP rank-agreed-draws item): seeded
    transient injection under a REAL two-process control plane.  The
    injectors' draws are keyed on schedule identity + attempt counter, so
    both ranks inject the same faults at the same attempts and the
    rank-coherent ``agree_fault`` protocol retries them together —
    divergent draws would deadlock one rank in the measurement barrier.
    The asserted fingerprint includes each rank's injection counts."""
    import pytest

    try:
        _run_two_ranks(CHAOS_DRIVER)
    except AssertionError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            pytest.skip("jax CPU backend without multiprocess collectives")
        raise


@pytest.mark.needs_multiprocess
def test_two_process_mcts_explore():
    """The MCTS per-iteration protocol — rank-0 rollout, stop + schedule
    broadcast, all-rank benchmark, rank-0 backprop (reference
    mcts.hpp:154-327) — across two real processes."""
    _run_two_ranks(MCTS_DRIVER)
