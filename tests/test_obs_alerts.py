"""Watchtower alerting (ISSUE 13; docs/observability.md "Watchtower"):
golden multi-window burn-rate math, every rule in the catalog against
doctored fleet documents, the firing/resolved state machine (dedup,
transition timestamps, hysteresis — no flapping), rule overrides, and
the ``alerts check`` CLI exit-code contract (0 healthy / 1 firing /
2 unreadable tree).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from tenzing_tpu.obs.alerts import (
    Alert,
    AlertBook,
    AlertTreeError,
    DEFAULT_RULES,
    backlog_summary,
    burn_of,
    evaluate,
    firing_lines,
    load_rules,
)

NOW = 1_700_000_000.0


def snap(d, owner, seq, pct99, target=100.0, baseline=None,
         state="serving", gauges=None, tracer=None, now=NOW):
    doc = {"kind": "metrics_snapshot", "owner": owner, "seq": seq,
           "written_at": now - (10 - seq), "state": state,
           "metrics": {"counters": {}, "gauges": gauges or {},
                       "histograms": {}},
           "tracer": tracer or {"dropped_spans": 0, "dropped_events": 0}}
    if pct99 is not None:
        doc["slo"] = {"histogram": "serve.resolve_us.exact",
                      "pct99_us": pct99, "target_us": target,
                      "baseline_pct99_us": baseline}
    json.dump(doc, open(os.path.join(d, f"metrics-{owner}-{seq}.json"),
                        "w"))


def status(d, owner, state="serving", hb_age=0.0, kind="serve_loop",
           now=NOW):
    json.dump({"kind": kind, "owner": owner, "state": state,
               "heartbeat_at": now - hb_age},
              open(os.path.join(d, f"status-{owner}.json"), "w"))


@pytest.fixture()
def tree(tmp_path):
    store = tmp_path / "store"
    queue = tmp_path / "queue"
    store.mkdir()
    queue.mkdir()
    return str(store), str(queue)


# -- burn-rate math ----------------------------------------------------------

def test_burn_of_golden():
    assert burn_of({"pct99_us": 420.0, "target_us": 100.0}) == 4.2
    # no target: the committed baseline anchors the burn
    assert burn_of({"pct99_us": 150.0, "target_us": None,
                    "baseline_pct99_us": 100.0}) == 1.5
    assert burn_of({"pct99_us": None, "target_us": 100.0}) is None
    assert burn_of({"pct99_us": 50.0}) is None


def test_slo_burn_multiwindow_golden(tree):
    store, queue = tree
    # sustained burn: ring [110, 120, 400, 420] vs target 100
    # fast = 4.2 (latest), slow = median([1.1, 1.2, 4.0, 4.2]) = 2.6
    for i, p in enumerate([110.0, 120.0, 400.0, 420.0]):
        snap(store, "burn", i, p)
    alerts = evaluate([store], [queue], now=NOW)
    assert [a.rule for a in alerts] == ["slo_burn"]
    a = alerts[0]
    assert a.subject == "burn" and a.severity == "page"
    assert a.value == {"fast": 4.2, "slow": 2.6}
    assert a.threshold == {"fast_burn": 2.0, "slow_burn": 1.5}


def test_slo_burn_single_spike_does_not_fire(tree):
    store, queue = tree
    # one bad heartbeat in an otherwise healthy ring: fast window fires,
    # slow window (median 1.0) vetoes — the multi-window no-flap point
    for i, p in enumerate([100.0, 100.0, 100.0, 400.0]):
        snap(store, "spike", i, p)
    assert evaluate([store], [queue], now=NOW) == []


def test_slo_burn_needs_min_window(tree):
    """With a 1-2 doc ring the slow median IS the latest value, so the
    multi-window veto would degenerate: a just-restarted loop's one
    warm-up heartbeat must not page.  Three docs restore the veto."""
    store, queue = tree
    snap(store, "fresh", 0, 400.0)
    assert evaluate([store], [queue], now=NOW) == []
    snap(store, "fresh", 1, 410.0)
    assert evaluate([store], [queue], now=NOW) == []
    snap(store, "fresh", 2, 420.0)  # sustained across >= min_window
    assert [a.rule for a in evaluate([store], [queue], now=NOW)] == \
        ["slo_burn"]


def test_slo_burn_stopped_owner_skipped(tree):
    store, queue = tree
    for i, p in enumerate([400.0, 420.0, 430.0, 440.0]):
        snap(store, "gone", i, p, state="stopped" if i == 3 else "serving")
    assert evaluate([store], [queue], now=NOW) == []


# -- the rest of the catalog -------------------------------------------------

def test_stale_heartbeat_rule(tree):
    store, queue = tree
    status(store, "dead", state="serving", hb_age=300.0)
    status(store, "fresh", state="serving", hb_age=1.0)
    status(queue, "done", state="stopped", hb_age=9999.0, kind=None)
    alerts = evaluate([store], [queue], now=NOW)
    assert [a.key for a in alerts] == ["stale_heartbeat:dead"]
    assert alerts[0].value == 300.0


def test_poison_and_queue_age_rules(tree):
    store, queue = tree
    json.dump({"kind": "poisoned_request"},
              open(os.path.join(queue, "poison-deadbeef01.json"), "w"))
    item = os.path.join(queue, "work-abc.json")
    json.dump({"kind": "search_request"}, open(item, "w"))
    os.utime(item, (NOW - 1000, NOW - 1000))
    alerts = evaluate([store], [queue], now=NOW)
    keys = sorted(a.key for a in alerts)
    assert keys == ["poison:deadbeef01", f"queue_age:{queue}"]
    age = next(a for a in alerts if a.rule == "queue_age")
    assert age.value == 1000.0 and age.threshold == 600.0


def test_shed_rate_queue_wait_and_tracer_drops(tree):
    store, queue = tree
    snap(store, "hot", 0, None,
         gauges={"serve.shed_rate": 3.5, "serve.queue_age_s": 45.0},
         tracer={"dropped_spans": 7, "dropped_events": 2})
    alerts = {a.rule: a for a in evaluate([store], [queue], now=NOW)}
    assert alerts["shed_rate"].value == 3.5
    assert alerts["queue_age"].subject == "hot:pending"
    assert alerts["tracer_drops"].value == 9


def _snap_raw(d, owner, seq, counters=None, reqlog=None, state="serving",
              now=NOW):
    doc = {"kind": "metrics_snapshot", "owner": owner, "seq": seq,
           "written_at": now - (10 - seq), "state": state,
           "metrics": {"counters": counters or {}, "gauges": {},
                       "histograms": {}},
           "tracer": {"dropped_spans": 0, "dropped_events": 0}}
    if reqlog is not None:
        doc["reqlog"] = reqlog
    json.dump(doc, open(os.path.join(d, f"metrics-{owner}-{seq}.json"),
                        "w"))


def test_tenant_shed_rule_fires_on_ring_growth(tree):
    store, queue = tree
    # tenant "acme" sheds grow 2 -> 9 across the ring, "other" (the
    # capped-set aggregate label) collects a timeout; "quiet" is flat
    _snap_raw(store, "loop", 0, counters={"serve.shed.acme": 2,
                                          "serve.shed.quiet": 5})
    _snap_raw(store, "loop", 3, counters={"serve.shed.acme": 9,
                                          "serve.shed.quiet": 5,
                                          "serve.timeout.other": 1})
    alerts = evaluate([store], [queue], now=NOW)
    assert sorted(a.key for a in alerts) == \
        ["tenant_shed:loop:acme", "tenant_shed:loop:other"]
    acme = next(a for a in alerts if a.subject == "loop:acme")
    assert acme.value == {"shed": 7, "timeout": 0}
    assert acme.severity == "ticket"
    assert "acme" in acme.message


def test_tenant_shed_counter_reset_and_thresholds(tree):
    store, queue = tree
    # a counter reset (restart inside the ring) must read as "latest
    # value since the reset", never a negative delta that hides growth
    _snap_raw(store, "loop", 0, counters={"serve.shed.acme": 50})
    _snap_raw(store, "loop", 3, counters={"serve.shed.acme": 3})
    alerts = evaluate([store], [queue], now=NOW)
    assert [a.key for a in alerts] == ["tenant_shed:loop:acme"]
    assert alerts[0].value == {"shed": 3, "timeout": 0}
    # a raised budget (--set tenant_shed.max_shed=5) tolerates it
    rules = load_rules(sets=["tenant_shed.max_shed=5"])
    assert evaluate([store], [queue], rules=rules, now=NOW) == []


def _daemon_status(qd, owner, history, state="draining", now=NOW):
    json.dump({"owner": owner, "pid": 1, "state": state,
               "heartbeat_at": now, "history": history},
              open(os.path.join(qd, f"status-{owner}.json"), "w"))


def test_backlog_summary_and_burn_rule(tree):
    store, queue = tree
    # arrival: the reqlog position advances 0 -> 30 records across a
    # 3s ring window -> 10/s
    _snap_raw(store, "loop", 0, reqlog={"records": 0, "segments": 1})
    _snap_raw(store, "loop", 3, reqlog={"records": 30, "segments": 1})
    # drain: one live daemon completing items in 2s each -> 0.5/s
    _daemon_status(queue, "d1", [
        {"exact": "e", "outcome": "completed", "wall_s": 2.0},
        {"exact": "e", "outcome": "completed", "wall_s": 2.0},
        {"exact": "e", "outcome": "failed", "wall_s": 99.0},  # excluded
    ])
    json.dump({"kind": "search_request"},
              open(os.path.join(queue, "work-x.json"), "w"))
    bl = backlog_summary([store], [queue], max_daemons=0)
    assert bl["arrival_per_s"] == 10.0
    assert bl["drain_per_s"] == 0.5
    assert bl["daemons"] == 1 and bl["depth"] == 1
    assert bl["per_item_s"] == 2.0
    assert bl["recommended_daemons"] == 20  # ceil(10/s * 2s/item)
    assert bl["recommended_daemons_raw"] == 20
    assert bl["max_daemons"] is None  # 0 = unclamped
    # default clamps to os.cpu_count(); explicit bound wins
    clamped = backlog_summary([store], [queue], max_daemons=3)
    assert clamped["recommended_daemons"] == 3
    assert clamped["recommended_daemons_raw"] == 20
    assert clamped["max_daemons"] == 3
    dflt = backlog_summary([store], [queue])
    assert dflt["max_daemons"] == (os.cpu_count() or 4)
    assert dflt["recommended_daemons"] == min(20, dflt["max_daemons"])
    rules = load_rules(
        sets=["queue_backlog_burn.max_daemons=0"])  # unclamped
    alerts = [a for a in evaluate([store], [queue], rules=rules, now=NOW)
              if a.rule == "queue_backlog_burn"]
    assert len(alerts) == 1
    a = alerts[0]
    assert a.subject == "fleet" and a.severity == "page"
    assert a.value["arrival_per_s"] == 10.0
    assert "~20 daemon(s)" in a.message


def test_backlog_burn_needs_depth_and_arrival(tree):
    store, queue = tree
    # arrival without queued work: the fleet is keeping up — no alert
    _snap_raw(store, "loop", 0, reqlog={"records": 0})
    _snap_raw(store, "loop", 3, reqlog={"records": 30})
    assert [a.rule for a in evaluate([store], [queue], now=NOW)] == []
    # queued work without measurable arrival: queue_age owns that
    # story, the burn rule stays silent
    for n in os.listdir(store):
        os.unlink(os.path.join(store, n))
    json.dump({"kind": "search_request"},
              open(os.path.join(queue, "work-x.json"), "w"))
    assert [a.rule for a in evaluate([store], [queue], now=NOW)] == []


def test_backlog_burn_balanced_fleet_does_not_fire(tree):
    store, queue = tree
    _snap_raw(store, "loop", 0, reqlog={"records": 0})
    _snap_raw(store, "loop", 3, reqlog={"records": 3})  # 1/s
    # two daemons at 1s/item drain 2/s > 1.2 * arrival — healthy
    _daemon_status(queue, "d1", [{"outcome": "completed", "wall_s": 1.0}])
    _daemon_status(queue, "d2", [{"outcome": "completed", "wall_s": 1.0}])
    json.dump({"kind": "search_request"},
              open(os.path.join(queue, "work-x.json"), "w"))
    assert [a.rule for a in evaluate([store], [queue], now=NOW)] == []
    # a stopped daemon stops counting toward the fleet's drain rate
    _daemon_status(queue, "d1", [{"outcome": "completed", "wall_s": 1.0}],
                   state="stopped")
    _daemon_status(queue, "d2", [{"outcome": "completed", "wall_s": 1.0}],
                   state="stopped")
    fired = [a.rule for a in evaluate([store], [queue], now=NOW)]
    assert "queue_backlog_burn" in fired


def test_missing_tree_is_usage_error(tmp_path):
    with pytest.raises(AlertTreeError):
        evaluate([str(tmp_path / "nope")], [])
    # the follow view renders through it instead of raising
    assert firing_lines([str(tmp_path / "nope")], []) == []


# -- rule configuration ------------------------------------------------------

def test_load_rules_overrides(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps({"slo_burn": {"fast_burn": 9.0},
                             "poison": {"enabled": False}}))
    rules = load_rules(str(p), sets=["queue_age.max_s=5",
                                     "shed_rate.severity=ticket"])
    assert rules["slo_burn"]["fast_burn"] == 9.0
    assert rules["slo_burn"]["slow_burn"] == 1.5  # untouched default
    assert rules["poison"]["enabled"] is False
    assert rules["queue_age"]["max_s"] == 5
    assert rules["shed_rate"]["severity"] == "ticket"
    assert DEFAULT_RULES["slo_burn"]["fast_burn"] == 2.0  # no mutation
    with pytest.raises(AlertTreeError):
        load_rules(sets=["nope.max_s=5"])
    with pytest.raises(AlertTreeError):
        load_rules(sets=["slo_burn.nope=5"])
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"not_a_rule": {}}))
    with pytest.raises(AlertTreeError):
        load_rules(str(bad))
    # a typo'd PARAM in the file is just as loud as a typo'd rule —
    # it must not silently leave the real threshold at its default
    typo = tmp_path / "typo.json"
    typo.write_text(json.dumps({"stale_heartbeat": {"max_age_sec": 5}}))
    with pytest.raises(AlertTreeError):
        load_rules(str(typo))


def test_disabled_rule_does_not_fire(tree):
    store, queue = tree
    status(store, "dead", state="serving", hb_age=300.0)
    rules = load_rules(sets=["stale_heartbeat.enabled=false"])
    assert evaluate([store], [queue], rules=rules, now=NOW) == []


# -- the firing/resolved state machine ---------------------------------------

def _alert(key="slo_burn:o1", value=4.0):
    rule, subject = key.split(":")
    return Alert(rule, subject, "page", value, 2.0, f"{subject} burning")


def test_state_machine_fire_dedup_resolve_refire(tmp_path):
    path = str(tmp_path / "alerts.json")
    book = AlertBook(path, owner="t", resolve_hold_secs=0.0)
    # fire
    doc = book.apply([_alert()], now=NOW)
    e = doc["alerts"]["slo_burn:o1"]
    assert doc["firing"] == ["slo_burn:o1"]
    assert e["state"] == "firing" and e["count"] == 1
    assert e["first_fired_at"] == NOW
    assert e["transitions"] == [{"to": "firing", "at": NOW}]
    # still firing: dedup — observation refreshed, NO new transition
    doc = book.apply([_alert(value=5.0)], now=NOW + 10)
    e = doc["alerts"]["slo_burn:o1"]
    assert e["count"] == 1 and len(e["transitions"]) == 1
    assert e["value"] == 5.0 and e["last_seen_at"] == NOW + 10
    assert e["first_fired_at"] == NOW
    # absent: resolved, timestamped
    doc = book.apply([], now=NOW + 20)
    e = doc["alerts"]["slo_burn:o1"]
    assert e["state"] == "resolved" and e["resolved_at"] == NOW + 20
    assert doc["firing"] == []
    assert [t["to"] for t in e["transitions"]] == ["firing", "resolved"]
    # re-fire: visibly a re-fire (count 2, first_fired_at preserved)
    doc = book.apply([_alert()], now=NOW + 30)
    e = doc["alerts"]["slo_burn:o1"]
    assert e["state"] == "firing" and e["count"] == 2
    assert e["first_fired_at"] == NOW
    assert [t["to"] for t in e["transitions"]] == \
        ["firing", "resolved", "firing"]
    # the ledger round-trips through disk (a fresh book sees the state)
    doc2 = AlertBook(path, owner="t").load()
    assert doc2["alerts"]["slo_burn:o1"]["count"] == 2


def test_state_machine_resolve_hysteresis_no_flap(tmp_path):
    book = AlertBook(str(tmp_path / "alerts.json"), resolve_hold_secs=60.0)
    book.apply([_alert()], now=NOW)
    # absent, but inside the hold window: keeps firing (no flap)
    doc = book.apply([], now=NOW + 30)
    assert doc["alerts"]["slo_burn:o1"]["state"] == "firing"
    # flapping back in is a dedup, not a transition
    doc = book.apply([_alert()], now=NOW + 40)
    e = doc["alerts"]["slo_burn:o1"]
    assert e["count"] == 1 and len(e["transitions"]) == 1
    # absent past the hold: resolved exactly once
    doc = book.apply([], now=NOW + 101)
    assert doc["alerts"]["slo_burn:o1"]["state"] == "resolved"


def test_state_machine_survives_torn_ledger(tmp_path):
    path = str(tmp_path / "alerts.json")
    open(path, "w").write('{"torn')
    doc = AlertBook(path).apply([_alert()], now=NOW)
    assert doc["firing"] == ["slo_burn:o1"]


# -- the check CLI (the CI gate) ---------------------------------------------

def _check(*args):
    return subprocess.run(
        [sys.executable, "-m", "tenzing_tpu.obs.alerts", "check", *args],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_check_cli_exit_codes(tree, tmp_path):
    store, queue = tree
    now = time.time()
    snap(store, "ok", 0, 90.0, now=now)
    status(store, "ok", state="serving", hb_age=0.0, now=now)
    state = str(tmp_path / "ledger.json")
    r = _check("--store", store, "--queue-dir", queue, "--state", state)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["n_firing"] == 0
    # doctor the tree: pct99 10x over the SLO, sustained across the ring
    for i in range(4):
        snap(store, "ok", i, 1000.0, now=now)
    r = _check("--store", store, "--queue-dir", queue, "--state", state)
    assert r.returncode == 1, r.stdout
    out = json.loads(r.stdout)
    assert out["n_firing"] == 1
    assert out["firing"][0]["rule"] == "slo_burn"
    ledger = json.load(open(state))
    assert ledger["firing"] == ["slo_burn:ok"]
    # heal: the same ledger resolves the alert, exit back to 0
    for i in range(4):
        snap(store, "ok", i, 90.0, now=now)
    r = _check("--store", store, "--queue-dir", queue, "--state", state)
    assert r.returncode == 0, r.stdout
    ledger = json.load(open(state))
    assert ledger["alerts"]["slo_burn:ok"]["state"] == "resolved"
    # unreadable tree = usage error, not a verdict
    r = _check("--store", str(tmp_path / "missing"))
    assert r.returncode == 2
    assert "not a directory" in r.stderr
    # so is a malformed override
    r = _check("--store", store, "--set", "bogus.x=1")
    assert r.returncode == 2
    # and an unwritable ledger: a broken watchtower must never read as
    # "alerts firing" (exit 1) to the CI gate
    not_a_dir = str(tmp_path / "file")
    open(not_a_dir, "w").write("x")
    r = _check("--store", store, "--state",
               os.path.join(not_a_dir, "alerts.json"))
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)


def test_follow_renders_firing_lines(tree):
    store, queue = tree
    for i, p in enumerate([400.0, 410.0, 420.0, 430.0]):
        snap(store, "burn", i, p)
    lines = firing_lines([store], [queue])
    assert len(lines) == 1
    assert lines[0].startswith("ALERT  [page] slo_burn burn:")


# -- hostile-filesystem rules (ISSUE 19) -------------------------------------

RO = {"errno": 28, "error": "[Errno 28] injected enospc", "reason": "write",
      "latched_at": NOW}


def _ro_status(d, owner, state="paused", ro=RO, kind="drain_daemon",
               now=NOW):
    doc = {"kind": kind, "owner": owner, "state": state,
           "heartbeat_at": now}
    if ro is not None:
        doc["store_readonly"] = ro
    json.dump(doc, open(os.path.join(d, f"status-{owner}.json"), "w"))


def _ro_snap(d, owner, seq, ro=RO, now=NOW):
    doc = {"kind": "metrics_snapshot", "owner": owner, "seq": seq,
           "written_at": now - (10 - seq), "state": "serving",
           "metrics": {"counters": {}, "gauges": {}, "histograms": {}},
           "tracer": {"dropped_spans": 0, "dropped_events": 0}}
    if ro is not None:
        doc["store_readonly"] = ro
    json.dump(doc, open(os.path.join(d, f"metrics-{owner}-{seq}.json"),
                        "w"))


def test_store_unwritable_fires_from_daemon_status(tree):
    """Daemons publish no snapshot ring: the latch on their status doc
    alone must page."""
    store, queue = tree
    _ro_status(queue, "d1")
    alerts = evaluate([store], [queue], now=NOW)
    assert [a.key for a in alerts] == ["store_unwritable:d1"]
    a = alerts[0]
    assert a.severity == "page"
    assert a.value == {"errno": 28, "reason": "write"}
    assert "read-only" in a.message and "probe" in a.message


def test_store_unwritable_one_alert_per_owner(tree):
    """A latched serve loop carries the latch on BOTH its snapshot ring
    and its status doc — one alert, not two."""
    store, queue = tree
    _ro_snap(store, "loop", 0)
    _ro_status(store, "loop", state="serving", kind="serve_loop")
    alerts = [a for a in evaluate([store], [queue], now=NOW)
              if a.rule == "store_unwritable"]
    assert len(alerts) == 1 and alerts[0].subject == "loop"


def test_store_unwritable_stopped_owner_skipped(tree):
    store, queue = tree
    _ro_status(queue, "d1", state="stopped")
    _ro_snap(store, "loop", 0)
    _ro_snap(store, "loop", 1)
    json.dump(dict(json.load(open(os.path.join(
        store, "metrics-loop-1.json"))), state="stopped"),
        open(os.path.join(store, "metrics-loop-1.json"), "w"))
    assert evaluate([store], [queue], now=NOW) == []


def test_store_unwritable_fires_then_resolves_in_ledger(tree, tmp_path):
    """The fschaos drill's alert contract, in miniature: latch -> fire;
    probe write lands, latch clears -> resolve."""
    store, queue = tree
    book = AlertBook(str(tmp_path / "alerts.json"), resolve_hold_secs=0.0)
    _ro_status(queue, "d1")
    doc = book.apply(evaluate([store], [queue], now=NOW), now=NOW)
    assert doc["firing"] == ["store_unwritable:d1"]
    _ro_status(queue, "d1", ro=None, state="idle")
    doc = book.apply(evaluate([store], [queue], now=NOW + 5), now=NOW + 5)
    assert doc["firing"] == []
    assert doc["alerts"]["store_unwritable:d1"]["state"] == "resolved"


def test_store_damage_rate_fires_on_ring_growth(tree):
    store, queue = tree
    _snap_raw(store, "loop", 0,
              counters={"serve.store.checksum_failed": 0})
    _snap_raw(store, "loop", 3,
              counters={"serve.store.checksum_failed": 4,
                        "serve.store.segment_quarantined": 1})
    alerts = evaluate([store], [queue], now=NOW)
    assert [a.key for a in alerts] == ["store_damage_rate:loop"]
    a = alerts[0]
    assert a.severity == "ticket"
    assert a.value == {"checksum_failed": 4, "segment_quarantined": 1}
    assert "fsck" in a.message


def test_store_damage_rate_flat_and_reset(tree):
    store, queue = tree
    # flat counters: old damage is not NEW damage
    _snap_raw(store, "loop", 0,
              counters={"serve.store.checksum_failed": 4})
    _snap_raw(store, "loop", 3,
              counters={"serve.store.checksum_failed": 4})
    assert evaluate([store], [queue], now=NOW) == []
    # a counter reset (restart inside the ring) reads as "growth since
    # the reset", same rule as tenant_shed
    _snap_raw(store, "loop", 3,
              counters={"serve.store.checksum_failed": 3})
    alerts = evaluate([store], [queue], now=NOW)
    assert [a.key for a in alerts] == ["store_damage_rate:loop"]
    assert alerts[0].value == {"checksum_failed": 3}


def test_backlog_summary_excludes_quarantined_members(tree):
    """A crash-looped member leaves a stale never-'stopped' status doc
    behind; the supervisor's open breaker names it, and its phantom
    capacity must not shrink the recommended fleet."""
    store, queue = tree
    json.dump({"kind": "supervisor", "owner": "fleet-0",
               "state": "supervising", "heartbeat_at": NOW,
               "breakers": {"w1": {"state": "open"},
                            "w2": {"state": "closed"}}},
              open(os.path.join(queue, "status-fleet-0.json"), "w"))
    _daemon_status(queue, "w1", [{"outcome": "completed", "wall_s": 1.0}])
    _daemon_status(queue, "w2", [{"outcome": "completed", "wall_s": 1.0}])
    bl = backlog_summary([store], [queue], max_daemons=0)
    assert bl["daemons"] == 1  # w2 only
    assert bl["quarantined_daemons"] == 1
    assert bl["drain_per_s"] == 1.0  # w1's stale doc contributes nothing


def test_backlog_summary_in_memory_quarantined_owners(tree):
    """The supervisor's IN-MEMORY breaker state is fresher than its
    published status doc: ``quarantined_owners`` must exclude a member the
    docs still show as healthy (no supervisor doc at all here — the
    pre-first-publish window) from drain capacity."""
    store, queue = tree
    _daemon_status(queue, "w1", [{"outcome": "completed", "wall_s": 1.0}])
    _daemon_status(queue, "w2", [{"outcome": "completed", "wall_s": 1.0}])
    bl = backlog_summary([store], [queue], max_daemons=0)
    assert bl["daemons"] == 2  # no breaker evidence on disk
    bl = backlog_summary([store], [queue], max_daemons=0,
                         quarantined_owners={"w1"})
    assert bl["daemons"] == 1
    assert bl["quarantined_daemons"] == 1
    assert bl["drain_per_s"] == 1.0
