"""Ring attention: long-context sequence parallelism as a searchable op DAG.

The reference has no attention (SURVEY.md §2.5: TP/PP/ring-attention absent; the
op-DAG must nonetheless *express* such programs — "a compound op whose subgraph
is a ring of permute+compute steps is exactly ring-attention-shaped").  This
model is that compound: the structural sibling of the halo exchange
(models/halo.py — neighbor ppermute + pack/unpack) and of the SpMV remote
exchange, with the same searchable comm/compute-overlap shape as the
reference's pack->Isend->compute pipelines (ops_halo_exchange.cu:33-257).

Design (blockwise ring attention, double-buffered):

* the sequence axis is sharded over mesh axis ``"sp"``: each device holds local
  queries Q and one K/V block; K/V blocks rotate around the ring via
  ``lax.ppermute`` while flash-style online-softmax state (acc, m, l) folds in
  one block per step;
* K/V are **double-buffered** (kv0/kv1 ping-pong): ``rotate_s`` reads the
  current buffer and writes the other, so ``attn_s`` and ``rotate_s`` are
  independent in the DAG — computing block s can overlap rotating block s+1.
  How aggressively they overlap (lane assignment, ordering, sync placement) is
  the solver's schedule space, exactly the reference's premise;
* the WAR edge ``attn_{s-1} -> rotate_s`` keeps the buffer being overwritten
  free (its reader has executed) so every topological order is correct under
  the executor's SSA buffer semantics;
* m and l are carried broadcast to Q's (b, n, d) shape so the Pallas kernel
  works on uniform tiles (ops/attention_pallas.py).

The per-step block update has an implementation ChoiceOp: plain XLA einsums vs
the Pallas MXU kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import ChoiceOp, CompoundOp, DeviceOp, OpBase

AXIS = "sp"


@dataclass(frozen=True)
class RingAttnArgs:
    n_devices: int  # ring size (mesh axis "sp" extent)
    batch: int = 1
    seq_local: int = 128  # queries per device
    head_dim: int = 128
    dtype: str = "float32"

    @property
    def scale(self) -> float:
        return 1.0 / float(np.sqrt(self.head_dim))


def _kv(s: int) -> Tuple[str, str]:
    """Buffer names holding the K/V block consumed at ring step ``s``."""
    return f"K{s % 2}", f"V{s % 2}"


class AttnStep(DeviceOp):
    """Fold ring step ``s``'s K/V block into the online-softmax state via XLA
    einsums (the reference-shape 'plain' implementation)."""

    def __init__(self, name: str, s: int, args: RingAttnArgs):
        super().__init__(name)
        self._s = s
        self._args = args

    def reads(self):
        k, v = _kv(self._s)
        return ["Q", k, v, "acc", "m_run", "l_run"]

    def writes(self):
        return ["acc", "m_run", "l_run"]

    def _update(self, q, k, v, acc, m, l):
        import jax.numpy as jnp

        s_ = jnp.einsum("bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32)
        s_ = s_ * self._args.scale
        m_blk = jnp.max(s_, axis=2, keepdims=True)  # (b, n, 1)
        m_new = jnp.maximum(m, jnp.broadcast_to(m_blk, m.shape))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s_ - m_new[..., :1])
        l_new = l * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=2, keepdims=True), l.shape
        )
        acc_new = acc * alpha + jnp.einsum(
            "bqk,bkd->bqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
        ).astype(acc.dtype)
        return acc_new, m_new, l_new

    def apply(self, bufs, ctx):
        k, v = _kv(self._s)
        acc, m, l = self._update(
            bufs["Q"], bufs[k], bufs[v], bufs["acc"], bufs["m_run"], bufs["l_run"]
        )
        return {"acc": acc, "m_run": m, "l_run": l}

    # megakernel fusion (runtime/fused.py): the online-softmax update is
    # row-independent along the query axis (axis 1 of the (b, n, d) state);
    # the K/V block being folded must stay whole.  The Pallas subclasses
    # inherit this but are excluded by the partitioner's uses_pallas test
    # (no nested kernels).
    def fusible(self) -> bool:
        return True

    def fuse_tiling(self):
        t = {"Q": 1, "acc": 1, "m_run": 1, "l_run": 1}
        for n in self.reads():
            t.setdefault(n, None)  # the K/V pair, whatever its names
        return t


class AttnStepPallas(AttnStep):
    """Same update via the Pallas MXU kernel (ops/attention_pallas.py)."""

    def _update(self, q, k, v, acc, m, l):
        from tenzing_tpu.ops.attention_pallas import attn_block_pallas

        return attn_block_pallas(q, k, v, acc, m, l, self._args.scale)

    def uses_pallas(self) -> bool:
        return True


class AttnStepPallasBf16(AttnStep):
    """Pallas kernel with Q/K/V cast to bfloat16 for the MXU matmuls (double
    the systolic-array throughput; softmax state and accumulation stay
    float32 via preferred_element_type inside the kernel)."""

    def _update(self, q, k, v, acc, m, l):
        import jax.numpy as jnp

        from tenzing_tpu.ops.attention_pallas import attn_block_pallas

        bf = jnp.bfloat16
        return attn_block_pallas(
            q.astype(bf), k.astype(bf), v.astype(bf), acc, m, l,
            self._args.scale,
        )

    def uses_pallas(self) -> bool:
        return True


class AttnStepChoice(ChoiceOp):
    """Implementation menu for one ring step: XLA einsums vs Pallas kernel
    (float32 and bfloat16-input variants)."""

    def __init__(self, name: str, s: int, args: RingAttnArgs):
        super().__init__(name)
        self._s = s
        self._args = args

    def choices(self) -> List[OpBase]:
        return [
            AttnStep(self.name() + ".xla", self._s, self._args),
            AttnStepPallas(self.name() + ".pallas", self._s, self._args),
            AttnStepPallasBf16(self.name() + ".pallas_bf16", self._s, self._args),
        ]


class RotateKV(DeviceOp):
    """Send the step-``s`` K/V block one hop around the ring into the *other*
    buffer pair (double-buffering: the write never clobbers what step ``s``
    reads).  The ICI analog of the halo Exchange op (models/halo.py) and of the
    reference's Isend/Irecv pairs (ops_mpi.hpp:17-146)."""

    def __init__(self, name: str, s: int):
        super().__init__(name)
        self._s = s

    def reads(self):
        return list(_kv(self._s))

    def writes(self):
        return list(_kv(self._s + 1))

    def apply(self, bufs, ctx):
        import jax

        n = jax.lax.axis_size(AXIS)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_in, v_in = _kv(self._s)
        k_out, v_out = _kv(self._s + 1)
        return {
            k_out: jax.lax.ppermute(bufs[k_in], AXIS, perm),
            v_out: jax.lax.ppermute(bufs[v_in], AXIS, perm),
        }


class FinalizeAttn(DeviceOp):
    """O = acc / l (the denominator division deferred past the ring)."""

    def __init__(self, name: str = "attn_finalize"):
        super().__init__(name)

    def reads(self):
        return ["acc", "l_run"]

    def writes(self):
        return ["O"]

    def apply(self, bufs, ctx):
        return {"O": bufs["acc"] / bufs["l_run"]}

    # fusion: elementwise over the (b, n, d) state
    def fusible(self) -> bool:
        return True

    def fuse_tiling(self):
        return {"acc": 1, "l_run": 1, "O": 1}


class RingAttention(CompoundOp):
    """The whole ring as one compound op: n_devices attn steps chained through
    the softmax state, n_devices-1 rotates chained through the kv buffers, WAR
    edges attn_{s-1} -> rotate_s, finalize at the end."""

    def __init__(self, args: RingAttnArgs, name: str = "ring_attention",
                 impl_choice: bool = False):
        super().__init__(name)
        self._args = args
        self._impl_choice = impl_choice

    def args(self) -> RingAttnArgs:
        return self._args

    def graph(self) -> Graph:
        g = Graph()
        n = self._args.n_devices
        mk = AttnStepChoice if self._impl_choice else AttnStep
        attns = [mk(f"attn_{s}", s, self._args) for s in range(n)]
        rots = [RotateKV(f"rotate_{s}", s) for s in range(n - 1)]
        g.start_then(attns[0])
        for s in range(1, n):
            g.then(attns[s - 1], attns[s])
            g.then(rots[s - 1], attns[s])
        for s in range(1, n - 1):
            g.then(rots[s - 1], rots[s])
        if rots:
            g.start_then(rots[0])
        for s in range(1, n - 1):
            # WAR: rotate_s overwrites the buffer attn_{s-1} reads
            g.then(attns[s - 1], rots[s])
        fin = FinalizeAttn()
        g.then(attns[-1], fin)
        g.then_finish(fin)
        return g


class BlockAttnStep(AttnStep):
    """Single-device variant: fold K/V block ``s`` *sliced from the resident
    K/V* into the state (blockwise/flash attention without the ring — the
    1-device degenerate case of sequence parallelism, long context in HBM)."""

    def reads(self):
        return ["Q", "K", "V", "acc", "m_run", "l_run"]

    def apply(self, bufs, ctx):
        import jax.lax as lax

        blk = self._args.seq_local
        k = lax.dynamic_slice_in_dim(bufs["K"], self._s * blk, blk, 1)
        v = lax.dynamic_slice_in_dim(bufs["V"], self._s * blk, blk, 1)
        acc, m, l = self._update(
            bufs["Q"], k, v, bufs["acc"], bufs["m_run"], bufs["l_run"]
        )
        return {"acc": acc, "m_run": m, "l_run": l}

    # -- op-chunking protocol (core/chunking.py, T3): the fold splits over
    # the K/V block axis into n sub-folds of seq_local/n columns each —
    # a sub-fold IS a finer BlockAttnStep (the online-softmax state chain
    # is the combine), so a neighboring op can interleave with the tail
    # sub-folds instead of waiting for the whole block.  XLA fold only:
    # the Pallas kernels own their internal blocking (and the partitioner
    # excludes nested kernels anyway).
    def chunkable(self) -> bool:
        return True

    def chunk_counts(self) -> List[int]:
        from tenzing_tpu.core.chunking import pow2_counts

        return pow2_counts(self._args.seq_local)

    def split(self, n: int) -> List["BlockAttnStep"]:
        from dataclasses import replace

        blk = self._args.seq_local
        if n < 1 or blk % n:
            raise ValueError(f"{blk} K/V columns do not split {n} ways")
        sub = replace(self._args, seq_local=blk // n)
        # sub-fold j of block s slices K/V at s*blk + j*(blk//n): the same
        # dynamic_slice arithmetic, one power of two finer
        return [BlockAttnSubFold(f"{self.name()}.c{n}p{j}", self._s * n + j,
                                 sub)
                for j in range(n)]


class BlockAttnSubFold(BlockAttnStep):
    """A :meth:`BlockAttnStep.split` sub-fold: the same op one power of
    two finer (the online-softmax state chain is the combine), except it
    never re-splits — partials are leaves of the chunking protocol."""

    def chunkable(self) -> bool:
        return False


class BlockAttnStepPallas(BlockAttnStep):
    """Blocked step with the Pallas MXU kernel update."""

    _update = AttnStepPallas._update

    def uses_pallas(self) -> bool:
        return True

    def chunkable(self) -> bool:
        return False  # the kernel owns its internal blocking


class BlockAttnStepPallasBf16(BlockAttnStep):
    """Blocked step with the bfloat16-input Pallas kernel update."""

    _update = AttnStepPallasBf16._update

    def uses_pallas(self) -> bool:
        return True

    def chunkable(self) -> bool:
        return False


def fold_chunk_menu(args: RingAttnArgs, relax: bool = False):
    """(pruned counts, {count: est hidden µs}) for one block fold — the
    roofline sketch constraint (bench/roofline.py::prune_chunkings).  The
    single-chip blocked fold has NO neighboring transfer to hide
    (``comm_us=0``), so the honest full-size menu prunes every n>1 and the
    driver's ``perf.chunked`` block says so; ``relax=True`` (the CPU smoke
    and the library tests — the ``min_tile_bytes=0`` convention of
    tests/test_fused.py) keeps every structurally-valid count so the
    machinery is searchable on toy shapes."""
    from tenzing_tpu.bench import roofline

    bpe = np.dtype(args.dtype).itemsize
    b, d, blk = args.batch, args.head_dim, args.seq_local
    nq = args.n_devices * blk  # all queries fold against each block
    state = 6.0 * b * nq * d * bpe  # read+write acc/m_run/l_run
    cost = roofline.Cost(flops=4.0 * b * nq * blk * d,
                         hbm_bytes=state + 2.0 * b * blk * d * bpe)
    # combine cost: every extra sub-fold re-presents the full softmax
    # state (the accumulating RMW is the combine)
    return roofline.chunk_menu(
        BlockAttnStep("probe", 0, args).chunk_counts(), cost,
        comm_us=0.0, combine_bytes=state, relax=relax)


class BlockAttnChoice(ChoiceOp):
    def __init__(self, name: str, s: int, args: RingAttnArgs,
                 chunk_counts=(), chunk_est=None):
        super().__init__(name)
        self._s = s
        self._args = args
        self._chunks = tuple(int(c) for c in chunk_counts if int(c) > 1)
        self._chunk_est = dict(chunk_est or {})
        if chunk_counts:
            from tenzing_tpu.core.chunking import menu_info

            self.chunk_menu = menu_info(name + ".xla", chunk_counts,
                                        self._chunk_est)

    def choices(self) -> List[OpBase]:
        from tenzing_tpu.core.chunking import ChunkedOp

        out: List[OpBase] = [
            BlockAttnStep(self.name() + ".xla", self._s, self._args),
            BlockAttnStepPallas(self.name() + ".pallas", self._s, self._args),
            BlockAttnStepPallasBf16(
                self.name() + ".pallas_bf16", self._s, self._args
            ),
        ]
        # chunked alternatives of the XLA fold: ordinary menu entries the
        # solvers pick like any kernel (core/chunking.py)
        out += [
            ChunkedOp(BlockAttnStep(self.name() + ".xla", self._s,
                                    self._args),
                      n, est_hidden_us=self._chunk_est.get(n))
            for n in self._chunks
        ]
        return out


class FusedBlockAttn(DeviceOp):
    """ALL K/V blocks folded in one fused Pallas flash kernel
    (ops/attention_pallas.attn_fused_pallas): the online-softmax state lives
    in VMEM scratch across the kv grid dimension instead of round-tripping
    HBM between per-block ops.  Measured motivation (r5): the chained
    variant moves ~0.8 GB of acc/m/l state per iteration at the bench config
    (b=4, n=8k, d=128) — HBM-state-bound at 66.5% MFU; fusing removes
    6 x 16.8 MB of traffic per block."""

    BF16 = False

    def __init__(self, name: str, args: RingAttnArgs):
        super().__init__(name)
        self._args = args

    def reads(self):
        return ["Q", "K", "V", "acc", "m_run", "l_run"]

    def writes(self):
        return ["acc", "m_run", "l_run"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        from tenzing_tpu.ops.attention_pallas import attn_fused_pallas

        q, k, v = bufs["Q"], bufs["K"], bufs["V"]
        if self.BF16:
            bf = jnp.bfloat16
            q, k, v = q.astype(bf), k.astype(bf), v.astype(bf)
        acc, m, l = attn_fused_pallas(
            q, k, v, bufs["acc"], bufs["m_run"], bufs["l_run"],
            self._args.scale, bkv=self._args.seq_local,
        )
        return {"acc": acc, "m_run": m, "l_run": l}

    def uses_pallas(self) -> bool:
        return True


class FusedBlockAttnBf16(FusedBlockAttn):
    BF16 = True


def _mk_block_step(name: str, s: int, args: RingAttnArgs, impl_choice: bool,
                   chunk_counts, chunk_est) -> OpBase:
    """One block fold vertex: the kernel ChoiceOp (optionally extended
    with chunked alternatives), a bare step wrapped in a
    :class:`~tenzing_tpu.core.chunking.ChunkChoice` when only chunking is
    searched, or the plain step."""
    if impl_choice:
        return BlockAttnChoice(name, s, args, chunk_counts=chunk_counts,
                               chunk_est=chunk_est)
    step = BlockAttnStep(name, s, args)
    counts = [c for c in (chunk_counts or ()) if int(c) > 1]
    if counts:
        from tenzing_tpu.core.chunking import ChunkChoice, chunk_variants

        return ChunkChoice(step, chunk_variants(step, counts, chunk_est))
    return step


class BlockChain(CompoundOp):
    """The per-block fold chain as one expandable vertex — the staged
    alternative the fused kernel competes with inside
    :class:`AttnEngineChoice` (the HostRoundTrip-in-TransferChoice
    precedent, models/halo_pipeline.py)."""

    def __init__(self, name: str, args: RingAttnArgs, impl_choice: bool,
                 chunk_counts=(), chunk_est=None):
        super().__init__(name)
        self._args = args
        self._impl_choice = impl_choice
        self._chunk_counts = tuple(chunk_counts)
        self._chunk_est = dict(chunk_est or {})

    def graph(self) -> Graph:
        g = Graph()
        n = self._args.n_devices
        attns = [_mk_block_step(f"attn_{s}", s, self._args,
                                self._impl_choice, self._chunk_counts,
                                self._chunk_est)
                 for s in range(n)]
        g.start_then(attns[0])
        for s in range(1, n):
            g.then(attns[s - 1], attns[s])
        g.then_finish(attns[-1])
        return g


class AttnEngineChoice(ChoiceOp):
    """Granularity menu for the whole blocked fold: the per-block chain
    (searchable order x lane x per-block kernel) vs the fused single-kernel
    flash (f32 or bf16 MXU inputs) — kernel granularity is itself a
    scheduling decision the solver owns."""

    def __init__(self, args: RingAttnArgs, impl_choice: bool,
                 chunk_counts=(), chunk_est=None):
        super().__init__("attn_blocks")
        self._args = args
        self._impl_choice = impl_choice
        self._chunk_counts = tuple(chunk_counts)
        self._chunk_est = dict(chunk_est or {})

    def choices(self) -> List[OpBase]:
        return [
            BlockChain("attn_blocks.chain", self._args, self._impl_choice,
                       self._chunk_counts, self._chunk_est),
            FusedBlockAttn("attn_blocks.fused", self._args),
            FusedBlockAttnBf16("attn_blocks.fused_bf16", self._args),
        ]


class BlockedAttention(CompoundOp):
    """Single-device blockwise attention over ``n_blocks`` K/V blocks: the attn
    steps chain through the softmax state; block loads overlap on lanes; the
    per-step kernel is a ChoiceOp when ``impl_choice``; with ``fused_choice``
    the whole chain additionally competes with the fused single-kernel flash
    (:class:`AttnEngineChoice`).  ``args.n_devices`` is reused as the block
    count (no mesh involved).

    ``chunk=True`` adds chunked sub-fold alternatives of each block's XLA
    fold to the menus (core/chunking.py; :func:`fold_chunk_menu` prunes the
    counts through the roofline — ``chunk_relax`` skips the pruning, the
    CPU-smoke/tests mode)."""

    def __init__(self, args: RingAttnArgs, name: str = "blocked_attention",
                 impl_choice: bool = False, fused_choice: bool = False,
                 chunk: bool = False, chunk_relax: bool = False):
        super().__init__(name)
        self._args = args
        self._impl_choice = impl_choice
        self._fused_choice = fused_choice
        self._chunk = chunk
        self._chunk_relax = chunk_relax

    def args(self) -> RingAttnArgs:
        return self._args

    def graph(self) -> Graph:
        g = Graph()
        n = self._args.n_devices
        counts, est = ((), None)
        if self._chunk:
            counts, est = fold_chunk_menu(self._args,
                                          relax=self._chunk_relax)
        fin = FinalizeAttn()
        if self._fused_choice:
            eng = AttnEngineChoice(self._args, self._impl_choice,
                                   counts, est)
            g.start_then(eng)
            g.then(eng, fin)
        else:
            attns = [_mk_block_step(f"attn_{s}", s, self._args,
                                    self._impl_choice, counts, est)
                     for s in range(n)]
            g.start_then(attns[0])
            for s in range(1, n):
                g.then(attns[s - 1], attns[s])
            g.then(attns[-1], fin)
        g.then_finish(fin)
        return g


def make_blocked_buffers(
    args: RingAttnArgs, seed: int = 0
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """(buffers, expected O) for single-device blockwise attention;
    ``args.n_devices`` K/V blocks of ``seq_local`` each, resident in HBM."""
    bufs, _specs, want = make_ring_buffers(args, seed=seed)
    out = {
        "Q": bufs["Q"],
        "K": bufs["K0"],
        "V": bufs["V0"],
        "acc": bufs["acc"],
        "m_run": bufs["m_run"],
        "l_run": bufs["l_run"],
        "O": bufs["O"],
    }
    return out, want


def make_ring_buffers(
    args: RingAttnArgs, seed: int = 0
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray]:
    """(buffers, partition specs, expected O) for a ring over ``args.n_devices``
    shards.  Expected O is full (global) softmax attention computed densely on
    the host, laid out in the same sp-sharded order as the device buffers."""
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(seed)
    b, nl, d, nsp = args.batch, args.seq_local, args.head_dim, args.n_devices
    n = nl * nsp
    dt = np.dtype(args.dtype)
    q = rng.standard_normal((b, n, d)).astype(dt)
    k = rng.standard_normal((b, n, d)).astype(dt)
    v = rng.standard_normal((b, n, d)).astype(dt)
    # dense reference
    s_ = np.einsum("bqd,bkd->bqk", q.astype(np.float64), k.astype(np.float64))
    s_ *= args.scale
    p = np.exp(s_ - s_.max(axis=2, keepdims=True))
    p /= p.sum(axis=2, keepdims=True)
    want = np.einsum("bqk,bkd->bqd", p, v.astype(np.float64)).astype(np.float32)

    shape = (b, n, d)
    bufs = {
        "Q": q,
        "K0": k,
        "V0": v,
        "K1": np.zeros_like(k),
        "V1": np.zeros_like(v),
        "acc": np.zeros(shape, np.float32),
        "m_run": np.full(shape, -1e30, np.float32),
        "l_run": np.zeros(shape, np.float32),
        "O": np.zeros(shape, np.float32),
    }
    spec = P(None, AXIS, None)
    specs = {name: spec for name in bufs}
    return bufs, specs, want
