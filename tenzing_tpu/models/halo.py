"""3D halo exchange over a device mesh: the spatial-decomposition workload.

Parity target: reference ``src/halo_exchange`` + ``include/tenzing/halo_exchange``
(C11 in SURVEY.md §2): a ``nX x nY x nZ x nQ`` grid with ghost radius ``r`` is
decomposed over ranks; per face direction the DAG is
Pack(GpuOp) -> Isend -> wait, Irecv -> Wait -> Unpack(GpuOp)
(``HaloExchange::add_to_graph``, ops_halo_exchange.cu:33-257), with pack/unpack
CUDA kernels per storage order (ops_halo_exchange.cu:519-699) and periodic
rank-coordinate wrap (halo_run_strategy.hpp:80-98).

TPU-native redesign: the grid (with ghost shells) is sharded over a 3D device
mesh ``("x", "y", "z")``; per direction the DAG is
Pack(slice of the interior edge) -> post (host-posted transfer along the
face's mesh axis, periodic: ``PermuteStart`` ICI collective-permute or
``RdmaShiftStart`` per-neighbor remote DMA) -> AwaitTransfer (the reference's
Wait) -> Unpack(``dynamic_update_slice`` into the ghost shell).  Pack/unpack
are XLA slice ops (contiguous copies the compiler fuses; the reference needs
hand-written CUDA kernels for exactly this).  The six directions are
independent in the graph and the post and wait are separate vertices, so the
solver searches how exchanges overlap each other and how much work hides
between each post and its wait — the reference's post-all-before-wait-any
discipline becomes one more region of the schedule space rather than a
hard-coded edge set.

SSA note: the six Unpacks all write ``U``, so within one schedule they chain
through the buffer's SSA versions in sequence order (disjoint ghost regions, so
any order is numerically identical); pack/exchange stages overlap freely.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import ChoiceOp, CompoundOp, DeviceOp

# the six face directions (reference loops dx,dy,dz with exactly_one,
# ops_halo_exchange.cu:29-31,57-144)
DIRECTIONS: List[Tuple[int, int, int]] = [
    (1, 0, 0),
    (-1, 0, 0),
    (0, 1, 0),
    (0, -1, 0),
    (0, 0, 1),
    (0, 0, -1),
]

_AXIS_NAMES = ("x", "y", "z")


def dir_name(d: Tuple[int, int, int]) -> str:
    """'px'/'mx'/'py'/... (the reference's dir_to_tag analog,
    ops_halo_exchange.cu:16-27)."""
    for i, v in enumerate(d):
        if v != 0:
            return ("p" if v > 0 else "m") + _AXIS_NAMES[i]
    raise ValueError(d)


@dataclass(frozen=True)
class HaloArgs:
    """Per-shard grid extents (reference HaloExchange::Args,
    ops_halo_exchange.hpp:33-55; rank coords come from the mesh, not lambdas)."""

    nq: int = 3
    lx: int = 64
    ly: int = 64
    lz: int = 64
    radius: int = 3
    # grid element dtype, as a string so the dataclass stays hashable (the
    # sublane tile — and with it the Pallas menu gating — depends on itemsize)
    dtype: str = "float32"

    def local_shape(self) -> Tuple[int, int, int, int]:
        r = self.radius
        return (self.nq, self.lx + 2 * r, self.ly + 2 * r, self.lz + 2 * r)

    def itemsize(self) -> int:
        return np.dtype(self.dtype).itemsize


def sublane_tile(itemsize: int) -> int:
    """TPU sublane tile for an element width (8 for 4-byte, 16 for 2-byte,
    32 for 1-byte) — the ONE definition shared by the grid padding
    (halo_pipeline._padded_shape) and the Pallas window/menu gating
    (ops/halo_pallas._tile_window): the two must agree or the kernels'
    tile-aligned HBM DMA windows fall outside the allocated padding."""
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


def _face_slices(args: HaloArgs, d: Tuple[int, int, int], which: str):
    """Start indices + sizes of the face region along direction ``d``:
    ``which`` = 'pack' (interior edge) or 'unpack' (ghost shell)."""
    r = args.radius
    ext = [args.lx, args.ly, args.lz]
    starts = [0, r, r, r]
    sizes = [args.nq, ext[0], ext[1], ext[2]]
    for i, v in enumerate(d):
        if v == 0:
            continue
        sizes[1 + i] = r
        if which == "pack":
            # the interior edge facing the neighbor
            starts[1 + i] = ext[i] if v > 0 else r
        else:
            # the ghost shell on the OPPOSITE side (data arrives from -d)
            starts[1 + i] = 0 if v > 0 else ext[i] + r
    return starts, sizes


class Pack(DeviceOp):
    """Slice the interior edge for one direction (reference Pack,
    ops_halo_exchange.hpp:97-141, kernels ops_halo_exchange.cu:519-573)."""

    def __init__(self, args: HaloArgs, d: Tuple[int, int, int]):
        super().__init__(f"pack_{dir_name(d)}")
        self._args, self._d = args, d

    def reads(self):
        return ["U"]

    def writes(self):
        return [f"buf_{dir_name(self._d)}"]

    def apply(self, bufs, ctx):
        import jax.lax as lax

        starts, sizes = _face_slices(self._args, self._d, "pack")
        sl = lax.dynamic_slice(bufs["U"], starts, sizes)
        return {f"buf_{dir_name(self._d)}": sl}


def _dir_axis_sign(d: Tuple[int, int, int]) -> Tuple[str, int]:
    """(mesh axis name, ±1) of a face direction."""
    i = [j for j, v in enumerate(d) if v != 0][0]
    return _AXIS_NAMES[i], (1 if sum(d) > 0 else -1)


def exchange_post(d: Tuple[int, int, int], engine: str = "xla"):
    """The host-posted exchange op for one direction: ``engine='xla'`` is a
    ``PermuteStart`` (ICI collective-permute, XLA-scheduled), ``'rdma'`` a
    ``RdmaShiftStart`` (per-neighbor Pallas remote DMA with a neighbor
    barrier — on TPU a true split post whose wait kernel runs at the matching
    AwaitTransfer; ops/rdma.py).  Both post the transfer and return with it in
    flight — the reference's Isend (ops_mpi.hpp:17-146); the separate await is
    wired by :func:`add_to_graph`."""
    from tenzing_tpu.ops.comm_ops import PermuteStart
    from tenzing_tpu.ops.rdma import RdmaShiftStart

    name = dir_name(d)
    axis, sign = _dir_axis_sign(d)
    if engine == "xla":
        return PermuteStart(
            f"exchange_{name}.xla", f"buf_{name}", f"recv_{name}",
            axis=axis, shift=sign,
        )
    if engine == "rdma":
        return RdmaShiftStart(
            f"exchange_{name}.rdma", f"buf_{name}", f"recv_{name}",
            axis=axis, shift=sign,
            # barrier semaphores are shared by collective id: one id per
            # direction keeps six concurrent exchanges from cross-talking
            collective_id=DIRECTIONS.index(tuple(d)),
        )
    raise ValueError(f"unknown exchange engine {engine!r}")


# -- synthesized exchange (collectives/synth.py) ----------------------------


def halo_synth_counts(args: HaloArgs) -> List[int]:
    """Chunk counts splitting a face's ``nq`` quantities: {1, 2} filtered
    by divisibility — pure routing, bit-identical for any count."""
    return [k for k in (1, 2) if 1 <= k <= args.nq and args.nq % k == 0]


def halo_synth_plans(args: HaloArgs, d: Tuple[int, int, int]):
    """Chunked neighbor-exchange instantiations for one face direction:
    the face payload splits along ``nq`` into k single-hop permutes whose
    awaits interleave (collectives/synth.py::plan_neighbor_shift)."""
    from tenzing_tpu.collectives.synth import plan_neighbor_shift

    name = dir_name(d)
    axis, sign = _dir_axis_sign(d)
    _, sizes = _face_slices(args, d, "pack")
    return [
        plan_neighbor_shift(f"exchange_{name}", f"buf_{name}", f"recv_{name}",
                            axis, sign, tuple(sizes), k,
                            itemsize=args.itemsize())
        for k in halo_synth_counts(args)
    ]


class ExchangeChoice(ChoiceOp):
    """XLA collective-permute vs Pallas remote-DMA for one direction's
    neighbor exchange — the transfer-engine half of the searched menu (the
    kernel half is ops/halo_pallas.py's pack/unpack choice).  Either way the
    chosen op only POSTS the transfer; the graph's AwaitTransfer is the
    separate wait, so the solver places post and wait independently
    (VERDICT r3 item 2).

    With ``synth=True`` the menu additionally offers synthesized
    chunk-routed decompositions of the shift (:func:`halo_synth_plans`,
    priced and pruned per collectives/synth.py) — the engine menu and the
    synthesized menu compete in ONE ChooseOp, so the solvers weigh
    "which engine" and "which decomposition" as a single decision."""

    def __init__(self, d: Tuple[int, int, int], args: Optional[HaloArgs] = None,
                 synth: bool = False, synth_relax: bool = False):
        super().__init__(f"exchange_{dir_name(d)}")
        self._d = tuple(d)
        self._variants: List = []
        if synth:
            if args is None:
                raise ValueError("ExchangeChoice(synth=True) needs HaloArgs")
            from tenzing_tpu.collectives.synth import sketch_menu
            from tenzing_tpu.collectives.topology import mesh_topology

            axis, _ = _dir_axis_sign(self._d)
            _, sizes = _face_slices(args, self._d, "pack")
            face_bytes = float(np.prod(sizes)) * args.itemsize()
            # a single-hop shift's per-link cost is extent-independent, so
            # a 2-ring prices it without knowing the mesh shape
            self._variants, self.synth_menu = sketch_menu(
                halo_synth_plans(args, self._d),
                mesh_topology({axis: 2}, host=False),
                fixed_bytes=face_bytes, relax=synth_relax,
                collective="shift")

    def choices(self):
        return ([exchange_post(self._d, "xla"), exchange_post(self._d, "rdma")]
                + list(self._variants))


class Unpack(DeviceOp):
    """Write the received face into the ghost shell (reference Unpack,
    ops_halo_exchange.hpp:143-186, kernels ops_halo_exchange.cu:611-699 — and
    without the stray device-sync defect noted in SURVEY.md §7.3)."""

    def __init__(self, args: HaloArgs, d: Tuple[int, int, int]):
        super().__init__(f"unpack_{dir_name(d)}")
        self._args, self._d = args, d

    def reads(self):
        return ["U", f"recv_{dir_name(self._d)}"]

    def writes(self):
        return ["U"]

    def apply(self, bufs, ctx):
        import jax.lax as lax

        starts, _ = _face_slices(self._args, self._d, "unpack")
        return {"U": lax.dynamic_update_slice(bufs["U"], bufs[f"recv_{dir_name(self._d)}"], starts)}


class HaloExchange(CompoundOp):
    """The whole 6-direction exchange as one compound op."""

    def __init__(self, args: HaloArgs, name: str = "halo_exchange"):
        super().__init__(name)
        self._args = args

    def graph(self) -> Graph:
        return add_to_graph(Graph(), self._args)

    def args(self) -> HaloArgs:
        return self._args


def add_to_graph(
    g: Graph,
    args: HaloArgs,
    preds: Optional[List] = None,
    succs: Optional[List] = None,
    xfer_choice: bool = False,
    synth: bool = False,
    synth_relax: bool = False,
) -> Graph:
    """Build the per-direction pack -> post -> await -> unpack chains
    (reference HaloExchange::add_to_graph, ops_halo_exchange.cu:33-257: the
    Isend and the Wait are SEPARATE vertices, and their relative placement is
    the searched overlap freedom).  With ``xfer_choice`` each post is a
    ChoiceOp over the transfer-engine menu (XLA collective-permute vs Pallas
    remote DMA) — same flag name as the pipelined halo's transfer menu
    (halo_pipeline.add_to_graph).  ``synth=True`` (implies the choice node)
    appends synthesized chunk-routed decompositions to each direction's
    menu; ``synth_relax`` keeps analytically-losing instantiations
    searchable."""
    from tenzing_tpu.ops.comm_ops import AwaitTransfer

    preds = preds if preds is not None else [g.start()]
    succs = succs if succs is not None else [g.finish()]
    for d in DIRECTIONS:
        name = dir_name(d)
        if synth:
            exch = ExchangeChoice(d, args=args, synth=True,
                                  synth_relax=synth_relax)
        elif xfer_choice:
            exch = ExchangeChoice(d)
        else:
            exch = exchange_post(d, "xla")
        await_ = AwaitTransfer(f"await_{name}", f"recv_{name}")
        pack, unpack = Pack(args, d), Unpack(args, d)
        for p in preds:
            g.then(p, pack)
        g.then(pack, exch)
        g.then(exch, await_)
        g.then(await_, unpack)
        for s in succs:
            g.then(unpack, s)
    return g


def make_halo_buffers(
    mesh_shape: Tuple[int, int, int], args: HaloArgs, seed: int = 0,
    synth: bool = False
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray]:
    """(buffers, partition specs, expected U after one exchange).

    The global interior grid is periodic; the expected array has every shard's
    ghost faces filled from its periodic neighbors (edges/corners of the shells
    stay untouched — the reference exchanges faces only)."""
    from jax.sharding import PartitionSpec as P

    mx, my, mz = mesh_shape
    r, nq = args.radius, args.nq
    rng = np.random.default_rng(seed)
    # global interior
    G = rng.random((nq, mx * args.lx, my * args.ly, mz * args.lz), dtype=np.float32)

    def shard_block(i, j, k, arr=None):
        a = G if arr is None else arr
        return a[
            :,
            i * args.lx : (i + 1) * args.lx,
            j * args.ly : (j + 1) * args.ly,
            k * args.lz : (k + 1) * args.lz,
        ]

    # per-shard local arrays with ghost shells, interiors filled
    locs = np.zeros((mx, my, mz) + args.local_shape(), dtype=np.float32)
    want = np.zeros_like(locs)
    for i in range(mx):
        for j in range(my):
            for k in range(mz):
                locs[i, j, k][:, r : r + args.lx, r : r + args.ly, r : r + args.lz] = (
                    shard_block(i, j, k)
                )
    want[:] = locs
    # expected ghosts: periodic neighbor interior edges
    for i in range(mx):
        for j in range(my):
            for k in range(mz):
                w = want[i, j, k]
                for d in DIRECTIONS:
                    ni = ((i - d[0]) % mx, (j - d[1]) % my, (k - d[2]) % mz)
                    nb = locs[ni]  # the shard the face arrives FROM
                    ps, sz = _face_slices(args, d, "pack")
                    us, _ = _face_slices(args, d, "unpack")
                    face = nb[
                        :,
                        ps[1] : ps[1] + sz[1],
                        ps[2] : ps[2] + sz[2],
                        ps[3] : ps[3] + sz[3],
                    ]
                    w[
                        :,
                        us[1] : us[1] + sz[1],
                        us[2] : us[2] + sz[2],
                        us[3] : us[3] + sz[3],
                    ] = face

    def assemble(blocks):
        """(mx,my,mz, nq, X,Y,Z) -> global (nq, mx*X, my*Y, mz*Z) layout."""
        return np.concatenate(
            [
                np.concatenate(
                    [np.concatenate(list(blocks[i, j]), axis=3) for j in range(my)],
                    axis=2,
                )
                for i in range(mx)
            ],
            axis=1,
        )

    U = assemble(locs)
    want_g = assemble(want)
    bufs = {"U": U}
    specs = {"U": P(None, "x", "y", "z")}
    for d in DIRECTIONS:
        _, sz = _face_slices(args, d, "pack")
        buf = np.zeros((sz[0], mx * sz[1], my * sz[2], mz * sz[3]), dtype=np.float32)
        bufs[f"buf_{dir_name(d)}"] = buf
        bufs[f"recv_{dir_name(d)}"] = buf.copy()
        specs[f"buf_{dir_name(d)}"] = P(None, "x", "y", "z")
        specs[f"recv_{dir_name(d)}"] = P(None, "x", "y", "z")
        if synth:
            # staging decls of the synthesized shift: plans carry per-device
            # face-chunk shapes; globals tile them over the spatial mesh
            # exactly like the face buffers they slice
            for plan in halo_synth_plans(args, d):
                for decl in plan.buffers:
                    s = decl.shape
                    bufs[decl.name] = np.zeros(
                        (s[0], mx * s[1], my * s[2], mz * s[3]),
                        dtype=np.float32)
                    specs[decl.name] = P(None, "x", "y", "z")
    return bufs, specs, want_g
